/*
 * libmxnet_tpu — compiled C API over the Python substrate.
 *
 * Reproduces the reference's binding contract (ref:
 * include/mxnet/c_api.h, src/c_api/*.cc: opaque handles, int status
 * returns, MXGetLastError) as real `extern "C"` symbols a non-Python
 * client can link (cpp-package/R/Scala-style consumers, SURVEY.md §2.7).
 * Each entry point marshals into mxnet_tpu.c_api via the embedded CPython
 * interpreter; handles are the Python-side integer registry keys.
 *
 * Build: make -C src/capi     (links libpython via python3-config --embed)
 * Smoke client: src/capi/smoke_client.c trains a layer through this ABI.
 */
#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <stdint.h>
#include <stdio.h>
#include <string.h>

typedef uint64_t NDArrayHandle;
typedef uint64_t SymbolHandle;
typedef uint64_t ExecutorHandle;
typedef uint64_t KVStoreHandle;

#define MXTPU_EXPORT __attribute__((visibility("default")))

static PyObject *g_capi = NULL;          /* mxnet_tpu.c_api module */
static __thread char g_err[4096];
static __thread char g_shape_buf[32 * sizeof(uint32_t)];

static void set_err(const char *msg) {
    strncpy(g_err, msg ? msg : "unknown error", sizeof(g_err) - 1);
    g_err[sizeof(g_err) - 1] = 0;
}

static void set_err_from_py(void) {
    PyObject *t, *v, *tb;
    PyErr_Fetch(&t, &v, &tb);
    if (v) {
        PyObject *s = PyObject_Str(v);
        set_err(s ? PyUnicode_AsUTF8(s) : "python error");
        Py_XDECREF(s);
    } else {
        set_err("python error (no message)");
    }
    Py_XDECREF(t); Py_XDECREF(v); Py_XDECREF(tb);
}

/* Initialize the interpreter + import mxnet_tpu.c_api once.
 * Mutex-guarded: concurrent first calls from multiple client threads must
 * not double-run Py_InitializeEx/PyEval_SaveThread. */
#include <dlfcn.h>
#include <pthread.h>
static pthread_mutex_t g_init_lock = PTHREAD_MUTEX_INITIALIZER;

#define MXTPU_STR2(x) #x
#define MXTPU_STR(x) MXTPU_STR2(x)

static int ensure_init(void) {
    if (g_capi) return 0;
    pthread_mutex_lock(&g_init_lock);
    if (g_capi) {
        pthread_mutex_unlock(&g_init_lock);
        return 0;
    }
    if (!Py_IsInitialized()) {
        /* when THIS library was dlopen'd by a foreign host (Perl, R, Lua),
         * libpython's symbols are not in the global namespace and python's
         * own extension modules (math, _struct, numpy) fail to resolve
         * them — promote libpython to RTLD_GLOBAL first */
        const char *pylibs[] = {
            "libpython" MXTPU_STR(PY_MAJOR_VERSION) "."
                MXTPU_STR(PY_MINOR_VERSION) ".so.1.0",
            "libpython" MXTPU_STR(PY_MAJOR_VERSION) "."
                MXTPU_STR(PY_MINOR_VERSION) ".so",
            NULL};
        for (int i = 0; pylibs[i]; i++)
            if (dlopen(pylibs[i], RTLD_NOW | RTLD_GLOBAL)) break;
        Py_InitializeEx(0);
        /* release the GIL so PyGILState_Ensure works from any thread */
        PyEval_SaveThread();
    }
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *m = PyImport_ImportModule("mxnet_tpu.c_api");
    if (!m) {
        set_err_from_py();
        PyGILState_Release(st);
        pthread_mutex_unlock(&g_init_lock);
        return -1;
    }
    g_capi = m;                           /* keep the reference forever */
    PyGILState_Release(st);
    pthread_mutex_unlock(&g_init_lock);
    return 0;
}

/* Call c_api.<name>(*args); unpack the (status, value) tuple.
 * Returns new ref to value or NULL (error stored). */
static PyObject *capi_call(const char *name, PyObject *args) {
    PyObject *fn = PyObject_GetAttrString(g_capi, name);
    if (!fn) { set_err_from_py(); Py_XDECREF(args); return NULL; }
    PyObject *res = PyObject_CallObject(fn, args);
    Py_DECREF(fn);
    Py_XDECREF(args);
    if (!res) { set_err_from_py(); return NULL; }
    if (!PyTuple_Check(res) || PyTuple_Size(res) != 2) {
        set_err("c_api returned malformed result");
        Py_DECREF(res);
        return NULL;
    }
    long status = PyLong_AsLong(PyTuple_GetItem(res, 0));
    if (status != 0) {
        PyObject *le = PyObject_CallMethod(g_capi, "MXGetLastError", NULL);
        if (le) {
            PyObject *msg = PyTuple_Check(le) && PyTuple_Size(le) == 2
                                ? PyTuple_GetItem(le, 1) : le;
            if (msg && PyUnicode_Check(msg)) set_err(PyUnicode_AsUTF8(msg));
            else set_err("c_api call failed");
            Py_DECREF(le);
        } else {
            PyErr_Clear();
            set_err("c_api call failed");
        }
        Py_DECREF(res);
        return NULL;
    }
    PyObject *val = PyTuple_GetItem(res, 1);
    Py_INCREF(val);
    Py_DECREF(res);
    return val;
}

#define ENSURE() do { if (ensure_init()) return -1; } while (0)

MXTPU_EXPORT const char *MXGetLastError(void) { return g_err; }

MXTPU_EXPORT int MXGetVersion(int *out) {
    ENSURE();
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *v = capi_call("MXGetVersion", PyTuple_New(0));
    int rc = -1;
    if (v) { *out = (int)PyLong_AsLong(v); Py_DECREF(v); rc = 0; }
    PyGILState_Release(st);
    return rc;
}

MXTPU_EXPORT int MXNotifyShutdown(void) {
    ENSURE();
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *v = capi_call("MXNotifyShutdown", PyTuple_New(0));
    int rc = v ? 0 : -1;
    Py_XDECREF(v);
    PyGILState_Release(st);
    return rc;
}

/* ---------------- NDArray ---------------- */

MXTPU_EXPORT int MXNDArrayCreate(const uint32_t *shape, uint32_t ndim,
                                 int dev_type, int dev_id, int delay_alloc,
                                 NDArrayHandle *out) {
    ENSURE();
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *pshape = PyTuple_New(ndim);
    for (uint32_t i = 0; i < ndim; i++)
        PyTuple_SetItem(pshape, i, PyLong_FromUnsignedLong(shape[i]));
    PyObject *v = capi_call("MXNDArrayCreate",
                            Py_BuildValue("(Niii)", pshape, dev_type,
                                          dev_id, delay_alloc));
    int rc = -1;
    if (v) { *out = PyLong_AsUnsignedLongLong(v); Py_DECREF(v); rc = 0; }
    PyGILState_Release(st);
    return rc;
}

MXTPU_EXPORT int MXNDArrayFree(NDArrayHandle h) {
    ENSURE();
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *v = capi_call("MXNDArrayFree", Py_BuildValue("(K)", h));
    int rc = v ? 0 : -1;
    Py_XDECREF(v);
    PyGILState_Release(st);
    return rc;
}

MXTPU_EXPORT int MXNDArraySyncCopyFromCPU(NDArrayHandle h, const void *data,
                                          size_t size) {
    ENSURE();
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *buf = PyBytes_FromStringAndSize((const char *)data,
                                              size * sizeof(float));
    PyObject *v = capi_call("MXNDArraySyncCopyFromBytes",
                            Py_BuildValue("(KN)", h, buf));
    int rc = v ? 0 : -1;
    Py_XDECREF(v);
    PyGILState_Release(st);
    return rc;
}

MXTPU_EXPORT int MXNDArraySyncCopyToCPU(NDArrayHandle h, void *data,
                                        size_t size) {
    ENSURE();
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *v = capi_call("MXNDArraySyncCopyToBytes",
                            Py_BuildValue("(K)", h));
    int rc = -1;
    if (v) {
        size_t n = (size_t)PyBytes_Size(v);
        size_t want = size * sizeof(float);
        if (n != want) {
            /* reference contract (CHECK_EQ(size, arr.Size())): a size
             * mismatch is an error, never a silent truncation */
            char msg[128];
            snprintf(msg, sizeof(msg),
                     "MXNDArraySyncCopyToCPU: caller size %zu bytes does "
                     "not match array size %zu bytes", want, n);
            set_err(msg);
        } else {
            memcpy(data, PyBytes_AsString(v), want);
            rc = 0;
        }
        Py_DECREF(v);
    }
    PyGILState_Release(st);
    return rc;
}

MXTPU_EXPORT int MXNDArrayGetShape(NDArrayHandle h, uint32_t *out_dim,
                                   const uint32_t **out_pdata) {
    ENSURE();
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *v = capi_call("MXNDArrayGetShape", Py_BuildValue("(K)", h));
    int rc = -1;
    if (v) {
        uint32_t n = (uint32_t)PySequence_Size(v);
        if (n > 32) {
            /* never hand out a buffer holding fewer dims than ndim claims */
            char msg[96];
            snprintf(msg, sizeof(msg),
                     "MXNDArrayGetShape: ndim %u exceeds the 32-dim "
                     "shape buffer", n);
            set_err(msg);
        } else {
            uint32_t *buf = (uint32_t *)g_shape_buf;
            for (uint32_t i = 0; i < n; i++) {
                PyObject *it = PySequence_GetItem(v, i);
                buf[i] = (uint32_t)PyLong_AsUnsignedLong(it);
                Py_DECREF(it);
            }
            *out_dim = n;
            *out_pdata = buf;
            rc = 0;
        }
        Py_DECREF(v);
    }
    PyGILState_Release(st);
    return rc;
}

MXTPU_EXPORT int MXNDArrayWaitAll(void) {
    ENSURE();
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *v = capi_call("MXNDArrayWaitAll", PyTuple_New(0));
    int rc = v ? 0 : -1;
    Py_XDECREF(v);
    PyGILState_Release(st);
    return rc;
}

/* ---------------- Symbol ---------------- */

MXTPU_EXPORT int MXSymbolCreateVariable(const char *name, SymbolHandle *out) {
    ENSURE();
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *v = capi_call("MXSymbolCreateVariable",
                            Py_BuildValue("(s)", name));
    int rc = -1;
    if (v) { *out = PyLong_AsUnsignedLongLong(v); Py_DECREF(v); rc = 0; }
    PyGILState_Release(st);
    return rc;
}

MXTPU_EXPORT int MXSymbolCreateAtomicSymbol(const char *op_name,
                                            uint32_t num_param,
                                            const char **keys,
                                            const char **vals,
                                            SymbolHandle *out) {
    ENSURE();
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *pk = PyList_New(num_param), *pv = PyList_New(num_param);
    for (uint32_t i = 0; i < num_param; i++) {
        PyList_SetItem(pk, i, PyUnicode_FromString(keys[i]));
        PyList_SetItem(pv, i, PyUnicode_FromString(vals[i]));
    }
    PyObject *v = capi_call("MXSymbolCreateAtomicSymbol",
                            Py_BuildValue("(sNN)", op_name, pk, pv));
    int rc = -1;
    if (v) { *out = PyLong_AsUnsignedLongLong(v); Py_DECREF(v); rc = 0; }
    PyGILState_Release(st);
    return rc;
}

MXTPU_EXPORT int MXSymbolCompose(SymbolHandle sym, const char *name,
                                 uint32_t num_args, const char **keys,
                                 SymbolHandle *args) {
    ENSURE();
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *pa = PyList_New(num_args);
    for (uint32_t i = 0; i < num_args; i++)
        PyList_SetItem(pa, i, PyLong_FromUnsignedLongLong(args[i]));
    PyObject *pk;
    if (keys) {
        pk = PyList_New(num_args);
        for (uint32_t i = 0; i < num_args; i++)
            PyList_SetItem(pk, i, PyUnicode_FromString(keys[i]));
    } else {
        pk = Py_None;
        Py_INCREF(Py_None);
    }
    PyObject *v = capi_call("MXSymbolCompose",
                            Py_BuildValue("(KsNN)", sym, name, pa, pk));
    int rc = v ? 0 : -1;
    Py_XDECREF(v);
    PyGILState_Release(st);
    return rc;
}

MXTPU_EXPORT int MXSymbolSaveToJSON(SymbolHandle sym, const char **out) {
    ENSURE();
    PyGILState_STATE st = PyGILState_Ensure();
    static __thread char *json_buf = NULL;
    PyObject *v = capi_call("MXSymbolSaveToJSON", Py_BuildValue("(K)", sym));
    int rc = -1;
    if (v) {
        const char *s = PyUnicode_AsUTF8(v);
        free(json_buf);
        json_buf = strdup(s ? s : "");
        *out = json_buf;
        Py_DECREF(v);
        rc = 0;
    }
    PyGILState_Release(st);
    return rc;
}

MXTPU_EXPORT int MXSymbolCreateFromJSON(const char *json, SymbolHandle *out) {
    ENSURE();
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *v = capi_call("MXSymbolCreateFromJSON",
                            Py_BuildValue("(s)", json));
    int rc = -1;
    if (v) { *out = PyLong_AsUnsignedLongLong(v); Py_DECREF(v); rc = 0; }
    PyGILState_Release(st);
    return rc;
}

/* list arguments: returns count; names via repeated calls (thread buffer) */
MXTPU_EXPORT int MXSymbolListArguments(SymbolHandle sym, uint32_t *out_size,
                                       const char ***out_array) {
    ENSURE();
    PyGILState_STATE st = PyGILState_Ensure();
    static __thread char **name_buf = NULL;
    static __thread uint32_t name_cnt = 0;
    PyObject *v = capi_call("MXSymbolListArguments",
                            Py_BuildValue("(K)", sym));
    int rc = -1;
    if (v) {
        for (uint32_t i = 0; i < name_cnt; i++) free(name_buf[i]);
        free(name_buf);
        name_cnt = (uint32_t)PySequence_Size(v);
        name_buf = (char **)calloc(name_cnt, sizeof(char *));
        for (uint32_t i = 0; i < name_cnt; i++) {
            PyObject *it = PySequence_GetItem(v, i);
            name_buf[i] = strdup(PyUnicode_AsUTF8(it));
            Py_DECREF(it);
        }
        *out_size = name_cnt;
        *out_array = (const char **)name_buf;
        Py_DECREF(v);
        rc = 0;
    }
    PyGILState_Release(st);
    return rc;
}

/* ---------------- Executor ---------------- */

MXTPU_EXPORT int MXExecutorBind(SymbolHandle sym, int dev_type, int dev_id,
                                uint32_t num_args, NDArrayHandle *in_args,
                                NDArrayHandle *arg_grads,
                                uint32_t num_aux, NDArrayHandle *aux_states,
                                ExecutorHandle *out) {
    ENSURE();
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *pargs = PyList_New(num_args);
    for (uint32_t i = 0; i < num_args; i++)
        PyList_SetItem(pargs, i, PyLong_FromUnsignedLongLong(in_args[i]));
    PyObject *pgrads;
    if (arg_grads) {
        pgrads = PyList_New(num_args);
        for (uint32_t i = 0; i < num_args; i++)
            PyList_SetItem(pgrads, i,
                           PyLong_FromUnsignedLongLong(arg_grads[i]));
    } else {
        pgrads = Py_None;
        Py_INCREF(Py_None);
    }
    PyObject *paux;
    if (num_aux) {
        paux = PyList_New(num_aux);
        for (uint32_t i = 0; i < num_aux; i++)
            PyList_SetItem(paux, i,
                           PyLong_FromUnsignedLongLong(aux_states[i]));
    } else {
        paux = Py_None;
        Py_INCREF(Py_None);
    }
    PyObject *v = capi_call("MXExecutorBind",
                            Py_BuildValue("(KiiNNsN)", sym, dev_type, dev_id,
                                          pargs, pgrads, "write", paux));
    int rc = -1;
    if (v) { *out = PyLong_AsUnsignedLongLong(v); Py_DECREF(v); rc = 0; }
    PyGILState_Release(st);
    return rc;
}

MXTPU_EXPORT int MXExecutorForward(ExecutorHandle h, int is_train) {
    ENSURE();
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *v = capi_call("MXExecutorForward",
                            Py_BuildValue("(Ki)", h, is_train));
    int rc = v ? 0 : -1;
    Py_XDECREF(v);
    PyGILState_Release(st);
    return rc;
}

MXTPU_EXPORT int MXExecutorBackward(ExecutorHandle h, uint32_t len,
                                    NDArrayHandle *head_grads) {
    ENSURE();
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *pg;
    if (len && head_grads) {
        pg = PyList_New(len);
        for (uint32_t i = 0; i < len; i++)
            PyList_SetItem(pg, i, PyLong_FromUnsignedLongLong(head_grads[i]));
    } else {
        pg = Py_None;
        Py_INCREF(Py_None);
    }
    PyObject *v = capi_call("MXExecutorBackward",
                            Py_BuildValue("(KN)", h, pg));
    int rc = v ? 0 : -1;
    Py_XDECREF(v);
    PyGILState_Release(st);
    return rc;
}

MXTPU_EXPORT int MXExecutorOutputs(ExecutorHandle h, uint32_t *out_size,
                                   NDArrayHandle **out) {
    ENSURE();
    PyGILState_STATE st = PyGILState_Ensure();
    static __thread NDArrayHandle *out_buf = NULL;
    PyObject *v = capi_call("MXExecutorOutputs", Py_BuildValue("(K)", h));
    int rc = -1;
    if (v) {
        uint32_t n = (uint32_t)PySequence_Size(v);
        free(out_buf);
        out_buf = (NDArrayHandle *)calloc(n, sizeof(NDArrayHandle));
        for (uint32_t i = 0; i < n; i++) {
            PyObject *it = PySequence_GetItem(v, i);
            out_buf[i] = PyLong_AsUnsignedLongLong(it);
            Py_DECREF(it);
        }
        *out_size = n;
        *out = out_buf;
        Py_DECREF(v);
        rc = 0;
    }
    PyGILState_Release(st);
    return rc;
}

/* ---------------- KVStore ---------------- */

MXTPU_EXPORT int MXKVStoreCreate(const char *type, KVStoreHandle *out) {
    ENSURE();
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *v = capi_call("MXKVStoreCreate", Py_BuildValue("(s)", type));
    int rc = -1;
    if (v) { *out = PyLong_AsUnsignedLongLong(v); Py_DECREF(v); rc = 0; }
    PyGILState_Release(st);
    return rc;
}

static int kv_keyvals(const char *fname, KVStoreHandle h, uint32_t num,
                      const int *keys, NDArrayHandle *vals) {
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *pk = PyList_New(num), *pv = PyList_New(num);
    for (uint32_t i = 0; i < num; i++) {
        PyList_SetItem(pk, i, PyLong_FromLong(keys[i]));
        PyList_SetItem(pv, i, PyLong_FromUnsignedLongLong(vals[i]));
    }
    PyObject *v = capi_call(fname, Py_BuildValue("(KNN)", h, pk, pv));
    int rc = v ? 0 : -1;
    Py_XDECREF(v);
    PyGILState_Release(st);
    return rc;
}

MXTPU_EXPORT int MXKVStoreInit(KVStoreHandle h, uint32_t num,
                               const int *keys, NDArrayHandle *vals) {
    ENSURE();
    return kv_keyvals("MXKVStoreInit", h, num, keys, vals);
}

MXTPU_EXPORT int MXKVStorePush(KVStoreHandle h, uint32_t num,
                               const int *keys, NDArrayHandle *vals) {
    ENSURE();
    return kv_keyvals("MXKVStorePush", h, num, keys, vals);
}

MXTPU_EXPORT int MXKVStorePull(KVStoreHandle h, uint32_t num,
                               const int *keys, NDArrayHandle *vals) {
    ENSURE();
    return kv_keyvals("MXKVStorePull", h, num, keys, vals);
}

/* ---------------- C predict API (ref: c_predict_api.h) ---------------- */

typedef uint64_t PredictorHandle;

MXTPU_EXPORT int MXPredCreate(const char *symbol_json,
                              const void *param_bytes, int param_size,
                              int dev_type, int dev_id,
                              uint32_t num_input_nodes,
                              const char **input_keys,
                              const uint32_t *input_shape_indptr,
                              const uint32_t *input_shape_data,
                              PredictorHandle *out) {
    ENSURE();
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *pk = PyList_New(num_input_nodes);
    PyObject *ps = PyList_New(num_input_nodes);
    for (uint32_t i = 0; i < num_input_nodes; i++) {
        PyList_SetItem(pk, i, PyUnicode_FromString(input_keys[i]));
        uint32_t b = input_shape_indptr[i], e = input_shape_indptr[i + 1];
        PyObject *shape = PyTuple_New(e - b);
        for (uint32_t j = b; j < e; j++)
            PyTuple_SetItem(shape, j - b,
                            PyLong_FromUnsignedLong(input_shape_data[j]));
        PyList_SetItem(ps, i, shape);
    }
    PyObject *pb = PyBytes_FromStringAndSize(
        (const char *)param_bytes, param_size);
    PyObject *v = capi_call("MXPredCreate",
                            Py_BuildValue("(sNiiNN)", symbol_json, pb,
                                          dev_type, dev_id, pk, ps));
    int rc = -1;
    if (v) { *out = PyLong_AsUnsignedLongLong(v); Py_DECREF(v); rc = 0; }
    PyGILState_Release(st);
    return rc;
}

/* CSR key/shape marshalling shared by the MXPred* entry points: fills
 * *out_keys / *out_shapes with new refs (call under the GIL) */
static void pred_keys_shapes(uint32_t n, const char **keys,
                             const uint32_t *indptr, const uint32_t *data,
                             PyObject **out_keys, PyObject **out_shapes) {
    PyObject *pk = PyList_New(n), *ps = PyList_New(n);
    for (uint32_t i = 0; i < n; i++) {
        PyList_SetItem(pk, i, PyUnicode_FromString(keys[i]));
        uint32_t b = indptr[i], e = indptr[i + 1];
        PyObject *shape = PyTuple_New(e - b);
        for (uint32_t j = b; j < e; j++)
            PyTuple_SetItem(shape, j - b, PyLong_FromUnsignedLong(data[j]));
        PyList_SetItem(ps, i, shape);
    }
    *out_keys = pk;
    *out_shapes = ps;
}

MXTPU_EXPORT int MXPredCreatePartialOut(
    const char *symbol_json, const void *param_bytes, int param_size,
    int dev_type, int dev_id, uint32_t num_input_nodes,
    const char **input_keys, const uint32_t *input_shape_indptr,
    const uint32_t *input_shape_data, uint32_t num_output_nodes,
    const char **output_keys, PredictorHandle *out) {
    ENSURE();
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *pk, *ps;
    pred_keys_shapes(num_input_nodes, input_keys, input_shape_indptr,
                     input_shape_data, &pk, &ps);
    PyObject *po = PyList_New(num_output_nodes);
    for (uint32_t i = 0; i < num_output_nodes; i++)
        PyList_SetItem(po, i, PyUnicode_FromString(output_keys[i]));
    PyObject *pb = PyBytes_FromStringAndSize(
        (const char *)param_bytes, param_size);
    PyObject *v = capi_call("MXPredCreatePartialOut",
                            Py_BuildValue("(sNiiNNN)", symbol_json, pb,
                                          dev_type, dev_id, pk, ps, po));
    int rc = -1;
    if (v) { *out = PyLong_AsUnsignedLongLong(v); Py_DECREF(v); rc = 0; }
    PyGILState_Release(st);
    return rc;
}

MXTPU_EXPORT int MXPredReshape(uint32_t num_input_nodes,
                               const char **input_keys,
                               const uint32_t *input_shape_indptr,
                               const uint32_t *input_shape_data,
                               PredictorHandle handle,
                               PredictorHandle *out) {
    ENSURE();
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *pk, *ps;
    pred_keys_shapes(num_input_nodes, input_keys, input_shape_indptr,
                     input_shape_data, &pk, &ps);
    PyObject *v = capi_call("MXPredReshape",
                            Py_BuildValue("(KNN)", handle, pk, ps));
    int rc = -1;
    if (v) { *out = PyLong_AsUnsignedLongLong(v); Py_DECREF(v); rc = 0; }
    PyGILState_Release(st);
    return rc;
}

MXTPU_EXPORT int MXPredSetInput(PredictorHandle h, const char *key,
                                const float *data, uint32_t size) {
    ENSURE();
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *buf = PyBytes_FromStringAndSize((const char *)data,
                                              (Py_ssize_t)size * 4);
    PyObject *v = capi_call("MXPredSetInput",
                            Py_BuildValue("(KsN)", h, key, buf));
    int rc = v ? 0 : -1;
    Py_XDECREF(v);
    PyGILState_Release(st);
    return rc;
}

MXTPU_EXPORT int MXPredForward(PredictorHandle h) {
    ENSURE();
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *v = capi_call("MXPredForward", Py_BuildValue("(K)", h));
    int rc = v ? 0 : -1;
    Py_XDECREF(v);
    PyGILState_Release(st);
    return rc;
}

MXTPU_EXPORT int MXPredGetOutputShape(PredictorHandle h, uint32_t index,
                                      uint32_t **shape_data,
                                      uint32_t *shape_ndim) {
    ENSURE();
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *v = capi_call("MXPredGetOutputShape",
                            Py_BuildValue("(KI)", h, index));
    int rc = -1;
    if (v) {
        uint32_t n = (uint32_t)PySequence_Size(v);
        if (n > 32) {
            char msg[96];
            snprintf(msg, sizeof(msg),
                     "MXPredGetOutputShape: ndim %u exceeds the 32-dim "
                     "shape buffer", n);
            set_err(msg);
        } else {
            uint32_t *buf = (uint32_t *)g_shape_buf;
            for (uint32_t i = 0; i < n; i++) {
                PyObject *it = PySequence_GetItem(v, i);
                buf[i] = (uint32_t)PyLong_AsUnsignedLong(it);
                Py_DECREF(it);
            }
            *shape_data = buf;
            *shape_ndim = n;
            rc = 0;
        }
        Py_DECREF(v);
    }
    PyGILState_Release(st);
    return rc;
}

MXTPU_EXPORT int MXPredGetOutput(PredictorHandle h, uint32_t index,
                                 float *data, uint32_t size) {
    ENSURE();
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *v = capi_call("MXPredGetOutput", Py_BuildValue("(KI)", h,
                                                             index));
    int rc = -1;
    if (v) {
        size_t n = (size_t)PyBytes_Size(v);
        size_t want = (size_t)size * 4;
        if (n != want) {
            char msg[128];
            snprintf(msg, sizeof(msg),
                     "MXPredGetOutput: caller size %zu bytes does not "
                     "match output size %zu bytes", want, n);
            set_err(msg);
        } else {
            memcpy(data, PyBytes_AsString(v), want);
            rc = 0;
        }
        Py_DECREF(v);
    }
    PyGILState_Release(st);
    return rc;
}

MXTPU_EXPORT int MXPredFree(PredictorHandle h) {
    ENSURE();
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *v = capi_call("MXPredFree", Py_BuildValue("(K)", h));
    int rc = v ? 0 : -1;
    Py_XDECREF(v);
    PyGILState_Release(st);
    return rc;
}

/* ======================================================================
 * r5: remaining c_api.h families — DataIter, autograd, RecordIO, Rtc,
 * profiler, Func registry, op introspection, symbol/executor/kvstore
 * completion (ref: include/mxnet/c_api.h; impls src/c_api/c_api*.cc).
 *
 * Return-buffer contract matches the reference's MXAPIThreadLocalEntry:
 * pointers handed out are valid until the next API call on the SAME
 * thread (per-thread slot arenas below).
 * ====================================================================== */

typedef uint64_t FunctionHandle;
typedef uint64_t AtomicSymbolCreator;
typedef uint64_t DataIterCreator;
typedef uint64_t DataIterHandle;
typedef uint64_t RecordIOHandle;
typedef uint64_t RtcHandle;
typedef unsigned int mx_uint;

/* ---- per-thread return arenas ---- */
#define MXTPU_SLOTS 8
typedef struct { char **strs; uint32_t n; } StrListSlot;
static __thread StrListSlot g_sl[MXTPU_SLOTS];

static void slot_reset(int s) {
    for (uint32_t i = 0; i < g_sl[s].n; i++) free(g_sl[s].strs[i]);
    free(g_sl[s].strs);
    g_sl[s].strs = NULL;
    g_sl[s].n = 0;
}

/* store a python str sequence into slot s; returns the char** array */
static const char **slot_strlist(int s, PyObject *seq, mx_uint *out_n) {
    slot_reset(s);
    uint32_t n = (uint32_t)PySequence_Size(seq);
    g_sl[s].strs = (char **)calloc(n ? n : 1, sizeof(char *));
    for (uint32_t i = 0; i < n; i++) {
        PyObject *it = PySequence_GetItem(seq, i);
        const char *c = it && PyUnicode_Check(it) ? PyUnicode_AsUTF8(it) : "";
        g_sl[s].strs[i] = strdup(c ? c : "");
        Py_XDECREF(it);
    }
    g_sl[s].n = n;
    if (out_n) *out_n = n;
    return (const char **)g_sl[s].strs;
}

/* store one python str into slot s (index 0) */
static const char *slot_str(int s, PyObject *str) {
    slot_reset(s);
    g_sl[s].strs = (char **)calloc(1, sizeof(char *));
    const char *c = str && PyUnicode_Check(str) ? PyUnicode_AsUTF8(str) : "";
    g_sl[s].strs[0] = strdup(c ? c : "");
    g_sl[s].n = 1;
    return g_sl[s].strs[0];
}

/* per-thread uint64 handle-array buffers */
#define MXTPU_HSLOTS 4
static __thread uint64_t *g_hl[MXTPU_HSLOTS];
static uint64_t *hslot_fill(int s, PyObject *seq, mx_uint *out_n) {
    uint32_t n = (uint32_t)PySequence_Size(seq);
    free(g_hl[s]);
    g_hl[s] = (uint64_t *)calloc(n ? n : 1, sizeof(uint64_t));
    for (uint32_t i = 0; i < n; i++) {
        PyObject *it = PySequence_GetItem(seq, i);
        g_hl[s][i] = PyLong_AsUnsignedLongLong(it);
        Py_XDECREF(it);
    }
    if (out_n) *out_n = n;
    return g_hl[s];
}

/* build a python list of uint64 handles (NULL array -> empty list) */
static PyObject *hlist(const uint64_t *hs, uint32_t n) {
    if (!hs) n = 0;
    PyObject *l = PyList_New(n);
    for (uint32_t i = 0; i < n; i++)
        PyList_SetItem(l, i, PyLong_FromUnsignedLongLong(hs[i]));
    return l;
}

/* build a python list of strings (NULL -> empty list) */
static PyObject *slist(const char **ss, uint32_t n) {
    if (!ss) n = 0;
    PyObject *l = PyList_New(n);
    for (uint32_t i = 0; i < n; i++)
        PyList_SetItem(l, i, PyUnicode_FromString(ss[i] ? ss[i] : ""));
    return l;
}

/* common call shapes.
 *
 * The ``args`` expression at every call site builds Python objects
 * (Py_BuildValue / hlist / slist) and therefore MUST run under the GIL —
 * these are GNU statement-expression macros so the GIL is acquired BEFORE
 * the argument expression is evaluated (a plain function would evaluate
 * args at the call site, GIL-less: immediate segfault on 3.12). */
static int call_void_locked(const char *fn, PyObject *args) {
    PyObject *v = capi_call(fn, args);
    int rc = v ? 0 : -1;
    Py_XDECREF(v);
    return rc;
}

static int call_out_u64_locked(const char *fn, PyObject *args,
                               uint64_t *out) {
    PyObject *v = capi_call(fn, args);
    int rc = -1;
    if (v) { *out = PyLong_AsUnsignedLongLong(v); Py_DECREF(v); rc = 0; }
    return rc;
}

static int call_out_int_locked(const char *fn, PyObject *args, int *out) {
    PyObject *v = capi_call(fn, args);
    int rc = -1;
    if (v) { *out = (int)PyLong_AsLong(v); Py_DECREF(v); rc = 0; }
    return rc;
}

static int call_out_str_locked(const char *fn, PyObject *args, int slot,
                               const char **out) {
    PyObject *v = capi_call(fn, args);
    int rc = -1;
    if (v) { *out = slot_str(slot, v); Py_DECREF(v); rc = 0; }
    return rc;
}

static int call_out_strlist_locked(const char *fn, PyObject *args, int slot,
                                   mx_uint *out_n, const char ***out_arr) {
    PyObject *v = capi_call(fn, args);
    int rc = -1;
    if (v) { *out_arr = slot_strlist(slot, v, out_n); Py_DECREF(v); rc = 0; }
    return rc;
}

#define WITH_GIL(expr)                               \
    ({                                               \
        PyGILState_STATE _g = PyGILState_Ensure();   \
        int _rc = (expr);                            \
        PyGILState_Release(_g);                      \
        _rc;                                         \
    })

#define call_void(fn, args) WITH_GIL(call_void_locked(fn, args))
#define call_out_u64(fn, args, out) \
    WITH_GIL(call_out_u64_locked(fn, args, out))
#define call_out_int(fn, args, out) \
    WITH_GIL(call_out_int_locked(fn, args, out))
#define call_out_str(fn, args, slot, out) \
    WITH_GIL(call_out_str_locked(fn, args, slot, out))
#define call_out_strlist(fn, args, slot, out_n, out_arr) \
    WITH_GIL(call_out_strlist_locked(fn, args, slot, out_n, out_arr))

/* ---------------- NDArray (remaining) ---------------- */

MXTPU_EXPORT int MXNDArrayCreateNone(NDArrayHandle *out) {
    ENSURE();
    return call_out_u64("MXNDArrayCreateNone", PyTuple_New(0), out);
}

MXTPU_EXPORT int MXNDArrayCreateEx(const mx_uint *shape, mx_uint ndim,
                                   int dev_type, int dev_id, int delay_alloc,
                                   int dtype, NDArrayHandle *out) {
    ENSURE();
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *pshape = PyTuple_New(ndim);
    for (mx_uint i = 0; i < ndim; i++)
        PyTuple_SetItem(pshape, i, PyLong_FromUnsignedLong(shape[i]));
    PyObject *v = capi_call("MXNDArrayCreateEx",
                            Py_BuildValue("(Niiii)", pshape, dev_type, dev_id,
                                          delay_alloc, dtype));
    int rc = -1;
    if (v) { *out = PyLong_AsUnsignedLongLong(v); Py_DECREF(v); rc = 0; }
    PyGILState_Release(st);
    return rc;
}

MXTPU_EXPORT int MXNDArrayAt(NDArrayHandle h, mx_uint idx,
                             NDArrayHandle *out) {
    ENSURE();
    return call_out_u64("MXNDArrayAt", Py_BuildValue("(KI)", h, idx), out);
}

MXTPU_EXPORT int MXNDArraySlice(NDArrayHandle h, mx_uint begin, mx_uint end,
                                NDArrayHandle *out) {
    ENSURE();
    return call_out_u64("MXNDArraySlice",
                        Py_BuildValue("(KII)", h, begin, end), out);
}

MXTPU_EXPORT int MXNDArrayReshape(NDArrayHandle h, int ndim, int *dims,
                                  NDArrayHandle *out) {
    ENSURE();
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *pshape = PyTuple_New(ndim);
    for (int i = 0; i < ndim; i++)
        PyTuple_SetItem(pshape, i, PyLong_FromLong(dims[i]));
    PyObject *v = capi_call("MXNDArrayReshape",
                            Py_BuildValue("(KN)", h, pshape));
    int rc = -1;
    if (v) { *out = PyLong_AsUnsignedLongLong(v); Py_DECREF(v); rc = 0; }
    PyGILState_Release(st);
    return rc;
}

static int dtype_name2id(const char *n);

MXTPU_EXPORT int MXNDArrayGetDType(NDArrayHandle h, int *out) {
    ENSURE();
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *v = capi_call("MXNDArrayGetDType", Py_BuildValue("(K)", h));
    int rc = -1;
    if (v) {
        /* reference dtype ids (mshadow TypeFlag) */
        *out = dtype_name2id(PyUnicode_AsUTF8(v));
        Py_DECREF(v);
        rc = 0;
    }
    PyGILState_Release(st);
    return rc;
}

MXTPU_EXPORT int MXNDArrayGetContext(NDArrayHandle h, int *out_dev_type,
                                     int *out_dev_id) {
    ENSURE();
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *v = capi_call("MXNDArrayGetContext", Py_BuildValue("(K)", h));
    int rc = -1;
    if (v && PyTuple_Check(v) && PyTuple_Size(v) == 2) {
        *out_dev_type = (int)PyLong_AsLong(PyTuple_GetItem(v, 0));
        *out_dev_id = (int)PyLong_AsLong(PyTuple_GetItem(v, 1));
        rc = 0;
    }
    Py_XDECREF(v);
    PyGILState_Release(st);
    return rc;
}

MXTPU_EXPORT int MXNDArrayWaitToRead(NDArrayHandle h) {
    ENSURE();
    return call_void("MXNDArrayWaitToRead", Py_BuildValue("(K)", h));
}

MXTPU_EXPORT int MXNDArrayWaitToWrite(NDArrayHandle h) {
    ENSURE();
    return call_void("MXNDArrayWaitToWrite", Py_BuildValue("(K)", h));
}

/* raw data view: bytes copied into a per-thread buffer */
static __thread char *g_data_buf = NULL;
MXTPU_EXPORT int MXNDArrayGetData(NDArrayHandle h, void **out_pdata) {
    ENSURE();
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *v = capi_call("MXNDArrayGetData", Py_BuildValue("(K)", h));
    int rc = -1;
    if (v) {
        Py_ssize_t n = PyBytes_Size(v);
        free(g_data_buf);
        g_data_buf = (char *)malloc(n ? n : 1);
        memcpy(g_data_buf, PyBytes_AsString(v), n);
        *out_pdata = g_data_buf;
        Py_DECREF(v);
        rc = 0;
    }
    PyGILState_Release(st);
    return rc;
}

static __thread char *g_raw_buf = NULL;
MXTPU_EXPORT int MXNDArraySaveRawBytes(NDArrayHandle h, size_t *out_size,
                                       const char **out_buf) {
    ENSURE();
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *v = capi_call("MXNDArraySaveRawBytes", Py_BuildValue("(K)", h));
    int rc = -1;
    if (v) {
        Py_ssize_t n = PyBytes_Size(v);
        free(g_raw_buf);
        g_raw_buf = (char *)malloc(n ? n : 1);
        memcpy(g_raw_buf, PyBytes_AsString(v), n);
        *out_size = (size_t)n;
        *out_buf = g_raw_buf;
        Py_DECREF(v);
        rc = 0;
    }
    PyGILState_Release(st);
    return rc;
}

MXTPU_EXPORT int MXNDArrayLoadFromRawBytes(const void *buf, size_t size,
                                           NDArrayHandle *out) {
    ENSURE();
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *pb = PyBytes_FromStringAndSize((const char *)buf,
                                             (Py_ssize_t)size);
    PyObject *v = capi_call("MXNDArrayLoadFromRawBytes",
                            Py_BuildValue("(N)", pb));
    int rc = -1;
    if (v) { *out = PyLong_AsUnsignedLongLong(v); Py_DECREF(v); rc = 0; }
    PyGILState_Release(st);
    return rc;
}

MXTPU_EXPORT int MXNDArraySave(const char *fname, mx_uint num_args,
                               NDArrayHandle *args, const char **keys) {
    ENSURE();
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *v = capi_call("MXNDArraySave",
                            Py_BuildValue("(sNN)", fname,
                                          hlist(args, num_args),
                                          keys ? slist(keys, num_args)
                                               : (Py_INCREF(Py_None),
                                                  Py_None)));
    int rc = v ? 0 : -1;
    Py_XDECREF(v);
    PyGILState_Release(st);
    return rc;
}

MXTPU_EXPORT int MXNDArrayLoad(const char *fname, mx_uint *out_size,
                               NDArrayHandle **out_arr,
                               mx_uint *out_name_size,
                               const char ***out_names) {
    ENSURE();
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *v = capi_call("MXNDArrayLoad", Py_BuildValue("(s)", fname));
    int rc = -1;
    if (v && PyTuple_Check(v) && PyTuple_Size(v) == 2) {
        *out_arr = hslot_fill(0, PyTuple_GetItem(v, 0), out_size);
        *out_names = slot_strlist(0, PyTuple_GetItem(v, 1), out_name_size);
        rc = 0;
    }
    Py_XDECREF(v);
    PyGILState_Release(st);
    return rc;
}

MXTPU_EXPORT int MXRandomSeed(int seed) {
    ENSURE();
    return call_void("MXRandomSeed", Py_BuildValue("(i)", seed));
}

/* ---------------- op invocation + Function registry ---------------- */

MXTPU_EXPORT int MXListAllOpNames(mx_uint *out_size, const char ***out_array) {
    ENSURE();
    return call_out_strlist("MXListAllOpNames", PyTuple_New(0), 1,
                            out_size, out_array);
}

MXTPU_EXPORT int MXImperativeInvoke(AtomicSymbolCreator creator,
                                    int num_inputs, NDArrayHandle *inputs,
                                    int *num_outputs, NDArrayHandle **outputs,
                                    int num_params, const char **param_keys,
                                    const char **param_vals) {
    ENSURE();
    PyGILState_STATE st = PyGILState_Ensure();
    /* creator is an index into the sorted op list: resolve its name */
    PyObject *pname = capi_call("MXSymbolGetAtomicSymbolName",
                                Py_BuildValue("(K)", creator));
    int rc = -1;
    if (pname) {
        PyObject *attrs = PyDict_New();
        for (int i = 0; i < num_params; i++) {
            PyObject *pv = PyUnicode_FromString(param_vals[i]);
            PyDict_SetItemString(attrs, param_keys[i], pv);
            Py_XDECREF(pv);
        }
        if (*outputs != NULL) {
            /* reference contract (c_api_ndarray.cc): a caller-supplied
             * output array means write-in-place into those existing
             * NDArray handles (out= semantics) — the handle array, the
             * count and the handles themselves are left untouched */
            PyObject *v = capi_call(
                "MXImperativeInvokeInPlace",
                Py_BuildValue("(NNNN)", pname,
                              hlist(inputs, (uint32_t)num_inputs), attrs,
                              hlist(*outputs, (uint32_t)*num_outputs)));
            if (v) {
                Py_DECREF(v);
                rc = 0;
            }
        } else {
            PyObject *v = capi_call(
                "MXImperativeInvoke",
                Py_BuildValue("(NNN)", pname,
                              hlist(inputs, (uint32_t)num_inputs), attrs));
            if (v) {
                mx_uint n = 0;
                *outputs = hslot_fill(1, v, &n);
                *num_outputs = (int)n;
                Py_DECREF(v);
                rc = 0;
            }
        }
    }
    PyGILState_Release(st);
    return rc;
}

MXTPU_EXPORT int MXListFunctions(mx_uint *out_size, FunctionHandle **out_array) {
    ENSURE();
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *v = capi_call("MXListFunctions", PyTuple_New(0));
    int rc = -1;
    if (v) { *out_array = hslot_fill(2, v, out_size); Py_DECREF(v); rc = 0; }
    PyGILState_Release(st);
    return rc;
}

MXTPU_EXPORT int MXGetFunction(const char *name, FunctionHandle *out) {
    ENSURE();
    return call_out_u64("MXGetFunction", Py_BuildValue("(s)", name), out);
}

MXTPU_EXPORT int MXFuncGetInfo(FunctionHandle fun, const char **name,
                               const char **description, mx_uint *num_args,
                               const char ***arg_names,
                               const char ***arg_type_infos,
                               const char ***arg_descriptions,
                               const char **return_type) {
    ENSURE();
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *v = capi_call("MXFuncGetInfo", Py_BuildValue("(K)", fun));
    int rc = -1;
    if (v && PyTuple_Check(v) && PyTuple_Size(v) == 6) {
        *name = slot_str(2, PyTuple_GetItem(v, 0));
        *description = slot_str(3, PyTuple_GetItem(v, 1));
        *num_args = (mx_uint)PyLong_AsUnsignedLong(PyTuple_GetItem(v, 2));
        *arg_names = slot_strlist(4, PyTuple_GetItem(v, 3), NULL);
        *arg_type_infos = slot_strlist(5, PyTuple_GetItem(v, 4), NULL);
        *arg_descriptions = slot_strlist(6, PyTuple_GetItem(v, 5), NULL);
        if (return_type) *return_type = "";
        rc = 0;
    }
    Py_XDECREF(v);
    PyGILState_Release(st);
    return rc;
}

MXTPU_EXPORT int MXFuncDescribe(FunctionHandle fun, mx_uint *num_use_vars,
                                mx_uint *num_scalars, mx_uint *num_mutate_vars,
                                int *type_mask) {
    ENSURE();
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *v = capi_call("MXFuncDescribe", Py_BuildValue("(K)", fun));
    int rc = -1;
    if (v && PyTuple_Check(v) && PyTuple_Size(v) == 4) {
        *num_use_vars = (mx_uint)PyLong_AsUnsignedLong(PyTuple_GetItem(v, 0));
        *num_scalars = (mx_uint)PyLong_AsUnsignedLong(PyTuple_GetItem(v, 1));
        *num_mutate_vars =
            (mx_uint)PyLong_AsUnsignedLong(PyTuple_GetItem(v, 2));
        *type_mask = (int)PyLong_AsLong(PyTuple_GetItem(v, 3));
        rc = 0;
    }
    Py_XDECREF(v);
    PyGILState_Release(st);
    return rc;
}

static int func_invoke(FunctionHandle fun, NDArrayHandle *use_vars,
                       float *scalar_args, NDArrayHandle *mutate_vars,
                       int num_params, const char **param_keys,
                       const char **param_vals) {
    PyGILState_STATE st = PyGILState_Ensure();
    mx_uint nu = 0, ns = 0, nm = 0;
    int tm = 0;
    PyObject *d = capi_call("MXFuncDescribe", Py_BuildValue("(K)", fun));
    if (d && PyTuple_Check(d) && PyTuple_Size(d) == 4) {
        nu = (mx_uint)PyLong_AsUnsignedLong(PyTuple_GetItem(d, 0));
        ns = (mx_uint)PyLong_AsUnsignedLong(PyTuple_GetItem(d, 1));
        nm = (mx_uint)PyLong_AsUnsignedLong(PyTuple_GetItem(d, 2));
        tm = (int)PyLong_AsLong(PyTuple_GetItem(d, 3));
        (void)tm;
    }
    Py_XDECREF(d);
    PyObject *scal = PyList_New(ns);
    for (mx_uint i = 0; i < ns; i++)
        PyList_SetItem(scal, i, PyFloat_FromDouble(scalar_args[i]));
    PyObject *v;
    if (num_params > 0) {
        v = capi_call("MXFuncInvokeEx",
                      Py_BuildValue("(KNNNNN)", fun, hlist(use_vars, nu), scal,
                                    hlist(mutate_vars, nm),
                                    slist(param_keys, num_params),
                                    slist(param_vals, num_params)));
    } else {
        v = capi_call("MXFuncInvoke",
                      Py_BuildValue("(KNNN)", fun, hlist(use_vars, nu), scal,
                                    hlist(mutate_vars, nm)));
    }
    int rc = v ? 0 : -1;
    Py_XDECREF(v);
    PyGILState_Release(st);
    return rc;
}

MXTPU_EXPORT int MXFuncInvoke(FunctionHandle fun, NDArrayHandle *use_vars,
                              float *scalar_args, NDArrayHandle *mutate_vars) {
    ENSURE();
    return func_invoke(fun, use_vars, scalar_args, mutate_vars, 0, NULL, NULL);
}

MXTPU_EXPORT int MXFuncInvokeEx(FunctionHandle fun, NDArrayHandle *use_vars,
                                float *scalar_args, NDArrayHandle *mutate_vars,
                                int num_params, const char **param_keys,
                                const char **param_vals) {
    ENSURE();
    return func_invoke(fun, use_vars, scalar_args, mutate_vars, num_params,
                       param_keys, param_vals);
}

/* ---------------- autograd ---------------- */

MXTPU_EXPORT int MXAutogradSetIsTraining(int is_training, int *prev) {
    ENSURE();
    return call_out_int("MXAutogradSetIsTraining",
                        Py_BuildValue("(i)", is_training), prev);
}

MXTPU_EXPORT int MXAutogradMarkVariables(mx_uint num_var,
                                         NDArrayHandle *var_handles,
                                         mx_uint *reqs_array,
                                         NDArrayHandle *grad_handles) {
    ENSURE();
    PyGILState_STATE st = PyGILState_Ensure();
    static const char *req_names[] = {"null", "write", "inplace", "add"};
    PyObject *reqs = PyList_New(num_var);
    for (mx_uint i = 0; i < num_var; i++) {
        mx_uint r = reqs_array ? reqs_array[i] : 1;
        PyList_SetItem(reqs, i, PyUnicode_FromString(
                           r < 4 ? req_names[r] : "write"));
    }
    PyObject *v = capi_call("MXAutogradMarkVariables",
                            Py_BuildValue("(NNN)",
                                          hlist(var_handles, num_var),
                                          hlist(grad_handles, num_var),
                                          reqs));
    int rc = v ? 0 : -1;
    Py_XDECREF(v);
    PyGILState_Release(st);
    return rc;
}

MXTPU_EXPORT int MXAutogradComputeGradient(mx_uint num_output,
                                           NDArrayHandle *output_handles) {
    ENSURE();
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *v = capi_call("MXAutogradComputeGradient",
                            Py_BuildValue("(N)",
                                          hlist(output_handles, num_output)));
    int rc = v ? 0 : -1;
    Py_XDECREF(v);
    PyGILState_Release(st);
    return rc;
}

/* ---------------- Symbol (remaining) ---------------- */

MXTPU_EXPORT int MXSymbolFree(SymbolHandle h) {
    ENSURE();
    return call_void("MXSymbolFree", Py_BuildValue("(K)", h));
}

MXTPU_EXPORT int MXSymbolCopy(SymbolHandle h, SymbolHandle *out) {
    ENSURE();
    return call_out_u64("MXSymbolCopy", Py_BuildValue("(K)", h), out);
}

MXTPU_EXPORT int MXSymbolCreateFromFile(const char *fname, SymbolHandle *out) {
    ENSURE();
    return call_out_u64("MXSymbolCreateFromFile",
                        Py_BuildValue("(s)", fname), out);
}

MXTPU_EXPORT int MXSymbolSaveToFile(SymbolHandle h, const char *fname) {
    ENSURE();
    return call_void("MXSymbolSaveToFile", Py_BuildValue("(Ks)", h, fname));
}

MXTPU_EXPORT int MXSymbolCreateGroup(mx_uint num_symbols,
                                     SymbolHandle *symbols,
                                     SymbolHandle *out) {
    ENSURE();
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *v = capi_call("MXSymbolCreateGroup",
                            Py_BuildValue("(N)",
                                          hlist(symbols, num_symbols)));
    int rc = -1;
    if (v) { *out = PyLong_AsUnsignedLongLong(v); Py_DECREF(v); rc = 0; }
    PyGILState_Release(st);
    return rc;
}

MXTPU_EXPORT int MXSymbolGetName(SymbolHandle h, const char **out,
                                 int *success) {
    ENSURE();
    int rc = call_out_str("MXSymbolGetName", Py_BuildValue("(K)", h), 7, out);
    if (success) *success = (rc == 0 && **out) ? 1 : 0;
    return rc;
}

MXTPU_EXPORT int MXSymbolGetAttr(SymbolHandle h, const char *key,
                                 const char **out, int *success) {
    ENSURE();
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *v = capi_call("MXSymbolGetAttr", Py_BuildValue("(Ks)", h, key));
    int rc = -1;
    if (v && PyTuple_Check(v) && PyTuple_Size(v) == 2) {
        *out = slot_str(7, PyTuple_GetItem(v, 0));
        *success = (int)PyLong_AsLong(PyTuple_GetItem(v, 1));
        rc = 0;
    }
    Py_XDECREF(v);
    PyGILState_Release(st);
    return rc;
}

MXTPU_EXPORT int MXSymbolSetAttr(SymbolHandle h, const char *key,
                                 const char *value) {
    ENSURE();
    return call_void("MXSymbolSetAttr", Py_BuildValue("(Kss)", h, key, value));
}

MXTPU_EXPORT int MXSymbolListAttr(SymbolHandle h, mx_uint *out_size,
                                  const char ***out) {
    ENSURE();
    mx_uint n = 0;
    int rc = call_out_strlist("MXSymbolListAttr", Py_BuildValue("(K)", h), 1,
                              &n, out);
    if (rc == 0) *out_size = n / 2;  /* pairs, ref contract */
    return rc;
}

MXTPU_EXPORT int MXSymbolListAttrShallow(SymbolHandle h, mx_uint *out_size,
                                         const char ***out) {
    ENSURE();
    mx_uint n = 0;
    int rc = call_out_strlist("MXSymbolListAttrShallow",
                              Py_BuildValue("(K)", h), 1, &n, out);
    if (rc == 0) *out_size = n / 2;
    return rc;
}

MXTPU_EXPORT int MXSymbolListOutputs(SymbolHandle h, mx_uint *out_size,
                                     const char ***out_array) {
    ENSURE();
    return call_out_strlist("MXSymbolListOutputs", Py_BuildValue("(K)", h), 1,
                            out_size, out_array);
}

MXTPU_EXPORT int MXSymbolListAuxiliaryStates(SymbolHandle h, mx_uint *out_size,
                                             const char ***out_array) {
    ENSURE();
    return call_out_strlist("MXSymbolListAuxiliaryStates",
                            Py_BuildValue("(K)", h), 2, out_size, out_array);
}

MXTPU_EXPORT int MXSymbolGetInternals(SymbolHandle h, SymbolHandle *out) {
    ENSURE();
    return call_out_u64("MXSymbolGetInternals", Py_BuildValue("(K)", h), out);
}

MXTPU_EXPORT int MXSymbolGetChildren(SymbolHandle h, SymbolHandle *out) {
    ENSURE();
    return call_out_u64("MXSymbolGetChildren", Py_BuildValue("(K)", h), out);
}

MXTPU_EXPORT int MXSymbolGetOutput(SymbolHandle h, mx_uint index,
                                   SymbolHandle *out) {
    ENSURE();
    return call_out_u64("MXSymbolGetOutput", Py_BuildValue("(KI)", h, index),
                        out);
}

MXTPU_EXPORT int MXSymbolGrad(SymbolHandle h, mx_uint num_wrt,
                              const char **wrt, SymbolHandle *out) {
    ENSURE();
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *v = capi_call("MXSymbolGrad",
                            Py_BuildValue("(KN)", h, slist(wrt, num_wrt)));
    int rc = v ? 0 : -1;  /* matches reference: always errors */
    Py_XDECREF(v);
    PyGILState_Release(st);
    return rc;
}

MXTPU_EXPORT int MXSymbolPrint(SymbolHandle h, const char **out_str) {
    ENSURE();
    return call_out_str("MXSymbolPrint", Py_BuildValue("(K)", h), 3, out_str);
}

MXTPU_EXPORT int MXExecutorPrint(ExecutorHandle h, const char **out_str) {
    ENSURE();
    return call_out_str("MXExecutorPrint", Py_BuildValue("(K)", h), 3,
                        out_str);
}

/* ---- shape inference: CSR in, three shape groups out ---- */

typedef struct {
    mx_uint *ndims;
    mx_uint **datas;   /* per-shape pointers */
    mx_uint *flat;     /* backing storage */
    mx_uint n;
} ShapeGroup;
static __thread ShapeGroup g_sg[3];

static void shape_group_reset(int g) {
    free(g_sg[g].ndims); free(g_sg[g].datas); free(g_sg[g].flat);
    memset(&g_sg[g], 0, sizeof(ShapeGroup));
}

/* fill group g from a python list of int tuples */
static int shape_group_fill(int g, PyObject *shapes) {
    shape_group_reset(g);
    mx_uint n = (mx_uint)PySequence_Size(shapes);
    size_t total = 0;
    for (mx_uint i = 0; i < n; i++) {
        PyObject *s = PySequence_GetItem(shapes, i);
        total += (size_t)(s && s != Py_None ? PySequence_Size(s) : 0);
        Py_XDECREF(s);
    }
    g_sg[g].n = n;
    g_sg[g].ndims = (mx_uint *)calloc(n ? n : 1, sizeof(mx_uint));
    g_sg[g].datas = (mx_uint **)calloc(n ? n : 1, sizeof(mx_uint *));
    g_sg[g].flat = (mx_uint *)calloc(total ? total : 1, sizeof(mx_uint));
    size_t off = 0;
    for (mx_uint i = 0; i < n; i++) {
        PyObject *s = PySequence_GetItem(shapes, i);
        mx_uint nd = (mx_uint)(s && s != Py_None ? PySequence_Size(s) : 0);
        g_sg[g].ndims[i] = nd;
        g_sg[g].datas[i] = g_sg[g].flat + off;
        for (mx_uint j = 0; j < nd; j++) {
            PyObject *d = PySequence_GetItem(s, j);
            g_sg[g].flat[off + j] = (mx_uint)PyLong_AsUnsignedLong(d);
            Py_XDECREF(d);
        }
        off += nd;
        Py_XDECREF(s);
    }
    return 0;
}

static int infer_shape_impl(const char *fname, SymbolHandle sym,
                            mx_uint num_args, const char **keys,
                            const mx_uint *arg_ind_ptr,
                            const mx_uint *arg_shape_data,
                            mx_uint *in_size, const mx_uint **in_ndim,
                            const mx_uint ***in_data, mx_uint *out_size,
                            const mx_uint **out_ndim, const mx_uint ***out_data,
                            mx_uint *aux_size, const mx_uint **aux_ndim,
                            const mx_uint ***aux_data, int *complete) {
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *pk = PyList_New(num_args), *ps = PyList_New(num_args);
    for (mx_uint i = 0; i < num_args; i++) {
        PyList_SetItem(pk, i, PyUnicode_FromString(keys[i]));
        mx_uint b = arg_ind_ptr[i], e = arg_ind_ptr[i + 1];
        PyObject *shape = PyTuple_New(e - b);
        for (mx_uint j = b; j < e; j++)
            PyTuple_SetItem(shape, j - b,
                            PyLong_FromUnsignedLong(arg_shape_data[j]));
        PyList_SetItem(ps, i, shape);
    }
    PyObject *v = capi_call(fname, Py_BuildValue("(KNN)", sym, pk, ps));
    int rc = -1;
    if (v && PyTuple_Check(v) && PyTuple_Size(v) == 3) {
        /* completeness = no None entries in the arg/output groups; a None
         * is "unknown", an empty tuple is a legitimate scalar shape —
         * ndim alone cannot distinguish them */
        if (complete) {
            *complete = 1;
            for (int g = 0; g < 2; g++) {
                PyObject *lst = PyTuple_GetItem(v, g);
                Py_ssize_t n = PySequence_Size(lst);
                for (Py_ssize_t i = 0; i < n; i++) {
                    PyObject *s = PySequence_GetItem(lst, i);
                    if (s == Py_None) *complete = 0;
                    Py_XDECREF(s);
                }
            }
        }
        shape_group_fill(0, PyTuple_GetItem(v, 0));
        shape_group_fill(1, PyTuple_GetItem(v, 1));
        shape_group_fill(2, PyTuple_GetItem(v, 2));
        *in_size = g_sg[0].n; *in_ndim = g_sg[0].ndims;
        *in_data = (const mx_uint **)g_sg[0].datas;
        *out_size = g_sg[1].n; *out_ndim = g_sg[1].ndims;
        *out_data = (const mx_uint **)g_sg[1].datas;
        *aux_size = g_sg[2].n; *aux_ndim = g_sg[2].ndims;
        *aux_data = (const mx_uint **)g_sg[2].datas;
        rc = 0;
    }
    Py_XDECREF(v);
    PyGILState_Release(st);
    return rc;
}

MXTPU_EXPORT int MXSymbolInferShape(SymbolHandle sym, mx_uint num_args,
                                    const char **keys,
                                    const mx_uint *arg_ind_ptr,
                                    const mx_uint *arg_shape_data,
                                    mx_uint *in_size, const mx_uint **in_ndim,
                                    const mx_uint ***in_data,
                                    mx_uint *out_size,
                                    const mx_uint **out_ndim,
                                    const mx_uint ***out_data,
                                    mx_uint *aux_size,
                                    const mx_uint **aux_ndim,
                                    const mx_uint ***aux_data, int *complete) {
    ENSURE();
    return infer_shape_impl("MXSymbolInferShape", sym, num_args, keys,
                            arg_ind_ptr, arg_shape_data, in_size, in_ndim,
                            in_data, out_size, out_ndim, out_data, aux_size,
                            aux_ndim, aux_data, complete);
}

MXTPU_EXPORT int MXSymbolInferShapePartial(
    SymbolHandle sym, mx_uint num_args, const char **keys,
    const mx_uint *arg_ind_ptr, const mx_uint *arg_shape_data,
    mx_uint *in_size, const mx_uint **in_ndim, const mx_uint ***in_data,
    mx_uint *out_size, const mx_uint **out_ndim, const mx_uint ***out_data,
    mx_uint *aux_size, const mx_uint **aux_ndim, const mx_uint ***aux_data,
    int *complete) {
    ENSURE();
    return infer_shape_impl("MXSymbolInferShapePartial", sym, num_args, keys,
                            arg_ind_ptr, arg_shape_data, in_size, in_ndim,
                            in_data, out_size, out_ndim, out_data, aux_size,
                            aux_ndim, aux_data, complete);
}

/* dtype-id based InferType (ref ids as in MXNDArrayGetDType) */
static const char *dtype_id2name(int id) {
    switch (id) {
        case 0: return "float32"; case 1: return "float64";
        case 2: return "float16"; case 3: return "uint8";
        case 4: return "int32"; case 5: return "int8";
        case 6: return "int64"; case 12: return "bfloat16";
        default: return NULL;
    }
}
static int dtype_name2id(const char *n) {
    if (!n) return -1;
    if (!strcmp(n, "float32")) return 0;
    if (!strcmp(n, "float64")) return 1;
    if (!strcmp(n, "float16")) return 2;
    if (!strcmp(n, "uint8")) return 3;
    if (!strcmp(n, "int32")) return 4;
    if (!strcmp(n, "int8")) return 5;
    if (!strcmp(n, "int64")) return 6;
    if (!strcmp(n, "bfloat16")) return 12;
    return -1;
}

static __thread int *g_ty[3];
MXTPU_EXPORT int MXSymbolInferType(SymbolHandle sym, mx_uint num_args,
                                   const char **keys, const int *arg_type_data,
                                   mx_uint *in_size, const int **in_type,
                                   mx_uint *out_size, const int **out_type,
                                   mx_uint *aux_size, const int **aux_type,
                                   int *complete) {
    ENSURE();
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *pk = PyList_New(num_args), *pt = PyList_New(num_args);
    for (mx_uint i = 0; i < num_args; i++) {
        PyList_SetItem(pk, i, PyUnicode_FromString(keys[i]));
        const char *tn = dtype_id2name(arg_type_data[i]);
        PyList_SetItem(pt, i, PyUnicode_FromString(tn ? tn : "float32"));
    }
    PyObject *v = capi_call("MXSymbolInferType",
                            Py_BuildValue("(KNN)", sym, pk, pt));
    int rc = -1;
    if (v && PyTuple_Check(v) && PyTuple_Size(v) == 3) {
        mx_uint *sizes[3] = {in_size, out_size, aux_size};
        const int **outs[3] = {in_type, out_type, aux_type};
        if (complete) *complete = 1;
        for (int g = 0; g < 3; g++) {
            PyObject *lst = PyTuple_GetItem(v, g);
            mx_uint n = (mx_uint)PySequence_Size(lst);
            free(g_ty[g]);
            g_ty[g] = (int *)calloc(n ? n : 1, sizeof(int));
            for (mx_uint i = 0; i < n; i++) {
                PyObject *it = PySequence_GetItem(lst, i);
                if (it == Py_None) {
                    g_ty[g][i] = -1;
                    if (complete) *complete = 0;
                } else {
                    g_ty[g][i] = dtype_name2id(PyUnicode_AsUTF8(it));
                }
                Py_XDECREF(it);
            }
            *sizes[g] = n;
            *outs[g] = g_ty[g];
        }
        rc = 0;
    }
    Py_XDECREF(v);
    PyGILState_Release(st);
    return rc;
}

/* ---------------- op introspection ---------------- */

MXTPU_EXPORT int MXSymbolListAtomicSymbolCreators(mx_uint *out_size,
                                                  AtomicSymbolCreator **out) {
    ENSURE();
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *v = capi_call("MXSymbolListAtomicSymbolCreators",
                            PyTuple_New(0));
    int rc = -1;
    if (v) { *out = hslot_fill(3, v, out_size); Py_DECREF(v); rc = 0; }
    PyGILState_Release(st);
    return rc;
}

MXTPU_EXPORT int MXSymbolGetAtomicSymbolName(AtomicSymbolCreator creator,
                                             const char **name) {
    ENSURE();
    return call_out_str("MXSymbolGetAtomicSymbolName",
                        Py_BuildValue("(K)", creator), 0, name);
}

MXTPU_EXPORT int MXSymbolGetAtomicSymbolInfo(
    AtomicSymbolCreator creator, const char **name, const char **description,
    mx_uint *num_args, const char ***arg_names, const char ***arg_type_infos,
    const char ***arg_descriptions, const char **key_var_num_args,
    const char **return_type) {
    ENSURE();
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *v = capi_call("MXSymbolGetAtomicSymbolInfo",
                            Py_BuildValue("(K)", creator));
    int rc = -1;
    if (v && PyTuple_Check(v) && PyTuple_Size(v) == 8) {
        *name = slot_str(0, PyTuple_GetItem(v, 0));
        *description = slot_str(1, PyTuple_GetItem(v, 1));
        *num_args = (mx_uint)PyLong_AsUnsignedLong(PyTuple_GetItem(v, 2));
        *arg_names = slot_strlist(4, PyTuple_GetItem(v, 3), NULL);
        *arg_type_infos = slot_strlist(5, PyTuple_GetItem(v, 4), NULL);
        *arg_descriptions = slot_strlist(6, PyTuple_GetItem(v, 5), NULL);
        *key_var_num_args = slot_str(2, PyTuple_GetItem(v, 6));
        if (return_type) *return_type = slot_str(3, PyTuple_GetItem(v, 7));
        rc = 0;
    }
    Py_XDECREF(v);
    PyGILState_Release(st);
    return rc;
}

/* ---------------- Executor (remaining) ---------------- */

MXTPU_EXPORT int MXExecutorFree(ExecutorHandle h) {
    ENSURE();
    return call_void("MXExecutorFree", Py_BuildValue("(K)", h));
}

static PyObject *grad_req_list(const mx_uint *reqs, mx_uint len) {
    static const char *names[] = {"null", "write", "inplace", "add"};
    PyObject *l = PyList_New(len);
    for (mx_uint i = 0; i < len; i++) {
        mx_uint r = reqs ? reqs[i] : 1;
        PyList_SetItem(l, i, PyUnicode_FromString(r < 4 ? names[r] : "write"));
    }
    return l;
}

static int bind_x(const char *fname, SymbolHandle sym, int dev_type,
                  int dev_id, mx_uint num_map, const char **map_keys,
                  const int *map_dev_types, const int *map_dev_ids,
                  mx_uint len, NDArrayHandle *in_args,
                  NDArrayHandle *arg_grad_store, mx_uint *grad_req_type,
                  mx_uint aux_len, NDArrayHandle *aux_states,
                  ExecutorHandle shared_exec, ExecutorHandle *out) {
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *gk = PyList_New(num_map), *gt = PyList_New(num_map),
             *gi = PyList_New(num_map);
    for (mx_uint i = 0; i < num_map; i++) {
        PyList_SetItem(gk, i, PyUnicode_FromString(map_keys[i]));
        PyList_SetItem(gt, i, PyLong_FromLong(map_dev_types[i]));
        PyList_SetItem(gi, i, PyLong_FromLong(map_dev_ids[i]));
    }
    PyObject *args;
    if (shared_exec) {
        args = Py_BuildValue("(KiiNNNNNNNK)", sym, dev_type, dev_id, gk, gt,
                             gi, hlist(in_args, len),
                             hlist(arg_grad_store, len),
                             grad_req_list(grad_req_type, len),
                             hlist(aux_states, aux_len), shared_exec);
    } else {
        args = Py_BuildValue("(KiiNNNNNNN)", sym, dev_type, dev_id, gk, gt,
                             gi, hlist(in_args, len),
                             hlist(arg_grad_store, len),
                             grad_req_list(grad_req_type, len),
                             hlist(aux_states, aux_len));
    }
    PyObject *v = capi_call(fname, args);
    int rc = -1;
    if (v) { *out = PyLong_AsUnsignedLongLong(v); Py_DECREF(v); rc = 0; }
    PyGILState_Release(st);
    return rc;
}

MXTPU_EXPORT int MXExecutorBindX(SymbolHandle sym, int dev_type, int dev_id,
                                 mx_uint num_map, const char **map_keys,
                                 const int *map_dev_types,
                                 const int *map_dev_ids, mx_uint len,
                                 NDArrayHandle *in_args,
                                 NDArrayHandle *arg_grad_store,
                                 mx_uint *grad_req_type, mx_uint aux_len,
                                 NDArrayHandle *aux_states,
                                 ExecutorHandle *out) {
    ENSURE();
    return bind_x("MXExecutorBindX", sym, dev_type, dev_id, num_map, map_keys,
                  map_dev_types, map_dev_ids, len, in_args, arg_grad_store,
                  grad_req_type, aux_len, aux_states, 0, out);
}

MXTPU_EXPORT int MXExecutorBindEX(SymbolHandle sym, int dev_type, int dev_id,
                                  mx_uint num_map, const char **map_keys,
                                  const int *map_dev_types,
                                  const int *map_dev_ids, mx_uint len,
                                  NDArrayHandle *in_args,
                                  NDArrayHandle *arg_grad_store,
                                  mx_uint *grad_req_type, mx_uint aux_len,
                                  NDArrayHandle *aux_states,
                                  ExecutorHandle shared_exec,
                                  ExecutorHandle *out) {
    ENSURE();
    return bind_x("MXExecutorBindEX", sym, dev_type, dev_id, num_map,
                  map_keys, map_dev_types, map_dev_ids, len, in_args,
                  arg_grad_store, grad_req_type, aux_len, aux_states,
                  shared_exec, out);
}

typedef void (*ExecutorMonitorCallback)(const char *, NDArrayHandle, void *);

MXTPU_EXPORT int MXExecutorSetMonitorCallback(ExecutorHandle h,
                                              ExecutorMonitorCallback cb,
                                              void *cb_handle) {
    ENSURE();
    return call_void("MXExecutorSetMonitorCallback",
                     Py_BuildValue("(KKK)", h, (uint64_t)(uintptr_t)cb,
                                   (uint64_t)(uintptr_t)cb_handle));
}

/* ---------------- DataIter ---------------- */

MXTPU_EXPORT int MXListDataIters(mx_uint *out_size, DataIterCreator **out) {
    ENSURE();
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *v = capi_call("MXListDataIters", PyTuple_New(0));
    int rc = -1;
    if (v) { *out = hslot_fill(3, v, out_size); Py_DECREF(v); rc = 0; }
    PyGILState_Release(st);
    return rc;
}

MXTPU_EXPORT int MXDataIterGetIterInfo(DataIterCreator creator,
                                       const char **name,
                                       const char **description,
                                       mx_uint *num_args,
                                       const char ***arg_names,
                                       const char ***arg_type_infos,
                                       const char ***arg_descriptions) {
    ENSURE();
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *v = capi_call("MXDataIterGetIterInfo",
                            Py_BuildValue("(K)", creator));
    int rc = -1;
    if (v && PyTuple_Check(v) && PyTuple_Size(v) == 6) {
        *name = slot_str(0, PyTuple_GetItem(v, 0));
        *description = slot_str(1, PyTuple_GetItem(v, 1));
        *num_args = (mx_uint)PyLong_AsUnsignedLong(PyTuple_GetItem(v, 2));
        *arg_names = slot_strlist(4, PyTuple_GetItem(v, 3), NULL);
        *arg_type_infos = slot_strlist(5, PyTuple_GetItem(v, 4), NULL);
        *arg_descriptions = slot_strlist(6, PyTuple_GetItem(v, 5), NULL);
        rc = 0;
    }
    Py_XDECREF(v);
    PyGILState_Release(st);
    return rc;
}

MXTPU_EXPORT int MXDataIterCreateIter(DataIterCreator creator,
                                      mx_uint num_param, const char **keys,
                                      const char **vals,
                                      DataIterHandle *out) {
    ENSURE();
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *v = capi_call("MXDataIterCreateIter",
                            Py_BuildValue("(KNN)", creator,
                                          slist(keys, num_param),
                                          slist(vals, num_param)));
    int rc = -1;
    if (v) { *out = PyLong_AsUnsignedLongLong(v); Py_DECREF(v); rc = 0; }
    PyGILState_Release(st);
    return rc;
}

MXTPU_EXPORT int MXDataIterFree(DataIterHandle h) {
    ENSURE();
    return call_void("MXDataIterFree", Py_BuildValue("(K)", h));
}

MXTPU_EXPORT int MXDataIterNext(DataIterHandle h, int *out) {
    ENSURE();
    return call_out_int("MXDataIterNext", Py_BuildValue("(K)", h), out);
}

MXTPU_EXPORT int MXDataIterBeforeFirst(DataIterHandle h) {
    ENSURE();
    return call_void("MXDataIterBeforeFirst", Py_BuildValue("(K)", h));
}

MXTPU_EXPORT int MXDataIterGetData(DataIterHandle h, NDArrayHandle *out) {
    ENSURE();
    return call_out_u64("MXDataIterGetData", Py_BuildValue("(K)", h), out);
}

MXTPU_EXPORT int MXDataIterGetLabel(DataIterHandle h, NDArrayHandle *out) {
    ENSURE();
    return call_out_u64("MXDataIterGetLabel", Py_BuildValue("(K)", h), out);
}

static __thread uint64_t *g_idx_buf = NULL;
MXTPU_EXPORT int MXDataIterGetIndex(DataIterHandle h, uint64_t **out_index,
                                    uint64_t *out_size) {
    ENSURE();
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *v = capi_call("MXDataIterGetIndex", Py_BuildValue("(K)", h));
    int rc = -1;
    if (v) {
        uint64_t n = (uint64_t)PySequence_Size(v);
        free(g_idx_buf);
        g_idx_buf = (uint64_t *)calloc(n ? n : 1, sizeof(uint64_t));
        for (uint64_t i = 0; i < n; i++) {
            PyObject *it = PySequence_GetItem(v, i);
            g_idx_buf[i] = PyLong_AsUnsignedLongLong(it);
            Py_XDECREF(it);
        }
        *out_index = g_idx_buf;
        *out_size = n;
        Py_DECREF(v);
        rc = 0;
    }
    PyGILState_Release(st);
    return rc;
}

MXTPU_EXPORT int MXDataIterGetPadNum(DataIterHandle h, int *pad) {
    ENSURE();
    return call_out_int("MXDataIterGetPadNum", Py_BuildValue("(K)", h), pad);
}

/* ---------------- RecordIO ---------------- */

MXTPU_EXPORT int MXRecordIOWriterCreate(const char *uri, RecordIOHandle *out) {
    ENSURE();
    return call_out_u64("MXRecordIOWriterCreate", Py_BuildValue("(s)", uri),
                        out);
}

MXTPU_EXPORT int MXRecordIOWriterFree(RecordIOHandle h) {
    ENSURE();
    return call_void("MXRecordIOWriterFree", Py_BuildValue("(K)", h));
}

MXTPU_EXPORT int MXRecordIOWriterWriteRecord(RecordIOHandle h, const char *buf,
                                             size_t size) {
    ENSURE();
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *pb = PyBytes_FromStringAndSize(buf, (Py_ssize_t)size);
    PyObject *v = capi_call("MXRecordIOWriterWriteRecord",
                            Py_BuildValue("(KN)", h, pb));
    int rc = v ? 0 : -1;
    Py_XDECREF(v);
    PyGILState_Release(st);
    return rc;
}

MXTPU_EXPORT int MXRecordIOWriterTell(RecordIOHandle h, size_t *pos) {
    ENSURE();
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *v = capi_call("MXRecordIOWriterTell", Py_BuildValue("(K)", h));
    int rc = -1;
    if (v) { *pos = (size_t)PyLong_AsUnsignedLongLong(v); Py_DECREF(v);
             rc = 0; }
    PyGILState_Release(st);
    return rc;
}

MXTPU_EXPORT int MXRecordIOReaderCreate(const char *uri, RecordIOHandle *out) {
    ENSURE();
    return call_out_u64("MXRecordIOReaderCreate", Py_BuildValue("(s)", uri),
                        out);
}

MXTPU_EXPORT int MXRecordIOReaderFree(RecordIOHandle h) {
    ENSURE();
    return call_void("MXRecordIOReaderFree", Py_BuildValue("(K)", h));
}

static __thread char *g_rec_buf = NULL;
MXTPU_EXPORT int MXRecordIOReaderReadRecord(RecordIOHandle h,
                                            char const **buf, size_t *size) {
    ENSURE();
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *v = capi_call("MXRecordIOReaderReadRecord",
                            Py_BuildValue("(K)", h));
    int rc = -1;
    if (v) {
        Py_ssize_t n = PyBytes_Size(v);
        free(g_rec_buf);
        g_rec_buf = (char *)malloc(n ? n : 1);
        memcpy(g_rec_buf, PyBytes_AsString(v), n);
        *buf = n ? g_rec_buf : NULL;  /* NULL at EOF, ref contract */
        *size = (size_t)n;
        Py_DECREF(v);
        rc = 0;
    }
    PyGILState_Release(st);
    return rc;
}

MXTPU_EXPORT int MXRecordIOReaderSeek(RecordIOHandle h, size_t pos) {
    ENSURE();
    return call_void("MXRecordIOReaderSeek", Py_BuildValue("(KK)", h,
                                                           (uint64_t)pos));
}

/* ---------------- Rtc ---------------- */

MXTPU_EXPORT int MXRtcCreate(char *name, mx_uint num_input, mx_uint num_output,
                             char **input_names, char **output_names,
                             NDArrayHandle *inputs, NDArrayHandle *outputs,
                             char *kernel, RtcHandle *out) {
    ENSURE();
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *v = capi_call(
        "MXRtcCreate",
        Py_BuildValue("(sNNNNs)", name,
                      slist((const char **)input_names, num_input),
                      slist((const char **)output_names, num_output),
                      hlist(inputs, num_input), hlist(outputs, num_output),
                      kernel));
    int rc = -1;
    if (v) { *out = PyLong_AsUnsignedLongLong(v); Py_DECREF(v); rc = 0; }
    PyGILState_Release(st);
    return rc;
}

MXTPU_EXPORT int MXRtcPush(RtcHandle h, mx_uint num_input, mx_uint num_output,
                           NDArrayHandle *inputs, NDArrayHandle *outputs,
                           mx_uint gridDimX, mx_uint gridDimY,
                           mx_uint gridDimZ, mx_uint blockDimX,
                           mx_uint blockDimY, mx_uint blockDimZ) {
    ENSURE();
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *v = capi_call(
        "MXRtcPush",
        Py_BuildValue("(KNNIIIIII)", h, hlist(inputs, num_input),
                      hlist(outputs, num_output), gridDimX, gridDimY,
                      gridDimZ, blockDimX, blockDimY, blockDimZ));
    int rc = v ? 0 : -1;
    Py_XDECREF(v);
    PyGILState_Release(st);
    return rc;
}

MXTPU_EXPORT int MXRtcFree(RtcHandle h) {
    ENSURE();
    return call_void("MXRtcFree", Py_BuildValue("(K)", h));
}

/* ---------------- profiler ---------------- */

MXTPU_EXPORT int MXSetProfilerConfig(int mode, const char *filename) {
    ENSURE();
    return call_void("MXSetProfilerConfig",
                     Py_BuildValue("(is)", mode, filename));
}

MXTPU_EXPORT int MXSetProfilerState(int state) {
    ENSURE();
    return call_void("MXSetProfilerState", Py_BuildValue("(i)", state));
}

MXTPU_EXPORT int MXDumpProfile(void) {
    ENSURE();
    return call_void("MXDumpProfile", PyTuple_New(0));
}

/* ---------------- KVStore (remaining) ---------------- */

MXTPU_EXPORT int MXKVStoreFree(KVStoreHandle h) {
    ENSURE();
    return call_void("MXKVStoreFree", Py_BuildValue("(K)", h));
}

MXTPU_EXPORT int MXKVStoreGetType(KVStoreHandle h, const char **type) {
    ENSURE();
    return call_out_str("MXKVStoreGetType", Py_BuildValue("(K)", h), 7, type);
}

MXTPU_EXPORT int MXKVStoreGetRank(KVStoreHandle h, int *rank) {
    ENSURE();
    return call_out_int("MXKVStoreGetRank", Py_BuildValue("(K)", h), rank);
}

MXTPU_EXPORT int MXKVStoreGetGroupSize(KVStoreHandle h, int *size) {
    ENSURE();
    return call_out_int("MXKVStoreGetGroupSize", Py_BuildValue("(K)", h),
                        size);
}

MXTPU_EXPORT int MXKVStoreBarrier(KVStoreHandle h) {
    ENSURE();
    return call_void("MXKVStoreBarrier", Py_BuildValue("(K)", h));
}

MXTPU_EXPORT int MXKVStoreGetNumDeadNode(KVStoreHandle h, const int node_id,
                                         int *number, const int timeout_sec) {
    ENSURE();
    return call_out_int("MXKVStoreGetNumDeadNode",
                        Py_BuildValue("(Kii)", h, node_id, timeout_sec),
                        number);
}

MXTPU_EXPORT int MXKVStoreIsWorkerNode(int *ret) {
    ENSURE();
    return call_out_int("MXKVStoreIsWorkerNode", PyTuple_New(0), ret);
}

MXTPU_EXPORT int MXKVStoreIsServerNode(int *ret) {
    ENSURE();
    return call_out_int("MXKVStoreIsServerNode", PyTuple_New(0), ret);
}

MXTPU_EXPORT int MXKVStoreIsSchedulerNode(int *ret) {
    ENSURE();
    return call_out_int("MXKVStoreIsSchedulerNode", PyTuple_New(0), ret);
}

MXTPU_EXPORT int MXKVStoreRunServer(KVStoreHandle h,
                                    void *controller, void *controller_handle) {
    ENSURE();
    (void)controller; (void)controller_handle;
    return call_void("MXKVStoreRunServer", Py_BuildValue("(K)", h));
}

/* Length-explicit variant: command bodies are arbitrary bytes (the cmd_id 0
 * kController body is a pickled optimizer, which contains NULs), so the
 * NUL-terminated legacy signature cannot carry them faithfully. */
MXTPU_EXPORT int MXKVStoreSendCommmandToServersEx(KVStoreHandle h, int cmd_id,
                                                  const char *cmd_body,
                                                  size_t body_len) {
    ENSURE();
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *pb = PyBytes_FromStringAndSize(cmd_body ? cmd_body : "",
                                             cmd_body ? (Py_ssize_t)body_len
                                                      : 0);
    PyObject *v = capi_call("MXKVStoreSendCommmandToServers",
                            Py_BuildValue("(KiN)", h, cmd_id, pb));
    int rc = v ? 0 : -1;
    Py_XDECREF(v);
    PyGILState_Release(st);
    return rc;
}

MXTPU_EXPORT int MXKVStoreSendCommmandToServers(KVStoreHandle h, int cmd_id,
                                                const char *cmd_body) {
    /* legacy NUL-terminated entry point: delegate with an explicit length
     * so the marshalled body is exactly what strlen sees (binary bodies
     * must use the Ex variant) */
    return MXKVStoreSendCommmandToServersEx(
        h, cmd_id, cmd_body, cmd_body ? strlen(cmd_body) : 0);
}

MXTPU_EXPORT int MXKVStoreSetBarrierBeforeExit(KVStoreHandle h,
                                               const int do_barrier) {
    ENSURE();
    return call_void("MXKVStoreSetBarrierBeforeExit",
                     Py_BuildValue("(Ki)", h, do_barrier));
}

typedef void (MXKVStoreUpdater)(int key, NDArrayHandle recv,
                                NDArrayHandle local, void *handle);

MXTPU_EXPORT int MXKVStoreSetUpdater(KVStoreHandle h, MXKVStoreUpdater updater,
                                     void *updater_handle) {
    ENSURE();
    return call_void("MXKVStoreSetUpdater",
                     Py_BuildValue("(KKK)", h, (uint64_t)(uintptr_t)updater,
                                   (uint64_t)(uintptr_t)updater_handle));
}

MXTPU_EXPORT int MXInitPSEnv(mx_uint num_vars, const char **keys,
                             const char **vals) {
    ENSURE();
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *v = capi_call("MXInitPSEnv",
                            Py_BuildValue("(NN)", slist(keys, num_vars),
                                          slist(vals, num_vars)));
    int rc = v ? 0 : -1;
    Py_XDECREF(v);
    PyGILState_Release(st);
    return rc;
}

/* ---------------- CustomOp ---------------- */

MXTPU_EXPORT int MXCustomOpRegister(const char *op_type, void *creator) {
    ENSURE();
    return call_void("MXCustomOpRegister",
                     Py_BuildValue("(sK)", op_type,
                                   (uint64_t)(uintptr_t)creator));
}
