/*
 * libmxnet_tpu — compiled C API over the Python substrate.
 *
 * Reproduces the reference's binding contract (ref:
 * include/mxnet/c_api.h, src/c_api/*.cc: opaque handles, int status
 * returns, MXGetLastError) as real `extern "C"` symbols a non-Python
 * client can link (cpp-package/R/Scala-style consumers, SURVEY.md §2.7).
 * Each entry point marshals into mxnet_tpu.c_api via the embedded CPython
 * interpreter; handles are the Python-side integer registry keys.
 *
 * Build: make -C src/capi     (links libpython via python3-config --embed)
 * Smoke client: src/capi/smoke_client.c trains a layer through this ABI.
 */
#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <stdint.h>
#include <stdio.h>
#include <string.h>

typedef uint64_t NDArrayHandle;
typedef uint64_t SymbolHandle;
typedef uint64_t ExecutorHandle;
typedef uint64_t KVStoreHandle;

#define MXTPU_EXPORT __attribute__((visibility("default")))

static PyObject *g_capi = NULL;          /* mxnet_tpu.c_api module */
static __thread char g_err[4096];
static __thread char g_shape_buf[32 * sizeof(uint32_t)];

static void set_err(const char *msg) {
    strncpy(g_err, msg ? msg : "unknown error", sizeof(g_err) - 1);
    g_err[sizeof(g_err) - 1] = 0;
}

static void set_err_from_py(void) {
    PyObject *t, *v, *tb;
    PyErr_Fetch(&t, &v, &tb);
    if (v) {
        PyObject *s = PyObject_Str(v);
        set_err(s ? PyUnicode_AsUTF8(s) : "python error");
        Py_XDECREF(s);
    } else {
        set_err("python error (no message)");
    }
    Py_XDECREF(t); Py_XDECREF(v); Py_XDECREF(tb);
}

/* Initialize the interpreter + import mxnet_tpu.c_api once.
 * Mutex-guarded: concurrent first calls from multiple client threads must
 * not double-run Py_InitializeEx/PyEval_SaveThread. */
#include <pthread.h>
static pthread_mutex_t g_init_lock = PTHREAD_MUTEX_INITIALIZER;

static int ensure_init(void) {
    if (g_capi) return 0;
    pthread_mutex_lock(&g_init_lock);
    if (g_capi) {
        pthread_mutex_unlock(&g_init_lock);
        return 0;
    }
    if (!Py_IsInitialized()) {
        Py_InitializeEx(0);
        /* release the GIL so PyGILState_Ensure works from any thread */
        PyEval_SaveThread();
    }
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *m = PyImport_ImportModule("mxnet_tpu.c_api");
    if (!m) {
        set_err_from_py();
        PyGILState_Release(st);
        pthread_mutex_unlock(&g_init_lock);
        return -1;
    }
    g_capi = m;                           /* keep the reference forever */
    PyGILState_Release(st);
    pthread_mutex_unlock(&g_init_lock);
    return 0;
}

/* Call c_api.<name>(*args); unpack the (status, value) tuple.
 * Returns new ref to value or NULL (error stored). */
static PyObject *capi_call(const char *name, PyObject *args) {
    PyObject *fn = PyObject_GetAttrString(g_capi, name);
    if (!fn) { set_err_from_py(); Py_XDECREF(args); return NULL; }
    PyObject *res = PyObject_CallObject(fn, args);
    Py_DECREF(fn);
    Py_XDECREF(args);
    if (!res) { set_err_from_py(); return NULL; }
    if (!PyTuple_Check(res) || PyTuple_Size(res) != 2) {
        set_err("c_api returned malformed result");
        Py_DECREF(res);
        return NULL;
    }
    long status = PyLong_AsLong(PyTuple_GetItem(res, 0));
    if (status != 0) {
        PyObject *le = PyObject_CallMethod(g_capi, "MXGetLastError", NULL);
        if (le) {
            PyObject *msg = PyTuple_Check(le) && PyTuple_Size(le) == 2
                                ? PyTuple_GetItem(le, 1) : le;
            if (msg && PyUnicode_Check(msg)) set_err(PyUnicode_AsUTF8(msg));
            else set_err("c_api call failed");
            Py_DECREF(le);
        } else {
            PyErr_Clear();
            set_err("c_api call failed");
        }
        Py_DECREF(res);
        return NULL;
    }
    PyObject *val = PyTuple_GetItem(res, 1);
    Py_INCREF(val);
    Py_DECREF(res);
    return val;
}

#define ENSURE() do { if (ensure_init()) return -1; } while (0)

MXTPU_EXPORT const char *MXGetLastError(void) { return g_err; }

MXTPU_EXPORT int MXGetVersion(int *out) {
    ENSURE();
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *v = capi_call("MXGetVersion", PyTuple_New(0));
    int rc = -1;
    if (v) { *out = (int)PyLong_AsLong(v); Py_DECREF(v); rc = 0; }
    PyGILState_Release(st);
    return rc;
}

MXTPU_EXPORT int MXNotifyShutdown(void) {
    ENSURE();
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *v = capi_call("MXNotifyShutdown", PyTuple_New(0));
    int rc = v ? 0 : -1;
    Py_XDECREF(v);
    PyGILState_Release(st);
    return rc;
}

/* ---------------- NDArray ---------------- */

MXTPU_EXPORT int MXNDArrayCreate(const uint32_t *shape, uint32_t ndim,
                                 int dev_type, int dev_id, int delay_alloc,
                                 NDArrayHandle *out) {
    ENSURE();
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *pshape = PyTuple_New(ndim);
    for (uint32_t i = 0; i < ndim; i++)
        PyTuple_SetItem(pshape, i, PyLong_FromUnsignedLong(shape[i]));
    PyObject *v = capi_call("MXNDArrayCreate",
                            Py_BuildValue("(Niii)", pshape, dev_type,
                                          dev_id, delay_alloc));
    int rc = -1;
    if (v) { *out = PyLong_AsUnsignedLongLong(v); Py_DECREF(v); rc = 0; }
    PyGILState_Release(st);
    return rc;
}

MXTPU_EXPORT int MXNDArrayFree(NDArrayHandle h) {
    ENSURE();
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *v = capi_call("MXNDArrayFree", Py_BuildValue("(K)", h));
    int rc = v ? 0 : -1;
    Py_XDECREF(v);
    PyGILState_Release(st);
    return rc;
}

MXTPU_EXPORT int MXNDArraySyncCopyFromCPU(NDArrayHandle h, const void *data,
                                          size_t size) {
    ENSURE();
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *buf = PyBytes_FromStringAndSize((const char *)data,
                                              size * sizeof(float));
    PyObject *v = capi_call("MXNDArraySyncCopyFromBytes",
                            Py_BuildValue("(KN)", h, buf));
    int rc = v ? 0 : -1;
    Py_XDECREF(v);
    PyGILState_Release(st);
    return rc;
}

MXTPU_EXPORT int MXNDArraySyncCopyToCPU(NDArrayHandle h, void *data,
                                        size_t size) {
    ENSURE();
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *v = capi_call("MXNDArraySyncCopyToBytes",
                            Py_BuildValue("(K)", h));
    int rc = -1;
    if (v) {
        Py_ssize_t n = PyBytes_Size(v);
        size_t want = size * sizeof(float);
        if ((size_t)n < want) want = (size_t)n;
        memcpy(data, PyBytes_AsString(v), want);
        Py_DECREF(v);
        rc = 0;
    }
    PyGILState_Release(st);
    return rc;
}

MXTPU_EXPORT int MXNDArrayGetShape(NDArrayHandle h, uint32_t *out_dim,
                                   const uint32_t **out_pdata) {
    ENSURE();
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *v = capi_call("MXNDArrayGetShape", Py_BuildValue("(K)", h));
    int rc = -1;
    if (v) {
        uint32_t n = (uint32_t)PySequence_Size(v);
        uint32_t *buf = (uint32_t *)g_shape_buf;
        for (uint32_t i = 0; i < n && i < 32; i++) {
            PyObject *it = PySequence_GetItem(v, i);
            buf[i] = (uint32_t)PyLong_AsUnsignedLong(it);
            Py_DECREF(it);
        }
        *out_dim = n;
        *out_pdata = buf;
        Py_DECREF(v);
        rc = 0;
    }
    PyGILState_Release(st);
    return rc;
}

MXTPU_EXPORT int MXNDArrayWaitAll(void) {
    ENSURE();
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *v = capi_call("MXNDArrayWaitAll", PyTuple_New(0));
    int rc = v ? 0 : -1;
    Py_XDECREF(v);
    PyGILState_Release(st);
    return rc;
}

/* ---------------- Symbol ---------------- */

MXTPU_EXPORT int MXSymbolCreateVariable(const char *name, SymbolHandle *out) {
    ENSURE();
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *v = capi_call("MXSymbolCreateVariable",
                            Py_BuildValue("(s)", name));
    int rc = -1;
    if (v) { *out = PyLong_AsUnsignedLongLong(v); Py_DECREF(v); rc = 0; }
    PyGILState_Release(st);
    return rc;
}

MXTPU_EXPORT int MXSymbolCreateAtomicSymbol(const char *op_name,
                                            uint32_t num_param,
                                            const char **keys,
                                            const char **vals,
                                            SymbolHandle *out) {
    ENSURE();
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *pk = PyList_New(num_param), *pv = PyList_New(num_param);
    for (uint32_t i = 0; i < num_param; i++) {
        PyList_SetItem(pk, i, PyUnicode_FromString(keys[i]));
        PyList_SetItem(pv, i, PyUnicode_FromString(vals[i]));
    }
    PyObject *v = capi_call("MXSymbolCreateAtomicSymbol",
                            Py_BuildValue("(sNN)", op_name, pk, pv));
    int rc = -1;
    if (v) { *out = PyLong_AsUnsignedLongLong(v); Py_DECREF(v); rc = 0; }
    PyGILState_Release(st);
    return rc;
}

MXTPU_EXPORT int MXSymbolCompose(SymbolHandle sym, const char *name,
                                 uint32_t num_args, const char **keys,
                                 SymbolHandle *args) {
    ENSURE();
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *pa = PyList_New(num_args);
    for (uint32_t i = 0; i < num_args; i++)
        PyList_SetItem(pa, i, PyLong_FromUnsignedLongLong(args[i]));
    PyObject *pk;
    if (keys) {
        pk = PyList_New(num_args);
        for (uint32_t i = 0; i < num_args; i++)
            PyList_SetItem(pk, i, PyUnicode_FromString(keys[i]));
    } else {
        pk = Py_None;
        Py_INCREF(Py_None);
    }
    PyObject *v = capi_call("MXSymbolCompose",
                            Py_BuildValue("(KsNN)", sym, name, pa, pk));
    int rc = v ? 0 : -1;
    Py_XDECREF(v);
    PyGILState_Release(st);
    return rc;
}

MXTPU_EXPORT int MXSymbolSaveToJSON(SymbolHandle sym, const char **out) {
    ENSURE();
    PyGILState_STATE st = PyGILState_Ensure();
    static __thread char *json_buf = NULL;
    PyObject *v = capi_call("MXSymbolSaveToJSON", Py_BuildValue("(K)", sym));
    int rc = -1;
    if (v) {
        const char *s = PyUnicode_AsUTF8(v);
        free(json_buf);
        json_buf = strdup(s ? s : "");
        *out = json_buf;
        Py_DECREF(v);
        rc = 0;
    }
    PyGILState_Release(st);
    return rc;
}

MXTPU_EXPORT int MXSymbolCreateFromJSON(const char *json, SymbolHandle *out) {
    ENSURE();
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *v = capi_call("MXSymbolCreateFromJSON",
                            Py_BuildValue("(s)", json));
    int rc = -1;
    if (v) { *out = PyLong_AsUnsignedLongLong(v); Py_DECREF(v); rc = 0; }
    PyGILState_Release(st);
    return rc;
}

/* list arguments: returns count; names via repeated calls (thread buffer) */
MXTPU_EXPORT int MXSymbolListArguments(SymbolHandle sym, uint32_t *out_size,
                                       const char ***out_array) {
    ENSURE();
    PyGILState_STATE st = PyGILState_Ensure();
    static __thread char **name_buf = NULL;
    static __thread uint32_t name_cnt = 0;
    PyObject *v = capi_call("MXSymbolListArguments",
                            Py_BuildValue("(K)", sym));
    int rc = -1;
    if (v) {
        for (uint32_t i = 0; i < name_cnt; i++) free(name_buf[i]);
        free(name_buf);
        name_cnt = (uint32_t)PySequence_Size(v);
        name_buf = (char **)calloc(name_cnt, sizeof(char *));
        for (uint32_t i = 0; i < name_cnt; i++) {
            PyObject *it = PySequence_GetItem(v, i);
            name_buf[i] = strdup(PyUnicode_AsUTF8(it));
            Py_DECREF(it);
        }
        *out_size = name_cnt;
        *out_array = (const char **)name_buf;
        Py_DECREF(v);
        rc = 0;
    }
    PyGILState_Release(st);
    return rc;
}

/* ---------------- Executor ---------------- */

MXTPU_EXPORT int MXExecutorBind(SymbolHandle sym, int dev_type, int dev_id,
                                uint32_t num_args, NDArrayHandle *in_args,
                                NDArrayHandle *arg_grads,
                                uint32_t num_aux, NDArrayHandle *aux_states,
                                ExecutorHandle *out) {
    ENSURE();
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *pargs = PyList_New(num_args);
    for (uint32_t i = 0; i < num_args; i++)
        PyList_SetItem(pargs, i, PyLong_FromUnsignedLongLong(in_args[i]));
    PyObject *pgrads;
    if (arg_grads) {
        pgrads = PyList_New(num_args);
        for (uint32_t i = 0; i < num_args; i++)
            PyList_SetItem(pgrads, i,
                           PyLong_FromUnsignedLongLong(arg_grads[i]));
    } else {
        pgrads = Py_None;
        Py_INCREF(Py_None);
    }
    PyObject *paux;
    if (num_aux) {
        paux = PyList_New(num_aux);
        for (uint32_t i = 0; i < num_aux; i++)
            PyList_SetItem(paux, i,
                           PyLong_FromUnsignedLongLong(aux_states[i]));
    } else {
        paux = Py_None;
        Py_INCREF(Py_None);
    }
    PyObject *v = capi_call("MXExecutorBind",
                            Py_BuildValue("(KiiNNsN)", sym, dev_type, dev_id,
                                          pargs, pgrads, "write", paux));
    int rc = -1;
    if (v) { *out = PyLong_AsUnsignedLongLong(v); Py_DECREF(v); rc = 0; }
    PyGILState_Release(st);
    return rc;
}

MXTPU_EXPORT int MXExecutorForward(ExecutorHandle h, int is_train) {
    ENSURE();
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *v = capi_call("MXExecutorForward",
                            Py_BuildValue("(Ki)", h, is_train));
    int rc = v ? 0 : -1;
    Py_XDECREF(v);
    PyGILState_Release(st);
    return rc;
}

MXTPU_EXPORT int MXExecutorBackward(ExecutorHandle h, uint32_t len,
                                    NDArrayHandle *head_grads) {
    ENSURE();
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *pg;
    if (len && head_grads) {
        pg = PyList_New(len);
        for (uint32_t i = 0; i < len; i++)
            PyList_SetItem(pg, i, PyLong_FromUnsignedLongLong(head_grads[i]));
    } else {
        pg = Py_None;
        Py_INCREF(Py_None);
    }
    PyObject *v = capi_call("MXExecutorBackward",
                            Py_BuildValue("(KN)", h, pg));
    int rc = v ? 0 : -1;
    Py_XDECREF(v);
    PyGILState_Release(st);
    return rc;
}

MXTPU_EXPORT int MXExecutorOutputs(ExecutorHandle h, uint32_t *out_size,
                                   NDArrayHandle **out) {
    ENSURE();
    PyGILState_STATE st = PyGILState_Ensure();
    static __thread NDArrayHandle *out_buf = NULL;
    PyObject *v = capi_call("MXExecutorOutputs", Py_BuildValue("(K)", h));
    int rc = -1;
    if (v) {
        uint32_t n = (uint32_t)PySequence_Size(v);
        free(out_buf);
        out_buf = (NDArrayHandle *)calloc(n, sizeof(NDArrayHandle));
        for (uint32_t i = 0; i < n; i++) {
            PyObject *it = PySequence_GetItem(v, i);
            out_buf[i] = PyLong_AsUnsignedLongLong(it);
            Py_DECREF(it);
        }
        *out_size = n;
        *out = out_buf;
        Py_DECREF(v);
        rc = 0;
    }
    PyGILState_Release(st);
    return rc;
}

/* ---------------- KVStore ---------------- */

MXTPU_EXPORT int MXKVStoreCreate(const char *type, KVStoreHandle *out) {
    ENSURE();
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *v = capi_call("MXKVStoreCreate", Py_BuildValue("(s)", type));
    int rc = -1;
    if (v) { *out = PyLong_AsUnsignedLongLong(v); Py_DECREF(v); rc = 0; }
    PyGILState_Release(st);
    return rc;
}

static int kv_keyvals(const char *fname, KVStoreHandle h, uint32_t num,
                      const int *keys, NDArrayHandle *vals) {
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *pk = PyList_New(num), *pv = PyList_New(num);
    for (uint32_t i = 0; i < num; i++) {
        PyList_SetItem(pk, i, PyLong_FromLong(keys[i]));
        PyList_SetItem(pv, i, PyLong_FromUnsignedLongLong(vals[i]));
    }
    PyObject *v = capi_call(fname, Py_BuildValue("(KNN)", h, pk, pv));
    int rc = v ? 0 : -1;
    Py_XDECREF(v);
    PyGILState_Release(st);
    return rc;
}

MXTPU_EXPORT int MXKVStoreInit(KVStoreHandle h, uint32_t num,
                               const int *keys, NDArrayHandle *vals) {
    ENSURE();
    return kv_keyvals("MXKVStoreInit", h, num, keys, vals);
}

MXTPU_EXPORT int MXKVStorePush(KVStoreHandle h, uint32_t num,
                               const int *keys, NDArrayHandle *vals) {
    ENSURE();
    return kv_keyvals("MXKVStorePush", h, num, keys, vals);
}

MXTPU_EXPORT int MXKVStorePull(KVStoreHandle h, uint32_t num,
                               const int *keys, NDArrayHandle *vals) {
    ENSURE();
    return kv_keyvals("MXKVStorePull", h, num, keys, vals);
}

/* ---------------- C predict API (ref: c_predict_api.h) ---------------- */

typedef uint64_t PredictorHandle;

MXTPU_EXPORT int MXPredCreate(const char *symbol_json,
                              const void *param_bytes, int param_size,
                              int dev_type, int dev_id,
                              uint32_t num_input_nodes,
                              const char **input_keys,
                              const uint32_t *input_shape_indptr,
                              const uint32_t *input_shape_data,
                              PredictorHandle *out) {
    ENSURE();
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *pk = PyList_New(num_input_nodes);
    PyObject *ps = PyList_New(num_input_nodes);
    for (uint32_t i = 0; i < num_input_nodes; i++) {
        PyList_SetItem(pk, i, PyUnicode_FromString(input_keys[i]));
        uint32_t b = input_shape_indptr[i], e = input_shape_indptr[i + 1];
        PyObject *shape = PyTuple_New(e - b);
        for (uint32_t j = b; j < e; j++)
            PyTuple_SetItem(shape, j - b,
                            PyLong_FromUnsignedLong(input_shape_data[j]));
        PyList_SetItem(ps, i, shape);
    }
    PyObject *pb = PyBytes_FromStringAndSize(
        (const char *)param_bytes, param_size);
    PyObject *v = capi_call("MXPredCreate",
                            Py_BuildValue("(sNiiNN)", symbol_json, pb,
                                          dev_type, dev_id, pk, ps));
    int rc = -1;
    if (v) { *out = PyLong_AsUnsignedLongLong(v); Py_DECREF(v); rc = 0; }
    PyGILState_Release(st);
    return rc;
}

MXTPU_EXPORT int MXPredSetInput(PredictorHandle h, const char *key,
                                const float *data, uint32_t size) {
    ENSURE();
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *buf = PyBytes_FromStringAndSize((const char *)data,
                                              (Py_ssize_t)size * 4);
    PyObject *v = capi_call("MXPredSetInput",
                            Py_BuildValue("(KsN)", h, key, buf));
    int rc = v ? 0 : -1;
    Py_XDECREF(v);
    PyGILState_Release(st);
    return rc;
}

MXTPU_EXPORT int MXPredForward(PredictorHandle h) {
    ENSURE();
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *v = capi_call("MXPredForward", Py_BuildValue("(K)", h));
    int rc = v ? 0 : -1;
    Py_XDECREF(v);
    PyGILState_Release(st);
    return rc;
}

MXTPU_EXPORT int MXPredGetOutputShape(PredictorHandle h, uint32_t index,
                                      uint32_t **shape_data,
                                      uint32_t *shape_ndim) {
    ENSURE();
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *v = capi_call("MXPredGetOutputShape",
                            Py_BuildValue("(KI)", h, index));
    int rc = -1;
    if (v) {
        uint32_t n = (uint32_t)PySequence_Size(v);
        uint32_t *buf = (uint32_t *)g_shape_buf;
        for (uint32_t i = 0; i < n && i < 32; i++) {
            PyObject *it = PySequence_GetItem(v, i);
            buf[i] = (uint32_t)PyLong_AsUnsignedLong(it);
            Py_DECREF(it);
        }
        *shape_data = buf;
        *shape_ndim = n;
        Py_DECREF(v);
        rc = 0;
    }
    PyGILState_Release(st);
    return rc;
}

MXTPU_EXPORT int MXPredGetOutput(PredictorHandle h, uint32_t index,
                                 float *data, uint32_t size) {
    ENSURE();
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *v = capi_call("MXPredGetOutput", Py_BuildValue("(KI)", h,
                                                             index));
    int rc = -1;
    if (v) {
        size_t n = (size_t)PyBytes_Size(v);
        size_t want = (size_t)size * 4;
        if (n < want) want = n;
        memcpy(data, PyBytes_AsString(v), want);
        Py_DECREF(v);
        rc = 0;
    }
    PyGILState_Release(st);
    return rc;
}

MXTPU_EXPORT int MXPredFree(PredictorHandle h) {
    ENSURE();
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *v = capi_call("MXPredFree", Py_BuildValue("(K)", h));
    int rc = v ? 0 : -1;
    Py_XDECREF(v);
    PyGILState_Release(st);
    return rc;
}
