/*
 * mt_client: concurrency + error-path exercise of the compiled C ABI
 * (ref: the reference ABI serves multi-threaded JNI/Scala consumers —
 * scala-package/; VERDICT r4 weak #3).
 *
 * 4 threads x 250 iterations each = 1000 iterations of
 * create/copy/invoke/forward/backward/push/pull against shared state,
 * plus per-handle-type error-path checks (invalid handles must return -1
 * with a message, never crash).
 */
#include <pthread.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

typedef uint64_t H;
typedef unsigned int mx_uint;

extern const char *MXGetLastError(void);
extern int MXNDArrayCreate(const uint32_t *, uint32_t, int, int, int, H *);
extern int MXNDArraySyncCopyFromCPU(H, const void *, size_t);
extern int MXNDArraySyncCopyToCPU(H, void *, size_t);
extern int MXNDArrayGetShape(H, uint32_t *, const uint32_t **);
extern int MXNDArrayFree(H);
extern int MXGetFunction(const char *, H *);
extern int MXFuncInvoke(H, H *, float *, H *);
extern int MXSymbolCreateVariable(const char *, H *);
extern int MXSymbolCreateAtomicSymbol(const char *, uint32_t, const char **,
                                      const char **, H *);
extern int MXSymbolCompose(H, const char *, uint32_t, const char **, H *);
extern int MXExecutorBind(H, int, int, uint32_t, H *, H *, uint32_t, H *,
                          H *);
extern int MXExecutorForward(H, int);
extern int MXExecutorBackward(H, uint32_t, H *);
extern int MXExecutorOutputs(H, uint32_t *, H **);
extern int MXExecutorFree(H);
extern int MXKVStoreCreate(const char *, H *);
extern int MXKVStoreInit(H, uint32_t, const int *, H *);
extern int MXKVStorePush(H, uint32_t, const int *, H *);
extern int MXKVStorePull(H, uint32_t, const int *, H *);
extern int MXDataIterGetData(H, H *);
extern int MXRecordIOWriterCreate(const char *, H *);
extern int MXRecordIOWriterWriteRecord(H, const char *, size_t);
extern int MXRecordIOReaderCreate(const char *, H *);
extern int MXRecordIOReaderReadRecord(H, char const **, size_t *);
extern int MXRecordIOReaderFree(H);

#define ITER 250
#define NTHREAD 4
#define DIM 8

static H g_kv;
static H g_add_fn;
static int g_fail = 0;

#define TCHK(call)                                                        \
    do {                                                                  \
        if ((call) != 0) {                                                \
            fprintf(stderr, "thread FAILED %s: %s\n", #call,              \
                    MXGetLastError());                                    \
            __sync_fetch_and_add(&g_fail, 1);                             \
            return NULL;                                                  \
        }                                                                 \
    } while (0)

static void *worker(void *arg) {
    long tid = (long)(intptr_t)arg;
    uint32_t shape1[] = {DIM};

    /* per-thread net: fc(data) bound once, driven every iteration */
    H data, fc;
    char vname[32];
    snprintf(vname, sizeof(vname), "data_t%ld", tid);
    TCHK(MXSymbolCreateVariable(vname, &data));
    const char *fck[] = {"num_hidden", "no_bias"};
    const char *fcv[] = {"4", "True"};
    TCHK(MXSymbolCreateAtomicSymbol("FullyConnected", 2, fck, fcv, &fc));
    char cname[32];
    snprintf(cname, sizeof(cname), "fc_t%ld", tid);
    TCHK(MXSymbolCompose(fc, cname, 1, NULL, &data));
    uint32_t sh_in[] = {2, DIM}, sh_w[] = {4, DIM};
    H a_in, a_w, g_in, g_w;
    TCHK(MXNDArrayCreate(sh_in, 2, 1, 0, 0, &a_in));
    TCHK(MXNDArrayCreate(sh_w, 2, 1, 0, 0, &a_w));
    TCHK(MXNDArrayCreate(sh_in, 2, 1, 0, 0, &g_in));
    TCHK(MXNDArrayCreate(sh_w, 2, 1, 0, 0, &g_w));
    H args[] = {a_in, a_w}, grads[] = {g_in, g_w};
    H exec;
    TCHK(MXExecutorBind(fc, 1, 0, 2, args, grads, 0, NULL, &exec));

    float buf[2 * DIM], out[2 * 4];
    for (int it = 0; it < ITER; it++) {
        /* NDArray create/copy/free churn */
        H tmp;
        TCHK(MXNDArrayCreate(shape1, 1, 1, 0, 0, &tmp));
        float v[DIM];
        for (int i = 0; i < DIM; i++) v[i] = (float)(tid * 1000 + it + i);
        TCHK(MXNDArraySyncCopyFromCPU(tmp, v, DIM));
        float r[DIM];
        TCHK(MXNDArraySyncCopyToCPU(tmp, r, DIM));
        if (memcmp(v, r, sizeof(v)) != 0) {
            fprintf(stderr, "thread %ld: copy round-trip mismatch\n", tid);
            __sync_fetch_and_add(&g_fail, 1);
            return NULL;
        }

        /* imperative invoke through the Function registry */
        H sum;
        TCHK(MXNDArrayCreate(shape1, 1, 1, 0, 0, &sum));
        H use[] = {tmp, tmp}, mut[] = {sum};
        TCHK(MXFuncInvoke(g_add_fn, use, NULL, mut));
        TCHK(MXNDArraySyncCopyToCPU(sum, r, DIM));
        for (int i = 0; i < DIM; i++) {
            if (r[i] != 2 * v[i]) {
                fprintf(stderr, "thread %ld: add wrong\n", tid);
                __sync_fetch_and_add(&g_fail, 1);
                return NULL;
            }
        }
        TCHK(MXNDArrayFree(sum));

        /* forward/backward on the private executor */
        for (int i = 0; i < 2 * DIM; i++) buf[i] = (float)(it + i);
        TCHK(MXNDArraySyncCopyFromCPU(a_in, buf, 2 * DIM));
        TCHK(MXExecutorForward(exec, 1));
        TCHK(MXExecutorBackward(exec, 0, NULL));
        uint32_t nout = 0;
        H *outs = NULL;
        TCHK(MXExecutorOutputs(exec, &nout, &outs));
        TCHK(MXNDArraySyncCopyToCPU(outs[0], out, 2 * 4));

        /* shared kvstore traffic on a thread-owned key */
        int key = 100 + (int)tid;
        H hval;
        TCHK(MXNDArrayCreate(shape1, 1, 1, 0, 0, &hval));
        TCHK(MXNDArraySyncCopyFromCPU(hval, v, DIM));
        if (it == 0) {
            TCHK(MXKVStoreInit(g_kv, 1, &key, &hval));
        } else {
            TCHK(MXKVStorePush(g_kv, 1, &key, &hval));
            TCHK(MXKVStorePull(g_kv, 1, &key, &hval));
        }
        TCHK(MXNDArrayFree(hval));
        TCHK(MXNDArrayFree(tmp));
    }
    TCHK(MXExecutorFree(exec));
    return NULL;
}

static int expect_fail(int rc, const char *what) {
    if (rc == 0) {
        fprintf(stderr, "error-path %s unexpectedly succeeded\n", what);
        return 1;
    }
    const char *msg = MXGetLastError();
    if (!msg || !msg[0]) {
        fprintf(stderr, "error-path %s: empty error message\n", what);
        return 1;
    }
    return 0;
}

int main(void) {
    uint32_t shape1[] = {DIM};

    if (MXKVStoreCreate("local", &g_kv) != 0 ||
        MXGetFunction("elemwise_add", &g_add_fn) != 0) {
        fprintf(stderr, "setup failed: %s\n", MXGetLastError());
        return 1;
    }

    /* ---- error paths, one per handle type (before the storm) ---- */
    int bad = 0;
    uint32_t nd_ = 0;
    const uint32_t *pd_ = NULL;
    bad += expect_fail(MXNDArrayGetShape((H)0xdeadbeef, &nd_, &pd_),
                       "NDArrayGetShape(bad handle)");
    float one = 1.f;
    bad += expect_fail(MXNDArraySyncCopyFromCPU((H)0xdeadbeef, &one, 1),
                       "NDArrayCopyFrom(bad handle)");
    H hsym = 0;
    bad += expect_fail(
        MXSymbolCreateAtomicSymbol("NoSuchOperator", 0, NULL, NULL, &hsym)
            == 0 /* creation defers resolution */
            ? MXSymbolCompose(hsym, "x", 0, NULL, NULL)
            : -1,
        "Symbol(NoSuchOperator) compose");
    bad += expect_fail(MXExecutorForward((H)0xdeadbeef, 0),
                       "ExecutorForward(bad handle)");
    int k0 = 0;
    H hv = 0;
    MXNDArrayCreate(shape1, 1, 1, 0, 0, &hv);
    bad += expect_fail(MXKVStorePush((H)0xdeadbeef, 1, &k0, &hv),
                       "KVStorePush(bad store)");
    bad += expect_fail(MXDataIterGetData((H)0xdeadbeef, &hv),
                       "DataIterGetData(bad iter)");
    H hr = 0;
    bad += expect_fail(MXRecordIOReaderCreate("/nonexistent/dir/x.rec", &hr),
                       "RecordIOReaderCreate(bad path)");
    /* reading from a writer handle is a type error, not a crash */
    H hw = 0;
    if (MXRecordIOWriterCreate("/tmp/mt_err.rec", &hw) == 0) {
        const char *rbuf = NULL;
        size_t rsz = 0;
        bad += expect_fail(MXRecordIOReaderReadRecord(hw, &rbuf, &rsz),
                           "RecordIORead(on writer)");
    } else {
        fprintf(stderr, "could not set up RecordIO writer probe\n");
        bad += 1;
    }
    if (bad) {
        fprintf(stderr, "MT FAIL: %d error-path checks\n", bad);
        return 1;
    }
    printf("error paths: 8/8 returned -1 with messages\n");

    /* ---- the 4-thread storm ---- */
    pthread_t th[NTHREAD];
    for (long i = 0; i < NTHREAD; i++)
        pthread_create(&th[i], NULL, worker, (void *)(intptr_t)i);
    for (int i = 0; i < NTHREAD; i++) pthread_join(th[i], NULL);
    if (g_fail) {
        fprintf(stderr, "MT FAIL: %d thread failures\n", g_fail);
        return 1;
    }
    printf("%d threads x %d iterations: no failures\n", NTHREAD, ITER);
    printf("MT PASS\n");
    return 0;
}
