// Native fused JPEG decode + augment + batch: the hot half of the data plane.
//
// TPU-native counterpart of the reference's threaded ImageRecordIter v2
// (ref: src/io/iter_image_recordio_2.cc:595 fused decode/augment/batch,
// src/io/iter_image_recordio.cc:31 OMP parallel decode,
// src/io/image_aug_default.cc resize/crop/mirror augmenters). One C call
// decodes a whole batch on a std::thread pool (no GIL), applies
// resize-short -> crop -> resize -> mirror, and writes the final
// float32 NCHW tensor with mean/std folded in — images never round-trip
// through Python objects.
//
// libjpeg tricks used:
//  - scale_denom DCT scaling: when the target is much smaller than the
//    source, decode directly at 1/2, 1/4 or 1/8 scale (large speedup).
//  - per-image setjmp error trap: a corrupt JPEG fails that image only
//    (output zeroed, status -1), never the process.
//
// C ABI (ctypes, no pybind11 in this image):
//   mxtpu_img_decode_batch(...)  — full fused batch pipeline
//   mxtpu_img_decode_one(...)    — single image to HWC uint8 (imdecode)
//
// Build: make -C src  (part of libmxtpu_io.so)

#include <cstdio>   // jpeglib.h needs size_t/FILE declared first
#include <cstddef>

#include <jpeglib.h>

#include <atomic>
#include <algorithm>
#include <cmath>
#include <csetjmp>
#include <cstdint>
#include <cstring>
#include <random>
#include <thread>
#include <vector>

namespace {

struct ErrTrap {
  jpeg_error_mgr mgr;
  jmp_buf jump;
};

void err_exit(j_common_ptr cinfo) {
  ErrTrap* t = reinterpret_cast<ErrTrap*>(cinfo->err);
  longjmp(t->jump, 1);
}

// Decode a JPEG into an RGB buffer, optionally DCT-downscaled so the short
// edge stays >= min_short (0 = full size). Returns false on corrupt input.
bool DecodeRGB(const uint8_t* buf, uint64_t size, int min_short,
               std::vector<uint8_t>* out, int* w, int* h) {
  jpeg_decompress_struct cinfo;
  ErrTrap trap;
  cinfo.err = jpeg_std_error(&trap.mgr);
  trap.mgr.error_exit = err_exit;
  if (setjmp(trap.jump)) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<uint8_t*>(buf),
               static_cast<unsigned long>(size));
  jpeg_read_header(&cinfo, TRUE);
  cinfo.out_color_space = JCS_RGB;
  if (min_short > 0) {
    int short_edge = std::min<int>(cinfo.image_width, cinfo.image_height);
    int denom = 1;
    while (denom < 8 && short_edge / (denom * 2) >= min_short) denom *= 2;
    cinfo.scale_num = 1;
    cinfo.scale_denom = denom;
  }
  jpeg_start_decompress(&cinfo);
  *w = cinfo.output_width;
  *h = cinfo.output_height;
  out->resize(static_cast<size_t>(*w) * *h * 3);
  // grayscale sources still output 3 components because of out_color_space
  while (cinfo.output_scanline < cinfo.output_height) {
    uint8_t* row = out->data() +
                   static_cast<size_t>(cinfo.output_scanline) * *w * 3;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  return true;
}

// Bilinear resize RGB u8 HWC.
void Resize(const uint8_t* src, int sw, int sh, uint8_t* dst, int dw, int dh) {
  const float sx = static_cast<float>(sw) / dw;
  const float sy = static_cast<float>(sh) / dh;
  for (int y = 0; y < dh; ++y) {
    float fy = (y + 0.5f) * sy - 0.5f;
    int y0 = std::max(0, static_cast<int>(std::floor(fy)));
    int y1 = std::min(sh - 1, y0 + 1);
    float wy = fy - y0;
    if (wy < 0) wy = 0;
    for (int x = 0; x < dw; ++x) {
      float fx = (x + 0.5f) * sx - 0.5f;
      int x0 = std::max(0, static_cast<int>(std::floor(fx)));
      int x1 = std::min(sw - 1, x0 + 1);
      float wx = fx - x0;
      if (wx < 0) wx = 0;
      for (int c = 0; c < 3; ++c) {
        float v00 = src[(static_cast<size_t>(y0) * sw + x0) * 3 + c];
        float v01 = src[(static_cast<size_t>(y0) * sw + x1) * 3 + c];
        float v10 = src[(static_cast<size_t>(y1) * sw + x0) * 3 + c];
        float v11 = src[(static_cast<size_t>(y1) * sw + x1) * 3 + c];
        float v = v00 * (1 - wy) * (1 - wx) + v01 * (1 - wy) * wx +
                  v10 * wy * (1 - wx) + v11 * wy * wx;
        dst[(static_cast<size_t>(y) * dw + x) * 3 + c] =
            static_cast<uint8_t>(v + 0.5f);
      }
    }
  }
}

struct AugSpec {
  int resize_short;   // 0 = skip
  int out_h, out_w;
  int rand_crop;      // 0 center, 1 random
  int rand_mirror;    // 0 never, 1 coin flip
  uint64_t seed;      // per-batch; per-image streams fold the index in
  const float* mean;  // 3 floats or null
  const float* std_;  // 3 floats or null
};

// Decode one image and write float32 CHW (3,out_h,out_w) into out.
bool ProcessOne(const uint8_t* buf, uint64_t size, const AugSpec& spec,
                int index, float* out) {
  std::vector<uint8_t> rgb;
  int w = 0, h = 0;
  // DCT downscale only when a resize-short follows (that path re-interpolates
  // so it stays exact). Without resize_short the fixed-size crop must come
  // from the FULL-resolution image — a DCT-scaled decode would make the crop
  // window cover up to 8x more of the original, changing augmentation stats
  // vs the reference's crop-from-full-res semantics.
  int min_needed = spec.resize_short > 0 ? spec.resize_short : 0;
  if (!DecodeRGB(buf, size, min_needed, &rgb, &w, &h)) return false;

  std::vector<uint8_t> tmp;
  if (spec.resize_short > 0) {
    int nw, nh;
    if (w < h) {
      nw = spec.resize_short;
      nh = std::max(1l, lroundf(static_cast<float>(h) * nw / w));
    } else {
      nh = spec.resize_short;
      nw = std::max(1l, lroundf(static_cast<float>(w) * nh / h));
    }
    if (nw != w || nh != h) {
      tmp.resize(static_cast<size_t>(nw) * nh * 3);
      Resize(rgb.data(), w, h, tmp.data(), nw, nh);
      rgb.swap(tmp);
      w = nw;
      h = nh;
    }
  }

  std::mt19937_64 rng(spec.seed * 0x9e3779b97f4a7c15ull + index);
  int cw = std::min(spec.out_w, w), ch = std::min(spec.out_h, h);
  int x0, y0;
  if (spec.rand_crop) {
    x0 = w > cw ? static_cast<int>(rng() % (w - cw + 1)) : 0;
    y0 = h > ch ? static_cast<int>(rng() % (h - ch + 1)) : 0;
  } else {
    x0 = (w - cw) / 2;
    y0 = (h - ch) / 2;
  }
  const uint8_t* crop_src = rgb.data();
  std::vector<uint8_t> crop;
  if (cw != w || ch != h) {
    crop.resize(static_cast<size_t>(cw) * ch * 3);
    for (int y = 0; y < ch; ++y)
      memcpy(crop.data() + static_cast<size_t>(y) * cw * 3,
             rgb.data() + ((static_cast<size_t>(y0) + y) * w + x0) * 3,
             static_cast<size_t>(cw) * 3);
    crop_src = crop.data();
  }
  std::vector<uint8_t> fin;
  if (cw != spec.out_w || ch != spec.out_h) {
    fin.resize(static_cast<size_t>(spec.out_w) * spec.out_h * 3);
    Resize(crop_src, cw, ch, fin.data(), spec.out_w, spec.out_h);
    crop_src = fin.data();
  }
  bool mirror = spec.rand_mirror && (rng() & 1);
  const size_t plane = static_cast<size_t>(spec.out_h) * spec.out_w;
  const float m0 = spec.mean ? spec.mean[0] : 0.f;
  const float m1 = spec.mean ? spec.mean[1] : 0.f;
  const float m2 = spec.mean ? spec.mean[2] : 0.f;
  const float s0 = spec.std_ ? 1.f / spec.std_[0] : 1.f;
  const float s1 = spec.std_ ? 1.f / spec.std_[1] : 1.f;
  const float s2 = spec.std_ ? 1.f / spec.std_[2] : 1.f;
  for (int y = 0; y < spec.out_h; ++y) {
    for (int x = 0; x < spec.out_w; ++x) {
      int sx = mirror ? spec.out_w - 1 - x : x;
      const uint8_t* p =
          crop_src + (static_cast<size_t>(y) * spec.out_w + sx) * 3;
      size_t o = static_cast<size_t>(y) * spec.out_w + x;
      out[o] = (p[0] - m0) * s0;
      out[plane + o] = (p[1] - m1) * s1;
      out[2 * plane + o] = (p[2] - m2) * s2;
    }
  }
  return true;
}

}  // namespace

extern "C" {

// Fused batch pipeline. bufs/sizes: n jpeg buffers. out: float32 (n,3,H,W).
// status: n int8 entries, 1 ok / 0 failed (failed images are zeroed).
// Returns number of successfully decoded images.
int mxtpu_img_decode_batch(const uint8_t* const* bufs, const uint64_t* sizes,
                           int n, int resize_short, int out_h, int out_w,
                           int rand_crop, int rand_mirror, uint64_t seed,
                           const float* mean, const float* std_dev,
                           float* out, int8_t* status, int nthreads) {
  AugSpec spec{resize_short, out_h, out_w, rand_crop,
               rand_mirror, seed,  mean,  std_dev};
  const size_t img_elems = static_cast<size_t>(3) * out_h * out_w;
  std::atomic<int> next(0), ok(0);
  auto work = [&]() {
    while (true) {
      int i = next.fetch_add(1);
      if (i >= n) break;
      float* dst = out + static_cast<size_t>(i) * img_elems;
      bool good = ProcessOne(bufs[i], sizes[i], spec, i, dst);
      if (!good) memset(dst, 0, img_elems * sizeof(float));
      if (status) status[i] = good ? 1 : 0;
      if (good) ok.fetch_add(1);
    }
  };
  int nt = std::max(1, nthreads);
  if (nt == 1) {
    work();
  } else {
    std::vector<std::thread> pool;
    pool.reserve(nt);
    for (int t = 0; t < nt; ++t) pool.emplace_back(work);
    for (auto& th : pool) th.join();
  }
  return ok.load();
}

// Single-image decode to HWC uint8 (the mx.image.imdecode hot path).
// out must hold max_h*max_w*3; actual dims returned via w/h. Pass
// min_short=0 for full-resolution decode. Returns 1 ok, 0 corrupt,
// -1 too large for the provided buffer.
int mxtpu_img_decode_one(const uint8_t* buf, uint64_t size, int min_short,
                         uint8_t* out, uint64_t cap, int* w, int* h) {
  std::vector<uint8_t> rgb;
  if (!DecodeRGB(buf, size, min_short, &rgb, w, h)) return 0;
  if (rgb.size() > cap) return -1;
  memcpy(out, rgb.data(), rgb.size());
  return 1;
}

}  // extern "C"
