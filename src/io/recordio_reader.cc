// Native RecordIO reader: the C++ half of the data plane.
//
// TPU-native counterpart of the reference's dmlc-core RecordIO reader +
// threaded iterator stack (ref: src/io/iter_image_recordio_2.cc,
// iter_prefetcher.h — SURVEY.md section 2.5). The format is the dmlc framing
// reproduced in mxnet_tpu/recordio.py: magic 0xced7230a, a length word whose
// top 3 bits carry the continuation flag, 4-byte alignment.
//
// Exposed as a flat C ABI consumed via ctypes (no pybind11 in this image):
//   mxtpu_rio_open / mxtpu_rio_next / mxtpu_rio_rewind / mxtpu_rio_close
//   mxtpu_rio_open_indexed / mxtpu_rio_read_at
// plus a background prefetcher that decodes record boundaries ahead of the
// consumer thread:
//   mxtpu_rio_prefetch_start / mxtpu_rio_prefetch_next
//
// Build: make -C src  (produces libmxtpu_io.so loaded by mxnet_tpu.recordio)

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <condition_variable>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

constexpr uint32_t kMagic = 0xced7230a;

struct Reader {
  FILE* fp = nullptr;
  std::vector<char> buf;
  // index for read_at
  std::vector<uint64_t> offsets;
  // prefetch state
  std::thread worker;
  std::mutex mu;
  std::condition_variable cv_nonempty, cv_nonfull;
  std::deque<std::vector<char>> queue;
  size_t max_queue = 64;
  bool done = false;
  bool stop = false;

  ~Reader() {
    {
      std::lock_guard<std::mutex> lk(mu);
      stop = true;
    }
    cv_nonfull.notify_all();
    cv_nonempty.notify_all();
    if (worker.joinable()) worker.join();
    if (fp) fclose(fp);
  }
};

// Read one framed record into out. Returns 1 on success, 0 on EOF/short read.
int ReadRecord(FILE* fp, std::vector<char>* out) {
  uint32_t magic = 0, lrec = 0;
  if (fread(&magic, 4, 1, fp) != 1) return 0;
  if (magic != kMagic) return 0;
  if (fread(&lrec, 4, 1, fp) != 1) return 0;
  uint32_t len = lrec & ((1u << 29) - 1);
  out->resize(len);
  if (len && fread(out->data(), 1, len, fp) != len) return 0;
  uint32_t pad = (4 - (len % 4)) % 4;
  if (pad) fseek(fp, pad, SEEK_CUR);
  return 1;
}

}  // namespace

extern "C" {

void* mxtpu_rio_open(const char* path) {
  FILE* fp = fopen(path, "rb");
  if (!fp) return nullptr;
  Reader* r = new Reader();
  r->fp = fp;
  return r;
}

// Returns pointer to an internal buffer valid until the next call; len via
// out param. Returns nullptr at EOF.
const char* mxtpu_rio_next(void* handle, uint64_t* len) {
  Reader* r = static_cast<Reader*>(handle);
  if (!ReadRecord(r->fp, &r->buf)) {
    *len = 0;
    return nullptr;
  }
  *len = r->buf.size();
  return r->buf.data();
}

void mxtpu_rio_rewind(void* handle) {
  Reader* r = static_cast<Reader*>(handle);
  fseek(r->fp, 0, SEEK_SET);
}

void mxtpu_rio_close(void* handle) { delete static_cast<Reader*>(handle); }

// ---- indexed access (sidecar .idx: "<key>\t<offset>\n") -------------------

int64_t mxtpu_rio_build_index(void* handle) {
  Reader* r = static_cast<Reader*>(handle);
  fseek(r->fp, 0, SEEK_SET);
  r->offsets.clear();
  std::vector<char> tmp;
  while (true) {
    uint64_t off = static_cast<uint64_t>(ftell(r->fp));
    if (!ReadRecord(r->fp, &tmp)) break;
    r->offsets.push_back(off);
  }
  fseek(r->fp, 0, SEEK_SET);
  return static_cast<int64_t>(r->offsets.size());
}

const char* mxtpu_rio_read_at(void* handle, int64_t i, uint64_t* len) {
  Reader* r = static_cast<Reader*>(handle);
  if (i < 0 || static_cast<size_t>(i) >= r->offsets.size()) {
    *len = 0;
    return nullptr;
  }
  fseek(r->fp, static_cast<long>(r->offsets[i]), SEEK_SET);
  if (!ReadRecord(r->fp, &r->buf)) {
    *len = 0;
    return nullptr;
  }
  *len = r->buf.size();
  return r->buf.data();
}

// ---- background prefetch (the dmlc::ThreadedIter role) --------------------

void mxtpu_rio_prefetch_start(void* handle, int queue_size) {
  Reader* r = static_cast<Reader*>(handle);
  if (queue_size > 0) r->max_queue = static_cast<size_t>(queue_size);
  r->done = false;
  r->worker = std::thread([r]() {
    std::vector<char> rec;
    while (true) {
      if (!ReadRecord(r->fp, &rec)) break;
      std::unique_lock<std::mutex> lk(r->mu);
      r->cv_nonfull.wait(
          lk, [r] { return r->queue.size() < r->max_queue || r->stop; });
      if (r->stop) return;
      r->queue.emplace_back(std::move(rec));
      rec.clear();
      lk.unlock();
      r->cv_nonempty.notify_one();
    }
    {
      std::lock_guard<std::mutex> lk(r->mu);
      r->done = true;
    }
    r->cv_nonempty.notify_all();
  });
}

// Copies the next prefetched record into out (caller-allocated, cap bytes).
// Returns the record length (0 = empty record), -2 at end of stream, -1 if
// cap is too small (record stays queued so the caller can retry bigger).
int64_t mxtpu_rio_prefetch_next(void* handle, char* out, uint64_t cap) {
  Reader* r = static_cast<Reader*>(handle);
  std::unique_lock<std::mutex> lk(r->mu);
  r->cv_nonempty.wait(lk, [r] { return !r->queue.empty() || r->done; });
  if (r->queue.empty()) return -2;
  std::vector<char>& front = r->queue.front();
  if (front.size() > cap) return -1;
  int64_t n = static_cast<int64_t>(front.size());
  memcpy(out, front.data(), front.size());
  r->queue.pop_front();
  lk.unlock();
  r->cv_nonfull.notify_one();
  return n;
}

}  // extern "C"
