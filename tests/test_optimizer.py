"""Optimizer tests vs numpy references (ref strategy:
tests/python/unittest/test_optimizer.py)."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu import optimizer as opt


def _run_updates(optimizer, w0, grads):
    w = nd.array(w0.copy())
    state = optimizer.create_state(0, w)
    for g in grads:
        optimizer.update(0, w, nd.array(g), state)
    return w.asnumpy()


def test_sgd_no_momentum():
    w0 = np.random.rand(5).astype(np.float32)
    grads = [np.random.rand(5).astype(np.float32) for _ in range(3)]
    o = opt.create("sgd", learning_rate=0.1, rescale_grad=1.0, wd=0.0)
    got = _run_updates(o, w0, grads)
    expect = w0.copy()
    for g in grads:
        expect = expect - 0.1 * g
    assert np.allclose(got, expect, rtol=1e-5)


def test_sgd_momentum_wd():
    w0 = np.random.rand(5).astype(np.float32)
    grads = [np.random.rand(5).astype(np.float32) for _ in range(4)]
    lr, mom, wd = 0.1, 0.9, 0.01
    o = opt.create("sgd", learning_rate=lr, momentum=mom, wd=wd,
                   rescale_grad=1.0)
    got = _run_updates(o, w0, grads)
    expect = w0.copy()
    m = np.zeros_like(w0)
    for g in grads:
        m = mom * m - lr * (g + wd * expect)
        expect = expect + m
    assert np.allclose(got, expect, rtol=1e-5)


def test_adam():
    w0 = np.random.rand(5).astype(np.float32)
    grads = [np.random.rand(5).astype(np.float32) for _ in range(3)]
    lr, b1, b2, eps = 0.01, 0.9, 0.999, 1e-8
    o = opt.create("adam", learning_rate=lr, beta1=b1, beta2=b2, epsilon=eps,
                   rescale_grad=1.0, wd=0.0)
    got = _run_updates(o, w0, grads)
    expect = w0.copy()
    m = np.zeros_like(w0)
    v = np.zeros_like(w0)
    for t, g in enumerate(grads, 1):
        m = b1 * m + (1 - b1) * g
        v = b2 * v + (1 - b2) * g * g
        lr_t = lr * np.sqrt(1 - b2 ** t) / (1 - b1 ** t)
        expect = expect - lr_t * m / (np.sqrt(v) + eps)
    assert np.allclose(got, expect, rtol=1e-4, atol=1e-6)


def test_rmsprop():
    w0 = np.random.rand(5).astype(np.float32)
    grads = [np.random.rand(5).astype(np.float32) for _ in range(3)]
    lr, g1, eps = 0.01, 0.95, 1e-8
    o = opt.create("rmsprop", learning_rate=lr, gamma1=g1, epsilon=eps,
                   rescale_grad=1.0, wd=0.0)
    got = _run_updates(o, w0, grads)
    expect = w0.copy()
    n = np.zeros_like(w0)
    for g in grads:
        n = (1 - g1) * g * g + g1 * n
        expect = expect - lr * g / np.sqrt(n + eps)
    assert np.allclose(got, expect, rtol=1e-4)


def test_adagrad():
    w0 = np.random.rand(5).astype(np.float32)
    grads = [np.random.rand(5).astype(np.float32) for _ in range(3)]
    lr, eps = 0.1, 1e-7
    o = opt.create("adagrad", learning_rate=lr, eps=eps, rescale_grad=1.0,
                   wd=0.0)
    got = _run_updates(o, w0, grads)
    expect = w0.copy()
    h = np.zeros_like(w0)
    for g in grads:
        h += g * g
        expect = expect - lr * g / np.sqrt(h + eps)
    assert np.allclose(got, expect, rtol=1e-4)


def test_clip_gradient():
    w0 = np.zeros(3, np.float32)
    g = np.array([10.0, -10.0, 0.5], np.float32)
    o = opt.create("sgd", learning_rate=1.0, clip_gradient=1.0,
                   rescale_grad=1.0, wd=0.0)
    got = _run_updates(o, w0, [g])
    assert np.allclose(got, [-1.0, 1.0, -0.5], rtol=1e-5)


def test_lr_scheduler():
    from mxnet_tpu.lr_scheduler import FactorScheduler, MultiFactorScheduler
    s = FactorScheduler(step=10, factor=0.5)
    s.base_lr = 1.0
    assert s(5) == 1.0
    assert s(11) == 0.5
    m = MultiFactorScheduler(step=[5, 8], factor=0.1)
    m.base_lr = 1.0
    assert m(3) == 1.0
    assert abs(m(6) - 0.1) < 1e-9
    assert abs(m(9) - 0.01) < 1e-9


def test_updater_states_roundtrip():
    o = opt.create("sgd", learning_rate=0.1, momentum=0.9)
    u = opt.get_updater(o)
    w = nd.ones((4,))
    u(0, nd.ones((4,)), w)
    states = u.get_states()
    u2 = opt.get_updater(opt.create("sgd", learning_rate=0.1, momentum=0.9))
    u2.set_states(states)
    assert 0 in u2.states


def test_lr_wd_mult_from_attrs():
    data = mx.sym.Variable("data")
    w = mx.sym.Variable("fcx_weight", lr_mult=0.0)
    fc = mx.sym.FullyConnected(data=data, weight=w, num_hidden=3, name="fcx")
    o = opt.create("sgd", learning_rate=1.0, sym=fc,
                   param_idx2name={0: "fcx_weight"})
    w0 = np.ones(3, np.float32)
    got = _run_updates(o, w0, [np.ones(3, np.float32)])
    assert np.allclose(got, w0)  # lr_mult 0 freezes
