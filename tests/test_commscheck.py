"""commscheck tests (docs/static_analysis.md "Communication lints"): the
static collective-communication analyzer over compiled partitioned
programs.

The load-bearing assertions:

* the HLO collective parser handles every spelling the partitioner
  emits — explicit and iota replica_groups, tuple-typed (combined /
  tiled) collectives, async ``-start``/``-done`` pairs counted once,
  ``op_name``-based while-body detection with source provenance;
* the comms *signatures* of the parallel stack hold: ring attention is
  ppermute-only (no all-gather), Ulysses is all-to-all-only (3 in + 1
  out per attention), ``pipeline_spmd`` is an in-loop ppermute ring plus
  one final psum, and the data-parallel fused scan syncs by in-loop
  all-reduce only;
* one SEEDED violation per comms lint class — ``resharding-copy``,
  ``replicated-large``, ``gather-in-loop``, ``comms-bound`` — is caught
  with op path and source provenance asserted;
* the baseline drift gate fails a seeded in-scan all-gather regression
  WITH its byte count and provenance (the ci/commscheck.sh contract);
* the CLI smoke (mlp + lenet, json mode) exits 0 with zero findings and
  zero collectives — the tier-1 mirror of the full-zoo CI gate.
"""
import functools
import json
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from mxnet_tpu import commscheck as cc  # noqa: E402
from mxnet_tpu import tracecheck as tc  # noqa: E402
from mxnet_tpu.base import MXNetError  # noqa: E402

P = jax.sharding.PartitionSpec

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 2,
    reason="commscheck partitioned-program tests need >=2 devices "
           "(conftest forces an 8-device virtual CPU mesh)")


def _mesh(n, names=("data",)):
    shape = (n,) if len(names) == 1 else (n // 2, 2)
    return jax.sharding.Mesh(
        np.array(jax.devices()[:n]).reshape(shape), names)


def _ns(mesh, spec):
    return jax.sharding.NamedSharding(mesh, spec)


def _sds(shape, mesh=None, spec=None, dtype=np.float32):
    if mesh is None:
        return jax.ShapeDtypeStruct(tuple(shape), dtype)
    return jax.ShapeDtypeStruct(tuple(shape), dtype,
                                sharding=_ns(mesh, spec))


# ---------------------------------------------------------------------------
# the HLO parser
# ---------------------------------------------------------------------------

_FAKE_HLO = """HloModule t, is_scheduled=true, entry_computation_layout={(f32[8]{0})->f32[8]{0}}

%wide.body (p: f32[8]) -> f32[8] {
  %p.1 = f32[8]{0} parameter(0)
}

ENTRY %main.1 (Arg_0.1: f32[8], Arg_1.2: f32[16,4]) -> f32[8] {
  %Arg_0.1 = f32[8]{0} parameter(0), metadata={op_name="state['w']"}
  %Arg_1.2 = f32[16,4]{1,0} parameter(1), metadata={op_name="batch"}
  %all-reduce.1 = f32[8]{0} all-reduce(f32[8]{0} %mul.1), channel_id=1, replica_groups=[1,8]<=[8], use_global_device_ids=true, to_apply=%add, metadata={op_name="jit(f)/jit(main)/while/body/psum" source_file="a.py" source_line=3}
  %all-gather.1 = f32[64,4]{1,0} all-gather(f32[16,4]{1,0} %Arg_1.2), channel_id=2, replica_groups=[2,4]<=[4,2]T(1,0), dimensions={0}, metadata={op_name="jit(f)/jit(main)/gather" source_file="a.py" source_line=7}
  %all-to-all.1 = (f32[2,4]{1,0}, f32[2,4]{1,0}) all-to-all(f32[2,4]{1,0} %s.1, f32[2,4]{1,0} %s.2), channel_id=3, replica_groups={{0,1},{2,3},{4,5},{6,7}}, metadata={op_name="jit(f)/jit(main)/a2a" source_file="a.py" source_line=9}
  %collective-permute-start.1 = f32[4,4]{1,0} collective-permute-start(f32[4,4]{1,0} %q.1), channel_id=4, source_target_pairs={{0,1},{1,2},{2,3},{3,0}}, metadata={op_name="jit(f)/jit(main)/while/body/ppermute" source_file="a.py" source_line=11}
  %collective-permute-done.1 = f32[4,4]{1,0} collective-permute-done(f32[4,4]{1,0} %collective-permute-start.1)
}
"""


def test_parser_kinds_groups_and_loop_detection():
    mesh = _mesh(8, ("data", "model"))  # 4x2 grid, flat-order ids
    entries = cc.parse_collectives(_FAKE_HLO, mesh=mesh, loop_trips=3)
    by_kind = {e.kind: e for e in entries}
    assert sorted(by_kind) == ["all-gather", "all-reduce", "all-to-all",
                               "collective-permute"]
    ar = by_kind["all-reduce"]
    assert ar.bytes == 32 and ar.group_size == 8
    assert ar.axes == ("data", "model")       # the full-mesh group
    assert ar.in_loop and ar.multiplier == 3  # /while/ path, 3 trips
    assert ar.provenance == "a.py:3"
    ag = by_kind["all-gather"]
    assert ag.bytes == 64 * 4 * 4
    assert ag.axes == ("data",)               # iota T(1,0): the data axis
    assert not ag.in_loop and ag.multiplier == 1
    assert ag.operand_params == ["batch"]     # consumes an entry param
    a2a = by_kind["all-to-all"]
    assert a2a.bytes == 2 * (2 * 4 * 4)       # TUPLE type: both operands
    assert a2a.axes == ("model",)             # explicit {{0,1},...} groups
    cp = by_kind["collective-permute"]        # -start counted, -done not
    assert cp.bytes == 4 * 4 * 4
    assert cp.in_loop and cp.multiplier == 3
    assert len([e for e in entries if e.kind == "collective-permute"]) == 1


_ASYNC_HLO = """HloModule t, is_scheduled=true, entry_computation_layout={(f32[8,4]{1,0})->f32[32,4]{1,0}}

ENTRY %main.1 (p0: f32[8,4]) -> f32[32,4] {
  %p0 = f32[8,4]{1,0} parameter(0)
  %all-gather-start.1 = (f32[8,4]{1,0}, f32[32,4]{1,0}) all-gather-start(f32[8,4]{1,0} %p0.copy), channel_id=1, replica_groups={{0,1,2,3}}, dimensions={0}, metadata={op_name="jit(f)/ag" source_file="a.py" source_line=4}
  %all-gather-done.1 = f32[32,4]{1,0} all-gather-done((f32[8,4]{1,0}, f32[32,4]{1,0}) %all-gather-start.1)
}
"""


def test_parser_async_start_uses_done_result_type_not_tuple_sum():
    """An async -start's own result type bundles operand AND result
    ((f32[shard], f32[full]) for all-gather-start): the payload must be
    the -done's single result type, not the tuple sum (which would
    double-count on TPU, where async pairs are the default)."""
    entries = cc.parse_collectives(_ASYNC_HLO)
    assert len(entries) == 1
    ag = entries[0]
    assert ag.kind == "all-gather"
    assert ag.bytes == 32 * 4 * 4        # the gathered result ONLY
    assert ag.group_size == 4
    # with the -done line stripped, the largest-tuple-element fallback
    # still avoids the operand+result double count
    stripped = "\n".join(ln for ln in _ASYNC_HLO.splitlines()
                         if "all-gather-done" not in ln)
    entries2 = cc.parse_collectives(stripped)
    assert entries2[0].bytes == 32 * 4 * 4


def test_hlo_unavailable_is_not_a_clean_audit(tmp_path):
    """If the executable's HLO text cannot be read, the empty inventory
    is absence of EVIDENCE: the report says so, the roofline claims
    nothing, and the drift gate fails the program instead of reading a
    pinned-20-collectives program as a 'nice shrink' to zero."""
    class FakeCompiled:
        def as_text(self):
            raise RuntimeError("text unavailable on this backend")

        def cost_analysis(self):
            return {"flops": 1e9}

    rep = cc.analyze_compiled(FakeCompiled(), "gate/scan")
    assert rep.hlo_unavailable
    assert rep.entries == []
    assert rep.predicted_efficiency is None   # no 1.0 claim
    path = str(tmp_path / "b.json")
    cc.write_baseline({"gate/scan": _fake_report("gate/scan", 20, 4096)},
                      path)
    failures, notes = cc.compare_baseline({"gate/scan": rep}, path)
    assert len(failures) == 1
    assert "absence of evidence" in failures[0]
    assert not any("shrank" in n for n in notes)
    # the write path refuses too: a fabricated zero must never be pinned
    with pytest.raises(MXNetError, match="fabricated"):
        cc.write_baseline({"gate/scan": rep}, str(tmp_path / "b2.json"))
    # and the armed dispatch hook does not pass vacuously
    from mxnet_tpu import engine
    prev = engine.set_commscheck("error")
    try:
        cc._AUDITED.discard("blind-prog")

        class FakeJit:
            def lower(self, *a, **k):
                return self

            def compile(self):
                return FakeCompiled()

        with pytest.raises(MXNetError, match="unavailable"):
            cc.maybe_audit_dispatch("blind-prog", FakeJit(), ())
    finally:
        engine.set_commscheck(prev if prev != "off" else None)


def test_parser_empty_replica_groups_defaults_to_whole_mesh():
    """The bare ``replica_groups={}`` spelling means every device in one
    group: the entry must price real wire bytes (whole-mesh group), not
    silently zero out the roofline; with no mesh at all, an unknown
    group still charges one full payload."""
    txt = ("ENTRY %main.1 (p0: f32[1024]) -> f32[1024] {\n"
           "  %p0 = f32[1024]{0} parameter(0)\n"
           "  %all-reduce.9 = f32[1024]{0} all-reduce(f32[1024]{0} %x.1),"
           " channel_id=1, replica_groups={}, use_global_device_ids=true,"
           " to_apply=%add\n}\n")
    mesh = _mesh(8)
    (e,) = cc.parse_collectives(txt, mesh=mesh)
    assert e.group_size == 8
    assert e.axes == ("data",)
    assert e.wire_bytes == cc._wire_bytes("all-reduce", 4096, 8) > 0
    (e2,) = cc.parse_collectives(txt)
    assert e2.group_size is None
    assert e2.wire_bytes == e2.bytes == 4096  # full payload, never zero


def test_parser_tuple_type_with_tpu_tiled_layouts():
    """TPU layouts carry tiling parens inside the braces: a tuple-typed
    combined all-reduce like ``(bf16[256,256]{1,0:T(8,128)}, ...)`` must
    still parse (a lazy type match would truncate at ``T(…)``'s paren
    and the dominant gradient all-reduce would vanish from the
    inventory)."""
    txt = ("ENTRY %main.1 (p0: bf16[256,256]) -> bf16[256,256] {\n"
           "  %all-reduce.3 = (bf16[256,256]{1,0:T(8,128)}, "
           "bf16[256]{0:T(256)}) all-reduce(bf16[256,256]{1,0:T(8,128)} "
           "%a.1, bf16[256]{0:T(256)} %b.1), channel_id=1, "
           "replica_groups={{0,1,2,3}}, to_apply=%add, "
           "metadata={op_name=\"jit(f)/psum\"}\n}\n")
    (e,) = cc.parse_collectives(txt)
    assert e.kind == "all-reduce"
    assert e.bytes == 256 * 256 * 2 + 256 * 2  # both tuple elements
    assert e.group_size == 4


def test_wire_bytes_model():
    # ring-algorithm costs: all-reduce 2(n-1)/n, gather (n-1)/n x result,
    # reduce-scatter (n-1) x its scattered result, ppermute one hop
    assert cc._wire_bytes("all-reduce", 800, 8) == 1400
    assert cc._wire_bytes("all-gather", 800, 8) == 700
    assert cc._wire_bytes("reduce-scatter", 100, 8) == 700
    assert cc._wire_bytes("collective-permute", 800, None) == 800
    assert cc._wire_bytes("all-reduce", 800, 1) == 0


def test_report_totals_and_efficiency_bounds():
    mesh = _mesh(8, ("data", "model"))
    entries = cc.parse_collectives(_FAKE_HLO, mesh=mesh, loop_trips=3)
    rep = cc.CommsReport("fake", "cpu", 8, entries, loop_trips=3,
                         flops=1e9)
    assert rep.collective_count == sum(e.multiplier for e in entries)
    assert rep.collective_bytes == sum(e.bytes * e.multiplier
                                       for e in entries)
    assert 0.0 < rep.predicted_efficiency < 1.0
    assert rep.compute_seconds > 0
    d = rep.as_dict()
    assert d["collective_count"] == rep.collective_count
    assert d["counts_by_kind"]["all-reduce"] == 3
    # collective-free program: efficiency is exactly 1.0
    empty = cc.CommsReport("empty", "cpu", 1, [], flops=1e9)
    assert empty.predicted_efficiency == 1.0
    # collectives but no cost-model FLOPs: no claim, not a guess
    blind = cc.CommsReport("blind", "cpu", 8, entries, flops=None)
    assert blind.predicted_efficiency is None


# ---------------------------------------------------------------------------
# comms signatures of the parallel stack
# ---------------------------------------------------------------------------

def _seq_spec():
    return P(None, None, "seq", None)


def test_ring_attention_signature_ppermute_only():
    """Ring attention rotates K/V via ppermute over neighbor links — its
    compiled signature is collective-permute ONLY (in the ring loop, on
    the 'seq' axis); an all-gather would mean the ring degenerated into
    every chip holding the full sequence."""
    from mxnet_tpu.parallel import ring as pring
    from mxnet_tpu.parallel.mesh import shard_map_compat
    n = min(4, len(jax.devices()))
    mesh = jax.sharding.Mesh(np.array(jax.devices()[:n]), ("seq",))
    fn = shard_map_compat(
        functools.partial(pring.ring_attention, axis_name="seq",
                          causal=True),
        mesh=mesh, in_specs=(_seq_spec(),) * 3, out_specs=_seq_spec(),
        check_vma=False)
    q = _sds((2, 4, 8 * n, 8), mesh, _seq_spec())
    rep = cc.analyze(jax.jit(fn), (q, q, q), name="ring-attn", mesh=mesh)
    counts = rep.counts_by_kind()
    assert counts == {"collective-permute": 2}  # the K and V rotations
    assert all(e.in_loop and e.axes == ("seq",) for e in rep.entries)
    findings = cc.lint_report(rep, min_eff=0.0)
    assert [f for f in findings if f.lint == "gather-in-loop"] == []


def test_ulysses_signature_all_to_all_only():
    """Ulysses converts sequence sharding to head sharding and back: 3
    input all-to-alls (q, k, v) + 1 output all-to-all per attention, and
    nothing else — no all-gather, no ppermute."""
    from mxnet_tpu.parallel import ring as pring
    from mxnet_tpu.parallel.mesh import shard_map_compat
    n = min(4, len(jax.devices()))
    mesh = jax.sharding.Mesh(np.array(jax.devices()[:n]), ("seq",))
    fn = shard_map_compat(
        functools.partial(pring.ulysses_attention, axis_name="seq"),
        mesh=mesh, in_specs=(_seq_spec(),) * 3, out_specs=_seq_spec(),
        check_vma=False)
    q = _sds((2, n, 8 * n, 8), mesh, _seq_spec())
    rep = cc.analyze(jax.jit(fn), (q, q, q), name="ulysses", mesh=mesh)
    assert rep.counts_by_kind() == {"all-to-all": 4}
    assert all(e.axes == ("seq",) for e in rep.entries)


def test_pipeline_spmd_signature_ppermute_ring_plus_final_psum():
    """The GPipe schedule: activations hop stage-to-stage via ppermute
    INSIDE the tick loop; one all-reduce (the last-stage output share)
    outside it. Both allowed — gather-in-loop stays clean."""
    from mxnet_tpu.parallel.pipeline import pipeline_apply
    n = min(4, len(jax.devices()))
    mesh = jax.sharding.Mesh(np.array(jax.devices()[:n]), ("pipe",))

    def stage(p, x):
        return jnp.tanh(x @ p["w"])

    params = {"w": jax.ShapeDtypeStruct((n, 16, 16), np.float32)}
    batch = jax.ShapeDtypeStruct((8, 16), np.float32)

    def pfn(p, b):
        return pipeline_apply(stage, p, b, mesh, axis_name="pipe")

    rep = cc.analyze(jax.jit(pfn), (params, batch), name="pipeline",
                     mesh=mesh)
    counts = rep.counts_by_kind()
    assert counts.get("collective-permute") == 1
    assert counts.get("all-reduce") == 1
    cp = [e for e in rep.entries if e.kind == "collective-permute"][0]
    assert cp.in_loop
    findings = cc.lint_report(rep, min_eff=0.0)
    assert [f for f in findings if f.lint == "gather-in-loop"] == []


@pytest.fixture(scope="module")
def dp_scan_audit():
    """One compile of a data-parallel fused-scan program shared by the
    signature/lint tests (args carry real shardings, state built with
    the no-op initializer — nothing executes)."""
    from mxnet_tpu import models
    from mxnet_tpu.train_step import TrainStep
    from mxnet_tpu.parallel.mesh import data_parallel_mesh
    n = min(4, len(jax.devices()))
    mesh = data_parallel_mesh(n)
    ts = TrainStep(models.mlp(num_classes=4, hidden=(32,)),
                   optimizer="sgd", learning_rate=0.1, momentum=0.9,
                   mesh=mesh)
    batch, k = 8 * n, 2
    state = ts.init({"data": (batch, 64)}, {"softmax_label": (batch,)},
                    initializer=lambda desc, arr: None, seed=0)
    st = cc.struct_args(state)
    sb_spec = P(None, "data")
    sb = {"data": _sds((k, batch, 64), mesh, sb_spec),
          "softmax_label": _sds((k, batch), mesh, sb_spec)}
    args = (st, sb, ts._dispatch_key(), _sds((k,), mesh, P()))
    return cc.check_program(ts._build_scan(batch, k), args,
                            name="dp-mlp-scan", mesh=mesh, loop_trips=k,
                            min_eff=0.0)


def test_dp_scan_syncs_by_in_loop_all_reduce_only(dp_scan_audit):
    """The PR 7 contract, now statically pinned: the partitioned K-step
    scan syncs by all-reduce inside the while body (grad + metric psum)
    and nothing else — and every in-loop entry carries the K
    multiplier."""
    findings, rep = dp_scan_audit
    assert rep.collective_count > 0
    assert set(rep.counts_by_kind()) == {"all-reduce"}
    assert all(e.in_loop and e.multiplier == 2 for e in rep.entries)
    assert all(e.axes == ("data",) for e in rep.entries)
    assert 0.0 < rep.predicted_efficiency <= 1.0
    assert findings == []


def test_zoo_single_device_program_has_empty_inventory():
    findings, reports = cc.check_zoo(names=["mlp"], k=2, guard=False)
    assert findings == []
    for rep in reports.values():
        assert rep.entries == []
        assert rep.collective_count == 0
        assert rep.predicted_efficiency == 1.0


# ---------------------------------------------------------------------------
# seeded violations — one per comms lint class
# ---------------------------------------------------------------------------

def _gather_in_scan_program(n=4):
    """The regression the drift gate exists for: an all_gather inside
    the scan body."""
    from jax.experimental.shard_map import shard_map
    mesh = jax.sharding.Mesh(np.array(jax.devices()[:n]), ("data",))

    def bad(xs):
        def body(c, x):
            g = jax.lax.all_gather(x, "data")
            return c + jnp.sum(g), None
        out, _ = jax.lax.scan(body, jnp.float32(0), xs)
        return out

    sm = shard_map(bad, mesh=mesh, in_specs=P(None, "data"), out_specs=P(),
                   check_rep=False)
    xs = _sds((3, 8 * n), mesh, P(None, "data"))
    return jax.jit(sm), (xs,), mesh


def test_gather_in_loop_finding_seeded():
    fn, args, mesh = _gather_in_scan_program()
    findings, rep = cc.check_program(fn, args, name="seeded-gather",
                                     mesh=mesh, loop_trips=3, min_eff=0.0)
    hits = [f for f in findings if f.lint == "gather-in-loop"]
    assert len(hits) == 1
    assert "/while/" in hits[0].op_path
    assert hits[0].provenance and "test_commscheck" in hits[0].provenance
    assert "x3 per dispatch" in hits[0].message
    # and tracecheck's collective-in-scan stays a working thin alias over
    # the same inventory pass (same program, historical lint id)
    alias = tc.check_collectives(fn, args, name="seeded-gather")
    assert [f.lint for f in alias] == ["collective-in-scan"]
    assert "/while/" in alias[0].op_path


def test_resharding_copy_finding_seeded():
    """An entry argument declared sharded but consumed replicated: the
    partitioner re-lays it out (an all-gather on the parameter) before
    first use — the silent copy PR 7's pre-sharded landing eliminated."""
    n = min(4, len(jax.devices()))
    mesh = jax.sharding.Mesh(np.array(jax.devices()[:n]), ("data",))

    def f(x):
        y = jax.lax.with_sharding_constraint(x, _ns(mesh, P()))
        return jnp.sum(y)

    x = _sds((1024, 64), mesh, P("data"))
    findings, rep = cc.check_program(jax.jit(f), (x,), name="seeded-reshard",
                                     mesh=mesh, min_eff=0.0,
                                     repl_threshold=1 << 30)
    hits = [f_ for f_ in findings if f_.lint == "resharding-copy"]
    assert len(hits) == 1
    assert "'x'" in hits[0].message          # names the argument
    assert "all-gather" in hits[0].message
    assert hits[0].op_path
    assert hits[0].provenance and "test_commscheck" in hits[0].provenance


def test_replicated_large_finding_seeded():
    n = min(4, len(jax.devices()))
    mesh = jax.sharding.Mesh(np.array(jax.devices()[:n]), ("data",))

    def f(x):
        h = x * jnp.float32(2.0)  # sharded intermediate...
        return jax.lax.with_sharding_constraint(h, _ns(mesh, P()))

    x = _sds((1024, 64), mesh, P("data"))
    findings, rep = cc.check_program(jax.jit(f), (x,), name="seeded-repl",
                                     mesh=mesh, min_eff=0.0,
                                     repl_threshold=64 << 10)
    hits = [f_ for f_ in findings if f_.lint == "replicated-large"]
    assert len(hits) == 1
    assert "MXTPU_COMMSCHECK_REPL_BYTES" in hits[0].message
    assert "axis data" in hits[0].message
    assert hits[0].provenance and "test_commscheck" in hits[0].provenance


def test_comms_bound_finding_seeded():
    """A comm-heavy loop against a high floor: the roofline flags the
    program as communication-bound WITH the inventory attached."""
    fn, args, mesh = _gather_in_scan_program()
    findings, rep = cc.check_program(fn, args, name="seeded-bound",
                                     mesh=mesh, loop_trips=3,
                                     min_eff=0.999)
    hits = [f for f in findings if f.lint == "comms-bound"]
    assert len(hits) == 1
    assert "MXTPU_COMMSCHECK_MIN_EFF" in hits[0].message
    assert "Inventory:" in hits[0].message
    assert "all-gather" in hits[0].message   # the inventory rides along
    assert rep.predicted_efficiency < 0.999


def test_comms_lints_suppressible_via_shared_registry():
    tok = tc.add_suppression("gather-in-loop", program="seeded-gather")
    try:
        fn, args, mesh = _gather_in_scan_program()
        findings, _ = cc.check_program(fn, args, name="seeded-gather",
                                       mesh=mesh, loop_trips=3,
                                       min_eff=0.0)
        hits = [f for f in findings if f.lint == "gather-in-loop"]
        assert hits and all(f.suppressed for f in hits)
        assert cc.unsuppressed(hits) == []
    finally:
        tc.remove_suppression(tok)


# ---------------------------------------------------------------------------
# knobs + the runtime hook
# ---------------------------------------------------------------------------

def test_repl_bytes_and_min_eff_env(monkeypatch):
    monkeypatch.delenv("MXTPU_COMMSCHECK_REPL_BYTES", raising=False)
    assert cc.repl_bytes() == 1 << 20
    monkeypatch.setenv("MXTPU_COMMSCHECK_REPL_BYTES", "4M")
    assert cc.repl_bytes() == 4 << 20
    monkeypatch.setenv("MXTPU_COMMSCHECK_REPL_BYTES", "banana")
    with pytest.raises(MXNetError, match="MXTPU_COMMSCHECK_REPL_BYTES"):
        cc.repl_bytes()
    monkeypatch.delenv("MXTPU_COMMSCHECK_MIN_EFF", raising=False)
    assert cc.min_efficiency() == 0.5
    monkeypatch.setenv("MXTPU_COMMSCHECK_MIN_EFF", "0.8")
    assert cc.min_efficiency() == 0.8


def test_commscheck_mode_knob(monkeypatch):
    from mxnet_tpu import engine
    engine.set_commscheck(None)
    monkeypatch.delenv("MXTPU_COMMSCHECK", raising=False)
    assert engine.commscheck_mode() == "off"
    monkeypatch.setenv("MXTPU_COMMSCHECK", "warn")
    assert engine.commscheck_mode() == "warn"
    monkeypatch.setenv("MXTPU_COMMSCHECK", "error")
    assert engine.commscheck_mode() == "error"
    monkeypatch.setenv("MXTPU_COMMSCHECK", "banana")
    with pytest.raises(MXNetError, match="MXTPU_COMMSCHECK"):
        engine.commscheck_mode()
    monkeypatch.delenv("MXTPU_COMMSCHECK", raising=False)
    prev = engine.set_commscheck("error")
    try:
        assert engine.commscheck_mode() == "error"
    finally:
        engine.set_commscheck(prev if prev != "off" else None)


def _dp_train_step(n=2):
    from mxnet_tpu import models
    from mxnet_tpu.train_step import TrainStep
    from mxnet_tpu.parallel.mesh import data_parallel_mesh
    mesh = data_parallel_mesh(n)
    ts = TrainStep(models.mlp(num_classes=4, hidden=(16,)),
                   optimizer="sgd", learning_rate=0.1, mesh=mesh)
    batch, k = 4 * n, 2
    state = ts.init({"data": (batch, 16)}, {"softmax_label": (batch,)})
    rng = np.random.default_rng(0)
    sb = ts.shard_superbatch({
        "data": rng.normal(size=(k, batch, 16)).astype(np.float32),
        "softmax_label": rng.integers(0, 4, (k, batch))
        .astype(np.float32)})
    return ts, state, sb


def test_dispatch_hook_audits_sharded_program_once(monkeypatch):
    """MXTPU_COMMSCHECK=warn: the first dispatch of a sharded program
    runs the comms audit once (one extra compile) and registers the
    program as audited; clean programs log nothing and training
    proceeds."""
    from mxnet_tpu import engine
    prev = engine.set_commscheck("warn")
    try:
        before = set(cc._AUDITED)
        ts, state, sb = _dp_train_step()
        state, m = ts.run_steps(state, sb)
        new = set(cc._AUDITED) - before
        assert len(new) == 1 and "scan" in next(iter(new))
        # second dispatch: memoized, no re-audit
        state, m = ts.run_steps(state, sb)
        assert set(cc._AUDITED) - before == new
        assert m.num_samples > 0
    finally:
        engine.set_commscheck(prev if prev != "off" else None)


def test_dispatch_hook_error_mode_raises_on_finding(monkeypatch):
    """MXTPU_COMMSCHECK=error + an impossible efficiency floor: the
    first sharded dispatch fails fast with the comms findings instead of
    burning a slow multichip run."""
    from mxnet_tpu import engine
    monkeypatch.setenv("MXTPU_COMMSCHECK_MIN_EFF", "0.9999")
    prev = engine.set_commscheck("error")
    try:
        ts, state, sb = _dp_train_step()
        with pytest.raises(MXNetError, match="comms-bound"):
            ts.run_steps(state, sb)
    finally:
        engine.set_commscheck(prev if prev != "off" else None)


def test_dispatch_hook_off_by_default(monkeypatch):
    from mxnet_tpu import engine
    engine.set_commscheck(None)
    monkeypatch.delenv("MXTPU_COMMSCHECK", raising=False)
    before = set(cc._AUDITED)
    ts, state, sb = _dp_train_step()
    ts.run_steps(state, sb)
    assert set(cc._AUDITED) == before


# ---------------------------------------------------------------------------
# the baseline drift gate (ci/commscheck.sh contract)
# ---------------------------------------------------------------------------

def _fake_report(name, count=0, nbytes=0, in_loop=True, kind="all-reduce",
                 prov=None):
    entries = []
    for i in range(count):
        entries.append(cc.CollectiveEntry(
            "%s.%d" % (kind, i), kind, nbytes // max(1, count),
            nbytes // max(1, count), 8, ("data",), None, in_loop, 1,
            "jit(f)/jit(main)/while/body/op", prov))
    return cc.CommsReport(name, jax.devices()[0].platform, 8, entries,
                          flops=1e9)


def test_baseline_roundtrip_passes(tmp_path):
    reports = {"a/scan[k=2]": _fake_report("a/scan[k=2]", 3, 3000),
               "b/step": _fake_report("b/step", 0, 0)}
    path = str(tmp_path / "b.json")
    cc.write_baseline(reports, path)
    failures, notes = cc.compare_baseline(reports, path)
    assert failures == []
    assert notes == []


def test_baseline_fails_seeded_in_scan_all_gather_with_provenance(tmp_path):
    """The acceptance contract: a baseline pinned on the clean psum-only
    scan FAILS when the same program grows an in-scan all-gather — with
    the gather's byte count and source provenance in the failure."""
    fn, args, mesh = _gather_in_scan_program()
    clean = {"gate/scan": _fake_report("gate/scan", 2, 2048)}
    path = str(tmp_path / "b.json")
    cc.write_baseline(clean, path)
    regressed = cc.analyze(fn, args, name="gate/scan", mesh=mesh,
                           loop_trips=3)
    assert any(e.kind == "all-gather" for e in regressed.entries)
    failures, _ = cc.compare_baseline({"gate/scan": regressed}, path)
    assert failures
    joined = "\n".join(failures)
    assert "collective_count grew" in joined or \
        "collective_bytes grew" in joined
    assert "all-gather" in joined            # the inventory rides along
    assert "test_commscheck" in joined       # ...with provenance
    assert "MXTPU_COMMSCHECK_TOL" in joined


def test_baseline_zero_pinned_program_fails_on_first_collective(tmp_path):
    """A single-device zoo program pins ZERO collectives — counts are
    HLO-deterministic, so there is no absolute slack and the first
    collective to appear fails at any tolerance."""
    path = str(tmp_path / "b.json")
    cc.write_baseline({"mlp/step": _fake_report("mlp/step", 0, 0)}, path)
    failures, _ = cc.compare_baseline(
        {"mlp/step": _fake_report("mlp/step", 1, 8)}, path, tol=10.0)
    assert len(failures) == 2  # count AND bytes grew past 0


def test_baseline_missing_stale_platform_and_shrink(tmp_path):
    reports = {"a/step": _fake_report("a/step", 4, 4096)}
    path = str(tmp_path / "b.json")
    cc.write_baseline(reports, path)
    # missing program fails, stale entry is a note
    failures, notes = cc.compare_baseline(
        {"a/step": reports["a/step"],
         "new/step": _fake_report("new/step", 1, 8)}, path)
    assert len(failures) == 1 and "new/step" in failures[0]
    assert "--write-baseline" in failures[0]
    failures2, notes2 = cc.compare_baseline({}, path)
    assert failures2 == []
    assert any("stale" in n for n in notes2)
    # platform mismatch skips the gate with one note
    failures3, notes3 = cc.compare_baseline(reports, {
        "platform": "tpu", "tolerance": 0.1,
        "programs": {"a/step": {"collective_count": 1,
                                "collective_bytes": 1}}})
    assert failures3 == []
    assert len(notes3) == 1 and "platform" in notes3[0]
    # shrink is a note, not a failure
    failures4, notes4 = cc.compare_baseline(
        {"a/step": _fake_report("a/step", 1, 1024)}, path)
    assert failures4 == []
    assert any("shrank" in n for n in notes4)
    # ...but a TOTAL collapse to zero on a nonzero-pinned program fails:
    # indistinguishable from a parser gone blind on an HLO format drift
    failures5, _ = cc.compare_baseline(
        {"a/step": _fake_report("a/step", 0, 0)}, path)
    assert len(failures5) == 2
    assert all("collapsed" in f for f in failures5)


def test_baseline_tol_env_overrides_stored_band(tmp_path, monkeypatch):
    reports = {"a/step": _fake_report("a/step", 10, 10240)}
    path = str(tmp_path / "b.json")
    cc.write_baseline(reports, path, tol=0.1)
    grown = {"a/step": _fake_report("a/step", 13, 13312)}
    monkeypatch.delenv("MXTPU_COMMSCHECK_TOL", raising=False)
    failures, _ = cc.compare_baseline(grown, path)
    assert failures  # +30% past the stored 10% band
    monkeypatch.setenv("MXTPU_COMMSCHECK_TOL", "0.5")
    failures, _ = cc.compare_baseline(grown, path)
    assert failures == []  # env-widened band wins


# ---------------------------------------------------------------------------
# CLI (tier-1 smoke of the ci/commscheck.sh gate)
# ---------------------------------------------------------------------------

def test_cli_smoke_json_mlp_lenet(capsys):
    """The tier-1 mirror of the full-zoo CI gate: mlp + lenet in json
    mode exit 0 with zero findings and ZERO collectives on every
    single-device program."""
    rc = cc.main(["--models", "mlp,lenet", "--json"])
    data = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert data["findings"] == []
    assert data["suppressed"] == 0
    assert len(data["programs"]) == 8
    for rep in data["programs"].values():
        assert rep["collective_count"] == 0
        assert rep["collective_bytes"] == 0
        assert rep["predicted_efficiency"] == 1.0
    assert data["platform"] == jax.devices()[0].platform


def test_cli_fails_on_hlo_unavailable_even_without_baseline(
        capsys, monkeypatch):
    """The absence-of-evidence contract holds in the no-baseline CLI
    modes too (the model-subset smoke): a backend where as_text() fails
    must not print PASS over an audit that saw no HLO."""
    blind = cc.CommsReport("mlp/step", jax.devices()[0].platform, 1, [],
                           hlo_unavailable=True)
    monkeypatch.setattr(cc, "check_zoo",
                        lambda **kw: ([], {"mlp/step": blind}))
    rc = cc.main(["--models", "mlp", "--json"])
    data = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert any("absence of evidence" in f
               for f in data["baseline_failures"])
    assert data["programs"]["mlp/step"]["hlo_unavailable"] is True


def test_cli_list_and_bad_model(capsys):
    assert cc.main(["--list"]) == 0
    assert "mlp" in capsys.readouterr().out
    with pytest.raises(MXNetError, match="unknown zoo model"):
        cc.main(["--models", "nope"])


def test_cli_write_and_gate_baseline(tmp_path, capsys):
    path = str(tmp_path / "b.json")
    rc = cc.main(["--models", "mlp", "--quiet", "--write-baseline", path])
    capsys.readouterr()
    assert rc == 0
    rc = cc.main(["--models", "mlp", "--quiet", "--baseline", path])
    out = capsys.readouterr().out
    assert rc == 0
    assert "0 baseline regression(s)" in out
    # a baseline claiming programs this CLI run does not audit: failure
    # comes only from the MISSING direction (deliberate-add contract)
    with open(path) as f:
        base = json.load(f)
    base["programs"]["ghost/step"] = {"collective_count": 0,
                                      "collective_bytes": 0}
    with open(path, "w") as f:
        json.dump(base, f)
    rc = cc.main(["--models", "mlp", "--quiet", "--baseline", path])
    out = capsys.readouterr().out
    assert rc == 0  # stale entries are notes, not failures
    assert "stale" in out


def test_sharded_programs_reject_insufficient_devices():
    if len(jax.devices()) >= 64:
        pytest.skip("cannot provoke the under-provisioned error here")
    with pytest.raises(MXNetError, match="xla_force_host_platform"):
        cc.sharded_programs(n_devices=64)
