"""Chaos harness unit tier (docs/robustness.md "Chaos harness").

Everything here is tier-1-cheap: plan determinism (in-process AND across
a subprocess), serialization round-trips, the greedy shrinker against
synthetic run functions, the invariant judgments against synthetic fact
sheets, the registry/docs/tests audit, and a smoke pass that fires every
registered site once through ``faults.fire``/``fire_flag``. The real
scenario executions live in the CI gate (``ci/chaos.sh`` →
``tools/chaos_gate.py``), not in pytest.
"""
import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import faults
from mxnet_tpu.chaos import (ChaosPlan, sample_plan, check_scenario,
                             shrink_plan, INVARIANTS, SCENARIOS)
from mxnet_tpu.chaos import audit as chaos_audit
from mxnet_tpu.chaos.plan import PLAN_VERSION


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


# -- the site registry + smoke: fire every registered site ------------------

# the canonical literal inventory — the audit greps tests/ for each site
# name, and this smoke proves each (site, kind) pair round-trips through
# arm/fire. Keep in sync with faults.SITES (the test asserts equality).
ALL_SITES = [
    ("checkpoint.write", "raise"),
    ("checkpoint.write.mid", "raise"),
    ("ckpt.async_die", "die"),
    ("ckpt.async_write", "raise"),
    ("ckpt.disk_full", "enospc"),
    ("data.decode_delay", "delay"),
    ("data.worker_die", "die"),
    ("fleet.replica_die", "die"),
    ("guard.grad_nan", "poison"),
    ("guard.loss_spike", "poison"),
    ("guard.param_nan", "poison"),
    ("io.batch_read", "transient"),
    ("io.h2d", "transient"),
    ("io.record_read", "transient"),
    ("kv.partition", "drop"),
    ("kv.push_delay", "delay"),
    ("kv.reform_delay", "delay"),
    ("kv.worker_die", "die"),
    ("kvstore.barrier", "transient"),
    ("kvstore.dead_node", "dead:1"),
    ("kvstore.pull", "transient"),
    ("kvstore.push", "transient"),
    ("serve.decode_die", "die"),
    ("serve.enqueue_drop", "drop"),
    ("serve.sample", "raise"),
    ("serve.spec_verify", "raise"),
    ("superbatch.producer", "die"),
]


def test_site_inventory_matches_registry():
    assert [s for s, _ in ALL_SITES] == sorted(faults.SITES)
    for site, kind in ALL_SITES:
        info = faults.SITES[site]
        assert kind in info.kinds, (site, kind, info.kinds)
        assert info.doc, site
        for scen in info.scenarios:
            assert scen in SCENARIOS, (site, scen)


@pytest.mark.faults
@pytest.mark.parametrize("site,kind", ALL_SITES,
                         ids=[s for s, _ in ALL_SITES])
def test_every_registered_site_fires(site, kind):
    """Each site's first registered kind round-trips arm -> fire ->
    fired_counts — the coverage the chaos sampler builds on."""
    flag = faults.SITES[site].flag
    faults.inject(site, nth=1, kind=kind, delay=0.0)
    if flag:
        assert faults.fire_flag(site) is True
    else:
        try:
            act = faults.fire(site)
        except mx.MXNetError:
            act = "raised"  # raise/transient kinds: typed, still counted
        assert act is not None
    assert faults.fired(site) == 1
    assert faults.fired_counts() == {site: 1}
    faults.clear()
    assert faults.fired_counts() == {}


def test_arm_rejects_unregistered_site():
    with pytest.raises(mx.MXNetError, match="unregistered fault site"):
        faults.arm([{"site": "no.such.site", "kind": "raise", "nth": 1}])


def test_plan_scope_clears_on_exit():
    rules = [{"site": "io.record_read", "kind": "raise", "nth": 1}]
    with faults.plan_scope(rules):
        with pytest.raises(mx.MXNetError):
            faults.fire("io.record_read")
        assert faults.fired("io.record_read") == 1
    assert faults.fire("io.record_read") is None  # disarmed + reset


def test_sites_filter_by_scenario():
    for scen in SCENARIOS:
        pool = faults.sites(scen)
        assert pool, scen
        for s in pool:
            assert scen in faults.SITES[s].scenarios
    # kvstore.dead_node is registered but deliberately never sampled
    assert "kvstore.dead_node" in faults.SITES
    assert all("kvstore.dead_node" not in faults.sites(s)
               for s in SCENARIOS)


# -- plan determinism -------------------------------------------------------

def test_same_seed_same_plan_bytes():
    for scen in SCENARIOS:
        a = sample_plan(11, scen)
        b = sample_plan(11, scen)
        assert a == b and a.to_json() == b.to_json()
        assert sample_plan(12, scen) != a


def test_plan_deterministic_across_processes():
    """The committable-regression property: a fresh interpreter (its own
    PYTHONHASHSEED, import order, everything) emits byte-identical plan
    JSON for the same seed."""
    here = sample_plan(5, "train").to_json()
    out = subprocess.run(
        [sys.executable, "-m", "mxnet_tpu.chaos", "--emit-plan",
         "--seed", "5", "--scenario", "train"],
        capture_output=True, text=True, timeout=120,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert out.returncode == 0, out.stderr
    assert out.stdout == here


def test_plan_samples_are_well_formed():
    for scen in SCENARIOS:
        for seed in range(20):
            plan = sample_plan(seed, scen)
            assert 1 <= len(plan) <= 4
            died = 0
            for r in plan.faults:
                info = faults.SITES[r["site"]]
                assert scen in info.scenarios
                assert r["kind"] in info.kinds
                assert r["nth"] >= 1 and r["times"] >= 1
                assert 0.0 < r["delay"] <= 0.2
                if scen == "dist":
                    assert 0 <= r["rank"] <= 2
                    if r["kind"] == "die":
                        died += 1
                        assert r["rank"] != 0, \
                            "a plan must never kill rank 0 (it hosts " \
                            "the coordination service)"
                else:
                    assert "rank" not in r
            assert died <= 1  # max_per_plan on destructive rules


def test_plan_roundtrip_and_version_gate(tmp_path):
    plan = sample_plan(9, "serve")
    path = plan.save(str(tmp_path / "p.json"))
    loaded = ChaosPlan.load(path)
    assert loaded == plan
    assert loaded.to_json() == open(path).read()  # byte-for-byte
    bad = plan.to_dict()
    bad["version"] = PLAN_VERSION + 1
    with pytest.raises(mx.MXNetError, match="plan version"):
        ChaosPlan.from_dict(bad)


def test_rules_for_rank_partitions_dist_plan():
    plan = ChaosPlan(0, "dist", [
        {"site": "kv.worker_die", "kind": "die", "nth": 9, "times": 1,
         "delay": 0.05, "rank": 2},
        {"site": "kvstore.pull", "kind": "transient", "nth": 1,
         "times": 1, "delay": 0.05, "rank": 0},
        {"site": "kv.push_delay", "kind": "delay", "nth": 3, "times": 1,
         "delay": 0.1},          # no rank -> every rank arms it
    ])
    assert [r["site"] for r in plan.rules_for_rank(0)] == \
        ["kvstore.pull", "kv.push_delay"]
    assert [r["site"] for r in plan.rules_for_rank(2)] == \
        ["kv.worker_die", "kv.push_delay"]
    assert plan.sites() == ["kv.push_delay", "kv.worker_die",
                            "kvstore.pull"]


def test_committed_regression_plan_replays_byte_for_byte():
    """tests/chaos_plans/ holds plans CI replays forever; each must be
    exactly what its (seed, scenario) samples today — sampler drift
    would silently change what the regression reproduces."""
    plans_dir = os.path.join(os.path.dirname(__file__), "chaos_plans")
    committed = sorted(os.listdir(plans_dir))
    assert committed, "no committed regression plans"
    for name in committed:
        path = os.path.join(plans_dir, name)
        raw = open(path).read()
        plan = ChaosPlan.load(path)
        assert plan.to_json() == raw, name
        resampled = sample_plan(plan.seed, plan.scenario,
                                n_faults=len(plan))
        assert resampled.to_json() == raw, \
            "%s: sampler drifted from the committed bytes" % name


# -- the shrinker -----------------------------------------------------------

def _mk_plan(sites):
    return ChaosPlan(0, "train", [
        {"site": s, "kind": "raise", "nth": i + 1, "times": 1,
         "delay": 0.05} for i, s in enumerate(sites)])


def test_shrink_drops_irrelevant_rules():
    plan = _mk_plan(["io.batch_read", "checkpoint.write",
                     "ckpt.async_write", "io.h2d"])

    def violates(p):  # only the checkpoint.write+io.h2d pair matters
        s = set(p.sites())
        return "checkpoint.write" in s and "io.h2d" in s

    shrunk, runs = shrink_plan(plan, violates)
    assert shrunk.sites() == ["checkpoint.write", "io.h2d"]
    assert violates(shrunk)
    assert runs >= 4  # it actually re-ran candidates


def test_shrink_single_culprit():
    plan = _mk_plan(["io.batch_read", "checkpoint.write", "io.h2d"])
    shrunk, _ = shrink_plan(plan, lambda p: "io.h2d" in p.sites())
    assert shrunk.sites() == ["io.h2d"] and len(shrunk) == 1


def test_shrink_keeps_minimal_plan_unchanged():
    plan = _mk_plan(["io.batch_read", "io.h2d"])
    shrunk, runs = shrink_plan(plan, lambda p: len(p) == 2)
    assert shrunk == plan and runs == 2  # tried both drops, both passed


# -- invariant judgments over synthetic fact sheets -------------------------

def _result(**over):
    base = {"scenario": "train", "outcome": "completed", "typed": True,
            "fault_fired": {}, "fault_counts": {}, "health": {},
            "flight": {"exists": True, "parses": True}}
    base.update(over)
    return base


def _outcome(result=None, **over):
    base = {"scenario": "train", "watchdog_fired": False, "rc": 0,
            "wall_s": 1.0, "deadline_s": 240.0, "result": result}
    base.update(over)
    return base


def _viols(plan, outcome):
    return {v.invariant for v in check_scenario(plan, outcome)}


def test_invariant_green_run_is_green():
    plan = sample_plan(0, "train")
    assert check_scenario(plan, _outcome(_result())) == []


def test_invariant_watchdog_is_no_hang():
    plan = sample_plan(0, "train")
    assert _viols(plan, _outcome(None, watchdog_fired=True)) == \
        {"no_hang"}


def test_invariant_missing_result_is_bare_crash():
    plan = sample_plan(0, "train")
    assert _viols(plan, _outcome(None, rc=1)) == {"typed_outcome"}


def test_invariant_untyped_error_flagged():
    plan = sample_plan(0, "train")
    res = _result(outcome="error", typed=False, error_type="ValueError",
                  error_msg="boom")
    assert "typed_outcome" in _viols(plan, _outcome(res))
    res = _result(outcome="error", typed=True,
                  error_type="InjectedFault", error_msg="injected")
    assert check_scenario(plan, _outcome(res)) == []


def test_invariant_settle_partition():
    plan = sample_plan(1, "serve")
    ok = {"submitted": 10, "completed": 7, "expired": 1, "shed": 1,
          "failed": 1, "unsettled": 0}
    assert check_scenario(
        plan, _outcome(_result(scenario="serve", settle=ok))) == []
    lost = dict(ok, completed=6, unsettled=1)
    assert _viols(plan, _outcome(_result(scenario="serve",
                                         settle=lost))) == \
        {"settled_once"}


def test_invariant_resume_and_stream():
    plan = sample_plan(0, "train")
    bad = _result(resume={"mode": "bitwise", "ok": False,
                          "detail": "hash mismatch"})
    assert _viols(plan, _outcome(bad)) == {"bitwise_resume"}
    bad = _result(scenario="data",
                  stream={"ok": False, "detail": "reordered"})
    assert _viols(plan, _outcome(bad)) == {"bitwise_resume"}


def test_invariant_health_consistency_grad_nan():
    plan = ChaosPlan(0, "train", [
        {"site": "guard.grad_nan", "kind": "poison", "nth": 1,
         "times": 1, "delay": 0.05}])
    fired = _result(fault_fired={"guard.grad_nan": 1})
    assert _viols(plan, _outcome(fired)) == {"health_consistent"}
    fired_ok = _result(fault_fired={"guard.grad_nan": 1},
                       health={"training": {"skipped": 1}})
    assert check_scenario(plan, _outcome(fired_ok)) == []


def test_invariant_flight_dump_required_on_failure_sites():
    plan = ChaosPlan(1, "serve", [
        {"site": "fleet.replica_die", "kind": "die", "nth": 1,
         "times": 1, "delay": 0.05}])
    res = _result(scenario="serve",
                  fault_fired={"fleet.replica_die": 1},
                  flight={"exists": False, "parses": False})
    assert _viols(plan, _outcome(res)) == {"flight_dump"}


def test_invariant_dist_survivor_hash_divergence():
    plan = sample_plan(13, "dist")
    ranks = {0: _result(scenario="dist", final_hash="aa" * 32),
             1: _result(scenario="dist", final_hash="bb" * 32),
             2: None}
    out = _outcome(None, scenario="dist", rank_results=ranks,
                   expected_dead=[2], rc=137)
    del out["result"]
    assert _viols(plan, out) == {"bitwise_resume"}


def test_break_invariant_env_inverts_verdict(monkeypatch):
    """The gate's RED self-test hook: a green run turns red on the named
    invariant, and a red run's matching violations are suppressed."""
    plan = sample_plan(0, "train")
    monkeypatch.setenv("MXTPU_CHAOS_BREAK_INVARIANT", "typed_outcome")
    viols = check_scenario(plan, _outcome(_result()))
    assert [v.invariant for v in viols] == ["typed_outcome"]
    assert "deliberately inverted" in viols[0].detail
    # a genuinely red run: its typed_outcome violations are dropped
    red = _outcome(_result(outcome="error", typed=False,
                           error_type="ValueError", error_msg="x"))
    assert check_scenario(plan, red) == []


# -- the audit --------------------------------------------------------------

def test_audit_sites_clean():
    """Tier-1 wiring of ``python -m mxnet_tpu.chaos --audit-sites``: the
    live registry, the docs site table and test coverage agree."""
    assert chaos_audit.audit_sites() == []


def test_audit_detects_doc_drift(tmp_path):
    doc = tmp_path / "robustness.md"
    doc.write_text(
        "<!-- chaos-site-table:begin -->\n"
        "| site | kinds | scenarios | effect |\n|---|---|---|---|\n"
        "| `io.record_read` | transient | data | x |\n"
        "| `no.such.site` | raise | train | ghost |\n"
        "<!-- chaos-site-table:end -->\n")
    problems = chaos_audit.audit_sites(doc_path=str(doc))
    assert any("'ckpt.disk_full'" in p and "missing from" in p
               for p in problems)
    assert any("'no.such.site'" in p and "not registered" in p
               for p in problems)


def test_audit_detects_missing_markers(tmp_path):
    doc = tmp_path / "robustness.md"
    doc.write_text("no table here\n")
    with pytest.raises(ValueError, match="markers missing"):
        chaos_audit.doc_sites(doc_path=str(doc))


def test_audit_detects_untested_site(tmp_path):
    (tmp_path / "test_x.py").write_text('faults.fire("io.record_read")\n')
    problems = chaos_audit.audit_sites(tests_dir=str(tmp_path))
    assert any("'ckpt.disk_full'" in p and "no test" in p
               for p in problems)
    assert not any("'io.record_read'" in p for p in problems)


def test_audit_cli_exit_code():
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = subprocess.run(
        [sys.executable, "-m", "mxnet_tpu.chaos", "--audit-sites"],
        capture_output=True, text=True, timeout=120, cwd=root)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "OK" in out.stdout
