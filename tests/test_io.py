"""Data iterator tests (ref strategy: tests/python/unittest/test_io.py)."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import io


def test_ndarray_iter_basic():
    X = np.arange(40).reshape(10, 4).astype(np.float32)
    y = np.arange(10).astype(np.float32)
    it = io.NDArrayIter(X, y, batch_size=5)
    batches = list(it)
    assert len(batches) == 2
    assert batches[0].data[0].shape == (5, 4)
    assert (batches[0].data[0].asnumpy() == X[:5]).all()
    assert (batches[0].label[0].asnumpy() == y[:5]).all()
    assert batches[0].pad == 0


def test_ndarray_iter_pad():
    X = np.arange(28).reshape(7, 4).astype(np.float32)
    it = io.NDArrayIter(X, None, batch_size=3, last_batch_handle="pad")
    batches = list(it)
    assert len(batches) == 3
    assert batches[-1].pad == 2
    # padded with wrap-around
    assert (batches[-1].data[0].asnumpy()[1:] == X[:2]).all()


def test_ndarray_iter_discard():
    X = np.arange(28).reshape(7, 4).astype(np.float32)
    it = io.NDArrayIter(X, None, batch_size=3, last_batch_handle="discard")
    assert len(list(it)) == 2


def test_ndarray_iter_reset():
    X = np.arange(12).reshape(6, 2).astype(np.float32)
    it = io.NDArrayIter(X, None, batch_size=3)
    n1 = len(list(it))
    it.reset()
    n2 = len(list(it))
    assert n1 == n2 == 2


def test_provide_data_desc():
    X = np.zeros((8, 3, 4, 4), np.float32)
    y = np.zeros(8, np.float32)
    it = io.NDArrayIter(X, y, batch_size=2)
    assert it.provide_data[0].name == "data"
    assert it.provide_data[0].shape == (2, 3, 4, 4)
    assert it.provide_label[0].name == "softmax_label"
    assert it.provide_label[0].shape == (2,)


def test_resize_iter():
    X = np.zeros((6, 2), np.float32)
    it = io.ResizeIter(io.NDArrayIter(X, None, batch_size=2), size=5)
    assert len(list(it)) == 5


def test_prefetching_iter():
    X = np.arange(24).reshape(12, 2).astype(np.float32)
    y = np.arange(12).astype(np.float32)
    inner = io.NDArrayIter(X, y, batch_size=4)
    it = io.PrefetchingIter(inner)
    batches = list(it)
    assert len(batches) == 3
    assert (batches[0].data[0].asnumpy() == X[:4]).all()
    it.reset()
    assert len(list(it)) == 3


def test_csv_iter(tmp_path):
    data_path = str(tmp_path / "data.csv")
    label_path = str(tmp_path / "label.csv")
    X = np.random.rand(10, 3).astype(np.float32)
    y = np.arange(10).astype(np.float32)
    np.savetxt(data_path, X, delimiter=",")
    np.savetxt(label_path, y, delimiter=",")
    it = io.CSVIter(data_csv=data_path, data_shape=(3,),
                    label_csv=label_path, batch_size=5)
    batches = list(it)
    assert len(batches) == 2
    assert np.allclose(batches[0].data[0].asnumpy(), X[:5], rtol=1e-5)


def test_native_recordio_reader(tmp_path):
    """C++ reader parity with the python writer (src/io/recordio_reader.cc)."""
    from mxnet_tpu import recordio
    path = str(tmp_path / "native.rec")
    w = recordio.MXRecordIO(path, "w")
    payloads = [b"hello", b"x" * 1000, b"", b"tail"]
    for p in payloads:
        w.write(p)
    w.close()

    r = recordio.NativeRecordIOReader(path)
    got = []
    while True:
        rec = r.read()
        if rec is None:
            break
        got.append(rec)
    assert got == payloads
    n = r.build_index()
    assert n == 4
    assert r.read_at(1) == payloads[1]
    assert r.read_at(3) == payloads[3]
    r.close()

    r2 = recordio.NativeRecordIOReader(path, prefetch=True)
    got2 = []
    while True:
        rec = r2.read()
        if rec is None:
            break
        got2.append(rec)
    assert got2 == payloads
    r2.close()


def test_recordio_python_roundtrip(tmp_path):
    from mxnet_tpu import recordio
    path = str(tmp_path / "py.rec")
    idx = str(tmp_path / "py.idx")
    w = recordio.MXIndexedRecordIO(idx, path, "w")
    for i in range(5):
        header = recordio.IRHeader(0, float(i), i, 0)
        w.write_idx(i, recordio.pack(header, b"payload%d" % i))
    w.close()
    r = recordio.MXIndexedRecordIO(idx, path, "r")
    h, payload = recordio.unpack(r.read_idx(3))
    assert h.label == 3.0
    assert payload == b"payload3"
