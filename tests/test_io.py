"""Data iterator tests (ref strategy: tests/python/unittest/test_io.py)."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import io


def test_ndarray_iter_basic():
    X = np.arange(40).reshape(10, 4).astype(np.float32)
    y = np.arange(10).astype(np.float32)
    it = io.NDArrayIter(X, y, batch_size=5)
    batches = list(it)
    assert len(batches) == 2
    assert batches[0].data[0].shape == (5, 4)
    assert (batches[0].data[0].asnumpy() == X[:5]).all()
    assert (batches[0].label[0].asnumpy() == y[:5]).all()
    assert batches[0].pad == 0


def test_ndarray_iter_pad():
    X = np.arange(28).reshape(7, 4).astype(np.float32)
    it = io.NDArrayIter(X, None, batch_size=3, last_batch_handle="pad")
    batches = list(it)
    assert len(batches) == 3
    assert batches[-1].pad == 2
    # padded with wrap-around
    assert (batches[-1].data[0].asnumpy()[1:] == X[:2]).all()


def test_ndarray_iter_discard():
    X = np.arange(28).reshape(7, 4).astype(np.float32)
    it = io.NDArrayIter(X, None, batch_size=3, last_batch_handle="discard")
    assert len(list(it)) == 2


def test_ndarray_iter_reset():
    X = np.arange(12).reshape(6, 2).astype(np.float32)
    it = io.NDArrayIter(X, None, batch_size=3)
    n1 = len(list(it))
    it.reset()
    n2 = len(list(it))
    assert n1 == n2 == 2


def test_provide_data_desc():
    X = np.zeros((8, 3, 4, 4), np.float32)
    y = np.zeros(8, np.float32)
    it = io.NDArrayIter(X, y, batch_size=2)
    assert it.provide_data[0].name == "data"
    assert it.provide_data[0].shape == (2, 3, 4, 4)
    assert it.provide_label[0].name == "softmax_label"
    assert it.provide_label[0].shape == (2,)


def test_resize_iter():
    X = np.zeros((6, 2), np.float32)
    it = io.ResizeIter(io.NDArrayIter(X, None, batch_size=2), size=5)
    assert len(list(it)) == 5


def test_prefetching_iter():
    X = np.arange(24).reshape(12, 2).astype(np.float32)
    y = np.arange(12).astype(np.float32)
    inner = io.NDArrayIter(X, y, batch_size=4)
    it = io.PrefetchingIter(inner)
    batches = list(it)
    assert len(batches) == 3
    assert (batches[0].data[0].asnumpy() == X[:4]).all()
    it.reset()
    assert len(list(it)) == 3


def test_csv_iter(tmp_path):
    data_path = str(tmp_path / "data.csv")
    label_path = str(tmp_path / "label.csv")
    X = np.random.rand(10, 3).astype(np.float32)
    y = np.arange(10).astype(np.float32)
    np.savetxt(data_path, X, delimiter=",")
    np.savetxt(label_path, y, delimiter=",")
    it = io.CSVIter(data_csv=data_path, data_shape=(3,),
                    label_csv=label_path, batch_size=5)
    batches = list(it)
    assert len(batches) == 2
    assert np.allclose(batches[0].data[0].asnumpy(), X[:5], rtol=1e-5)


def test_native_recordio_reader(tmp_path):
    """C++ reader parity with the python writer (src/io/recordio_reader.cc)."""
    from mxnet_tpu import recordio
    path = str(tmp_path / "native.rec")
    w = recordio.MXRecordIO(path, "w")
    payloads = [b"hello", b"x" * 1000, b"", b"tail"]
    for p in payloads:
        w.write(p)
    w.close()

    r = recordio.NativeRecordIOReader(path)
    got = []
    while True:
        rec = r.read()
        if rec is None:
            break
        got.append(rec)
    assert got == payloads
    n = r.build_index()
    assert n == 4
    assert r.read_at(1) == payloads[1]
    assert r.read_at(3) == payloads[3]
    r.close()

    r2 = recordio.NativeRecordIOReader(path, prefetch=True)
    got2 = []
    while True:
        rec = r2.read()
        if rec is None:
            break
        got2.append(rec)
    assert got2 == payloads
    r2.close()


def test_recordio_python_roundtrip(tmp_path):
    from mxnet_tpu import recordio
    path = str(tmp_path / "py.rec")
    idx = str(tmp_path / "py.idx")
    w = recordio.MXIndexedRecordIO(idx, path, "w")
    for i in range(5):
        header = recordio.IRHeader(0, float(i), i, 0)
        w.write_idx(i, recordio.pack(header, b"payload%d" % i))
    w.close()
    r = recordio.MXIndexedRecordIO(idx, path, "r")
    h, payload = recordio.unpack(r.read_idx(3))
    assert h.label == 3.0
    assert payload == b"payload3"


# -- superbatch mode (K-steps-per-dispatch input side) ----------------------

def test_superbatch_iter_stacks_k_batches():
    X = np.arange(96).reshape(24, 4).astype(np.float32)
    y = np.arange(24).astype(np.float32)
    it = io.NDArrayIter(X, y, batch_size=4).superbatch(3, prefetch=False)
    assert it.provide_data[0].shape == (3, 4, 4)
    assert it.provide_label[0].shape == (3, 4)
    sbs = list(it)
    assert len(sbs) == 2
    assert sbs[0].num_steps == 3
    assert sbs[0].data[0].shape == (3, 4, 4)
    np.testing.assert_array_equal(sbs[0].data[0].asnumpy(),
                                  X[:12].reshape(3, 4, 4))
    np.testing.assert_array_equal(sbs[1].label[0].asnumpy(),
                                  y[12:].reshape(3, 4))


def test_superbatch_iter_partial_tail_and_discard():
    X = np.arange(80).reshape(20, 4).astype(np.float32)
    it = io.NDArrayIter(X, None, batch_size=4,
                        last_batch_handle="discard")
    sbs = list(it.superbatch(3, prefetch=False))
    assert [sb.num_steps for sb in sbs] == [3, 2]  # 5 batches -> 3 + tail 2
    per_step = [b.data[0].shape for sb in sbs for b in sb.unstack()]
    assert per_step == [(4, 4)] * 5
    it.reset()
    sbs = list(it.superbatch(3, prefetch=False, last_group_handle="discard"))
    assert [sb.num_steps for sb in sbs] == [3]


def test_superbatch_iter_prefetch_thread_and_reset():
    X = np.arange(192).reshape(48, 4).astype(np.float32)
    y = np.arange(48).astype(np.float32)
    it = io.NDArrayIter(X, y, batch_size=4).superbatch(4)  # threaded
    for _ in range(2):  # two epochs through reset()
        sbs = list(it)
        assert len(sbs) == 3
        np.testing.assert_array_equal(sbs[0].data[0].asnumpy(),
                                      X[:16].reshape(4, 4, 4))
        np.testing.assert_array_equal(sbs[2].label[0].asnumpy(),
                                      y[32:].reshape(4, 4))
        it.reset()


def test_superbatch_unstack_preserves_pads():
    X = np.arange(72).reshape(18, 4).astype(np.float32)
    it = io.NDArrayIter(X, None, batch_size=4)  # last batch pad=2
    sbs = list(it.superbatch(5, prefetch=False))
    assert sbs[0].num_steps == 5
    assert sbs[0].pads == [0, 0, 0, 0, 2]
    assert [b.pad for b in sbs[0].unstack()] == [0, 0, 0, 0, 2]


def test_superbatch_feeds_run_steps():
    """End-to-end: SuperBatchIter output drives TrainStep.run_steps."""
    import jax.numpy as jnp
    from mxnet_tpu.train_step import TrainStep

    rng = np.random.default_rng(0)
    X = rng.normal(size=(32, 10)).astype(np.float32)
    y = rng.integers(0, 4, 32).astype(np.float32)
    net = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=4,
                              name="fc"), name="softmax")
    step = TrainStep(net, optimizer="sgd", learning_rate=0.1)
    state = step.init({"data": (8, 10)}, {"softmax_label": (8,)})
    it = io.NDArrayIter(X, y, batch_size=8).superbatch(2, prefetch=False)
    total = 0
    for sb in it:
        batch = {"data": sb.data[0].data, "softmax_label": sb.label[0].data}
        state, sums = step.run_steps(state, batch)
        total += sums.num_samples
    assert total == 32
    assert int(np.asarray(state["step"])) == 4


def test_superbatch_producer_error_propagates():
    class Boom(io.DataIter):
        def __init__(self):
            super().__init__(4)
            self.n = 0
        @property
        def provide_data(self):
            return [io.DataDesc("data", (4, 2))]
        @property
        def provide_label(self):
            return []
        def next(self):
            self.n += 1
            if self.n > 2:
                raise RuntimeError("corrupt record")
            return io.DataBatch(data=[np.zeros((4, 2), np.float32)],
                                label=[], pad=0)

    it = Boom().superbatch(2)  # threaded
    import pytest as _pytest
    with _pytest.raises(RuntimeError, match="corrupt record"):
        for _ in it:
            pass


def test_superbatch_abandoned_iterator_is_collectable():
    """The producer thread must not hold a strong ref to the iterator: an
    abandoned SuperBatchIter must be GC-able and its thread must exit."""
    import gc
    X = np.zeros((64, 2), np.float32)
    it = io.NDArrayIter(X, None, batch_size=4).superbatch(2)  # threaded
    it.next()  # producer running, queue filling
    th = it._thread
    del it
    gc.collect()
    th.join(timeout=3.0)
    assert not th.is_alive()


def test_superbatch_accepts_legacy_tuple_descs():
    class TupleIter(io.DataIter):
        def __init__(self):
            super().__init__(4)
            self.n = 0
        @property
        def provide_data(self):
            return [("data", (4, 2))]  # legacy descriptor form
        @property
        def provide_label(self):
            return []
        def reset(self):
            self.n = 0
        def next(self):
            if self.n >= 4:
                raise StopIteration
            self.n += 1
            return io.DataBatch(data=[np.full((4, 2), self.n, np.float32)],
                                label=[], pad=0)

    it = TupleIter().superbatch(2, prefetch=False)
    assert it.provide_data[0].shape == (2, 4, 2)
    sbs = list(it)
    assert [sb.num_steps for sb in sbs] == [2, 2]
    np.testing.assert_array_equal(sbs[0].data[0].asnumpy()[:, 0, 0], [1, 2])


# -- MXIndexedRecordIO tell/seek consistency (the sharded reader depends
#    on exact offsets — docs/perf.md "Device-fed input pipeline") ----------

def test_recordio_write_tell_interleaving_exact_offsets(tmp_path):
    """write/tell interleaving: tell() flushes in write mode, so a reader
    opened MID-WRITE sees exact, durable offsets for every record already
    indexed."""
    from mxnet_tpu import recordio
    path = str(tmp_path / "w.rec")
    idx = str(tmp_path / "w.idx")
    w = recordio.MXIndexedRecordIO(idx, path, "w")
    offsets = []
    payloads = []
    for i in range(6):
        offsets.append(w.tell())
        payloads.append(b"x" * (7 + 11 * i))  # deliberately unaligned
        w.write_idx(i, recordio.pack(
            recordio.IRHeader(0, float(i), i, 0), payloads[i]))
        # mid-write read-back through an independent handle at the
        # recorded offset: tell()'s flush makes the bytes durable NOW
        assert w.tell() > offsets[i]
        rr = recordio.MXRecordIO(path, "r")
        rr.handle.seek(offsets[i])
        h, p = recordio.unpack(rr.read())
        assert (h.label, p) == (float(i), payloads[i])
        rr.close()
    assert offsets == sorted(set(offsets)), "offsets must be increasing"
    w.close()
    r = recordio.MXIndexedRecordIO(idx, path, "r")
    assert [r.idx[k] for k in r.keys] == offsets


def test_recordio_read_idx_interleaved_with_sequential_read(tmp_path):
    from mxnet_tpu import recordio
    path = str(tmp_path / "s.rec")
    idx = str(tmp_path / "s.idx")
    w = recordio.MXIndexedRecordIO(idx, path, "w")
    for i in range(8):
        w.write_idx(i, recordio.pack(
            recordio.IRHeader(0, float(i), i, 0), b"p%d" % i))
    w.close()
    r = recordio.MXIndexedRecordIO(idx, path, "r")
    # random-access seeks in arbitrary order...
    for key in (5, 0, 7, 2, 2, 6):
        h, p = recordio.unpack(r.read_idx(key))
        assert (h.label, p) == (float(key), b"p%d" % key)
    # ...and sequential read() continues from AFTER the last read_idx
    # (the handle lands on the next record boundary, never mid-record)
    h, p = recordio.unpack(r.read())
    assert (h.label, p) == (7.0, b"p7")
    r.seek(3)
    assert r.tell() == r.idx[3]
    h, p = recordio.unpack(r.read())
    assert h.label == 3.0


def test_recordio_partial_read_restores_position(tmp_path):
    """A failed read (truncated record) must leave the handle at the
    record START: tell() stays meaningful, a later read_idx of a good key
    works, and re-reading the bad offset fails identically instead of
    parsing garbage."""
    import pytest
    from mxnet_tpu import recordio
    from mxnet_tpu.base import MXNetError
    path = str(tmp_path / "t.rec")
    idx = str(tmp_path / "t.idx")
    w = recordio.MXIndexedRecordIO(idx, path, "w")
    for i in range(4):
        w.write_idx(i, recordio.pack(
            recordio.IRHeader(0, float(i), i, 0), b"y" * 64))
    w.close()
    # tear the LAST record's payload
    import os
    with open(path, "r+b") as f:
        f.truncate(os.path.getsize(path) - 30)
    r = recordio.MXIndexedRecordIO(idx, path, "r")
    with pytest.raises(MXNetError, match="truncated"):
        r.read_idx(3)
    assert r.tell() == r.idx[3], "position must restore to record start"
    # earlier keys still read exactly after the failure...
    h, p = recordio.unpack(r.read_idx(1))
    assert (h.label, p) == (1.0, b"y" * 64)
    # ...and the bad record fails the SAME way again (no garbage parse)
    with pytest.raises(MXNetError, match="truncated"):
        r.read_idx(3)
    with pytest.raises(MXNetError, match="truncated"):
        r.read_idx(3)


def test_recordio_bad_magic_read_restores_position(tmp_path):
    import pytest
    from mxnet_tpu import recordio
    from mxnet_tpu.base import MXNetError
    path = str(tmp_path / "m.rec")
    idx = str(tmp_path / "m.idx")
    w = recordio.MXIndexedRecordIO(idx, path, "w")
    for i in range(3):
        w.write_idx(i, recordio.pack(
            recordio.IRHeader(0, float(i), i, 0), b"z" * 20))
    w.close()
    r = recordio.MXIndexedRecordIO(idx, path, "r")
    off1 = r.idx[1]
    with open(path, "r+b") as f:  # corrupt record 1's magic
        f.seek(off1)
        f.write(b"\xde\xad\xbe\xef")
    with pytest.raises(MXNetError, match="magic"):
        r.read_idx(1)
    assert r.tell() == off1
    h, p = recordio.unpack(r.read_idx(2))  # neighbors unaffected
    assert h.label == 2.0
