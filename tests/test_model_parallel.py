"""group2ctx model parallelism on the virtual 8-device mesh.

Covers the SPMD lowering of the reference's PlaceDevice model parallelism
(ref: src/executor/graph_executor.cc:244-334,
example/model-parallel-lstm/lstm.py:48-112): ctx_group annotations become
mesh sharding constraints, grouped parameters allocate sharded, and the
numerics are IDENTICAL to the single-device run (sharding preserves values).
Also covers the GPipe-style scan+ppermute pipeline over the 'pipe' axis.
"""
import os
import sys

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import mxnet_tpu as mx
from mxnet_tpu.parallel import make_mesh, MeshScope, pipeline_apply
from mxnet_tpu.parallel.placement import resolve, param_groups
from mxnet_tpu.symbol import _topo
from mxnet_tpu.train_step import TrainStep

P = jax.sharding.PartitionSpec


def _two_group_mlp():
    """Front half in group 'dev1', classifier in group 'dev2' — the shape of
    the reference's model-parallel examples."""
    data = mx.Variable("data")
    with mx.AttrScope(ctx_group="dev1"):
        h = mx.sym.FullyConnected(data, name="fc1", num_hidden=64)
        h = mx.sym.Activation(h, name="relu1", act_type="relu")
    with mx.AttrScope(ctx_group="dev2"):
        h = mx.sym.FullyConnected(h, name="fc2", num_hidden=32)
        out = mx.sym.SoftmaxOutput(h, name="softmax")
    return out


def test_param_groups_propagate():
    sym = _two_group_mlp()
    groups = param_groups(_topo(sym._out_nodes()))
    assert groups["fc1_weight"] == "dev1"
    assert groups["fc1_bias"] == "dev1"
    assert groups["fc2_weight"] == "dev2"
    # data feeds only dev1 nodes, so it inherits dev1 (harmless: constraint
    # fits shape or is skipped)
    assert groups.get("data") == "dev1"


def test_group2ctx_numerics_match_single_device():
    sym = _two_group_mlp()
    np.random.seed(0)
    x = np.random.randn(16, 48).astype(np.float32)
    y = np.random.randint(0, 32, (16,)).astype(np.float32)

    # single-device reference run
    exe0 = sym.simple_bind(mx.cpu(), data=(16, 48), softmax_label=(16,))
    rng = np.random.RandomState(1)
    params = {n: rng.randn(*a.shape).astype(np.float32) * 0.1
              for n, a in exe0.arg_dict.items()
              if n not in ("data", "softmax_label")}
    for n, v in params.items():
        exe0.arg_dict[n][:] = v
    exe0.forward(is_train=False, data=x, softmax_label=y)
    ref = exe0.outputs[0].asnumpy()

    # model-parallel run: groups spread over the 8-device mesh
    mesh = make_mesh({"model": 8})
    with MeshScope(mesh):
        exe1 = sym.simple_bind(mx.cpu(), data=(16, 48), softmax_label=(16,),
                               group2ctx={"dev1": "model", "dev2": "model"})
    for n, v in params.items():
        exe1.arg_dict[n][:] = v
    exe1.forward(is_train=False, data=x, softmax_label=y)
    out = exe1.outputs[0].asnumpy()
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)

    # grouped params actually allocated sharded across the mesh
    w = exe1.arg_dict["fc1_weight"].data
    assert len(w.sharding.device_set) == 8


def test_group2ctx_backward_matches():
    sym = _two_group_mlp()
    np.random.seed(2)
    x = np.random.randn(8, 48).astype(np.float32)
    y = np.random.randint(0, 32, (8,)).astype(np.float32)
    mesh = make_mesh({"model": 8})

    grads = {}
    for tag, g2c in (("ref", None),
                     ("mp", {"dev1": "model", "dev2": P(None, "model")})):
        with MeshScope(mesh):
            exe = sym.simple_bind(mx.cpu(), data=(8, 48), softmax_label=(8,),
                                  grad_req="write", group2ctx=g2c)
        rng = np.random.RandomState(3)
        for n in exe.arg_dict:
            if n not in ("data", "softmax_label"):
                exe.arg_dict[n][:] = rng.randn(
                    *exe.arg_dict[n].shape).astype(np.float32) * 0.1
        exe.forward(is_train=True, data=x, softmax_label=y)
        exe.backward()
        grads[tag] = {n: exe.grad_dict[n].asnumpy()
                      for n in ("fc1_weight", "fc2_weight")}
    for n in grads["ref"]:
        np.testing.assert_allclose(grads["mp"][n], grads["ref"][n],
                                   rtol=1e-4, atol=1e-5)


def test_legacy_context_group2ctx_accepted():
    """Reference-style group2ctx={'dev1': mx.cpu(0)} still binds and runs."""
    sym = _two_group_mlp()
    exe = sym.simple_bind(mx.cpu(), data=(4, 48), softmax_label=(4,),
                          group2ctx={"dev1": mx.cpu(0), "dev2": mx.cpu(1)})
    exe.forward(is_train=False,
                data=np.zeros((4, 48), np.float32),
                softmax_label=np.zeros((4,), np.float32))
    assert exe.outputs[0].shape == (4, 32)


def test_trainstep_group2ctx_trains():
    """Fused TrainStep with group2ctx: grouped params shard automatically,
    loss falls, numerics track the unsharded step."""
    sym = _two_group_mlp()
    np.random.seed(4)
    x = np.random.randn(32, 48).astype(np.float32)
    y = (x[:, 0] > 0).astype(np.float32)  # learnable toy labels

    mesh = make_mesh({"model": 8})
    step_mp = TrainStep(sym, optimizer="sgd", learning_rate=0.1, momentum=0.0,
                        mesh=mesh, group2ctx={"dev1": "model",
                                              "dev2": "model"})
    step_ref = TrainStep(sym, optimizer="sgd", learning_rate=0.1, momentum=0.0)
    s_mp = step_mp.init({"data": (32, 48)}, {"softmax_label": (32,)}, seed=7)
    s_ref = step_ref.init({"data": (32, 48)}, {"softmax_label": (32,)}, seed=7)

    # auto-sharding from the group annotation (fc1_weight is (64, 48):
    # dim0 divisible by 8)
    assert len(s_mp["params"]["fc1_weight"].sharding.device_set) == 8

    batch = {"data": x, "softmax_label": y}
    for _ in range(5):
        s_mp, _ = step_mp.step(s_mp, batch)
        s_ref, _ = step_ref.step(s_ref, batch)
    for n in s_ref["params"]:
        np.testing.assert_allclose(np.asarray(s_mp["params"][n]),
                                   np.asarray(s_ref["params"][n]),
                                   rtol=1e-4, atol=1e-5)


def test_pipeline_matches_serial():
    """GPipe scan+ppermute over 'pipe' == serial stage-by-stage execution."""
    mesh = make_mesh({"pipe": 8})
    S, B, D = 8, 16, 32
    rng = np.random.RandomState(5)
    Ws = jnp.asarray(rng.randn(S, D, D).astype(np.float32) * 0.3)
    bs = jnp.asarray(rng.randn(S, D).astype(np.float32) * 0.1)
    x = jnp.asarray(rng.randn(B, D).astype(np.float32))

    def stage(params, h):
        W, b = params
        return jnp.tanh(h @ W + b)

    out = pipeline_apply(stage, (Ws, bs), x, mesh, num_microbatches=4)

    ref = x
    for s in range(S):
        ref = stage((Ws[s], bs[s]), ref)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_pipeline_differentiable():
    mesh = make_mesh({"pipe": 8})
    S, B, D = 8, 8, 16
    rng = np.random.RandomState(6)
    Ws = jnp.asarray(rng.randn(S, D, D).astype(np.float32) * 0.3)
    x = jnp.asarray(rng.randn(B, D).astype(np.float32))

    def stage(W, h):
        return jnp.tanh(h @ W)

    def loss(Ws):
        out = pipeline_apply(stage, Ws, x, mesh, num_microbatches=2)
        return jnp.sum(out ** 2)

    def loss_ref(Ws):
        h = x
        for s in range(S):
            h = stage(Ws[s], h)
        return jnp.sum(h ** 2)

    g = jax.grad(loss)(Ws)
    g_ref = jax.grad(loss_ref)(Ws)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref),
                               rtol=1e-4, atol=1e-4)


def test_model_parallel_lstm_example_runs():
    """The reference config-5 example, end to end under assertion."""
    import subprocess
    env = dict(os.environ)
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=8")
    env["JAX_PLATFORMS"] = "cpu"
    script = os.path.join(os.path.dirname(__file__), "..", "example",
                          "model-parallel-lstm", "lstm.py")
    r = subprocess.run(
        [sys.executable, script, "--check", "--num-layers", "2",
         "--steps", "60"],
        env=env, capture_output=True, text=True, timeout=900)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "check ok" in r.stdout


def test_group2ctx_bad_axis_raises_clearly():
    sym = _two_group_mlp()
    from mxnet_tpu.base import MXNetError
    with MeshScope(make_mesh({"data": 8})):
        with pytest.raises(MXNetError, match="model.*not in mesh"):
            sym.simple_bind(mx.cpu(), data=(4, 48), softmax_label=(4,),
                            group2ctx={"dev1": "model"})


def test_group2ctx_no_mesh_raises_clearly():
    sym = _two_group_mlp()
    from mxnet_tpu.base import MXNetError
    with pytest.raises(MXNetError, match="needs a device mesh"):
        TrainStep(sym, group2ctx={"dev1": "model"})


def test_group2ctx_conflicting_meshes_rejected():
    """One jit = one mesh: a NamedSharding over a different mesh than the
    binding mesh must fail loudly at bind, not deep inside tracing."""
    sym = _two_group_mlp()
    from mxnet_tpu.base import MXNetError
    model_mesh = make_mesh({"model": 8})
    data_mesh = make_mesh({"data": 8})
    ns = jax.sharding.NamedSharding(data_mesh, P("data"))
    with MeshScope(model_mesh):
        with pytest.raises(MXNetError, match="share one mesh"):
            sym.simple_bind(mx.cpu(), data=(16, 48), softmax_label=(16,),
                            group2ctx={"dev1": "model", "dev2": ns})


def test_group2ctx_namedsharding_sets_mesh():
    """With no ambient mesh, NamedSharding values supply the mesh."""
    sym = _two_group_mlp()
    mesh = make_mesh({"model": 8})
    ns = jax.sharding.NamedSharding(mesh, P("model"))
    exe = sym.simple_bind(mx.cpu(), data=(16, 48), softmax_label=(16,),
                          group2ctx={"dev1": ns, "dev2": ns})
    exe.forward(is_train=False, data=np.zeros((16, 48), np.float32),
                softmax_label=np.zeros((16,), np.float32))
    assert exe.outputs[0].shape == (16, 32)
    assert len(exe.arg_dict["fc1_weight"].data.sharding.device_set) == 8
