"""Test configuration: run the suite on a virtual 8-device CPU mesh.

Mirrors the reference's multi-device-without-hardware strategy (SURVEY.md §4:
cpu(0)/cpu(1) contexts, faked device lists) using
--xla_force_host_platform_device_count=8. The axon sitecustomize pins
JAX_PLATFORMS=axon, so the platform is forced back to cpu via jax.config
before any device is touched.
"""
import os
import time

os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")
os.environ["JAX_PLATFORMS"] = "cpu"

# flight-recorder post-mortems (docs/observability.md) default to the
# CWD in production; a test run triggers dozens of deliberate failure
# paths and must not litter the repo root with mxtpu_flight.json
if "MXTPU_FLIGHT_RECORDER_PATH" not in os.environ:
    import tempfile
    os.environ["MXTPU_FLIGHT_RECORDER_PATH"] = os.path.join(
        tempfile.mkdtemp(prefix="mxtpu_flight_"), "mxtpu_flight.json")

import jax  # noqa: E402
import pytest  # noqa: E402

jax.config.update("jax_platforms", "cpu")


# -- tier-1 wall-clock budget (docs/perf.md "Host off the critical path") ---
#
# The tier-1 suite runs under a hard 870s timeout (ROADMAP.md) and has
# already crept into it once. The pipelined-dispatch / async-checkpoint
# tests are contractually SLEEP-FREE (event-paced, fault-injected — never
# time.sleep waits); a regression that reintroduces real waiting fails at
# the offending test instead of silently re-inflating the suite.

_PIPELINE_TEST_CAP = float(os.environ.get("MXTPU_PIPELINE_TEST_CAP", "90"))
_T1_BUDGET = float(os.environ.get("MXTPU_T1_BUDGET", "870"))


@pytest.fixture(autouse=True)
def _tracecheck_transfer_guard(request):
    """``tracecheck``-marked tests run under ``jax.transfer_guard
    ("disallow")`` (docs/static_analysis.md "Transfer-guard interplay"):
    the runtime complement of the static host-sync lint. Explicit
    transfers (``jnp.asarray``, ``device_put``, the packed StepMetrics
    readback) stay legal; an IMPLICIT transfer inside the fused-dispatch
    hot loop — a numpy array leaking into a jit call, a Python scalar
    index forcing an H2D — raises immediately, naming the callsite,
    instead of silently serializing every dispatch."""
    if request.node.get_closest_marker("tracecheck") is None:
        yield
        return
    with jax.transfer_guard("disallow"):
        yield


@pytest.fixture(autouse=True)
def _pipeline_wall_clock_cap(request):
    """Per-test wall-clock ceiling for ``pipeline``-marked tests."""
    if request.node.get_closest_marker("pipeline") is None:
        yield
        return
    t0 = time.perf_counter()
    yield
    dt = time.perf_counter() - t0
    if dt >= _PIPELINE_TEST_CAP:
        pytest.fail(
            "pipeline-marked test took %.1fs (cap %.0fs, "
            "MXTPU_PIPELINE_TEST_CAP): these tests are contractually "
            "sleep-free — something is waiting on wall-clock instead of "
            "an event/fault hook" % (dt, _PIPELINE_TEST_CAP),
            pytrace=False)


def pytest_sessionstart(session):
    session.config._mxtpu_wall_t0 = time.time()


def pytest_terminal_summary(terminalreporter, exitstatus, config):
    t0 = getattr(config, "_mxtpu_wall_t0", None)
    if t0 is None:
        return
    wall = time.time() - t0
    line = ("tier-1 wall clock: %.1fs of the %ds budget (%.0f%%)"
            % (wall, int(_T1_BUDGET), 100.0 * wall / _T1_BUDGET))
    if wall > 0.9 * _T1_BUDGET:
        line += " — WARNING: within 10% of the timeout, trim before adding"
    terminalreporter.write_line(line)
