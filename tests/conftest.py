"""Test configuration: run the suite on a virtual 8-device CPU mesh.

Mirrors the reference's multi-device-without-hardware strategy (SURVEY.md §4:
cpu(0)/cpu(1) contexts, faked device lists) using
--xla_force_host_platform_device_count=8. The axon sitecustomize pins
JAX_PLATFORMS=axon, so the platform is forced back to cpu via jax.config
before any device is touched.
"""
import os

os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
