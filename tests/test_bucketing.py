"""BucketingModule end-to-end (ref config 3: example/rnn/lstm_bucketing.py
behavior — variable-length LSTM LM with per-bucket shared-parameter bind)."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import sym
from mxnet_tpu.rnn import LSTMCell, BucketSentenceIter
from mxnet_tpu.module import BucketingModule


def _make_sym_gen(num_hidden, vocab_size, num_embed):
    cell = LSTMCell(num_hidden=num_hidden, prefix="lstm_")

    def sym_gen(seq_len):
        data = sym.Variable("data")
        label = sym.Variable("softmax_label")
        embed = sym.Embedding(data=data, input_dim=vocab_size,
                              output_dim=num_embed, name="embed")
        cell.reset()
        outputs, states = cell.unroll(seq_len, inputs=embed, layout="NTC",
                                      merge_outputs=True)
        pred = sym.Reshape(data=outputs, shape=(-1, num_hidden))
        pred = sym.FullyConnected(data=pred, num_hidden=vocab_size,
                                  name="pred")
        label_flat = sym.Reshape(data=label, shape=(-1,))
        pred = sym.SoftmaxOutput(data=pred, label=label_flat, name="softmax")
        return pred, ("data",), ("softmax_label",)

    return cell, sym_gen


def test_bucketing_module_trains():
    vocab_size, num_embed, num_hidden = 16, 8, 12
    batch = 4
    rng = np.random.default_rng(0)
    # synthetic "language": next token = (token + 1) % vocab (fully learnable)
    sentences = []
    for _ in range(120):
        length = int(rng.choice([4, 7]))
        start = int(rng.integers(1, vocab_size - 1))
        sentences.append([(start + t) % (vocab_size - 1) + 1
                          for t in range(length)])
    it = BucketSentenceIter(sentences, batch, buckets=[4, 7],
                            invalid_label=0, layout="NT")

    cell, sym_gen = _make_sym_gen(num_hidden, vocab_size, num_embed)

    class StatefulIter:
        """Wrap the bucket iter to append zero begin-states per batch."""
        def __init__(self, inner):
            self.inner = inner
            self.batch_size = inner.batch_size
            self.default_bucket_key = inner.default_bucket_key

        @property
        def provide_data(self):
            return list(self.inner.provide_data) + [
                ("lstm_begin_state_0", (batch, num_hidden)),
                ("lstm_begin_state_1", (batch, num_hidden))]

        @property
        def provide_label(self):
            return self.inner.provide_label

        def reset(self):
            self.inner.reset()

        def __iter__(self):
            return self

        def __next__(self):
            b = next(self.inner)
            b.data = list(b.data) + [mx.nd.zeros((batch, num_hidden)),
                                     mx.nd.zeros((batch, num_hidden))]
            b.provide_data = list(b.provide_data) + [
                ("lstm_begin_state_0", (batch, num_hidden)),
                ("lstm_begin_state_1", (batch, num_hidden))]
            return b

        def next(self):
            return self.__next__()

    it2 = StatefulIter(it)
    mod = BucketingModule(
        lambda key: (sym_gen(key)[0],
                     ("data", "lstm_begin_state_0", "lstm_begin_state_1"),
                     ("softmax_label",)),
        default_bucket_key=it.default_bucket_key, context=mx.cpu())
    mod.bind(data_shapes=it2.provide_data, label_shapes=it2.provide_label)
    mod.init_params(initializer=mx.initializer.Xavier())
    mod.init_optimizer(optimizer="adam",
                       optimizer_params={"learning_rate": 0.01})
    metric = mx.metric.Perplexity(ignore_label=0)
    for epoch in range(10):
        it2.reset()
        metric.reset()
        for b in it2:
            mod.forward(b, is_train=True)
            mod.backward()
            mod.update()
            mod.update_metric(metric, b.label)
    name, ppl = metric.get()
    # vocab 16 => random ppl ~15; the pattern is deterministic so it should
    # drop well below that
    assert ppl < 8.0, ppl
    # both buckets were bound and share parameters
    assert len(mod._buckets) == 2
    p4 = mod._buckets[4]._exec_group.executor.arg_dict["pred_weight"]
    p7 = mod._buckets[7]._exec_group.executor.arg_dict["pred_weight"]
    assert p4 is p7  # shared parameter arrays across buckets


# ---------------------------------------------------------------------------
# r5 depth (VERDICT r4 weak #4): jit-cache reuse, mid-epoch switching
# correctness, unseen buckets, and a gated run of the PTB-style example
# (ref: tests/python/unittest/test_module.py bucketing cases,
# example/rnn/lstm_bucketing.py)
# ---------------------------------------------------------------------------

def _fc_sym_gen(key):
    """Bucketed bag-of-tokens net: bucket key = sequence length; all weight
    shapes are length-independent so every bucket shares them (like the
    reference's unrolled RNN buckets)."""
    data = sym.Variable("data")
    emb = sym.Embedding(data=data, input_dim=16, output_dim=8,
                        name="shared_embed")          # (B, key, 8)
    feat = sym.sum(emb, axis=1)                        # (B, 8)
    pred = sym.FullyConnected(data=feat, num_hidden=8, name="shared_fc")
    pred = sym.SoftmaxOutput(data=pred, name="softmax")
    return pred, ("data",), ("softmax_label",)


def _batch(key, batch=6, seed=0):
    rng = np.random.default_rng(seed + key)
    b = mx.io.DataBatch(
        data=[mx.nd.array(rng.integers(0, 16, (batch, key))
                          .astype(np.float32))],
        label=[mx.nd.array(rng.integers(0, 8, batch).astype(np.float32))])
    b.bucket_key = key
    b.provide_data = [("data", (batch, key))]
    b.provide_label = [("softmax_label", (batch,))]
    return b


def _bound_bucketing_module(default_key=10):
    mod = BucketingModule(_fc_sym_gen, default_bucket_key=default_key,
                          context=mx.cpu())
    mod.bind(data_shapes=[("data", (6, default_key))],
             label_shapes=[("softmax_label", (6,))])
    mod.init_params(initializer=mx.initializer.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1})
    return mod


def test_bucket_bind_and_jit_cache_reuse():
    """Revisiting a bucket must reuse its bound module (no rebind) and its
    executor's jit cache (no regrowth) — the per-bucket compile-once
    contract the reference gets from shared_exec memory reuse
    (ref: BucketingModule.switch_bucket, bucketing_module.py:39;
    graph_executor.cc:352-355 shared-pool path)."""
    from mxnet_tpu.executor import Executor
    binds = []
    orig_init = Executor.__init__

    def counting_init(self, *a, **k):
        binds.append(1)
        return orig_init(self, *a, **k)

    Executor.__init__ = counting_init
    try:
        mod = _bound_bucketing_module(10)
        # interleave buckets: 10,6,10,6,10 — only TWO binds may ever happen
        for key in (10, 6, 10, 6, 10):
            b = _batch(key)
            mod.forward(b, is_train=True)
            mod.backward()
            mod.update()
        assert sum(binds) == 2, "expected 2 executor binds, saw %d" % \
            sum(binds)
    finally:
        Executor.__init__ = orig_init
    # module identity: switching back returns the SAME bound module
    m10_a = mod._buckets[10]
    mod.forward(_batch(6), is_train=True)
    mod.forward(_batch(10), is_train=True)
    assert mod._buckets[10] is m10_a
    assert mod._curr_module is m10_a
    # jit caches did not regrow on revisit
    ex = m10_a._exec_group.executor
    n_cached = len(ex._jit_fused) + len(ex._jit_fwd)
    mod.forward(_batch(10), is_train=True)
    mod.backward()
    mod.update()
    assert len(ex._jit_fused) + len(ex._jit_fwd) == n_cached, \
        "revisiting a bucket recompiled"


def test_bucket_switch_mid_epoch_matches_plain_module():
    """After interleaved training, each bucket's forward must equal a plain
    Module bound at that shape with the same parameters — bucket switching
    corrupts nothing (ref: test_module.py test_module_switch_bucket)."""
    mod = _bound_bucketing_module(10)
    for step in range(6):
        key = (10, 6)[step % 2]
        b = _batch(key, seed=step)
        mod.forward(b, is_train=True)
        mod.backward()
        mod.update()
    arg_params, aux_params = mod.get_params()
    for key in (10, 6):
        b = _batch(key, seed=99)
        mod.forward(b, is_train=False)
        out_bucketed = mod.get_outputs()[0].asnumpy()
        plain = mx.mod.Module(_fc_sym_gen(key)[0], context=mx.cpu())
        plain.bind(data_shapes=b.provide_data,
                   label_shapes=b.provide_label, for_training=False)
        plain.set_params(arg_params, aux_params)
        plain.forward(b, is_train=False)
        np.testing.assert_allclose(out_bucketed,
                                   plain.get_outputs()[0].asnumpy(),
                                   rtol=1e-5, atol=1e-6)


def test_unseen_bucket_key_binds_on_demand_with_shared_params():
    """A bucket key first seen mid-epoch binds on demand, shares parameter
    arrays with the default bucket, and trains (ref: switch_bucket's
    shared_module path)."""
    mod = _bound_bucketing_module(10)
    mod.forward(_batch(10), is_train=True)
    mod.backward()
    mod.update()
    assert 7 not in mod._buckets
    b7 = _batch(7)
    mod.forward(b7, is_train=True)     # unseen: must bind on the fly
    mod.backward()
    mod.update()
    assert 7 in mod._buckets
    w_def = mod._buckets[10]._exec_group.executor.arg_dict["shared_fc_weight"]
    w_new = mod._buckets[7]._exec_group.executor.arg_dict["shared_fc_weight"]
    assert w_def is w_new, "new bucket did not share parameter arrays"
    assert mod.get_outputs()[0].shape == (6, 8)


def test_lstm_bucketing_example_perplexity_gate():
    """The PTB-style example trains under a perplexity gate on synthetic
    text (ref: example/rnn/lstm_bucketing.py driven by the nightly
    check_val pattern)."""
    import os
    import subprocess
    import sys
    root = os.path.join(os.path.dirname(__file__), "..")
    script = os.path.join(root, "example", "rnn", "lstm_bucketing.py")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    r = subprocess.run(
        [sys.executable, script, "--synthetic", "--num-hidden", "32",
         "--num-embed", "32", "--num-layers", "1", "--batch-size", "16",
         "--buckets", "6,10", "--num-epochs", "3", "--lr", "0.02",
         "--ppl-gate", "10"],
        capture_output=True, text=True, timeout=900, env=env)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "PPL PASS" in r.stdout, r.stdout + r.stderr
