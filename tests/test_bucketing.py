"""BucketingModule end-to-end (ref config 3: example/rnn/lstm_bucketing.py
behavior — variable-length LSTM LM with per-bucket shared-parameter bind)."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import sym
from mxnet_tpu.rnn import LSTMCell, BucketSentenceIter
from mxnet_tpu.module import BucketingModule


def _make_sym_gen(num_hidden, vocab_size, num_embed):
    cell = LSTMCell(num_hidden=num_hidden, prefix="lstm_")

    def sym_gen(seq_len):
        data = sym.Variable("data")
        label = sym.Variable("softmax_label")
        embed = sym.Embedding(data=data, input_dim=vocab_size,
                              output_dim=num_embed, name="embed")
        cell.reset()
        outputs, states = cell.unroll(seq_len, inputs=embed, layout="NTC",
                                      merge_outputs=True)
        pred = sym.Reshape(data=outputs, shape=(-1, num_hidden))
        pred = sym.FullyConnected(data=pred, num_hidden=vocab_size,
                                  name="pred")
        label_flat = sym.Reshape(data=label, shape=(-1,))
        pred = sym.SoftmaxOutput(data=pred, label=label_flat, name="softmax")
        return pred, ("data",), ("softmax_label",)

    return cell, sym_gen


def test_bucketing_module_trains():
    vocab_size, num_embed, num_hidden = 16, 8, 12
    batch = 4
    rng = np.random.default_rng(0)
    # synthetic "language": next token = (token + 1) % vocab (fully learnable)
    sentences = []
    for _ in range(120):
        length = int(rng.choice([4, 7]))
        start = int(rng.integers(1, vocab_size - 1))
        sentences.append([(start + t) % (vocab_size - 1) + 1
                          for t in range(length)])
    it = BucketSentenceIter(sentences, batch, buckets=[4, 7],
                            invalid_label=0, layout="NT")

    cell, sym_gen = _make_sym_gen(num_hidden, vocab_size, num_embed)

    class StatefulIter:
        """Wrap the bucket iter to append zero begin-states per batch."""
        def __init__(self, inner):
            self.inner = inner
            self.batch_size = inner.batch_size
            self.default_bucket_key = inner.default_bucket_key

        @property
        def provide_data(self):
            return list(self.inner.provide_data) + [
                ("lstm_begin_state_0", (batch, num_hidden)),
                ("lstm_begin_state_1", (batch, num_hidden))]

        @property
        def provide_label(self):
            return self.inner.provide_label

        def reset(self):
            self.inner.reset()

        def __iter__(self):
            return self

        def __next__(self):
            b = next(self.inner)
            b.data = list(b.data) + [mx.nd.zeros((batch, num_hidden)),
                                     mx.nd.zeros((batch, num_hidden))]
            b.provide_data = list(b.provide_data) + [
                ("lstm_begin_state_0", (batch, num_hidden)),
                ("lstm_begin_state_1", (batch, num_hidden))]
            return b

        def next(self):
            return self.__next__()

    it2 = StatefulIter(it)
    mod = BucketingModule(
        lambda key: (sym_gen(key)[0],
                     ("data", "lstm_begin_state_0", "lstm_begin_state_1"),
                     ("softmax_label",)),
        default_bucket_key=it.default_bucket_key, context=mx.cpu())
    mod.bind(data_shapes=it2.provide_data, label_shapes=it2.provide_label)
    mod.init_params(initializer=mx.initializer.Xavier())
    mod.init_optimizer(optimizer="adam",
                       optimizer_params={"learning_rate": 0.01})
    metric = mx.metric.Perplexity(ignore_label=0)
    for epoch in range(10):
        it2.reset()
        metric.reset()
        for b in it2:
            mod.forward(b, is_train=True)
            mod.backward()
            mod.update()
            mod.update_metric(metric, b.label)
    name, ppl = metric.get()
    # vocab 16 => random ppl ~15; the pattern is deterministic so it should
    # drop well below that
    assert ppl < 8.0, ppl
    # both buckets were bound and share parameters
    assert len(mod._buckets) == 2
    p4 = mod._buckets[4]._exec_group.executor.arg_dict["pred_weight"]
    p7 = mod._buckets[7]._exec_group.executor.arg_dict["pred_weight"]
    assert p4 is p7  # shared parameter arrays across buckets
