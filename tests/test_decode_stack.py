"""Production decode path tests (docs/serving.md "Production decode
path"): in-graph sampling, quantized weights, prefix-cache reuse,
speculative decoding.

The load-bearing assertions:

* ``temperature=0`` through the sampled body is BITWISE the greedy path
  (token-for-token against full re-forward through the AOT engine);
* a fixed seed reproduces the exact token stream regardless of which
  co-riders share the batch or how slots churn — per-(seed, position)
  randomness, not per-dispatch;
* int8 quantization cuts resident weight bytes by >= 40% with the
  quality gate green, and a sharded quantized engine holds 1/N of the
  quantized bytes per chip (scale sharded beside its weight);
* prefix-cache hits produce the IDENTICAL stream a cold prefill would
  (reuse changes where decoding starts, never what it computes);
* speculative decode output is token-identical to target-only sampling
  under the same seeds — with a perfect draft (100%-ish acceptance) AND
  with a deliberately weak one;
* the ``serve.sample`` / ``serve.spec_verify`` fault sites shed every
  in-flight sequence with a clear error, never a hang.
"""
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import mxnet_tpu as mx  # noqa: E402,F401
from mxnet_tpu import faults, models, serving  # noqa: E402
from mxnet_tpu.base import MXNetError  # noqa: E402
from mxnet_tpu.serving.quantize import check_quality  # noqa: E402


# ---------------------------------------------------------------------------
# fixtures
# ---------------------------------------------------------------------------

_LM = dict(vocab_size=17, embed=16, num_heads=2, num_layers=2, seq_len=12)


def _lm_params(seed=3, num_layers=None):
    cfg = dict(_LM)
    if num_layers is not None:
        cfg["num_layers"] = num_layers
    sym = models.transformer(**cfg)
    s = cfg["seq_len"]
    arg_shapes, _, _ = sym.infer_shape(data=(1, s), softmax_label=(1, s))
    rs = np.random.RandomState(seed)
    return {n: (rs.randn(*shp) * 0.3).astype(np.float32)
            for n, shp in zip(sym.list_arguments(), arg_shapes)
            if n not in ("data", "softmax_label")}


def _loop(params=None, **kw):
    kw.setdefault("slots", 2)
    return serving.DecodeLoop(params if params is not None else _lm_params(),
                              num_layers=_LM["num_layers"],
                              num_heads=_LM["num_heads"],
                              max_len=_LM["seq_len"], **kw)


def _gen(loop, prompt, n, **kw):
    return loop.generate(prompt, n, **kw).result(timeout=120.0)


# ---------------------------------------------------------------------------
# in-graph sampling
# ---------------------------------------------------------------------------

def test_temperature_zero_is_bitwise_greedy():
    """temp=0 rows must take the argmax value chain (no scaling, no
    sort): identical tokens to the default-greedy generate call."""
    params = _lm_params()
    loop = _loop(params, prefix_cache=False)
    try:
        prompts = [[1, 2, 3], [4, 5], [6, 7, 1]]
        greedy = [_gen(loop, p, 5) for p in prompts]
        explicit = [_gen(loop, p, 5, temperature=0.0, top_k=3, top_p=0.5,
                         seed=99) for p in prompts]
        assert greedy == explicit
    finally:
        loop.close()


def test_fixed_seed_reproduces_stream_across_loops():
    params = _lm_params()
    outs = []
    for _ in range(2):
        loop = _loop(params, prefix_cache=False)
        try:
            outs.append(_gen(loop, [1, 2, 3], 6, temperature=0.9,
                             top_k=8, top_p=0.9, seed=42))
        finally:
            loop.close()
    assert outs[0] == outs[1]
    assert len(outs[0]) == 6


def test_sampled_stream_immune_to_corider_churn():
    """Per-(seed, position) randomness: the SAME request draws the SAME
    tokens whether it runs alone or with co-riders joining and retiring
    around it mid-stream."""
    params = _lm_params()
    loop = _loop(params, prefix_cache=False)
    try:
        alone = _gen(loop, [1, 2, 3], 8, temperature=0.8, seed=7)
        # now the same request with churn: short co-riders retire and new
        # ones join while it decodes
        fut = loop.generate([1, 2, 3], 8, temperature=0.8, seed=7)
        riders = [loop.generate([i + 1], 2, temperature=1.2, seed=i)
                  for i in range(4)]
        crowded = fut.result(timeout=120.0)
        for r in riders:
            r.result(timeout=120.0)
        assert crowded == alone
    finally:
        loop.close()


def test_sampling_validation_rejects_nonsense():
    loop = _loop(prefix_cache=False)
    try:
        with pytest.raises(MXNetError, match="temperature"):
            loop.generate([1], 1, temperature=-0.5)
        with pytest.raises(MXNetError, match="top_k"):
            loop.generate([1], 1, top_k=-1)
        with pytest.raises(MXNetError, match="top_p"):
            loop.generate([1], 1, top_p=0.0)
        with pytest.raises(MXNetError, match="prefix_len"):
            loop.generate([1, 2], 1, prefix_len=2)
    finally:
        loop.close()


# ---------------------------------------------------------------------------
# quantized weights
# ---------------------------------------------------------------------------

def test_int8_weight_bytes_reduction_and_quality_gate():
    params = _lm_params()
    f32 = _loop(params, quantize="none", prefix_cache=False)
    q8 = _loop(params, quantize="int8", prefix_cache=False)
    try:
        reduction = 1.0 - q8.weight_bytes() / f32.weight_bytes()
        assert reduction >= 0.40, reduction
        # the loop still decodes sensibly: greedy streams agree with the
        # f32 loop on this tiny model (the engine-level gate below is the
        # deploy workflow)
        a = _gen(f32, [1, 2, 3], 5)
        b = _gen(q8, [1, 2, 3], 5)
        assert len(b) == 5
        agree = np.mean([x == y for x, y in zip(a, b)])
        assert agree >= 0.6, (a, b)
    finally:
        f32.close()
        q8.close()


@pytest.mark.slow
def test_bf16_mode_halves_weight_bytes():
    params = _lm_params()
    f32 = _loop(params, quantize="none", prefix_cache=False)
    bf = _loop(params, quantize="bf16", prefix_cache=False)
    try:
        assert bf.weight_bytes() == f32.weight_bytes() // 2
        assert len(_gen(bf, [1, 2], 4)) == 4
    finally:
        f32.close()
        bf.close()


def test_engine_quality_gate_workflow():
    """The documented deploy gate: probe the f32 and quantized engines
    with the same batch; check_quality passes at high agreement and
    raises below the floor."""
    sym = models.transformer(**_LM)
    params = _lm_params()
    s = _LM["seq_len"]
    ref = serving.ServingEngine(sym, params, {"data": (s,)}, buckets=(2,))
    q = serving.ServingEngine(sym, params, {"data": (s,)}, buckets=(2,),
                              quantize="int8")
    probe = np.zeros((2, s), np.float32)
    probe[:, :3] = [[1, 2, 3], [4, 5, 6]]
    rep = q.quality_report(ref, {"data": probe})
    # the transformer engine emits per-position logits, so a (2, seq)
    # probe compares 2*seq rows, not 2
    assert rep["probe_rows"] == 2 * _LM["seq_len"]
    check_quality(rep, min_agree=0.9, who="test")
    # an engine that disagrees must fail loudly, naming the numbers
    bad = {"top1_agreement": 0.5, "max_abs_err": 3.0, "probe_rows": 2}
    with pytest.raises(MXNetError, match="quality gate FAILED"):
        check_quality(bad, min_agree=0.98, who="test")
    assert ref.quant_mode == "none" and q.quant_mode == "int8"
    assert q.weight_bytes() < ref.weight_bytes()


def test_sharded_quantized_engine_holds_one_nth_per_chip():
    """int8 payloads shard along axis 0 (auto_spec's first choice) with
    the per-channel scale pinned to the SAME split: each chip holds 1/N
    of the quantized bytes, not a replicated copy."""
    import jax
    devs = jax.devices()
    if len(devs) < 2:
        pytest.skip("needs the forced multi-device host")
    rs = np.random.RandomState(0)
    net = mx.sym.FullyConnected(mx.sym.Variable("data"),
                                num_hidden=len(devs), name="fc1")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    params = {"arg:fc1_weight":
              rs.randn(len(devs), 6).astype(np.float32),
              "arg:fc1_bias": rs.randn(len(devs)).astype(np.float32)}
    eng = serving.ServingEngine(net, params, {"data": (6,)}, buckets=(2,),
                                contexts=devs, quantize="int8")
    leaf = eng._params["fc1_weight"]
    assert set(leaf) == {"q", "s"}
    qshards = leaf["q"].addressable_shards
    assert len(qshards) == len(devs)
    assert qshards[0].data.shape[0] == 1          # 1/N of axis 0
    sshards = leaf["s"].addressable_shards
    assert sshards[0].data.shape[0] == 1          # scale rides the split
    out = eng.infer({"data": np.zeros((2, 6), np.float32)})[0]
    assert out.shape == (2, len(devs))


@pytest.mark.slow
def test_update_params_requantizes_in_place():
    params = _lm_params()
    loop = _loop(params, quantize="int8", prefix_cache=False)
    try:
        before = _gen(loop, [1, 2, 3], 5)
        bytes_before = loop.weight_bytes()
        loop.update_params(_lm_params(seed=11))
        after = _gen(loop, [1, 2, 3], 5)
        assert loop.weight_bytes() == bytes_before   # still int8-resident
        assert after != before                       # new weights serve
    finally:
        loop.close()


# ---------------------------------------------------------------------------
# prefix cache
# ---------------------------------------------------------------------------

def test_prefix_hit_stream_identical_to_cold():
    params = _lm_params()
    shared = [1, 2, 3, 4]
    cold = _loop(params, prefix_cache=False)
    warm = _loop(params, prefix_cache=True)
    try:
        ref = [_gen(cold, shared + t, 5, temperature=0.7, seed=9)
               for t in ([5], [6, 7])]
        got = [_gen(warm, shared + t, 5, temperature=0.7, seed=9,
                    prefix_len=len(shared)) for t in ([5], [6, 7])]
        assert got == ref
        assert warm.health.prefix_prefills == 1      # first request fills
        assert warm.health.prefix_hits == 1          # second implants
    finally:
        cold.close()
        warm.close()


@pytest.mark.slow
def test_prefix_lru_evicts_at_capacity(monkeypatch):
    monkeypatch.setenv("MXTPU_SERVE_PREFIX_MAX", "1")
    params = _lm_params()
    loop = _loop(params, prefix_cache=True)
    try:
        a, b = [1, 2, 3], [4, 5, 6]
        _gen(loop, a + [7], 2, prefix_len=3)    # prefill A
        _gen(loop, a + [8], 2, prefix_len=3)    # hit A
        _gen(loop, b + [7], 2, prefix_len=3)    # prefill B, evict A
        _gen(loop, a + [9], 2, prefix_len=3)    # A again: re-prefill
        assert loop.health.prefix_prefills == 3
        assert loop.health.prefix_hits == 1
    finally:
        loop.close()


# ---------------------------------------------------------------------------
# speculative decoding
# ---------------------------------------------------------------------------

def test_spec_decode_token_identical_perfect_draft():
    """draft == target: every proposal must verify, and the output is
    token-identical to target-only sampling under the same seeds."""
    params = _lm_params()
    plain = _loop(params, prefix_cache=False)
    spec = _loop(params, prefix_cache=False, spec_k=2,
                 draft_params=params,
                 draft_num_layers=_LM["num_layers"])
    try:
        prompts = [[1, 2, 3], [4, 5]]
        ref = [_gen(plain, p, 6, temperature=0.8, seed=10 + i)
               for i, p in enumerate(prompts)]
        got = [_gen(spec, p, 6, temperature=0.8, seed=10 + i)
               for i, p in enumerate(prompts)]
        assert got == ref
        h = spec.health
        assert h.spec_rounds > 0
        # drafted counts only proposals the target ruled on, so a perfect
        # draft earns exactly 100% acceptance
        assert h.spec_drafted > 0
        assert h.spec_accepted == h.spec_drafted, h.report()
    finally:
        plain.close()
        spec.close()


def test_spec_decode_token_identical_weak_draft():
    """A deliberately useless draft (different random weights) costs
    acceptance, never correctness: the emitted stream is still identical
    to target-only decoding — greedy AND sampled."""
    params = _lm_params()
    plain = _loop(params, prefix_cache=False)
    spec = _loop(params, prefix_cache=False, spec_k=2,
                 draft_params=_lm_params(seed=77, num_layers=1),
                 draft_num_layers=1)
    try:
        for kw in (dict(), dict(temperature=1.1, top_k=6, seed=5)):
            ref = _gen(plain, [2, 4, 6], 7, **kw)
            got = _gen(spec, [2, 4, 6], 7, **kw)
            assert got == ref, kw
    finally:
        plain.close()
        spec.close()


@pytest.mark.slow
def test_spec_program_set_audits_clean():
    params = _lm_params()
    spec = _loop(params, prefix_cache=True, spec_k=2,
                 draft_params=_lm_params(seed=8, num_layers=1),
                 draft_num_layers=1)
    try:
        names = sorted(spec.memory_report())
        assert any("verify[" in n for n in names)
        assert any("draft[" in n for n in names)
        assert [f.format() for f in spec.check(memory=True)] == []
    finally:
        spec.close()


def test_spec_k_without_draft_raises():
    with pytest.raises(MXNetError, match="draft_params"):
        _loop(spec_k=2)


# ---------------------------------------------------------------------------
# knob resolution
# ---------------------------------------------------------------------------

def test_decode_knobs_resolve_from_tuning_db(monkeypatch, tmp_path):
    """DB knobs apply when arg and env are silent; a DB spec_k without a
    draft model falls back with a warning (never breaks a deploy); env
    beats DB."""
    from mxnet_tpu.autotune import db as _adb
    params = _lm_params()
    tdb = _adb.TuningDB(str(tmp_path / "tune.json"))
    tdb.put("lm", "decode_tokens_per_sec", 0,
            {"spec_k": 2, "prefix_cache": 0}, 100.0, "tokens/sec",
            kind="decode", symbol_sig=_adb.param_signature(params))
    tdb.save()
    monkeypatch.setenv("MXTPU_AUTOTUNE_DB", str(tmp_path / "tune.json"))
    loop = _loop(params)
    try:
        assert loop.prefix_enabled is False          # db applied
        assert loop.spec_k == 0                      # no draft: warned off
    finally:
        loop.close()
    monkeypatch.setenv("MXTPU_SERVE_PREFIX_CACHE", "1")
    loop = _loop(params)
    try:
        assert loop.prefix_enabled is True           # env beats db
    finally:
        loop.close()


# ---------------------------------------------------------------------------
# fault sites
# ---------------------------------------------------------------------------

@pytest.mark.faults
def test_fault_sample_sheds_in_flight():
    loop = _loop(prefix_cache=False)
    try:
        faults.inject("serve.sample", nth=2, kind="raise")
        fut = loop.generate([1, 2, 3], 8, temperature=0.8, seed=3)
        with pytest.raises(serving.ServingClosedError):
            fut.result(timeout=60.0)
        assert loop.health.shed >= 1
        assert loop.dead is not None
    finally:
        faults.clear("serve.sample")
        loop.close()


@pytest.mark.faults
def test_fault_spec_verify_sheds_without_emitting_drafts():
    params = _lm_params()
    loop = _loop(params, prefix_cache=False, spec_k=2,
                 draft_params=params,
                 draft_num_layers=_LM["num_layers"])
    try:
        faults.inject("serve.spec_verify", nth=1, kind="raise")
        fut = loop.generate([1, 2, 3], 6)
        with pytest.raises(serving.ServingClosedError):
            fut.result(timeout=60.0)
        # the round died between draft and verify: nothing was committed
        assert loop.health.spec_accepted == 0
        assert loop.dead is not None
    finally:
        faults.clear("serve.spec_verify")
        loop.close()
