"""Training-health guardrails (docs/robustness.md "Numerical guardrails").

Pins the TrainingGuard contract: on-device NaN/Inf sentinels make a
poisoned step a device-side no-op (bitwise — every other step identical to
a run that never saw the bad batch), skipped batches stay out of metric
denominators, the unguarded fused program is untouched (no sentinel ops, no
retrace), sustained loss spikes roll training back to the newest KNOWN-GOOD
checkpoint with the lr reduced, and ``max_rollbacks`` ends in
``TrainingDivergedError``. Satellites: fused ``clip_global_norm`` parity
vs. the imperative helper, the CrossEntropy eps declared-constant specs,
Speedometer health surfacing, known-good manifest refusal.
"""
import json
import logging

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import faults, guard as guard_mod, optimizer as opt, sym
from mxnet_tpu.base import MXNetError
from mxnet_tpu.executor import simple_bind
from mxnet_tpu.guard import TrainingGuard, TrainingDivergedError
from mxnet_tpu.model import CheckpointManager, atomic_write_bytes
from mxnet_tpu.train_step import TrainStep

pytestmark = pytest.mark.guard


@pytest.fixture(autouse=True)
def _clean_slate():
    faults.clear()
    guard_mod.TRAINING_HEALTH.reset()
    yield
    faults.clear()
    guard_mod.TRAINING_HEALTH.reset()


def _mlp():
    data = sym.Variable("data")
    net = sym.FullyConnected(data=data, num_hidden=16, name="fc1")
    net = sym.Activation(data=net, act_type="tanh")
    net = sym.FullyConnected(data=net, num_hidden=4, name="fc2")
    return sym.SoftmaxOutput(data=net, name="softmax")


def _stacked(k=4, batch=8, seed=0):
    rng = np.random.default_rng(seed)
    Xs = rng.normal(size=(k, batch, 10)).astype(np.float32)
    ys = rng.integers(0, 4, (k, batch)).astype(np.float32)
    return Xs, ys


def _mk_step(momentum=0.9, **kw):
    o = opt.create("sgd", learning_rate=0.05, momentum=momentum,
                   rescale_grad=1.0 / 8, **kw)
    return TrainStep(_mlp(), optimizer=o)


def _init(step, B=8, seed=1):
    return step.init({"data": (B, 10)}, {"softmax_label": (B,)}, seed=seed)


def _toy_data(n=128, dim=10, classes=4, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, dim)).astype(np.float32)
    w = rng.normal(size=(dim, classes)).astype(np.float32)
    y = np.argmax(X @ w, axis=1).astype(np.float32)
    return X, y


# -- on-device sentinels: parity and the bitwise no-op ----------------------

def test_guarded_run_matches_unguarded_bitwise():
    """Without faults, the guarded scan must produce the SAME params and
    metric sums as the unguarded one (the sentinels observe, never touch)."""
    K, B = 4, 8
    Xs, ys = _stacked(K, B)
    sb = {"data": jnp.asarray(Xs), "softmax_label": jnp.asarray(ys)}

    a = _mk_step()
    sa = _init(a)
    sa, ma = a.run_steps(sa, sb)
    b = _mk_step()
    sb_state = _init(b)
    sb_state, mb = b.run_steps(sb_state, dict(sb), guard=True)

    for n in a.param_names:
        np.testing.assert_array_equal(np.asarray(sa["params"][n]),
                                      np.asarray(sb_state["params"][n]),
                                      err_msg=n)
    assert mb.skipped == 0
    assert mb.num_samples == ma.num_samples == K * B
    assert mb.loss_sum == ma.loss_sum
    assert np.isfinite(mb.last_grad_norm)


def test_grad_nan_step_is_bitwise_noop():
    """Acceptance: with guard.grad_nan armed for step N, that step is a
    device-side no-op — final params (and metric sums) bitwise-identical to
    a run over the same batches WITHOUT batch N, skipped==1, params finite,
    and the step counter does not advance for the skipped step."""
    K, B = 4, 8
    Xs, ys = _stacked(K, B)

    faults.inject("guard.grad_nan", nth=2)      # poison step index 1
    f = _mk_step()
    sf = _init(f)
    sf, mf = f.run_steps(sf, {"data": jnp.asarray(Xs),
                              "softmax_label": jnp.asarray(ys)}, guard=True)
    faults.clear()
    assert mf.skipped == 1
    assert mf.num_samples == (K - 1) * B        # metric denominator excludes
    assert int(np.asarray(sf["step"])) == K - 1  # full no-op: clock too
    for n in f.param_names:
        assert np.isfinite(np.asarray(sf["params"][n])).all(), n

    idx = [0, 2, 3]                              # same run minus the batch
    r = _mk_step()
    sr = _init(r)
    sr, mr = r.run_steps(sr, {"data": jnp.asarray(Xs[idx]),
                              "softmax_label": jnp.asarray(ys[idx])},
                         guard=True)
    for n in f.param_names:
        np.testing.assert_array_equal(np.asarray(sf["params"][n]),
                                      np.asarray(sr["params"][n]),
                                      err_msg=n)
    assert mf.loss_sum == mr.loss_sum
    assert mf.top1_correct == mr.top1_correct


def test_guarded_single_step_skip_and_sentinels():
    B = 8
    Xs, ys = _stacked(2, B)
    batch = {"data": jnp.asarray(Xs[0]), "softmax_label": jnp.asarray(ys[0])}
    s = _mk_step()
    st = _init(s)
    st, outs, packed = s.step(st, batch, guard=True)
    sent = np.asarray(packed)
    assert sent[2] == B and sent[3] == 0 and np.isfinite(sent[4])

    faults.inject("guard.grad_nan", nth=1)
    before = {n: np.asarray(st["params"][n]).copy() for n in s.param_names}
    st, outs, packed = s.step(st, {"data": jnp.asarray(Xs[1]),
                                   "softmax_label": jnp.asarray(ys[1])},
                              guard=True)
    sent = np.asarray(packed)
    assert sent[3] == 1 and sent[2] == 0        # skipped, zero samples
    for n in s.param_names:
        np.testing.assert_array_equal(before[n], np.asarray(st["params"][n]),
                                      err_msg=n)


def test_guard_disabled_trace_and_caches_unchanged():
    """Acceptance: with guard disabled the fused step's jaxpr has no
    sentinel ops, and guarded dispatches never touch (or retrace) the
    unguarded jit caches — still one compiled program per (batch, k)."""
    K, B = 2, 8
    Xs, ys = _stacked(K, B)
    sb = {"data": jnp.asarray(Xs), "softmax_label": jnp.asarray(ys)}
    s = _mk_step()
    st = _init(s)

    fn = s._make_step_fn(B)
    jaxpr = str(jax.make_jaxpr(lambda a, b, k_, lr: fn(a, b, k_, lr))(
        st, {"data": jnp.asarray(Xs[0]), "softmax_label": jnp.asarray(ys[0])},
        jax.random.key(0), jnp.float32(0.1)))
    assert "is_finite" not in jaxpr

    st, _ = s.run_steps(st, dict(sb))
    st, _ = s.run_steps(st, dict(sb), guard=True)
    # the guarded scan holds one program across repeat dispatches — pinned
    # by the tracecheck cache-key differ, which would name the argument
    # whose signature drifted if either cache missed
    from mxnet_tpu.test_utils import assert_no_retrace
    with assert_no_retrace(s._jit_scan[(B, K)], s._jit_scan_g[(B, K)],
                           msg="guard on/off toggling"):
        st, _ = s.run_steps(st, dict(sb), guard=True)
        st, _ = s.run_steps(st, dict(sb))
    assert set(s._jit_scan) == {(B, K)}
    assert set(s._jit_scan_g) == {(B, K)}
    for f in list(s._jit_scan.values()) + list(s._jit_scan_g.values()):
        assert f._cache_size() == 1, "guard toggling retraced a scan"


# -- fused clip_global_norm (satellite) -------------------------------------

def test_clip_global_norm_fused_matches_imperative():
    """Fused in-graph global-norm clip == imperative clip_by_global_norm
    over the same (pre-scaled) gradients, SGD with momentum."""
    B, c = 8, 0.05
    Xs, ys = _stacked(3, B, seed=7)
    fused = _mk_step(clip_global_norm=c)
    state = _init(fused, seed=2)

    ex = simple_bind(_mlp(), mx.cpu(), grad_req="write", data=(B, 10),
                     softmax_label=(B,))
    for n in fused.param_names:
        ex.arg_dict[n]._set_data(jnp.copy(state["params"][n]))
    imp = opt.create("sgd", learning_rate=0.05, momentum=0.9,
                     rescale_grad=1.0)   # grads pre-scaled below
    upd = opt.get_updater(imp)
    names = list(fused.param_names)

    for i in range(3):
        batch = {"data": jnp.asarray(Xs[i]),
                 "softmax_label": jnp.asarray(ys[i])}
        state, _ = fused.step(state, batch)
        ex.forward(is_train=True, data=Xs[i], softmax_label=ys[i])
        ex.backward()
        grads = [ex.grad_dict[n] * (1.0 / B) for n in names]
        opt.clip_by_global_norm(grads, c)
        for j, n in enumerate(names):
            upd(j, grads[j], ex.arg_dict[n])

    for n in names:
        np.testing.assert_allclose(np.asarray(state["params"][n]),
                                   ex.arg_dict[n].asnumpy(),
                                   atol=2e-5, rtol=2e-5, err_msg=n)


def test_clip_by_global_norm_scales_and_reports_norm():
    a = mx.nd.array(np.full((3,), 3.0, np.float32))
    b = mx.nd.array(np.full((4,), 2.0, np.float32))
    norm = opt.clip_by_global_norm([a, b], 1.0)
    np.testing.assert_allclose(norm, np.sqrt(9 * 3 + 4 * 4), rtol=1e-6)
    np.testing.assert_allclose(opt.global_norm([a, b]), 1.0, rtol=1e-5)


def test_imperative_updater_rejects_clip_global_norm():
    o = opt.create("sgd", learning_rate=0.1, clip_global_norm=1.0)
    upd = opt.get_updater(o)
    w = mx.nd.array(np.ones((2,), np.float32))
    g = mx.nd.array(np.ones((2,), np.float32))
    with pytest.raises(MXNetError, match="clip_by_global_norm"):
        upd(0, g, w)


# -- fit()-level guard: skip, health, metric denominators -------------------

def _guarded_fit(X, y, k, guard, num_epoch=1, prefix=None, every=None,
                 lr=0.1, seed=3):
    mx.random.seed(seed)
    train = mx.io.NDArrayIter(X, y, batch_size=16)
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    metric = mx.metric.create(["acc", "ce"])
    mod.fit(train, num_epoch=num_epoch, eval_metric=metric,
            optimizer_params={"learning_rate": lr, "momentum": 0.9},
            steps_per_dispatch=k, guard=guard,
            checkpoint_prefix=prefix,
            checkpoint_every_n_batches=every, checkpoint_keep=10)
    return mod, metric


@pytest.mark.parametrize("k", [1, 4])
def test_fit_guard_skips_nan_batch_and_stays_finite(k):
    X, y = _toy_data()
    faults.inject("guard.grad_nan", nth=3)
    g = TrainingGuard(max_skips_per_window=100)
    mod, metric = _guarded_fit(X, y, k, g)
    assert g.health.skipped == 1
    assert g.health.steps == 8
    arg, _ = mod.get_params()
    for n, v in arg.items():
        assert np.isfinite(v.asnumpy()).all(), n
    # the skipped batch is excluded from the metric denominator
    for m in metric.metrics:
        assert m.num_inst == 128 - 16
    # and the process-global aggregate mirrored it
    assert guard_mod.TRAINING_HEALTH.report()["skipped"] == 1


def test_fit_guard_true_and_env_default(caplog, monkeypatch):
    X, y = _toy_data(64)

    def run():
        mx.random.seed(0)
        train = mx.io.NDArrayIter(X, y, batch_size=16)
        mod = mx.mod.Module(_mlp(), context=mx.cpu())
        with caplog.at_level(logging.WARNING):
            mod.fit(train, num_epoch=1,
                    optimizer_params={"learning_rate": 0.1}, guard=None)

    # guard=None + no env: silent, unguarded
    run()
    assert not any("checkpoint_prefix" in r.message for r in caplog.records)
    # MXTPU_GUARD=1 turns the guard on by default: without checkpoints it
    # trains but warns that divergence cannot roll back
    monkeypatch.setenv("MXTPU_GUARD", "1")
    run()
    assert any("no checkpoint_prefix" in r.message for r in caplog.records)


def test_fit_guard_ineligible_warns_and_trains_unguarded(caplog):
    # multi-head net: no single classification head -> guard unavailable
    data = sym.Variable("data")
    net = sym.FullyConnected(data=data, num_hidden=8, name="fc1")
    a = sym.SoftmaxOutput(sym.FullyConnected(net, num_hidden=4, name="ha"),
                          name="sa")
    b = sym.SoftmaxOutput(sym.FullyConnected(net, num_hidden=4, name="hb"),
                          name="sb")
    net = sym.Group([a, b])
    X, y = _toy_data(32)
    train = mx.io.NDArrayIter(X, {"sa_label": y, "sb_label": y},
                              batch_size=16)
    mod = mx.mod.Module(net, label_names=("sa_label", "sb_label"),
                        context=mx.cpu())
    g = TrainingGuard()
    with caplog.at_level(logging.WARNING):
        mod.fit(train, num_epoch=1, optimizer_params={"learning_rate": 0.1},
                guard=g)
    assert any("UNGUARDED" in r.message for r in caplog.records)
    assert g.health.steps == 0


# -- divergence -> rollback -> TrainingDivergedError ------------------------

def test_loss_spike_triggers_rollback_and_lr_reduction(tmp_path):
    X, y = _toy_data()
    prefix = str(tmp_path / "ck")
    g = TrainingGuard(patience=2, max_rollbacks=1, lr_factor=0.5)
    faults.inject("guard.loss_spike", nth=6, times=2)
    mod, _ = _guarded_fit(X, y, 1, g, num_epoch=2, prefix=prefix, every=3)
    assert g.health.rollbacks == 1
    assert g.health.divergences == 1
    assert abs(mod._optimizer.lr - 0.05) < 1e-12    # 0.1 * 0.5
    arg, _ = mod.get_params()
    assert all(np.isfinite(v.asnumpy()).all() for v in arg.values())


def test_rollback_restores_checkpoint_bitwise(tmp_path):
    """The rollback hook itself: params, optimizer momentum and the update
    clock all come back bitwise from the last known-good checkpoint, and
    the lr is reduced by the policy factor."""
    X, y = _toy_data()
    prefix = str(tmp_path / "ck")
    g = TrainingGuard(lr_factor=0.25)
    mod, _ = _guarded_fit(X, y, 1, g, prefix=prefix, every=4)
    mgr = CheckpointManager(prefix, keep=10)
    want = mgr.load_latest()
    assert want is not None and want.known_good is True
    clock_before = mod._optimizer.num_update

    # keep training so live params drift away from the checkpoint
    train = mx.io.NDArrayIter(X, y, batch_size=16)
    for batch in train:
        assert mod._try_fused_fit_step(batch)
    drifted, _ = mod.get_params()
    assert any(not np.array_equal(drifted[n].asnumpy(),
                                  want.arg_params[n].asnumpy())
               for n in drifted)

    g.diverged = True
    g.diverged_reason = "test"
    st = mod._guard_rollback(g, mgr)
    assert st.tag == want.tag
    arg, _ = mod.get_params()
    for n in arg:
        np.testing.assert_array_equal(arg[n].asnumpy(),
                                      want.arg_params[n].asnumpy(),
                                      err_msg=n)
    assert mod._optimizer.num_update == want.num_update != clock_before + 8
    assert abs(mod._optimizer.lr - 0.1 * 0.25) < 1e-12
    assert g.health.rollbacks == 1 and not g.diverged
    # optimizer momentum restored: the next fused step reseeds from the
    # checkpointed updater states, bitwise
    train.reset()
    batch = next(iter(train))
    assert mod._try_fused_fit_step(batch)
    assert int(np.asarray(mod._fused_state["step"])) == want.num_update + 1


def test_rollback_under_dispatch_bulking(tmp_path):
    """Divergence mid-epoch under steps_per_dispatch=4: the superbatch
    iterator resets cleanly mid-stream, the rollback fast-forwards whole
    dispatches, and training completes at the reduced lr."""
    X, y = _toy_data()
    prefix = str(tmp_path / "ck")
    g = TrainingGuard(patience=1, max_rollbacks=1, lr_factor=0.5)
    faults.inject("guard.loss_spike", nth=2)     # 2nd dispatch observation
    # post-rollback resume must redispatch through the SAME compiled scan
    # (PR-3's no-recompile rollback contract) — the tracecheck differ
    # names the drifting argument if the reseeded state ever retraces
    from mxnet_tpu.test_utils import assert_no_retrace
    with assert_no_retrace(msg="rollback + resume"):
        mod, _ = _guarded_fit(X, y, 4, g, num_epoch=2, prefix=prefix,
                              every=4)
    assert g.health.rollbacks == 1
    assert abs(mod._optimizer.lr - 0.05) < 1e-12
    arg, _ = mod.get_params()
    assert all(np.isfinite(v.asnumpy()).all() for v in arg.values())
    # training resumed and finished both epochs after the rollback
    assert int(np.asarray(mod._fused_state["step"])) == 16


def test_max_rollbacks_exhausted_raises_diverged(tmp_path):
    X, y = _toy_data()
    prefix = str(tmp_path / "ck")
    g = TrainingGuard(patience=2, max_rollbacks=0)
    faults.inject("guard.loss_spike", nth=6, times=2)
    with pytest.raises(TrainingDivergedError, match="max_rollbacks"):
        _guarded_fit(X, y, 1, g, num_epoch=2, prefix=prefix, every=3)
    assert g.health.divergences == 1 and g.health.rollbacks == 0


def test_divergence_without_checkpoint_raises(tmp_path):
    X, y = _toy_data()
    g = TrainingGuard(patience=2)
    faults.inject("guard.loss_spike", nth=4, times=2)
    with pytest.raises(TrainingDivergedError, match="checkpoint_prefix"):
        _guarded_fit(X, y, 1, g, num_epoch=1)


def test_skip_storm_triggers_divergence(tmp_path):
    """>= max_skips_per_window skipped batches inside one window is a
    divergence signal too (the data, not the lr, has gone bad)."""
    X, y = _toy_data()
    g = TrainingGuard(max_skips_per_window=2, window=50)
    faults.inject("guard.grad_nan", nth=3, times=2)
    with pytest.raises(TrainingDivergedError, match="skipped"):
        _guarded_fit(X, y, 1, g, num_epoch=1)
    assert g.health.skipped == 2


def test_checkpoints_deferred_while_spiking(tmp_path):
    """A state inside the spike window must not be sealed as a checkpoint:
    the rollback target has to PREdate the divergence it is escaping."""
    X, y = _toy_data()
    prefix = str(tmp_path / "ck")
    g = TrainingGuard(patience=3, max_rollbacks=1)
    faults.inject("guard.loss_spike", nth=5, times=3)  # obs 5-7 spike
    _guarded_fit(X, y, 1, g, num_epoch=1, prefix=prefix, every=2)
    assert g.health.rollbacks == 1
    # cadence would have saved b6 mid-spike; it was deferred, so the
    # rollback landed on b4 — the last pre-spike state
    assert g.health.last_event == "rolled back to checkpoint e0000-b00000004"


class _Stop(Exception):
    pass


def test_guarded_resume_restores_noise_clock_after_skip(tmp_path):
    """A guard-skipped step leaves the device step clock one behind
    num_update. Resume must restore the DEVICE clock (Adam's t, noise
    streams) from the manifest's fused_step, not re-derive it from
    num_update — asserted by bitwise parity of an interrupted+resumed
    guarded Adam run against an uninterrupted one."""
    X, y = _toy_data(64)

    def run(prefix, interrupt_after=None, resume=None, inject=True):
        faults.clear()
        if inject:
            faults.inject("guard.grad_nan", nth=2)
        mx.random.seed(3)
        train = mx.io.NDArrayIter(X, y, batch_size=16)
        mod = mx.mod.Module(_mlp(), context=mx.cpu())
        g = TrainingGuard(max_skips_per_window=100)
        cb = None
        if interrupt_after is not None:
            def cb(p):
                if p.nbatch + 1 >= interrupt_after:
                    raise _Stop()
        try:
            mod.fit(train, num_epoch=1, optimizer="adam",
                    optimizer_params={"learning_rate": 0.01}, guard=g,
                    batch_end_callback=cb, checkpoint_prefix=prefix,
                    checkpoint_every_n_batches=3, resume=resume)
        except _Stop:
            pass
        faults.clear()
        arg, _ = mod.get_params()
        return {n: v.asnumpy() for n, v in arg.items()}

    ref = run(str(tmp_path / "ref"))
    run(str(tmp_path / "vic"), interrupt_after=3)
    # the checkpoint recorded both clocks: 3 host updates, 2 device steps
    st = CheckpointManager(str(tmp_path / "vic")).load_latest()
    assert st.num_update == 3 and st.fused_step == 2
    got = run(str(tmp_path / "vic"), resume="auto", inject=False)
    for n in ref:
        np.testing.assert_array_equal(ref[n], got[n], err_msg=n)


# -- known-good manifests ----------------------------------------------------

def _fit_with_ckpt(X, y, prefix, every=4):
    mx.random.seed(0)
    train = mx.io.NDArrayIter(X, y, batch_size=16)
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    mod.fit(train, num_epoch=1, optimizer_params={"learning_rate": 0.1,
                                                  "momentum": 0.9},
            checkpoint_prefix=prefix, checkpoint_every_n_batches=every,
            checkpoint_keep=10)
    return mod


def test_checkpoints_marked_known_good(tmp_path):
    X, y = _toy_data()
    prefix = str(tmp_path / "ck")
    _fit_with_ckpt(X, y, prefix)
    mgr = CheckpointManager(prefix)
    for tag in mgr.list_tags():
        man = json.loads(open(mgr._file(tag, "manifest.json")).read())
        assert man["known_good"] is True and man["version"] == 2


def test_nonfinite_params_not_marked_known_good(tmp_path, caplog):
    X, y = _toy_data()
    prefix = str(tmp_path / "ck")
    mod = _fit_with_ckpt(X, y, prefix)
    mgr = CheckpointManager(prefix, keep=10)
    good = mgr.load_latest()
    # poison a live param, then checkpoint: saved but NOT known-good
    arg, aux = mod.get_params()
    bad = arg["fc1_weight"].asnumpy().copy()
    bad[0, 0] = np.nan
    arg["fc1_weight"] = mx.nd.array(bad)
    mod.set_params(arg, aux)
    with caplog.at_level(logging.WARNING):
        tag = mgr.save(mod, 7, 0)
    man = json.loads(open(mgr._file(tag, "manifest.json")).read())
    assert man["known_good"] is False
    assert any("NOT all finite" in r.message for r in caplog.records)
    # resume/rollback refuses it and falls back to the known-good one
    with caplog.at_level(logging.WARNING):
        st = mgr.load_latest()
    assert st.tag == good.tag
    assert any("known-good" in r.message for r in caplog.records)
    # forensics path still reaches it
    assert mgr.load_latest(require_known_good=False).tag == tag


def test_param_nan_fault_site_unmarks_checkpoint(tmp_path):
    X, y = _toy_data()
    prefix = str(tmp_path / "ck")
    mod = _fit_with_ckpt(X, y, prefix)
    mgr = CheckpointManager(prefix, keep=10)
    faults.inject("guard.param_nan", nth=1)
    tag = mgr.save(mod, 8, 0)
    man = json.loads(open(mgr._file(tag, "manifest.json")).read())
    assert man["known_good"] is False


def test_prune_never_deletes_newest_known_good(tmp_path):
    """A numerically dead run keeps writing post-mortem (not-known-good)
    checkpoints; age-only retention would push the last RESUMABLE state
    out of the keep window and resume would silently restart from
    scratch."""
    X, y = _toy_data()
    prefix = str(tmp_path / "ck")
    mod = _fit_with_ckpt(X, y, prefix, every=None)   # one good epoch-end tag
    mgr = CheckpointManager(prefix, keep=2)
    good = mgr.load_latest()
    assert good is not None
    # three post-mortem saves (params "went non-finite" via the fault site)
    for i in range(3):
        faults.inject("guard.param_nan", nth=1)
        mgr.save(mod, 10 + i, 0)
    tags = mgr.list_tags()
    assert good.tag in tags, "newest known-good tag was pruned"
    assert len(tags) == 3                   # keep=2 bad tags + the good one
    st = mgr.load_latest()
    assert st is not None and st.tag == good.tag


def test_resume_refuses_manifest_without_known_good_bit(tmp_path, caplog):
    """A manifest that LACKS the bit (pre-guard format) is refused for
    resume: the newest checkpoint that can prove finite params wins."""
    X, y = _toy_data()
    prefix = str(tmp_path / "ck")
    _fit_with_ckpt(X, y, prefix)
    mgr = CheckpointManager(prefix, keep=10)
    tags = mgr.list_tags()
    man_f = mgr._file(tags[-1], "manifest.json")
    man = json.loads(open(man_f).read())
    del man["known_good"]
    atomic_write_bytes(man_f, json.dumps(man, indent=1).encode())
    with caplog.at_level(logging.WARNING):
        st = mgr.load_latest()
    assert st is not None and st.tag == tags[-2]
    assert any("known-good" in r.message for r in caplog.records)


# -- metric eps (packed-accumulator protocol, satellite) ---------------------

def test_device_sums_carry_nondefault_ce_eps():
    """CrossEntropy(eps != 1e-8) now DECLARES its eps as a traced constant
    in its packed-accumulator spec instead of raising — distinct eps
    values are distinct jit-cache signatures, composites concatenate."""
    m = mx.metric.CrossEntropy(eps=1e-5)
    assert mx.metric.supports_device_sums(m)
    sp = mx.metric.device_sum_spec(m, [(4, 3)], [(4,)])
    sp8 = mx.metric.device_sum_spec(mx.metric.CrossEntropy(),
                                    [(4, 3)], [(4,)])
    assert sp.signature != sp8.signature
    # the traced eps actually differs: same inputs, different loss
    import jax.numpy as jnp
    o = jnp.asarray(np.full((4, 3), 1.0 / 3.0, np.float32))
    l = jnp.asarray(np.zeros(4, np.float32))
    v5 = float(sp.step_sums([o], [l])[0])
    v8 = float(sp8.step_sums([o], [l])[0])
    host = mx.metric.CrossEntropy(eps=1e-5)
    host.update([np.asarray(l)], [np.asarray(o)])
    np.testing.assert_allclose(v5, host.sum_metric, rtol=1e-6)
    assert v5 != v8
    # composites concatenate child specs, any position
    comp = mx.metric.CompositeEvalMetric(
        [mx.metric.CrossEntropy(eps=1e-5), mx.metric.Accuracy()])
    assert mx.metric.supports_device_sums(comp)
    # ...but one spec-less child still forces the per-step fallback
    comp2 = mx.metric.CompositeEvalMetric(
        [mx.metric.F1(), mx.metric.CrossEntropy(eps=1e-5)])
    assert mx.metric.supports_device_sums(comp2) is False


def test_fit_nondefault_ce_eps_parity_under_bulking():
    """fit(steps_per_dispatch=4) with CrossEntropy(eps=1e-5) rides the
    fused scan and reports the SAME metric as the k=1 host-update run —
    the parity the old hard raise existed to protect, now guaranteed by
    the declared-constant spec."""
    def train(k):
        X, y = _toy_data(64)
        train_it = mx.io.NDArrayIter(X, y, batch_size=16)
        mod = mx.mod.Module(_mlp(), context=mx.cpu())
        mx.random.seed(5)
        m = mx.metric.CrossEntropy(eps=1e-5)
        mod.fit(train_it, num_epoch=2,
                initializer=mx.initializer.Xavier(),
                optimizer_params={"learning_rate": 0.1},
                eval_metric=m, steps_per_dispatch=k)
        return mod, dict(m.get_name_value())["cross-entropy"]

    mod4, ce4 = train(4)
    assert any(key[:2] == (16, 4) for key in mod4._fused._jit_scan)
    _, ce1 = train(1)
    np.testing.assert_allclose(ce4, ce1, rtol=1e-5)


# -- observability (satellite) ----------------------------------------------

def _fire_speedometer(locals_):
    from mxnet_tpu.callback import Speedometer
    from mxnet_tpu.module.base_module import BatchEndParam
    sp = Speedometer(batch_size=16, frequent=10)
    fired = []
    orig = logging.info
    logging.info = lambda *a: fired.append(a)
    try:
        for nbatch in (5, 15):
            sp(BatchEndParam(epoch=0, nbatch=nbatch, eval_metric=None,
                             locals=locals_))
    finally:
        logging.info = orig
    assert fired, "speedometer never fired"
    return " ".join(str(x) for call in fired for x in call)


def test_speedometer_surfaces_training_health():
    g = TrainingGuard(logger=logging.getLogger("quiet"))
    g.health.record_steps(100, 2, 0.43)
    g.health.record_rollback("e0001-b00000004")
    joined = _fire_speedometer({"guard": g})   # fit exposes its locals
    assert "skipped=2" in joined and "rollbacks=1" in joined \
        and "grad_norm=0.43" in joined


def test_speedometer_strictly_per_run():
    """Another run's counters must never leak in: an unguarded fit
    (guard=None in locals) and a hand-built BatchEndParam (score()'s
    locals have no guard) both stay clean even while the process-global
    aggregate holds counts from an earlier guarded run."""
    guard_mod.TRAINING_HEALTH.record_steps(100, 2, 0.43)
    guard_mod.TRAINING_HEALTH.record_rollback("e0001-b00000004")
    assert "Guard:" not in _fire_speedometer({"guard": None})
    assert "Guard:" not in _fire_speedometer({"other": 1})
    assert "Guard:" not in _fire_speedometer(None)
    # and a guarded run with nothing to report is quiet too
    assert "Guard:" not in _fire_speedometer({"guard": TrainingGuard()})


# -- policy knobs ------------------------------------------------------------

def test_guard_env_knobs(monkeypatch):
    monkeypatch.setenv("MXTPU_GUARD_WINDOW", "25")
    monkeypatch.setenv("MXTPU_GUARD_SPIKE_FACTOR", "3.5")
    monkeypatch.setenv("MXTPU_GUARD_PATIENCE", "7")
    monkeypatch.setenv("MXTPU_GUARD_MAX_SKIPS", "9")
    monkeypatch.setenv("MXTPU_GUARD_LR_FACTOR", "0.25")
    monkeypatch.setenv("MXTPU_GUARD_MAX_ROLLBACKS", "4")
    g = TrainingGuard()
    assert (g.window, g.spike_factor, g.patience, g.max_skips_per_window,
            g.lr_factor, g.max_rollbacks) == (25, 3.5, 7, 9, 0.25, 4)
    # explicit args win over env
    assert TrainingGuard(patience=2).patience == 2
    monkeypatch.setenv("MXTPU_GUARD_WINDOW", "bogus")
    with pytest.raises(MXNetError, match="MXTPU_GUARD_WINDOW"):
        TrainingGuard()


def test_guard_env_disable_spellings(monkeypatch):
    """MXTPU_GUARD=False/OFF/No must DISABLE, not enable (case folded)."""
    X, y = _toy_data(32)
    for spelling in ("False", "OFF"):
        monkeypatch.setenv("MXTPU_GUARD", spelling)
        mx.random.seed(0)
        train = mx.io.NDArrayIter(X, y, batch_size=16)
        mod = mx.mod.Module(_mlp(), context=mx.cpu())
        mod.fit(train, num_epoch=1,
                optimizer_params={"learning_rate": 0.1})
        assert not mod._fused._jit_g, \
            "MXTPU_GUARD=%r must not enable the guard" % spelling


def test_nonfinite_loss_observation_skipped_with_warning():
    """A NaN loss observation (non-probability head slipping the shape
    gate) must not poison the EMA and kill the watcher silently."""
    g = TrainingGuard(patience=2, spike_factor=2.0,
                      logger=logging.getLogger("capture"))
    g.on_dispatch(loss_sum=1.0, nsamp=1, skipped=0, grad_norm=0.1)
    ema = g._ema
    g.on_dispatch(loss_sum=float("nan"), nsamp=1, skipped=0, grad_norm=0.1)
    assert g._ema == ema and g._warned_nonfinite_loss
    # the watcher still works afterwards: two real spikes diverge
    for _ in range(2):
        g.on_dispatch(loss_sum=100.0, nsamp=1, skipped=0, grad_norm=0.1)
    assert g.diverged


def test_guard_rejects_bad_policy():
    with pytest.raises(MXNetError, match="lr_factor"):
        TrainingGuard(lr_factor=0.0)
    with pytest.raises(MXNetError, match="patience"):
        TrainingGuard(patience=0)


def test_spiked_observation_never_updates_ema():
    g = TrainingGuard(patience=3, spike_factor=2.0,
                      logger=logging.getLogger("quiet"))
    for _ in range(3):
        g.on_dispatch(loss_sum=1.0, nsamp=1, skipped=0, grad_norm=0.1)
    ema = g._ema
    g.on_dispatch(loss_sum=100.0, nsamp=1, skipped=0, grad_norm=0.1)
    assert g._ema == ema and g._spike_run == 1 and not g.diverged
    g.on_dispatch(loss_sum=1.0, nsamp=1, skipped=0, grad_norm=0.1)
    assert g._spike_run == 0


# -- bucketed guard: per-bucket scans carry the sentinels --------------------
# (ROADMAP item 3 first gap: BucketingModule used to train UNGUARDED under
# MXTPU_GUARD=1 because the per-bucket fused programs had no sentinels)

def _bucket_sym_gen(key):
    data = sym.Variable("data")
    emb = sym.Embedding(data=data, input_dim=16, output_dim=8,
                        name="shared_embed")
    feat = sym.sum(emb, axis=1)
    pred = sym.FullyConnected(data=feat, num_hidden=8, name="shared_fc")
    return (sym.SoftmaxOutput(data=pred, name="softmax"),
            ("data",), ("softmax_label",))


class _BucketIter(mx.io.DataIter):
    """Deterministic bucketed stream: run-length-grouped bucket keys."""

    def __init__(self, keys, batch=4, seed=0):
        super().__init__(batch)
        rng = np.random.default_rng(seed)
        self.batches = []
        for key in keys:
            self.batches.append(mx.io.DataBatch(
                data=[mx.nd.array(rng.integers(0, 16, (batch, key))
                                  .astype(np.float32))],
                label=[mx.nd.array(rng.integers(0, 8, batch)
                                   .astype(np.float32))],
                pad=0, bucket_key=key,
                provide_data=[mx.io.DataDesc("data", (batch, key))],
                provide_label=[mx.io.DataDesc("softmax_label", (batch,))]))
        self.i = 0

    @property
    def provide_data(self):
        return [mx.io.DataDesc("data", (4, 10))]

    @property
    def provide_label(self):
        return [mx.io.DataDesc("softmax_label", (4,))]

    def reset(self):
        self.i = 0

    def next(self):
        if self.i >= len(self.batches):
            raise StopIteration
        b = self.batches[self.i]
        self.i += 1
        return b


def _bucketed_guarded_fit(keys, k, guard, num_epoch=1, prefix=None,
                          every=None, seed=21):
    from mxnet_tpu.module import BucketingModule
    it = _BucketIter(keys)
    mod = BucketingModule(_bucket_sym_gen, default_bucket_key=10,
                          context=mx.cpu())
    mx.random.seed(seed)
    metric = mx.metric.create(["acc", "ce"])
    mod.fit(it, num_epoch=num_epoch, initializer=mx.initializer.Xavier(),
            optimizer_params={"learning_rate": 0.1}, eval_metric=metric,
            steps_per_dispatch=k, guard=guard, checkpoint_prefix=prefix,
            checkpoint_every_n_batches=every, checkpoint_keep=10)
    return mod, metric


@pytest.mark.parametrize("k", [1, 4])
def test_bucketed_fit_guard_skips_nan_batch(k):
    """guard.grad_nan under bucketed dispatch: the poisoned step is a
    device-side no-op inside the BUCKET's guarded program — counted,
    excluded from the metric denominators, host step-clock mirror not
    advanced, params stay finite. k=1 exercises the guarded bucket-tail
    single step, k=4 the guarded per-bucket scan."""
    keys = [10] * 4 + [6] * 4
    faults.inject("guard.grad_nan", nth=3)
    g = TrainingGuard(max_skips_per_window=100)
    mod, metric = _bucketed_guarded_fit(keys, k, g)
    assert g.health.skipped == 1
    assert g.health.steps == 8
    assert mod._fused_host_step == 7  # the skipped step did not advance
    for m in metric.metrics:
        assert m.num_inst == (8 - 1) * 4  # skipped batch excluded
    arg, _ = mod.get_params()
    assert all(np.isfinite(v.asnumpy()).all() for v in arg.values())
    assert guard_mod.TRAINING_HEALTH.report()["skipped"] == 1


def test_bucketed_guarded_matches_unguarded_when_clean():
    """A clean guarded bucketed run trains the SAME numbers as the
    unguarded one (the sentinel where-selects are no-ops on finite
    steps) — params bitwise across both bucket shapes."""
    keys = [10] * 4 + [6] * 4
    g = TrainingGuard(max_skips_per_window=100)
    mod_g, _ = _bucketed_guarded_fit(keys, 4, g)
    assert g.health.skipped == 0
    mod_u, _ = _bucketed_guarded_fit(keys, 4, None)
    arg_g, _ = mod_g.get_params()
    arg_u, _ = mod_u.get_params()
    for n in arg_g:
        assert np.array_equal(arg_g[n].asnumpy(), arg_u[n].asnumpy()), n


def test_bucketed_guard_rollback_and_lr_reduction(tmp_path):
    """Divergence mid-run under bucketed dispatch: rollback restores the
    newest known-good checkpoint through the shared state tree (opt
    states included), reduces the shared optimizer's lr, and training
    completes both epochs across both bucket shapes."""
    keys = [10] * 4 + [6] * 4
    prefix = str(tmp_path / "ck")
    faults.inject("guard.loss_spike", nth=2)
    g = TrainingGuard(patience=1, max_rollbacks=1, lr_factor=0.5)
    mod, _ = _bucketed_guarded_fit(keys, 4, g, num_epoch=2, prefix=prefix,
                                   every=4)
    assert g.health.rollbacks == 1
    assert abs(mod._base_module._optimizer.lr - 0.05) < 1e-12
    # both epochs finished after the rollback (8 steps x 2 epochs)
    assert mod._fused_host_step == 16
    arg, _ = mod.get_params()
    assert all(np.isfinite(v.asnumpy()).all() for v in arg.values())


def test_bucketed_guard_skip_storm_diverges(tmp_path):
    """>= max_skips_per_window device-side skips inside one window is a
    divergence signal on the bucketed path too."""
    keys = [10] * 8
    faults.inject("guard.grad_nan", nth=3, times=2)
    g = TrainingGuard(max_skips_per_window=2, window=50)
    with pytest.raises(TrainingDivergedError, match="skipped"):
        _bucketed_guarded_fit(keys, 4, g)
    assert g.health.skipped == 2
