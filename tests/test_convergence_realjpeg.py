"""Suite-sized run of the real-JPEG convergence gate: 10-class generated
JPEG dataset through the native decode/augment pipeline, multi-epoch with
an LR schedule, held-out accuracy gate (ref: tests/nightly/test_all.sh
check_val; the full-size gate runs in ci/run.sh's chip stage)."""
import os
import subprocess
import sys

ROOT = os.path.join(os.path.dirname(__file__), "..")


def test_realjpeg_convergence_gate_small():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    r = subprocess.run(
        [sys.executable,
         os.path.join(ROOT, "tools", "convergence_gate_realdata.py"),
         "--classes", "10", "--n-per-class", "60", "--size", "40",
         "--crop", "32", "--batch", "50", "--epochs", "5",
         "--min-acc", "0.85"],
        capture_output=True, text=True, timeout=1500, env=env)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "REALDATA CONVERGENCE PASS" in r.stdout
