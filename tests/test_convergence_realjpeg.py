"""Real-JPEG convergence gates: generated JPEG datasets through the native
decode/augment pipeline, multi-epoch with an LR schedule, held-out accuracy
gate (ref: tests/nightly/test_all.sh check_val; the full-size gate runs in
ci/run.sh's chip stage).

Two tiers: a ~75s smoke gate (6 classes, 3 epochs) keeps the
JPEG->decode->augment->train->converge path in every tier-1 run, and the
original 10-class/5-epoch gate (~5 min — more than a third of the tier-1
wall-clock budget) runs in the slow tier with the other long integration
tests."""
import os
import subprocess
import sys

import pytest

ROOT = os.path.join(os.path.dirname(__file__), "..")


def _run_gate(*args, **extra_env):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    env.update(extra_env)
    r = subprocess.run(
        [sys.executable,
         os.path.join(ROOT, "tools", "convergence_gate_realdata.py")]
        + list(args),
        capture_output=True, text=True, timeout=1500, env=env)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "REALDATA CONVERGENCE PASS" in r.stdout


def test_realjpeg_convergence_gate_smoke():
    # deterministic (seeded generator + seeded iterator shuffle + fresh
    # process): observed holdout acc 0.8375, gated with margin at 0.75
    _run_gate("--classes", "6", "--n-per-class", "40", "--size", "36",
              "--crop", "28", "--batch", "40", "--epochs", "3",
              "--min-acc", "0.75")


def test_realjpeg_convergence_bf16_stats_parity():
    """MXTPU_BF16_STATS=all (bf16 BatchNorm moving stats + optimizer
    state, docs/perf.md "bf16 non-param state") must hold the SAME
    convergence floor on the real-JPEG path as f32 — a reduced-size run
    of the smoke gate's exact pipeline, so a precision regression in the
    moving-stat/momentum storage fails loudly here."""
    # deterministic (seeded): observed 0.75 holdout with bf16 stats+opt
    # state vs 0.69 f32 at the 2-epoch config — gated with margin at 0.65
    _run_gate("--classes", "4", "--n-per-class", "40", "--size", "32",
              "--crop", "24", "--batch", "20", "--epochs", "3",
              "--min-acc", "0.65", MXTPU_BF16_STATS="all")


@pytest.mark.slow
def test_realjpeg_convergence_gate_small():
    _run_gate("--classes", "10", "--n-per-class", "60", "--size", "40",
              "--crop", "32", "--batch", "50", "--epochs", "5",
              "--min-acc", "0.85")
