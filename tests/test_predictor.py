"""Predictor satellites (ISSUE 6): loss-head stripping coverage, strict
missing-parameter checking, and the reshape executor cache.

``_strip_loss_heads`` is the contract the whole serving tier binds
through — every ``_LOSS_HEADS`` entry must round-trip to its
inference-time transform, label arguments must vanish, and partial-output
predictors must compose with the stripping.
"""
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu.base import MXNetError  # noqa: E402
from mxnet_tpu.predictor import _LOSS_HEADS, _strip_loss_heads  # noqa: E402


def _softmax(x, axis=-1):
    e = np.exp(x - x.max(axis=axis, keepdims=True))
    return e / e.sum(axis=axis, keepdims=True)


# ---------------------------------------------------------------------------
# _strip_loss_heads: every entry round-trips
# ---------------------------------------------------------------------------

def _head_symbol(op_name, **attrs):
    data = mx.sym.Variable("data")
    make = getattr(mx.sym, op_name)
    return make(data=data, name="head", **attrs)


_EXPECTED_TRANSFORM = {
    "SoftmaxOutput": lambda x: _softmax(x.reshape(x.shape[0], -1)
                                        ).reshape(x.shape),
    "LogisticRegressionOutput": lambda x: 1.0 / (1.0 + np.exp(-x)),
    "LinearRegressionOutput": lambda x: x,
    "MAERegressionOutput": lambda x: x,
    "SVMOutput": lambda x: x,
    "MakeLoss": lambda x: x,
    "IdentityAttachKLSparseReg": lambda x: x,
}


@pytest.mark.parametrize("op_name", sorted(_LOSS_HEADS))
def test_strip_loss_head_roundtrips(op_name):
    """Each loss head strips to its inference transform, the label
    argument vanishes, and the stripped symbol binds with data only."""
    sym = _head_symbol(op_name)
    stripped = _strip_loss_heads(sym)
    args = stripped.list_arguments()
    assert args == ["data"], "label must vanish from arguments: %s" % args
    # binding needs NO label arrays
    pred = mx.Predictor(stripped, {}, {"data": (3, 4)})
    x = np.random.RandomState(0).randn(3, 4).astype(np.float32)
    out = pred.forward(data=x).get_output(0).asnumpy()
    np.testing.assert_allclose(out, _EXPECTED_TRANSFORM[op_name](x),
                               rtol=1e-5, atol=1e-6)


def test_strip_softmax_multi_output_channel_mode():
    """SoftmaxOutput(multi_output=True) — softmax over dim 1 of
    (batch, c, d1, ...) — must strip to CHANNEL-mode SoftmaxActivation,
    not instance mode."""
    sym = _head_symbol("SoftmaxOutput", multi_output=True)
    stripped = _strip_loss_heads(sym)
    node = stripped._outputs[0][0]
    assert node.op.name == "SoftmaxActivation"
    assert node.attrs["mode"] == "channel"
    pred = mx.Predictor(stripped, {}, {"data": (2, 3, 5)})
    x = np.random.RandomState(1).randn(2, 3, 5).astype(np.float32)
    out = pred.forward(data=x).get_output(0).asnumpy()
    np.testing.assert_allclose(out, _softmax(x, axis=1), rtol=1e-5,
                               atol=1e-6)
    # channel sums are 1 per (batch, position)
    np.testing.assert_allclose(out.sum(axis=1), np.ones((2, 5)), atol=1e-5)


def test_strip_loss_heads_json_roundtrip():
    """Stripping applies identically to a symbol reloaded from JSON (the
    deploy path: save_checkpoint -> -symbol.json -> Predictor)."""
    sym = _head_symbol("SoftmaxOutput")
    reloaded = mx.sym.load_json(sym.tojson())
    stripped = _strip_loss_heads(reloaded)
    assert stripped.list_arguments() == ["data"]
    assert stripped._outputs[0][0].op.name == "SoftmaxActivation"


def test_strip_preserves_non_loss_outputs():
    data = mx.sym.Variable("data")
    plain = mx.sym.Activation(data=data, act_type="relu", name="relu0")
    loss = mx.sym.SoftmaxOutput(data=data, name="softmax")
    group = mx.sym.Group([plain, loss])
    stripped = _strip_loss_heads(group)
    names = [n.op.name for n, _ in stripped._outputs]
    assert names == ["Activation", "SoftmaxActivation"]


def _two_layer_net():
    net = mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=5,
                                name="fc1")
    net = mx.sym.Activation(net, act_type="relu", name="relu1")
    net = mx.sym.FullyConnected(net, num_hidden=3, name="fc2")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def _two_layer_params(seed=0):
    rs = np.random.RandomState(seed)
    return {"arg:fc1_weight": mx.nd.array(rs.randn(5, 4).astype(np.float32)),
            "arg:fc1_bias": mx.nd.array(np.zeros(5, np.float32)),
            "arg:fc2_weight": mx.nd.array(rs.randn(3, 5).astype(np.float32)),
            "arg:fc2_bias": mx.nd.array(np.zeros(3, np.float32))}


def test_partial_outputs_compose_with_stripping():
    """output_names= picks an internal head AFTER stripping: the partial
    predictor binds label-free and computes the internal activation."""
    params = _two_layer_params()
    pred = mx.Predictor(_two_layer_net(), params, {"data": (2, 4)},
                        output_names=["relu1"])
    assert "softmax_label" not in pred._symbol.list_arguments()
    x = np.random.RandomState(2).rand(2, 4).astype(np.float32)
    out = pred.forward(data=x).get_output(0).asnumpy()
    w = params["arg:fc1_weight"].asnumpy()
    ref = np.maximum(x @ w.T, 0)
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)


# ---------------------------------------------------------------------------
# missing-parameter strictness (satellite bugfix 1)
# ---------------------------------------------------------------------------

def test_missing_param_raises_naming_keys():
    params = _two_layer_params()
    del params["arg:fc2_weight"]
    with pytest.raises(MXNetError, match="fc2_weight"):
        mx.Predictor(_two_layer_net(), params, {"data": (2, 4)})


def test_missing_param_zero_fill_is_opt_in():
    params = _two_layer_params()
    del params["arg:fc2_weight"]
    pred = mx.Predictor(_two_layer_net(), params, {"data": (2, 4)},
                        allow_missing=True)
    out = pred.forward(data=np.ones((2, 4), np.float32)) \
        .get_output(0).asnumpy()
    # zero fc2_weight + zero bias => uniform softmax
    np.testing.assert_allclose(out, np.full((2, 3), 1.0 / 3), atol=1e-6)


def test_unstripped_head_label_not_counted_missing():
    """A loss head outside _LOSS_HEADS keeps its label in
    list_arguments(); the strict check must not demand it from the
    checkpoint (labels are inputs, not parameters)."""
    from mxnet_tpu.predictor import check_missing_params
    data = mx.sym.Variable("data")
    lbl = mx.sym.Variable("myloss_label")
    net = mx.sym.FullyConnected(data, num_hidden=3, name="fc")
    out = net * lbl            # custom loss shape: label stays an argument
    assert "myloss_label" in out.list_arguments()
    # complete weights, label absent: must NOT raise
    check_missing_params(out, {"data"},
                         {"fc_weight": 0, "fc_bias": 0}, {})
    # a genuinely missing weight still raises
    with pytest.raises(MXNetError, match="fc_bias"):
        check_missing_params(out, {"data"}, {"fc_weight": 0}, {})


def test_typoed_key_raises_not_garbage(tmp_path):
    """The original bug: a typo'd checkpoint key was silently zero-filled
    and the predictor served garbage. It must raise, naming the key."""
    params = _two_layer_params()
    params["arg:fc2_weihgt"] = params.pop("arg:fc2_weight")  # typo
    with pytest.raises(MXNetError, match="fc2_weight"):
        mx.Predictor(_two_layer_net(), params, {"data": (2, 4)})


# ---------------------------------------------------------------------------
# reshape executor cache (satellite bugfix 2)
# ---------------------------------------------------------------------------

def test_reshape_caches_executors_per_shape():
    """Alternating batch sizes must reuse the executor bound for each
    shape (one bind/compile per shape, ever) — the serving batcher's
    bucket flipping depends on this."""
    pred = mx.Predictor(_two_layer_net(), _two_layer_params(),
                        {"data": (2, 4)})
    e2 = pred._executor
    pred.reshape({"data": (6, 4)})
    e6 = pred._executor
    assert e6 is not e2
    pred.reshape({"data": (2, 4)})
    assert pred._executor is e2       # cache hit, no rebind
    pred.reshape({"data": (6, 4)})
    assert pred._executor is e6
    # numerics survive the flips
    x = np.random.RandomState(3).rand(6, 4).astype(np.float32)
    out6 = pred.forward(data=x).get_output(0).asnumpy()
    pred.reshape({"data": (2, 4)})
    out2 = pred.forward(data=x[:2]).get_output(0).asnumpy()
    np.testing.assert_allclose(out6[:2], out2, rtol=1e-5, atol=1e-6)


def test_reshape_exec_cache_is_bounded():
    """The executor cache is LRU-bounded: a server fed unquantized batch
    sizes must not pin one compiled program per distinct size forever."""
    pred = mx.Predictor(_two_layer_net(), _two_layer_params(),
                        {"data": (2, 4)})
    cap = mx.Predictor._EXEC_CACHE_CAP
    for n in range(1, cap + 5):
        pred.reshape({"data": (n, 4)})
    assert len(pred._exec_cache) <= cap
    # the current executor survives eviction churn and still computes
    x = np.random.RandomState(4).rand(cap + 4, 4).astype(np.float32)
    out = pred.forward(data=x).get_output(0).asnumpy()
    assert out.shape == (cap + 4, 3)


def test_reshape_still_rejects_parameter_shape_changes():
    pred = mx.Predictor(_two_layer_net(), _two_layer_params(),
                        {"data": (2, 4)})
    with pytest.raises(MXNetError, match="parameter"):
        pred.reshape({"data": (2, 9)})
