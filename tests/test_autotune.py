"""mxnet_tpu.autotune (docs/perf.md "Autotuning").

Pins the contract: deterministic bounded search with crash/timeout
isolation, the memcheck pruner rejecting over-budget candidates WITHOUT
executing them, the tuning-DB schema/platform fallback rules, and the
knob-resolution precedence **explicit arg > env > tuning DB > built-in
default** across ``Module.fit`` and ``ServingEngine``.
"""
import json
import logging
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autotune, models
from mxnet_tpu.autotune.benchcfg import benv
from mxnet_tpu.autotune.db import SCHEMA_VERSION, TuningDB
from mxnet_tpu.autotune.harness import TrainHarness
from mxnet_tpu.autotune.search import NEG_INF, Knob, SearchDriver
from mxnet_tpu.base import MXNetError
from mxnet_tpu.tracecheck import ZOO


@pytest.fixture(autouse=True)
def _isolated_db(tmp_path, monkeypatch):
    """Every test runs against its own tuning DB: the committed repo DB
    must never leak knobs into unrelated tests, and tests must never
    write the committed file."""
    monkeypatch.setenv("MXTPU_AUTOTUNE_DB", str(tmp_path / "tune_db.json"))
    yield


def _zoo_mlp():
    return models.get_symbol("mlp", **ZOO["mlp"]["kwargs"])


def _write_train_entry(path, sym, batch, knobs, model="mlp",
                       objective="img_per_sec", schema=SCHEMA_VERSION,
                       device_kind=None):
    from mxnet_tpu.autotune.db import _device_kind
    entry = {
        "model": model, "objective": objective, "kind": "train",
        "global_batch": int(batch),
        "device_kind": device_kind or _device_kind(),
        "platform": "cpu", "symbol": sym.name,
        "symbol_sig": autotune.symbol_signature(sym),
        "knobs": dict(knobs), "score": 1.0, "unit": "images/sec",
    }
    key = "%s|%s|b%d|%s" % (model, entry["device_kind"], batch, objective)
    with open(path, "w") as f:
        json.dump({"schema": schema, "entries": {key: entry}}, f)
    return key


# -- search driver ----------------------------------------------------------

def test_grid_is_exhaustive_and_deterministic():
    seen = []

    def ev(kn):
        seen.append((kn["a"], kn["b"]))
        return kn["a"] * 10 + kn["b"]

    d = SearchDriver([Knob("a", (1, 2)), Knob("b", (0, 1, 2))], ev,
                     budget=10)
    best, trials = d.run()
    # itertools.product order in declared knob order; trial #0 = defaults
    assert seen == [(1, 0), (1, 1), (1, 2), (2, 0), (2, 1), (2, 2)]
    assert d.default_trial.knobs == {"a": 1, "b": 0}
    assert best.knobs == {"a": 2, "b": 2}
    # same space, same budget -> identical trial sequence
    seen2 = []
    d2 = SearchDriver([Knob("a", (1, 2)), Knob("b", (0, 1, 2))],
                      lambda kn: seen2.append((kn["a"], kn["b"])) or 0.0,
                      budget=10)
    d2.run()
    assert seen2 == seen


def test_hill_climb_bounded_and_greedy():
    calls = []

    def ev(kn):
        calls.append(dict(kn))
        return kn["a"] + kn["b"] + kn["c"]

    space = [Knob("a", (0, 1, 2)), Knob("b", (0, 1, 2)),
             Knob("c", (0, 1, 2))]  # 27 candidates > budget
    d = SearchDriver(space, ev, budget=7)
    best, trials = d.run()
    assert len(trials) == 7
    assert trials[0].knobs == {"a": 0, "b": 0, "c": 0}
    # greedy: after sweeping knob a it holds the best (a=2) while
    # sweeping b
    assert best.score == max(t.score for t in trials if t.ok)
    assert best.knobs["a"] == 2


def test_crashing_candidate_scores_neg_inf_and_sweep_survives():
    def ev(kn):
        if kn["a"] == 2:
            raise RuntimeError("RESOURCE_EXHAUSTED: out of memory")
        return float(kn["a"])

    d = SearchDriver([Knob("a", (1, 2, 3))], ev, budget=5)
    best, trials = d.run()
    assert [t.status for t in trials] == ["ok", "error", "ok"]
    assert trials[1].score == NEG_INF
    assert "RESOURCE_EXHAUSTED" in trials[1].detail
    assert best.knobs == {"a": 3}


def test_wedged_candidate_times_out_and_stops_sweep():
    """A timed-out trial's abandoned thread may still hold the shared
    harness: the sweep must stop there (later measurements would be
    contaminated by the zombie's contention) and report only the clean
    prefix."""
    def ev(kn):
        if kn["a"] == 2:
            time.sleep(30)
        return float(kn["a"])

    d = SearchDriver([Knob("a", (1, 2, 3))], ev, budget=5,
                     trial_timeout=0.2)
    best, trials = d.run()
    assert [t.status for t in trials] == ["ok", "timeout"]
    assert trials[1].score == NEG_INF
    assert d.timed_out
    assert best.knobs == {"a": 1}  # a=3 was never (mis)measured


def test_pruned_candidate_never_executes():
    executed = []

    def ev(kn):
        executed.append(kn["a"])
        return float(kn["a"])

    def prune(kn):
        if kn["a"] == 2:
            return ["peak HBM over budget"]
        return []

    d = SearchDriver([Knob("a", (1, 2, 3))], ev, prune=prune,
                     program_knobs=("a",), budget=5)
    best, trials = d.run()
    assert [t.status for t in trials] == ["ok", "pruned", "ok"]
    assert executed == [1, 3]  # the pruned candidate never ran
    assert best.knobs == {"a": 3}


# -- static pruner over a real program set ----------------------------------

def test_memcheck_pruner_rejects_over_budget_scan(monkeypatch):
    """A tiny MXTPU_AUTOTUNE_BUDGET makes the mlp scan over-budget: the
    pruner reports hbm-budget findings from ONE compile, and a driver
    using it records the candidate as pruned without evaluating."""
    h = TrainHarness(model="mlp", batch=8)
    assert h.prune({"steps_per_dispatch": 2}) == []  # sane budget: admits
    monkeypatch.setenv("MXTPU_AUTOTUNE_BUDGET", "4K")
    findings = h.prune({"steps_per_dispatch": 2})
    assert findings and all(f.lint in ("hbm-budget", "resident-set")
                            for f in findings)


# -- tuning DB --------------------------------------------------------------

def test_db_roundtrip_atomic_and_lookup(tmp_path):
    sym = _zoo_mlp()
    path = str(tmp_path / "db.json")
    db = TuningDB(path)
    db.put("mlp", "img_per_sec", 16, {"steps_per_dispatch": 2}, 123.0,
           "images/sec", symbol=sym.name,
           symbol_sig=autotune.symbol_signature(sym))
    db.save()
    db2 = TuningDB.load(path)
    assert not db2.stale
    key, entry, note = db2.lookup(
        "train", symbol_sig=autotune.symbol_signature(sym),
        global_batch=16)
    assert entry is not None and note is None
    assert entry["knobs"] == {"steps_per_dispatch": 2}
    # batch mismatch: no entry
    _, miss, _ = db2.lookup(
        "train", symbol_sig=autotune.symbol_signature(sym),
        global_batch=32)
    assert miss is None


def test_db_schema_mismatch_is_stale_with_warning(tmp_path, caplog):
    sym = _zoo_mlp()
    path = str(tmp_path / "db.json")
    _write_train_entry(path, sym, 16, {"steps_per_dispatch": 2},
                       schema=SCHEMA_VERSION + 99)
    with caplog.at_level(logging.WARNING):
        db = TuningDB.load(path)
    assert db.stale
    assert any("schema" in r.message for r in caplog.records)
    _, entry, _ = db.lookup("train",
                            symbol_sig=autotune.symbol_signature(sym),
                            global_batch=16)
    assert entry is None


def test_db_device_kind_mismatch_is_note_not_error(tmp_path):
    sym = _zoo_mlp()
    path = str(tmp_path / "db.json")
    _write_train_entry(path, sym, 16, {"steps_per_dispatch": 2},
                       device_kind="TPU v5e")
    db = TuningDB.load(path)
    key, entry, note = db.lookup(
        "train", symbol_sig=autotune.symbol_signature(sym),
        global_batch=16)
    assert entry is None
    assert note is not None and "TPU v5e" in note


def test_db_foreign_sibling_entry_does_not_note_when_match_found(tmp_path):
    """A multi-device DB (the intended layout) holds one entry per device
    kind: scanning past a foreign-device sibling must NOT report a
    mismatch when a same-device entry is then found and applied."""
    from mxnet_tpu.autotune.db import _device_kind
    sym = _zoo_mlp()
    path = str(tmp_path / "db.json")
    db = TuningDB(path)
    for dk, k in (("TPU v5e", 8), (_device_kind(), 2)):
        sig = autotune.symbol_signature(sym)
        db.entries["mlp|%s|b16|img_per_sec" % dk] = {
            "model": "mlp", "objective": "img_per_sec", "kind": "train",
            "global_batch": 16, "device_kind": dk, "platform": "cpu",
            "symbol": sym.name, "symbol_sig": sig,
            "knobs": {"steps_per_dispatch": k}, "score": 1.0,
            "unit": "images/sec"}
    key, entry, note = db.lookup(
        "train", symbol_sig=autotune.symbol_signature(sym),
        global_batch=16)
    assert entry is not None and note is None
    assert entry["knobs"]["steps_per_dispatch"] == 2


def test_mismatch_note_survives_objective_preference_loop(tmp_path,
                                                          monkeypatch):
    """A device-kind mismatch found under the FIRST preferred objective
    must still be reported when later objectives simply have no entries
    (the note accumulates across the preference loop)."""
    from mxnet_tpu.obs import REGISTRY
    sym = _zoo_mlp()
    path = str(tmp_path / "tune_db.json")
    monkeypatch.setenv("MXTPU_AUTOTUNE_DB", path)
    _write_train_entry(path, sym, 16, {"steps_per_dispatch": 8},
                       device_kind="TPU v5e")
    before = REGISTRY.snapshot().get("autotune.db_mismatches", 0)
    key, knobs = autotune.resolve_train_knobs(sym, 16)
    assert knobs is None
    assert REGISTRY.snapshot()["autotune.db_mismatches"] == before + 1


def test_img_per_sec_score_not_inflated_by_label_tokens():
    """An img_per_sec sweep over a multi-dim-label model must report
    samples/sec, not samples*tokens/sec — DB scores stay comparable with
    bench.py's img/s lines; the token multiplier is the tokens_per_sec
    objective's alone."""
    h_img = TrainHarness(model="transformer", batch=4,
                         objective="img_per_sec")
    h_tok = TrainHarness(model="transformer", batch=4,
                         objective="tokens_per_sec")
    assert h_tok.tokens_per_sample == 16  # ZOO transformer seq_len
    # monkey-free check: evaluate() on the same knobs — tokens objective
    # reports ~seq_len x the img objective's rate (same measurement)
    import mxnet_tpu.autotune.harness as _h
    calls = {}

    def fake_measure(step, state, sb, batch, k, depth, ns, nl, rounds=2,
                     warmup=2):
        calls["hit"] = calls.get("hit", 0) + 1
        return 100.0

    real = _h.measure_pipelined_ips
    _h.measure_pipelined_ips = fake_measure
    try:
        s_img = h_img.evaluate({"steps_per_dispatch": 1,
                                "dispatch_pipeline": 0})
        s_tok = h_tok.evaluate({"steps_per_dispatch": 1,
                                "dispatch_pipeline": 0})
    finally:
        _h.measure_pipelined_ips = real
    assert s_img == 100.0
    assert s_tok == 1600.0


def test_train_resolution_prefers_img_per_sec_objective(tmp_path,
                                                        monkeypatch):
    """Two training objectives tuned for one symbol/batch/device: the
    documented preference order (img_per_sec first) picks the entry,
    never key-sort accident."""
    from mxnet_tpu.autotune.db import _device_kind
    sym = _zoo_mlp()
    path = str(tmp_path / "tune_db.json")
    monkeypatch.setenv("MXTPU_AUTOTUNE_DB", path)
    sig = autotune.symbol_signature(sym)
    entries = {}
    # 'a_weird_objective'-style sort traps: img_per_sec sorts AFTER
    # "aaa" and BEFORE "tokens"; insert both real objectives
    for objective, k in (("tokens_per_sec", 8), ("img_per_sec", 2)):
        entries["mlp|%s|b16|%s" % (_device_kind(), objective)] = {
            "model": "mlp", "objective": objective, "kind": "train",
            "global_batch": 16, "device_kind": _device_kind(),
            "platform": "cpu", "symbol": sym.name, "symbol_sig": sig,
            "knobs": {"steps_per_dispatch": k}, "score": 1.0,
            "unit": "x"}
    with open(path, "w") as f:
        json.dump({"schema": SCHEMA_VERSION, "entries": entries}, f)
    key, knobs = autotune.resolve_train_knobs(sym, 16)
    assert knobs["steps_per_dispatch"] == 2
    assert "img_per_sec" in key


def test_corrupt_db_bucket_spec_falls_back_at_serving_load(tmp_path,
                                                           monkeypatch,
                                                           caplog):
    """A hand-edited/corrupt knob value in the DB must never break the
    deploy it configures: the engine warns and uses built-in buckets."""
    from mxnet_tpu import serving
    path = str(tmp_path / "tune_db.json")
    monkeypatch.setenv("MXTPU_AUTOTUNE_DB", path)
    sym, params, shape = _serve_entry(
        path, {"buckets": "0,garbage", "max_latency_ms": "wat"})
    with caplog.at_level(logging.WARNING):
        eng = serving.ServingEngine(sym, params, {"data": shape},
                                    buckets=None)
    assert eng.buckets == (1, 8, 32)  # built-in default
    assert eng._autotuned is None
    assert any("unusable" in r.message for r in caplog.records)


def test_symbol_signature_stable_across_rebuilds_and_discriminating():
    s1 = _zoo_mlp()
    s2 = _zoo_mlp()  # same process, fresh auto-name counters
    assert autotune.symbol_signature(s1) == autotune.symbol_signature(s2)
    other = models.get_symbol("mlp", num_classes=7, hidden=(32,))
    assert autotune.symbol_signature(s1) != autotune.symbol_signature(other)


# -- knob-resolution precedence across Module.fit ---------------------------

def _fit_data(batch=16, n=64):
    rng = np.random.default_rng(0)
    X = rng.normal(size=(n, 64)).astype(np.float32)
    y = rng.integers(0, 4, n).astype(np.float32)
    return mx.io.NDArrayIter(X, y, batch_size=batch)


def _bound_module(sym, batch=16):
    it = _fit_data(batch)
    mod = mx.mod.Module(sym, context=mx.cpu())
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    return mod, it


def test_fit_resolution_precedence(tmp_path, monkeypatch):
    """explicit arg > env > tuning DB > built-in default, per knob."""
    sym = _zoo_mlp()
    path = str(tmp_path / "tune_db.json")
    monkeypatch.setenv("MXTPU_AUTOTUNE_DB", path)
    _write_train_entry(path, sym, 16, {"steps_per_dispatch": 2,
                                       "dispatch_pipeline": 0})
    mod, it = _bound_module(sym)
    # DB wins when nothing else is set
    k, depth, src = autotune.resolve_fit_knobs(mod, it, None, None)
    assert (k, depth) == (2, 0)
    assert src == {"steps_per_dispatch": "db", "dispatch_pipeline": "db"}
    # explicit args beat the DB
    k, depth, src = autotune.resolve_fit_knobs(mod, it, 4, 2)
    assert (k, depth) == (4, 2)
    assert src == {"steps_per_dispatch": "arg", "dispatch_pipeline": "arg"}
    # env beats the DB (pipeline via env var; K via an engine bulk scope)
    monkeypatch.setenv("MXTPU_DISPATCH_PIPELINE", "3")
    with mx.engine.bulk(8):
        k, depth, src = autotune.resolve_fit_knobs(mod, it, None, None)
    assert (k, depth) == (8, 3)
    assert src == {"steps_per_dispatch": "env", "dispatch_pipeline": "env"}
    monkeypatch.delenv("MXTPU_DISPATCH_PIPELINE")
    # an EXPLICIT bulk(1) means "the operator asked for 1" — the DB must
    # not re-enable bulking over it
    with mx.engine.bulk(1):
        k, depth, src = autotune.resolve_fit_knobs(mod, it, None, None)
    assert k == 1 and src["steps_per_dispatch"] == "env"
    # ...and the scope's exit restores "unset": DB resolution is back
    k, _, src = autotune.resolve_fit_knobs(mod, it, None, None)
    assert k == 2 and src["steps_per_dispatch"] == "db"
    # MXTPU_AUTOTUNE=0 disarms the DB: built-in defaults
    monkeypatch.setenv("MXTPU_AUTOTUNE", "0")
    k, depth, src = autotune.resolve_fit_knobs(mod, it, None, None)
    assert (k, depth) == (1, 1)
    assert src == {"steps_per_dispatch": "default",
                   "dispatch_pipeline": "default"}


def test_fit_resolves_db_knobs_end_to_end(tmp_path, monkeypatch, caplog):
    """A fresh Module.fit with NO knob args trains at the DB's K (the
    compiled scan cache keys on it) and logs the resolution once via the
    obs registry."""
    from mxnet_tpu.obs import REGISTRY
    sym = _zoo_mlp()
    path = str(tmp_path / "tune_db.json")
    monkeypatch.setenv("MXTPU_AUTOTUNE_DB", path)
    _write_train_entry(path, sym, 16, {"steps_per_dispatch": 2,
                                       "dispatch_pipeline": 1})
    before = REGISTRY.snapshot().get("autotune.db_resolutions", 0)
    it = _fit_data()
    mod = mx.mod.Module(sym, context=mx.cpu())
    with caplog.at_level(logging.INFO):
        mod.fit(it, num_epoch=1, optimizer_params={"learning_rate": 0.1})
    assert mod._fused is not None
    assert any(key[1] == 2 for key in mod._fused._jit_scan)
    assert REGISTRY.snapshot()["autotune.db_resolutions"] == before + 1
    assert any("tuning DB" in r.message for r in caplog.records)


def test_fit_stale_db_warns_and_uses_defaults(tmp_path, monkeypatch,
                                              caplog):
    sym = _zoo_mlp()
    path = str(tmp_path / "tune_db.json")
    monkeypatch.setenv("MXTPU_AUTOTUNE_DB", path)
    _write_train_entry(path, sym, 16, {"steps_per_dispatch": 2},
                       schema=SCHEMA_VERSION + 1)
    mod, it = _bound_module(sym)
    with caplog.at_level(logging.WARNING):
        k, depth, src = autotune.resolve_fit_knobs(mod, it, None, None)
    assert (k, depth) == (1, 1)
    assert src["steps_per_dispatch"] == "default"
    assert any("schema" in r.message for r in caplog.records)


# -- knob-resolution precedence across ServingEngine ------------------------

def _serve_entry(path, knobs):
    from mxnet_tpu.autotune.db import _device_kind
    from mxnet_tpu.autotune.harness import serve_model
    from mxnet_tpu.predictor import _strip_loss_heads
    name, sym, params, shape = serve_model("mlp")
    sig = autotune.symbol_signature(_strip_loss_heads(sym))
    key = "mlp|%s|b0|serve_p99" % _device_kind()
    entry = {"model": "mlp", "objective": "serve_p99", "kind": "serve",
             "global_batch": 0, "device_kind": _device_kind(),
             "platform": "cpu", "symbol": sym.name, "symbol_sig": sig,
             "knobs": dict(knobs), "score": -5.0, "unit": "ms_p99_neg"}
    with open(path, "w") as f:
        json.dump({"schema": SCHEMA_VERSION, "entries": {key: entry}}, f)
    return sym, params, shape


def test_serving_engine_bucket_precedence(tmp_path, monkeypatch):
    from mxnet_tpu import serving
    path = str(tmp_path / "tune_db.json")
    monkeypatch.setenv("MXTPU_AUTOTUNE_DB", path)
    sym, params, shape = _serve_entry(
        path, {"buckets": "1,4", "max_latency_ms": 2.0})
    # DB wins when neither ctor arg nor env is set — and the Batcher
    # resolves its own knobs from the engine's stashed entry
    eng = serving.ServingEngine(sym, params, {"data": shape})
    assert eng.buckets == (1, 4)
    assert eng._autotuned["max_latency_ms"] == 2.0
    b = serving.Batcher(eng, start=False)
    assert abs(b.max_latency - 0.002) < 1e-12
    # env beats the DB
    monkeypatch.setenv("MXTPU_SERVE_BUCKETS", "1,2")
    eng_env = serving.ServingEngine(sym, params, {"data": shape})
    assert eng_env.buckets == (1, 2)
    assert eng_env._autotuned is None
    monkeypatch.delenv("MXTPU_SERVE_BUCKETS")
    # explicit ctor arg beats everything
    eng_arg = serving.ServingEngine(sym, params, {"data": shape},
                                    buckets=(1, 3))
    assert eng_arg.buckets == (1, 3)
    assert eng_arg._autotuned is None


# -- benchcfg ---------------------------------------------------------------

def test_benv_types_defaults_and_junk(monkeypatch):
    assert benv("BENCH_BATCH") == 128
    monkeypatch.setenv("BENCH_BATCH", "64")
    assert benv("BENCH_BATCH") == 64
    monkeypatch.setenv("BENCH_BATCH", "12q")
    with pytest.raises(MXNetError, match="BENCH_BATCH"):
        benv("BENCH_BATCH")
    monkeypatch.setenv("BENCH_SERVE_QPS", "not-a-number")
    with pytest.raises(MXNetError, match="BENCH_SERVE_QPS"):
        benv("BENCH_SERVE_QPS")
    # flags: unset -> default, off spellings -> False
    assert benv("BENCH_FLEET_DRAIN") is True
    monkeypatch.setenv("BENCH_FLEET_DRAIN", "0")
    assert benv("BENCH_FLEET_DRAIN") is False
    with pytest.raises(MXNetError, match="declared bench knob"):
        benv("BENCH_NOT_A_KNOB")


# -- end-to-end sweep (tiny) ------------------------------------------------

def test_tune_writes_db_and_winner_beats_nothing(tmp_path, monkeypatch):
    """A 2-trial sweep over mlp: the default config is trial #0, the
    winner's measured score >= the default's (it IS the max), the DB
    entry round-trips, and resolution finds it."""
    monkeypatch.setenv("MXTPU_AUTOTUNE_MEASURE", "2,5")
    path = str(tmp_path / "tune_db.json")
    res = autotune.tune(
        model="mlp", objective="img_per_sec", budget=2, batch=8,
        db_path=path, write_db=True, rounds=1,
        space=[autotune.Knob("steps_per_dispatch", (1, 2)),
               autotune.Knob("dispatch_pipeline", (1,))])
    assert res["best"] is not None
    assert res["default"]["knobs"]["steps_per_dispatch"] == 1
    assert res["best"]["score"] >= res["default"]["score"]
    db = TuningDB.load(path)
    key, entry, _ = db.lookup("train", symbol_sig=res["symbol_sig"],
                              global_batch=8)
    assert entry is not None
    assert entry["knobs"] == res["best"]["knobs"]
    assert entry["unit"] == "images/sec"
