"""Packed-accumulator protocol (docs/perf.md "Packed accumulators"):
per-metric device-sums-vs-host parity, composite concatenation, guarded
skip exclusion at 8 devices, bucketed-cache retrace pins, and the SSD
multi-head fit parity — the suite that pins every model in the zoo onto
the fused K-step fast path."""
import logging

import numpy as np
import jax.numpy as jnp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import faults, metric as M, sym, tracecheck
from mxnet_tpu.module import BucketingModule
from mxnet_tpu.test_utils import assert_no_retrace
from mxnet_tpu.train_step import StepMetrics, TrainStep


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


# ---------------------------------------------------------------------------
# per-metric device-sums-vs-host parity (spec.step_sums + spec.fold vs
# metric.update over the SAME arrays)
# ---------------------------------------------------------------------------

def _fold_one_step(metric, spec, outs, labels):
    vals = spec.step_sums([jnp.asarray(o) for o in outs],
                          [jnp.asarray(l) for l in labels])
    spec.fold(metric, {s: float(v) for s, v in zip(spec.slots, vals)})
    return metric


def _probs(rng, n, c):
    p = rng.random((n, c)).astype(np.float32) + 0.05
    return p / p.sum(axis=1, keepdims=True)


_RNG = np.random.default_rng(0)
_OUT = _probs(_RNG, 16, 5)
_LAB = _RNG.integers(0, 5, 16).astype(np.float32)


@pytest.mark.parametrize("make", [
    lambda: M.Accuracy(),
    lambda: M.TopKAccuracy(top_k=3),
    lambda: M.CrossEntropy(),
    lambda: M.CrossEntropy(eps=1e-5),
    lambda: M.MSE(),
    lambda: M.RMSE(),
    lambda: M.MAE(),
    lambda: M.Loss(),
], ids=["acc", "top3", "ce", "ce-eps", "mse", "rmse", "mae", "loss"])
def test_device_sums_match_host_update(make):
    host = make()
    dev = make()
    if isinstance(host, (M.MSE, M.RMSE, M.MAE)):
        outs, labels = [_LAB + 0.25], [_LAB]          # regression pair
        shapes = ([(16,)], [(16,)])
    else:
        outs, labels = [_OUT], [_LAB]
        shapes = ([(16, 5)], [(16,)])
    spec = M.device_sum_spec(dev, *shapes)
    assert spec is not None, type(host).__name__
    host.update([l for l in labels], [o for o in outs])
    _fold_one_step(dev, spec, outs, labels)
    hn, hv = host.get()
    dn, dv = dev.get()
    assert hn == dn
    np.testing.assert_allclose(dv, hv, rtol=1e-6, err_msg=str(hn))
    assert host.num_inst == dev.num_inst


def test_accuracy_any_axis_and_multihead():
    """axis != 1 (SSD-style rank-3 heads) and multiple positional pairs."""
    rng = np.random.default_rng(1)
    o1 = rng.random((4, 6, 3)).astype(np.float32)     # argmax over axis=2
    l1 = rng.integers(0, 3, (4, 6)).astype(np.float32)
    host = M.Accuracy(axis=2)
    dev = M.Accuracy(axis=2)
    spec = M.device_sum_spec(dev, [(4, 6, 3)], [(4, 6)])
    host.update([l1], [o1])
    _fold_one_step(dev, spec, [o1], [l1])
    assert host.get() == dev.get()
    # two heads fold into one correct/n pair, like host's pairwise zip
    host2, dev2 = M.Accuracy(), M.Accuracy()
    o = [_OUT, _probs(rng, 16, 4)]
    l = [_LAB, rng.integers(0, 4, 16).astype(np.float32)]
    spec2 = M.device_sum_spec(dev2, [(16, 5), (16, 4)], [(16,), (16,)])
    host2.update(l, o)
    _fold_one_step(dev2, spec2, o, l)
    assert host2.get() == dev2.get()
    assert dev2.num_inst == 32


def test_perplexity_parity_with_ignore_label():
    rng = np.random.default_rng(2)
    o = _probs(rng, 24, 7)
    l = rng.integers(0, 7, (3, 8)).astype(np.float32)
    host = M.Perplexity(ignore_label=0)
    dev = M.Perplexity(ignore_label=0)
    spec = M.device_sum_spec(dev, [(24, 7)], [(3, 8)])
    assert spec.loss_slots == ("loss", "n")   # guard-watchable CE pair
    host.update([l], [o.reshape(3, 8, 7)])
    _fold_one_step(dev, spec, [o], [l])
    np.testing.assert_allclose(dev.get()[1], host.get()[1], rtol=1e-5)
    assert dev.num_inst == host.num_inst


def test_multibox_parity():
    rng = np.random.default_rng(3)
    b, c, a = 2, 4, 12
    cls_prob = _probs(rng, b * a, c).reshape(b, a, c).transpose(0, 2, 1)
    loc_loss = rng.random((b, a * 4)).astype(np.float32)
    cls_tgt = rng.integers(-1, c, (b, a)).astype(np.float32)
    det = rng.random((b, a, 6)).astype(np.float32)
    outs = [cls_prob, loc_loss, cls_tgt, det]
    host = M.MultiBoxMetric()
    dev = M.MultiBoxMetric()
    spec = M.device_sum_spec(
        dev, [(b, c, a), (b, a * 4), (b, a), (b, a, 6)], [(b, 2, 5)])
    assert spec is not None and spec.loss_slots == ("ce", "n")
    host.update([], outs)
    _fold_one_step(dev, spec, outs, [np.zeros((b, 2, 5), np.float32)])
    np.testing.assert_allclose(dev.get()[1], host.get()[1], rtol=1e-6)


def test_composite_concat_and_fold():
    comp_host = M.create(["acc", "ce"])
    comp_dev = M.create(["acc", "ce"])
    spec = M.device_sum_spec(comp_dev, [(16, 5)], [(16,)])
    assert spec.slots == ("0/correct", "0/n", "1/loss", "1/n")
    assert spec.loss_slots == ("1/loss", "1/n")
    comp_host.update([_LAB], [_OUT])
    _fold_one_step(comp_dev, spec, [_OUT], [_LAB])
    for (hn, hv), (dn, dv) in zip(comp_host.get_name_value(),
                                  comp_dev.get_name_value()):
        assert hn == dn
        np.testing.assert_allclose(dv, hv, rtol=1e-6, err_msg=hn)


def test_custom_metric_opt_in():
    def host_feval(label, pred):
        return float(np.sum(pred)), int(pred.shape[0])

    def dev_sums(outs, labels):
        return jnp.sum(outs[0]), jnp.float32(outs[0].shape[0])

    host = M.CustomMetric(host_feval, name="mysum")
    dev = M.CustomMetric(host_feval, name="mysum",
                         device_step_sums=dev_sums)
    assert M.device_sum_spec(host, [(16, 5)], [(16,)]) is None  # no opt-in
    spec = M.device_sum_spec(dev, [(16, 5)], [(16,)])
    assert spec is not None
    host.update([_LAB], [_OUT])
    _fold_one_step(dev, spec, [_OUT], [_LAB])
    np.testing.assert_allclose(dev.get()[1], host.get()[1], rtol=1e-6)


def test_supports_device_sums_probe_and_subclass_safety():
    assert M.supports_device_sums(M.Accuracy())
    assert M.supports_device_sums(M.CrossEntropy(eps=1e-5))
    assert M.supports_device_sums(M.MSE())
    assert not M.supports_device_sums(M.F1())

    class WeirdAcc(M.Accuracy):    # subclass redefining update()
        def update(self, labels, preds):
            self.sum_metric += 1.0
            self.num_inst += 1
    # subclasses INHERIT the parent's spec — that is the documented
    # contract: redefine device_sum_spec (or return None) when update()
    # semantics change
    assert M.supports_device_sums(WeirdAcc())


# ---------------------------------------------------------------------------
# fit-level parity: regression metric + SSD multi-head, k=1 vs k=4
# ---------------------------------------------------------------------------

def _reg_net():
    data = sym.Variable("data")
    net = sym.FullyConnected(data=data, num_hidden=8, name="fc1")
    net = sym.Activation(data=net, act_type="relu")
    net = sym.FullyConnected(data=net, num_hidden=1, name="fc2")
    return sym.LinearRegressionOutput(data=net, label=sym.Variable(
        "lro_label"), name="lro")


def test_regression_fit_parity_k1_vs_k4():
    """RMSE — the silent-k=1 class the matrix-fact failure lived in —
    rides the packed protocol: same params AND same train metric as the
    k=1 host-update run."""
    def train(k):
        rng = np.random.default_rng(0)
        X = rng.normal(size=(64, 6)).astype(np.float32)
        y = (X.sum(axis=1) * 0.3).astype(np.float32).reshape(-1, 1)
        it = mx.io.NDArrayIter({"data": X}, {"lro_label": y},
                               batch_size=8)
        mod = mx.mod.Module(_reg_net(), label_names=("lro_label",),
                            context=mx.cpu())
        mx.random.seed(11)
        m = M.RMSE()
        mod.fit(it, num_epoch=2, initializer=mx.initializer.Xavier(),
                optimizer_params={"learning_rate": 0.05},
                eval_metric=m, steps_per_dispatch=k)
        return mod.get_params()[0], dict(m.get_name_value())["rmse"]

    p4, rmse4 = train(4)
    p1, rmse1 = train(1)
    for n in p1:
        np.testing.assert_allclose(p4[n].asnumpy(), p1[n].asnumpy(),
                                   rtol=1e-5, atol=1e-6, err_msg=n)
    np.testing.assert_allclose(rmse4, rmse1, rtol=1e-5)


def _ssd_data(n=32, image=32, nobj=2, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, 3, image, image)).astype(np.float32)
    lab = rng.random((n, nobj, 5)).astype(np.float32)
    lab[..., 0] = rng.integers(0, 3, (n, nobj))
    x1 = np.minimum(lab[..., 1], lab[..., 3])
    y1 = np.minimum(lab[..., 2], lab[..., 4])
    lab[..., 3] = np.maximum(lab[..., 1], lab[..., 3]) + 0.05
    lab[..., 4] = np.maximum(lab[..., 2], lab[..., 4]) + 0.05
    lab[..., 1], lab[..., 2] = x1, y1
    return X, lab


def test_ssd_multihead_fit_parity_k1_vs_k4():
    """SSD (rank-3 cls + loc smooth-L1 multi-head) trains through the
    fused K-step scan with MultiBoxMetric — parity vs the k=1 per-step
    run in both final params and the reported metric."""
    from mxnet_tpu import models

    def train(k):
        X, lab = _ssd_data()
        it = mx.io.NDArrayIter({"data": X}, {"label": lab}, batch_size=4)
        symt = models.get_symbol("ssd", num_classes=3, width=8)
        mod = mx.mod.Module(symt, data_names=("data",),
                            label_names=("label",), context=mx.cpu())
        mx.random.seed(13)
        m = M.MultiBoxMetric()
        mod.fit(it, num_epoch=1, initializer=mx.initializer.Xavier(),
                optimizer_params={"learning_rate": 0.01},
                eval_metric=m, steps_per_dispatch=k)
        return mod, m.get_name_value()

    mod4, m4 = train(4)
    assert any(key[1] == 4 for key in mod4._fused._jit_scan)
    assert mod4._fused_metric_spec.slots == ("ce", "l1", "n")
    mod1, m1 = train(1)
    p4, p1 = mod4.get_params()[0], mod1.get_params()[0]
    for n in p1:
        np.testing.assert_allclose(p4[n].asnumpy(), p1[n].asnumpy(),
                                   rtol=1e-4, atol=1e-5, err_msg=n)
    for (n4, v4), (n1, v1) in zip(m4, m1):
        np.testing.assert_allclose(v4, v1, rtol=1e-4, err_msg=n4)


# ---------------------------------------------------------------------------
# guarded skip exclusion at 8 devices: a spec metric's accumulators must
# exclude the device-side no-op step, sharded
# ---------------------------------------------------------------------------

def test_guarded_skip_excluded_from_spec_sums_8dev():
    from mxnet_tpu.guard import TrainingGuard
    rng = np.random.default_rng(5)
    X = rng.normal(size=(128, 10)).astype(np.float32)
    w = rng.normal(size=(10, 4)).astype(np.float32)
    y = np.argmax(X @ w, axis=1).astype(np.float32)
    it = mx.io.NDArrayIter(X, y, batch_size=32)
    data = sym.Variable("data")
    net = sym.FullyConnected(data=data, num_hidden=4, name="fc")
    net = sym.SoftmaxOutput(data=net, name="softmax")
    mod = mx.mod.Module(net, context=[mx.cpu(i) for i in range(8)])
    mx.random.seed(6)
    m = M.create(["acc", "ce"])
    g = TrainingGuard(max_skips_per_window=100, patience=100)
    faults.inject("guard.grad_nan", nth=2)    # poison the 2nd step
    mod.fit(it, num_epoch=1, initializer=mx.initializer.Xavier(),
            optimizer_params={"learning_rate": 0.1},
            eval_metric=m, steps_per_dispatch=4, guard=g)
    assert g.health.skipped == 1
    acc = m.metrics[0]
    # the skipped step's 32 samples never reached the accumulators
    assert acc.num_inst == 128 - 32
    # guarded spec dispatch: one program, sentinels ride the same packed
    # array as the metric slots
    assert any(key[1] == 4 for key in mod._fused._jit_scan_g)
    assert mod._fused._jit_scan == {}


def test_guard_loss_slots_augmentation():
    """A spec with NO watchable loss pair (plain Accuracy) gets hidden
    in-scan CE slots under guard — the guard's EMA keeps observing, the
    metric's fold never sees them."""
    data = sym.Variable("data")
    net = sym.FullyConnected(data=data, num_hidden=4, name="fc")
    net = sym.SoftmaxOutput(data=net, name="softmax")
    ts = TrainStep(net, optimizer="sgd", learning_rate=0.1)
    state = ts.init({"data": (8, 6)}, {"softmax_label": (8,)})
    rng = np.random.default_rng(7)
    sb = {"data": jnp.asarray(rng.normal(size=(2, 8, 6)), jnp.float32),
          "softmax_label": jnp.asarray(
              rng.integers(0, 4, (2, 8)), jnp.float32)}
    spec = M.device_sum_spec(M.Accuracy(), [(8, 4)], [(8,)])
    assert spec.loss_slots is None
    state, sums = ts.run_steps(state, sb, guard=True, metric_spec=spec)
    assert sums.spec.loss_slots == ("__guard_loss", "__guard_n")
    assert sums.num_samples == 16 and np.isfinite(sums.loss_sum)
    acc = M.Accuracy()
    M.update_from_device_sums(acc, sums)
    assert acc.num_inst == 16          # hidden slots never reach the fold
    # unguarded dispatch of the SAME spec carries no hidden slots
    state, sums2 = ts.run_steps(state, sb, metric_spec=spec)
    assert sums2.spec.loss_slots is None
    assert set(sums2.values()) == {"correct", "n"}


# ---------------------------------------------------------------------------
# bucketed-shape jit-cache handling
# ---------------------------------------------------------------------------

def _bucket_sym_gen(key):
    data = sym.Variable("data")
    emb = sym.Embedding(data=data, input_dim=16, output_dim=8,
                        name="shared_embed")
    feat = sym.sum(emb, axis=1)
    pred = sym.FullyConnected(data=feat, num_hidden=8, name="shared_fc")
    return (sym.SoftmaxOutput(data=pred, name="softmax"),
            ("data",), ("softmax_label",))


class _BucketIter(mx.io.DataIter):
    """Deterministic bucketed stream: run-length-grouped bucket keys."""

    def __init__(self, keys, batch=4, seed=0):
        super().__init__(batch)
        rng = np.random.default_rng(seed)
        self.batches = []
        for key in keys:
            self.batches.append(mx.io.DataBatch(
                data=[mx.nd.array(rng.integers(0, 16, (batch, key))
                                  .astype(np.float32))],
                label=[mx.nd.array(rng.integers(0, 8, batch)
                                   .astype(np.float32))],
                pad=0, bucket_key=key,
                provide_data=[mx.io.DataDesc("data", (batch, key))],
                provide_label=[mx.io.DataDesc("softmax_label",
                                              (batch,))]))
        self.i = 0

    @property
    def provide_data(self):
        return [mx.io.DataDesc("data", (4, 10))]

    @property
    def provide_label(self):
        return [mx.io.DataDesc("softmax_label", (4,))]

    def reset(self):
        self.i = 0

    def next(self):
        if self.i >= len(self.batches):
            raise StopIteration
        b = self.batches[self.i]
        self.i += 1
        return b


def _bucketing_fit(keys, k, num_epoch=2, metric=None):
    it = _BucketIter(keys)
    mod = BucketingModule(_bucket_sym_gen, default_bucket_key=10,
                          context=mx.cpu())
    mx.random.seed(21)
    metric = metric if metric is not None else M.create(["acc", "ce"])
    mod.fit(it, num_epoch=num_epoch,
            initializer=mx.initializer.Xavier(),
            optimizer_params={"learning_rate": 0.1},
            eval_metric=metric, steps_per_dispatch=k)
    return mod, metric


def test_bucketed_dispatch_one_program_per_bucket_no_retrace():
    """Interleaved bucket runs: ONE compiled scan per bucket shape,
    revisits are pure cache hits (assert_no_retrace pins), and the
    superbatch grouper cuts at bucket switches so order is preserved."""
    keys = [10] * 4 + [6] * 4 + [10] * 4 + [6] * 4
    mod, _ = _bucketing_fit(keys, 4, num_epoch=1)
    assert sorted(mod._bucket_fused) == [6, 10]
    scans = []
    for key, ts in mod._bucket_fused.items():
        assert len(ts._jit_scan) == 1, (key, list(ts._jit_scan))
        scans += list(ts._jit_scan.values())
    # epoch 2 + 3 over the same bucket cache: zero retraces
    with assert_no_retrace(*scans, msg="bucket revisit"):
        it = _BucketIter(keys)
        mod.fit(it, num_epoch=2,
                optimizer_params={"learning_rate": 0.1},
                eval_metric=M.create(["acc", "ce"]),
                steps_per_dispatch=4)
    assert sorted(mod._bucket_fused) == [6, 10]
    for key, ts in mod._bucket_fused.items():
        assert len(ts._jit_scan) == 1


def test_bucketed_dispatch_parity_vs_per_step():
    """Bucketed fused K-step training == the same batches trained
    per-step through the executor path (forward/backward/update), params
    compared at the end — the scan body is the step body."""
    keys = [10] * 4 + [6] * 4 + [10] * 2       # 2-batch tail on bucket 10
    mod, metric = _bucketing_fit(keys, 4, num_epoch=1)
    assert mod._fused_host_step == len(keys)
    # reference: plain per-step bucketing module over identical batches
    it = _BucketIter(keys)
    ref = BucketingModule(_bucket_sym_gen, default_bucket_key=10,
                          context=mx.cpu())
    # seed BEFORE bind, exactly where _bucketing_fit seeds: bind itself
    # consumes the global stream, so the Xavier draws only match when
    # both paths seed at the same point
    mx.random.seed(21)
    ref.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    ref.init_params(initializer=mx.initializer.Xavier())
    ref.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1})
    ref_metric = M.create(["acc", "ce"])
    for b in it:
        ref.forward(b, is_train=True)
        ref.backward()
        ref.update()
        ref.update_metric(ref_metric, b.label)
    pa, _ = mod.get_params()
    pb, _ = ref.get_params()
    for n in pb:
        np.testing.assert_allclose(pa[n].asnumpy(), pb[n].asnumpy(),
                                   rtol=1e-5, atol=1e-6, err_msg=n)
    for (na, va), (nb, vb) in zip(metric.get_name_value(),
                                  ref_metric.get_name_value()):
        np.testing.assert_allclose(va, vb, rtol=1e-5, err_msg=na)


def test_bucketed_cache_memory_audit_clean():
    """The whole bucket cache audits as a unit: tracecheck + memcheck
    (incl. the resident-set lint over every bucket's compiled scan)."""
    keys = [10] * 4 + [6] * 4
    mod, _ = _bucketing_fit(keys, 4, num_epoch=1)
    findings = [f for f in mod.check(memory=True) if not f.suppressed]
    assert findings == [], [f.format() for f in findings]


def test_bucketed_discard_cut_keeps_iterating():
    """last_group_handle='discard' + a mid-epoch bucket cut: the short
    run is dropped per the discard contract, but the epoch CONTINUES
    into the held bucket — a cut is not the tail."""
    keys = [10] * 2 + [6] * 4 + [10] * 4    # short 10-run, then full runs
    it = _BucketIter(keys)
    sb_iter = mx.io.SuperBatchIter(it, 4, prefetch=False,
                                   last_group_handle="discard")
    seen = [(b.bucket_key, b.num_steps) for b in sb_iter]
    # the 2-batch 10-run was discarded; both full groups still arrived
    assert seen == [(6, 4), (10, 4)]


def test_bucketed_fallback_warns_with_reason(caplog):
    """A metric with no packed layout falls back — warning names it."""
    it = _BucketIter([10] * 4)
    mod = BucketingModule(_bucket_sym_gen, default_bucket_key=10,
                          context=mx.cpu())
    hostonly = M.CustomMetric(
        lambda label, pred: float((np.argmax(pred, 1) == label).mean()),
        name="hostonly")
    with caplog.at_level(logging.WARNING):
        mod.fit(it, num_epoch=1, initializer=mx.initializer.Xavier(),
                optimizer_params={"learning_rate": 0.1},
                eval_metric=hostonly, steps_per_dispatch=4)
    # the K-step SCAN never engaged (host metrics need per-step updates);
    # the metric-independent fused single step may still run
    assert all(ts._jit_scan == {} for ts in mod._bucket_fused.values())
    assert any("steps_per_dispatch=4 unavailable" in r.message
               and "hostonly" in r.message for r in caplog.records)
