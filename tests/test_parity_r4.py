"""Round-4 API-parity fills: PythonModule, FusedRNN initializer,
Executor.reshape flag semantics, heartbeat num_dead_node, signal handler.
Refs: python/mxnet/module/python_module.py, python/mxnet/initializer.py
(FusedRNN), python/mxnet/executor.py (reshape), src/kvstore/
kvstore_dist.h:159-168, src/initialize.cc.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.base import MXNetError


# ---------------------------------------------------------------------------
# PythonModule / PythonLossModule
# ---------------------------------------------------------------------------
def test_python_loss_module_forward_backward():
    def grad_func(scores, labels):
        return scores - labels

    m = mx.mod.PythonLossModule(grad_func=grad_func)
    m.bind(data_shapes=[("data", (4, 3))],
           label_shapes=[("softmax_label", (4, 3))])
    assert m.output_shapes == [("pyloss_output", (4, 3))]
    from mxnet_tpu.io import DataBatch
    s = mx.nd.array(np.ones((4, 3), np.float32) * 2)
    l = mx.nd.array(np.ones((4, 3), np.float32))
    m.forward(DataBatch(data=[s], label=[l]))
    assert m.get_outputs()[0] is s
    m.backward()
    np.testing.assert_array_equal(m.get_input_grads()[0].asnumpy(),
                                  np.ones((4, 3), np.float32))


def test_python_module_bind_contract():
    m = mx.mod.PythonLossModule()
    with pytest.raises(ValueError):
        m.bind(data_shapes=[("wrong_name", (2, 2))])
    m.bind(data_shapes=[("data", (2, 2))],
           label_shapes=[("softmax_label", (2, 2))])
    # rebind without force is a warning no-op
    m.bind(data_shapes=[("data", (8, 8))],
           label_shapes=[("softmax_label", (8, 8))])
    assert m.data_shapes[0][1] == (2, 2)
    assert m.get_params() == ({}, {})


def test_python_loss_module_no_grad_func():
    m = mx.mod.PythonLossModule()
    m.bind(data_shapes=[("data", (2, 2))],
           label_shapes=[("softmax_label", (2, 2))])
    from mxnet_tpu.io import DataBatch
    m.forward(DataBatch(data=[mx.nd.ones((2, 2))],
                        label=[mx.nd.ones((2, 2))]))
    with pytest.raises(NotImplementedError):
        m.backward()


# ---------------------------------------------------------------------------
# FusedRNN initializer
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("mode,bid", [("lstm", False), ("gru", False),
                                      ("lstm", True)])
def test_fused_rnn_initializer(mode, bid):
    from mxnet_tpu.ops.rnn_op import rnn_param_size
    h, nl, isz = 8, 2, 4
    n = rnn_param_size(mode=mode, input_size=isz, state_size=h,
                       num_layers=nl, bidirectional=bid)
    arr = mx.nd.zeros((n,))
    init = mx.initializer.FusedRNN(mx.initializer.Xavier(), h, nl, mode,
                                   bidirectional=bid)
    init(mx.initializer.InitDesc("rnn_parameters"), arr)
    v = arr.asnumpy()
    assert (v != 0).mean() > 0.5          # weights initialized
    if mode == "lstm":
        dirs = 2 if bid else 1
        # forget-gate bias slice == 1.0 in i2h+h2h of every layer*dir
        assert np.isclose(v, 1.0).sum() >= 2 * h * nl * dirs


def test_fused_rnn_initializer_string_init_roundtrip():
    init = mx.initializer.FusedRNN(mx.initializer.Uniform(0.1), 4, 1, "lstm")
    init2 = mx.initializer.FusedRNN(mx.initializer.Uniform(0.1).dumps(),
                                    4, 1, "lstm")
    assert isinstance(init2._init, mx.initializer.Uniform)
    assert "fusedrnn" in init.dumps()


def test_fused_rnn_initializer_matches_unfused_cell_shapes():
    """Unpacked-then-packed layout agrees with FusedRNNCell.unpack."""
    from mxnet_tpu.rnn import rnn_cell
    from mxnet_tpu.ops.rnn_op import rnn_param_size
    h, nl = 6, 2
    n = rnn_param_size(mode="lstm", input_size=h, state_size=h,
                       num_layers=nl, bidirectional=False)
    arr = mx.nd.zeros((n,))
    mx.initializer.FusedRNN(mx.initializer.One(), h, nl, "lstm")(
        mx.initializer.InitDesc("p"), arr)
    cell = rnn_cell.FusedRNNCell(h, nl, "lstm", prefix="")
    args = cell.unpack_weights({"parameters": arr})
    w = args["l0_i2h_weight"].asnumpy()
    assert w.shape == (4 * h, h)
    np.testing.assert_array_equal(w, np.ones_like(w))  # One() everywhere
    b = args["l0_i2h_bias"].asnumpy()
    np.testing.assert_array_equal(b[h:2 * h], np.ones(h))  # forget bias 1.0


# ---------------------------------------------------------------------------
# Executor.reshape flags
# ---------------------------------------------------------------------------
def _bound_fc(batch=4, hidden=8):
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data=data, num_hidden=hidden, name="fc")
    return net.simple_bind(mx.cpu(), data=(batch, 6))


def test_reshape_batch_ok():
    ex = _bound_fc()
    ex2 = ex.reshape(data=(2, 6))
    assert ex2.arg_dict["data"].shape == (2, 6)
    # weights shared, not reallocated
    assert ex2.arg_dict["fc_weight"] is ex.arg_dict["fc_weight"]


def test_reshape_up_sizing_requires_flag():
    ex = _bound_fc(batch=4)
    with pytest.raises(MXNetError, match="allow_up_sizing"):
        ex.reshape(data=(16, 6))
    ex2 = ex.reshape(data=(16, 6), allow_up_sizing=True)
    assert ex2.arg_dict["data"].shape == (16, 6)


def test_reshape_derived_shape_change_requires_partial_shaping():
    ex = _bound_fc()
    # feature-dim change forces fc_weight to change -> derived reshape
    with pytest.raises(MXNetError, match="partial_shaping"):
        ex.reshape(data=(4, 3))
    ex2 = ex.reshape(data=(4, 3), partial_shaping=True)
    assert ex2.arg_dict["fc_weight"].shape == (8, 3)


# ---------------------------------------------------------------------------
# num_dead_node heartbeat
# ---------------------------------------------------------------------------
def test_num_dead_node_local_zero():
    kv = mx.kv.create("local")
    assert kv.num_dead_node(1) == 0


def test_heartbeat_no_client_is_quiet():
    from mxnet_tpu.kvstore import _Heartbeat
    hb = _Heartbeat(rank=0)
    assert hb.dead_nodes(size=1, timeout_sec=1) == 0
    hb.stop()


# ---------------------------------------------------------------------------
# initialize
# ---------------------------------------------------------------------------
def test_signal_handler_installed():
    import faulthandler
    import mxnet_tpu.initialize  # noqa: F401  (import side effect)
    assert faulthandler.is_enabled()
