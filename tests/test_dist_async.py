"""Bounded-staleness dist_async (docs/robustness.md "Elastic distributed
training"). The SSP contract under test: push never blocks; pull blocks
ONLY while this worker is more than S versions ahead of the slowest live
peer, proceeds at lag <= S, drops dead laggards from the window, and a
persistent stall ends in KVStoreTimeoutError — never a hang. Workers are
threads over the in-memory LocalClient plane (``_plane`` injection); no
test sleeps its way to a verdict.
"""
import threading

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.base import MXNetError
from mxnet_tpu.dist_ring import DIST_HEALTH, LocalClient
from mxnet_tpu.kvstore import KVStoreDistAsync, create


def _pair(size=2, staleness=1, timeout=30.0):
    c = LocalClient()
    kvs = [KVStoreDistAsync(_plane=(c, r, size)) for r in range(size)]
    for kv in kvs:
        kv.staleness = staleness
        kv._poll = 0.0
        kv._pull_timeout = timeout
    return c, kvs


def _val(kv, k=3, shape=(4,)):
    out = nd.zeros(shape)
    kv.pull(k, out=out)
    return np.asarray(out.data)


def test_create_returns_async_store():
    kv = create("dist_async")
    assert isinstance(kv, KVStoreDistAsync)
    assert kv.type == "dist_async"
    # single process: the store is fully local, no plane required
    kv.init(3, nd.ones((2,)))
    kv.push(3, nd.ones((2,)) * 4)
    out = nd.zeros((2,))
    kv.pull(3, out=out)
    np.testing.assert_array_equal(np.asarray(out.data), np.full(2, 4.0))


def test_rank0_init_is_authoritative():
    c, (kv0, kv1) = _pair()
    kv0.init(3, nd.ones((4,)) * 7)       # rank 0 publishes
    kv1.init(3, nd.zeros((4,)))          # rank 1 adopts rank 0's value
    np.testing.assert_array_equal(_val(kv1), np.full(4, 7.0))


def test_no_updater_sum_of_latest_pushes():
    c, (kv0, kv1) = _pair(staleness=4)
    kv0.init(3, nd.zeros((4,)))
    kv1.init(3, nd.zeros((4,)))
    kv0.push(3, nd.ones((4,)) * 1)
    kv1.push(3, nd.ones((4,)) * 2)
    # the dist_sync closed form when everyone pushed the same number of
    # times: sum of each worker's latest push
    np.testing.assert_array_equal(_val(kv0), np.full(4, 3.0))
    np.testing.assert_array_equal(_val(kv1), np.full(4, 3.0))
    # a second round overwrites in place, never doubles
    kv0.push(3, nd.ones((4,)) * 10)
    kv1.push(3, nd.ones((4,)) * 20)
    np.testing.assert_array_equal(_val(kv0), np.full(4, 30.0))


def test_updater_applies_each_contribution_exactly_once():
    c, (kv0, kv1) = _pair(staleness=8)
    for kv in (kv0, kv1):
        kv.init(3, nd.zeros((4,)))
        kv._set_updater(lambda k, g, s: s._set_data(s.data + g.data))
    kv0.push(3, nd.ones((4,)))
    kv0.push(3, nd.ones((4,)))
    kv1.push(3, nd.ones((4,)) * 5)
    # delta = visible cumulative total - already applied: repeated pulls
    # are idempotent, interleaved pulls never double-count
    np.testing.assert_array_equal(_val(kv0), np.full(4, 7.0))
    np.testing.assert_array_equal(_val(kv0), np.full(4, 7.0))
    np.testing.assert_array_equal(_val(kv1), np.full(4, 7.0))
    kv1.push(3, nd.ones((4,)))
    np.testing.assert_array_equal(_val(kv1), np.full(4, 8.0))
    np.testing.assert_array_equal(_val(kv0), np.full(4, 8.0))


# -- the staleness window ----------------------------------------------------

def test_pull_proceeds_at_lag_within_window():
    c, (kv0, kv1) = _pair(staleness=2)
    kv0.init(3, nd.zeros((4,)))
    kv1.init(3, nd.zeros((4,)))
    kv0.push(3, nd.ones((4,)))
    kv0.push(3, nd.ones((4,)))   # 2 ahead of rank 1 == S: allowed
    np.testing.assert_array_equal(_val(kv0), np.full(4, 1.0))
    assert kv0.staleness_lag == 2
    assert DIST_HEALTH.staleness_lag == 2


def test_pull_blocks_past_window_and_times_out():
    c, (kv0, kv1) = _pair(staleness=1, timeout=0.05)
    kv0.init(3, nd.zeros((4,)))
    kv1.init(3, nd.zeros((4,)))
    kv0.push(3, nd.ones((4,)))
    kv0.push(3, nd.ones((4,)))   # 2 ahead, S=1: pull must gate
    out = nd.zeros((4,))
    # a started-but-stuck pull escalates through _robust as MXNetError
    # (never retried: the op already started) — the window is named
    with pytest.raises(MXNetError) as ei:
        kv0.pull(3, out=out)
    assert "window S=1" in str(ei.value)


def test_blocked_pull_unblocks_when_laggard_pushes():
    c, (kv0, kv1) = _pair(staleness=1, timeout=30.0)
    kv0.init(3, nd.zeros((4,)))
    kv1.init(3, nd.zeros((4,)))
    kv0.push(3, nd.ones((4,)))
    kv0.push(3, nd.ones((4,)))   # 2 ahead: the pull below gates...

    t = threading.Thread(
        target=lambda: kv1.push(3, nd.ones((4,)) * 3), daemon=True)
    t.start()                    # ...until the laggard's push lands
    got = _val(kv0)
    t.join(30)
    np.testing.assert_array_equal(got, np.full(4, 4.0))
    assert kv0.staleness_lag <= 1


def test_dead_laggard_is_dropped_from_window():
    c, (kv0, kv1) = _pair(staleness=1, timeout=30.0)
    kv0.init(3, nd.zeros((4,)))
    kv1.init(3, nd.zeros((4,)))
    kv1.push(3, nd.ones((4,)) * 9)
    kv0.push(3, nd.ones((4,)))
    kv0.push(3, nd.ones((4,)))
    kv0.push(3, nd.ones((4,)))   # 3 ahead of rank 1, S=1
    c.mark_dead(1)
    # async tolerates loss: the dead laggard stops gating, its LANDED
    # contribution stays in the aggregate
    np.testing.assert_array_equal(_val(kv0), np.full(4, 10.0))
    assert kv0.num_workers == 1
    assert kv0.num_dead_node(0) == 1


def test_push_never_blocks_on_stale_peers():
    c, (kv0, kv1) = _pair(staleness=0, timeout=0.05)
    kv0.init(3, nd.zeros((4,)))
    kv1.init(3, nd.zeros((4,)))
    for _ in range(5):           # far past any window: still instant
        kv0.push(3, nd.ones((4,)))
    assert kv0._ver[3] == 5
