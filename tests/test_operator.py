"""Operator correctness (ref strategy: tests/python/unittest/test_operator.py:
numpy forward references + finite-difference gradient checks)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import sym, nd
from mxnet_tpu.test_utils import (check_numeric_gradient,
                                  check_symbolic_forward, assert_almost_equal)


def test_elementwise_forward():
    x = np.random.rand(3, 4).astype(np.float32) + 0.5
    for name, ref in [("sqrt", np.sqrt), ("exp", np.exp), ("log", np.log),
                      ("abs", np.abs), ("square", np.square),
                      ("sigmoid", lambda v: 1 / (1 + np.exp(-v))),
                      ("tanh", np.tanh)]:
        data = sym.Variable("data")
        s = getattr(sym, name)(data=data)
        check_symbolic_forward(s, {"data": x}, [ref(x)], rtol=1e-4, atol=1e-5)


def test_fully_connected_numeric_grad():
    data = sym.Variable("data")
    fc = sym.FullyConnected(data=data, num_hidden=3, name="fc")
    x = np.random.rand(2, 4).astype(np.float32)
    w = np.random.rand(3, 4).astype(np.float32)
    b = np.random.rand(3).astype(np.float32)
    check_numeric_gradient(fc, {"data": x, "fc_weight": w, "fc_bias": b},
                           numeric_eps=1e-2, rtol=1e-1, atol=1e-2)


def test_convolution_forward():
    # conv vs explicit numpy loop
    x = np.random.rand(1, 1, 5, 5).astype(np.float32)
    w = np.random.rand(2, 1, 3, 3).astype(np.float32)
    b = np.zeros(2, np.float32)
    data = sym.Variable("data")
    conv = sym.Convolution(data=data, kernel=(3, 3), num_filter=2, name="c")
    expected = np.zeros((1, 2, 3, 3), np.float32)
    for f in range(2):
        for i in range(3):
            for j in range(3):
                expected[0, f, i, j] = np.sum(x[0, 0, i:i+3, j:j+3] * w[f, 0])
    check_symbolic_forward(conv, {"data": x, "c_weight": w, "c_bias": b},
                           [expected], rtol=1e-4, atol=1e-4)


def test_convolution_numeric_grad():
    data = sym.Variable("data")
    conv = sym.Convolution(data=data, kernel=(2, 2), num_filter=2, name="c",
                           no_bias=True)
    x = np.random.rand(1, 1, 4, 4).astype(np.float32)
    w = np.random.rand(2, 1, 2, 2).astype(np.float32)
    check_numeric_gradient(conv, {"data": x, "c_weight": w},
                           numeric_eps=1e-2, rtol=1e-1, atol=1e-2)


def test_pooling():
    x = np.random.rand(1, 1, 4, 4).astype(np.float32)
    data = sym.Variable("data")
    pmax = sym.Pooling(data=data, kernel=(2, 2), stride=(2, 2),
                       pool_type="max")
    expected = x.reshape(1, 1, 2, 2, 2, 2).max(axis=(3, 5))
    check_symbolic_forward(pmax, {"data": x}, [expected], rtol=1e-5, atol=1e-6)
    pavg = sym.Pooling(data=data, kernel=(2, 2), stride=(2, 2),
                       pool_type="avg")
    expected = x.reshape(1, 1, 2, 2, 2, 2).mean(axis=(3, 5))
    check_symbolic_forward(pavg, {"data": x}, [expected], rtol=1e-5, atol=1e-6)


def test_global_pooling():
    x = np.random.rand(2, 3, 4, 5).astype(np.float32)
    data = sym.Variable("data")
    p = sym.Pooling(data=data, kernel=(1, 1), global_pool=True,
                    pool_type="avg")
    check_symbolic_forward(p, {"data": x},
                           [x.mean(axis=(2, 3), keepdims=True)],
                           rtol=1e-5, atol=1e-6)


def test_activation_grads():
    # keep |x| > 0.05: finite differences are ill-defined at the relu kink
    rng = np.random.default_rng(3)
    x = rng.uniform(0.05, 0.5, (3, 4)).astype(np.float32)
    x *= rng.choice([-1.0, 1.0], x.shape).astype(np.float32)
    for act in ["relu", "sigmoid", "tanh", "softrelu"]:
        data = sym.Variable("data")
        s = sym.Activation(data=data, act_type=act)
        check_numeric_gradient(s, {"data": x}, numeric_eps=1e-3, rtol=1e-1,
                               atol=1e-2)


def test_leaky_relu():
    x = np.array([[-1.0, 2.0], [-3.0, 4.0]], np.float32)
    data = sym.Variable("data")
    s = sym.LeakyReLU(data=data, act_type="leaky", slope=0.1)
    check_symbolic_forward(s, {"data": x}, [np.where(x > 0, x, 0.1 * x)],
                           rtol=1e-5, atol=1e-6)


def test_batchnorm_train_stats():
    x = np.random.rand(8, 3, 2, 2).astype(np.float32) * 5
    data = sym.Variable("data")
    bn = sym.BatchNorm(data=data, name="bn", fix_gamma=False, eps=1e-5)
    ex = bn.simple_bind(mx.cpu(), data=x.shape)
    ex.arg_dict["data"][:] = x
    ex.arg_dict["bn_gamma"][:] = 1
    ex.arg_dict["bn_beta"][:] = 0
    ex.forward(is_train=True)
    out = ex.outputs[0].asnumpy()
    # per-channel normalized
    assert np.allclose(out.mean(axis=(0, 2, 3)), 0, atol=1e-4)
    assert np.allclose(out.var(axis=(0, 2, 3)), 1, atol=1e-2)


def test_softmax_output_grad():
    # backward produces softmax - onehot
    x = np.random.rand(4, 3).astype(np.float32)
    label = np.array([0.0, 1.0, 2.0, 1.0], np.float32)
    data = sym.Variable("data")
    s = sym.SoftmaxOutput(data=data, name="sm")
    ag = nd.zeros((4, 3))
    ex = s.bind(mx.cpu(), {"data": nd.array(x), "sm_label": nd.array(label)},
                args_grad={"data": ag},
                grad_req={"data": "write", "sm_label": "null"})
    ex.forward(is_train=True)
    ex.backward()
    sm = np.exp(x) / np.exp(x).sum(1, keepdims=True)
    oh = np.eye(3, dtype=np.float32)[label.astype(int)]
    assert_almost_equal(ag.asnumpy(), sm - oh, rtol=1e-4, atol=1e-5)


def test_linear_regression_grad():
    x = np.random.rand(4, 2).astype(np.float32)
    label = np.random.rand(4, 2).astype(np.float32)
    data = sym.Variable("data")
    s = sym.LinearRegressionOutput(data=data, name="lro")
    ag = nd.zeros((4, 2))
    ex = s.bind(mx.cpu(), {"data": nd.array(x), "lro_label": nd.array(label)},
                args_grad={"data": ag},
                grad_req={"data": "write", "lro_label": "null"})
    ex.forward(is_train=True)
    ex.backward()
    assert_almost_equal(ag.asnumpy(), x - label, rtol=1e-5, atol=1e-6)


def test_block_grad():
    a = sym.Variable("a")
    blocked = sym.BlockGrad(data=a * 2) + a
    ag = nd.zeros((3,))
    ex = blocked.bind(mx.cpu(), {"a": nd.ones((3,))}, args_grad={"a": ag})
    ex.forward(is_train=True)
    ex.backward(out_grads=nd.ones((3,)))
    assert np.allclose(ag.asnumpy(), 1.0)  # only the unblocked path


def test_concat_slice_channel():
    xs = [np.random.rand(2, 3).astype(np.float32) for _ in range(3)]
    syms = [sym.Variable("x%d" % i) for i in range(3)]
    cat = sym.Concat(*syms, dim=1)
    ex = cat.bind(mx.cpu(), {("x%d" % i): nd.array(x)
                             for i, x in enumerate(xs)})
    ex.forward()
    assert np.allclose(ex.outputs[0].asnumpy(), np.concatenate(xs, axis=1))

    data = sym.Variable("data")
    sc = sym.SliceChannel(data=data, num_outputs=3, axis=1)
    ex = sc.bind(mx.cpu(), {"data": nd.array(np.concatenate(xs, axis=1))})
    ex.forward()
    for o, x in zip(ex.outputs, xs):
        assert np.allclose(o.asnumpy(), x)


def test_reshape_special_codes():
    x = np.random.rand(2, 3, 4).astype(np.float32)
    data = sym.Variable("data")
    r = sym.Reshape(data=data, shape=(0, -1))
    check_symbolic_forward(r, {"data": x}, [x.reshape(2, 12)], rtol=1e-6)
    r = sym.Reshape(data=data, shape=(-3, 0))
    check_symbolic_forward(r, {"data": x}, [x.reshape(6, 4)], rtol=1e-6)


def test_transpose_swapaxis():
    x = np.random.rand(2, 3, 4).astype(np.float32)
    data = sym.Variable("data")
    check_symbolic_forward(sym.transpose(data=data), {"data": x},
                           [x.T], rtol=1e-6)
    check_symbolic_forward(sym.SwapAxis(data=data, dim1=0, dim2=2),
                           {"data": x}, [np.swapaxes(x, 0, 2)], rtol=1e-6)


def test_embedding():
    idx = np.array([[0.0, 2.0], [1.0, 0.0]], np.float32)
    w = np.random.rand(3, 4).astype(np.float32)
    data = sym.Variable("data")
    emb = sym.Embedding(data=data, input_dim=3, output_dim=4, name="emb")
    check_symbolic_forward(emb, {"data": idx, "emb_weight": w},
                           [w[idx.astype(int)]], rtol=1e-6)


def test_reductions():
    x = np.random.rand(2, 3, 4).astype(np.float32)
    data = sym.Variable("data")
    check_symbolic_forward(sym.sum(data=data, axis=1), {"data": x},
                           [x.sum(1)], rtol=1e-5, atol=1e-6)
    check_symbolic_forward(sym.mean(data=data, axis=(0, 2), keepdims=True),
                           {"data": x}, [x.mean(axis=(0, 2), keepdims=True)],
                           rtol=1e-5, atol=1e-6)
    check_symbolic_forward(sym.argmax(data=data, axis=2), {"data": x},
                           [x.argmax(2).astype(np.float32)], rtol=1e-6)


def test_topk_sort():
    x = np.random.rand(3, 5).astype(np.float32)
    data = sym.Variable("data")
    k = sym.topk(data=data, k=2, ret_typ="value")
    expected = np.sort(x, axis=1)[:, ::-1][:, :2]
    check_symbolic_forward(k, {"data": x}, [expected], rtol=1e-6)
    s = sym.sort(data=data)
    check_symbolic_forward(s, {"data": x}, [np.sort(x, 1)], rtol=1e-6)


def test_where():
    cond = np.array([1.0, 0.0, 1.0], np.float32)
    x = np.array([1.0, 2.0, 3.0], np.float32)
    y = np.array([7.0, 8.0, 9.0], np.float32)
    c, a, b = sym.Variable("c"), sym.Variable("a"), sym.Variable("b")
    w = sym.where(condition=c, x=a, y=b)
    ex = w.bind(mx.cpu(), {"c": nd.array(cond), "a": nd.array(x),
                           "b": nd.array(y)})
    ex.forward()
    assert np.allclose(ex.outputs[0].asnumpy(), [1, 8, 3])


def test_dropout_train_eval():
    data = sym.Variable("data")
    d = sym.Dropout(data=data, p=0.5)
    x = np.ones((100, 100), np.float32)
    ex = d.bind(mx.cpu(), {"data": nd.array(x)})
    ex.forward(is_train=False)
    assert np.allclose(ex.outputs[0].asnumpy(), x)  # identity in eval
    ex.forward(is_train=True)
    out = ex.outputs[0].asnumpy()
    frac_zero = (out == 0).mean()
    assert 0.3 < frac_zero < 0.7
    # kept elements scaled by 1/(1-p)
    assert np.allclose(out[out != 0], 2.0)


def test_sequence_mask():
    x = np.random.rand(4, 2, 3).astype(np.float32)
    seq_len = np.array([2.0, 4.0], np.float32)
    data = sym.Variable("data")
    sl = sym.Variable("sl")
    m = sym.SequenceMask(data=data, sequence_length=sl,
                         use_sequence_length=True, value=-1.0)
    ex = m.bind(mx.cpu(), {"data": nd.array(x), "sl": nd.array(seq_len)})
    ex.forward()
    out = ex.outputs[0].asnumpy()
    assert np.allclose(out[:2, 0], x[:2, 0])
    assert np.allclose(out[2:, 0], -1.0)
    assert np.allclose(out[:, 1], x[:, 1])


def test_elemwise_grad_via_numeric():
    x = np.random.rand(3, 3).astype(np.float32) + 0.1
    a = sym.Variable("a")
    b = sym.Variable("b")
    for op in [lambda: a * b + a, lambda: a / (b + 1), lambda: a ** 2 + b]:
        s = op()
        check_numeric_gradient(s, {"a": x, "b": x + 0.5}, numeric_eps=1e-3,
                               rtol=1e-1, atol=1e-2)


def test_pooling_numeric_grad():
    """Regression: reduce_window init must be a literal for JAX's vjp rule.

    Values are spaced 0.1 apart so the finite-difference eps can never flip a
    max-pool argmax (which would make the numeric gradient ill-defined)."""
    rng = np.random.default_rng(5)
    x = rng.permutation(np.arange(32, dtype=np.float32) * 0.1).reshape(
        1, 2, 4, 4)
    data = sym.Variable("data")
    for ptype in ["max", "avg"]:
        p = sym.Pooling(data=data, kernel=(2, 2), stride=(2, 2),
                        pool_type=ptype)
        check_numeric_gradient(p, {"data": x}, numeric_eps=1e-2, rtol=1e-1,
                               atol=1e-2)
