"""Reference-format interop: NNVM symbol JSON + dmlc binary .params.

Ref contracts: src/nnvm/legacy_json_util.cc (JSON upgrade),
src/ndarray/ndarray.cc:605-693 + include/mxnet/ndarray.h:360-373 (.params).
"""
import json
import os
import struct

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import dmlc_serial

REF_JSON = "/root/reference/tests/python/unittest/save_000800.json"

# Root cause of the two reference-fixture xfails below: save_000800.json is
# the UPSTREAM repo's checked-in legacy-JSON fixture and lives in the
# reference checkout at /root/reference, which is not shipped inside this
# container image. The loader they exercise is covered fixture-free by
# test_repo_legacy_2tuple_format_still_loads / test_nnvm_json_* below; when
# a reference checkout IS mounted, both tests run (and must pass) again.
_ref_fixture_missing = pytest.mark.xfail(
    not os.path.exists(REF_JSON),
    reason="reference checkout not present in this container: %s" % REF_JSON,
    raises=FileNotFoundError, strict=True)


# ---------------------------------------------------------------------------
# symbol JSON
# ---------------------------------------------------------------------------
@_ref_fixture_missing
def test_load_reference_legacy_json():
    sym = mx.symbol.load(REF_JSON)
    args = sym.list_arguments()
    assert args[0] == "data" and "fc1_weight" in args
    # suffix hidden-key migration: "weight_lr_mult" lands on fc1_weight
    ad = sym.attr_dict()
    assert ad["fc1_weight"]["__lr_mult__"] == "1.2"
    assert ad["fc1_weight"]["__wd_mult__"] == "0.3"
    assert ad["fc1_weight"]["ctx_group"] == "stage1"
    # node-level hidden keys migrate too
    assert ad["fc2_weight"]["__lr_mult__"] == "0.01"


@_ref_fixture_missing
def test_legacy_json_binds_and_runs():
    sym = mx.symbol.load(REF_JSON)
    ex = sym.simple_bind(mx.cpu(), data=(4, 10), softmax_label=(4,))
    ex.forward(is_train=False)
    out = ex.outputs[0].asnumpy()
    assert out.shape[0] == 4
    np.testing.assert_allclose(out.sum(axis=1), 1.0, rtol=1e-5)


def test_nnvm_json_shape():
    data = mx.sym.Variable("data", lr_mult=2.0)
    net = mx.sym.FullyConnected(data, num_hidden=8, name="fc")
    net = mx.sym.SoftmaxOutput(net, name="sm")
    d = json.loads(net.tojson())
    assert set(d) == {"nodes", "arg_nodes", "node_row_ptr", "heads", "attrs"}
    assert d["attrs"]["mxnet_version"] == ["int", 905]
    for n in d["nodes"]:
        for e in n["inputs"]:
            assert len(e) == 3 and e[2] == 0
    null_ids = [i for i, n in enumerate(d["nodes"]) if n["op"] == "null"]
    assert d["arg_nodes"] == null_ids
    assert d["node_row_ptr"][0] == 0
    assert d["node_row_ptr"][-1] >= len(d["nodes"])


def test_nnvm_json_roundtrip_semantics():
    data = mx.sym.Variable("data", lr_mult=0.5, wd_mult=2.0)
    w = mx.sym.Variable("w", shape=(8, 10))
    net = mx.sym.FullyConnected(data=data, weight=w, num_hidden=8, name="fc")
    net = mx.sym.Activation(net, act_type="relu", name="r")
    back = mx.sym.load_json(net.tojson())
    assert back.list_arguments() == net.list_arguments()
    assert back.attr_dict()["data"]["__lr_mult__"] == "0.5"
    s1, _, _ = net.infer_shape(data=(4, 10))
    s2, _, _ = back.infer_shape(data=(4, 10))
    assert s1 == s2
    # second-generation JSON identical (stable emission)
    assert back.tojson() == mx.sym.load_json(back.tojson()).tojson()


def test_repo_legacy_2tuple_format_still_loads():
    js = json.dumps({
        "nodes": [
            {"op": "null", "name": "x", "attrs": {}, "user_attrs": {},
             "inputs": []},
            {"op": "relu", "name": "r", "attrs": {}, "user_attrs": {},
             "inputs": [[0, 0]]},
        ],
        "heads": [[1, 0]],
        "mxnet_tpu_version": 1,
    })
    sym = mx.sym.load_json(js)
    assert sym.list_arguments() == ["x"]


def test_unknown_op_raises():
    js = json.dumps({"nodes": [{"op": "NoSuchOp9", "name": "n", "inputs": []}],
                     "arg_nodes": [], "heads": [[0, 0, 0]],
                     "attrs": {"mxnet_version": ["int", 905]}})
    with pytest.raises(mx.base.MXNetError):
        mx.sym.load_json(js)


# ---------------------------------------------------------------------------
# binary .params
# ---------------------------------------------------------------------------
def test_params_header_layout(tmp_path):
    f = str(tmp_path / "x.params")
    mx.nd.save(f, {"w": mx.nd.array(np.arange(6, np.float32).reshape(2, 3)
                                    if False else
                                    np.arange(6, dtype=np.float32).reshape(2, 3))})
    buf = open(f, "rb").read()
    magic, reserved, count = struct.unpack("<QQQ", buf[:24])
    assert magic == 0x112 and reserved == 0 and count == 1
    ndim = struct.unpack("<I", buf[24:28])[0]
    assert ndim == 2
    dims = struct.unpack("<2I", buf[28:36])
    assert dims == (2, 3)
    dev_type, dev_id, type_flag = struct.unpack("<iii", buf[36:48])
    assert dev_type == 1 and type_flag == 0        # kCPU, kFloat32
    vals = np.frombuffer(buf[48:48 + 24], np.float32)
    np.testing.assert_array_equal(vals, np.arange(6, dtype=np.float32))


@pytest.mark.parametrize("dtype", ["float32", "float16", "uint8", "int32",
                                   "bfloat16"])
def test_params_roundtrip_dtypes(tmp_path, dtype):
    f = str(tmp_path / "d.params")
    dt = np.dtype(dtype)
    a = (np.random.rand(3, 5) * 10).astype(dt)
    mx.nd.save(f, {"a": mx.nd.array(a, dtype=dt)})
    b = mx.nd.load(f)["a"].asnumpy()
    assert b.dtype == dt
    np.testing.assert_array_equal(np.asarray(a, np.float64),
                                  np.asarray(b, np.float64))


@pytest.mark.parametrize("dtype", ["float64", "int64"])
def test_params_wire_dtypes_beyond_jax_default(dtype):
    """f64/i64 survive the wire format itself (JAX x64-off narrows NDArrays,
    so these are exercised at the serializer layer)."""
    a = (np.random.rand(4, 3) * 9).astype(dtype)
    arrs, names = dmlc_serial.loads(dmlc_serial.dumps([a], ["a"]))
    assert names == ["a"] and arrs[0].dtype == np.dtype(dtype)
    np.testing.assert_array_equal(arrs[0], a)


def test_params_list_roundtrip(tmp_path):
    f = str(tmp_path / "l.params")
    data = [mx.nd.ones((2, 2)), mx.nd.zeros((3,))]
    mx.nd.save(f, data)
    back = mx.nd.load(f)
    assert isinstance(back, list) and len(back) == 2
    np.testing.assert_array_equal(back[0].asnumpy(), np.ones((2, 2), np.float32))


def test_params_bit_exact_double_roundtrip(tmp_path):
    f1, f2 = str(tmp_path / "a.params"), str(tmp_path / "b.params")
    data = {"x": mx.nd.array(np.random.randn(4, 7).astype(np.float32)),
            "y": mx.nd.array(np.random.randn(9).astype(np.float32))}
    mx.nd.save(f1, data)
    mx.nd.save(f2, mx.nd.load(f1))
    assert open(f1, "rb").read() == open(f2, "rb").read()


def test_legacy_npz_still_loads(tmp_path):
    f = str(tmp_path / "legacy.npz")
    np.savez(open(f, "wb"), w=np.ones((2, 2), np.float32))
    back = mx.nd.load(f)
    np.testing.assert_array_equal(back["w"].asnumpy(), np.ones((2, 2)))


def test_module_checkpoint_reference_format(tmp_path):
    """Module.save_checkpoint emits a reference-openable pair."""
    data = mx.sym.Variable("data")
    net = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(data, num_hidden=4, name="fc"), name="softmax")
    mod = mx.mod.Module(net, data_names=("data",), label_names=("softmax_label",))
    mod.bind(data_shapes=[("data", (2, 6))], label_shapes=[("softmax_label", (2,))])
    mod.init_params()
    prefix = str(tmp_path / "m")
    mod.save_checkpoint(prefix, 3)
    buf = open(prefix + "-0003.params", "rb").read()
    assert dmlc_serial.sniff(buf)
    arrs, names = dmlc_serial.loads(buf)
    assert any(n.startswith("arg:") for n in names)
    sym = mx.symbol.load(prefix + "-symbol.json")
    assert "fc_weight" in sym.list_arguments()
    sym2, args, auxs = mx.model.load_checkpoint(prefix, 3)
    np.testing.assert_array_equal(
        args["fc_weight"].asnumpy(),
        mod.get_params()[0]["fc_weight"].asnumpy())
