"""Data-parallel multi-chip training through the fused K-step scan
(docs/perf.md "Data-parallel scaling").

The suite runs on the conftest-provided 8-device virtual CPU mesh: a
Module over N contexts trains the SAME fused ``lax.scan`` dispatch sharded
over an N-way 'data' mesh — superbatches land per-chip sharded off the
producer thread, params/optimizer state replicate, the gradient psum rides
inside the donated body, and the guard + checkpoint/resume stack composes
unchanged.
"""
import os
import signal
import subprocess
import sys
import time

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import faults, sym, tracecheck
from mxnet_tpu.base import MXNetError
from mxnet_tpu.parallel.mesh import (data_parallel_mesh, data_axis_size,
                                     superbatch_sharding)
from mxnet_tpu.train_step import TrainStep

P = jax.sharding.PartitionSpec


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


def _mlp():
    data = sym.Variable("data")
    net = sym.FullyConnected(data=data, num_hidden=16, name="fc1")
    net = sym.Activation(data=net, act_type="relu")
    net = sym.FullyConnected(data=net, num_hidden=4, name="fc2")
    return sym.SoftmaxOutput(data=net, name="softmax")


def _fit_data(n=128, batch=32):
    rng = np.random.default_rng(3)
    X = rng.normal(size=(n, 10)).astype(np.float32)
    w = rng.normal(size=(10, 4)).astype(np.float32)
    y = np.argmax(X @ w, axis=1).astype(np.float32)
    return mx.io.NDArrayIter(X, y, batch_size=batch), X, y


def _fit(nctx, k=2, num_epoch=2, guard=None, seed=7, **kw):
    mx.random.seed(seed)
    it, X, y = _fit_data()
    ctx = [mx.cpu(i) for i in range(nctx)] if nctx > 1 else mx.cpu()
    mod = mx.mod.Module(_mlp(), context=ctx)
    mod.fit(it, num_epoch=num_epoch, steps_per_dispatch=k, guard=guard,
            optimizer_params={"learning_rate": 0.1, "momentum": 0.9}, **kw)
    return mod


def test_mesh_helpers():
    mesh = data_parallel_mesh(8)
    assert data_axis_size(mesh) == 8
    assert data_axis_size(None) == 1
    s = superbatch_sharding(mesh)
    assert s.spec == P(None, "data")
    assert superbatch_sharding(None) is None


def test_sharded_fused_fit_matches_single_device():
    """Same seed, same global batch: the 8-device sharded fused fit must
    match the single-device fused fit numerically — the psum'd gradient is
    the same sum the one-chip backward computes."""
    a = _fit(1).get_params()[0]
    b = _fit(8).get_params()[0]
    for n in a:
        np.testing.assert_allclose(a[n].asnumpy(), b[n].asnumpy(),
                                   rtol=1e-4, atol=1e-5, err_msg=n)


def test_sharded_fit_engages_mesh_and_superbatch_sharding():
    mod = _fit(8)
    assert mod._fused is not None and mod._fused.mesh is not None
    assert data_axis_size(mod._fused.mesh) == 8
    sh = mod._superbatch_sharding()
    assert sh is not None and sh.spec == P(None, "data")
    # single-device module: no sharding handed to the producer
    assert _fit(1)._superbatch_sharding() is None


def test_superbatch_iter_lands_sharded():
    """With ``sharding=``, the producer's H2D IS the scatter: every stacked
    array carries the (None, 'data') NamedSharding, so the dispatch-side
    device_put is a no-op (same committed array, no resharding copy)."""
    mesh = data_parallel_mesh(8)
    sh = superbatch_sharding(mesh)
    it, _, _ = _fit_data()
    sb_it = it.superbatch(2, sharding=sh)
    try:
        batch = next(iter(sb_it))
        for arr in batch.data + batch.label:
            assert arr.data.sharding == sh, arr.data.sharding
        ts = TrainStep(_mlp(), optimizer="sgd", mesh=mesh)
        placed = ts.shard_superbatch(
            {"data": batch.data[0], "softmax_label": batch.label[0]})
        # already-sharded input passes through without a new buffer
        assert placed["data"] is batch.data[0].data
    finally:
        sb_it.close()


def test_sharded_fit_no_retrace_across_dispatches():
    """Epochs of sharded dispatches reuse ONE compiled scan program: the
    producer-landed sharding matches what the jit cache keyed on, so no
    dispatch re-traces (docs/static_analysis.md)."""
    from mxnet_tpu.test_utils import assert_no_retrace
    with assert_no_retrace(msg="8-device sharded fit"):
        mod = _fit(8, num_epoch=3)
    assert mod._fused._jit_scan  # the scan path actually ran


def test_sharded_scan_donation_and_collectives_clean():
    """tracecheck over the SHARDED program set: donation must survive
    sharding (state buffers alias outputs shard-for-shard) and the
    compiled partitioned scan body may sync only by all-reduce — the
    grad/metric psum, nothing gather-shaped (collective-in-scan lint)."""
    mesh = data_parallel_mesh(8)
    ts = TrainStep(_mlp(), optimizer="sgd", learning_rate=0.1, momentum=0.9,
                   mesh=mesh)
    k, bs = 2, 32
    state = ts.init({"data": (bs, 10)}, {"softmax_label": (bs,)})
    rng = np.random.default_rng(0)
    sb = ts.shard_superbatch({
        "data": rng.normal(size=(k, bs, 10)).astype(np.float32),
        "softmax_label": rng.integers(0, 4, (k, bs)).astype(np.float32)})
    fn = ts._build_scan(bs, k)
    lrs = jnp.asarray(np.asarray([0.1] * k, np.float32))
    args = (state, sb, ts._dispatch_key(), lrs)
    findings = tracecheck.check_program(fn, args, donate_argnums=(0,),
                                        name="dp8/mlp-scan")
    findings += tracecheck.check_collectives(fn, args, name="dp8/mlp-scan")
    bad = tracecheck.unsuppressed(findings)
    assert not bad, [f.format() for f in bad]


def test_check_collectives_flags_batch_gather():
    """Regression for the in-scan metric gather: the fancy-index
    ``o[arange(bs), label]`` form loses the batch-dim alignment GSPMD
    needs and lowers to all-gathers INSIDE the scan body — exactly what
    ``check_collectives`` must flag (the shipped ``_metric_step_sums``
    uses take_along_axis and stays clean, previous test)."""
    mesh = data_parallel_mesh(8)
    sh = jax.sharding.NamedSharding(mesh, P(None, "data"))

    def scan_fancy(os_, lis):
        def body(c, xs):
            o, li = xs
            return c + jnp.sum(o[jnp.arange(o.shape[0]), li]), None
        out, _ = jax.lax.scan(body, jnp.float32(0), (os_, lis))
        return out

    rng = np.random.default_rng(0)
    os_ = jax.device_put(rng.normal(size=(2, 32, 4)).astype(np.float32), sh)
    lis = jax.device_put(rng.integers(0, 4, (2, 32)).astype(np.int32), sh)
    findings = tracecheck.check_collectives(jax.jit(scan_fancy), (os_, lis),
                                            name="fancy-gather")
    assert any(f.lint == "collective-in-scan" for f in findings), \
        "fancy-index batch gather must be flagged"


def test_guard_composes_on_mesh():
    """guard.grad_nan at 8 devices: the poisoned step is a GLOBAL no-op
    (every chip takes the same select), the skip rides the packed sentinel
    readback, and params stay finite."""
    mesh = data_parallel_mesh(8)
    ts = TrainStep(_mlp(), optimizer="sgd", learning_rate=0.1, momentum=0.9,
                   mesh=mesh)
    K, bs = 4, 16
    state = ts.init({"data": (bs, 10)}, {"softmax_label": (bs,)})
    rng = np.random.default_rng(0)
    sb = ts.shard_superbatch({
        "data": rng.normal(size=(K, bs, 10)).astype(np.float32),
        "softmax_label": rng.integers(0, 4, (K, bs)).astype(np.float32)})
    faults.inject("guard.grad_nan", nth=2)
    state, m = ts.run_steps(state, sb, guard=True)
    assert m.skipped == 1
    assert m.num_samples == (K - 1) * bs
    assert int(np.asarray(state["step"])) == K - 1
    for n in ts.param_names:
        assert np.isfinite(np.asarray(state["params"][n])).all(), n


def test_sharded_checkpoint_resume_bitwise(tmp_path):
    """The PR 2 stack at 8 devices: fit to an epoch-end checkpoint, resume
    in a FRESH module, finish — final params bitwise-equal to the
    uninterrupted 8-device run (replicated params are identical on every
    chip, so the host snapshot is exact)."""
    full = _fit(8, checkpoint_prefix=str(tmp_path / "a" / "ck"))
    _fit(8, num_epoch=1, checkpoint_prefix=str(tmp_path / "b" / "ck"))
    resumed = _fit(8, checkpoint_prefix=str(tmp_path / "b" / "ck"),
                   resume="auto")
    a, b = full.get_params()[0], resumed.get_params()[0]
    for n in a:
        np.testing.assert_array_equal(a[n].asnumpy(), b[n].asnumpy(),
                                      err_msg=n)


def test_shard_batch_rejects_indivisible_batch():
    mesh = data_parallel_mesh(8)
    ts = TrainStep(_mlp(), optimizer="sgd", mesh=mesh)
    with pytest.raises(MXNetError, match="does not divide"):
        ts.shard_batch({"data": np.zeros((6, 10), np.float32)})
    with pytest.raises(MXNetError, match="does not divide"):
        ts.shard_superbatch({"data": np.zeros((2, 6, 10), np.float32)})


def test_bulk_dispatch_precheck_rejects_indivisible_batch():
    mod = mx.mod.Module(_mlp(), context=[mx.cpu(i) for i in range(8)])
    it = mx.io.NDArrayIter(np.zeros((36, 10), np.float32),
                           np.zeros((36,), np.float32), batch_size=36)
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label,
             for_training=True)
    mod.init_params()
    mod.init_optimizer()
    ok, why = mod._can_bulk_dispatch()
    assert not ok and "does not divide" in why


def test_dp_devices_env(monkeypatch):
    """MXTPU_DP_DEVICES=N spreads a context-less Module over N devices;
    an over-ask fails actionably naming the XLA_FLAGS knob."""
    monkeypatch.setenv("MXTPU_DP_DEVICES", "4")
    mod = mx.mod.Module(_mlp())
    assert len(mod._context) == 4
    assert len({c.to_device() for c in mod._context}) == 4
    monkeypatch.setenv("MXTPU_DP_DEVICES", "4096")
    with pytest.raises(MXNetError, match="xla_force_host_platform"):
        mx.mod.Module(_mlp())
    monkeypatch.setenv("MXTPU_DP_DEVICES", "zoom")
    with pytest.raises(MXNetError, match="MXTPU_DP_DEVICES"):
        mx.mod.Module(_mlp())


class _FakeDistModule(object):
    def _global_batch_scale(self):
        return 4


def test_speedometer_reports_global_img_per_sec(caplog):
    """Under multi-process data parallelism each worker's iterator yields
    its LOCAL shard; the Speedometer line must report GLOBAL img/s —
    per-chip local batch x axis size (here scale 4)."""
    import logging
    from mxnet_tpu.callback import Speedometer
    from mxnet_tpu.module.base_module import BatchEndParam

    def fire(mod):
        spd = Speedometer(batch_size=16, frequent=2)
        t0 = time.time() - 1.0  # ~1s window
        spd(BatchEndParam(epoch=0, nbatch=0, eval_metric=None,
                          locals={"self": mod}))
        spd.tic = t0
        spd(BatchEndParam(epoch=0, nbatch=2, eval_metric=None,
                          locals={"self": mod}))
        for rec in caplog.records:
            if "Speed:" in rec.getMessage():
                return float(rec.getMessage().split("Speed: ")[1]
                             .split(" ")[0])
        raise AssertionError("Speedometer did not fire")

    with caplog.at_level(logging.INFO):
        local = fire(object())            # no scale hook -> per-process
    caplog.clear()
    with caplog.at_level(logging.INFO):
        scaled = fire(_FakeDistModule())  # dist module -> x4
    assert 0.8 * 4 < scaled / local < 1.2 * 4, (local, scaled)


def test_module_global_batch_scale_defaults_to_one():
    mod = _fit(8)
    assert mod._global_batch_scale() == 1


# -- the real thing: SIGKILL an 8-device run and resume it ------------------

@pytest.mark.slow
def test_sharded_sigkill_and_resume_bitwise_identical(tmp_path):
    """SIGKILL a chip-count-8 fused run mid-epoch and re-launch it: the
    resumed run must produce bitwise-identical final params to an
    uninterrupted 8-device run — the PR 2 contract, unchanged by
    sharding."""
    worker = os.path.join(os.path.dirname(__file__), "resume_worker.py")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               RESUME_WORKER_CONTEXTS="8",
               XLA_FLAGS=(os.environ.get("XLA_FLAGS", "")
                          + " --xla_force_host_platform_device_count=8"))

    def launch(prefix, out):
        return subprocess.Popen(
            [sys.executable, worker, prefix, out, "2"],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, env=env)

    ref_out = str(tmp_path / "ref.npz")
    p = launch(str(tmp_path / "ref-ck"), ref_out)
    assert p.wait(timeout=600) == 0, p.stdout.read()

    prefix = str(tmp_path / "ck")
    out = str(tmp_path / "resumed.npz")
    p = launch(prefix, out)
    killed = False
    deadline = time.monotonic() + 600
    for line in p.stdout:
        if line.startswith("BATCH 1.") and time.monotonic() < deadline:
            os.kill(p.pid, signal.SIGKILL)
            killed = True
            break
    p.wait(timeout=60)
    assert killed, "worker finished before it could be killed"
    assert not os.path.exists(out)

    p = launch(prefix, out)
    assert p.wait(timeout=600) == 0, p.stdout.read()

    ref = np.load(ref_out)
    got = np.load(out)
    assert sorted(ref.files) == sorted(got.files)
    for name in ref.files:
        np.testing.assert_array_equal(ref[name], got[name], err_msg=name)
