"""Fused train step: optimizer-zoo equivalence and the Module fast path.

The fused step is the TPU analog of the reference's in-graph optimizer
update ops + update_on_kvstore fast path (ref:
src/operator/optimizer_op-inl.h, python/mxnet/model.py:88-117). These tests
assert the fused jit produces the SAME numbers as the imperative
Executor + Updater path for every optimizer in the zoo, and that Module.fit
actually trains through it.
"""
import numpy as np
import jax.numpy as jnp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import optimizer as opt
from mxnet_tpu.executor import simple_bind
from mxnet_tpu.train_step import TrainStep


def _mlp():
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=16, name="fc1")
    net = mx.sym.Activation(net, act_type="tanh")
    net = mx.sym.FullyConnected(net, num_hidden=4, name="fc2")
    return mx.sym.SoftmaxOutput(net, name="softmax")


ZOO = [
    ("sgd", dict(momentum=0.9)),
    ("sgd", dict(momentum=0.0)),
    ("sgd", dict(momentum=0.9, clip_gradient=0.02)),
    ("nag", dict(momentum=0.9)),
    ("dcasgd", dict(momentum=0.9)),
    ("adam", {}),
    ("adagrad", {}),
    ("rmsprop", {}),
    ("rmsprop", dict(centered=True)),
    ("adadelta", {}),
    ("ftrl", {}),
    ("test", {}),
]


@pytest.mark.parametrize("name,kwargs", ZOO)
def test_fused_matches_imperative(name, kwargs):
    net = _mlp()
    rng = np.random.default_rng(0)
    X = rng.normal(size=(8, 10)).astype(np.float32)
    y = rng.integers(0, 4, 8).astype(np.float32)
    batch = {"data": jnp.asarray(X), "softmax_label": jnp.asarray(y)}

    def mk():
        o = opt.create(name, learning_rate=0.05, rescale_grad=1.0 / 8,
                       **kwargs)
        o.wd = 1e-3
        return o

    step = TrainStep(net, optimizer=mk())
    state = step.init({"data": (8, 10)}, {"softmax_label": (8,)}, seed=1)

    ex = simple_bind(net, mx.cpu(), grad_req="write", data=(8, 10),
                     softmax_label=(8,))
    for n in step.param_names:
        # copy: the fused step donates its state buffers
        ex.arg_dict[n]._set_data(jnp.copy(state["params"][n]))
    upd = opt.get_updater(mk())

    for _ in range(3):
        state, _outs = step.step(state, batch)
        ex.forward(is_train=True, data=X, softmax_label=y)
        ex.backward()
        for i, n in enumerate(step.param_names):
            upd(i, ex.grad_dict[n], ex.arg_dict[n])

    for n in step.param_names:
        np.testing.assert_allclose(
            np.asarray(state["params"][n]), ex.arg_dict[n].asnumpy(),
            atol=2e-5, rtol=2e-5, err_msg="%s/%s" % (name, n))


def test_fused_lr_scheduler_and_mults():
    """lr_scheduler + lr_mult/wd_mult must flow into the fused update."""
    net = _mlp()
    rng = np.random.default_rng(1)
    X = rng.normal(size=(8, 10)).astype(np.float32)
    y = rng.integers(0, 4, 8).astype(np.float32)
    batch = {"data": jnp.asarray(X), "softmax_label": jnp.asarray(y)}

    def mk():
        o = opt.create("sgd", learning_rate=0.1, momentum=0.9,
                       rescale_grad=1.0 / 8,
                       lr_scheduler=mx.lr_scheduler.FactorScheduler(
                           step=2, factor=0.5),
                       param_idx2name={0: "fc1_weight", 1: "fc1_bias",
                                       2: "fc2_weight", 3: "fc2_bias"})
        o.wd = 1e-2
        o.set_lr_mult({"fc1_weight": 0.3})
        o.set_wd_mult({"fc2_weight": 2.0})
        return o

    step = TrainStep(net, optimizer=mk())
    state = step.init({"data": (8, 10)}, {"softmax_label": (8,)}, seed=2)

    ex = simple_bind(net, mx.cpu(), grad_req="write", data=(8, 10),
                     softmax_label=(8,))
    for n in step.param_names:
        ex.arg_dict[n]._set_data(jnp.copy(state["params"][n]))
    imp = mk()
    upd = opt.get_updater(imp)
    idx_of = {n: i for i, n in enumerate(
        ["fc1_weight", "fc1_bias", "fc2_weight", "fc2_bias"])}

    for _ in range(5):  # crosses the scheduler step boundary
        state, _ = step.step(state, batch)
        ex.forward(is_train=True, data=X, softmax_label=y)
        ex.backward()
        for n in step.param_names:
            upd(idx_of[n], ex.grad_dict[n], ex.arg_dict[n])

    for n in step.param_names:
        np.testing.assert_allclose(
            np.asarray(state["params"][n]), ex.arg_dict[n].asnumpy(),
            atol=2e-5, rtol=2e-5, err_msg=n)


def _fit_data(batch_size=16, n=64, shuffle=True):
    rng = np.random.default_rng(3)
    templates = rng.normal(size=(4, 10)).astype(np.float32)
    X = templates[rng.integers(0, 4, n)] \
        + 0.05 * rng.normal(size=(n, 10)).astype(np.float32)
    y = np.argmin(((X[:, None, :] - templates[None]) ** 2).sum(-1),
                  axis=1).astype(np.float32)
    return (mx.io.NDArrayIter(X, y, batch_size=batch_size, shuffle=shuffle),
            X, y)


def test_module_fit_uses_fused_path():
    net = _mlp()
    it, X, y = _fit_data()
    mod = mx.mod.Module(net)
    mod.fit(it, num_epoch=4, initializer=mx.initializer.Xavier(),
            optimizer_params={"learning_rate": 0.2})
    assert mod._fused is not None, "fit() did not engage the fused path"
    assert int(np.asarray(mod._fused_state["step"])) > 0
    val = mx.io.NDArrayIter(X, y, batch_size=16)
    acc = dict(mod.score(val, mx.metric.Accuracy()))
    assert acc["accuracy"] > 0.9, acc


def test_module_fit_fused_equals_executor_path():
    """Same seed, same data: fused fit must equal the executor-path fit."""
    def train(disable_fused):
        net = _mlp()
        it, X, y = _fit_data(shuffle=False)  # identical batch order
        mod = mx.mod.Module(net)
        if disable_fused:
            mod._fused_ok = False
        mx.random.seed(7)
        mod.fit(it, num_epoch=2, initializer=mx.initializer.Xavier(),
                optimizer_params={"learning_rate": 0.1, "momentum": 0.9})
        return mod.get_params()[0]

    a = train(False)
    b = train(True)
    for n in a:
        np.testing.assert_allclose(a[n].asnumpy(), b[n].asnumpy(),
                                   atol=1e-5, rtol=1e-5, err_msg=n)


def test_module_fused_checkpoint_roundtrip(tmp_path):
    net = _mlp()
    it, X, y = _fit_data()
    mod = mx.mod.Module(net)
    mod.fit(it, num_epoch=2, initializer=mx.initializer.Xavier(),
            optimizer_params={"learning_rate": 0.1, "momentum": 0.9})
    prefix = str(tmp_path / "fused_ckpt")
    mod.save_checkpoint(prefix, 2, save_optimizer_states=True)

    mod2 = mx.mod.Module.load(prefix, 2, load_optimizer_states=True)
    mod2.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod2.init_params()
    mod2.init_optimizer(optimizer_params={"learning_rate": 0.1,
                                          "momentum": 0.9})
    # predictions identical after round trip
    val = mx.io.NDArrayIter(X, y, batch_size=16)
    p1 = mod.predict(val).asnumpy()
    val = mx.io.NDArrayIter(X, y, batch_size=16)
    p2 = mod2.predict(val).asnumpy()
    np.testing.assert_allclose(p1, p2, atol=1e-6)
    # momentum state survived into the new module's fused seed
    it.reset()
    batch = next(iter(it))
    assert mod2._try_fused_fit_step(batch)
    mom = mod2._fused_state["opt"]["fc1_weight"]
    assert float(jnp.abs(mom).max()) > 0.0


def test_module_fixed_params_stay_fixed():
    net = _mlp()
    it, X, y = _fit_data()
    mod = mx.mod.Module(net, fixed_param_names=["fc1_weight"])
    mod.fit(it, num_epoch=2, initializer=mx.initializer.Xavier(),
            optimizer_params={"learning_rate": 0.2})
    assert mod._fused is not None
    w0 = np.asarray(mod._fused_state["params"]["fc1_weight"])
    it.reset()
    for batch in it:
        assert mod._try_fused_fit_step(batch)
    np.testing.assert_array_equal(
        w0, np.asarray(mod._fused_state["params"]["fc1_weight"]))
