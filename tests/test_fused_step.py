"""Fused train step: optimizer-zoo equivalence and the Module fast path.

The fused step is the TPU analog of the reference's in-graph optimizer
update ops + update_on_kvstore fast path (ref:
src/operator/optimizer_op-inl.h, python/mxnet/model.py:88-117). These tests
assert the fused jit produces the SAME numbers as the imperative
Executor + Updater path for every optimizer in the zoo, and that Module.fit
actually trains through it.
"""
import numpy as np
import jax.numpy as jnp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import optimizer as opt
from mxnet_tpu.executor import simple_bind
from mxnet_tpu.train_step import TrainStep


def _mlp():
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=16, name="fc1")
    net = mx.sym.Activation(net, act_type="tanh")
    net = mx.sym.FullyConnected(net, num_hidden=4, name="fc2")
    return mx.sym.SoftmaxOutput(net, name="softmax")


ZOO = [
    ("sgd", dict(momentum=0.9)),
    ("sgd", dict(momentum=0.0)),
    # clip_gradient parity across the update families: the fused
    # _fused_clip and the imperative nd.clip paths must produce identical
    # params (guards clip_global_norm against the same drift)
    ("sgd", dict(momentum=0.9, clip_gradient=0.02)),
    ("sgd", dict(momentum=0.0, clip_gradient=0.02)),
    ("adam", dict(clip_gradient=0.02)),
    ("nag", dict(momentum=0.9)),
    ("dcasgd", dict(momentum=0.9)),
    ("adam", {}),
    ("adagrad", {}),
    ("rmsprop", {}),
    ("rmsprop", dict(centered=True)),
    ("adadelta", {}),
    ("ftrl", {}),
    ("test", {}),
]


@pytest.mark.parametrize("name,kwargs", ZOO)
def test_fused_matches_imperative(name, kwargs):
    net = _mlp()
    rng = np.random.default_rng(0)
    X = rng.normal(size=(8, 10)).astype(np.float32)
    y = rng.integers(0, 4, 8).astype(np.float32)
    batch = {"data": jnp.asarray(X), "softmax_label": jnp.asarray(y)}

    def mk():
        o = opt.create(name, learning_rate=0.05, rescale_grad=1.0 / 8,
                       **kwargs)
        o.wd = 1e-3
        return o

    step = TrainStep(net, optimizer=mk())
    state = step.init({"data": (8, 10)}, {"softmax_label": (8,)}, seed=1)

    ex = simple_bind(net, mx.cpu(), grad_req="write", data=(8, 10),
                     softmax_label=(8,))
    for n in step.param_names:
        # copy: the fused step donates its state buffers
        ex.arg_dict[n]._set_data(jnp.copy(state["params"][n]))
    upd = opt.get_updater(mk())

    for _ in range(3):
        state, _outs = step.step(state, batch)
        ex.forward(is_train=True, data=X, softmax_label=y)
        ex.backward()
        for i, n in enumerate(step.param_names):
            upd(i, ex.grad_dict[n], ex.arg_dict[n])

    for n in step.param_names:
        np.testing.assert_allclose(
            np.asarray(state["params"][n]), ex.arg_dict[n].asnumpy(),
            atol=2e-5, rtol=2e-5, err_msg="%s/%s" % (name, n))


def test_fused_lr_scheduler_and_mults():
    """lr_scheduler + lr_mult/wd_mult must flow into the fused update."""
    net = _mlp()
    rng = np.random.default_rng(1)
    X = rng.normal(size=(8, 10)).astype(np.float32)
    y = rng.integers(0, 4, 8).astype(np.float32)
    batch = {"data": jnp.asarray(X), "softmax_label": jnp.asarray(y)}

    def mk():
        o = opt.create("sgd", learning_rate=0.1, momentum=0.9,
                       rescale_grad=1.0 / 8,
                       lr_scheduler=mx.lr_scheduler.FactorScheduler(
                           step=2, factor=0.5),
                       param_idx2name={0: "fc1_weight", 1: "fc1_bias",
                                       2: "fc2_weight", 3: "fc2_bias"})
        o.wd = 1e-2
        o.set_lr_mult({"fc1_weight": 0.3})
        o.set_wd_mult({"fc2_weight": 2.0})
        return o

    step = TrainStep(net, optimizer=mk())
    state = step.init({"data": (8, 10)}, {"softmax_label": (8,)}, seed=2)

    ex = simple_bind(net, mx.cpu(), grad_req="write", data=(8, 10),
                     softmax_label=(8,))
    for n in step.param_names:
        ex.arg_dict[n]._set_data(jnp.copy(state["params"][n]))
    imp = mk()
    upd = opt.get_updater(imp)
    idx_of = {n: i for i, n in enumerate(
        ["fc1_weight", "fc1_bias", "fc2_weight", "fc2_bias"])}

    for _ in range(5):  # crosses the scheduler step boundary
        state, _ = step.step(state, batch)
        ex.forward(is_train=True, data=X, softmax_label=y)
        ex.backward()
        for n in step.param_names:
            upd(idx_of[n], ex.grad_dict[n], ex.arg_dict[n])

    for n in step.param_names:
        np.testing.assert_allclose(
            np.asarray(state["params"][n]), ex.arg_dict[n].asnumpy(),
            atol=2e-5, rtol=2e-5, err_msg=n)


def _fit_data(batch_size=16, n=64, shuffle=True):
    rng = np.random.default_rng(3)
    templates = rng.normal(size=(4, 10)).astype(np.float32)
    X = templates[rng.integers(0, 4, n)] \
        + 0.05 * rng.normal(size=(n, 10)).astype(np.float32)
    y = np.argmin(((X[:, None, :] - templates[None]) ** 2).sum(-1),
                  axis=1).astype(np.float32)
    return (mx.io.NDArrayIter(X, y, batch_size=batch_size, shuffle=shuffle),
            X, y)


def test_module_fit_uses_fused_path():
    net = _mlp()
    it, X, y = _fit_data()
    mod = mx.mod.Module(net)
    mod.fit(it, num_epoch=4, initializer=mx.initializer.Xavier(),
            optimizer_params={"learning_rate": 0.2})
    assert mod._fused is not None, "fit() did not engage the fused path"
    assert int(np.asarray(mod._fused_state["step"])) > 0
    val = mx.io.NDArrayIter(X, y, batch_size=16)
    acc = dict(mod.score(val, mx.metric.Accuracy()))
    assert acc["accuracy"] > 0.9, acc


def test_module_fit_fused_equals_executor_path():
    """Same seed, same data: fused fit must equal the executor-path fit."""
    def train(disable_fused):
        net = _mlp()
        it, X, y = _fit_data(shuffle=False)  # identical batch order
        mod = mx.mod.Module(net)
        if disable_fused:
            mod._fused_ok = False
        mx.random.seed(7)
        mod.fit(it, num_epoch=2, initializer=mx.initializer.Xavier(),
                optimizer_params={"learning_rate": 0.1, "momentum": 0.9})
        return mod.get_params()[0]

    a = train(False)
    b = train(True)
    for n in a:
        np.testing.assert_allclose(a[n].asnumpy(), b[n].asnumpy(),
                                   atol=1e-5, rtol=1e-5, err_msg=n)


def test_module_fused_checkpoint_roundtrip(tmp_path):
    net = _mlp()
    it, X, y = _fit_data()
    mod = mx.mod.Module(net)
    mod.fit(it, num_epoch=2, initializer=mx.initializer.Xavier(),
            optimizer_params={"learning_rate": 0.1, "momentum": 0.9})
    prefix = str(tmp_path / "fused_ckpt")
    mod.save_checkpoint(prefix, 2, save_optimizer_states=True)

    mod2 = mx.mod.Module.load(prefix, 2, load_optimizer_states=True)
    mod2.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod2.init_params()
    mod2.init_optimizer(optimizer_params={"learning_rate": 0.1,
                                          "momentum": 0.9})
    # predictions identical after round trip
    val = mx.io.NDArrayIter(X, y, batch_size=16)
    p1 = mod.predict(val).asnumpy()
    val = mx.io.NDArrayIter(X, y, batch_size=16)
    p2 = mod2.predict(val).asnumpy()
    np.testing.assert_allclose(p1, p2, atol=1e-6)
    # momentum state survived into the new module's fused seed
    it.reset()
    batch = next(iter(it))
    assert mod2._try_fused_fit_step(batch)
    mom = mod2._fused_state["opt"]["fc1_weight"]
    assert float(jnp.abs(mom).max()) > 0.0


# -- multi-step dispatch (run_steps / steps_per_dispatch) -------------------

def _stacked_batches(k=4, batch=8, seed=11):
    rng = np.random.default_rng(seed)
    Xs = rng.normal(size=(k, batch, 10)).astype(np.float32)
    ys = rng.integers(0, 4, (k, batch)).astype(np.float32)
    return Xs, ys


@pytest.mark.tracecheck  # hot loop under jax.transfer_guard("disallow")
@pytest.mark.parametrize("momentum", [0.0, 0.9])
def test_run_steps_matches_sequential(momentum):
    """run_steps(state, sb, k) == K sequential step() calls: params AND the
    device metric sums against host Accuracy/CrossEntropy over the same
    per-step outputs."""
    net = _mlp()
    K, B = 4, 8
    Xs, ys = _stacked_batches(K, B)

    def mk():
        o = opt.create("sgd", learning_rate=0.05, momentum=momentum,
                       rescale_grad=1.0 / B)
        o.wd = 1e-3
        return o

    from mxnet_tpu import metric as _metric
    stepA = TrainStep(net, optimizer=mk())
    sA = stepA.init({"data": (B, 10)}, {"softmax_label": (B,)}, seed=1)
    acc, ce = _metric.Accuracy(), _metric.CrossEntropy()
    for i in range(K):
        sA, outs = stepA.step(sA, {"data": jnp.asarray(Xs[i]),
                                   "softmax_label": jnp.asarray(ys[i])})
        acc.update([ys[i]], [np.asarray(outs[0])])
        ce.update([ys[i]], [np.asarray(outs[0])])

    stepB = TrainStep(net, optimizer=mk())
    sB = stepB.init({"data": (B, 10)}, {"softmax_label": (B,)}, seed=1)
    sB, sums = stepB.run_steps(sB, {"data": jnp.asarray(Xs),
                                    "softmax_label": jnp.asarray(ys)}, k=K)

    for n in stepA.param_names:
        np.testing.assert_allclose(
            np.asarray(sA["params"][n]), np.asarray(sB["params"][n]),
            atol=1e-6, rtol=1e-6, err_msg=n)
    assert int(np.asarray(sB["step"])) == K
    assert sums.num_samples == K * B
    assert sums.top1_correct == acc.sum_metric
    np.testing.assert_allclose(sums.loss_sum, ce.sum_metric, rtol=1e-5)


def test_run_steps_lr_scheduler_granularity():
    """A scheduler stepping INSIDE the dispatch window must produce the same
    trajectory as per-step dispatch: lrs ride in as a traced (k,) vector."""
    net = _mlp()
    K, B = 4, 8
    Xs, ys = _stacked_batches(K, B, seed=5)

    def mk():
        return opt.create("sgd", learning_rate=0.2, momentum=0.9,
                          rescale_grad=1.0 / B,
                          lr_scheduler=mx.lr_scheduler.FactorScheduler(
                              step=3, factor=0.5))

    stepA = TrainStep(net, optimizer=mk())
    sA = stepA.init({"data": (B, 10)}, {"softmax_label": (B,)}, seed=3)
    for i in range(K):
        sA, _ = stepA.step(sA, {"data": jnp.asarray(Xs[i]),
                                "softmax_label": jnp.asarray(ys[i])})

    stepB = TrainStep(net, optimizer=mk())
    sB = stepB.init({"data": (B, 10)}, {"softmax_label": (B,)}, seed=3)
    sB, _ = stepB.run_steps(sB, {"data": jnp.asarray(Xs),
                                 "softmax_label": jnp.asarray(ys)})

    for n in stepA.param_names:
        np.testing.assert_allclose(
            np.asarray(sA["params"][n]), np.asarray(sB["params"][n]),
            atol=1e-6, rtol=1e-6, err_msg=n)


@pytest.mark.tracecheck
def test_run_steps_no_retrace_across_epochs():
    """Same (batch, k) shape must reuse ONE compiled scan across epochs;
    different k compiles separately, returning to a seen k reuses it.
    The whole loop runs inside ``assert_no_retrace`` (the tracecheck
    cache-key differ) and under ``jax.transfer_guard("disallow")`` via the
    ``tracecheck`` marker — a retrace OR an implicit host transfer in the
    dispatch loop fails with the offending argument/callsite named."""
    from mxnet_tpu.test_utils import assert_no_retrace
    net = _mlp()
    B = 8
    step = TrainStep(net, optimizer="sgd", learning_rate=0.05)
    state = step.init({"data": (B, 10)}, {"softmax_label": (B,)}, seed=1)

    with assert_no_retrace(msg="varying-K epochs"):
        for k in (2, 4, 2, 2, 4):  # "epochs" of varying K
            Xs, ys = _stacked_batches(k, B, seed=k)
            state, _ = step.run_steps(
                state, {"data": jnp.asarray(Xs),
                        "softmax_label": jnp.asarray(ys)})
    assert set(step._jit_scan) == {(B, 2), (B, 4)}
    for fn in step._jit_scan.values():
        assert fn._cache_size() == 1, "scan retraced for an already-seen K"


def test_run_steps_shape_validation():
    net = _mlp()
    step = TrainStep(net, optimizer="sgd")
    state = step.init({"data": (8, 10)}, {"softmax_label": (8,)})
    Xs, ys = _stacked_batches(4, 8)
    with pytest.raises(mx.base.MXNetError):
        step.run_steps(state, {"data": jnp.asarray(Xs),
                               "softmax_label": jnp.asarray(ys)}, k=3)
    with pytest.raises(mx.base.MXNetError):
        step.run_steps(state, {"data": jnp.asarray(Xs),
                               "softmax_label": jnp.asarray(ys[:2])})


def test_module_fit_steps_per_dispatch_parity():
    """Module.fit(steps_per_dispatch=k) == k=1: same final params and the
    same train metric over the epoch (device sums vs per-step update)."""
    final_metric = {}

    def train(k):
        net = _mlp()
        it, X, y = _fit_data(shuffle=False)
        mod = mx.mod.Module(net)
        mx.random.seed(7)
        captured = []
        mod.fit(it, num_epoch=3, initializer=mx.initializer.Xavier(),
                optimizer_params={"learning_rate": 0.1, "momentum": 0.9},
                eval_metric=mx.metric.create(["acc", "ce"]),
                steps_per_dispatch=k,
                batch_end_callback=lambda p: captured.append(
                    [v for _, v in p.eval_metric.get_name_value()]))
        final_metric[k] = captured[-1]
        return mod.get_params()[0]

    a = train(1)
    b = train(4)
    for n in a:
        np.testing.assert_allclose(a[n].asnumpy(), b[n].asnumpy(),
                                   atol=1e-5, rtol=1e-5, err_msg=n)
    np.testing.assert_allclose(final_metric[1], final_metric[4], rtol=1e-5)


def test_module_fit_steps_per_dispatch_epoch_tail():
    """96 samples / batch 16 = 6 batches; k=4 leaves a 2-batch tail that
    must train through the per-step path — every sample still seen, and the
    metric must cover all of them."""
    net = _mlp()
    it, X, y = _fit_data(n=96, shuffle=False)
    mod = mx.mod.Module(net)
    seen = []
    mod.fit(it, num_epoch=1, initializer=mx.initializer.Xavier(),
            optimizer_params={"learning_rate": 0.1},
            steps_per_dispatch=4,
            batch_end_callback=lambda p: seen.append(
                (p.nbatch, p.eval_metric.num_inst)))
    assert int(np.asarray(mod._fused_state["step"])) == 6
    assert seen[-1][0] == 5  # nbatch counts single batches
    assert seen[-1][1] == 96  # metric covered every sample


def test_module_fit_unsupported_metric_falls_back(caplog):
    """A metric with NO declared packed layout (a CustomMetric without
    the device_step_sums opt-in) falls back to k=1 — and the warning
    names the metric, never silently (the zoo-dispatch gate pins this
    contract)."""
    import logging
    net = _mlp()
    it, X, y = _fit_data(shuffle=False)
    mod = mx.mod.Module(net)
    metric = mx.metric.CustomMetric(
        lambda label, pred: float((np.argmax(pred, 1) == label).mean()),
        name="hostonly")
    with caplog.at_level(logging.WARNING):
        mod.fit(it, num_epoch=1, initializer=mx.initializer.Xavier(),
                optimizer_params={"learning_rate": 0.1},
                eval_metric=metric, steps_per_dispatch=4)
    # fell back to per-step dispatch but still trained
    assert int(np.asarray(mod._fused_state["step"])) == 4
    assert mod._fused._jit_scan == {}
    assert any("steps_per_dispatch=4 unavailable" in r.message
               and "hostonly" in r.message for r in caplog.records)


def test_module_fit_mse_rides_packed_accumulators():
    """MSE — the regression class that used to silently fall back to k=1
    — now declares a packed layout and rides the fused scan; the train
    metric matches the k=1 host fold."""
    def train(k):
        net = _mlp()
        it, X, y = _fit_data(shuffle=False)
        mod = mx.mod.Module(net)
        mx.random.seed(3)
        m = mx.metric.MSE()
        mod.fit(it, num_epoch=2, initializer=mx.initializer.Xavier(),
                optimizer_params={"learning_rate": 0.05},
                eval_metric=m, steps_per_dispatch=k)
        return mod, dict(m.get_name_value())["mse"]

    mod4, mse4 = train(4)
    assert any(key[:2] == (16, 4) for key in mod4._fused._jit_scan)
    _, mse1 = train(1)
    np.testing.assert_allclose(mse4, mse1, rtol=1e-5)


def test_engine_bulk_scope_sets_fit_default():
    net = _mlp()
    it, X, y = _fit_data(shuffle=False)
    mod = mx.mod.Module(net)
    assert mx.engine.bulk_size() == 1
    with mx.engine.bulk(4):
        assert mx.engine.bulk_size() == 4
        mod.fit(it, num_epoch=1, initializer=mx.initializer.Xavier(),
                optimizer_params={"learning_rate": 0.1})
    assert mx.engine.bulk_size() == 1
    # the K-step scan path was engaged by the engine default (the scan
    # cache key carries the metric spec's signature since the
    # packed-accumulator protocol)
    assert any(k[:2] == (16, 4) for k in mod._fused._jit_scan)


def test_module_fit_multihead_rides_packed_accumulators():
    """Two softmax heads: under the packed-accumulator protocol Accuracy
    declares a layout covering BOTH (rank-2 output, rank-1 label) pairs,
    so fit(steps_per_dispatch=k) stays on the fused scan (the
    pre-protocol code fell back to k=1 here) — and the reported accuracy
    must match the k=1 host-metric run exactly."""
    def build():
        data = mx.sym.Variable("data")
        net = mx.sym.FullyConnected(data, num_hidden=8, name="fc1")
        a = mx.sym.SoftmaxOutput(
            mx.sym.FullyConnected(net, num_hidden=4, name="ha"), name="sa")
        b = mx.sym.SoftmaxOutput(
            mx.sym.FullyConnected(net, num_hidden=4, name="hb"), name="sb")
        return mx.sym.Group([a, b])

    def train(k):
        it, X, y = _fit_data(shuffle=False)
        mod = mx.mod.Module(build(), label_names=("sa_label", "sb_label"))
        mx.random.seed(9)
        acc = mx.metric.Accuracy()
        # two labels: reuse y for both heads
        class TwoLabelIter(mx.io.DataIter):
            def __init__(self, base):
                super().__init__(base.batch_size)
                self.base = base
            @property
            def provide_data(self):
                return self.base.provide_data
            @property
            def provide_label(self):
                d = self.base.provide_label[0]
                return [mx.io.DataDesc("sa_label", d.shape, d.dtype),
                        mx.io.DataDesc("sb_label", d.shape, d.dtype)]
            def reset(self):
                self.base.reset()
            def next(self):
                b = self.base.next()
                return mx.io.DataBatch(data=b.data, label=b.label * 2,
                                       pad=b.pad)
        mod.fit(TwoLabelIter(it), num_epoch=2,
                initializer=mx.initializer.Xavier(),
                optimizer_params={"learning_rate": 0.1},
                eval_metric=acc, steps_per_dispatch=k)
        return mod, dict(acc.get_name_value())["accuracy"]

    mod4, acc4 = train(4)
    assert mod4._fused is not None
    # the scan path engaged with the metric's own packed layout
    assert any(k[:2] == (16, 4) for k in mod4._fused._jit_scan)
    assert mod4._fused_metric_spec is not None
    assert mod4._fused_metric_spec.slots == ("correct", "n")
    _, acc1 = train(1)
    np.testing.assert_allclose(acc4, acc1, rtol=1e-6)


def test_speedometer_fires_under_dispatch_jumps():
    """batch_end arrives in K-batch jumps under steps_per_dispatch; the
    Speedometer must still fire on every `frequent` boundary crossing."""
    from mxnet_tpu.callback import Speedometer
    from mxnet_tpu.module.base_module import BatchEndParam
    import logging as _logging
    sp = Speedometer(batch_size=16, frequent=50)
    fired = []
    orig = _logging.info
    _logging.info = lambda *a: fired.append(a)
    try:
        for nbatch in range(7, 500, 8):  # K=8 jumps: 7, 15, ..., never %50==0
            sp(BatchEndParam(epoch=0, nbatch=nbatch, eval_metric=None,
                             locals=None))
    finally:
        _logging.info = orig
    assert len(fired) == 9  # one per 50-batch boundary crossed


def test_fit_superbatch_leaves_iterator_reset():
    """After fit(steps_per_dispatch=k) returns, no producer thread may keep
    consuming the user's iterator: a fresh epoch must see every batch."""
    net = _mlp()
    it, X, y = _fit_data(shuffle=False)
    mod = mx.mod.Module(net)
    mod.fit(it, num_epoch=2, initializer=mx.initializer.Xavier(),
            optimizer_params={"learning_rate": 0.1}, steps_per_dispatch=2)
    assert len(list(it)) == 4  # all 64/16 batches still there


def test_module_fixed_params_stay_fixed():
    net = _mlp()
    it, X, y = _fit_data()
    mod = mx.mod.Module(net, fixed_param_names=["fc1_weight"])
    mod.fit(it, num_epoch=2, initializer=mx.initializer.Xavier(),
            optimizer_params={"learning_rate": 0.2})
    assert mod._fused is not None
    w0 = np.asarray(mod._fused_state["params"]["fc1_weight"])
    it.reset()
    for batch in it:
        assert mod._try_fused_fit_step(batch)
    np.testing.assert_array_equal(
        w0, np.asarray(mod._fused_state["params"]["fc1_weight"]))


def test_fit_exception_stops_producer_thread():
    """An exception escaping fit(steps_per_dispatch=k) must not leave a
    producer thread consuming the user's iterator."""
    import threading
    import time as _t
    net = _mlp()
    it, X, y = _fit_data(shuffle=False)
    mod = mx.mod.Module(net)
    before = set(threading.enumerate())

    def boom(_param):
        raise ValueError("stop training")

    with pytest.raises(ValueError, match="stop training"):
        mod.fit(it, num_epoch=2, initializer=mx.initializer.Xavier(),
                optimizer_params={"learning_rate": 0.1},
                steps_per_dispatch=2, batch_end_callback=boom)
    deadline = _t.time() + 3.0
    while _t.time() < deadline and set(threading.enumerate()) - before:
        _t.sleep(0.05)
    assert not (set(threading.enumerate()) - before), "producer still alive"


def test_log_train_metric_fires_under_dispatch_jumps():
    from mxnet_tpu.callback import log_train_metric
    from mxnet_tpu.module.base_module import BatchEndParam
    import logging as _logging
    cb = log_train_metric(50)
    m = mx.metric.Accuracy()
    m.sum_metric, m.num_inst = 5, 10
    fired = []
    orig = _logging.info
    _logging.info = lambda *a: fired.append(a)
    try:
        for nbatch in range(7, 500, 8):  # K=8 jumps, never % 50 == 0
            cb(BatchEndParam(epoch=0, nbatch=nbatch, eval_metric=m,
                             locals=None))
    finally:
        _logging.info = orig
    assert len(fired) == 10  # batch 7 (crosses -1->0) + 9 later boundaries
