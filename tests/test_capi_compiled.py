"""Compiled C API: build libmxnet_tpu.so + run the pure-C smoke client
that trains a layer through the ABI (ref: include/mxnet/c_api.h contract,
cpp-package consumption; SURVEY.md §2.7 layer 11)."""
import os
import shutil
import subprocess

import pytest

ROOT = os.path.join(os.path.dirname(__file__), "..")
LIB = os.path.join(ROOT, "lib", "libmxnet_tpu.so")
CLIENT = os.path.join(ROOT, "lib", "smoke_client")
SRC = os.path.join(ROOT, "src", "capi")


def _build():
    r = subprocess.run(["make", "-C", SRC], capture_output=True, text=True,
                       timeout=300)
    return r.returncode == 0, r.stdout + r.stderr


@pytest.mark.skipif(shutil.which("cc") is None
                    or shutil.which("python3-config") is None,
                    reason="no C toolchain")
def test_compiled_capi_smoke_client_trains():
    src_newer = (not os.path.exists(LIB)
                 or os.path.getmtime(os.path.join(SRC, "libmxnet_tpu.c"))
                 > os.path.getmtime(LIB))
    if src_newer or not os.path.exists(CLIENT):
        ok, log = _build()
        assert ok, "C API build failed:\n%s" % log
    env = dict(os.environ)
    env["PYTHONPATH"] = ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run([CLIENT], capture_output=True, text=True, env=env,
                       timeout=600)
    assert r.returncode == 0, "smoke client failed:\nstdout:%s\nstderr:%s" \
        % (r.stdout, r.stderr)
    assert "SMOKE PASS" in r.stdout


def test_exported_symbols_are_c_linkage():
    if not os.path.exists(LIB):
        pytest.skip("lib not built")
    r = subprocess.run(["nm", "-D", LIB], capture_output=True, text=True)
    syms = r.stdout
    for s in ("MXGetLastError", "MXNDArrayCreate", "MXSymbolCompose",
              "MXExecutorBind", "MXExecutorForward", "MXExecutorBackward",
              "MXKVStorePush", "MXKVStorePull"):
        assert " T %s" % s in syms or " T _%s" % s in syms, \
            "symbol %s not exported" % s


def test_predict_client_runs_checkpoint(tmp_path):
    """C predict client (MXPred ABI) serves a real Module checkpoint."""
    if shutil.which("cc") is None:
        pytest.skip("no C toolchain")
    import mxnet_tpu as mx
    net = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=4,
                              name="fc"), name="softmax")
    mod = mx.mod.Module(net)
    mod.bind(data_shapes=[("data", (3, 6))],
             label_shapes=[("softmax_label", (3,))])
    mod.init_params()
    prefix = str(tmp_path / "pc")
    mod.save_checkpoint(prefix, 1)
    client = os.path.join(ROOT, "lib", "predict_client")
    if not os.path.exists(client):
        ok, log = _build()
        assert ok, log
    env = dict(os.environ)
    env["PYTHONPATH"] = ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run([client, prefix + "-symbol.json",
                        prefix + "-0001.params", "3", "6"],
                       capture_output=True, text=True, env=env, timeout=600)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "PREDICT PASS" in r.stdout


def test_mxpred_python_surface():
    """MXPred glue round-trip at the Python layer (shape + values)."""
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import c_api, dmlc_serial
    net = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=3,
                              name="fc"), name="softmax")
    w = np.random.RandomState(0).randn(3, 4).astype(np.float32)
    b = np.zeros(3, np.float32)
    params = dmlc_serial.dumps([w, b], ["arg:fc_weight", "arg:fc_bias"])
    st, h = c_api.MXPredCreate(net.tojson(), params, 1, 0, ["data"],
                               [(2, 4)])
    assert st == 0, c_api.MXGetLastError()
    x = np.random.rand(2, 4).astype(np.float32)
    assert c_api.MXPredSetInput(h, "data", x.tobytes())[0] == 0
    assert c_api.MXPredForward(h)[0] == 0
    st, shape = c_api.MXPredGetOutputShape(h, 0)
    assert shape == (2, 3)
    st, buf = c_api.MXPredGetOutput(h, 0)
    out = np.frombuffer(buf, np.float32).reshape(shape)
    ref = x @ w.T
    ref = np.exp(ref - ref.max(1, keepdims=True))
    ref /= ref.sum(1, keepdims=True)
    np.testing.assert_allclose(out, ref, atol=1e-5)
    assert c_api.MXPredFree(h)[0] == 0
