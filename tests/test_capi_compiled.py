"""Compiled C API: build libmxnet_tpu.so + run the pure-C smoke client
that trains a layer through the ABI (ref: include/mxnet/c_api.h contract,
cpp-package consumption; SURVEY.md §2.7 layer 11)."""
import os
import shutil
import subprocess

import pytest

ROOT = os.path.join(os.path.dirname(__file__), "..")
LIB = os.path.join(ROOT, "lib", "libmxnet_tpu.so")
CLIENT = os.path.join(ROOT, "lib", "smoke_client")
SRC = os.path.join(ROOT, "src", "capi")


def _build():
    r = subprocess.run(["make", "-C", SRC], capture_output=True, text=True,
                       timeout=300)
    return r.returncode == 0, r.stdout + r.stderr


@pytest.mark.skipif(shutil.which("cc") is None
                    or shutil.which("python3-config") is None,
                    reason="no C toolchain")
def test_compiled_capi_smoke_client_trains():
    src_newer = (not os.path.exists(LIB)
                 or os.path.getmtime(os.path.join(SRC, "libmxnet_tpu.c"))
                 > os.path.getmtime(LIB))
    if src_newer or not os.path.exists(CLIENT):
        ok, log = _build()
        assert ok, "C API build failed:\n%s" % log
    env = dict(os.environ)
    env["PYTHONPATH"] = ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run([CLIENT], capture_output=True, text=True, env=env,
                       timeout=600)
    assert r.returncode == 0, "smoke client failed:\nstdout:%s\nstderr:%s" \
        % (r.stdout, r.stderr)
    assert "SMOKE PASS" in r.stdout


def test_exported_symbols_are_c_linkage():
    if not os.path.exists(LIB):
        pytest.skip("lib not built")
    r = subprocess.run(["nm", "-D", LIB], capture_output=True, text=True)
    syms = r.stdout
    for s in ("MXGetLastError", "MXNDArrayCreate", "MXSymbolCompose",
              "MXExecutorBind", "MXExecutorForward", "MXExecutorBackward",
              "MXKVStorePush", "MXKVStorePull"):
        assert " T %s" % s in syms or " T _%s" % s in syms, \
            "symbol %s not exported" % s
