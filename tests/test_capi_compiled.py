"""Compiled C API: build libmxnet_tpu.so + run the pure-C smoke client
that trains a layer through the ABI (ref: include/mxnet/c_api.h contract,
cpp-package consumption; SURVEY.md §2.7 layer 11)."""
import os
import shutil
import subprocess

import pytest

ROOT = os.path.join(os.path.dirname(__file__), "..")
LIB = os.path.join(ROOT, "lib", "libmxnet_tpu.so")
CLIENT = os.path.join(ROOT, "lib", "smoke_client")
SRC = os.path.join(ROOT, "src", "capi")


def _build():
    r = subprocess.run(["make", "-C", SRC], capture_output=True, text=True,
                       timeout=300)
    return r.returncode == 0, r.stdout + r.stderr


@pytest.mark.skipif(shutil.which("cc") is None
                    or shutil.which("python3-config") is None,
                    reason="no C toolchain")
def test_compiled_capi_smoke_client_trains():
    src_newer = (not os.path.exists(LIB)
                 or os.path.getmtime(os.path.join(SRC, "libmxnet_tpu.c"))
                 > os.path.getmtime(LIB))
    if src_newer or not os.path.exists(CLIENT):
        ok, log = _build()
        assert ok, "C API build failed:\n%s" % log
    env = dict(os.environ)
    env["PYTHONPATH"] = ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run([CLIENT], capture_output=True, text=True, env=env,
                       timeout=600)
    assert r.returncode == 0, "smoke client failed:\nstdout:%s\nstderr:%s" \
        % (r.stdout, r.stderr)
    assert "SMOKE PASS" in r.stdout


def test_abi_client_families():
    """r5 ABI families end-to-end in pure C: op introspection, training
    from a C-created DataIter, C updater callback, autograd, RecordIO
    (ref: c_api.h DataIter/autograd/RecordIO/introspection families;
    VERDICT r4 item 2 done-criteria)."""
    if shutil.which("cc") is None:
        pytest.skip("no C toolchain")
    client = os.path.join(ROOT, "lib", "abi_client")
    src_newer = (not os.path.exists(client)
                 or os.path.getmtime(os.path.join(SRC, "abi_client.c"))
                 > os.path.getmtime(client)
                 or os.path.getmtime(os.path.join(SRC, "libmxnet_tpu.c"))
                 > os.path.getmtime(client))
    if src_newer:
        ok, log = _build()
        assert ok, "build failed:\n%s" % log
    env = dict(os.environ)
    env["PYTHONPATH"] = ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run([client], capture_output=True, text=True, env=env,
                       timeout=600)
    assert r.returncode == 0, "abi client failed:\nstdout:%s\nstderr:%s" \
        % (r.stdout, r.stderr)
    assert "ABI PASS" in r.stdout
    assert "introspection: 2" in r.stdout  # 200+ ops through the ABI
    assert "updater calls" in r.stdout
    # caller-supplied *outputs != NULL: write-in-place contract (ISSUE 4)
    assert "imperative in-place: square -> [1 4 9]" in r.stdout


def test_abi_covers_all_114_reference_functions():
    """Every `MXNET_DLL int MX*` in the reference c_api.h must be exported
    by the compiled .so (ref: include/mxnet/c_api.h — the contract every
    binding consumes)."""
    import re
    if not os.path.exists(LIB):
        pytest.skip("lib not built")
    ref_h = "/root/reference/include/mxnet/c_api.h"
    if not os.path.exists(ref_h):
        pytest.skip("reference not available")
    with open(ref_h) as f:
        ref_fns = set(re.findall(r"MXNET_DLL int (MX[A-Za-z0-9]+)",
                                 f.read()))
    r = subprocess.run(["nm", "-D", LIB], capture_output=True, text=True)
    exported = set(re.findall(r" T (MX[A-Za-z0-9]+)", r.stdout))
    missing = sorted(ref_fns - exported)
    assert not missing, "ABI missing %d reference functions: %s" % (
        len(missing), missing)


def test_op_enumeration_through_compiled_abi_ctypes():
    """Enumerate ops + arg docs purely through the compiled ABI from
    python/ctypes — the mechanical path a binding generator uses (ref:
    OpWrapperGenerator.py over MXSymbolGetAtomicSymbolInfo)."""
    import ctypes
    if not os.path.exists(LIB):
        pytest.skip("lib not built")
    # the .so embeds CPython: loading it into this process is fine (it
    # reuses the live interpreter via PyGILState)
    lib = ctypes.CDLL(LIB)
    lib.MXGetLastError.restype = ctypes.c_char_p
    n = ctypes.c_uint(0)
    arr = ctypes.POINTER(ctypes.c_uint64)()
    assert lib.MXSymbolListAtomicSymbolCreators(
        ctypes.byref(n), ctypes.byref(arr)) == 0, lib.MXGetLastError()
    assert n.value > 200
    seen = {}
    for i in range(n.value):
        name = ctypes.c_char_p()
        desc = ctypes.c_char_p()
        na = ctypes.c_uint()
        an = ctypes.POINTER(ctypes.c_char_p)()
        at = ctypes.POINTER(ctypes.c_char_p)()
        ad = ctypes.POINTER(ctypes.c_char_p)()
        kv = ctypes.c_char_p()
        rt = ctypes.c_char_p()
        assert lib.MXSymbolGetAtomicSymbolInfo(
            ctypes.c_uint64(arr[i]), ctypes.byref(name), ctypes.byref(desc),
            ctypes.byref(na), ctypes.byref(an), ctypes.byref(at),
            ctypes.byref(ad), ctypes.byref(kv), ctypes.byref(rt)) == 0
        seen[name.value.decode()] = [an[j].decode() for j in range(na.value)]
    assert "Convolution" in seen and seen["Convolution"][0] == "data"
    assert "FullyConnected" in seen
    assert "BatchNorm" in seen
    # registry parity: the ABI must see exactly what python sees
    from mxnet_tpu.ops import list_ops
    assert set(seen) == set(list_ops())


def test_exported_symbols_are_c_linkage():
    if not os.path.exists(LIB):
        pytest.skip("lib not built")
    r = subprocess.run(["nm", "-D", LIB], capture_output=True, text=True)
    syms = r.stdout
    for s in ("MXGetLastError", "MXNDArrayCreate", "MXSymbolCompose",
              "MXExecutorBind", "MXExecutorForward", "MXExecutorBackward",
              "MXKVStorePush", "MXKVStorePull"):
        assert " T %s" % s in syms or " T _%s" % s in syms, \
            "symbol %s not exported" % s


def test_predict_client_runs_checkpoint(tmp_path):
    """C predict client (MXPred ABI) serves a real Module checkpoint."""
    if shutil.which("cc") is None:
        pytest.skip("no C toolchain")
    import mxnet_tpu as mx
    net = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=4,
                              name="fc"), name="softmax")
    mod = mx.mod.Module(net)
    mod.bind(data_shapes=[("data", (3, 6))],
             label_shapes=[("softmax_label", (3,))])
    mod.init_params()
    prefix = str(tmp_path / "pc")
    mod.save_checkpoint(prefix, 1)
    client = os.path.join(ROOT, "lib", "predict_client")
    if not os.path.exists(client):
        ok, log = _build()
        assert ok, log
    env = dict(os.environ)
    env["PYTHONPATH"] = ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run([client, prefix + "-symbol.json",
                        prefix + "-0001.params", "3", "6"],
                       capture_output=True, text=True, env=env, timeout=600)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "RESHAPE PASS" in r.stdout  # MXPredReshape through the ABI
    assert "PREDICT PASS" in r.stdout


def test_mt_client_concurrency_and_error_paths():
    """4 C threads x 250 iterations of create/copy/invoke/forward/push/pull
    + 8 per-handle-type error-path probes (ref: the ABI serves
    multi-threaded Scala/JNI; VERDICT r4 weak #3)."""
    if (shutil.which("cc") is None
            or shutil.which("python3-config") is None):
        pytest.skip("no C toolchain")
    client = os.path.join(ROOT, "lib", "mt_client")
    if (not os.path.exists(client)
            or os.path.getmtime(os.path.join(SRC, "mt_client.c"))
            > os.path.getmtime(client)
            or os.path.getmtime(os.path.join(SRC, "libmxnet_tpu.c"))
            > os.path.getmtime(client)):
        ok, log = _build()
        assert ok, log
    env = dict(os.environ)
    env["PYTHONPATH"] = ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run([client], capture_output=True, text=True, env=env,
                       timeout=600)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "MT PASS" in r.stdout
    assert "error paths: 8/8" in r.stdout


def test_pred_partial_out_and_reshape_python():
    """MXPredCreatePartialOut picks an internal head; Predictor.reshape
    rebinds input shapes keeping weights (ref: c_predict_api.h:92-102)."""
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import c_api, dmlc_serial
    net = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(
            mx.sym.Activation(
                mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=5,
                                      name="fc1"),
                act_type="relu", name="relu1"),
            num_hidden=3, name="fc2"), name="softmax")
    rs = np.random.RandomState(0)
    params = {"arg:fc1_weight": rs.randn(5, 4).astype(np.float32),
              "arg:fc1_bias": np.zeros(5, np.float32),
              "arg:fc2_weight": rs.randn(3, 5).astype(np.float32),
              "arg:fc2_bias": np.zeros(3, np.float32)}
    blob = dmlc_serial.dumps(list(params.values()), list(params.keys()))
    # partial out: fc1 activations instead of the softmax head
    st, h = c_api.MXPredCreatePartialOut(net.tojson(), blob, 1, 0,
                                         ["data"], [(2, 4)], ["relu1"])
    assert st == 0, c_api.MXGetLastError()
    x = rs.rand(2, 4).astype(np.float32)
    assert c_api.MXPredSetInput(h, "data", x.tobytes())[0] == 0
    assert c_api.MXPredForward(h)[0] == 0
    st, shape = c_api.MXPredGetOutputShape(h, 0)
    assert shape == (2, 5), shape
    st, buf = c_api.MXPredGetOutput(h, 0)
    got = np.frombuffer(buf, np.float32).reshape(shape)
    ref = np.maximum(x @ params["arg:fc1_weight"].T, 0)
    np.testing.assert_allclose(got, ref, atol=1e-5)

    # reshape: full-net predictor rebound to batch 6; weights intact
    st, hp = c_api.MXPredCreate(net.tojson(), blob, 1, 0, ["data"],
                                [(2, 4)])
    assert st == 0, c_api.MXGetLastError()
    st, h6 = c_api.MXPredReshape(hp, ["data"], [(6, 4)])
    assert st == 0, c_api.MXGetLastError()
    x6 = np.vstack([x, x, x]).astype(np.float32)
    assert c_api.MXPredSetInput(h6, "data", x6.tobytes())[0] == 0
    assert c_api.MXPredForward(h6)[0] == 0
    st, shape6 = c_api.MXPredGetOutputShape(h6, 0)
    assert shape6 == (6, 3), shape6
    # a reshape that would change a PARAMETER shape must error
    st, _ = c_api.MXPredReshape(hp, ["data"], [(6, 9)])
    assert st == -1
    assert "parameter" in c_api.MXGetLastError()


def test_mxpred_python_surface():
    """MXPred glue round-trip at the Python layer (shape + values)."""
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import c_api, dmlc_serial
    net = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=3,
                              name="fc"), name="softmax")
    w = np.random.RandomState(0).randn(3, 4).astype(np.float32)
    b = np.zeros(3, np.float32)
    params = dmlc_serial.dumps([w, b], ["arg:fc_weight", "arg:fc_bias"])
    st, h = c_api.MXPredCreate(net.tojson(), params, 1, 0, ["data"],
                               [(2, 4)])
    assert st == 0, c_api.MXGetLastError()
    x = np.random.rand(2, 4).astype(np.float32)
    assert c_api.MXPredSetInput(h, "data", x.tobytes())[0] == 0
    assert c_api.MXPredForward(h)[0] == 0
    st, shape = c_api.MXPredGetOutputShape(h, 0)
    assert shape == (2, 3)
    st, buf = c_api.MXPredGetOutput(h, 0)
    out = np.frombuffer(buf, np.float32).reshape(shape)
    ref = x @ w.T
    ref = np.exp(ref - ref.max(1, keepdims=True))
    ref /= ref.sum(1, keepdims=True)
    np.testing.assert_allclose(out, ref, atol=1e-5)
    assert c_api.MXPredFree(h)[0] == 0


def test_compiled_abi_error_contracts_r6():
    """r6 hardening: shape queries reject ndim > the 32-dim return buffer,
    CPU copies reject size mismatches instead of silently truncating, and
    kvstore command bodies marshal length-explicit (binary pickles carry
    NULs that the legacy NUL-terminated entry point cannot)."""
    import ctypes
    import pickle
    if not os.path.exists(LIB):
        pytest.skip("lib not built")
    import numpy as np
    from mxnet_tpu import c_api, optimizer as opt

    lib = ctypes.CDLL(LIB)  # shares the live interpreter's handle registry
    lib.MXGetLastError.restype = ctypes.c_char_p

    # -- copy size mismatch -> -1, exact size -> 0 ----------------------
    _, h = c_api.MXNDArrayCreateFromNumpy(
        np.arange(6, dtype=np.float32).reshape(2, 3))
    buf = (ctypes.c_float * 6)()
    assert lib.MXNDArraySyncCopyToCPU(
        ctypes.c_uint64(h), buf, ctypes.c_size_t(6)) == 0
    assert [buf[i] for i in range(6)] == [0, 1, 2, 3, 4, 5]
    assert lib.MXNDArraySyncCopyToCPU(
        ctypes.c_uint64(h), buf, ctypes.c_size_t(4)) == -1
    assert b"does not match" in lib.MXGetLastError()
    assert lib.MXNDArraySyncCopyToCPU(
        ctypes.c_uint64(h), buf, ctypes.c_size_t(8)) == -1

    # -- shape ndim > 32 -> -1 with message, never a truncated buffer ---
    try:
        _, h33 = c_api.MXNDArrayCreateFromNumpy(
            np.zeros((1,) * 33, np.float32))
        ok = _ == 0
    except Exception:
        ok = False
    if ok:
        ndim = ctypes.c_uint32()
        pdata = ctypes.POINTER(ctypes.c_uint32)()
        assert lib.MXNDArrayGetShape(ctypes.c_uint64(h33),
                                     ctypes.byref(ndim),
                                     ctypes.byref(pdata)) == -1
        assert b"32-dim" in lib.MXGetLastError()

    # -- kvstore command body: length-explicit Ex carries binary pickles
    _, kv = c_api.MXKVStoreCreate("local")
    body = pickle.dumps(opt.create("sgd", learning_rate=0.25))
    assert b"\x00" in body  # the truncation hazard is real
    assert lib.MXKVStoreSendCommmandToServersEx(
        ctypes.c_uint64(kv), 0, ctypes.c_char_p(body),
        ctypes.c_size_t(len(body))) == 0
    assert abs(c_api._get(kv)._updater.optimizer.lr - 0.25) < 1e-9
    # the legacy NUL-terminated path truncates the pickle -> the python
    # side must now REJECT the garbage body instead of swallowing it
    assert lib.MXKVStoreSendCommmandToServers(
        ctypes.c_uint64(kv), 0, ctypes.c_char_p(body)) == -1
    assert b"unpickle" in lib.MXGetLastError()
