"""Registry-wide operator sweep: forward-vs-numpy + finite-difference
gradient checks over every registered op, with an explicit, justified
skip-list (VERDICT r3 #8; ref test strategy:
tests/python/unittest/test_operator.py, SURVEY.md §4).

Families share generated configs; `test_registry_coverage` enforces that
every op in the registry is either exercised here, covered by a named
dedicated test file, or skip-listed with a reason.
"""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

from mxnet_tpu.ndarray import NDArray, array, invoke
from mxnet_tpu.ops import registry as _reg

RNG = np.random.RandomState(7)


def run_op(name, inputs, attrs=None, n_out=1):
    out = invoke(_reg.get(name), [array(x) for x in inputs], attrs or {})
    if isinstance(out, (list, tuple)):
        return [o.asnumpy() for o in out]
    return [out.asnumpy()]


def fd_grad_check(name, inputs, attrs=None, eps=1e-3, rtol=1e-2, atol=1e-3,
                  wrt=None):
    """loss = sum(op(x) * proj); analytic jax.grad vs central differences."""
    attrs = attrs or {}
    opdef = _reg.get(name)
    proj = None
    wrt = list(range(len(inputs))) if wrt is None else wrt

    def loss_fn(*args):
        ctx = _reg.OpContext(is_train=True, rng=None)
        outs, _ = opdef.apply(ctx, attrs, list(args), [])
        nonlocal proj
        flat = jnp.concatenate([jnp.ravel(o.astype(jnp.float32))
                                for o in outs])
        if proj is None:
            proj = RNG.randn(flat.shape[0]).astype(np.float32)
        return jnp.sum(flat * proj)

    args = [jnp.asarray(x) for x in inputs]
    analytic = jax.grad(loss_fn, argnums=tuple(wrt))(*args)
    for gi, ai in zip(analytic, wrt):
        x = np.asarray(inputs[ai], np.float64)
        fd = np.zeros_like(x)
        it = np.nditer(x, flags=["multi_index"])
        while not it.finished:
            ix = it.multi_index
            xp = x.copy(); xp[ix] += eps
            xm = x.copy(); xm[ix] -= eps
            a_p = [jnp.asarray(xp.astype(np.float32)) if j == ai else args[j]
                   for j in range(len(args))]
            a_m = [jnp.asarray(xm.astype(np.float32)) if j == ai else args[j]
                   for j in range(len(args))]
            fd[ix] = (float(loss_fn(*a_p)) - float(loss_fn(*a_m))) / (2 * eps)
            it.iternext()
        np.testing.assert_allclose(np.asarray(gi, np.float64), fd,
                                   rtol=rtol, atol=atol,
                                   err_msg="%s grad wrt input %d" % (name, ai))


# ---------------------------------------------------------------------------
# family tables
# ---------------------------------------------------------------------------
def _pos(shape):          # strictly positive, away from 0
    return (RNG.rand(*shape) * 1.5 + 0.3).astype(np.float32)


def _unit(shape):         # inside (-0.9, 0.9)
    return (RNG.rand(*shape) * 1.6 - 0.8).astype(np.float32)


def _gen(shape):          # generic, away from non-smooth points
    return (RNG.rand(*shape) * 3.0 - 1.5 + 0.25).astype(np.float32)


S = (2, 3)

UNARY = {
    # name: (np ref, input generator, differentiable)
    "abs": (np.abs, _gen, False),           # kink at 0 (inputs avoid it but
    "negative": (lambda x: -x, _gen, True),  # keep fd stable: mark smooth only
    "reciprocal": (lambda x: 1 / x, _pos, True),
    "square": (np.square, _gen, True),
    "sqrt": (np.sqrt, _pos, True),
    "rsqrt": (lambda x: 1 / np.sqrt(x), _pos, True),
    "exp": (np.exp, _unit, True),
    "expm1": (np.expm1, _unit, True),
    "log": (np.log, _pos, True),
    "log1p": (np.log1p, _pos, True),
    "log2": (np.log2, _pos, True),
    "log10": (np.log10, _pos, True),
    "sin": (np.sin, _gen, True),
    "cos": (np.cos, _gen, True),
    "tan": (np.tan, _unit, True),
    "arcsin": (np.arcsin, _unit, True),
    "arccos": (np.arccos, _unit, True),
    "arctan": (np.arctan, _gen, True),
    "sinh": (np.sinh, _unit, True),
    "cosh": (np.cosh, _unit, True),
    "tanh": (np.tanh, _gen, True),
    "arcsinh": (np.arcsinh, _gen, True),
    "arccosh": (lambda x: np.arccosh(x), lambda s: _pos(s) + 1.0, True),
    "arctanh": (np.arctanh, _unit, True),
    "degrees": (np.degrees, _gen, True),
    "radians": (np.radians, _gen, True),
    "sign": (np.sign, _gen, False),
    "floor": (np.floor, _gen, False),
    "ceil": (np.ceil, _gen, False),
    "round": (np.round, _gen, False),
    "rint": (np.rint, _gen, False),
    "fix": (np.trunc, _gen, False),
    "sigmoid": (lambda x: 1 / (1 + np.exp(-x)), _gen, True),
    "relu": (lambda x: np.maximum(x, 0), _gen, False),
    "softsign": (lambda x: x / (1 + np.abs(x)), _gen, True),
    "erf": (None, _gen, True),              # scipy-free: fd-grad only
    "gamma": (None, _pos, True),
    "gammaln": (None, _pos, True),
    "identity": (lambda x: x, _gen, True),
    "_copy": (lambda x: x, _gen, True),
    "stop_gradient": (lambda x: x, _gen, False),
    "BlockGrad": (lambda x: x, _gen, False),
    "argmax_channel": (lambda x: np.argmax(x, 1).astype(np.float32), _gen,
                       False),
}

BINARY = {
    "_add": np.add, "_plus": np.add, "elemwise_add": np.add,
    "_sub": np.subtract, "_minus": np.subtract, "elemwise_sub": np.subtract,
    "_mul": np.multiply, "elemwise_mul": np.multiply,
    "_div": np.divide, "elemwise_div": np.divide,
    "_mod": np.mod, "elemwise_mod": np.mod,
    "_power": np.power, "elemwise_power": np.power,
    "_maximum": np.maximum, "elemwise_maximum": np.maximum,
    "maximum": np.maximum,
    "_minimum": np.minimum, "elemwise_minimum": np.minimum,
    "minimum": np.minimum,
    "_hypot": np.hypot, "elemwise_hypot": np.hypot,
    "_equal": lambda a, b: (a == b).astype(np.float32),
    "elemwise_equal": lambda a, b: (a == b).astype(np.float32),
    "_not_equal": lambda a, b: (a != b).astype(np.float32),
    "elemwise_not_equal": lambda a, b: (a != b).astype(np.float32),
    "_greater": lambda a, b: (a > b).astype(np.float32),
    "elemwise_greater": lambda a, b: (a > b).astype(np.float32),
    "_greater_equal": lambda a, b: (a >= b).astype(np.float32),
    "elemwise_greater_equal": lambda a, b: (a >= b).astype(np.float32),
    "_lesser": lambda a, b: (a < b).astype(np.float32),
    "elemwise_lesser": lambda a, b: (a < b).astype(np.float32),
    "_lesser_equal": lambda a, b: (a <= b).astype(np.float32),
    "elemwise_lesser_equal": lambda a, b: (a <= b).astype(np.float32),
    "_grad_add": np.add,
}
_BINARY_DIFF = {"_add", "_plus", "elemwise_add", "_sub", "_minus",
                "elemwise_sub", "_mul", "elemwise_mul", "_div",
                "elemwise_div", "_power", "elemwise_power", "_hypot",
                "elemwise_hypot", "_grad_add"}

SCALAR = {
    "_add_scalar": lambda x, s: x + s,
    "_plus_scalar": lambda x, s: x + s,
    "_minus_scalar": lambda x, s: x - s,
    "_sub_scalar": lambda x, s: x - s,
    "_rminus_scalar": lambda x, s: s - x,
    "_mul_scalar": lambda x, s: x * s,
    "_div_scalar": lambda x, s: x / s,
    "_rdiv_scalar": lambda x, s: s / x,
    "_mod_scalar": lambda x, s: np.mod(x, s),
    "_rmod_scalar": lambda x, s: np.mod(s, x),
    "_power_scalar": lambda x, s: np.power(x, s),
    "_rpower_scalar": lambda x, s: np.power(s, x),
    "_hypot_scalar": lambda x, s: np.hypot(x, s),
    "_maximum_scalar": lambda x, s: np.maximum(x, s),
    "_minimum_scalar": lambda x, s: np.minimum(x, s),
    "_equal_scalar": lambda x, s: (x == s).astype(np.float32),
    "_not_equal_scalar": lambda x, s: (x != s).astype(np.float32),
    "_greater_scalar": lambda x, s: (x > s).astype(np.float32),
    "_greater_equal_scalar": lambda x, s: (x >= s).astype(np.float32),
    "_lesser_scalar": lambda x, s: (x < s).astype(np.float32),
    "_lesser_equal_scalar": lambda x, s: (x <= s).astype(np.float32),
}

BROADCAST = {
    "broadcast_add": np.add, "broadcast_plus": np.add,
    "broadcast_sub": np.subtract, "broadcast_minus": np.subtract,
    "broadcast_mul": np.multiply, "broadcast_div": np.divide,
    "broadcast_mod": np.mod, "broadcast_power": np.power,
    "broadcast_maximum": np.maximum, "broadcast_minimum": np.minimum,
    "broadcast_hypot": np.hypot,
    "broadcast_equal": lambda a, b: (a == b).astype(np.float32),
    "broadcast_not_equal": lambda a, b: (a != b).astype(np.float32),
    "broadcast_greater": lambda a, b: (a > b).astype(np.float32),
    "broadcast_greater_equal": lambda a, b: (a >= b).astype(np.float32),
    "broadcast_lesser": lambda a, b: (a < b).astype(np.float32),
    "broadcast_lesser_equal": lambda a, b: (a <= b).astype(np.float32),
}
_BCAST_DIFF = {"broadcast_add", "broadcast_plus", "broadcast_sub",
               "broadcast_minus", "broadcast_mul", "broadcast_div",
               "broadcast_power", "broadcast_hypot"}

REDUCE = {
    # name: (np ref with axis kw, attrs)
    "sum": (lambda x: x.sum(1), {"axis": "1"}),
    "sum_axis": (lambda x: x.sum(1), {"axis": "1"}),
    "mean": (lambda x: x.mean(1), {"axis": "1"}),
    "prod": (lambda x: x.prod(1), {"axis": "1"}),
    "max": (lambda x: x.max(1), {"axis": "1"}),
    "max_axis": (lambda x: x.max(1), {"axis": "1"}),
    "min": (lambda x: x.min(1), {"axis": "1"}),
    "min_axis": (lambda x: x.min(1), {"axis": "1"}),
    "nansum": (lambda x: np.nansum(x, 1), {"axis": "1"}),
    "nanprod": (lambda x: np.nanprod(x, 1), {"axis": "1"}),
    "norm": (lambda x: np.asarray(np.sqrt((x * x).sum())), {}),
    "argmax": (lambda x: np.argmax(x, 1).astype(np.float32), {"axis": "1"}),
    "argmin": (lambda x: np.argmin(x, 1).astype(np.float32), {"axis": "1"}),
}
_REDUCE_DIFF = {"sum", "sum_axis", "mean", "prod", "nansum"}

SHAPE_OPS = {
    # name: (inputs, attrs, np ref or None)
    "Reshape": ([_gen((2, 6))], {"shape": "(3, 4)"},
                lambda x: x.reshape(3, 4)),
    "reshape": ([_gen((2, 6))], {"shape": "(3, 4)"},
                lambda x: x.reshape(3, 4)),
    "Flatten": ([_gen((2, 3, 4))], {}, lambda x: x.reshape(2, 12)),
    "flatten": ([_gen((2, 3, 4))], {}, lambda x: x.reshape(2, 12)),
    "transpose": ([_gen((2, 3, 4))], {"axes": "(2, 0, 1)"},
                  lambda x: x.transpose(2, 0, 1)),
    "expand_dims": ([_gen((2, 3))], {"axis": "1"},
                    lambda x: x[:, None, :]),
    "SwapAxis": ([_gen((2, 3, 4))], {"dim1": "0", "dim2": "2"},
                 lambda x: x.swapaxes(0, 2)),
    "swapaxes": ([_gen((2, 3, 4))], {"dim1": "0", "dim2": "2"},
                 lambda x: x.swapaxes(0, 2)),
    "tile": ([_gen((2, 3))], {"reps": "(2, 2)"},
             lambda x: np.tile(x, (2, 2))),
    "repeat": ([_gen((2, 3))], {"repeats": "2", "axis": "1"},
               lambda x: np.repeat(x, 2, 1)),
    "flip": ([_gen((2, 3))], {"axis": "1"}, lambda x: x[:, ::-1]),
    "reverse": ([_gen((2, 3))], {"axis": "1"}, lambda x: x[:, ::-1]),
    "slice": ([_gen((4, 5))], {"begin": "(1, 0)", "end": "(3, 4)"},
              lambda x: x[1:3, 0:4]),
    "slice_axis": ([_gen((4, 5))], {"axis": "1", "begin": "1", "end": "4"},
                   lambda x: x[:, 1:4]),
    "clip": ([_gen((3, 4))], {"a_min": "-0.5", "a_max": "0.5"},
             lambda x: np.clip(x, -0.5, 0.5)),
    "broadcast_to": ([_gen((1, 3))], {"shape": "(4, 3)"},
                     lambda x: np.broadcast_to(x, (4, 3))),
    "broadcast_axis": ([_gen((1, 3))], {"axis": "0", "size": "4"},
                       lambda x: np.broadcast_to(x, (4, 3))),
    "broadcast_axes": ([_gen((1, 3))], {"axis": "0", "size": "4"},
                       lambda x: np.broadcast_to(x, (4, 3))),
    "zeros_like": ([_gen(S)], {}, np.zeros_like),
    "ones_like": ([_gen(S)], {}, np.ones_like),
    "cast": ([_gen(S)], {"dtype": "int32"},
             lambda x: x.astype(np.int32)),
    "Cast": ([_gen(S)], {"dtype": "int32"},
             lambda x: x.astype(np.int32)),
    "sort": ([_gen((3, 5))], {"axis": "1"}, lambda x: np.sort(x, 1)),
    "argsort": ([_gen((3, 5))], {"axis": "1"},
                lambda x: np.argsort(x, 1).astype(np.float32)),
    "one_hot": ([np.array([0, 2, 1], np.float32)], {"depth": "3"},
                lambda x: np.eye(3, dtype=np.float32)[x.astype(int)]),
    "where": ([np.array([1, 0, 1], np.float32), _gen((3,)), _gen((3,))],
              {}, lambda c, a, b: np.where(c > 0, a, b)),
    "smooth_l1": ([_gen(S)], {"scalar": "1.0"},
                  lambda x: np.where(np.abs(x) < 1, 0.5 * x * x,
                                     np.abs(x) - 0.5)),
    "log_softmax": ([_gen(S)], {"axis": "1"},
                    lambda x: x - x.max(1, keepdims=True)
                    - np.log(np.exp(x - x.max(1, keepdims=True))
                             .sum(1, keepdims=True))),
    "softmax": ([_gen(S)], {"axis": "1"},
                lambda x: np.exp(x - x.max(1, keepdims=True))
                / np.exp(x - x.max(1, keepdims=True)).sum(1, keepdims=True)),
    "take": ([_gen((4, 3)), np.array([0, 2], np.float32)], {},
             lambda x, i: x[i.astype(int)]),
    "batch_take": ([_gen((3, 4)), np.array([0, 2, 1], np.float32)], {},
                   lambda x, i: x[np.arange(3), i.astype(int)]),
    "dot": ([_gen((2, 3)), _gen((3, 4))], {}, lambda a, b: a @ b),
    "batch_dot": ([_gen((2, 2, 3)), _gen((2, 3, 4))], {},
                  lambda a, b: np.einsum("bij,bjk->bik", a, b)),
    "Concat": ([_gen((2, 2)), _gen((2, 3))], {"dim": "1", "num_args": "2"},
               lambda a, b: np.concatenate([a, b], 1)),
    "concat": ([_gen((2, 2)), _gen((2, 3))], {"dim": "1", "num_args": "2"},
               lambda a, b: np.concatenate([a, b], 1)),
    "Pad": ([_gen((2, 3, 4, 4))],
            {"mode": "constant", "pad_width": "(0,0,0,0,1,1,2,2)"},
            lambda x: np.pad(x, ((0, 0), (0, 0), (1, 1), (2, 2)))),
    "pad": ([_gen((2, 3, 4, 4))],
            {"mode": "constant", "pad_width": "(0,0,0,0,1,1,2,2)"},
            lambda x: np.pad(x, ((0, 0), (0, 0), (1, 1), (2, 2)))),
    "Embedding": ([np.array([0, 2], np.float32), _gen((4, 5))],
                  {"input_dim": "4", "output_dim": "5"},
                  lambda i, w: w[i.astype(int)]),
}
_SHAPE_DIFF = {"Reshape", "reshape", "Flatten", "flatten", "transpose",
               "expand_dims", "SwapAxis", "swapaxes", "tile", "repeat",
               "flip", "broadcast_to", "slice",
               "slice_axis", "dot", "batch_dot", "Concat", "concat",
               "smooth_l1", "log_softmax", "softmax"}

INIT_OPS = {
    "_zeros": ({"shape": "(2, 3)"}, np.zeros((2, 3), np.float32)),
    "_ones": ({"shape": "(2, 3)"}, np.ones((2, 3), np.float32)),
    "_full": ({"shape": "(2, 3)", "value": "2.5"},
              np.full((2, 3), 2.5, np.float32)),
    "_arange": ({"start": "1", "stop": "7", "step": "2"},
                np.arange(1, 7, 2, dtype=np.float32)),
}

RANDOM_OPS = {
    "_random_uniform": {"low": "0", "high": "1", "shape": "(500,)"},
    "_random_normal": {"loc": "0", "scale": "1", "shape": "(500,)"},
    "_random_exponential": {"lam": "1.0", "shape": "(500,)"},
    "_random_gamma": {"alpha": "2.0", "beta": "1.0", "shape": "(500,)"},
    "_random_poisson": {"lam": "3.0", "shape": "(500,)"},
    "_random_negative_binomial": {"k": "3", "p": "0.5", "shape": "(500,)"},
    "random_uniform": {"shape": "(500,)"},
    "random_normal": {"shape": "(500,)"},
    "uniform": {"shape": "(500,)"},
    "normal": {"shape": "(500,)"},
}

SAMPLE_OPS = {
    "_sample_uniform": [np.array([0.0, 1.0], np.float32),
                        np.array([1.0, 2.0], np.float32)],
    "_sample_normal": [np.array([0.0, 5.0], np.float32),
                       np.array([1.0, 0.1], np.float32)],
    "_sample_exponential": [np.array([1.0, 4.0], np.float32)],
    "_sample_gamma": [np.array([2.0, 3.0], np.float32),
                      np.array([1.0, 1.0], np.float32)],
    "_sample_poisson": [np.array([2.0, 9.0], np.float32)],
    "_sample_negbinomial": [np.array([3.0, 5.0], np.float32),
                            np.array([0.5, 0.5], np.float32)],
}

# ops proven in dedicated suites; this sweep must not double-maintain them
COVERED_ELSEWHERE = {
    "TransformerStack":
        "test_lm_flagship/test_serving (models.transformer builds the "
        "whole LM through it)",
    "Activation": "test_operator", "BatchNorm": "test_operator/test_pallas",
    "Convolution": "test_operator", "Deconvolution": "test_operator",
    "FullyConnected": "test_operator", "Pooling": "test_operator",
    "Dropout": "test_autograd", "LRN": "test_operator",
    "InstanceNorm": "test_operator", "L2Normalization": "test_operator",
    "LayerNorm": "test_attention", "MultiHeadAttention": "test_attention",
    "LeakyReLU": "test_operator", "SoftmaxActivation": "test_operator",
    "SoftmaxOutput": "test_operator/test_models",
    "Softmax": "alias->SoftmaxOutput (test_operator)",
    "LinearRegressionOutput": "test_operator",
    "LogisticRegressionOutput": "test_operator",
    "MAERegressionOutput": "test_operator", "SVMOutput": "test_operator",
    "MakeLoss": "test_operator",
    "IdentityAttachKLSparseReg": "test_operator",
    "RNN": "test_rnn", "SequenceLast": "test_operator",
    "SequenceMask": "test_operator", "SequenceReverse": "test_operator",
    "SliceChannel": "test_operator", "split": "test_operator",
    "UpSampling": "test_operator", "Crop": "test_operator",
    "crop": "test_operator",
    "SpatialTransformer": "test_contrib_spatial",
    "GridGenerator": "test_contrib_spatial",
    "BilinearSampler": "test_contrib_spatial",
    "Correlation": "test_contrib_spatial",
    "ROIPooling": "test_contrib_spatial",
    "MultiBoxPrior": "test_ssd", "MultiBoxTarget": "test_ssd",
    "MultiBoxDetection": "test_ssd",
    "_contrib_MultiBoxPrior": "alias->test_ssd",
    "_contrib_MultiBoxTarget": "alias->test_ssd",
    "_contrib_MultiBoxDetection": "alias->test_ssd",
    "CTCLoss": "test_contrib_spatial", "ctc_loss": "alias",
    "_contrib_CTCLoss": "alias",
    "fft": "test_contrib_spatial", "ifft": "test_contrib_spatial",
    "_contrib_fft": "alias", "_contrib_ifft": "alias",
    "count_sketch": "test_contrib_spatial", "_contrib_count_sketch": "alias",
    "quantize": "test_contrib_spatial", "dequantize": "test_contrib_spatial",
    "_contrib_quantize": "alias", "_contrib_dequantize": "alias",
    "sgd_update": "test_fused_step", "sgd_mom_update": "test_fused_step",
    "adam_update": "test_fused_step", "rmsprop_update": "test_fused_step",
    "rmspropalex_update": "test_fused_step",
    "Custom": "test_custom_op_capi",
    "topk": "test_operator",
}

SKIP = {}  # name -> reason; empty on purpose: everything must be covered


# ---------------------------------------------------------------------------
# tests
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("name", sorted(UNARY))
def test_unary_forward(name):
    ref, gen, _ = UNARY[name]
    x = gen(S)
    out = run_op(name, [x])[0]
    if ref is not None:
        np.testing.assert_allclose(out, ref(x), rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("name",
                         sorted(n for n, v in UNARY.items() if v[2]))
def test_unary_gradient(name):
    _, gen, _ = UNARY[name]
    fd_grad_check(name, [gen(S)])


@pytest.mark.parametrize("name", sorted(BINARY))
def test_binary_forward(name):
    a, b = _pos(S), _pos(S)
    np.testing.assert_allclose(run_op(name, [a, b])[0], BINARY[name](a, b),
                               rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("name", sorted(_BINARY_DIFF))
def test_binary_gradient(name):
    fd_grad_check(name, [_pos(S), _pos(S)])


@pytest.mark.parametrize("name", sorted(SCALAR))
def test_scalar_forward(name):
    x = _pos(S)
    got = run_op(name, [x], {"scalar": "2.0"})[0]
    np.testing.assert_allclose(got, SCALAR[name](x, 2.0), rtol=1e-4,
                               atol=1e-5)


@pytest.mark.parametrize("name", sorted(BROADCAST))
def test_broadcast_forward(name):
    a, b = _pos((2, 3, 4)), _pos((1, 3, 1))
    np.testing.assert_allclose(run_op(name, [a, b])[0],
                               BROADCAST[name](a, b), rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("name", sorted(_BCAST_DIFF))
def test_broadcast_gradient(name):
    fd_grad_check(name, [_pos((2, 3)), _pos((1, 3))])


@pytest.mark.parametrize("name", sorted(REDUCE))
def test_reduce_forward(name):
    ref, attrs = REDUCE[name]
    x = _pos((3, 4))
    np.testing.assert_allclose(np.squeeze(run_op(name, [x], attrs)[0]),
                               ref(x), rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("name", sorted(_REDUCE_DIFF))
def test_reduce_gradient(name):
    _, attrs = REDUCE[name]
    fd_grad_check(name, [_pos((3, 4))], attrs)


@pytest.mark.parametrize("name", sorted(SHAPE_OPS))
def test_shape_op_forward(name):
    inputs, attrs, ref = SHAPE_OPS[name]
    out = run_op(name, inputs, attrs)[0]
    if ref is not None:
        np.testing.assert_allclose(np.asarray(out, np.float64),
                                   np.asarray(ref(*inputs), np.float64),
                                   rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("name", sorted(_SHAPE_DIFF))
def test_shape_op_gradient(name):
    inputs, attrs, _ = SHAPE_OPS[name]
    fd_grad_check(name, inputs, attrs,
                  wrt=[i for i, x in enumerate(inputs)
                       if np.asarray(x).dtype == np.float32][:2])


@pytest.mark.parametrize("name", sorted(INIT_OPS))
def test_init_op(name):
    attrs, expect = INIT_OPS[name]
    np.testing.assert_array_equal(run_op(name, [], attrs)[0], expect)


@pytest.mark.parametrize("name", sorted(RANDOM_OPS))
def test_random_op_runs_and_moments(name):
    out = run_op(name, [], RANDOM_OPS[name])[0]
    assert out.shape == (500,)
    assert np.isfinite(out).all()
    # two draws differ (seeded stream advances)
    out2 = run_op(name, [], RANDOM_OPS[name])[0]
    assert not np.array_equal(out, out2)


@pytest.mark.parametrize("name", sorted(SAMPLE_OPS))
def test_sample_op_runs(name):
    params = SAMPLE_OPS[name]
    out = run_op(name, params, {"shape": "(200,)"})[0]
    assert out.shape == (len(params[0]), 200)
    assert np.isfinite(out).all()


def test_registry_coverage():
    """Every registered op is exercised here, covered by a dedicated test
    file, or skip-listed with a reason."""
    here = (set(UNARY) | set(BINARY) | set(SCALAR) | set(BROADCAST)
            | set(REDUCE) | set(SHAPE_OPS) | set(INIT_OPS)
            | set(RANDOM_OPS) | set(SAMPLE_OPS))
    known = here | set(COVERED_ELSEWHERE) | set(SKIP)
    missing = [n for n in _reg.list_ops() if n not in known]
    assert not missing, "ops with no test coverage: %s" % missing
    exercised = here | {n for n in COVERED_ELSEWHERE}
    assert len(exercised) >= 200, len(exercised)


# ---------------------------------------------------------------------------
# extended gradient coverage: indexed / select / pad family
# ---------------------------------------------------------------------------
def test_embedding_gradient_wrt_weight():
    idx = np.array([0, 2, 1, 2], np.float32)
    w = _gen((4, 5))
    fd_grad_check("Embedding", [idx, w],
                  {"input_dim": "4", "output_dim": "5"}, wrt=[1])


def test_take_gradient_wrt_data():
    fd_grad_check("take", [_gen((4, 3)), np.array([0, 2, 2], np.float32)],
                  wrt=[0])


def test_where_gradient_wrt_branches():
    cond = np.array([1, 0, 1, 0], np.float32)
    fd_grad_check("where", [cond, _gen((4,)), _gen((4,))], wrt=[1, 2])


def test_pad_gradient():
    fd_grad_check("Pad", [_gen((1, 2, 3, 3))],
                  {"mode": "constant", "pad_width": "(0,0,0,0,1,1,1,1)"})


def test_clip_gradient_interior():
    x = (_unit(S) * 0.35)            # strictly inside (-0.5, 0.5): smooth
    fd_grad_check("clip", [x], {"a_min": "-0.5", "a_max": "0.5"})

