"""cpp-package smoke (C++ client over the compiled ABI) and the legacy
executor_manager API (ref: cpp-package/example/mlp.cpp,
python/mxnet/executor_manager.py)."""
import os
import shutil
import subprocess

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import executor_manager as em

ROOT = os.path.join(os.path.dirname(__file__), "..")


@pytest.mark.skipif(shutil.which("c++") is None, reason="no C++ toolchain")
def test_cpp_package_trains():
    lib = os.path.join(ROOT, "lib", "libmxnet_tpu.so")
    if not os.path.exists(lib):
        r = subprocess.run(["make", "-C", os.path.join(ROOT, "src", "capi")],
                           capture_output=True, text=True, timeout=300)
        assert r.returncode == 0, r.stdout + r.stderr
    binp = os.path.join(ROOT, "lib", "train_mlp_cpp")
    src = os.path.join(ROOT, "cpp-package", "example", "train_mlp.cpp")
    if (not os.path.exists(binp)
            or os.path.getmtime(src) > os.path.getmtime(binp)):
        r = subprocess.run(
            ["c++", "-O2", "-std=c++14",
             "-I", os.path.join(ROOT, "cpp-package", "include"),
             src, "-L", os.path.join(ROOT, "lib"), "-lmxnet_tpu",
             "-Wl,-rpath,$ORIGIN", "-o", binp],
            capture_output=True, text=True, timeout=300)
        assert r.returncode == 0, r.stderr
    env = dict(os.environ)
    env["PYTHONPATH"] = ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run([binp], capture_output=True, text=True, env=env,
                       timeout=600)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "CPP SMOKE PASS" in r.stdout


def test_split_input_slice():
    s = em._split_input_slice(10, [1, 1, 2])
    assert s == [slice(0, 2), slice(2, 4), slice(4, 10)]
    assert em._split_input_slice(4, [1]) == [slice(0, 4)]
    with pytest.raises(ValueError):
        em._split_input_slice(2, [1, 1, 1])


def test_executor_manager_train_step():
    data = mx.sym.Variable("data")
    net = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(data, num_hidden=4, name="fc"),
        name="softmax")
    rng = np.random.RandomState(0)
    X = rng.rand(8, 6).astype(np.float32)
    Y = rng.randint(0, 4, 8).astype(np.float32)
    it = mx.io.NDArrayIter(X, Y, batch_size=8)
    m = em.DataParallelExecutorManager(net, mx.cpu(), it)
    shapes, _, _ = net.infer_shape(data=(8, 6), softmax_label=(8,))
    init = mx.initializer.Xavier()
    arg_params = {}
    for n, s in zip(net.list_arguments(), shapes):
        if n in ("data", "softmax_label"):
            continue
        a = mx.nd.zeros(s)
        init(mx.initializer.InitDesc(n), a)
        arg_params[n] = a
    m.set_params(arg_params, {})
    b = it.next()
    m.load_data_batch(b)
    m.forward(is_train=True)
    m.backward()
    assert float(np.abs(m.grad_arrays[0].asnumpy()).sum()) > 0
    metric = mx.metric.Accuracy()
    m.update_metric(metric, b.label)
    out_a, out_x = {}, {}
    m.copy_to(out_a, out_x)
    assert set(out_a) == {"fc_weight", "fc_bias"}
    assert m.param_arrays[0].shape == (4, 6)


def test_executor_manager_rejects_bad_workload():
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=2)
    it = mx.io.NDArrayIter(np.zeros((4, 3), np.float32),
                           np.zeros(4, np.float32), batch_size=4)
    with pytest.raises(mx.base.MXNetError):
        em.DataParallelExecutorManager(net, mx.cpu(), it,
                                       work_load_list=[1, 2])
