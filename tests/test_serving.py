"""Serving-tier tests (docs/serving.md): AOT shape-bucketed engine,
dynamic batcher, continuous-batching decode loop, fault shedding.

The load-bearing assertions:

* batched ``serving.infer()`` output is BITWISE equal to unbatched
  ``Predictor.forward`` on the same rows — padding to a bucket never leaks
  into real examples;
* the serving program set (every AOT bucket + the decode body) audits
  clean under tracecheck, donation of the KV cache included;
* greedy decode through the slot loop is token-for-token identical to
  full re-forward decoding, across sequences joining and leaving
  mid-stream;
* a killed decode loop / closed batcher sheds in-flight requests with a
  clear error instead of hanging callers.
"""
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import faults, models, serving  # noqa: E402
from mxnet_tpu.base import MXNetError  # noqa: E402


# ---------------------------------------------------------------------------
# fixtures
# ---------------------------------------------------------------------------

def _mlp_sym():
    net = mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=8,
                                name="fc1")
    net = mx.sym.Activation(net, act_type="relu", name="relu1")
    net = mx.sym.FullyConnected(net, num_hidden=4, name="fc2")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def _mlp_params(seed=0):
    rs = np.random.RandomState(seed)
    return {
        "arg:fc1_weight": rs.randn(8, 6).astype(np.float32) * 0.5,
        "arg:fc1_bias": rs.randn(8).astype(np.float32) * 0.1,
        "arg:fc2_weight": rs.randn(4, 8).astype(np.float32) * 0.5,
        "arg:fc2_bias": rs.randn(4).astype(np.float32) * 0.1,
    }


def _engine(buckets=(4, 8), **kw):
    return serving.ServingEngine(_mlp_sym(), _mlp_params(), {"data": (6,)},
                                 buckets=buckets, **kw)


def _x(n, seed=1):
    return np.random.RandomState(seed).rand(n, 6).astype(np.float32)


# ---------------------------------------------------------------------------
# engine: buckets, padding parity, chunking, export
# ---------------------------------------------------------------------------

def test_engine_bucket_selection():
    eng = _engine(buckets=(2, 4, 16))
    assert eng.bucket_for(1) == 2
    assert eng.bucket_for(2) == 2
    assert eng.bucket_for(3) == 4
    assert eng.bucket_for(16) == 16
    with pytest.raises(MXNetError):
        eng.bucket_for(17)
    assert eng.max_batch == 16


def test_engine_pad_parity_bitwise_vs_predictor():
    """Acceptance: batched serving.infer == unbatched Predictor.forward,
    bitwise — the pad rows added to reach the bucket never leak."""
    eng = _engine(buckets=(4, 8))
    x = _x(3)
    out = eng.infer({"data": x})[0]           # padded 3 -> bucket 4
    params = {k: mx.nd.array(v) for k, v in _mlp_params().items()}
    pred = mx.Predictor(_mlp_sym(), params, {"data": (3, 6)})
    ref = pred.forward(data=x).get_output(0).asnumpy()
    assert out.shape == (3, 4)
    assert np.array_equal(out, ref)


def test_engine_pad_content_never_leaks():
    """Same rows, different co-riders/padding -> bitwise-identical rows."""
    eng = _engine(buckets=(4,))
    x = _x(3)
    a = eng.infer({"data": x})[0]             # zero-padded internally
    junk = np.full((1, 6), 1e6, np.float32)   # hostile 4th row
    b = eng.infer({"data": np.concatenate([x, junk])})[0][:3]
    assert np.array_equal(a, b)


def test_engine_chunks_requests_larger_than_max_bucket():
    eng = _engine(buckets=(4, 8))
    x = _x(19)
    out = eng.infer({"data": x})[0]
    assert out.shape == (19, 4)
    ref = eng.infer({"data": x[:4]})[0]
    assert np.array_equal(out[:4], ref)


def test_engine_input_validation():
    eng = _engine(buckets=(4,))
    with pytest.raises(MXNetError):
        eng.infer({})                          # missing input
    with pytest.raises(MXNetError):
        eng.infer({"data": np.zeros((2, 7), np.float32)})  # bad shape
    with pytest.raises(MXNetError):
        eng.infer({"data": np.zeros((0, 6), np.float32)})  # empty


def test_engine_missing_param_raises_by_name():
    params = _mlp_params()
    del params["arg:fc2_bias"]
    with pytest.raises(MXNetError, match="fc2_bias"):
        serving.ServingEngine(_mlp_sym(), params, {"data": (6,)},
                              buckets=(4,))
    # deliberate zero-fill still available
    eng = serving.ServingEngine(_mlp_sym(), params, {"data": (6,)},
                                buckets=(4,), allow_missing=True)
    out = eng.infer({"data": _x(2)})[0]
    assert np.all(np.isfinite(out))


def test_engine_export_import_cold_start(tmp_path):
    eng = _engine(buckets=(4, 8))
    x = _x(5)
    ref = eng.infer({"data": x})[0]
    path = str(tmp_path / "exe.bin")
    try:
        eng.export_compiled(path)
    except MXNetError:
        pytest.skip("backend cannot serialize executables")
    eng2 = serving.ServingEngine(_mlp_sym(), _mlp_params(), {"data": (6,)},
                                 buckets=(4, 8), executables=path)
    assert np.array_equal(eng2.infer({"data": x})[0], ref)


def test_engine_stale_executables_fall_back(tmp_path):
    eng = _engine(buckets=(4,))
    path = str(tmp_path / "exe.bin")
    try:
        eng.export_compiled(path)
    except MXNetError:
        pytest.skip("backend cannot serialize executables")
    # different bucket set: must warn + recompile, not serve stale programs
    eng2 = serving.ServingEngine(_mlp_sym(), _mlp_params(), {"data": (6,)},
                                 buckets=(2,), executables=path)
    out = eng2.infer({"data": _x(2)})[0]
    assert out.shape == (2, 4)


def test_engine_tracecheck_clean():
    """The serving bucket programs gate at zero findings, like the train
    step programs (ci/serve.sh runs the same audit)."""
    eng = _engine(buckets=(2, 4))
    findings = eng.check()
    assert [f.format() for f in findings] == []


# ---------------------------------------------------------------------------
# batcher
# ---------------------------------------------------------------------------

def test_batcher_coalesces_backlog_into_one_bucket():
    eng = _engine(buckets=(4, 8))
    b = serving.Batcher(eng, max_latency_ms=50.0, start=False)
    x = _x(3)
    reqs = [b.submit({"data": x[i:i + 1]}) for i in range(3)]
    before = eng.health.batches
    b.start()
    outs = [b.wait(r) for r in reqs]
    got = np.concatenate([o[0] for o in outs])
    params = {k: mx.nd.array(v) for k, v in _mlp_params().items()}
    pred = mx.Predictor(_mlp_sym(), params, {"data": (3, 6)})
    ref = pred.forward(data=x).get_output(0).asnumpy()
    assert np.array_equal(got, ref)
    # the backlog coalesced: one dispatch for all three requests
    assert eng.health.batches == before + 1
    assert b.health.requests == 3
    b.close()


def test_batcher_concurrent_callers_bitwise():
    import threading
    eng = _engine(buckets=(4, 8))
    b = serving.Batcher(eng, max_latency_ms=20.0)
    x = _x(8)
    results = [None] * 8
    errs = []

    def call(i):
        try:
            results[i] = b.infer({"data": x[i:i + 1]})[0]
        except Exception as e:   # surface in the main thread
            errs.append(e)

    threads = [threading.Thread(target=call, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs
    got = np.concatenate(results)
    ref = eng.infer({"data": x})[0]
    assert np.array_equal(got, ref)
    b.close()


def test_batcher_request_deadline_expires():
    eng = _engine(buckets=(4,))
    b = serving.Batcher(eng, start=False)
    req = b.submit({"data": _x(1)}, deadline_ms=0.0)
    b.start()
    with pytest.raises(serving.ServingDeadlineError):
        b.wait(req)
    assert b.health.expired >= 1
    b.close()


def test_batcher_backpressure_bounded_queue():
    eng = _engine(buckets=(4,))
    b = serving.Batcher(eng, queue_size=1, start=False)
    b.submit({"data": _x(1)})
    with pytest.raises(serving.ServingOverloadedError):
        b.submit({"data": _x(1)})
    assert b.health.dropped == 1
    b.close()


def test_batcher_oversized_request_rejected():
    eng = _engine(buckets=(4,))
    b = serving.Batcher(eng, start=False)
    with pytest.raises(MXNetError, match="max_batch"):
        b.submit({"data": _x(5)})
    b.close()


def test_batcher_rejects_malformed_shape_at_submit():
    """A bad per-example shape is rejected ALONE at submit — once
    coalesced it would fail every innocent co-rider in its batch."""
    eng = _engine(buckets=(4,))
    b = serving.Batcher(eng, max_latency_ms=50.0, start=False)
    good = b.submit({"data": _x(1)})
    with pytest.raises(MXNetError, match="per-example shape"):
        b.submit({"data": np.zeros((1, 7), np.float32)})
    b.start()
    out = b.wait(good)[0]          # the valid request is unaffected
    assert out.shape == (1, 4)
    b.close()


def test_batcher_close_sheds_queued_requests():
    eng = _engine(buckets=(4,))
    b = serving.Batcher(eng, start=False)
    r1 = b.submit({"data": _x(1)})
    r2 = b.submit({"data": _x(1)})
    b.close()
    for r in (r1, r2):
        with pytest.raises(serving.ServingClosedError):
            b.wait(r)
    assert b.health.shed == 2
    with pytest.raises(serving.ServingClosedError):
        b.submit({"data": _x(1)})


@pytest.mark.faults
def test_fault_enqueue_drop_rejects_with_clear_error():
    eng = _engine(buckets=(4,))
    b = serving.Batcher(eng, start=False)
    with faults.scoped("serve.enqueue_drop", nth=2, kind="drop"):
        b.submit({"data": _x(1)})              # call 1: clean
        with pytest.raises(serving.ServingOverloadedError,
                           match="enqueue"):
            b.submit({"data": _x(1)})          # call 2: dropped
    assert b.health.dropped == 1
    b.close()


# ---------------------------------------------------------------------------
# continuous-batching decode loop
# ---------------------------------------------------------------------------

_LM = dict(vocab_size=17, embed=16, num_heads=2, num_layers=2, seq_len=12)


def _lm_setup(seed=3):
    sym = models.transformer(**_LM)
    s = _LM["seq_len"]
    arg_shapes, _, _ = sym.infer_shape(data=(1, s), softmax_label=(1, s))
    rs = np.random.RandomState(seed)
    params = {}
    for n, shp in zip(sym.list_arguments(), arg_shapes):
        if n in ("data", "softmax_label"):
            continue
        params[n] = (rs.randn(*shp) * 0.3).astype(np.float32)
    eng = serving.ServingEngine(sym, params, {"data": (s,)}, buckets=(1,))
    return params, eng


def _ref_greedy(eng, prompt, max_new):
    """Greedy decode by full re-forward through the AOT engine."""
    s = _LM["seq_len"]
    seq = list(prompt)
    out = []
    for _ in range(max_new):
        x = np.zeros((1, s), np.float32)
        x[0, :len(seq)] = seq
        probs = eng.infer({"data": x})[0]      # (seq, vocab)
        tok = int(np.argmax(probs[len(seq) - 1]))
        out.append(tok)
        seq.append(tok)
    return out


def test_decode_greedy_parity_with_slot_join_leave():
    """Acceptance: the decode loop demonstrates slot join/leave mid-stream
    with the KV cache donated across steps, and greedy decode matches full
    re-forward token-for-token (cache numerics are right)."""
    params, eng = _lm_setup()
    loop = serving.DecodeLoop(params, num_layers=_LM["num_layers"],
                              num_heads=_LM["num_heads"],
                              max_len=_LM["seq_len"], slots=2)
    try:
        prompts = [[1, 2, 3], [4, 5], [6]]
        news = [5, 4, 6]
        # three sequences through two slots: the third must JOIN after an
        # earlier one retires, mid-stream
        futs = [loop.generate(p, n) for p, n in zip(prompts, news)]
        got = [f.result(timeout=120) for f in futs]
        ref = [_ref_greedy(eng, p, n) for p, n in zip(prompts, news)]
        assert got == ref
        assert [len(g) for g in got] == news
        assert loop.health.joined == 3
        assert loop.health.retired == 3
    finally:
        loop.close()


def test_decode_tracecheck_clean_including_donation():
    """The decode body's KV cache donation must actually alias (a copy
    would double serving memory) and the program must carry no host syncs
    or f64 leaks: zero findings."""
    params, _eng = _lm_setup()
    loop = serving.DecodeLoop(params, num_layers=_LM["num_layers"],
                              num_heads=_LM["num_heads"],
                              max_len=_LM["seq_len"], slots=2)
    try:
        findings = loop.check()
        assert [f.format() for f in findings] == []
    finally:
        loop.close()


def test_decode_validation():
    params, _eng = _lm_setup()
    loop = serving.DecodeLoop(params, num_layers=_LM["num_layers"],
                              num_heads=_LM["num_heads"],
                              max_len=_LM["seq_len"], slots=1)
    try:
        with pytest.raises(MXNetError):
            loop.generate([], 3)
        with pytest.raises(MXNetError, match="cache length"):
            loop.generate(list(range(10)), 10)
    finally:
        loop.close()
    bad = dict(params)
    del bad["lm_head_bias"]
    with pytest.raises(MXNetError, match="lm_head_bias"):
        serving.DecodeLoop(bad, num_layers=_LM["num_layers"],
                           num_heads=_LM["num_heads"],
                           max_len=_LM["seq_len"])


def test_decode_rejects_silent_gather_clamps():
    """jit-mode gather CLAMPS out-of-range indices — a max_len past the
    positional table or an out-of-vocab prompt id would produce silently
    wrong tokens; both must raise up front."""
    params, _eng = _lm_setup()
    with pytest.raises(MXNetError, match="positional embedding"):
        serving.DecodeLoop(params, num_layers=_LM["num_layers"],
                           num_heads=_LM["num_heads"],
                           max_len=_LM["seq_len"] + 1)
    loop = serving.DecodeLoop(params, num_layers=_LM["num_layers"],
                              num_heads=_LM["num_heads"],
                              max_len=_LM["seq_len"], slots=1)
    try:
        with pytest.raises(MXNetError, match="vocabulary"):
            loop.generate([_LM["vocab_size"]], 1)
        with pytest.raises(MXNetError, match="vocabulary"):
            loop.generate([-1], 1)
    finally:
        loop.close()


def test_decode_result_never_hangs_after_close():
    """result() on a future that raced close() must resolve — served or
    shed with ServingClosedError — never spin forever."""
    params, _eng = _lm_setup()
    loop = serving.DecodeLoop(params, num_layers=_LM["num_layers"],
                              num_heads=_LM["num_heads"],
                              max_len=_LM["seq_len"], slots=1)
    fut = loop.generate([1, 2], 10)
    loop.close()
    try:
        toks = fut.result(timeout=30)     # either fully served pre-close…
        assert len(toks) == 10
    except serving.ServingClosedError:
        pass                              # …or shed with a clear error


@pytest.mark.faults
def test_fault_decode_die_sheds_in_flight_requests():
    """A killed decode loop must fail waiting callers with a clear error
    — never hang them — and refuse new work."""
    params, _eng = _lm_setup()
    loop = serving.DecodeLoop(params, num_layers=_LM["num_layers"],
                              num_heads=_LM["num_heads"],
                              max_len=_LM["seq_len"], slots=2)
    try:
        faults.inject("serve.decode_die", nth=3, kind="die")
        fut = loop.generate([1, 2, 3], 8)
        with pytest.raises(serving.ServingClosedError, match="died"):
            fut.result(timeout=60)
        assert loop.health.shed >= 1
        assert loop.dead is not None
        with pytest.raises(serving.ServingClosedError):
            loop.generate([1], 1)
    finally:
        faults.clear("serve.decode_die")
        loop.close()


# ---------------------------------------------------------------------------
# health plumbing
# ---------------------------------------------------------------------------

def test_serving_health_mirrors_process_global():
    base = serving.SERVING_HEALTH.report()
    eng = _engine(buckets=(4,))
    eng.infer({"data": _x(3)})
    after = serving.SERVING_HEALTH.report()
    assert after["batches"] == base["batches"] + 1
    assert after["examples"] == base["examples"] + 3
    assert after["padded"] == base["padded"] + 1
    assert eng.health.report()["batches"] == 1


# ---------------------------------------------------------------------------
# retrace pins (docs/static_analysis.md): the serving tier is AOT — the
# jit entries behind the compiled executables must NEVER grow a cache
# ---------------------------------------------------------------------------

def test_engine_bucket_switching_never_retraces():
    """Alternating request sizes across every bucket — padding, exact fit,
    chunking past the max — is pure executable reuse: the engine's
    underlying jit entry must not trace once (a trace here means the AOT
    path silently fell back to jit dispatch)."""
    from mxnet_tpu.test_utils import assert_no_retrace
    eng = _engine(buckets=(2, 4, 8))
    with assert_no_retrace(eng._jfn):
        for n in (1, 4, 2, 8, 3, 20, 1, 8):
            outs = eng.infer({"data": _x(n)})
            assert outs[0].shape[0] == n


def test_decode_join_retire_cycles_never_retrace():
    """Sequences joining free slots mid-stream, retiring at different
    lengths, and fresh rounds re-filling the slots all ride ONE compiled
    decode body — no retrace across the whole churn."""
    from mxnet_tpu.test_utils import assert_no_retrace
    params, _eng = _lm_setup()
    loop = serving.DecodeLoop(params, num_layers=_LM["num_layers"],
                              num_heads=_LM["num_heads"],
                              max_len=_LM["seq_len"], slots=2)
    try:
        with assert_no_retrace(loop._jfn):
            for _round in range(2):
                futs = [loop.generate(p, n)
                        for p, n in zip([[1, 2], [3], [4, 5, 6]],
                                        [3, 2, 2])]
                for f in futs:
                    f.result(timeout=120)
        assert loop.health.retired == 6
    finally:
        loop.close()


# ---------------------------------------------------------------------------
# memory audit (docs/static_analysis.md "Memory lints")
# ---------------------------------------------------------------------------

def test_engine_memory_report_and_check_clean():
    """Every compiled bucket reports a static memory profile (no
    recompile, nothing executes) and the default budget audits clean."""
    eng = _engine(buckets=(2, 4))
    reps = eng.memory_report()
    assert sorted(reps) == [2, 4]
    for rep in reps.values():
        assert rep.peak_bytes > 0
        assert rep.argument_bytes > 0
        assert rep.platform
    assert [f.format() for f in eng.check(memory=True)] == []


def test_engine_memory_budget_findings():
    """An absurd budget turns every bucket into an hbm-budget finding plus
    one resident-set finding over the co-resident bucket set."""
    eng = _engine(buckets=(2, 4))
    fs = eng.check(memory=True, budget=256)
    lints = [f.lint for f in fs]
    assert lints.count("hbm-budget") == 2
    assert lints.count("resident-set") == 1
    rs = [f for f in fs if f.lint == "resident-set"][0]
    assert "bucket[b=2]" in rs.message and "bucket[b=4]" in rs.message


def test_engine_load_audit_error_mode():
    """MXTPU_MEMCHECK=error: a deploy whose bucket set cannot fit the
    budget fails at LOAD, naming the findings — not at the first
    full-batch request."""
    from mxnet_tpu import engine as _engmod
    prev = _engmod.set_memcheck("error")
    os.environ["MXTPU_MEMCHECK_BUDGET"] = "256"
    try:
        with pytest.raises(MXNetError, match="memory audit"):
            _engine(buckets=(2,))
    finally:
        del os.environ["MXTPU_MEMCHECK_BUDGET"]
        _engmod.set_memcheck(prev)
    # warn mode constructs fine and logs instead
    prev = _engmod.set_memcheck("warn")
    os.environ["MXTPU_MEMCHECK_BUDGET"] = "256"
    try:
        eng = _engine(buckets=(2,))
        assert eng.infer({"data": _x(2)})[0].shape[0] == 2
    finally:
        del os.environ["MXTPU_MEMCHECK_BUDGET"]
        _engmod.set_memcheck(prev)
    # a MALFORMED budget is an operator error, not an analyzer failure:
    # it must propagate even in warn mode rather than silently disarm
    # the gate the operator just configured
    prev = _engmod.set_memcheck("warn")
    os.environ["MXTPU_MEMCHECK_BUDGET"] = "16gigs"
    try:
        with pytest.raises(MXNetError, match="MXTPU_MEMCHECK_BUDGET"):
            _engine(buckets=(2,))
    finally:
        del os.environ["MXTPU_MEMCHECK_BUDGET"]
        _engmod.set_memcheck(prev)


def test_decode_memory_report_cache_aliased():
    """The decode body's dominant buffer is the donated KV cache — the
    memory report must show it fully aliased (a copy would double serving
    memory per step) and the memory lints stay clean."""
    params, _eng = _lm_setup()
    loop = serving.DecodeLoop(params, num_layers=_LM["num_layers"],
                              num_heads=_LM["num_heads"],
                              max_len=_LM["seq_len"], slots=2)
    try:
        # the program set now includes the prefix-cache get/put helpers;
        # the decode body is the one named "step[...]"
        reports = loop.memory_report()
        (name, rep), = [(n, r) for n, r in reports.items()
                        if "step[" in n]
        embed = params["tok_embed_weight"].shape[1]
        head_dim = embed // _LM["num_heads"]
        cache_bytes = 2 * (_LM["num_layers"] * 2 * _LM["num_heads"]
                           * _LM["seq_len"] * head_dim) * 4
        assert rep.alias_bytes >= cache_bytes
        assert rep.unaliased_donated == []
        assert [f.format() for f in loop.check(memory=True)] == []
    finally:
        loop.close()
