"""tracecheck — static analyzer for compiled step programs
(docs/static_analysis.md).

Pins the lint catalog with a SEEDED violation of every class — an injected
host callback inside a scan body, a shape-perturbed retrace, an un-donatable
donated argument, an f64 literal, a weak-typed input, an oversized
closure-captured constant — each detected with op path + source provenance.
The retrace explainer's negative controls check the cache-key differ names
the offending argument AND property (shape / dtype / weak-type / static
value). Plus: inline + programmatic suppressions, the TrainStep runtime
hooks (program registry, watcher, MXTPU_TRACECHECK=error), the
``assert_no_retrace`` helper, bitwise parity for the satellite dtype pins,
and the tier-1 CLI smoke over a zoo subset.
"""
import logging

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from mxnet_tpu import engine, guard as guard_mod, metric as metric_mod
from mxnet_tpu import sym, tracecheck as tc
from mxnet_tpu.base import MXNetError
from mxnet_tpu.test_utils import assert_no_retrace
from mxnet_tpu.train_step import StepMetrics, TrainStep

# NOTE: only the end-to-end TrainStep tests carry the ``tracecheck``
# marker (transfer_guard("disallow") via conftest): the lint/differ unit
# tests SEED violations — building arrays from Python scalars is their job.


@pytest.fixture(autouse=True)
def _clean_slate():
    tc.clear_suppressions()
    tc.RETRACE_EVENTS.clear()
    tc.PROGRAMS.clear()
    guard_mod.TRAINING_HEALTH.reset()
    engine.set_tracecheck(None)
    yield
    tc.clear_suppressions()
    tc.RETRACE_EVENTS.clear()
    guard_mod.TRAINING_HEALTH.reset()
    engine.set_tracecheck(None)


def _mlp():
    data = sym.Variable("data")
    net = sym.FullyConnected(data=data, num_hidden=16, name="fc1")
    net = sym.Activation(data=net, act_type="tanh")
    net = sym.FullyConnected(data=net, num_hidden=4, name="fc2")
    return sym.SoftmaxOutput(data=net, name="softmax")


def _sds(shape, dtype=np.float32):
    return jax.ShapeDtypeStruct(tuple(shape), dtype)


# ---------------------------------------------------------------------------
# seeded violations: one per lint class, op path + provenance asserted
# ---------------------------------------------------------------------------

def test_host_sync_lint_callback_in_scan_body():
    """An injected jax.debug.print inside the scan body — the single worst
    regression for the bulked dispatch (a host round-trip K times per
    dispatch) — is caught with an op path rooted in the scan."""
    def step_with_logging(x):
        def body(c, _):
            jax.debug.print("loss={}", c.sum())
            return c + 1.0, None
        out, _ = jax.lax.scan(body, x, None, length=3)
        return out

    findings = tc.check_program(step_with_logging, (_sds((4,)),),
                                name="seeded-cb")
    hits = [f for f in findings if f.lint == "host-sync"]
    assert len(hits) == 1
    f = hits[0]
    assert f.op_path.startswith("scan/")
    assert "INSIDE the scan body" in f.message
    assert f.provenance and "test_tracecheck" in f.provenance
    assert not f.suppressed


def test_host_sync_lint_clean_program_silent():
    findings = tc.check_program(lambda x: x * 2.0, (_sds((4,)),),
                                name="clean")
    assert not [f for f in findings if f.lint == "host-sync"]


def test_donation_lint_undonatable_argument():
    """A donated argument the lowering copies anyway (its shape matches no
    output) is named by flat path."""
    def shrink(x):
        return x[::2]

    findings = tc.check_program(shrink, (_sds((8,)),), donate_argnums=(0,),
                                name="seeded-don")
    hits = [f for f in findings if f.lint == "donation"]
    assert len(hits) == 1
    assert "args[0]" in hits[0].message
    assert "NOT aliased" in hits[0].message


def test_donation_lint_honored_donation_silent():
    findings = tc.check_program(lambda x: x + 1.0, (_sds((8,)),),
                                donate_argnums=(0,), name="don-ok")
    assert not [f for f in findings if f.lint == "donation"]


def test_dtype_lint_f64_literal():
    """An f64 literal leaking into the step program (only reachable with
    x64 enabled — exactly the config drift the lint is for) is reported
    with the producing op and provenance."""
    from jax.experimental import enable_x64
    with enable_x64():
        def f64_math(x):
            return x * np.float64(2.0)

        findings = tc.check_program(f64_math, (_sds((4,)),),
                                    name="seeded-f64")
    hits = [f for f in findings if f.lint == "dtype-f64"]
    assert hits, "f64 promotion not detected"
    assert any("float64" in f.message for f in hits)
    assert any(f.provenance and "test_tracecheck" in f.provenance
               for f in hits)
    assert any(f.op_path for f in hits)


def test_dtype_lint_weak_typed_input():
    """A bare Python scalar reaching the trace is flagged as a weak-typed
    program input, by argument path."""
    findings = tc.check_program(lambda x, s: x * s, (_sds((4,)), 2.5),
                                name="seeded-weak")
    hits = [f for f in findings if f.lint == "dtype-weak"]
    assert len(hits) == 1
    assert "[0][1]" in hits[0].message
    assert "weak-typed" in hits[0].message


def test_const_capture_lint_oversized_closure():
    big = jnp.ones((1024, 300), jnp.float32)  # 1.2 MB

    def with_baked_const(x):
        return x + jnp.sum(big, axis=1)[:4]

    findings = tc.check_program(with_baked_const, (_sds((4,)),),
                                name="seeded-const", const_bytes=100_000)
    hits = [f for f in findings if f.lint == "const-capture"]
    assert len(hits) == 1
    assert "1228800 bytes" in hits[0].message
    assert "consts[0]" == hits[0].op_path
    # the finding names the CAPTURED CLOSURE VARIABLE and its dtype/shape,
    # and carries the provenance of the constant's first use
    assert "variable 'big'" in hits[0].message
    assert "float32[1024, 300]" in hits[0].message
    assert hits[0].provenance and "test_tracecheck" in hits[0].provenance
    # above the default 1 MiB threshold too; a higher explicit one passes
    assert not [f for f in tc.check_program(
        with_baked_const, (_sds((4,)),), name="seeded-const",
        const_bytes=2_000_000) if f.lint == "const-capture"]


# ---------------------------------------------------------------------------
# the retrace explainer (cache-key differ)
# ---------------------------------------------------------------------------

def test_explain_diff_names_argument_and_property():
    """Negative controls: for each cache-key-relevant property — shape,
    dtype, weak type, static value — the differ names the argument and the
    property that changed."""
    x32 = jnp.ones((4, 3), jnp.float32)

    base = tc.signature((x32, 5), {"mode": "fast"})
    # shape
    d = tc.explain_diff(base, tc.signature((jnp.ones((5, 3)), 5),
                                           {"mode": "fast"}))
    assert d == ["argument [0][0]: shape (4, 3) -> (5, 3)"]
    # dtype
    d = tc.explain_diff(base, tc.signature(
        (x32.astype(jnp.float16), 5), {"mode": "fast"}))
    assert d == ["argument [0][0]: dtype float32 -> float16"]
    # weak type (a weak scalar array where a strong one used to be)
    weak = jnp.asarray(2.0)          # weak f32
    strong = jnp.float32(2.0)        # strong f32
    if weak.weak_type and not strong.weak_type:
        d = tc.explain_diff(tc.signature((strong,)),
                            tc.signature((weak,)))
        assert d == ["argument [0][0]: weak_type False -> True"]
    # static value (a non-scalar static leaf is keyed by VALUE)
    d = tc.explain_diff(base, tc.signature((x32, 5), {"mode": "slow"}))
    assert d == ["argument [1]['mode']: static value 'fast' -> 'slow'"]
    # python scalar type flip (int 5 -> float 5.0 retraces; the VALUE of a
    # traced scalar never keys the cache, so only the type is compared)
    d = tc.explain_diff(base, tc.signature((x32, 5.0), {"mode": "fast"}))
    assert d == ["argument [0][1]: Python scalar type int -> float"]
    assert tc.explain_diff(base,
                           tc.signature((x32, 7), {"mode": "fast"})) == []
    # unchanged signature -> empty diff
    assert tc.explain_diff(base, tc.signature((x32, 5),
                                              {"mode": "fast"})) == []


def test_explain_diff_committedness_is_benign():
    """The first dispatch after seeding flips donated state leaves
    uncommitted -> committed; that re-keys only jit's C++ fast path, never
    the trace — the differ must stay silent and benign_diff must name it."""
    x = jnp.ones((4,), jnp.float32)
    committed = jax.device_put(x, jax.devices()[0])
    a, b = tc.signature((x,)), tc.signature((committed,))
    if a != b:  # committedness differs on this backend
        assert tc.explain_diff(a, b) == []
        assert any("committed" in ln for ln in tc.benign_diff(a, b))


def test_trace_watcher_detects_shape_perturbed_retrace(caplog):
    """A watched jit entry re-traced by a shape change logs the diff naming
    the argument + property and lands in RETRACE_EVENTS + health."""
    f = jax.jit(lambda x: x * 2.0)
    w = tc.TraceWatcher("toy")
    x1, x2 = jnp.ones((4, 3)), jnp.ones((5, 3))
    f(x1)
    assert w.after_call("k", f, tc.signature((x1,))) is None
    f(x2)  # same watch key, perturbed shape -> cache grows
    with caplog.at_level(logging.WARNING):
        ev = w.after_call("k", f, tc.signature((x2,)))
    assert ev is not None
    assert ev.site == "toy/k"
    assert ev.diff == ("argument [0][0]: shape (4, 3) -> (5, 3)",)
    assert any("unexpected retrace at toy/k" in r.message
               for r in caplog.records)
    assert tc.retrace_count() == 1
    assert guard_mod.TRAINING_HEALTH.report()["retraces"] == 1


def test_trace_watcher_error_mode_raises():
    engine.set_tracecheck("error")
    f = jax.jit(lambda x: x + 1.0)
    w = tc.TraceWatcher("toy")
    x1, x2 = jnp.ones((4,)), jnp.ones((4,), jnp.float16)
    f(x1)
    w.after_call("k", f, tc.signature((x1,)))
    f(x2)
    with pytest.raises(MXNetError, match=r"dtype float32 -> float16"):
        w.after_call("k", f, tc.signature((x2,)))


@pytest.mark.tracecheck
def test_train_step_runtime_hook_catches_dtype_retrace(caplog):
    """End to end through the wired hooks: a batch dtype flip on an
    already-compiled TrainStep program is an unexpected retrace — the log
    names the batch argument and the dtype change."""
    B = 8
    ts = TrainStep(_mlp(), optimizer="sgd", learning_rate=0.05)
    state = ts.init({"data": (B, 10)}, {"softmax_label": (B,)}, seed=0)
    rng = np.random.default_rng(3)
    X = rng.normal(size=(B, 10)).astype(np.float32)
    y = rng.integers(0, 4, (B,)).astype(np.float32)
    batch = {"data": jnp.asarray(X), "softmax_label": jnp.asarray(y)}
    state, _ = ts.step(state, batch)
    assert tc.retrace_count() == 0
    bad = dict(batch, data=jnp.asarray(X.astype(np.float16)))
    with caplog.at_level(logging.WARNING):
        state, _ = ts.step(state, bad)
    assert tc.retrace_count() == 1
    ev = tc.RETRACE_EVENTS[-1]
    assert "step[bs=%d]" % B in ev.site
    assert any("data" in ln and "float32 -> float16" in ln
               for ln in ev.diff)


@pytest.mark.tracecheck
def test_train_step_registers_programs_cleanly():
    """The wired jit caches (step + scan) land in the program registry and
    the registered set audits clean — the guard-on/guard-off program set
    as a unit."""
    B, K = 8, 2
    ts = TrainStep(_mlp(), optimizer="sgd", learning_rate=0.05)
    state = ts.init({"data": (B, 10)}, {"softmax_label": (B,)}, seed=0)
    rng = np.random.default_rng(5)
    Xs = rng.normal(size=(K, B, 10)).astype(np.float32)
    ys = rng.integers(0, 4, (K, B)).astype(np.float32)
    sb = {"data": jnp.asarray(Xs), "softmax_label": jnp.asarray(ys)}
    state, _ = ts.run_steps(state, dict(sb))
    state, _ = ts.run_steps(state, dict(sb), guard=True)
    names = [r.name for r in tc.registered_programs()]
    assert any("scan[bs=%d,k=%d]" % (B, K) in n for n in names)
    assert any("guard-scan[bs=%d,k=%d]" % (B, K) in n for n in names)
    findings = tc.check_registered(match="scan")
    assert tc.unsuppressed(findings) == []


def test_error_mode_retrace_carries_dispatch_result():
    """MXTPU_TRACECHECK=error raises AFTER the dispatch has donated the
    old state — the RetraceError must carry the new state so the caller
    (Module._adopt_retrace_result) never dangles on deleted buffers."""
    engine.set_tracecheck("error")
    B = 8
    ts = TrainStep(_mlp(), optimizer="sgd", learning_rate=0.05)
    state = ts.init({"data": (B, 10)}, {"softmax_label": (B,)}, seed=0)
    X = np.zeros((B, 10), np.float32)
    y = np.zeros((B,), np.float32)
    batch = {"data": jnp.asarray(X), "softmax_label": jnp.asarray(y)}
    state, _ = ts.step(state, batch)
    bad = dict(batch, data=jnp.asarray(X.astype(np.float16)))
    with pytest.raises(tc.RetraceError,
                       match="float32 -> float16") as ei:
        ts.step(state, bad)
    assert ei.value.result is not None
    new_state, outs = ei.value.result
    assert int(np.asarray(new_state["step"])) == 2  # the dispatch DID run


@pytest.mark.tracecheck
def test_two_train_steps_same_symbol_name_both_register():
    """Registry names are process-unique: a second TrainStep over a
    same-named symbol (the default 'softmax' head) must register its OWN
    programs, not be shadowed by the first instance's entries."""
    B = 8
    batch = {"data": jnp.asarray(np.zeros((B, 10), np.float32)),
             "softmax_label": jnp.asarray(np.zeros((B,), np.float32))}
    steps = []
    for seed in (0, 1):
        ts = TrainStep(_mlp(), optimizer="sgd", learning_rate=0.05)
        state = ts.init({"data": (B, 10)}, {"softmax_label": (B,)},
                        seed=seed)
        ts.step(state, dict(batch))
        steps.append(ts)
    assert steps[0]._watcher.name != steps[1]._watcher.name
    regs = [r for r in tc.registered_programs()
            if "step[bs=%d]" % B in r.name]
    assert len(regs) == 2
    assert {r.fn_ref() for r in regs} == \
        {steps[0]._jit[B], steps[1]._jit[B]}


def test_tracecheck_off_mode_skips_capture():
    engine.set_tracecheck("off")
    B = 8
    ts = TrainStep(_mlp(), optimizer="sgd", learning_rate=0.05)
    state = ts.init({"data": (B, 10)}, {"softmax_label": (B,)}, seed=0)
    batch = {"data": jnp.zeros((B, 10), jnp.float32),
             "softmax_label": jnp.zeros((B,), jnp.float32)}
    ts.step(state, batch)
    assert ts._watcher is None
    assert tc.PROGRAMS == {} or not any(
        "TrainStep" in n for n in tc.PROGRAMS)


def test_engine_mode_parsing(monkeypatch):
    for raw, want in [("", "warn"), ("warn", "warn"), ("1", "warn"),
                      ("error", "error"), ("raise", "error"),
                      ("off", "off"), ("0", "off")]:
        monkeypatch.setenv("MXTPU_TRACECHECK", raw)
        assert engine.tracecheck_mode() == want
    monkeypatch.setenv("MXTPU_TRACECHECK", "bogus")
    with pytest.raises(MXNetError, match="MXTPU_TRACECHECK"):
        engine.tracecheck_mode()
    monkeypatch.delenv("MXTPU_TRACECHECK")
    with pytest.raises(MXNetError, match="set_tracecheck"):
        engine.set_tracecheck("loud")


# ---------------------------------------------------------------------------
# suppressions
# ---------------------------------------------------------------------------

def test_programmatic_suppression():
    tok = tc.add_suppression("dtype-weak", program="seeded")
    findings = tc.check_program(lambda x, s: x * s, (_sds((4,)), 2.5),
                                name="seeded-weak")
    hit = [f for f in findings if f.lint == "dtype-weak"][0]
    assert hit.suppressed
    tc.remove_suppression(tok)
    findings = tc.check_program(lambda x, s: x * s, (_sds((4,)), 2.5),
                                name="seeded-weak")
    assert not [f for f in findings if f.lint == "dtype-weak"][0].suppressed
    with pytest.raises(MXNetError, match="unknown lint"):
        tc.add_suppression("not-a-lint")


def test_inline_suppression_on_provenance_line():
    """`# tracecheck: ignore[host-sync]` on the source line a finding
    points at marks it suppressed (reported, but not gate-failing)."""
    def quiet(x):
        jax.debug.print("x={}", x.sum())  # tracecheck: ignore[host-sync]
        return x + 1.0

    findings = tc.check_program(quiet, (_sds((4,)),), name="inline-ok")
    hits = [f for f in findings if f.lint == "host-sync"]
    assert len(hits) == 1 and hits[0].suppressed
    assert tc.unsuppressed(findings) == []


def test_inline_suppression_wrong_lint_does_not_match():
    def noisy(x):
        jax.debug.print("x={}", x.sum())  # tracecheck: ignore[dtype-f64]
        return x + 1.0

    findings = tc.check_program(noisy, (_sds((4,)),), name="inline-no")
    hits = [f for f in findings if f.lint == "host-sync"]
    assert len(hits) == 1 and not hits[0].suppressed


# ---------------------------------------------------------------------------
# assert_no_retrace helper
# ---------------------------------------------------------------------------

def test_assert_no_retrace_passes_on_stable_cache():
    f = jax.jit(lambda x: x * 3.0)
    x = jnp.ones((4,))
    f(x)
    with assert_no_retrace(f):
        for _ in range(3):
            f(x)


def test_assert_no_retrace_fails_naming_growth():
    f = jax.jit(lambda x: x * 3.0)
    f(jnp.ones((4,)))
    with pytest.raises(AssertionError, match="re-traced"):
        with assert_no_retrace(f, msg="toy"):
            f(jnp.ones((5,)))  # new shape -> new trace


def test_assert_no_retrace_reports_watcher_events():
    """Events recorded by any runtime watcher inside the block fail the
    assertion with the differ's argument/property line."""
    f = jax.jit(lambda x: x + 1.0)
    w = tc.TraceWatcher("toy")
    x1, x2 = jnp.ones((4,)), jnp.ones((7,))
    f(x1)
    w.after_call("k", f, tc.signature((x1,)))
    with pytest.raises(AssertionError, match=r"shape \(4,\) -> \(7,\)"):
        with assert_no_retrace():
            f(x2)
            w.after_call("k", f, tc.signature((x2,)))


# ---------------------------------------------------------------------------
# satellite dtype pins: bitwise parity on the default (x64-off) config
# ---------------------------------------------------------------------------

def test_eps_pin_bitwise_parity():
    """`-log(p + jnp.float32(1e-8))` == `-log(p + 1e-8)` bitwise on the
    default config — the pin only matters under x64, where the unpinned
    form promotes."""
    p = jnp.asarray(np.random.default_rng(0).uniform(
        1e-6, 1.0, (64,)).astype(np.float32))
    a = np.asarray(jnp.sum(-jnp.log(p + 1e-8)))
    b = np.asarray(jnp.sum(-jnp.log(p + jnp.float32(1e-8))))
    assert a.tobytes() == b.tobytes()


def test_lr_vector_pin_bitwise_parity():
    lrs = [0.05, 0.049, 0.0485]
    a = np.asarray(jnp.asarray(lrs, jnp.float32))
    b = np.asarray(jnp.asarray(np.asarray(lrs, np.float32)))
    assert a.dtype == b.dtype == np.float32
    assert a.tobytes() == b.tobytes()
    assert not jnp.asarray(np.asarray(lrs, np.float32)).weak_type


def test_metric_fold_pins_accumulator_to_python_float():
    """update_from_device_sums keeps the host accumulator a Python
    float/int even when the sums object yields np.float32 scalars — under
    NEP 50 an np.float32 fold would demote sum_metric to f32 for the rest
    of the run (increments stop landing past 2**24)."""
    class _F32Sums(object):
        loss_sum = np.float32(2.5)
        top1_correct = np.float32(6.0)
        num_samples = np.float32(8.0)

    acc = metric_mod.Accuracy()
    metric_mod.update_from_device_sums(acc, _F32Sums())
    assert type(acc.sum_metric) is float and type(acc.num_inst) is int
    ce = metric_mod.CrossEntropy()
    metric_mod.update_from_device_sums(ce, _F32Sums())
    assert type(ce.sum_metric) is float
    assert ce.get()[1] == pytest.approx(2.5 / 8.0)
    # parity: the f64 fold equals the float32 values exactly at small counts
    assert acc.sum_metric == 6.0 and acc.num_inst == 8


def test_step_metrics_fold_parity():
    packed = jnp.asarray(np.asarray([2.5, 6.0, 8.0], np.float32))
    sums = StepMetrics(packed)
    acc = metric_mod.Accuracy()
    metric_mod.update_from_device_sums(acc, sums)
    assert acc.sum_metric == 6.0 and acc.num_inst == 8


def test_speedometer_surfaces_retrace_count():
    """`Retraces: N` appears in Speedometer lines once a watched jit entry
    re-traces during the run — and is baselined at the init fire, so an
    earlier run's misses never leak into this run's lines."""
    import logging as _logging
    from mxnet_tpu.callback import Speedometer
    from mxnet_tpu.module.base_module import BatchEndParam

    tc.RETRACE_EVENTS.append(tc.RetraceEvent("stale/run", ("old",)))
    sp = Speedometer(batch_size=16, frequent=10)
    fired = []
    orig = _logging.info
    _logging.info = lambda *a: fired.append(a)
    try:
        sp(BatchEndParam(epoch=0, nbatch=5, eval_metric=None, locals=None))
        tc.RETRACE_EVENTS.append(tc.RetraceEvent(
            "TrainStep(softmax)/scan[bs=8,k=2]",
            ("argument data: dtype float32 -> float16",)))
        sp(BatchEndParam(epoch=0, nbatch=15, eval_metric=None, locals=None))
    finally:
        _logging.info = orig
    joined = " ".join(str(x) for call in fired for x in call)
    assert "Retraces: 1" in joined

    # a REUSED Speedometer re-baselines: a miss between runs (score(), a
    # different Module) must not leak into run 2's lines — and a clean
    # window stays quiet (no "Retraces: 0" noise)
    tc.RETRACE_EVENTS.append(tc.RetraceEvent("between/runs", ("x",)))
    fired2 = []
    _logging.info = lambda *a: fired2.append(a)
    try:
        sp(BatchEndParam(epoch=0, nbatch=5, eval_metric=None, locals=None))
        sp(BatchEndParam(epoch=0, nbatch=15, eval_metric=None, locals=None))
    finally:
        _logging.info = orig
    assert "Retraces" not in " ".join(str(x) for call in fired2
                                      for x in call)


# ---------------------------------------------------------------------------
# zoo audit + CLI (tier-1 smoke)
# ---------------------------------------------------------------------------

def test_check_zoo_subset_clean():
    findings, nprog = tc.check_zoo(names=["mlp"], k=2)
    assert nprog == 4  # step / scan / guarded-step / guarded-scan
    assert tc.unsuppressed(findings) == []


def test_cli_smoke_exits_zero_on_zoo_subset(capsys):
    """The CI gate's tier-1 smoke: the CLI audits shipped models and exits
    0 (zero unsuppressed findings on the seed zoo)."""
    rc = tc.main(["--models", "mlp,lenet", "--quiet"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "0 finding(s)" in out or "finding(s)" in out


def test_cli_list_and_bad_model():
    assert tc.main(["--list"]) == 0
    with pytest.raises(MXNetError, match="unknown zoo model"):
        tc.main(["--models", "nope"])


def test_cli_json_output(capsys):
    """--json emits an object: the findings list plus the suppressed and
    program counts (machine-readable gate summary)."""
    import json
    rc = tc.main(["--models", "mlp", "--json"])
    data = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert isinstance(data["findings"], list)
    assert data["suppressed"] == 0
    assert data["total"] == len(data["findings"])
    assert data["programs"] == 4  # step / scan / guarded-step / guarded-scan


def test_cli_json_counts_suppressed_findings(capsys, monkeypatch):
    """A suppressed finding still reports and is COUNTED in the json
    summary's suppressed field; the unsuppressed one still fails the
    gate."""
    import json
    seeded = [
        tc.Finding("host-sync", "fake/step", "seeded-suppressed",
                   suppressed=True),
        tc.Finding("dtype-weak", "fake/step", "seeded-live"),
    ]
    monkeypatch.setattr(tc, "check_zoo", lambda **kw: (list(seeded), 4))
    rc = tc.main(["--models", "mlp", "--json"])
    data = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert data["total"] == 2
    assert data["suppressed"] == 1
    assert [f["suppressed"] for f in data["findings"]] == [True, False]


# ---------------------------------------------------------------------------
# collective-in-scan (docs/perf.md "Data-parallel scaling")
# ---------------------------------------------------------------------------

def _dp_mesh(n=8):
    import jax
    import numpy as np
    return jax.sharding.Mesh(np.array(jax.devices()[:n]), ("data",))


def test_collective_lint_flags_explicit_allgather_in_scan():
    """Jaxpr half: an explicit shard_map all_gather inside a scan body is
    a finding with the scan-rooted op path and the seeding line's
    provenance."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.experimental.shard_map import shard_map
    P = jax.sharding.PartitionSpec
    mesh = _dp_mesh()

    def bad(xs):
        def body(c, x):
            g = jax.lax.all_gather(x, "data")
            return c + jnp.sum(g), None
        out, _ = jax.lax.scan(body, jnp.float32(0), xs)
        return out

    sm = shard_map(bad, mesh=mesh, in_specs=P(None, "data"), out_specs=P(),
                   check_rep=False)
    xs = jax.device_put(np.ones((4, 8), np.float32),
                        jax.sharding.NamedSharding(mesh, P(None, "data")))
    findings = [f for f in tc.check_program(jax.jit(sm), (xs,),
                                            name="seeded-allgather")
                if f.lint == "collective-in-scan"]
    assert findings, "all_gather in scan body must be flagged"
    assert "scan" in findings[0].op_path
    assert findings[0].provenance and "test_tracecheck" in \
        findings[0].provenance


def test_collective_lint_allows_psum_in_scan():
    """psum IS the expected grad/metric sync — a psum-only shard_map scan
    stays clean on both the jaxpr pass and the compiled-HLO audit."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.experimental.shard_map import shard_map
    P = jax.sharding.PartitionSpec
    mesh = _dp_mesh()

    def good(xs):
        def body(c, x):
            return c + jax.lax.psum(jnp.sum(x), "data"), None
        out, _ = jax.lax.scan(body, jnp.float32(0), xs)
        return out

    sm = shard_map(good, mesh=mesh, in_specs=P(None, "data"), out_specs=P(),
                   check_rep=False)
    xs = jax.device_put(np.ones((4, 8), np.float32),
                        jax.sharding.NamedSharding(mesh, P(None, "data")))
    assert [f for f in tc.check_program(jax.jit(sm), (xs,), name="psum-scan")
            if f.lint == "collective-in-scan"] == []
    assert tc.check_collectives(jax.jit(sm), (xs,), name="psum-scan") == []


def test_collective_lint_suppressible():
    tok = tc.add_suppression("collective-in-scan", program="seeded")
    try:
        import jax
        import jax.numpy as jnp
        import numpy as np
        from jax.experimental.shard_map import shard_map
        P = jax.sharding.PartitionSpec
        mesh = _dp_mesh()

        def bad(xs):
            def body(c, x):
                return c + jnp.sum(jax.lax.all_gather(x, "data")), None
            return jax.lax.scan(body, jnp.float32(0), xs)[0]

        sm = shard_map(bad, mesh=mesh, in_specs=P(None, "data"),
                       out_specs=P(), check_rep=False)
        xs = jax.device_put(np.ones((4, 8), np.float32),
                            jax.sharding.NamedSharding(mesh, P(None, "data")))
        fs = [f for f in tc.check_program(jax.jit(sm), (xs,),
                                          name="seeded-suppressed")
              if f.lint == "collective-in-scan"]
        assert fs and all(f.suppressed for f in fs)
    finally:
        tc.remove_suppression(tok)
