"""Deterministic fault injection across the async training pipeline
(docs/robustness.md). Every failure mode the dependency-engine design
assumes — record reads, H2D copies, producer threads, checkpoint writes,
kvstore push/pull — is fired at an exact call count and its recovery path
asserted, with no sleeps or races.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import faults, nd
from mxnet_tpu import io as mxio
from mxnet_tpu.base import MXNetError

pytestmark = pytest.mark.faults

FAST = mxio.RetryPolicy(max_retries=3, base_delay=0.0)


@pytest.fixture(autouse=True)
def _clean_registry():
    faults.clear()
    yield
    faults.clear()


# -- registry semantics ------------------------------------------------------

def test_fire_counts_and_nth_targeting():
    assert faults.fire("t.site") is None
    faults.inject("t.site", nth=2, kind="raise")
    assert faults.fire("t.site") is None          # call 2 overall, nth is
    with pytest.raises(faults.InjectedFault):     # relative to arm time
        faults.fire("t.site")
    assert faults.fire("t.site") is None          # times=1: one shot
    assert faults.count("t.site") == 4


def test_scoped_clears_on_exit():
    with faults.scoped("t.scoped", nth=1, kind="transient"):
        with pytest.raises(faults.InjectedTransientFault):
            faults.fire("t.scoped")
    assert faults.fire("t.scoped") is None
    assert faults.count("t.scoped") == 1


def test_action_kinds_pass_through():
    faults.inject("t.act", nth=1, kind="truncate")
    assert faults.fire("t.act") == "truncate"


def test_env_spec_parsing(monkeypatch):
    monkeypatch.setenv("MXTPU_FAULTS", "t.env@2=transient*2")
    faults.clear()
    faults._env_loaded = False
    assert faults.fire("t.env") is None
    for _ in range(2):
        with pytest.raises(faults.InjectedTransientFault):
            faults.fire("t.env")
    assert faults.fire("t.env") is None


def test_env_spec_malformed_raises(monkeypatch):
    monkeypatch.setenv("MXTPU_FAULTS", "not-a-spec")
    faults.clear()
    faults._env_loaded = False
    with pytest.raises(MXNetError, match="MXTPU_FAULTS"):
        faults.fire("t.env2")


# -- retry helper ------------------------------------------------------------

def test_retry_call_transient_within_budget():
    health = mxio.DataHealth()
    faults.inject("t.retry", nth=1, kind="transient", times=2)

    def op():
        faults.fire("t.retry")
        return 42

    assert mxio.retry_call(op, "t.retry", FAST, health) == 42
    assert health.report()["retries"] == 2


def test_retry_call_budget_exhaustion_names_site_and_attempts():
    health = mxio.DataHealth()
    faults.inject("t.retry2", nth=1, kind="transient", times=99)

    def op():
        faults.fire("t.retry2")

    with pytest.raises(MXNetError, match=r"t\.retry2: giving up after 4 "
                                         r"attempts"):
        mxio.retry_call(op, "t.retry2", FAST, health)
    assert health.report()["failures"] == 1


def test_retry_call_nontransient_propagates_immediately():
    calls = []

    def op():
        calls.append(1)
        raise ValueError("permanent")

    with pytest.raises(ValueError):
        mxio.retry_call(op, "t.retry3", FAST)
    assert len(calls) == 1


def test_retry_policy_backoff_deterministic():
    p = mxio.RetryPolicy(base_delay=0.01, max_delay=0.04, jitter=0.5)
    d1 = [p.delay(a, "site") for a in (1, 2, 3, 4)]
    d2 = [p.delay(a, "site") for a in (1, 2, 3, 4)]
    assert d1 == d2                       # same run-to-run
    assert d1[0] < d1[1] < d1[2]          # exponential
    assert all(d <= 0.04 * 1.5 for d in d1)   # capped (+jitter)
    assert p.delay(2, "other") != d1[1]   # de-synchronized across sites


# -- superbatch pipeline -----------------------------------------------------

def _arange_iter(n=16, batch=4):
    X = np.arange(n * 4, dtype=np.float32).reshape(n, 4)
    y = np.zeros(n, np.float32)
    return mx.io.NDArrayIter(X, y, batch_size=batch)


def test_superbatch_transient_reads_are_invisible():
    def pull_all():
        it = _arange_iter().superbatch(2, retry_policy=FAST)
        return np.concatenate([b.data[0].asnumpy() for b in it])

    clean = pull_all()
    faults.inject("io.batch_read", nth=2, kind="transient", times=2)
    faulty = pull_all()
    np.testing.assert_array_equal(clean, faulty)


def test_superbatch_read_failures_beyond_budget_raise():
    faults.inject("io.batch_read", nth=1, kind="transient", times=99)
    it = _arange_iter().superbatch(2, retry_policy=FAST)
    with pytest.raises(MXNetError, match=r"io\.batch_read.*attempts"):
        for _ in it:
            pass


class _HostBatchIter(mx.io.DataIter):
    """Host-numpy batches (the next_host/ImageIter shape): superbatch
    stacking lands them through the ONE-H2D path where io.h2d fires."""

    def __init__(self, n_batches=4, batch=4):
        super().__init__(batch)
        self.n_batches = n_batches
        self.i = 0
        self.provide_data = [mx.io.DataDesc("data", (batch, 2))]
        self.provide_label = [mx.io.DataDesc("softmax_label", (batch,))]

    def reset(self):
        self.i = 0

    def next_host(self):
        if self.i >= self.n_batches:
            raise StopIteration
        self.i += 1
        return mx.io.DataBatch(
            data=[np.full((self.batch_size, 2), self.i, np.float32)],
            label=[np.zeros(self.batch_size, np.float32)], pad=0)


def test_superbatch_h2d_transient_retried():
    health = mxio.DataHealth()
    faults.inject("io.h2d", nth=1, kind="transient")
    it = _HostBatchIter().superbatch(2, retry_policy=FAST,
                                     data_health=health)
    batches = list(it)
    assert len(batches) == 2
    assert health.report()["retries"] >= 1
    np.testing.assert_array_equal(batches[0].data[0].asnumpy()[0],
                                  np.full((4, 2), 1, np.float32))


def test_superbatch_producer_death_detected_not_hung():
    faults.inject("superbatch.producer", nth=2, kind="die")
    it = _arange_iter().superbatch(2, queue_depth=1)
    with pytest.raises(MXNetError, match=r"superbatch\.producer"):
        for _ in it:
            pass


def test_data_health_mirrors_into_global_aggregate():
    mxio.DATA_HEALTH.reset()
    child = mxio.DataHealth(parent=mxio.DATA_HEALTH)
    child.record_retry("s", "e")
    child.record_skip("s", "e")
    assert child.report()["retries"] == 1
    assert mxio.DATA_HEALTH.report()["retries"] == 1
    assert mxio.DATA_HEALTH.report()["skipped_records"] == 1
    mxio.DATA_HEALTH.reset()


# -- image pipeline ----------------------------------------------------------

def _tiny_rec(tmp_path, n=8, corrupt=()):
    import io as _io
    from PIL import Image
    from mxnet_tpu import recordio
    rng = np.random.RandomState(7)
    rec_path = str(tmp_path / "data.rec")
    idx_path = str(tmp_path / "data.idx")
    writer = recordio.MXIndexedRecordIO(idx_path, rec_path, "w")
    for i in range(n):
        if i in corrupt:
            payload = b"\xff\xd8not-actually-a-jpeg"
        else:
            arr = (rng.rand(16, 16, 3) * 255).astype(np.uint8)
            b = _io.BytesIO()
            Image.fromarray(arr).save(b, "JPEG")
            payload = b.getvalue()
        writer.write_idx(i, recordio.pack(
            recordio.IRHeader(0, float(i), i, 0), payload))
    writer.close()
    return rec_path


def test_image_iter_transient_read_retried_same_pixels(tmp_path):
    rec = _tiny_rec(tmp_path)

    def read_all():
        it = mx.image.ImageIter(batch_size=4, data_shape=(3, 16, 16),
                                path_imgrec=rec, retry_policy=FAST)
        return np.concatenate([b.data[0].asnumpy() for b in it])

    clean = read_all()
    faults.inject("io.record_read", nth=3, kind="transient", times=3)
    faulty = read_all()
    np.testing.assert_array_equal(clean, faulty)


def test_image_iter_read_failures_beyond_budget_raise(tmp_path):
    rec = _tiny_rec(tmp_path)
    faults.inject("io.record_read", nth=1, kind="transient", times=99)
    it = mx.image.ImageIter(batch_size=4, data_shape=(3, 16, 16),
                            path_imgrec=rec, retry_policy=FAST)
    with pytest.raises(MXNetError, match=r"io\.record_read.*4 attempts"):
        it.next()


def test_image_iter_skips_corrupt_with_counter(tmp_path):
    rec = _tiny_rec(tmp_path, n=9, corrupt={2})
    health = mxio.DataHealth()
    it = mx.image.ImageIter(batch_size=4, data_shape=(3, 16, 16),
                            path_imgrec=rec, skip_corrupt=True,
                            data_health=health)
    batches = list(it)
    assert len(batches) == 2              # 8 good records / batch 4
    labels = np.concatenate([b.label[0].asnumpy() for b in batches])
    assert 2.0 not in labels              # the corrupt record is gone
    assert health.report()["skipped_records"] == 1


def test_image_iter_corrupt_raises_without_skip(tmp_path):
    rec = _tiny_rec(tmp_path, n=4, corrupt={1})
    it = mx.image.ImageIter(batch_size=4, data_shape=(3, 16, 16),
                            path_imgrec=rec)
    with pytest.raises(mxio.CorruptRecordError, match="corrupt image"):
        it.next()


def test_recordio_truncated_payload_detected(tmp_path):
    from mxnet_tpu import recordio
    path = str(tmp_path / "t.rec")
    w = recordio.MXRecordIO(path, "w")
    w.write(b"x" * 64)
    w.close()
    with open(path, "r+b") as f:
        f.truncate(32)                    # cut inside the payload
    r = recordio.MXRecordIO(path, "r")
    with pytest.raises(MXNetError, match="truncated record"):
        r.read()


# -- checkpoint writes -------------------------------------------------------

def test_atomic_write_abort_leaves_live_file_untouched(tmp_path):
    from mxnet_tpu.model import atomic_write_bytes
    target = str(tmp_path / "f.bin")
    atomic_write_bytes(target, b"generation-1")
    faults.inject("checkpoint.write.mid", nth=1, kind="raise")
    with pytest.raises(faults.InjectedFault):
        atomic_write_bytes(target, b"generation-2-longer")
    with open(target, "rb") as f:
        assert f.read() == b"generation-1"     # old data intact
    leftovers = [p for p in tmp_path.iterdir() if ".tmp" in p.name]
    assert not leftovers                       # no orphaned temp files


def test_atomic_write_truncate_kind_produces_torn_file(tmp_path):
    from mxnet_tpu.model import atomic_write_bytes
    target = str(tmp_path / "f.bin")
    faults.inject("checkpoint.write", nth=1, kind="truncate")
    atomic_write_bytes(target, b"0123456789")
    with open(target, "rb") as f:
        assert f.read() == b"01234"            # torn, for load-side tests


# -- kvstore -----------------------------------------------------------------

def _local_kv():
    kv = mx.kvstore.create("local")
    kv.set_fault_policy(retries=2, backoff=0.0)
    kv.init(0, nd.array(np.ones(3, np.float32)))
    return kv


def test_kvstore_push_transient_retried_once_applied_once():
    kv = _local_kv()
    faults.inject("kvstore.push", nth=1, kind="transient")
    kv.push(0, nd.array(np.full(3, 5.0, np.float32)))
    out = nd.array(np.zeros(3, np.float32))
    kv.pull(0, out)
    # the retried push replaced the stored value exactly once
    np.testing.assert_array_equal(out.asnumpy(), np.full(3, 5.0))


def test_kvstore_push_budget_exhaustion():
    kv = _local_kv()
    faults.inject("kvstore.push", nth=1, kind="transient", times=99)
    with pytest.raises(MXNetError, match=r"kvstore\.push failed after 3 "
                                         r"attempts"):
        kv.push(0, nd.array(np.ones(3, np.float32)))


def test_kvstore_drop_kind_is_retried():
    kv = _local_kv()
    faults.inject("kvstore.pull", nth=1, kind="drop")
    out = nd.array(np.zeros(3, np.float32))
    kv.pull(0, out)
    np.testing.assert_array_equal(out.asnumpy(), np.ones(3))


def test_kvstore_barrier_timeout_escalates_without_reentry():
    # a STARTED barrier that times out must escalate immediately, never
    # retry: the abandoned watchdog thread may still be participating in
    # the collective, and re-entering it would corrupt the rendezvous
    kv = mx.kvstore.create("local")
    kv.set_fault_policy(timeout=0.05, retries=3, backoff=0.0)
    held = {"v": True}
    entries = []

    def slow_barrier():
        import time
        entries.append(1)
        t0 = time.monotonic()
        while held["v"] and time.monotonic() - t0 < 5:
            time.sleep(0.005)

    kv._barrier = slow_barrier
    try:
        with pytest.raises(MXNetError, match=r"kvstore\.barrier timed out "
                                             r"after it started"):
            kv.barrier()
        assert len(entries) == 1          # no second entry into the barrier
    finally:
        held["v"] = False


def test_kvstore_degradation_warn_checkpoint_raise():
    kv = mx.kvstore.create("local")
    kv.set_fault_policy(health_interval=0.0)
    faults.inject("kvstore.dead_node", nth=1, kind="dead:2", times=99)
    checkpoints = []
    assert kv.check_health(on_degraded=lambda: checkpoints.append(1),
                           force=True) == 2          # strike 1: warn
    assert kv.check_health(on_degraded=lambda: checkpoints.append(1),
                           force=True) == 2          # strike 2: checkpoint
    assert checkpoints == [1]
    with pytest.raises(mx.kvstore.WorkerLostError):  # strike 3: raise
        kv.check_health(force=True)


def test_kvstore_recovery_resets_strikes():
    kv = mx.kvstore.create("local")
    kv.set_fault_policy(health_interval=0.0)
    faults.inject("kvstore.dead_node", nth=1, kind="dead:1", times=2)
    kv.check_health(force=True)
    kv.check_health(force=True)
    assert kv.check_health(force=True) == 0   # healthy scan resets
    assert kv._dead_strikes == 0


def test_heartbeat_startup_grace_not_dead_before_first_publish():
    from mxnet_tpu.kvstore import _Heartbeat

    class FakeClient(object):
        """Speaks the read API dead_nodes actually uses: one dir scan
        (this jaxlib has no key_value_try_get)."""

        def __init__(self, stamps):
            self.stamps = stamps

        def key_value_dir_get(self, prefix):
            return [(k, v) for k, v in self.stamps.items()
                    if k.startswith(prefix)]

    import time
    hb = _Heartbeat.__new__(_Heartbeat)
    hb.rank = 0
    hb.interval = 2.0
    hb.startup_grace = None
    hb._started = time.time()
    hb._seen = set()
    hb._stop = None
    client = FakeClient({})
    hb._client = lambda: client
    # peer 1 has never published and we just started: NOT dead (grace)
    assert hb.dead_nodes(2, timeout_sec=60) == 0
    # once a peer has been seen, silence means dead
    client.stamps[_Heartbeat.KEY % 1] = repr(time.time())
    assert hb.dead_nodes(2, timeout_sec=60) == 0
    del client.stamps[_Heartbeat.KEY % 1]
    assert hb.dead_nodes(2, timeout_sec=60) == 1
    # a stale (old) beat also counts as dead
    client.stamps[_Heartbeat.KEY % 1] = repr(time.time() - 120)
    assert hb.dead_nodes(2, timeout_sec=60) == 1
    # and a never-seen peer past the startup grace is dead too
    hb2 = _Heartbeat.__new__(_Heartbeat)
    hb2.rank = 0
    hb2.interval = 2.0
    hb2.startup_grace = 0.0
    hb2._started = time.time() - 1
    hb2._seen = set()
    hb2._client = lambda: FakeClient({})
    assert hb2.dead_nodes(2, timeout_sec=60) == 1


def test_retry_call_permanent_oserror_not_retried():
    calls = []

    def op():
        calls.append(1)
        raise FileNotFoundError("/no/such/file")

    with pytest.raises(FileNotFoundError):
        mxio.retry_call(op, "t.perm", FAST)
    assert len(calls) == 1                # no budget burned, real cause kept


def test_image_iter_skips_record_level_corruption(tmp_path):
    # damage the RECORD framing (not the JPEG): skip_corrupt must still skip
    from mxnet_tpu import recordio
    rec = _tiny_rec(tmp_path, n=8)
    idx_path = str(tmp_path / "data.idx")
    reader = recordio.MXIndexedRecordIO(idx_path, rec, "r")
    off = reader.idx[3]
    reader.close()
    with open(rec, "r+b") as f:
        f.seek(off)
        f.write(b"\x00\x00\x00\x00")      # clobber the magic of record 3
    health = mxio.DataHealth()
    it = mx.image.ImageIter(batch_size=4, data_shape=(3, 16, 16),
                            path_imgrec=rec, skip_corrupt=True,
                            data_health=health)
    labels = np.concatenate([b.label[0].asnumpy() for b in it])
    assert 3.0 not in labels
    assert health.report()["skipped_records"] == 1


def test_retry_policy_jitter_decorrelated_across_workers(monkeypatch):
    monkeypatch.setenv("MXTPU_RANK", "0")
    p0 = mxio.RetryPolicy(base_delay=0.01)
    monkeypatch.setenv("MXTPU_RANK", "1")
    p1 = mxio.RetryPolicy(base_delay=0.01)
    assert p0.delay(1, "io.record_read") != p1.delay(1, "io.record_read")
