"""MultiHeadAttention / LayerNorm ops and sequence-parallel execution.

The long-context flagship surface (SURVEY.md §5; supersedes
example/model-parallel-lstm). Ring/Ulysses numerics run on the virtual
8-device CPU mesh (conftest).
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import models
from mxnet_tpu.base import MXNetError
from mxnet_tpu.train_step import TrainStep
from mxnet_tpu.parallel.mesh import make_mesh, MeshScope


def _naive_mha(x, wqkv, bqkv, wout, bout, H, causal):
    B, S, E = x.shape
    d = E // H
    qkv = x @ wqkv.T + bqkv
    q, k, v = [qkv[:, :, i * E:(i + 1) * E].reshape(B, S, H, d)
               .transpose(0, 2, 1, 3) for i in range(3)]
    s = np.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(d)
    if causal:
        mask = np.tril(np.ones((S, S), bool))
        s = np.where(mask, s, -1e30)
    p = np.exp(s - s.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    o = np.einsum("bhqk,bhkd->bhqd", p, v).transpose(0, 2, 1, 3)
    return o.reshape(B, S, E) @ wout.T + bout


@pytest.mark.parametrize("causal", [False, True])
def test_mha_matches_naive(causal):
    rng = np.random.RandomState(0)
    B, S, E, H = 2, 16, 32, 4
    x = rng.randn(B, S, E).astype(np.float32)
    wqkv = (rng.randn(3 * E, E) * 0.1).astype(np.float32)
    bqkv = rng.randn(3 * E).astype(np.float32) * 0.1
    wout = (rng.randn(E, E) * 0.1).astype(np.float32)
    bout = rng.randn(E).astype(np.float32) * 0.1
    out = mx.nd.MultiHeadAttention(
        mx.nd.array(x), mx.nd.array(wqkv), mx.nd.array(bqkv),
        mx.nd.array(wout), mx.nd.array(bout),
        num_heads=H, causal=causal).asnumpy()
    ref = _naive_mha(x, wqkv, bqkv, wout, bout, H, causal)
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)


def test_mha_no_bias_and_infer_shape():
    data = mx.sym.Variable("data")
    att = mx.sym.MultiHeadAttention(data=data, num_heads=4, no_bias=True,
                                    name="att")
    assert att.list_arguments() == ["data", "att_qkv_weight",
                                    "att_out_weight"]
    arg, out, _ = att.infer_shape(data=(2, 8, 16))
    assert arg == [(2, 8, 16), (48, 16), (16, 16)]
    assert out == [(2, 8, 16)]


def test_mha_invalid_heads():
    data = mx.sym.Variable("data")
    att = mx.sym.MultiHeadAttention(data=data, num_heads=5)
    with pytest.raises(MXNetError, match="num_heads"):
        att.infer_shape(data=(2, 8, 16))


def test_mha_seq_parallel_needs_mesh():
    x = np.zeros((2, 8, 16), np.float32)
    w = np.zeros((48, 16), np.float32)
    o = np.zeros((16, 16), np.float32)
    with pytest.raises(MXNetError, match="seq"):
        mx.nd.MultiHeadAttention(mx.nd.array(x), mx.nd.array(w),
                                 mx.nd.array(o), num_heads=4, no_bias=True,
                                 seq_parallel="ring")


def test_layer_norm_matches_numpy():
    rng = np.random.RandomState(1)
    x = rng.randn(3, 5, 8).astype(np.float32)
    g = rng.rand(8).astype(np.float32) + 0.5
    b = rng.randn(8).astype(np.float32)
    out = mx.nd.LayerNorm(mx.nd.array(x), mx.nd.array(g), mx.nd.array(b),
                          eps=1e-5).asnumpy()
    mu = x.mean(-1, keepdims=True)
    var = ((x - mu) ** 2).mean(-1, keepdims=True)
    ref = (x - mu) / np.sqrt(var + 1e-5) * g + b
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)


def test_layer_norm_gradient():
    from mxnet_tpu.test_utils import check_numeric_gradient
    data = mx.sym.Variable("data")
    g = mx.sym.Variable("gamma")
    b = mx.sym.Variable("beta")
    net = mx.sym.LayerNorm(data=data, gamma=g, beta=b)
    check_numeric_gradient(net, {"data": np.random.rand(2, 3, 4).astype(
        np.float32), "gamma": np.ones(4, np.float32),
        "beta": np.zeros(4, np.float32)})


def _one_step(mode, mesh, B=4, S=32, V=32, E=32):
    rng = np.random.RandomState(0)
    data = rng.randint(0, V, (B, S)).astype(np.float32)
    label = rng.randint(0, V, (B, S)).astype(np.float32)
    sym = models.transformer(vocab_size=V, embed=E, num_heads=4,
                             num_layers=2, seq_len=S, seq_parallel=mode)
    scope = MeshScope(mesh) if mesh is not None else None
    if scope:
        scope.__enter__()
    try:
        step = TrainStep(sym, optimizer="sgd", learning_rate=0.1, mesh=mesh)
        st = step.init({"data": (B, S)}, {"softmax_label": (B, S)}, seed=3)
        batch = {"data": data, "softmax_label": label}
        if mesh is not None:
            batch = step.shard_batch(batch)
        st2, _ = step.step(st, batch)
        return {k: np.asarray(v, np.float32)
                for k, v in st2["params"].items()}
    finally:
        if scope:
            scope.__exit__(None, None, None)


@pytest.mark.parametrize("mode", ["ring", "ulysses"])
def test_seq_parallel_one_step_matches_single_device(mode):
    base = _one_step("", None)
    mesh = make_mesh({"data": 2, "seq": 4})
    got = _one_step(mode, mesh)
    for k in base:
        np.testing.assert_allclose(base[k], got[k], rtol=1e-4, atol=1e-5,
                                   err_msg=k)


def test_transformer_symbol_json_roundtrip():
    sym = models.transformer(vocab_size=32, embed=32, num_heads=4,
                             num_layers=1, seq_len=16)
    back = mx.sym.load_json(sym.tojson())
    assert back.list_arguments() == sym.list_arguments()
