"""Device-fed input tier (mxnet_tpu/data/, docs/perf.md "Device-fed input
pipeline"): shard-aware reader, decode worker pool, prefetch-to-device,
PipelineStats — and the tier's load-bearing contract: worker parallelism
never perturbs the sample stream (bitwise train parity across worker
counts, deterministic shuffle + resume), and failures are prompt and
named, never hangs (fault sites ``data.worker_die``/``data.decode_delay``).
"""
import io as _bio
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import data as mdata
from mxnet_tpu import faults, io as mxio, recordio
from mxnet_tpu.base import MXNetError

PIL = pytest.importorskip("PIL.Image")


# -- dataset helpers --------------------------------------------------------

def _make_rec(path, n=64, h=40, w=40, classes=4, seed=0, quality=92):
    rng = np.random.default_rng(seed)
    colors = np.array([[200, 40, 40], [40, 200, 40], [40, 40, 200],
                       [200, 200, 40]], np.float32)
    idx = os.path.splitext(path)[0] + ".idx"
    rec = recordio.MXIndexedRecordIO(idx, path, "w")
    for i in range(n):
        k = i % classes
        img = (rng.normal(110, 25, (h, w, 3))
               + 0.55 * (colors[k % 4] - 110)).clip(0, 255).astype(np.uint8)
        buf = _bio.BytesIO()
        PIL.fromarray(img).save(buf, format="JPEG", quality=quality)
        rec.write_idx(i, recordio.pack(
            recordio.IRHeader(0, float(k), i, 0), buf.getvalue()))
    rec.close()
    return path


def _record_iter(rec, num_workers, **kw):
    kw.setdefault("data_shape", (3, 32, 32))
    kw.setdefault("batch_size", 16)
    kw.setdefault("resize", 36)
    return mx.image.ImageRecordIter(path_imgrec=rec,
                                    num_workers=num_workers, **kw)


def _small_convnet(nc=4):
    d = mx.sym.Variable("data")
    n = mx.sym.Convolution(data=d, num_filter=8, kernel=(3, 3),
                           stride=(2, 2), pad=(1, 1), name="c1")
    n = mx.sym.BatchNorm(data=n, fix_gamma=False, name="bn1")
    n = mx.sym.Activation(data=n, act_type="relu")
    n = mx.sym.Pooling(data=n, global_pool=True, kernel=(1, 1),
                       pool_type="avg")
    n = mx.sym.Flatten(data=n)
    n = mx.sym.FullyConnected(data=n, num_hidden=nc, name="fc")
    return mx.sym.SoftmaxOutput(data=n, name="softmax")


# -- PipelineStats ----------------------------------------------------------

def test_pipeline_stats_stages_and_mirror():
    parent = mdata.PipelineStats()
    st = mdata.PipelineStats(parent=parent)
    st.add("read", 0.5, n=10)
    st.add("decode", 1.0, n=10)
    st.add("stall", 0.25)
    st.note_queue_depth(2)
    st.note_queue_depth(4)
    rep = st.report()
    assert rep["read_s"] == 0.5 and rep["read_n"] == 10
    assert rep["decode_s"] == 1.0
    assert rep["stall_s"] == 0.25 and rep["stall_frac"] > 0
    assert rep["queue_depth_avg"] == 3.0 and rep["queue_depth_max"] == 4
    # mirrors into the parent aggregate (the io.DATA_HEALTH convention)
    assert parent.report()["decode_s"] == 1.0
    assert parent.report()["queue_depth_max"] == 4
    st.reset()
    assert "read_s" not in st.report()


def test_pipeline_stats_timed():
    st = mdata.PipelineStats()
    assert st.timed("read", lambda: 7) == 7
    assert st.report()["read_n"] == 1


# -- ShardedRecordReader ----------------------------------------------------

def test_reader_two_level_sharding(tmp_path):
    rec = _make_rec(str(tmp_path / "a.rec"), n=64)
    full = mdata.ShardedRecordReader(rec)
    assert len(full) == 64
    host0 = mdata.ShardedRecordReader(rec, part_index=0, num_parts=2)
    host1 = mdata.ShardedRecordReader(rec, part_index=1, num_parts=2)
    assert host0.keys == full.keys[:32] and host1.keys == full.keys[32:]
    # per-chip sub-shard within the host shard (the data-mesh feeder)
    sub = mdata.ShardedRecordReader(rec, part_index=1, num_parts=2,
                                    sub_index=1, sub_parts=4)
    assert sub.keys == full.keys[32:][8:16]
    with pytest.raises(MXNetError, match="sub_parts"):
        mdata.ShardedRecordReader(rec, sub_index=0, sub_parts=128)


def test_reader_epoch_order_pure_function(tmp_path):
    rec = _make_rec(str(tmp_path / "a.rec"), n=32)
    r1 = mdata.ShardedRecordReader(rec, shuffle=True, seed=7)
    r2 = mdata.ShardedRecordReader(rec, shuffle=True, seed=7)
    # pure function of (seed, epoch): no reset-history dependence, and
    # calling epoch 5 before epoch 0 changes nothing
    assert r1.epoch_order(5) == r2.epoch_order(5)
    assert r1.epoch_order(0) == r2.epoch_order(0)
    assert r1.epoch_order(0) != r1.epoch_order(1)
    assert sorted(r1.epoch_order(1)) == sorted(r1.keys)
    r3 = mdata.ShardedRecordReader(rec, shuffle=True, seed=8)
    assert r3.epoch_order(0) != r1.epoch_order(0)
    plain = mdata.ShardedRecordReader(rec, shuffle=False, seed=7)
    assert plain.epoch_order(3) == plain.keys


def test_reader_reads_and_corrupt_classification(tmp_path):
    rec = _make_rec(str(tmp_path / "a.rec"), n=8)
    r = mdata.ShardedRecordReader(rec)
    hdr, payload = r.read(r.keys[3])
    assert hdr.label == 3.0 and payload[:2] == b"\xff\xd8"
    # truncate the file mid-way: a damaged record classifies as
    # CorruptRecordError (permanent; skip path), not a retried transient
    size = os.path.getsize(rec)
    with open(rec, "r+b") as f:
        f.truncate(size - 10)
    r2 = mdata.ShardedRecordReader(rec)
    with pytest.raises(mxio.CorruptRecordError):
        r2.read(r2.keys[-1])
    assert r2.data_health.report()["retries"] == 0  # permanent: no retry


def test_reader_transient_retry_rides_policy(tmp_path):
    rec = _make_rec(str(tmp_path / "a.rec"), n=8)
    faults.clear()
    health = mxio.DataHealth()
    r = mdata.ShardedRecordReader(
        rec, retry_policy=mxio.RetryPolicy(max_retries=2, base_delay=0.0),
        data_health=health)
    faults.inject("io.record_read", nth=1, kind="transient")
    hdr, _ = r.read(r.keys[0])
    assert hdr.label == 0.0
    assert health.report()["retries"] == 1
    faults.clear()


def test_reader_thread_safe_concurrent_reads(tmp_path):
    rec = _make_rec(str(tmp_path / "a.rec"), n=32)
    r = mdata.ShardedRecordReader(rec)
    import threading
    errs = []

    def hammer():
        try:
            for k in r.keys:
                hdr, payload = r.read(k)
                assert hdr.label == float(k % 4)
                assert payload[:2] == b"\xff\xd8"
        except Exception as e:  # pragma: no cover
            errs.append(e)

    ts = [threading.Thread(target=hammer) for _ in range(4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert not errs, errs


# -- DecodeWorkerPool -------------------------------------------------------

def _echo_tasks(n):
    return [(list(range(i * 4, (i + 1) * 4)), 100 + i) for i in range(n)]


def test_pool_emits_in_order_any_worker_count():
    def batch_fn(keys, seed):
        time.sleep(0.001 * (seed % 3))  # jitter completion order
        return (list(keys), seed)

    for nw in (1, 3):
        pool = mdata.DecodeWorkerPool(batch_fn, _echo_tasks(9), nw)
        got = []
        while True:
            try:
                got.append(pool.next_batch())
            except StopIteration:
                break
        assert got == [(list(range(i * 4, (i + 1) * 4)), 100 + i)
                       for i in range(9)]
        pool.close()


def test_pool_decode_error_surfaces_at_its_batch_position():
    def batch_fn(keys, seed):
        if seed == 102:
            raise mxio.CorruptRecordError("batch 2 is bad")
        return seed

    pool = mdata.DecodeWorkerPool(batch_fn, _echo_tasks(5), 2)
    assert pool.next_batch() == 100
    assert pool.next_batch() == 101
    with pytest.raises(mxio.CorruptRecordError, match="batch 2"):
        pool.next_batch()
    pool.close()


@pytest.mark.faults
def test_pool_dead_worker_fails_consumer_promptly():
    faults.clear()
    pool = mdata.DecodeWorkerPool(lambda keys, seed: seed,
                                  _echo_tasks(8), 1)
    faults.inject("data.worker_die", nth=3, kind="die")
    assert pool.next_batch() == 100
    assert pool.next_batch() == 101
    t0 = time.monotonic()
    with pytest.raises(MXNetError, match="data.worker_die"):
        for _ in range(6):
            pool.next_batch()
    assert time.monotonic() - t0 < 5.0, "detection must be prompt"
    faults.clear()
    pool.close()


@pytest.mark.faults
def test_pool_slow_worker_stalls_but_never_reorders():
    faults.clear()
    faults.inject("data.decode_delay", nth=2, kind="delay", delay=0.3)
    stats = mdata.PipelineStats()
    pool = mdata.DecodeWorkerPool(lambda keys, seed: seed,
                                  _echo_tasks(6), 2, stats=stats)
    got = []
    while True:
        try:
            got.append(pool.next_batch())
        except StopIteration:
            break
    assert got == [100 + i for i in range(6)], "order must survive a stall"
    rep = stats.report()
    # direct pool consumption charges "wait" (through the prefetcher the
    # same delay surfaces as training-loop "stall" once the queue dries)
    assert rep["wait_s"] >= 0.1, rep
    faults.clear()
    pool.close()


def test_pool_claim_pacing_bounds_decode_ahead():
    """One slow batch must not trigger unbounded decode-ahead: claims are
    paced to a window of queue_depth + workers past the consumer."""
    claimed = []

    def batch_fn(keys, seed):
        claimed.append(seed)
        if seed == 100:
            time.sleep(0.3)
        return seed

    pool = mdata.DecodeWorkerPool(batch_fn, _echo_tasks(40), 2,
                                  queue_depth=2)
    assert pool.next_batch() == 100
    # while batch 0 slept, workers could claim at most the pacing window
    assert len(claimed) <= 2 + 2 + 2 + 1, claimed  # window + in-flight slop
    while True:
        try:
            pool.next_batch()
        except StopIteration:
            break
    assert sorted(claimed) == [100 + i for i in range(40)]
    pool.close()


# -- image iterators through the pool --------------------------------------

def test_record_iter_pool_matches_legacy_no_shuffle(tmp_path):
    rec = _make_rec(str(tmp_path / "a.rec"), n=64)
    legacy = _record_iter(rec, 0, prefetch=False)
    pooled = _record_iter(rec, 2)
    for _ in range(4):
        a, b = legacy.next_host(), pooled.next_host()
        np.testing.assert_array_equal(a.data[0], b.data[0])
        np.testing.assert_array_equal(a.label[0], b.label[0])
    pooled.close()


def test_record_iter_pool_shuffle_parity_across_worker_counts(tmp_path):
    rec = _make_rec(str(tmp_path / "a.rec"), n=64)
    kw = dict(shuffle=True, seed=3, rand_crop=True, rand_mirror=True)
    one = _record_iter(rec, 1, **kw)
    four = _record_iter(rec, 4, **kw)
    for _ in range(2):  # two epochs: order differs across, matches within
        for _ in range(4):
            a, b = one.next_host(), four.next_host()
            np.testing.assert_array_equal(a.data[0], b.data[0])
            np.testing.assert_array_equal(a.label[0], b.label[0])
        one.reset()
        four.reset()
    one.close()
    four.close()


def test_record_iter_set_epoch_resumes_mid_schedule(tmp_path):
    rec = _make_rec(str(tmp_path / "a.rec"), n=64)
    kw = dict(shuffle=True, seed=3, rand_crop=True, rand_mirror=True)
    ref = _record_iter(rec, 1, **kw)
    epochs = []
    for _ in range(3):
        epochs.append([ref.next_host().data[0].copy() for _ in range(4)])
        ref.reset()
    ref.close()
    # a FRESH iterator pinned to epoch 2 reproduces epoch 2 exactly —
    # the property fit's resume fast-forward depends on
    fresh = _record_iter(rec, 2, **kw)
    fresh.set_epoch(2)
    for want in epochs[2]:
        np.testing.assert_array_equal(want, fresh.next_host().data[0])
    fresh.close()


def test_record_iter_pool_round_batch_pad(tmp_path):
    rec = _make_rec(str(tmp_path / "a.rec"), n=40)  # 2.5 batches of 16
    it = _record_iter(rec, 2)
    pads = []
    while True:
        try:
            pads.append(it.next_host().pad)
        except StopIteration:
            break
    assert pads == [0, 0, 8]  # tail wraps 8 records, reported as pad
    it.close()
    legacy = _record_iter(rec, 0, prefetch=False)
    lpads = []
    while True:
        try:
            lpads.append(legacy.next_host().pad)
        except StopIteration:
            break
    assert lpads == pads


def test_record_iter_pool_sub_sharding(tmp_path):
    rec = _make_rec(str(tmp_path / "a.rec"), n=64)
    whole = _record_iter(rec, 1, batch_size=8)
    chip1 = _record_iter(rec, 1, batch_size=8, sub_index=1, sub_parts=2)
    whole_labels = []
    for _ in range(8):
        whole_labels.extend(whole.next_host().label[0].tolist())
    chip_labels = []
    for _ in range(4):
        chip_labels.extend(chip1.next_host().label[0].tolist())
    assert chip_labels == whole_labels[32:]
    whole.close()
    chip1.close()


def test_image_iter_pool_parity_and_aug_determinism(tmp_path):
    rec = _make_rec(str(tmp_path / "a.rec"), n=48)
    aug = mx.image.CreateAugmenter((3, 24, 24), resize=28, rand_crop=True,
                                   rand_mirror=True)
    kw = dict(batch_size=16, data_shape=(3, 24, 24), path_imgrec=rec,
              shuffle=True, seed=9, aug_list=aug)
    a = mx.image.ImageIter(num_workers=1, **kw)
    b = mx.image.ImageIter(num_workers=3, **kw)
    for _ in range(3):
        ba, bb = a.next_host(), b.next_host()
        np.testing.assert_array_equal(ba.data[0], bb.data[0])
        np.testing.assert_array_equal(ba.label[0], bb.label[0])
    a.close()
    b.close()


def test_image_iter_pool_skip_corrupt_backfills_deterministically(tmp_path):
    rec = str(tmp_path / "a.rec")
    idx = str(tmp_path / "a.idx")
    w = recordio.MXIndexedRecordIO(idx, rec, "w")
    rng = np.random.default_rng(0)
    for i in range(16):
        if i == 5:
            w.write_idx(i, recordio.pack(
                recordio.IRHeader(0, float(i), i, 0), b"not a jpeg"))
            continue
        img = rng.integers(0, 255, (28, 28, 3)).astype(np.uint8)
        buf = _bio.BytesIO()
        PIL.fromarray(img).save(buf, format="JPEG")
        w.write_idx(i, recordio.pack(
            recordio.IRHeader(0, float(i), i, 0), buf.getvalue()))
    w.close()
    health = mxio.DataHealth()
    kw = dict(batch_size=8, data_shape=(3, 28, 28), path_imgrec=rec,
              skip_corrupt=True, data_health=health)
    it1 = mx.image.ImageIter(num_workers=1, **kw)
    it3 = mx.image.ImageIter(num_workers=3,
                             data_health=mxio.DataHealth(),
                             **{k: v for k, v in kw.items()
                                if k != "data_health"})
    b1, b3 = it1.next_host(), it3.next_host()
    np.testing.assert_array_equal(b1.data[0], b3.data[0])
    # slot 5 backfilled from slot 4 (nearest previous good), counted
    np.testing.assert_array_equal(b1.data[0][5], b1.data[0][4])
    assert b1.label[0][5] == 4.0
    assert health.report()["skipped_records"] == 1
    # without skip_corrupt the pool path raises at the right batch
    strict = mx.image.ImageIter(num_workers=2, batch_size=8,
                                data_shape=(3, 28, 28), path_imgrec=rec)
    with pytest.raises(mxio.CorruptRecordError):
        strict.next_host()
    it1.close()
    it3.close()
    strict.close()


# -- prefetch-to-device -----------------------------------------------------

def test_device_prefetcher_stages_and_superbatch(tmp_path):
    rec = _make_rec(str(tmp_path / "a.rec"), n=64)
    it = _record_iter(rec, 2)
    pf = mdata.DevicePrefetcher(it, 2, depth=1)
    assert pf.stats is it.data_stats  # ONE stats object for the tier
    sb = pf.next()
    assert sb.data[0].shape == (2, 16, 3, 32, 32)
    assert sb.num_steps == 2
    rep = pf.stats.report()
    for stage in ("read_s", "decode_s", "stack_s", "h2d_s"):
        assert rep.get(stage, 0) > 0, (stage, rep)
    assert "stall_frac" in rep and "queue_depth_avg" in rep
    pf.close()
    it.close()


def test_device_prefetcher_set_epoch_delegates(tmp_path):
    rec = _make_rec(str(tmp_path / "a.rec"), n=64)
    kw = dict(shuffle=True, seed=3)
    ref = _record_iter(rec, 1, **kw)
    ref.reset()  # epoch 1
    want = ref.next_host().data[0].copy()
    ref.close()
    it = _record_iter(rec, 2, **kw)
    pf = mdata.DevicePrefetcher(it, 2, depth=1)
    pf.set_epoch(1)
    sb = pf.next()
    np.testing.assert_array_equal(np.asarray(sb.data[0].data)[0], want)
    pf.close()
    it.close()


# -- fit through the tier: the bitwise contracts ---------------------------

def _fit_params(rec, num_workers, k=2, epochs=2, ckpt=None, resume=None,
                num_epoch_override=None):
    mx.random.seed(0)
    it = _record_iter(rec, num_workers, shuffle=True, seed=5)
    mod = mx.mod.Module(_small_convnet())
    mod.fit(it, num_epoch=num_epoch_override or epochs,
            steps_per_dispatch=k,
            optimizer_params={"learning_rate": 0.1, "momentum": 0.9},
            checkpoint_prefix=ckpt, resume=resume,
            checkpoint_every_n_batches=4 if ckpt else None)
    it.close()
    arg, aux = mod.get_params()
    out = {n: v.asnumpy() for n, v in arg.items()}
    out.update({n: v.asnumpy() for n, v in aux.items()})
    return out


def test_fit_bitwise_parity_across_worker_counts(tmp_path):
    rec = _make_rec(str(tmp_path / "a.rec"), n=128)
    p1 = _fit_params(rec, 1)
    p4 = _fit_params(rec, 4)
    assert sorted(p1) == sorted(p4)
    for n in p1:
        np.testing.assert_array_equal(p1[n], p4[n], err_msg=n)


def test_fit_resume_through_pool_bitwise(tmp_path):
    """Kill-free resume equivalence: train epoch 0 with checkpoints, then
    a FRESH process-state (new module + new iterator) resumes at epoch 1
    via set_epoch fast-forward — final params bitwise-match the
    uninterrupted 2-epoch run. This is the tier-1 stand-in for the slow
    SIGKILL test, exercising the same epoch-pinning path."""
    rec = _make_rec(str(tmp_path / "a.rec"), n=128)
    ref = _fit_params(rec, 2)
    ck = str(tmp_path / "ck")
    _fit_params(rec, 2, ckpt=ck, resume="auto", num_epoch_override=1)
    got = _fit_params(rec, 2, ckpt=ck, resume="auto")
    for n in ref:
        np.testing.assert_array_equal(ref[n], got[n], err_msg=n)


@pytest.mark.faults
def test_fit_dead_worker_surfaces_not_hangs(tmp_path):
    rec = _make_rec(str(tmp_path / "a.rec"), n=128)
    faults.clear()
    faults.inject("data.worker_die", nth=3, kind="die")
    it = _record_iter(rec, 2, shuffle=True, seed=5)
    mod = mx.mod.Module(_small_convnet())
    with pytest.raises(MXNetError, match="data.worker_die"):
        mod.fit(it, num_epoch=1, steps_per_dispatch=2,
                optimizer_params={"learning_rate": 0.1})
    faults.clear()
    it.close()


# -- MXTPU_BF16_STATS (perf.md next-steps item 2) --------------------------

def test_bf16_stats_storage_dtypes_and_sync(tmp_path, monkeypatch):
    monkeypatch.setenv("MXTPU_BF16_STATS", "all")
    rec = _make_rec(str(tmp_path / "a.rec"), n=64)
    mx.random.seed(0)
    it = _record_iter(rec, 1, shuffle=True, seed=5)
    mod = mx.mod.Module(_small_convnet())
    mod.fit(it, num_epoch=1, steps_per_dispatch=2,
            optimizer_params={"learning_rate": 0.1, "momentum": 0.9})
    it.close()
    st = mod._fused_state
    assert str(st["aux"]["bn1_moving_mean"].dtype) == "bfloat16"
    mom = st["opt"]["c1_weight"]
    leaf = mom[0] if isinstance(mom, tuple) else mom
    assert str(leaf.dtype) == "bfloat16"
    # executor arrays and checkpoints stay f32 (exact widen-back)
    _, aux = mod.get_params()
    assert aux["bn1_moving_mean"].asnumpy().dtype == np.float32
    assert np.isfinite(aux["bn1_moving_mean"].asnumpy()).all()
    # serialized optimizer state stays f32 too
    states = str(tmp_path / "opt.states")
    mod.save_optimizer_states(states)
    mod.load_optimizer_states(states)


def test_bf16_stats_run_to_run_deterministic(tmp_path, monkeypatch):
    monkeypatch.setenv("MXTPU_BF16_STATS", "all")
    rec = _make_rec(str(tmp_path / "a.rec"), n=64)
    a = _fit_params(rec, 2, epochs=1)
    b = _fit_params(rec, 2, epochs=1)
    for n in a:
        np.testing.assert_array_equal(a[n], b[n], err_msg=n)


# -- SIGKILL through the worker pool (slow tier) ---------------------------

@pytest.mark.slow
def test_sigkill_and_resume_through_worker_pool(tmp_path):
    """The PR 2 SIGKILL contract THROUGH the device-fed tier: a killed run
    re-launched with the same command line — shuffling ImageRecordIter,
    2 decode workers, superbatch dispatch — lands bitwise-identical final
    params (deterministic epoch order + set_epoch fast-forward)."""
    rec = _make_rec(str(tmp_path / "train.rec"), n=256, h=32, w=32)
    worker = os.path.join(os.path.dirname(__file__), "resume_worker.py")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               RESUME_WORKER_IMAGE_REC=rec,
               RESUME_WORKER_DATA_WORKERS="2")

    def launch(prefix, out):
        return subprocess.Popen(
            [sys.executable, worker, prefix, out, "2"],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, env=env)

    ref_out = str(tmp_path / "ref.npz")
    p = launch(str(tmp_path / "ref-ck"), ref_out)
    assert p.wait(timeout=600) == 0, p.stdout.read()

    prefix = str(tmp_path / "ck")
    out = str(tmp_path / "resumed.npz")
    p = launch(prefix, out)
    killed = False
    for line in p.stdout:
        if line.startswith("BATCH 1."):
            os.kill(p.pid, signal.SIGKILL)
            killed = True
            break
    p.wait(timeout=60)
    assert killed, "worker finished before it could be killed"
    assert not os.path.exists(out)

    p = launch(prefix, out)
    assert p.wait(timeout=600) == 0, p.stdout.read()
    ref, got = np.load(ref_out), np.load(out)
    assert sorted(ref.files) == sorted(got.files)
    for name in ref.files:
        np.testing.assert_array_equal(ref[name], got[name], err_msg=name)
