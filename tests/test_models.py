"""Model zoo shape checks + fused train step."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import models
from mxnet_tpu.train_step import TrainStep


@pytest.mark.parametrize("depth,blocks", [(18, "basic"), (50, "bottleneck")])
def test_resnet_shapes(depth, blocks):
    s = models.resnet(num_classes=10, num_layers=depth, image_shape="3,32,32")
    arg_shapes, out_shapes, aux_shapes = s.infer_shape(
        data=(2, 3, 32, 32), softmax_label=(2,))
    assert out_shapes == [(2, 10)]


def test_lenet_shapes():
    s = models.lenet(num_classes=10)
    _, out_shapes, _ = s.infer_shape(data=(4, 1, 28, 28), softmax_label=(4,))
    assert out_shapes == [(4, 10)]


def test_alexnet_vgg_inception_infer():
    for name, shape in [("alexnet", (2, 3, 224, 224)),
                        ("vgg", (2, 3, 224, 224)),
                        ("inception-bn", (2, 3, 224, 224))]:
        s = models.get_symbol(name, num_classes=10)
        _, out_shapes, _ = s.infer_shape(data=shape, softmax_label=(2,))
        assert out_shapes == [(2, 10)], name


def test_train_step_resnet18_learns():
    """Fused train step drives loss down on separable data."""
    s = models.resnet(num_classes=4, num_layers=18, image_shape="3,16,16")
    step = TrainStep(s, optimizer="sgd", learning_rate=0.1, momentum=0.9)
    state = step.init({"data": (16, 3, 16, 16)}, {"softmax_label": (16,)})
    rng = np.random.default_rng(0)
    templates = rng.normal(size=(4, 3, 16, 16)).astype(np.float32)
    ys = rng.integers(0, 4, 16)
    data = {"data": templates[ys] + 0.1 * rng.normal(
                size=(16, 3, 16, 16)).astype(np.float32),
            "softmax_label": ys.astype(np.float32)}
    accs = []
    for i in range(30):
        state, outs = step.step(state, data)
        accs.append((np.asarray(outs[0]).argmax(1) == ys).mean())
    assert accs[-1] >= 0.9, accs[-5:]


def test_train_step_remat():
    """jax.checkpoint memory-mirroring path compiles and trains."""
    s = models.mlp(num_classes=4, hidden=(32,))
    step = TrainStep(s, optimizer="sgd", learning_rate=0.5, remat=True)
    state = step.init({"data": (8, 10)}, {"softmax_label": (8,)})
    rng = np.random.default_rng(0)
    data = {"data": rng.normal(size=(8, 10)).astype(np.float32),
            "softmax_label": rng.integers(0, 4, 8).astype(np.float32)}
    w0 = np.asarray(state["params"]["fc1_weight"]).copy()
    state, outs = step.step(state, data)
    assert not np.allclose(w0, np.asarray(state["params"]["fc1_weight"]))
