"""KVStore semantics (ref strategy: tests/python/unittest/test_kvstore.py —
aggregation over fake device lists)."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu import kvstore as kvs


def test_init_pull():
    kv = kvs.create("local")
    kv.init(3, nd.ones((2, 3)))
    out = nd.zeros((2, 3))
    kv.pull(3, out=out)
    assert (out.asnumpy() == 1).all()


def test_push_aggregation():
    kv = kvs.create("local")
    kv.init(3, nd.zeros((2, 3)))
    # push a list standing in for 4 devices
    kv.push(3, [nd.ones((2, 3)) for _ in range(4)])
    out = nd.zeros((2, 3))
    kv.pull(3, out=out)
    assert (out.asnumpy() == 4).all()


def test_updater():
    kv = kvs.create("local")
    kv.init("w", nd.zeros((2,)))

    def updater(key, recv, stored):
        stored += recv * 2
    kv._set_updater(updater)
    kv.push("w", nd.ones((2,)))
    out = nd.zeros((2,))
    kv.pull("w", out=out)
    assert (out.asnumpy() == 2).all()


def test_list_keys():
    kv = kvs.create("local")
    keys = [5, 7, 9]
    kv.init(keys, [nd.ones((2,))] * 3)
    kv.push(keys, [[nd.ones((2,))] * 2] * 3)  # 2 fake devices per key
    outs = [nd.zeros((2,)) for _ in keys]
    kv.pull(keys, out=outs)
    for o in outs:
        assert (o.asnumpy() == 2).all()  # replaced by the 2-device reduce


def test_set_optimizer_bsp_closed_form():
    """BSP semantics closed form (ref: tests/nightly/dist_sync_kvstore.py:
    with Test optimizer w += rescale*grad, after nrepeat pushes of ones*rate:
    w == 1 + rate * nrepeat * ndev)."""
    kv = kvs.create("local")
    kv.init(0, nd.ones((4,)))
    kv.set_optimizer(mx.optimizer.create("test", rescale_grad=1.0))
    nrepeat, ndev, rate = 3, 2, 2.0
    for _ in range(nrepeat):
        kv.push(0, [nd.ones((4,)) * rate for _ in range(ndev)])
    out = nd.zeros((4,))
    kv.pull(0, out=out)
    assert np.allclose(out.asnumpy(), 1 + rate * nrepeat * ndev)


def test_dist_sync_single_process():
    kv = kvs.create("dist_sync")
    assert kv.rank == 0
    assert kv.num_workers == 1
    kv.init(0, nd.zeros((2,)))
    kv.push(0, nd.ones((2,)))
    out = nd.zeros((2,))
    kv.pull(0, out=out)
    assert (out.asnumpy() == 1).all()
    kv.barrier()


def test_dist_async_single_process():
    """dist_async exists now (bounded-staleness SSP, docs/robustness.md);
    single-process it degenerates to a local store with the async API."""
    kv = kvs.create("dist_async")
    assert kv.type == "dist_async"
    assert kv.rank == 0 and kv.num_workers == 1
    kv.init(0, nd.zeros((2,)))
    kv.push(0, nd.ones((2,)) * 3)
    out = nd.zeros((2,))
    kv.pull(0, out=out)
    assert (out.asnumpy() == 3).all()
    assert kv.staleness >= 0  # the window knob (MXTPU_KV_STALENESS)


def test_fault_policy_env_defaults(monkeypatch):
    """Timeout/retry/backoff knobs are env-seeded (docs/robustness.md) and
    overridable per-store via set_fault_policy."""
    monkeypatch.setenv("MXTPU_KV_TIMEOUT", "1.5")
    monkeypatch.setenv("MXTPU_KV_RETRIES", "5")
    monkeypatch.setenv("MXTPU_KV_BACKOFF", "0.01")
    kv = kvs.create("local")
    assert kv._timeout == 1.5
    assert kv._retries == 5
    assert kv._backoff == 0.01
    kv.set_fault_policy(timeout=None, retries=1)
    assert kv._timeout is None and kv._retries == 1


def test_fault_policy_env_malformed(monkeypatch):
    import pytest
    monkeypatch.setenv("MXTPU_KV_TIMEOUT", "soon")
    with pytest.raises(mx.base.MXNetError, match="MXTPU_KV_TIMEOUT"):
        kvs.create("local")


def test_check_health_throttled_by_interval():
    kv = kvs.create("local")
    kv.set_fault_policy(health_interval=3600.0)
    assert kv.check_health(force=True) == 0
    # a throttled scan does not even consult num_dead_node
    kv.num_dead_node = lambda *a, **k: (_ for _ in ()).throw(
        AssertionError("scan not throttled"))
    assert kv.check_health() == 0
