"""CustomOp + C API tests (ref strategy: test_operator.py custom-op section;
binding contract from include/mxnet/c_api.h)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import sym, nd
import mxnet_tpu.operator as mxop
from mxnet_tpu import c_api


@mxop.register("sqr")
class SqrProp(mxop.CustomOpProp):
    def __init__(self):
        super().__init__(need_top_grad=True)

    def list_arguments(self):
        return ["data"]

    def list_outputs(self):
        return ["output"]

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]], []

    def create_operator(self, ctx, shapes, dtypes):
        return Sqr()


class Sqr(mxop.CustomOp):
    def forward(self, is_train, req, in_data, out_data, aux):
        x = in_data[0].asnumpy()
        self.assign(out_data[0], req[0], x * x)

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        g = out_grad[0].asnumpy()
        x = in_data[0].asnumpy()
        self.assign(in_grad[0], req[0], 2 * x * g)


def test_custom_op_imperative():
    x = nd.array(np.array([1.0, 2.0, 3.0]))
    y = mx.nd.Custom(x, op_type="sqr")
    assert np.allclose(y.asnumpy(), [1, 4, 9])


def test_custom_op_symbolic_forward_backward():
    data = sym.Variable("data")
    s = sym.Custom(data=data, op_type="sqr", name="sqr0")
    assert s.list_arguments() == ["data"]
    x = np.array([1.0, 2.0, 3.0], np.float32)
    ag = nd.zeros((3,))
    ex = s.bind(mx.cpu(), {"data": nd.array(x)}, args_grad={"data": ag})
    ex.forward(is_train=True)
    assert np.allclose(ex.outputs[0].asnumpy(), x * x)
    ex.backward(out_grads=nd.ones((3,)))
    assert np.allclose(ag.asnumpy(), 2 * x)


def test_custom_op_in_graph():
    # custom op composed with builtin ops, differentiated end to end
    data = sym.Variable("data")
    s = sym.sum(data=sym.Custom(data=data * 2, op_type="sqr"))
    x = np.array([1.0, 2.0], np.float32)
    ag = nd.zeros((2,))
    ex = s.bind(mx.cpu(), {"data": nd.array(x)}, args_grad={"data": ag})
    ex.forward(is_train=True)
    assert np.allclose(ex.outputs[0].asnumpy(), np.sum((2 * x) ** 2))
    ex.backward(out_grads=nd.ones(()))
    assert np.allclose(ag.asnumpy(), 8 * x)  # d/dx (2x)^2 = 8x


def test_custom_op_infer_shape():
    data = sym.Variable("data")
    s = sym.Custom(data=data, op_type="sqr")
    _, out_shapes, _ = s.infer_shape(data=(4, 5))
    assert out_shapes == [(4, 5)]


def test_legacy_python_op():
    class Plus3(mxop.PythonOp):
        def forward(self, in_data, out_data):
            out_data[0][:] = in_data[0] + 3

        def backward(self, out_grad, in_data, out_data, in_grad):
            in_grad[0][:] = out_grad[0]

    op = Plus3()
    s = op.get_symbol(sym.Variable("data"))
    ex = s.bind(mx.cpu(), {"data": nd.ones((2,))})
    ex.forward()
    assert np.allclose(ex.outputs[0].asnumpy(), 4.0)


# -- C API -----------------------------------------------------------------

def test_capi_ndarray_roundtrip():
    code, h = c_api.MXNDArrayCreate((2, 3), 1, 0)
    assert code == 0
    code, _ = c_api.MXNDArraySyncCopyFromCPU(h, np.ones((2, 3), np.float32))
    assert code == 0
    code, arr = c_api.MXNDArraySyncCopyToCPU(h)
    assert code == 0 and (arr == 1).all()
    code, shape = c_api.MXNDArrayGetShape(h)
    assert shape == (2, 3)
    c_api.MXNDArrayFree(h)


def test_capi_error_contract():
    code, _ = c_api.MXNDArrayGetShape(99999999)  # bad handle
    assert code == -1
    assert "KeyError" in c_api.MXGetLastError()


def test_capi_imperative_invoke():
    code, h = c_api.MXNDArrayCreateFromNumpy(np.array([1.0, 2.0], np.float32))
    code, outs = c_api.MXImperativeInvoke("sqrt", [h], {})
    assert code == 0
    code, arr = c_api.MXNDArraySyncCopyToCPU(outs[0])
    assert np.allclose(arr, np.sqrt([1.0, 2.0]))


def test_capi_symbol_and_executor():
    code, v = c_api.MXSymbolCreateVariable("data")
    code, s = c_api.MXSymbolCreateAtomicSymbol(
        "FullyConnected", ["num_hidden"], [4])
    code, s = c_api.MXSymbolCompose(s, "fc", [v], ["data"])
    assert code == 0
    code, args = c_api.MXSymbolListArguments(s)
    assert args == ["data", "fc_weight", "fc_bias"]
    code, (arg_shapes, out_shapes, _) = c_api.MXSymbolInferShape(
        s, ["data"], [(2, 3)])
    assert out_shapes == [(2, 4)]
    handles = []
    for sh in arg_shapes:
        _, h = c_api.MXNDArrayCreate(sh, 1, 0)
        c_api.MXNDArraySyncCopyFromCPU(
            h, np.ones(sh, np.float32) * 0.1)
        handles.append(h)
    code, ex = c_api.MXExecutorBind(s, 1, 0, handles)
    assert code == 0
    code, _ = c_api.MXExecutorForward(ex, 0)
    assert code == 0
    code, outs = c_api.MXExecutorOutputs(ex)
    code, arr = c_api.MXNDArraySyncCopyToCPU(outs[0])
    assert arr.shape == (2, 4)


def test_capi_kvstore():
    code, kv = c_api.MXKVStoreCreate("local")
    _, h = c_api.MXNDArrayCreateFromNumpy(np.zeros(3, np.float32))
    c_api.MXKVStoreInit(kv, [0], [h])
    _, g = c_api.MXNDArrayCreateFromNumpy(np.ones(3, np.float32))
    c_api.MXKVStorePush(kv, [0], [g])
    _, out = c_api.MXNDArrayCreateFromNumpy(np.zeros(3, np.float32))
    c_api.MXKVStorePull(kv, [0], [out])
    _, arr = c_api.MXNDArraySyncCopyToCPU(out)
    assert (arr == 1).all()
    code, rank = c_api.MXKVStoreGetRank(kv)
    assert rank == 0


def test_capi_version_and_ops():
    code, v = c_api.MXGetVersion()
    assert code == 0 and v >= 100
    code, ops = c_api.MXListAllOpNames()
    assert "Convolution" in ops
