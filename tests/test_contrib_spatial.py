"""Spatial + contrib op tests (ref strategy: test_operator.py spatial
sections; SSD op behavior from contrib/multibox_*)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import sym, nd


def test_grid_generator_identity():
    # identity affine [1,0,0, 0,1,0] -> identity grid
    theta = nd.array(np.array([[1.0, 0, 0, 0, 1.0, 0]], np.float32))
    grid = mx.nd.GridGenerator(theta, transform_type="affine",
                               target_shape=(4, 4))
    g = grid.asnumpy()
    assert g.shape == (1, 2, 4, 4)
    assert np.allclose(g[0, 0, 0], np.linspace(-1, 1, 4), atol=1e-5)
    assert np.allclose(g[0, 1, :, 0], np.linspace(-1, 1, 4), atol=1e-5)


def test_bilinear_sampler_identity():
    x = np.random.rand(1, 2, 5, 5).astype(np.float32)
    theta = nd.array(np.array([[1.0, 0, 0, 0, 1.0, 0]], np.float32))
    grid = mx.nd.GridGenerator(theta, transform_type="affine",
                               target_shape=(5, 5))
    out = mx.nd.BilinearSampler(nd.array(x), grid)
    assert np.allclose(out.asnumpy(), x, atol=1e-4)


def test_spatial_transformer_identity():
    x = np.random.rand(2, 3, 6, 6).astype(np.float32)
    loc = np.tile(np.array([1.0, 0, 0, 0, 1.0, 0], np.float32), (2, 1))
    out = mx.nd.SpatialTransformer(nd.array(x), nd.array(loc),
                                   target_shape=(6, 6),
                                   transform_type="affine",
                                   sampler_type="bilinear")
    assert np.allclose(out.asnumpy(), x, atol=1e-4)


def test_roi_pooling():
    # feature map with known max positions
    x = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
    rois = np.array([[0, 0, 0, 3, 3]], np.float32)  # whole image
    out = mx.nd.ROIPooling(nd.array(x), nd.array(rois),
                           pooled_size=(2, 2), spatial_scale=1.0)
    o = out.asnumpy()
    assert o.shape == (1, 1, 2, 2)
    assert o[0, 0, 1, 1] == 15.0  # bottom-right bin max
    assert o[0, 0, 0, 0] == 5.0   # top-left 2x2 bin max


def test_multibox_prior():
    data = nd.zeros((1, 8, 2, 2))
    anchors = mx.nd.MultiBoxPrior(data, sizes=(0.5,), ratios=(1.0,))
    a = anchors.asnumpy()
    assert a.shape == (1, 4, 4)
    # first anchor centered at (0.25, 0.25), size 0.5 -> [0, 0, 0.5, 0.5]
    assert np.allclose(a[0, 0], [0, 0, 0.5, 0.5], atol=1e-5)


def test_multibox_target_matching():
    anchors = nd.array(np.array([[[0.0, 0.0, 0.5, 0.5],
                                  [0.5, 0.5, 1.0, 1.0]]], np.float32))
    # one gt box overlapping anchor 0 heavily
    labels = nd.array(np.array([[[0.0, 0.05, 0.05, 0.45, 0.45]]], np.float32))
    cls_preds = nd.zeros((1, 2, 2))
    loc_t, loc_m, cls_t = mx.nd.MultiBoxTarget(anchors, labels, cls_preds)
    assert cls_t.asnumpy()[0, 0] == 1.0   # matched -> class 0 + 1
    assert cls_t.asnumpy()[0, 1] == 0.0   # background
    assert loc_m.asnumpy()[0, :4].sum() == 4.0
    assert loc_m.asnumpy()[0, 4:].sum() == 0.0


def test_multibox_detection_nms():
    # two overlapping anchors, same class; NMS keeps higher score
    anchors = nd.array(np.array([[[0.1, 0.1, 0.5, 0.5],
                                  [0.12, 0.12, 0.52, 0.52],
                                  [0.6, 0.6, 0.9, 0.9]]], np.float32))
    cls_prob = nd.array(np.array([[[0.1, 0.2, 0.1],       # background
                                   [0.9, 0.8, 0.9]]], np.float32))
    loc_pred = nd.zeros((1, 12))
    det = mx.nd.MultiBoxDetection(cls_prob, loc_pred, anchors,
                                  nms_threshold=0.5)
    d = det.asnumpy()[0]
    kept = d[d[:, 0] >= 0]
    # anchor 1 suppressed by anchor 0 (higher score, same class, iou>0.5)
    assert len(kept) == 2


def test_ctc_loss_perfect_prediction():
    # if the net predicts the labels with certainty, loss ~ 0
    T, N, V, L = 4, 1, 3, 2
    acts = np.full((T, N, V), -10.0, np.float32)
    # labels [1, 2]: emit 1, 1, 2, 2 (collapses to [1,2])
    acts[0, 0, 1] = 10.0
    acts[1, 0, 1] = 10.0
    acts[2, 0, 2] = 10.0
    acts[3, 0, 2] = 10.0
    label = np.array([[1, 2]], np.float32)
    loss = mx.nd.CTCLoss(nd.array(acts), nd.array(label))
    assert loss.asnumpy()[0] < 0.1


def test_ctc_loss_gradient_flows():
    T, N, V = 5, 2, 4
    data = sym.Variable("data")
    label = sym.Variable("label")
    loss = sym.MakeLoss(data=sym.CTCLoss(data=data, label=label, name="ctc"))
    x = np.random.uniform(-1, 1, (T, N, V)).astype(np.float32)
    lab = np.array([[1, 2], [3, 0]], np.float32)
    ag = nd.zeros((T, N, V))
    ex = loss.bind(mx.cpu(), {"data": nd.array(x), "label": nd.array(lab)},
                   args_grad={"data": ag},
                   grad_req={"data": "write", "label": "null"})
    ex.forward(is_train=True)
    ex.backward()
    assert np.abs(ag.asnumpy()).sum() > 0


def test_fft_roundtrip():
    x = np.random.rand(2, 8).astype(np.float32)
    f = mx.nd.fft(nd.array(x))
    assert f.shape == (2, 16)
    back = mx.nd.ifft(f) / 8  # reference ifft is unnormalized
    assert np.allclose(back.asnumpy(), x, atol=1e-4)


def test_quantize_roundtrip():
    x = np.random.uniform(-1, 1, (3, 4)).astype(np.float32)
    lo = nd.array(np.array(-1.0, np.float32).reshape(1))
    hi = nd.array(np.array(1.0, np.float32).reshape(1))
    q, qlo, qhi = mx.nd.quantize(nd.array(x), lo, hi)
    deq = mx.nd.dequantize(q, qlo, qhi)
    assert np.allclose(deq.asnumpy(), x, atol=0.01)


def test_count_sketch():
    x = np.ones((1, 4), np.float32)
    h = nd.array(np.array([0, 1, 0, 1], np.float32))
    s = nd.array(np.array([1, 1, -1, 1], np.float32))
    out = mx.nd.count_sketch(nd.array(x), h, s, out_dim=2)
    assert np.allclose(out.asnumpy(), [[0.0, 2.0]])


def test_correlation_self():
    x = np.random.rand(1, 4, 6, 6).astype(np.float32)
    out = mx.nd.Correlation(nd.array(x), nd.array(x), kernel_size=1,
                            max_displacement=1, stride1=1, stride2=1,
                            pad_size=1)
    o = out.asnumpy()
    assert o.shape[1] == 9  # 3x3 displacement window
    # zero displacement channel (center, index 4) == mean of squares
    center = o[0, 4]
    expect = (x * x).mean(axis=1)[0]
    # cropped to the valid region
    assert np.allclose(center, expect[:center.shape[0], :center.shape[1]],
                       atol=1e-4)
