"""Fault-tolerant checkpointing and auto-resume (docs/robustness.md).

Pins the recovery contract: atomic checksummed checkpoints with retention
and a latest pointer, corrupt-checkpoint fallback, and ``fit(resume='auto')``
reaching bitwise-identical params to an uninterrupted run — in-process for
tier-1, and through a real SIGKILL of a subprocess in the slow-marked
integration test. Satellite coverage: load_checkpoint key validation,
optimizer-states error wrapping + round-trip, FeedForward save/load.
"""
import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import faults, nd, sym
from mxnet_tpu.base import MXNetError
from mxnet_tpu.model import (CheckpointManager, load_checkpoint,
                             save_checkpoint, atomic_write_bytes)


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


def _mlp(num_hidden=16, num_classes=4):
    data = sym.Variable("data")
    net = sym.FullyConnected(data=data, num_hidden=num_hidden, name="fc1")
    net = sym.Activation(data=net, act_type="relu")
    net = sym.FullyConnected(data=net, num_hidden=num_classes, name="fc2")
    return sym.SoftmaxOutput(data=net, name="softmax")


def _toy_data(n=128, dim=10, classes=4, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, dim)).astype(np.float32)
    w = rng.normal(size=(dim, classes)).astype(np.float32)
    y = np.argmax(X @ w, axis=1).astype(np.float32)
    return X, y


def _opt_params():
    from mxnet_tpu import lr_scheduler
    return {"learning_rate": 0.1, "momentum": 0.9,
            "lr_scheduler": lr_scheduler.FactorScheduler(step=5,
                                                         factor=0.5)}


class _Interrupt(Exception):
    pass


def _run_fit(X, y, k, num_epoch=2, interrupt_after=None, prefix=None,
             resume=None, every=4):
    """One deterministic training run; returns final arg params as numpy.
    ``interrupt_after`` simulates a kill after that many TOTAL batches."""
    mx.random.seed(3)
    train = mx.io.NDArrayIter(X, y, batch_size=16)
    n_per_epoch = X.shape[0] // 16
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    cb = None
    if interrupt_after is not None:
        def cb(p):
            if p.epoch * n_per_epoch + p.nbatch + 1 >= interrupt_after:
                raise _Interrupt()
    try:
        mod.fit(train, num_epoch=num_epoch, optimizer_params=_opt_params(),
                batch_end_callback=cb, steps_per_dispatch=k,
                checkpoint_prefix=prefix,
                checkpoint_every_n_batches=every if prefix else None,
                resume=resume)
    except _Interrupt:
        pass
    arg, _ = mod.get_params()
    return {n: v.asnumpy() for n, v in arg.items()}


# -- the core acceptance: kill mid-epoch, resume, bitwise-identical ---------

@pytest.mark.parametrize("k", [1, 2])
def test_interrupted_resume_bitwise_identical(tmp_path, k):
    X, y = _toy_data()
    ref = _run_fit(X, y, k)
    prefix = str(tmp_path / "ck")
    _run_fit(X, y, k, interrupt_after=11, prefix=prefix)   # dies mid-epoch 2
    got = _run_fit(X, y, k, prefix=prefix, resume="auto")
    assert sorted(ref) == sorted(got)
    for name in ref:
        np.testing.assert_array_equal(ref[name], got[name], err_msg=name)


def test_resume_after_k_change_trains_tail_per_step(tmp_path):
    # checkpoint cut mid-superbatch: saved under k=1 at a non-multiple of
    # the new k — resume with k=2 must still finish and converge sanely
    X, y = _toy_data()
    prefix = str(tmp_path / "ck")
    _run_fit(X, y, 1, interrupt_after=10, prefix=prefix, every=3)
    got = _run_fit(X, y, 2, prefix=prefix, resume="auto", every=3)
    assert all(np.isfinite(v).all() for v in got.values())


def test_resume_auto_without_checkpoint_starts_fresh(tmp_path):
    X, y = _toy_data()
    prefix = str(tmp_path / "never-written")
    ref = _run_fit(X, y, 1)
    got = _run_fit(X, y, 1, prefix=prefix, resume="auto")
    for name in ref:
        np.testing.assert_array_equal(ref[name], got[name])


def test_resume_requires_prefix():
    X, y = _toy_data(32)
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    train = mx.io.NDArrayIter(X, y, batch_size=16)
    with pytest.raises(MXNetError, match="checkpoint_prefix"):
        mod.fit(train, num_epoch=1, resume="auto")


# -- checkpoint manager mechanics -------------------------------------------

def _trained_module(X, y, prefix=None, every=None):
    mx.random.seed(0)
    train = mx.io.NDArrayIter(X, y, batch_size=16)
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    mod.fit(train, num_epoch=1, optimizer_params={"learning_rate": 0.1,
                                                  "momentum": 0.9},
            checkpoint_prefix=prefix, checkpoint_every_n_batches=every)
    return mod


def test_manifest_records_cursor_clock_and_checksums(tmp_path):
    X, y = _toy_data()
    prefix = str(tmp_path / "ck")
    _trained_module(X, y, prefix=prefix, every=3)
    mgr = CheckpointManager(prefix)
    st = mgr.load_latest()
    assert st.epoch == 1 and st.batches_done == 0   # epoch-end checkpoint
    assert st.num_update == 8                       # 8 batches trained
    man = json.loads(open(mgr._file(st.tag, "manifest.json")).read())
    assert set(man["files"]) == {"params", "states"}
    for info in man["files"].values():
        assert len(info["sha256"]) == 64 and info["size"] > 0
    assert st.rng is not None
    # latest pointer agrees
    assert open(mgr.latest_path).read().strip() == st.tag


def test_retention_prunes_oldest(tmp_path):
    X, y = _toy_data()
    prefix = str(tmp_path / "ck")
    mx.random.seed(0)
    train = mx.io.NDArrayIter(X, y, batch_size=16)
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    mod.fit(train, num_epoch=1, optimizer_params={"learning_rate": 0.1},
            checkpoint_prefix=prefix, checkpoint_every_n_batches=2,
            checkpoint_keep=2)
    mgr = CheckpointManager(prefix, keep=2)
    tags = mgr.list_tags()
    assert len(tags) == 2                 # 5 saves, 2 kept
    # pruned checkpoints' files are gone from disk
    data_files = [f for f in os.listdir(tmp_path)
                  if f.endswith((".params", ".states"))]
    assert len(data_files) == 4


def test_corrupt_newest_falls_back_to_previous(tmp_path, caplog):
    X, y = _toy_data()
    prefix = str(tmp_path / "ck")
    _trained_module(X, y, prefix=prefix, every=3)
    mgr = CheckpointManager(prefix)
    tags = mgr.list_tags()
    newest = tags[-1]
    # truncate the newest params file behind the manifest's back
    params_f = mgr._file(newest, "params")
    with open(params_f, "r+b") as f:
        f.truncate(os.path.getsize(params_f) // 2)
    import logging
    with caplog.at_level(logging.WARNING):
        st = mgr.load_latest()
    assert st is not None and st.tag == tags[-2]
    assert any("failed validation" in r.message for r in caplog.records)


def test_injected_torn_write_detected_and_skipped(tmp_path):
    X, y = _toy_data()
    prefix = str(tmp_path / "ck")
    mx.random.seed(0)
    train = mx.io.NDArrayIter(X, y, batch_size=16)
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    # write order per mid-epoch save: params, states, manifest, latest;
    # first save also writes symbol.json 3rd => call 6 is the SECOND
    # checkpoint's params write
    faults.inject("checkpoint.write", nth=6, kind="truncate")
    mod.fit(train, num_epoch=1, optimizer_params={"learning_rate": 0.1},
            checkpoint_prefix=prefix, checkpoint_every_n_batches=2,
            checkpoint_keep=10)
    faults.clear()
    mgr = CheckpointManager(prefix)
    tags = mgr.list_tags()
    torn = tags[1]
    with pytest.raises(MXNetError, match="truncated|checksum"):
        mgr.load(torn)
    st = mgr.load_latest()                 # falls back over the torn one
    assert st is not None and st.tag != torn


def test_checkpoint_write_abort_preserves_previous(tmp_path):
    X, y = _toy_data()
    prefix = str(tmp_path / "ck")
    _trained_module(X, y, prefix=prefix, every=4)
    mgr = CheckpointManager(prefix)
    before = mgr.load_latest()
    mod2 = _trained_module(X, y)
    faults.inject("checkpoint.write.mid", nth=1, kind="raise")
    with pytest.raises(faults.InjectedFault):
        mgr.save(mod2, 9, 0)
    faults.clear()
    st = mgr.load_latest()
    assert st.tag == before.tag            # old generation intact


@pytest.mark.faults
def test_disk_full_mid_write_actionable_and_no_litter(tmp_path):
    """ckpt.disk_full: ENOSPC halfway through an atomic write must (a)
    surface as an actionable MXNetError naming the path and the remedy,
    (b) remove the partial temp file, and (c) leave the live file's
    previous contents untouched."""
    path = str(tmp_path / "x.params")
    atomic_write_bytes(path, b"generation-1")
    faults.inject("ckpt.disk_full", nth=1, kind="enospc")
    with pytest.raises(mx.MXNetError) as ei:
        atomic_write_bytes(path, b"generation-2-never-lands")
    faults.clear()
    msg = str(ei.value)
    assert "no space left on device" in msg and "ENOSPC" in msg
    assert path in msg
    assert "free disk space" in msg          # the remedy, not just the errno
    assert open(path, "rb").read() == b"generation-1"
    assert [f for f in os.listdir(str(tmp_path)) if ".tmp" in f] == [], \
        "partial temp file littered after ENOSPC"
    # disarmed, the same write path works again
    atomic_write_bytes(path, b"generation-2")
    assert open(path, "rb").read() == b"generation-2"


@pytest.mark.faults
def test_disk_full_during_manager_save_keeps_previous_generation(tmp_path):
    X, y = _toy_data()
    prefix = str(tmp_path / "ck")
    _trained_module(X, y, prefix=prefix, every=4)
    mgr = CheckpointManager(prefix)
    before = mgr.load_latest()
    mod2 = _trained_module(X, y)
    faults.inject("ckpt.disk_full", nth=1, kind="enospc")
    with pytest.raises(mx.MXNetError, match="no space left on device"):
        mgr.save(mod2, 9, 0)
    faults.clear()
    st = mgr.load_latest()
    assert st is not None and st.tag == before.tag
    ckdir = os.path.dirname(prefix)
    assert [f for f in os.listdir(ckdir) if ".tmp" in f] == []


# -- legacy checkpoint API satellites ---------------------------------------

def test_load_checkpoint_rejects_malformed_keys(tmp_path):
    prefix = str(tmp_path / "model")
    save_checkpoint(prefix, 1, _mlp(), {"fc1_weight": nd.ones((2, 2))}, {})
    # overwrite with a params file containing a bad key
    bad = {"nonsense-key": nd.ones((1,))}
    nd.save("%s-0001.params" % prefix, bad)
    with pytest.raises(MXNetError) as ei:
        load_checkpoint(prefix, 1)
    assert "nonsense-key" in str(ei.value)
    assert "%s-0001.params" % prefix in str(ei.value)


def test_load_checkpoint_rejects_unknown_prefix(tmp_path):
    prefix = str(tmp_path / "model")
    save_checkpoint(prefix, 1, _mlp(), {"fc1_weight": nd.ones((2, 2))}, {})
    nd.save("%s-0001.params" % prefix, {"grad:fc1_weight": nd.ones((2, 2))})
    with pytest.raises(MXNetError, match="unknown prefix 'grad'"):
        load_checkpoint(prefix, 1)


def test_save_checkpoint_roundtrip_atomic(tmp_path):
    prefix = str(tmp_path / "model")
    arg = {"fc1_weight": nd.array(np.arange(6, dtype=np.float32)
                                  .reshape(2, 3))}
    aux = {"bn_moving_mean": nd.array(np.ones(3, np.float32))}
    save_checkpoint(prefix, 7, _mlp(), arg, aux)
    s, a, x = load_checkpoint(prefix, 7)
    np.testing.assert_array_equal(a["fc1_weight"].asnumpy(),
                                  arg["fc1_weight"].asnumpy())
    np.testing.assert_array_equal(x["bn_moving_mean"].asnumpy(),
                                  np.ones(3))
    assert not [f for f in os.listdir(tmp_path) if ".tmp" in f]


# -- optimizer states satellites --------------------------------------------

def test_kvstore_optimizer_states_roundtrip_momentum(tmp_path):
    kv = mx.kvstore.create("local")
    from mxnet_tpu import optimizer as opt
    kv.set_optimizer(opt.create("sgd", learning_rate=0.1, momentum=0.9))
    w = nd.array(np.ones((4,), np.float32))
    kv.init(0, w)
    kv.push(0, nd.array(np.full((4,), 0.5, np.float32)))
    kv.pull(0, w)
    mom_before = kv._updater.states[0].asnumpy()
    assert np.any(mom_before != 0)
    fname = str(tmp_path / "opt.states")
    kv.save_optimizer_states(fname)

    kv2 = mx.kvstore.create("local")
    kv2.set_optimizer(opt.create("sgd", learning_rate=0.1, momentum=0.9))
    kv2.load_optimizer_states(fname)
    np.testing.assert_array_equal(kv2._updater.states[0].asnumpy(),
                                  mom_before)


def test_kvstore_load_states_missing_file_actionable():
    kv = mx.kvstore.create("local")
    from mxnet_tpu import optimizer as opt
    kv.set_optimizer(opt.create("sgd", learning_rate=0.1))
    with pytest.raises(MXNetError, match="save_optimizer_states"):
        kv.load_optimizer_states("/nonexistent/opt.states")


def test_kvstore_load_states_truncated_actionable(tmp_path):
    kv = mx.kvstore.create("local")
    from mxnet_tpu import optimizer as opt
    kv.set_optimizer(opt.create("sgd", learning_rate=0.1, momentum=0.9))
    w = nd.array(np.ones((4,), np.float32))
    kv.init(0, w)
    kv.push(0, nd.array(np.ones((4,), np.float32)))
    fname = str(tmp_path / "opt.states")
    kv.save_optimizer_states(fname)
    with open(fname, "r+b") as f:
        f.truncate(max(1, os.path.getsize(fname) // 3))
    with pytest.raises(MXNetError, match="corrupt or truncated"):
        kv.load_optimizer_states(fname)


def test_module_load_states_errors_actionable(tmp_path):
    X, y = _toy_data(32)
    mod = _trained_module(X, y)
    with pytest.raises(MXNetError, match="save_optimizer_states"):
        mod.load_optimizer_states(str(tmp_path / "missing.states"))
    fname = str(tmp_path / "t.states")
    mod.save_optimizer_states(fname)
    with open(fname, "r+b") as f:
        f.truncate(5)
    with pytest.raises(MXNetError, match="corrupt or truncated"):
        mod.load_optimizer_states(fname)


# -- FeedForward satellites --------------------------------------------------

def _bn_mlp():
    data = sym.Variable("data")
    net = sym.FullyConnected(data=data, num_hidden=8, name="fc1")
    net = sym.BatchNorm(data=net, name="bn1")
    net = sym.Activation(data=net, act_type="relu")
    net = sym.FullyConnected(data=net, num_hidden=4, name="fc2")
    return sym.SoftmaxOutput(data=net, name="softmax")


def test_feedforward_save_load_epoch_none(tmp_path):
    X, y = _toy_data(64)
    model = mx.model.FeedForward(_mlp(), ctx=mx.cpu(), num_epoch=2,
                                 numpy_batch_size=16, learning_rate=0.1)
    model.fit(X, y)
    prefix = str(tmp_path / "ff")
    model.save(prefix)                     # epoch=None -> num_epoch
    assert os.path.exists("%s-0002.params" % prefix)
    loaded = mx.model.FeedForward.load(prefix, 2, ctx=mx.cpu())
    assert loaded.begin_epoch == 2
    for n, v in model.arg_params.items():
        np.testing.assert_array_equal(v.asnumpy(),
                                      loaded.arg_params[n].asnumpy(),
                                      err_msg=n)


def test_feedforward_save_load_with_aux_params(tmp_path):
    X, y = _toy_data(64)
    model = mx.model.FeedForward(_bn_mlp(), ctx=mx.cpu(), num_epoch=1,
                                 numpy_batch_size=16, learning_rate=0.05)
    model.fit(X, y)
    assert model.aux_params, "BatchNorm should produce aux params"
    prefix = str(tmp_path / "ffbn")
    model.save(prefix, epoch=5)
    loaded = mx.model.FeedForward.load(prefix, 5, ctx=mx.cpu())
    assert sorted(loaded.aux_params) == sorted(model.aux_params)
    for n, v in model.aux_params.items():
        np.testing.assert_array_equal(v.asnumpy(),
                                      loaded.aux_params[n].asnumpy(),
                                      err_msg=n)
    # and the loaded model predicts without re-fitting
    pred = loaded.predict(X[:16])
    assert pred.shape == (16, 4)


def test_feedforward_save_epoch_none_without_num_epoch_asserts(tmp_path):
    model = mx.model.FeedForward(_mlp(), ctx=mx.cpu())
    with pytest.raises(AssertionError):
        model.save(str(tmp_path / "ff"))


# -- the real thing: SIGKILL a training process and resume it ---------------

@pytest.mark.slow
@pytest.mark.parametrize("k", [1, 2])
def test_sigkill_and_resume_bitwise_identical(tmp_path, k):
    worker = os.path.join(os.path.dirname(__file__), "resume_worker.py")
    env = dict(os.environ, JAX_PLATFORMS="cpu")

    def launch(prefix, out):
        return subprocess.Popen(
            [sys.executable, worker, prefix, out, str(k)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, env=env)

    # reference: uninterrupted run
    ref_out = str(tmp_path / "ref.npz")
    p = launch(str(tmp_path / "ref-ck"), ref_out)
    assert p.wait(timeout=600) == 0, p.stdout.read()

    # victim: SIGKILL once it is past mid-epoch-1 (batch cursor 1.x)
    prefix = str(tmp_path / "ck")
    out = str(tmp_path / "resumed.npz")
    p = launch(prefix, out)
    killed = False
    deadline = time.monotonic() + 600
    for line in p.stdout:
        if line.startswith("BATCH 1.") and time.monotonic() < deadline:
            os.kill(p.pid, signal.SIGKILL)
            killed = True
            break
    p.wait(timeout=60)
    assert killed, "worker finished before it could be killed"
    assert not os.path.exists(out)

    # a resumed run must REFUSE a checkpoint whose manifest lacks the
    # known-good bit: strip it from the newest checkpoint and assert the
    # resume entry point falls back to the previous (still known-good) one
    mgr = CheckpointManager(prefix)
    st0 = mgr.load_latest()        # newest VALID checkpoint (kill may have
    assert st0 is not None         # torn the very last write)
    assert st0.known_good is True
    man_f = mgr._file(st0.tag, "manifest.json")
    man = json.loads(open(man_f).read())
    del man["known_good"]
    atomic_write_bytes(man_f, json.dumps(man, indent=1).encode())
    st = mgr.load_latest()
    assert st is not None and st.tag != st0.tag, \
        "resume must skip the manifest without the known-good bit"

    # resume: same command line, resume='auto' picks up the newest
    # known-good checkpoint (one interval earlier) and still replays to
    # bitwise-identical final params
    p = launch(prefix, out)
    assert p.wait(timeout=600) == 0, p.stdout.read()

    ref = np.load(ref_out)
    got = np.load(out)
    assert sorted(ref.files) == sorted(got.files)
    for name in ref.files:
        np.testing.assert_array_equal(ref[name], got[name], err_msg=name)


@pytest.mark.slow
def test_sigkill_mid_async_save_resumes_from_previous(tmp_path):
    """SIGKILL while an ASYNC checkpoint save is mid-write (the writer
    thread is stalled inside the job via the ckpt.async_write delay site):
    the torn save must never become `latest`, resume must land on the
    previous valid checkpoint, and the re-run must still produce
    bitwise-identical final params (docs/robustness.md "Asynchronous
    checkpointing")."""
    import signal
    import subprocess
    import sys
    import time
    worker = os.path.join(os.path.dirname(__file__), "resume_worker.py")
    k = 2

    def launch(prefix, out, extra_env=None):
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env.update(extra_env or {})
        return subprocess.Popen(
            [sys.executable, worker, prefix, out, str(k)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, env=env)

    # reference: uninterrupted SYNC run (async must be byte/bit-equivalent)
    ref_out = str(tmp_path / "ref.npz")
    p = launch(str(tmp_path / "ref-ck"), ref_out)
    assert p.wait(timeout=600) == 0, p.stdout.read()

    # victim: async checkpointing on, with the SECOND async save's writer
    # stalled 300s inside the job — the training loop races ahead (that is
    # the point of async saves), we kill it mid-save
    prefix = str(tmp_path / "ck")
    out = str(tmp_path / "resumed.npz")
    p = launch(prefix, out, {"MXTPU_ASYNC_CKPT": "1",
                             "RESUME_WORKER_ASYNC_DELAY": "300",
                             "RESUME_WORKER_ASYNC_DELAY_NTH": "2",
                             # save #1 (b4) is drained to disk before b8
                             # submits, so the 300s stall is exactly the
                             # SECOND save's job — deterministically
                             "RESUME_WORKER_DRAIN_UNTIL": "6"})
    killed = False
    deadline = time.monotonic() + 600
    for line in p.stdout:
        # cadence 4, 16 batches/epoch: save #2 (b8) submits after batch
        # 0.7; kill while its writer sleeps and the loop keeps training
        if line.startswith("BATCH 0.13") and time.monotonic() < deadline:
            os.kill(p.pid, signal.SIGKILL)
            killed = True
            break
    p.wait(timeout=60)
    assert killed, "worker finished before it could be killed"
    assert not os.path.exists(out)

    # the stalled save must have left NO trace under the live names:
    # resume lands on save #1 (e0000-b00000004), not the torn #2
    mgr = CheckpointManager(prefix)
    st = mgr.load_latest()
    assert st is not None and st.known_good is True
    assert st.tag == "e0000-b00000004", st.tag
    assert open(mgr.latest_path).read().strip() == "e0000-b00000004"

    # re-run (async on, no fault): resumes from the previous valid
    # checkpoint and finishes bitwise-identical to the sync reference
    p = launch(prefix, out, {"MXTPU_ASYNC_CKPT": "1"})
    assert p.wait(timeout=600) == 0, p.stdout.read()
    ref = np.load(ref_out)
    got = np.load(out)
    assert sorted(ref.files) == sorted(got.files)
    for name in ref.files:
        np.testing.assert_array_equal(ref[name], got[name], err_msg=name)


def test_load_latest_prefers_newer_tag_over_stale_pointer(tmp_path):
    # crash between the manifest write and the latest-pointer write: the
    # newest on-disk checkpoint must win over the stale pointer
    X, y = _toy_data()
    prefix = str(tmp_path / "ck")
    _trained_module(X, y, prefix=prefix, every=3)
    mgr = CheckpointManager(prefix)
    tags = mgr.list_tags()
    atomic_write_bytes(mgr.latest_path, tags[0].encode())  # stale pointer
    st = mgr.load_latest()
    assert st.tag == tags[-1]


def test_torn_states_write_fails_validation_and_falls_back(tmp_path):
    # torn .states publish: the manifest checksums the INTENDED payload, so
    # load_latest must reject the checkpoint and fall back, not seal it
    X, y = _toy_data()
    prefix = str(tmp_path / "ck")
    mx.random.seed(0)
    train = mx.io.NDArrayIter(X, y, batch_size=16)
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    # per-save write order: params, states, ... => call 7 is the SECOND
    # checkpoint's states write (first save also writes symbol.json 3rd)
    faults.inject("checkpoint.write", nth=7, kind="truncate")
    mod.fit(train, num_epoch=1, optimizer_params={"learning_rate": 0.1,
                                                  "momentum": 0.9},
            checkpoint_prefix=prefix, checkpoint_every_n_batches=2,
            checkpoint_keep=10)
    faults.clear()
    mgr = CheckpointManager(prefix)
    torn = mgr.list_tags()[1]
    with pytest.raises(MXNetError, match="truncated|checksum"):
        mgr.load(torn)
    st = mgr.load_latest()
    assert st is not None and st.tag != torn


def test_checkpoint_prefix_with_glob_chars(tmp_path):
    X, y = _toy_data()
    d = tmp_path / "run[1]"
    d.mkdir()
    prefix = str(d / "ck")
    _trained_module(X, y, prefix=prefix, every=4)
    mgr = CheckpointManager(prefix)
    assert mgr.list_tags(), "glob chars in prefix must not disable resume"
    assert mgr.load_latest() is not None


def test_feedforward_predict_missing_weight_raises(tmp_path):
    X, y = _toy_data(64)
    model = mx.model.FeedForward(_mlp(), ctx=mx.cpu(), num_epoch=1,
                                 numpy_batch_size=16, learning_rate=0.1)
    model.fit(X, y)
    prefix = str(tmp_path / "ff")
    model.save(prefix, epoch=1)
    loaded = mx.model.FeedForward.load(prefix, 1, ctx=mx.cpu())
    del loaded.arg_params["fc2_weight"]        # a REAL weight goes missing
    with pytest.raises(MXNetError, match="fc2_weight"):
        loaded.predict(X[:16])


def test_feedforward_predict_missing_aux_raises(tmp_path):
    X, y = _toy_data(64)
    model = mx.model.FeedForward(_bn_mlp(), ctx=mx.cpu(), num_epoch=1,
                                 numpy_batch_size=16, learning_rate=0.05)
    model.fit(X, y)
    prefix = str(tmp_path / "ffbn")
    model.save(prefix, epoch=1)
    loaded = mx.model.FeedForward.load(prefix, 1, ctx=mx.cpu())
    loaded.aux_params = {}                 # BN statistics go missing
    with pytest.raises(MXNetError, match="bn1_moving"):
        loaded.predict(X[:16])


def test_restore_trainer_clock_reaches_kvstore_updater():
    # the update_on_kvstore path updates through the kvstore updater's
    # pickled optimizer copy; resume must wind THAT clock too
    from mxnet_tpu import optimizer as opt
    X, y = _toy_data(32)
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    it = mx.io.NDArrayIter(X, y, batch_size=16)
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params()
    kv = mx.kvstore.create("local")
    kv.set_optimizer(opt.create("sgd", learning_rate=0.1))
    mod._kvstore = kv
    mod._update_on_kvstore = True
    mod._optimizer = opt.create("sgd", learning_rate=0.1)
    mod.optimizer_initialized = True
    mod._restore_trainer_clock(42)
    assert mod._optimizer.num_update == 42
    assert kv._updater.optimizer.num_update == 42
    assert kv._updater.optimizer.begin_num_update == 42


# -- graceful preemption: SIGTERM drains, emergency-checkpoints, resumes ----

@pytest.mark.slow
def test_sigterm_graceful_preempt_resumes_from_newer_checkpoint(tmp_path):
    """The TPU-preemption shape (docs/robustness.md "Graceful
    preemption"): SIGTERM mid-epoch must drain the dispatch pipeline,
    take an emergency checkpoint at the exact batch cursor, and exit
    cleanly via TrainingPreemptedError — and the relaunch must resume
    from that STRICTLY NEWER checkpoint to bitwise-identical final
    params. Cadence saves are disabled (RESUME_WORKER_CKPT_EVERY huge),
    so the only mid-epoch tag that can exist is the emergency one —
    unlike the SIGKILL drill, which loses everything since the last
    cadence save."""
    worker = os.path.join(os.path.dirname(__file__), "resume_worker.py")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               RESUME_WORKER_TERM_OK="1",
               RESUME_WORKER_CKPT_EVERY="1000")

    def launch(prefix, out):
        return subprocess.Popen(
            [sys.executable, worker, prefix, out, "2"],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, env=env)

    ref_out = str(tmp_path / "ref.npz")
    p = launch(str(tmp_path / "ref-ck"), ref_out)
    assert p.wait(timeout=600) == 0, p.stdout.read()

    prefix = str(tmp_path / "ck")
    out = str(tmp_path / "resumed.npz")
    p = launch(prefix, out)
    termed = False
    tail = []
    for line in p.stdout:
        tail.append(line)
        if not termed and line.startswith("BATCH 1."):
            os.kill(p.pid, signal.SIGTERM)
            termed = True
        elif line.startswith("PREEMPTED"):
            break
    assert termed, "worker finished before it could be preempted"
    assert p.wait(timeout=60) == 0, "".join(tail)
    assert any(l.startswith("PREEMPTED") for l in tail), "".join(tail)
    assert not os.path.exists(out)

    # the emergency checkpoint is MID-epoch-1 — strictly newer than the
    # epoch-end save (e0001-b00000000), which is all SIGKILL would keep
    mgr = CheckpointManager(prefix)
    st = mgr.load_latest()
    assert st is not None and st.known_good is True
    assert (st.epoch, st.batches_done) > (1, 0), st.tag
    preempt_line = [l for l in tail if l.startswith("PREEMPTED")][0]
    assert st.tag in preempt_line

    p = launch(prefix, out)
    assert p.wait(timeout=600) == 0, p.stdout.read()

    ref = np.load(ref_out)
    got = np.load(out)
    assert sorted(ref.files) == sorted(got.files)
    for name in ref.files:
        np.testing.assert_array_equal(ref[name], got[name], err_msg=name)
