"""SSD end-to-end: symbol wiring, target matching, decode geometry, and a
training smoke gate (loss decreases) on synthetic detection data.

Ref: example/ssd/symbol/symbol_vgg16_ssd_300.py:124-155 (head wiring),
example/ssd/train.py. The convergence-to-mAP run lives in
example/ssd/train.py --min-map (too slow for unit CI).
"""
import os
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.models import ssd as ssd_model

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                "example", "ssd"))
from train import MultiBoxMetric, synth_det_batch, voc_map  # noqa: E402


def test_train_symbol_shapes():
    net = ssd_model.get_symbol_train(num_classes=3, width=16)
    _, out, _ = net.infer_shape(data=(2, 3, 128, 128), label=(2, 4, 5))
    names = net.list_outputs()
    shapes = dict(zip(names, out))
    A = shapes["cls_label_output"][1]
    assert shapes["cls_prob_output"] == (2, 4, A)        # 3 classes + bg
    assert shapes["loc_loss_output"] == (2, 4 * A)
    assert shapes["det_out_output"] == (2, A, 6)


def test_eval_symbol_runs():
    net = ssd_model.get_symbol(num_classes=3, width=16)
    ex = net.simple_bind(mx.cpu(), data=(1, 3, 128, 128))
    ex.forward(is_train=False)
    det = ex.outputs[0].asnumpy()
    assert det.shape[2] == 6


def test_perfect_prediction_decodes_to_gt():
    """cls one-hot of targets + loc == loc_target must reproduce the gt box
    through MultiBoxDetection (decode+NMS geometry)."""
    anc = []
    for cy in np.linspace(0.1, 0.9, 8):
        for cx in np.linspace(0.1, 0.9, 8):
            for s in (0.2, 0.4):
                anc.append([cx - s / 2, cy - s / 2, cx + s / 2, cy + s / 2])
    anc = np.array(anc, np.float32)[None]
    A = anc.shape[1]
    gt = np.array([[[1, 0.3, 0.3, 0.62, 0.58], [-1, 0, 0, 0, 0]]],
                  np.float32)
    cls_pred = np.zeros((1, 3, A), np.float32)
    loc_t, _, cls_t = [x.asnumpy() for x in mx.nd.MultiBoxTarget(
        mx.nd.array(anc), mx.nd.array(gt), mx.nd.array(cls_pred),
        overlap_threshold=0.5, variances="0.1,0.1,0.2,0.2")]
    assert (cls_t > 0).sum() >= 1
    probs = np.zeros((1, 3, A), np.float32)
    probs[0, 0, :] = 1.0
    for a in range(A):
        if cls_t[0, a] > 0:
            probs[0, 0, a] = 0.0
            probs[0, int(cls_t[0, a]), a] = 1.0
    det = mx.nd.MultiBoxDetection(
        mx.nd.array(probs), mx.nd.array(loc_t.reshape(1, -1)),
        mx.nd.array(anc), nms_threshold=0.5,
        variances="0.1,0.1,0.2,0.2").asnumpy()
    kept = det[0][det[0, :, 0] >= 0]
    assert len(kept) == 1
    assert int(kept[0, 0]) == 1 and kept[0, 1] > 0.9
    np.testing.assert_allclose(kept[0, 2:], [0.3, 0.3, 0.62, 0.58],
                               atol=1e-5)


def test_ssd_training_smoke_loss_decreases():
    rng = np.random.default_rng(0)
    imgs, labels = synth_det_batch(rng, 32, 96, 3)
    it = mx.io.NDArrayIter(imgs, labels, batch_size=16, shuffle=True,
                           label_name="label")
    net = ssd_model.get_symbol_train(num_classes=3, width=8)
    mod = mx.mod.Module(net, data_names=("data",), label_names=("label",))
    metric = MultiBoxMetric()
    losses = []
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params(mx.initializer.Xavier())
    # adam: converges on the synthetic task in tens of steps where SGD
    # needs a long schedule (measured in example/ssd)
    mod.init_optimizer(optimizer="adam",
                       optimizer_params={"learning_rate": 1e-3,
                                         "rescale_grad": 1.0})
    for _epoch in range(16):
        it.reset()
        metric.reset()
        for b in it:
            mod.forward_backward(b)
            mod.update()
            mod.update_metric(metric, b.label)
        losses.append(metric.get()[1][0])      # cross-entropy
    assert losses[-1] < losses[0] * 0.8, \
        "SSD cls loss did not decrease: %s" % losses
    assert all(np.isfinite(losses)), losses


def test_voc_map_helper():
    gt = [np.array([[0, 0.1, 0.1, 0.5, 0.5]], np.float32)]
    perfect = [np.array([[0, 0.99, 0.1, 0.1, 0.5, 0.5]], np.float32)]
    wrong = [np.array([[0, 0.99, 0.6, 0.6, 0.9, 0.9]], np.float32)]
    assert voc_map(perfect, gt, 1) > 0.99
    assert voc_map(wrong, gt, 1) < 0.01


def test_det_iter_feeds_ssd(tmp_path):
    """ImageDetIter batch shapes slot into the SSD train symbol."""
    pytest.importorskip("PIL.Image")
    import io as _io
    from PIL import Image
    from mxnet_tpu import recordio
    rec_path = str(tmp_path / "det.rec")
    idx = str(tmp_path / "det.idx")
    w = recordio.MXIndexedRecordIO(idx, rec_path, "w")
    rng = np.random.default_rng(0)
    for i in range(8):
        img = (rng.random((96, 96, 3)) * 255).astype(np.uint8)
        buf = _io.BytesIO()
        Image.fromarray(img).save(buf, format="JPEG")
        # det array label: [header_width=2, obj_width=5, cls,x1,y1,x2,y2]
        label = np.array([2, 5, 0, 0.2, 0.2, 0.6, 0.6], np.float32)
        w.write_idx(i, recordio.pack(recordio.IRHeader(0, label, i, 0),
                                     buf.getvalue()))
    w.close()
    it = mx.image.ImageDetIter(batch_size=4, data_shape=(3, 96, 96),
                               path_imgrec=rec_path)
    b = it.next()
    assert b.data[0].shape == (4, 3, 96, 96)
    lab = b.label[0].asnumpy()
    assert lab.ndim == 3 and lab.shape[2] == 5
    net = ssd_model.get_symbol_train(num_classes=3, width=8)
    _, out, _ = net.infer_shape(data=tuple(b.data[0].shape),
                                label=tuple(lab.shape))
    assert out is not None
