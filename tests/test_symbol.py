"""Symbol graph tests (ref strategy: tests/python/unittest/test_symbol.py,
test_infer_shape.py, test_attr.py)."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import sym


def _mlp():
    data = sym.Variable("data")
    net = sym.FullyConnected(data=data, num_hidden=10, name="fc1")
    net = sym.Activation(data=net, act_type="relu")
    net = sym.FullyConnected(data=net, num_hidden=4, name="fc2")
    return sym.SoftmaxOutput(data=net, name="softmax")


def test_list_arguments():
    net = _mlp()
    args = net.list_arguments()
    assert args == ["data", "fc1_weight", "fc1_bias", "fc2_weight", "fc2_bias",
                    "softmax_label"]
    assert net.list_outputs() == ["softmax_output"]


def test_infer_shape():
    net = _mlp()
    arg_shapes, out_shapes, aux_shapes = net.infer_shape(data=(32, 100))
    d = dict(zip(net.list_arguments(), arg_shapes))
    assert d["fc1_weight"] == (10, 100)
    assert d["fc1_bias"] == (10,)
    assert d["fc2_weight"] == (4, 10)
    assert d["softmax_label"] == (32,)
    assert out_shapes == [(32, 4)]


def test_infer_shape_conv():
    data = sym.Variable("data")
    conv = sym.Convolution(data=data, kernel=(3, 3), num_filter=8, pad=(1, 1))
    pool = sym.Pooling(data=conv, kernel=(2, 2), stride=(2, 2),
                       pool_type="max")
    arg_shapes, out_shapes, _ = pool.infer_shape(data=(2, 3, 8, 8))
    d = dict(zip(pool.list_arguments(), arg_shapes))
    assert d[pool.list_arguments()[1]] == (8, 3, 3, 3)
    assert out_shapes == [(2, 8, 4, 4)]


def test_compose():
    data = sym.Variable("data")
    net1 = sym.FullyConnected(data=data, num_hidden=10, name="fc1")
    net1 = sym.FullyConnected(data=net1, num_hidden=100, name="fc2")
    data2 = sym.Variable("data2")
    net2 = sym.FullyConnected(data=data2, num_hidden=10, name="fc3")
    composed = net2(data2=net1, name="composed")
    args = composed.list_arguments()
    assert "fc1_weight" in args and "fc3_weight" in args
    assert "data2" not in args


def test_group_and_internals():
    data = sym.Variable("data")
    fc1 = sym.FullyConnected(data=data, num_hidden=10, name="fc1")
    fc2 = sym.FullyConnected(data=fc1, num_hidden=4, name="fc2")
    g = sym.Group([fc1, fc2])
    assert g.list_outputs() == ["fc1_output", "fc2_output"]
    internals = fc2.get_internals()
    assert "fc1_output" in internals.list_outputs()
    sliced = internals["fc1_output"]
    assert sliced.list_outputs() == ["fc1_output"]


def test_multi_output_indexing():
    data = sym.Variable("data")
    s = sym.SliceChannel(data=data, num_outputs=3, axis=1, name="sc")
    assert len(s.list_outputs()) == 3
    first = s[0]
    assert len(first.list_outputs()) == 1


def test_json_roundtrip():
    net = _mlp()
    js = net.tojson()
    net2 = sym.load_json(js)
    assert net2.list_arguments() == net.list_arguments()
    assert net2.list_outputs() == net.list_outputs()
    # graph still executable
    ex = net2.simple_bind(mx.cpu(), data=(4, 10), softmax_label=(4,))
    ex.forward()
    assert ex.outputs[0].shape == (4, 4)


def test_variable_shape_attr():
    data = mx.sym.Variable("data", shape=(4, 10))
    fc = sym.FullyConnected(data=data, num_hidden=3)
    arg_shapes, out_shapes, _ = fc.infer_shape()
    assert out_shapes == [(4, 3)]


def test_attr_scope():
    with mx.AttrScope(ctx_group="dev1"):
        data = sym.Variable("data")
        fc = sym.FullyConnected(data=data, num_hidden=3, name="fc_as")
    assert fc.attr("ctx_group") == "dev1"


def test_symbol_arithmetic():
    a = sym.Variable("a")
    b = sym.Variable("b")
    c = a + b
    d = c * 2 - a / b
    ex = d.bind(mx.cpu(), {"a": mx.nd.array(np.array([4.0])),
                           "b": mx.nd.array(np.array([2.0]))})
    ex.forward()
    assert np.allclose(ex.outputs[0].asnumpy(), [(4 + 2) * 2 - 4 / 2])


def test_bn_aux_states():
    data = sym.Variable("data")
    bn = sym.BatchNorm(data=data, name="bn")
    assert bn.list_auxiliary_states() == ["bn_moving_mean", "bn_moving_var"]
    arg_shapes, out_shapes, aux_shapes = bn.infer_shape(data=(2, 3, 4, 4))
    assert aux_shapes == [(3,), (3,)]
