"""Imperative autograd tests (ref strategy:
tests/python/unittest/test_autograd.py over contrib/autograd.py API)."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd as ag


def test_mark_and_compute_gradient():
    x = nd.array(np.array([1.0, 2.0, 3.0]))
    gx = nd.zeros((3,))
    ag.mark_variables([x], [gx])
    with ag.train_section():
        y = x * x + 2 * x
    ag.compute_gradient([y])
    assert np.allclose(gx.asnumpy(), 2 * x.asnumpy() + 2)


def test_grad_and_loss_decorator():
    @ag.grad_and_loss
    def f(a, b):
        return a * b

    an = np.array([1.0, 2.0], np.float32)
    bn = np.array([3.0, 4.0], np.float32)
    grads, loss = f(nd.array(an), nd.array(bn))
    assert np.allclose(grads[0].asnumpy(), bn)
    assert np.allclose(grads[1].asnumpy(), an)
    assert np.allclose(loss.asnumpy(), an * bn)


def test_grad_req_add():
    x = nd.array(np.array([2.0]))
    gx = nd.array(np.array([10.0]))
    ag.mark_variables([x], [gx], grad_reqs="add")
    with ag.train_section():
        y = x * 3
    ag.compute_gradient([y])
    assert np.allclose(gx.asnumpy(), 13.0)


def test_training_mode_dropout():
    x = nd.ones((50, 50))
    with ag.train_section():
        y = mx.nd.Dropout(x, p=0.5)
        assert (y.asnumpy() == 0).any()
    with ag.test_section():
        y = mx.nd.Dropout(x, p=0.5)
        assert not (y.asnumpy() == 0).any()


def test_chained_ops_gradient():
    x = nd.array(np.array([0.5, 1.5]))
    gx = nd.zeros((2,))
    ag.mark_variables([x], [gx])
    with ag.train_section():
        y = nd.exp(x)
        z = y * y
    ag.compute_gradient([z])
    # d(exp(x)^2)/dx = 2 exp(2x)
    assert np.allclose(gx.asnumpy(), 2 * np.exp(2 * x.asnumpy()), rtol=1e-4)


def test_out_grads():
    x = nd.array(np.array([1.0, 2.0]))
    gx = nd.zeros((2,))
    ag.mark_variables([x], [gx])
    with ag.train_section():
        y = x * 4
    ag.compute_gradient([y], out_grads=[nd.array(np.array([1.0, 0.5]))])
    assert np.allclose(gx.asnumpy(), [4.0, 2.0])


def test_multi_iteration_tape_id_reuse():
    """Regression (r4): dead intermediates' id()s recycled across/within
    record sections cross-wired the tape replay (mul shape error on the
    2nd training iteration). Tape entries hold their outputs alive so node
    keys cannot be reused; compute_gradient consumes and clears the tape
    (recording without ever computing accumulates, as in the reference)."""
    rng = np.random.RandomState(0)
    w1 = nd.array(rng.randn(6, 8).astype(np.float32) * 0.1)
    w2 = nd.array(rng.randn(8, 3).astype(np.float32) * 0.1)
    g1, g2 = nd.zeros((6, 8)), nd.zeros((8, 3))
    ag.mark_variables([w1, w2], [g1, g2])
    losses = []
    for it in range(4):
        x = nd.array(rng.randn(5, 6).astype(np.float32))
        with ag.train_section():
            h = nd.relu(nd.dot(x, w1))
            out = nd.dot(h, w2)
            loss = nd.sum(out * out)
        ag.compute_gradient([loss])
        w1[:] = w1.asnumpy() - 0.01 * g1.asnumpy()
        w2[:] = w2.asnumpy() - 0.01 * g2.asnumpy()
        losses.append(float(loss.asnumpy()))
    assert np.isfinite(losses).all()
