"""Imperative autograd tests (ref strategy:
tests/python/unittest/test_autograd.py over contrib/autograd.py API)."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd as ag


def test_mark_and_compute_gradient():
    x = nd.array(np.array([1.0, 2.0, 3.0]))
    gx = nd.zeros((3,))
    ag.mark_variables([x], [gx])
    with ag.train_section():
        y = x * x + 2 * x
    ag.compute_gradient([y])
    assert np.allclose(gx.asnumpy(), 2 * x.asnumpy() + 2)


def test_grad_and_loss_decorator():
    @ag.grad_and_loss
    def f(a, b):
        return a * b

    an = np.array([1.0, 2.0], np.float32)
    bn = np.array([3.0, 4.0], np.float32)
    grads, loss = f(nd.array(an), nd.array(bn))
    assert np.allclose(grads[0].asnumpy(), bn)
    assert np.allclose(grads[1].asnumpy(), an)
    assert np.allclose(loss.asnumpy(), an * bn)


def test_grad_req_add():
    x = nd.array(np.array([2.0]))
    gx = nd.array(np.array([10.0]))
    ag.mark_variables([x], [gx], grad_reqs="add")
    with ag.train_section():
        y = x * 3
    ag.compute_gradient([y])
    assert np.allclose(gx.asnumpy(), 13.0)


def test_training_mode_dropout():
    x = nd.ones((50, 50))
    with ag.train_section():
        y = mx.nd.Dropout(x, p=0.5)
        assert (y.asnumpy() == 0).any()
    with ag.test_section():
        y = mx.nd.Dropout(x, p=0.5)
        assert not (y.asnumpy() == 0).any()


def test_chained_ops_gradient():
    x = nd.array(np.array([0.5, 1.5]))
    gx = nd.zeros((2,))
    ag.mark_variables([x], [gx])
    with ag.train_section():
        y = nd.exp(x)
        z = y * y
    ag.compute_gradient([z])
    # d(exp(x)^2)/dx = 2 exp(2x)
    assert np.allclose(gx.asnumpy(), 2 * np.exp(2 * x.asnumpy()), rtol=1e-4)


def test_out_grads():
    x = nd.array(np.array([1.0, 2.0]))
    gx = nd.zeros((2,))
    ag.mark_variables([x], [gx])
    with ag.train_section():
        y = x * 4
    ag.compute_gradient([y], out_grads=[nd.array(np.array([1.0, 0.5]))])
    assert np.allclose(gx.asnumpy(), [4.0, 2.0])
