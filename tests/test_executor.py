"""Executor tests (ref strategy: tests/python/unittest/test_executor.py)."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import sym, nd


def test_bind_forward():
    a = sym.Variable("a")
    b = sym.Variable("b")
    c = a + b
    ex = c.bind(mx.cpu(), {"a": nd.ones((3,)), "b": nd.ones((3,)) * 2})
    ex.forward()
    assert (ex.outputs[0].asnumpy() == 3).all()


def test_backward_grads():
    # y = sum-ish via head grad: dy/da = b, dy/db = a for y = a*b
    a = sym.Variable("a")
    b = sym.Variable("b")
    y = a * b
    an = np.random.rand(4).astype(np.float32)
    bn = np.random.rand(4).astype(np.float32)
    ag = nd.zeros((4,))
    bg = nd.zeros((4,))
    ex = y.bind(mx.cpu(), {"a": nd.array(an), "b": nd.array(bn)},
                args_grad={"a": ag, "b": bg})
    ex.forward(is_train=True)
    ex.backward(out_grads=nd.ones((4,)))
    assert np.allclose(ag.asnumpy(), bn, rtol=1e-5)
    assert np.allclose(bg.asnumpy(), an, rtol=1e-5)


def test_grad_req_add():
    a = sym.Variable("a")
    y = a * 2
    ag = nd.ones((3,))  # pre-existing gradient content
    ex = y.bind(mx.cpu(), {"a": nd.ones((3,))}, args_grad={"a": ag},
                grad_req="add")
    ex.forward(is_train=True)
    ex.backward(out_grads=nd.ones((3,)))
    assert np.allclose(ag.asnumpy(), 1 + 2)  # accumulated
    ex.forward(is_train=True)
    ex.backward(out_grads=nd.ones((3,)))
    assert np.allclose(ag.asnumpy(), 3 + 2)


def test_grad_req_null():
    a = sym.Variable("a")
    b = sym.Variable("b")
    y = a * b
    ag = nd.zeros((2,))
    ex = y.bind(mx.cpu(), {"a": nd.ones((2,)), "b": nd.ones((2,))},
                args_grad={"a": ag}, grad_req={"a": "write", "b": "null"})
    ex.forward(is_train=True)
    ex.backward(out_grads=nd.ones((2,)))
    assert np.allclose(ag.asnumpy(), 1)


def test_outputs_after_backward_single_pass():
    data = sym.Variable("data")
    fc = sym.FullyConnected(data=data, num_hidden=3, name="fc")
    out = sym.SoftmaxOutput(data=fc, name="softmax")
    ex = out.simple_bind(mx.cpu(), data=(4, 5), softmax_label=(4,))
    ex.arg_dict["data"][:] = np.random.rand(4, 5)
    ex.arg_dict["fc_weight"][:] = np.random.rand(3, 5) * 0.1
    ex.forward(is_train=True)
    ex.backward()
    out_np = ex.outputs[0].asnumpy()
    assert np.allclose(out_np.sum(axis=1), 1.0, rtol=1e-5)


def test_aux_state_update():
    data = sym.Variable("data")
    bn = sym.BatchNorm(data=data, name="bn", momentum=0.5)
    ex = bn.simple_bind(mx.cpu(), data=(8, 3))
    x = np.random.rand(8, 3).astype(np.float32) * 10
    ex.arg_dict["data"][:] = x
    ex.arg_dict["bn_gamma"][:] = 1
    mean_before = ex.aux_dict["bn_moving_mean"].asnumpy().copy()
    ex.forward(is_train=True)
    _ = ex.outputs[0].asnumpy()
    mean_after = ex.aux_dict["bn_moving_mean"].asnumpy()
    assert not np.allclose(mean_before, mean_after)  # moving stats updated
    # eval mode must NOT update aux
    mean2 = mean_after.copy()
    ex.forward(is_train=False)
    _ = ex.outputs[0].asnumpy()
    assert np.allclose(mean2, ex.aux_dict["bn_moving_mean"].asnumpy())


def test_copy_params_and_reshape():
    data = sym.Variable("data")
    fc = sym.FullyConnected(data=data, num_hidden=3, name="fc")
    ex = fc.simple_bind(mx.cpu(), data=(4, 5))
    w = np.random.rand(3, 5).astype(np.float32)
    ex.copy_params_from({"fc_weight": nd.array(w)}, allow_extra_params=True)
    assert np.allclose(ex.arg_dict["fc_weight"].asnumpy(), w)
    # growing an array needs allow_up_sizing (ref executor.py reshape)
    ex2 = ex.reshape(data=(8, 5), allow_up_sizing=True)
    assert ex2.arg_dict["data"].shape == (8, 5)
    # weights shared
    assert np.allclose(ex2.arg_dict["fc_weight"].asnumpy(), w)
    ex3 = ex.reshape(data=(2, 5))  # shrinking needs no flag
    assert ex3.arg_dict["data"].shape == (2, 5)


def test_monitor_callback():
    seen = []
    data = sym.Variable("data")
    fc = sym.FullyConnected(data=data, num_hidden=2, name="fc")
    ex = fc.simple_bind(mx.cpu(), data=(2, 3))
    ex.set_monitor_callback(lambda name, arr: seen.append(name))
    ex.forward()
    assert "fc_output" in seen


def test_shared_grad_buffer_accumulates():
    """Weight tying: one grad buffer bound to two args receives the SUM."""
    a = sym.Variable("a")
    b = sym.Variable("b")
    y = a * 2 + b * 3
    g = nd.zeros((2,))
    ex = y.bind(mx.cpu(), {"a": nd.ones((2,)), "b": nd.ones((2,))},
                args_grad={"a": g, "b": g}, grad_req="write")
    ex.forward(is_train=True)
    ex.backward(out_grads=nd.ones((2,)))
    assert np.allclose(g.asnumpy(), 5.0)  # 2 + 3
    # and with add req
    ex2 = y.bind(mx.cpu(), {"a": nd.ones((2,)), "b": nd.ones((2,))},
                 args_grad={"a": g, "b": g}, grad_req="add")
    ex2.forward(is_train=True)
    ex2.backward(out_grads=nd.ones((2,)))
    assert np.allclose(g.asnumpy(), 10.0)  # 5 (prev) + 5


def test_forward_returns_lazy_outputs():
    a = sym.Variable("a")
    y = a * 2
    ex = y.bind(mx.cpu(), {"a": nd.ones((3,))})
    outs = ex.forward(is_train=True)
    assert len(outs) == 1
    assert np.allclose(outs[0].asnumpy(), 2.0)
