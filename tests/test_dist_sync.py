"""Multi-process dist_sync: REAL second processes, launched the reference way.

Parent spawns N workers via tools/launch.py (local launcher); each worker
initializes jax.distributed over Gloo on the CPU backend and runs
tests/dist_worker.py. Mirrors the reference's nightly dist tests
(ref: tests/nightly/dist_sync_kvstore.py, dist_lenet.py,
tools/launch.py:46-78).
"""
import os
import socket
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port():
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _launch(mode, nproc, timeout=600, expect_ranks=None, check_rc=True,
            extra_env=None):
    env = dict(os.environ)
    # workers must NOT inherit the 8-device virtual mesh of this suite:
    # each is one single-device CPU process in a Gloo ring
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env.update(extra_env or {})
    worker = os.path.join(ROOT, "tests", "dist_worker.py")
    cmd = [sys.executable, os.path.join(ROOT, "tools", "launch.py"),
           "-n", str(nproc), "--coord-port", str(_free_port()),
           "%s %s %s" % (sys.executable, worker, mode)]
    r = subprocess.run(cmd, env=env, capture_output=True, text=True,
                       timeout=timeout)
    out = r.stdout + r.stderr
    if check_rc:
        assert r.returncode == 0, out
    for rank in (expect_ranks if expect_ranks is not None
                 else range(nproc)):
        assert "RANK-%d-PASS" % rank in out, out
    return out


def test_dist_sync_kvstore_closed_form():
    """Every worker pushes a known value; aggregate matches the BSP formula
    (ref: dist_sync_kvstore.py:30-45)."""
    _launch("kvstore", 2)


def test_dist_sync_kvstore_three_workers():
    _launch("kvstore", 3)


def test_dist_lenet_to_accuracy():
    """Module.fit(kvstore='dist_sync') across 2 processes: fused in-step
    psum path, >=0.95 accuracy on every worker, replicas bitwise consistent
    (ref: dist_lenet.py)."""
    _launch("lenet", 2, timeout=900)


def test_dist_sync_kvstore_eight_workers():
    """BSP semantics at the width the multichip dryrun simulates
    (ref: dist_sync_kvstore.py run via launch.py -n; VERDICT r4 weak #6)."""
    _launch("kvstore", 8, timeout=900)


def test_dead_worker_detected_by_survivors():
    """Fault injection: SIGKILL one worker; every survivor must report
    num_dead_node > 0 within the heartbeat horizon (ref:
    kvstore_dist.h:159-168 GetDeadNodes; ps-lite heartbeats)."""
    nproc = 3
    # the victim (last rank) dies by SIGKILL: launcher exit is nonzero by
    # design; survivors prove detection via their PASS lines
    out = _launch("deadworker", nproc, timeout=600, check_rc=False,
                  expect_ranks=range(nproc - 1))
    assert "RANK-%d-PASS" % (nproc - 1) not in out, \
        "victim should never pass"


def test_dist_checkpoint_resume_mid_training(tmp_path):
    """Checkpoint at epoch 3, resume in a fresh module, finish to the
    accuracy gate with consistent replicas (ref: Module.save_checkpoint /
    load + --load-epoch, example/image-classification/common/fit.py)."""
    _launch("resume", 2, timeout=900,
            extra_env={"MXTPU_TEST_TMPDIR": str(tmp_path)})


@pytest.mark.slow
def test_elastic_worker_loss_survival(tmp_path):
    """SIGKILL one of three workers mid-epoch (kv.worker_die): survivors
    must emergency-checkpoint, re-form the ring at N-1, re-shard, finish
    to accuracy, stay bitwise consistent — and a fresh resume from the
    same prefix must reproduce the live post-reform state exactly
    (docs/robustness.md "Elastic distributed training")."""
    nproc = 3
    out = _launch("elastic", nproc, timeout=900, check_rc=False,
                  expect_ranks=range(nproc - 1),
                  extra_env={"MXTPU_TEST_TMPDIR": str(tmp_path)})
    assert "RANK-%d-PASS" % (nproc - 1) not in out, \
        "victim should never pass"
