"""Multi-process dist_sync: REAL second processes, launched the reference way.

Parent spawns N workers via tools/launch.py (local launcher); each worker
initializes jax.distributed over Gloo on the CPU backend and runs
tests/dist_worker.py. Mirrors the reference's nightly dist tests
(ref: tests/nightly/dist_sync_kvstore.py, dist_lenet.py,
tools/launch.py:46-78).
"""
import os
import socket
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port():
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _launch(mode, nproc, timeout=600):
    env = dict(os.environ)
    # workers must NOT inherit the 8-device virtual mesh of this suite:
    # each is one single-device CPU process in a Gloo ring
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    worker = os.path.join(ROOT, "tests", "dist_worker.py")
    cmd = [sys.executable, os.path.join(ROOT, "tools", "launch.py"),
           "-n", str(nproc), "--coord-port", str(_free_port()),
           "%s %s %s" % (sys.executable, worker, mode)]
    r = subprocess.run(cmd, env=env, capture_output=True, text=True,
                       timeout=timeout)
    out = r.stdout + r.stderr
    assert r.returncode == 0, out
    for rank in range(nproc):
        assert "RANK-%d-PASS" % rank in out, out
    return out


def test_dist_sync_kvstore_closed_form():
    """Every worker pushes a known value; aggregate matches the BSP formula
    (ref: dist_sync_kvstore.py:30-45)."""
    _launch("kvstore", 2)


def test_dist_sync_kvstore_three_workers():
    _launch("kvstore", 3)


def test_dist_lenet_to_accuracy():
    """Module.fit(kvstore='dist_sync') across 2 processes: fused in-step
    psum path, >=0.95 accuracy on every worker, replicas bitwise consistent
    (ref: dist_lenet.py)."""
    _launch("lenet", 2, timeout=900)
