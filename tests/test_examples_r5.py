"""Smoke tests for the r5 example breadth: numpy-ops (CustomOp story),
multi-task, cnn_text_classification, adversary/FGSM (ref:
example/{numpy-ops,multi-task,cnn_text_classification,adversary} —
each a user journey the reference ships; VERDICT r4 item 8)."""
import os
import subprocess
import sys

import pytest

ROOT = os.path.join(os.path.dirname(__file__), "..")


def _run(relpath, *args, timeout=900):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    r = subprocess.run([sys.executable, os.path.join(ROOT, relpath),
                       *args],
                      capture_output=True, text=True, timeout=timeout,
                      env=env)
    assert r.returncode == 0, r.stdout + r.stderr
    return r.stdout


@pytest.mark.parametrize("relpath,marker", [
    ("example/numpy-ops/numpy_softmax.py", "NUMPY-OPS PASS"),
    ("example/multi-task/multi_task.py", "MULTI-TASK PASS"),
    ("example/cnn_text_classification/text_cnn.py", "TEXT-CNN PASS"),
    ("example/adversary/fgsm.py", "ADVERSARY PASS"),
    ("example/recommenders/matrix_fact.py", "RECOMMENDER PASS"),
    ("example/nce-loss/nce_lm.py", "NCE PASS"),
    ("example/reinforcement-learning/reinforce.py", "RL PASS"),
])
def test_example_passes(relpath, marker):
    out = _run(relpath)
    assert marker in out, out
