"""Round-3 bug-sweep regressions.

Each test pins a previously reported defect: fused/executor param-authority
races in Module, silent rescale_grad divergence, seedable fused-step RNG,
cross-thread random seeding, TopKAccuracy 1-D scoring, Predictor loss-head
stripping, and Module.reshape fused-state invalidation.
"""
import threading

import numpy as np
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import optimizer as opt
from mxnet_tpu.train_step import TrainStep


def _mlp():
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=16, name="fc1")
    net = mx.sym.Activation(net, act_type="tanh")
    net = mx.sym.FullyConnected(net, num_hidden=4, name="fc2")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def _fit_data(batch_size=16, n=64):
    rng = np.random.default_rng(3)
    templates = rng.normal(size=(4, 10)).astype(np.float32)
    X = templates[rng.integers(0, 4, n)] \
        + 0.05 * rng.normal(size=(n, 10)).astype(np.float32)
    y = np.argmin(((X[:, None, :] - templates[None]) ** 2).sum(-1),
                  axis=1).astype(np.float32)
    return mx.io.NDArrayIter(X, y, batch_size=batch_size), X, y


def test_set_params_after_fused_fit_is_authoritative():
    """set_params after a fused fit must not be overwritten by a stale
    fused-state writeback on the next forward."""
    net = _mlp()
    it, X, y = _fit_data()
    mod = mx.mod.Module(net)
    mod.fit(it, num_epoch=1, initializer=mx.initializer.Xavier(),
            optimizer_params={"learning_rate": 0.1})
    assert mod._fused is not None
    zeros_args = {n: mx.nd.zeros(v.shape)
                  for n, v in mod.get_params()[0].items()}
    mod.set_params(zeros_args, {}, force_init=True)
    val = mx.io.NDArrayIter(X, y, batch_size=16)
    batch = next(iter(val))
    mod.forward(batch, is_train=False)
    args, _ = mod.get_params()
    for n, v in args.items():
        assert float(np.abs(v.asnumpy()).max()) == 0.0, n


def test_init_optimizer_force_init_keeps_trained_params():
    """Re-initializing the optimizer mid-run (e.g. to change lr) must flush
    the fused state first, not discard the trained weights."""
    net = _mlp()
    it, X, y = _fit_data()
    mod = mx.mod.Module(net)
    mod.fit(it, num_epoch=2, initializer=mx.initializer.Xavier(),
            optimizer_params={"learning_rate": 0.2})
    trained = {n: v.asnumpy() for n, v in mod.get_params()[0].items()}
    # mark fused dirty again with one more step, then force re-init
    it.reset()
    assert mod._try_fused_fit_step(next(iter(it)))
    stepped = {n: np.asarray(mod._fused_state["params"][n]) for n in trained}
    mod.init_optimizer(optimizer_params={"learning_rate": 0.01},
                       force_init=True)
    after = {n: v.asnumpy() for n, v in mod.get_params()[0].items()}
    for n in trained:
        np.testing.assert_allclose(after[n], stepped[n], atol=1e-6,
                                   err_msg=n)


def test_trainstep_explicit_rescale_grad_one_honored():
    """An Optimizer instance with rescale_grad=1.0 must be applied verbatim
    by the fused path (not silently replaced with 1/batch_size)."""
    net = _mlp()
    rng = np.random.default_rng(0)
    X = rng.normal(size=(8, 10)).astype(np.float32)
    y = rng.integers(0, 4, 8).astype(np.float32)
    batch = {"data": jnp.asarray(X), "softmax_label": jnp.asarray(y)}

    def mk():
        return opt.create("sgd", learning_rate=0.05, momentum=0.0,
                          rescale_grad=1.0)

    step = TrainStep(net, optimizer=mk())
    state = step.init({"data": (8, 10)}, {"softmax_label": (8,)}, seed=1)

    from mxnet_tpu.executor import simple_bind
    ex = simple_bind(net, mx.cpu(), grad_req="write", data=(8, 10),
                     softmax_label=(8,))
    for n in step.param_names:
        ex.arg_dict[n]._set_data(jnp.copy(state["params"][n]))
    upd = opt.get_updater(mk())
    for _ in range(2):
        state, _ = step.step(state, batch)
        ex.forward(is_train=True, data=X, softmax_label=y)
        ex.backward()
        for i, n in enumerate(step.param_names):
            upd(i, ex.grad_dict[n], ex.arg_dict[n])
    for n in step.param_names:
        np.testing.assert_allclose(np.asarray(state["params"][n]),
                                   ex.arg_dict[n].asnumpy(),
                                   atol=2e-5, rtol=2e-5, err_msg=n)


def test_trainstep_rng_respects_global_seed():
    """mx.random.seed must reach dropout inside the fused step."""
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=16, name="fc1")
    net = mx.sym.Dropout(net, p=0.5)
    net = mx.sym.FullyConnected(net, num_hidden=4, name="fc2")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    rng = np.random.default_rng(0)
    X = rng.normal(size=(8, 10)).astype(np.float32)
    y = rng.integers(0, 4, 8).astype(np.float32)
    batch = {"data": jnp.asarray(X), "softmax_label": jnp.asarray(y)}

    def one_step(seed):
        mx.random.seed(seed)
        step = TrainStep(net, optimizer="sgd", learning_rate=0.1)
        state = step.init({"data": (8, 10)}, {"softmax_label": (8,)}, seed=1)
        state, outs = step.step(state, batch)
        return np.asarray(outs[0])

    a = one_step(11)
    b = one_step(11)
    c = one_step(12)
    np.testing.assert_array_equal(a, b)
    assert np.abs(a - c).max() > 0, "seed had no effect on fused dropout"


def test_random_seed_reaches_other_threads():
    """Seeding is process-global: a producer thread (PrefetchingIter) must
    see the seeded stream, and two threads must not draw identical keys."""
    mx.random.seed(42)
    main_draw = mx.random.uniform(shape=(4,)).asnumpy()
    mx.random.seed(42)
    results = {}

    def worker(tag):
        results[tag] = mx.random.uniform(shape=(4,)).asnumpy()

    t = threading.Thread(target=worker, args=("t1",))
    t.start()
    t.join()
    np.testing.assert_array_equal(main_draw, results["t1"])
    # successive draws across threads advance one shared stream
    t2 = threading.Thread(target=worker, args=("t2",))
    t2.start()
    t2.join()
    assert np.abs(results["t1"] - results["t2"]).max() > 0


def test_topk_accuracy_1d_preds():
    """1-D predictions are class ids; previously unreachable branch raised."""
    m = mx.metric.TopKAccuracy(top_k=2)
    labels = [mx.nd.array(np.array([0, 1, 2, 3], np.float32))]
    preds_1d = [mx.nd.array(np.array([0, 1, 0, 3], np.float32))]
    m.update(labels, preds_1d)
    assert m.get()[1] == 0.75
    # 2-D path still works
    m2 = mx.metric.TopKAccuracy(top_k=2)
    p = np.zeros((4, 4), np.float32)
    p[np.arange(4), [0, 1, 2, 3]] = 1.0
    m2.update(labels, [mx.nd.array(p)])
    assert m2.get()[1] == 1.0


def test_predictor_strips_softmax_head(tmp_path):
    """Predictor must bind a SoftmaxOutput-headed symbol with only data."""
    net = _mlp()
    it, X, y = _fit_data()
    mod = mx.mod.Module(net)
    mod.fit(it, num_epoch=1, initializer=mx.initializer.Xavier(),
            optimizer_params={"learning_rate": 0.1})
    prefix = str(tmp_path / "strip")
    mod.save_checkpoint(prefix, 1)
    pred = mx.Predictor(prefix + "-symbol.json", prefix + "-0001.params",
                        {"data": (16, 10)})
    # label must NOT be an input anymore
    assert "softmax_label" not in pred._symbol.list_arguments()
    out = pred.forward(data=X[:16]).get_output(0).asnumpy()
    np.testing.assert_allclose(out.sum(axis=1), np.ones(16), atol=1e-5)
    # numerics match Module's inference
    val = mx.io.NDArrayIter(X[:16], y[:16], batch_size=16)
    ref = mod.predict(val).asnumpy()
    np.testing.assert_allclose(out, ref, atol=1e-5)


def test_module_reshape_invalidates_fused_state():
    net = _mlp()
    it, X, y = _fit_data()
    mod = mx.mod.Module(net)
    mod.fit(it, num_epoch=1, initializer=mx.initializer.Xavier(),
            optimizer_params={"learning_rate": 0.1})
    assert mod._fused is not None
    trained = {n: v.asnumpy() for n, v in mod.get_params()[0].items()}
    mod.reshape(data_shapes=[("data", (8, 10))],
                label_shapes=[("softmax_label", (8,))])
    assert mod._fused is None and mod._fused_state is None
    # trained params survived the reshape
    after = {n: v.asnumpy() for n, v in mod.get_params()[0].items()}
    for n in trained:
        np.testing.assert_allclose(after[n], trained[n], atol=1e-6,
                                   err_msg=n)
    batch = mx.io.DataBatch(data=[mx.nd.array(X[:8])],
                            label=[mx.nd.array(y[:8])])
    mod.forward(batch, is_train=False)
    assert mod.get_outputs()[0].shape[0] == 8
