"""Flagship LM: multi-axis mesh training, divisibility prechecks,
zero-recompile train-to-serve hot reload, Speedometer tokens/sec and
tuning-DB resolution (docs/perf.md "Flagship LM").

Runs on the virtual 8-device CPU mesh (conftest).
"""
import logging
import os
import tempfile
import types

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import models
from mxnet_tpu.base import MXNetError
from mxnet_tpu.parallel.mesh import (make_mesh, mesh_from_spec,
                                     parse_mesh_axes, MeshScope)
from mxnet_tpu.test_utils import assert_no_retrace

V, E, H, L, S, B = 32, 32, 4, 2, 16, 8


def _lm_symbol(**kw):
    kw.setdefault("vocab_size", V)
    kw.setdefault("embed", E)
    kw.setdefault("num_heads", H)
    kw.setdefault("num_layers", L)
    kw.setdefault("seq_len", S)
    return models.transformer(**kw)


def _lm_iter(n=4 * B, batch=B, seed=0):
    rng = np.random.RandomState(seed)
    return mx.io.NDArrayIter(
        data={"data": rng.randint(0, V, (n, S)).astype(np.float32)},
        label={"softmax_label": rng.randint(0, V, (n, S))
               .astype(np.float32)},
        batch_size=batch)


def _fit_lm(mesh_axes=None, seed=7, epochs=1, sym=None, **fit_kw):
    mod = mx.mod.Module(sym if sym is not None else _lm_symbol(),
                        context=mx.cpu(), mesh_axes=mesh_axes)
    mx.random.seed(seed)
    mod.fit(_lm_iter(), num_epoch=epochs, optimizer="sgd",
            optimizer_params={"learning_rate": 0.05},
            initializer=mx.initializer.Xavier(),
            eval_metric=mx.metric.Perplexity(ignore_label=None), **fit_kw)
    return mod


def _snap(mod):
    a, x = mod.get_params()
    return ({k: v.asnumpy().copy() for k, v in a.items()},
            {k: v.asnumpy().copy() for k, v in x.items()})


# ---------------------------------------------------------------------------
# mesh-spec parsing + divisibility prechecks (the actionable-error tentpole)
# ---------------------------------------------------------------------------

def test_parse_mesh_axes_rejects_junk():
    with pytest.raises(MXNetError, match="bogus"):
        parse_mesh_axes("bogus=2")
    with pytest.raises(MXNetError):
        parse_mesh_axes("data=0")
    with pytest.raises(MXNetError):
        parse_mesh_axes("data")
    assert parse_mesh_axes("data=2,seq=4") == {"data": 2, "seq": 4}
    assert parse_mesh_axes({"pipe": 2}) == {"pipe": 2}


def test_mesh_from_spec_device_shortfall_names_recipe():
    with pytest.raises(MXNetError, match="xla_force_host_platform"):
        mesh_from_spec("data=64")


def test_fit_batch_indivisible_names_data_axis():
    # batch 8 over a 3-way 'data' axis: the Module-level precheck must
    # fail actionably, naming the axis — not an XLA shape complaint
    mod = mx.mod.Module(_lm_symbol(), context=mx.cpu(), mesh_axes="data=3")
    with pytest.raises(MXNetError, match="data"):
        mod.fit(_lm_iter(), num_epoch=1, optimizer="sgd",
                initializer=mx.initializer.Xavier(),
                eval_metric=mx.metric.Perplexity(ignore_label=None))


def test_fit_seq_indivisible_names_seq_axis():
    # seq_len 16 over a 3-way 'seq' axis (batch 9 divides data=1 fine)
    mod = mx.mod.Module(_lm_symbol(), context=mx.cpu(), mesh_axes="seq=3")
    with pytest.raises(MXNetError, match="seq"):
        mod.fit(_lm_iter(n=18, batch=9), num_epoch=1, optimizer="sgd",
                initializer=mx.initializer.Xavier(),
                eval_metric=mx.metric.Perplexity(ignore_label=None))


def test_composed_mesh_error_names_offending_axis():
    # on the COMPOSED dp x sp mesh the batch divides 'data' but seq_len
    # 16 does not divide the 8-way 'seq' axis... the error must name
    # 'seq' and the dimension, not the first axis it checked
    mod = mx.mod.Module(_lm_symbol(), context=mx.cpu(),
                        mesh_axes="data=2,seq=8")
    with pytest.raises(MXNetError) as ei:
        mod.fit(_lm_iter(), num_epoch=1, optimizer="sgd",
                initializer=mx.initializer.Xavier(),
                eval_metric=mx.metric.Perplexity(ignore_label=None))
    msg = str(ei.value)
    assert "seq" in msg and "16" in msg


def test_ring_attention_seq_divisibility_precheck():
    from mxnet_tpu.train_step import TrainStep
    sym = _lm_symbol(seq_parallel="ring")
    mesh = make_mesh({"seq": 3}) if False else mesh_from_spec("seq=3")
    step = TrainStep(sym, optimizer="sgd", learning_rate=0.1, mesh=mesh)
    state = step.init({"data": (6, S)}, {"softmax_label": (6, S)})
    batch = {"data": np.zeros((6, S), np.float32),
             "softmax_label": np.zeros((6, S), np.float32)}
    with pytest.raises(MXNetError, match="sequence dim"):
        step.step(state, step.shard_batch(batch))


def test_ulysses_heads_divisibility_precheck():
    # seq divides the 8-way axis (16 % 8 == 0) but num_heads 4 does not:
    # Ulysses' head all-to-all needs heads % sp == 0 and must say so
    x = mx.nd.array(np.zeros((2, S, E), np.float32))
    wqkv = mx.nd.array(np.zeros((3 * E, E), np.float32))
    wout = mx.nd.array(np.zeros((E, E), np.float32))
    with MeshScope(mesh_from_spec("seq=8")):
        with pytest.raises(MXNetError, match="num_heads"):
            mx.nd.MultiHeadAttention(x, wqkv, wout, num_heads=H,
                                     no_bias=True, causal=True,
                                     seq_parallel="ulysses")


def test_pipe_stack_layer_divisibility_precheck():
    from mxnet_tpu.train_step import TrainStep
    sym = _lm_symbol(num_layers=3, stack_layers=True)
    step = TrainStep(sym, optimizer="sgd", learning_rate=0.1,
                     mesh=mesh_from_spec("pipe=2"))
    state = step.init({"data": (B, S)}, {"softmax_label": (B, S)})
    batch = {"data": np.zeros((B, S), np.float32),
             "softmax_label": np.zeros((B, S), np.float32)}
    with pytest.raises(MXNetError, match="num_layers"):
        step.step(state, step.shard_batch(batch))


def test_module_mesh_axes_rejects_dist_kvstore():
    # multi-worker dist kvstore (num_workers > 1 is what makes it dist —
    # unreachable in a single-process test, so fake it) + an explicit
    # multi-axis mesh must refuse before building the fused step
    mod = mx.mod.Module(_lm_symbol(), context=mx.cpu(),
                        mesh_axes="data=2")
    mod.bind(data_shapes=[("data", (B, S))],
             label_shapes=[("softmax_label", (B, S))])
    mod.init_params(mx.initializer.Xavier())
    mod.init_optimizer(optimizer="sgd")
    mod._kvstore = types.SimpleNamespace(type="dist_sync", num_workers=2)
    with pytest.raises(MXNetError, match="dist"):
        mod._build_fused()


# ---------------------------------------------------------------------------
# get_symbol build-time validation (the satellite's actionable errors)
# ---------------------------------------------------------------------------

def test_get_symbol_validation_errors():
    with pytest.raises(MXNetError, match="vocab_size"):
        _lm_symbol(vocab_size=1)
    with pytest.raises(MXNetError, match="num_heads"):
        _lm_symbol(embed=30)  # 30 % 4 != 0
    with pytest.raises(MXNetError, match="max_seq_len"):
        _lm_symbol(max_seq_len=S - 1)
    with pytest.raises(MXNetError, match="block_size"):
        _lm_symbol(block_size=S + 1)
    with pytest.raises(MXNetError, match="block"):
        _lm_symbol(block_size=3)  # 16 % 3 != 0
    with pytest.raises(MXNetError, match="seq_parallel"):
        _lm_symbol(stack_layers=True, seq_parallel="ring")
    with pytest.raises(MXNetError, match="dropout"):
        _lm_symbol(stack_layers=True, dropout=0.1)


def test_get_symbol_max_seq_len_table_rows():
    # the pos-embed table is decoupled from the training window
    sym = _lm_symbol(max_seq_len=4 * S)
    arg_shapes, _, _ = sym.infer_shape(data=(2, S), softmax_label=(2, S))
    shapes = dict(zip(sym.list_arguments(), arg_shapes))
    assert shapes["pos_embed_weight"] == (4 * S, E)


# ---------------------------------------------------------------------------
# multi-axis fit parity (dp x sp through the fused scan)
# ---------------------------------------------------------------------------

def test_fit_multi_axis_dp_sp_parity_and_no_retrace():
    ref = _fit_lm()
    a_ref, _ = _snap(ref)
    mod = _fit_lm(mesh_axes="data=2,seq=2", steps_per_dispatch=2)
    a, _ = _snap(mod)
    from mxnet_tpu import tracecheck
    assert tracecheck.retrace_count() == 0, tracecheck.RETRACE_EVENTS
    assert set(a) == set(a_ref)
    for k in a_ref:
        np.testing.assert_allclose(a[k], a_ref[k], rtol=2e-3, atol=2e-5,
                                   err_msg=k)


# ---------------------------------------------------------------------------
# zero-recompile train-to-serve hot reload
# ---------------------------------------------------------------------------

def _random_lm_params(seed):
    sym = _lm_symbol()
    arg_shapes, _, aux_shapes = sym.infer_shape(data=(2, S),
                                                softmax_label=(2, S))
    rng = np.random.RandomState(seed)
    args = {}
    for n, shp in zip(sym.list_arguments(), arg_shapes):
        if n in ("data", "softmax_label"):
            continue
        args[n] = (rng.randn(*shp) * 0.05).astype(np.float32)
    return sym, args


def test_decode_loop_update_params_bitwise():
    from mxnet_tpu.serving import DecodeLoop
    _, args0 = _random_lm_params(0)
    _, args1 = _random_lm_params(1)
    prompt = [1, 2, 3]
    loop = DecodeLoop(args0, num_layers=L, num_heads=H, max_len=S, slots=2)
    try:
        loop.generate(prompt, 4).result(timeout=60)
        with assert_no_retrace(msg="decode hot reload"):
            loop.update_params(args1)
            new = loop.generate(prompt, 4).result(timeout=60)
    finally:
        loop.close()
    fresh = DecodeLoop(args1, num_layers=L, num_heads=H, max_len=S,
                       slots=2)
    try:
        ref = fresh.generate(prompt, 4).result(timeout=60)
    finally:
        fresh.close()
    assert new == ref


def test_decode_loop_update_params_missing_key():
    from mxnet_tpu.serving import DecodeLoop
    _, args0 = _random_lm_params(0)
    loop = DecodeLoop(args0, num_layers=L, num_heads=H, max_len=S, slots=2)
    try:
        bad = dict(args0)
        bad.pop("lm_head_weight")
        with pytest.raises(MXNetError, match="lm_head_weight"):
            loop.update_params(bad)
    finally:
        loop.close()


def _engine_pair():
    from mxnet_tpu.serving import ServingEngine
    sym, args0 = _random_lm_params(0)
    _, args1 = _random_lm_params(1)
    sym_json = sym.tojson()
    pd = {"arg:" + k: v for k, v in args0.items()}
    eng = ServingEngine(sym_json, pd, {"data": (S,)}, buckets=(4,))
    return eng, sym_json, args0, args1


def test_engine_update_params_bitwise_and_zero_recompile():
    from mxnet_tpu.serving import ServingEngine
    eng, sym_json, args0, args1 = _engine_pair()
    x = np.arange(4 * S, dtype=np.float32).reshape(4, S) % V
    out_old = eng.infer({"data": x})[0]
    with assert_no_retrace(msg="engine hot reload"):
        eng.update_params(args1)
        out_new = eng.infer({"data": x})[0]
    eng2 = ServingEngine(
        sym_json, {"arg:" + k: v for k, v in args1.items()},
        {"data": (S,)}, buckets=(4,))
    out_ref = eng2.infer({"data": x})[0]
    assert np.array_equal(out_new, out_ref)
    assert not np.array_equal(out_new, out_old)


def test_engine_update_params_validation():
    eng, _sym_json, args0, args1 = _engine_pair()
    missing = dict(args1)
    missing.pop("lm_head_weight")
    with pytest.raises(MXNetError, match="missing"):
        eng.update_params(missing)
    bad_shape = dict(args1)
    bad_shape["lm_head_weight"] = np.zeros((V, E + 1), np.float32)
    with pytest.raises(MXNetError, match="lm_head_weight"):
        eng.update_params(bad_shape)
    # failed swaps must leave the resident set intact
    x = np.zeros((4, S), np.float32)
    eng.update_params(args0)
    assert eng.infer({"data": x})[0] is not None


def test_engine_update_params_from_checkpoint_file(tmp_path):
    eng, sym_json, _args0, args1 = _engine_pair()
    path = os.path.join(str(tmp_path), "lm-e0001-b00000000.params")
    mx.nd.save(path, {"arg:" + k: mx.nd.array(v)
                      for k, v in args1.items()})
    x = np.zeros((4, S), np.float32)
    with assert_no_retrace(msg="engine reload from checkpoint file"):
        eng.update_params(path)
        out = eng.infer({"data": x})[0]
    from mxnet_tpu.serving import ServingEngine
    eng2 = ServingEngine(
        sym_json, {"arg:" + k: v for k, v in args1.items()},
        {"data": (S,)}, buckets=(4,))
    assert np.array_equal(out, eng2.infer({"data": x})[0])


def test_fleet_update_params_fans_out_and_warm_join():
    from mxnet_tpu.obs import REGISTRY
    from mxnet_tpu.serving import FleetRouter, ServingEngine
    eng, sym_json, _args0, args1 = _engine_pair()
    counter = REGISTRY.counter("serving.param_reloads")
    before = counter.value
    router = FleetRouter({"r0": eng})
    try:
        reloaded = router.update_params(args1)
        assert len(reloaded) == 1  # engine names, one shared engine
        router.join("r1", lambda: ServingEngine(
            sym_json, {"arg:" + k: v for k, v in args1.items()},
            {"data": (S,)}, buckets=(4,)))
        x = np.zeros((4, S), np.float32)
        out = router.infer({"data": x})[0]
    finally:
        router.close()
    eng2 = ServingEngine(
        sym_json, {"arg:" + k: v for k, v in args1.items()},
        {"data": (S,)}, buckets=(4,))
    assert np.array_equal(out, eng2.infer({"data": x})[0])
    assert counter.value == before + 1


# ---------------------------------------------------------------------------
# Speedometer tokens/sec (per-run, leak-proof)
# ---------------------------------------------------------------------------

def _speedo_param(nbatch, mod=None):
    from mxnet_tpu.module.base_module import BatchEndParam
    return BatchEndParam(epoch=0, nbatch=nbatch, eval_metric=None,
                         locals={"self": mod} if mod is not None else None)


class _FakeLMModule(object):
    def _speed_tokens_per_sample(self):
        return S

    def _global_batch_scale(self):
        return 1.0


def test_speedometer_tokens_per_sec_suffix(caplog):
    speedo = mx.callback.Speedometer(batch_size=B, frequent=2)
    lm = _FakeLMModule()
    with caplog.at_level(logging.INFO):
        speedo(_speedo_param(0, lm))       # init
        speedo(_speedo_param(2, lm))       # fires: LM run -> tokens/sec
    lines = [r.getMessage() for r in caplog.records
             if "samples/sec" in r.getMessage()]
    assert lines and "tokens/sec" in lines[-1]


def test_speedometer_tokens_suffix_does_not_leak_across_runs(caplog):
    # ONE reused Speedometer: an LM run fires a tokens/sec line, then a
    # foreign stream (no locals: score(), another run) fires — its line
    # must NOT inherit the LM's tokens/sec suffix
    speedo = mx.callback.Speedometer(batch_size=B, frequent=2)
    lm = _FakeLMModule()
    with caplog.at_level(logging.INFO):
        speedo(_speedo_param(0, lm))
        speedo(_speedo_param(2, lm))
        speedo(_speedo_param(0))           # nbatch reset -> re-init
        speedo(_speedo_param(2))
    lines = [r.getMessage() for r in caplog.records
             if "samples/sec" in r.getMessage()]
    assert len(lines) == 2
    assert "tokens/sec" in lines[0]
    assert "tokens/sec" not in lines[1]


def test_module_speed_tokens_per_sample_reads_label_shape():
    mod = mx.mod.Module(_lm_symbol(), context=mx.cpu())
    mod.bind(data_shapes=[("data", (B, S))],
             label_shapes=[("softmax_label", (B, S))])
    assert mod._speed_tokens_per_sample() == S


# ---------------------------------------------------------------------------
# tuning-DB resolution: a fresh no-args LM fit picks up tokens_per_sec knobs
# ---------------------------------------------------------------------------

def test_fit_resolves_tokens_per_sec_db_entry(tmp_path, monkeypatch):
    from mxnet_tpu import autotune
    from mxnet_tpu.autotune.db import TuningDB, symbol_signature
    from mxnet_tpu.obs import REGISTRY
    sym = _lm_symbol()
    db_path = os.path.join(str(tmp_path), "tuned.json")
    db = TuningDB(db_path)
    db.put("transformer", "tokens_per_sec", B,
           {"steps_per_dispatch": 2, "dispatch_pipeline": 1},
           score=12345.0, unit="tokens/sec", kind="train",
           symbol=sym.name, symbol_sig=symbol_signature(sym))
    db.save()
    monkeypatch.setenv("MXTPU_AUTOTUNE_DB", db_path)
    counter = REGISTRY.counter("autotune.db_resolutions")
    before = counter.value
    # fresh NO-ARGS fit: no steps_per_dispatch arg, no env knob — the
    # only source for k=2 is the DB entry; and the resolved config must
    # hold zero unexpected retraces through the whole fit
    with assert_no_retrace(msg="db-resolved LM fit"):
        mod = _fit_lm()
    assert counter.value == before + 1
    assert any(k[1] == 2 for k in mod._fused._jit_scan), (
        "fit did not run the DB-resolved K=2 fused scan; scan cache keys: "
        "%r" % list(mod._fused._jit_scan))
