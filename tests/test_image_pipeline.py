"""Data plane: native fused decode/augment/batch + det iterator.

Covers the round-3 rebuild of the reference's threaded image stack
(ref: src/io/iter_image_recordio_2.cc:595 fused pipeline,
iter_image_recordio.cc:31 OMP decode, iter_image_det_recordio.cc:578,
image_det_aug_default.cc:667). Correctness is pinned against Pillow (same
libjpeg underneath, so pixels match exactly); throughput is asserted
per-core so the bar scales to the many-core TPU host.
"""
import io
import os
import time

import numpy as np
import pytest
from PIL import Image

import mxnet_tpu as mx
from mxnet_tpu import recordio
from mxnet_tpu.image import (ImageRecordIter, ImageDetIter, imdecode,
                             det_flip_boxes, det_crop_boxes)


def _make_jpeg(rng, h=256, w=256, quality=90):
    arr = (rng.rand(h, w, 3) * 255).astype(np.uint8)
    b = io.BytesIO()
    Image.fromarray(arr).save(b, "JPEG", quality=quality)
    return b.getvalue()


def _make_rec(tmp_path, n=64, h=256, w=256, label_fn=None, name="data"):
    rng = np.random.RandomState(42)
    rec_path = os.path.join(str(tmp_path), name + ".rec")
    idx_path = os.path.join(str(tmp_path), name + ".idx")
    writer = recordio.MXIndexedRecordIO(idx_path, rec_path, "w")
    jpegs = []
    for i in range(n):
        jpg = _make_jpeg(rng, h, w)
        jpegs.append(jpg)
        label = label_fn(i) if label_fn else float(i % 10)
        header = recordio.IRHeader(0, label, i, 0)
        writer.write_idx(i, recordio.pack(header, jpg))
    writer.close()
    return rec_path, jpegs


def test_imdecode_native_matches_pil(tmp_path):
    rng = np.random.RandomState(0)
    jpg = _make_jpeg(rng)
    ours = imdecode(jpg).asnumpy()
    ref = np.asarray(Image.open(io.BytesIO(jpg)).convert("RGB"))
    np.testing.assert_array_equal(ours, ref)  # same libjpeg -> exact


def test_record_iter_pixels_match_pil(tmp_path):
    rec, jpegs = _make_rec(tmp_path, n=8)
    it = ImageRecordIter(path_imgrec=rec, data_shape=(3, 224, 224),
                         batch_size=8, shuffle=False, prefetch=False)
    batch = it.next()
    data = batch.data[0].asnumpy()
    labels = batch.label[0].asnumpy()
    assert data.shape == (8, 3, 224, 224)
    np.testing.assert_allclose(labels, np.arange(8) % 10)
    x0 = (256 - 224) // 2
    for i in range(8):
        ref = np.asarray(Image.open(io.BytesIO(jpegs[i])).convert("RGB"))
        ref = ref[x0:x0 + 224, x0:x0 + 224].astype(np.float32)
        np.testing.assert_allclose(data[i].transpose(1, 2, 0), ref,
                                   atol=1e-4)


def test_record_iter_mean_std_and_resize(tmp_path):
    rec, jpegs = _make_rec(tmp_path, n=4, h=300, w=400)
    it = ImageRecordIter(path_imgrec=rec, data_shape=(3, 112, 112),
                         batch_size=4, resize=128,
                         mean_r=123.68, mean_g=116.28, mean_b=103.53,
                         std_r=58.4, std_g=57.1, std_b=57.4, prefetch=False)
    data = it.next().data[0].asnumpy()
    assert data.shape == (4, 3, 112, 112)
    # normalized pixels live in a few-sigma band, not [0,255]
    assert np.abs(data).max() < 6.0
    assert data.std() > 0.3


def test_record_iter_deterministic_and_random(tmp_path):
    rec, _ = _make_rec(tmp_path, n=16)
    def run(seed):
        it = ImageRecordIter(path_imgrec=rec, data_shape=(3, 200, 200),
                             batch_size=16, rand_crop=True, rand_mirror=True,
                             seed=seed, prefetch=False)
        return it.next().data[0].asnumpy()
    a, b, c = run(1), run(1), run(2)
    np.testing.assert_array_equal(a, b)     # same seed -> same batch
    assert np.abs(a - c).max() > 1          # different seed -> different aug


def test_record_iter_sharding_and_epochs(tmp_path):
    rec, _ = _make_rec(tmp_path, n=32)
    it0 = ImageRecordIter(path_imgrec=rec, data_shape=(3, 64, 64),
                          batch_size=8, part_index=0, num_parts=2,
                          prefetch=False)
    it1 = ImageRecordIter(path_imgrec=rec, data_shape=(3, 64, 64),
                          batch_size=8, part_index=1, num_parts=2,
                          prefetch=False)
    # shards are disjoint halves of the record keys
    assert set(it0.seq).isdisjoint(it1.seq)
    assert len(it0.seq) == len(it1.seq) == 16
    l0 = np.concatenate([it0.next().label[0].asnumpy() for _ in range(2)])
    l1 = np.concatenate([it1.next().label[0].asnumpy() for _ in range(2)])
    assert len(l0) == len(l1) == 16
    with pytest.raises(StopIteration):
        it0.next()
    it0.reset()
    assert it0.next().data[0].shape == (8, 3, 64, 64)


def test_record_iter_round_batch_wraps_tail(tmp_path):
    rec, _ = _make_rec(tmp_path, n=20)
    it = ImageRecordIter(path_imgrec=rec, data_shape=(3, 64, 64),
                         batch_size=8, prefetch=False, round_batch=True)
    pads = []
    count = 0
    while True:
        try:
            b = it.next()
        except StopIteration:
            break
        pads.append(b.pad)
        count += 8
    assert count == 24               # 2 full + 1 wrapped batch
    assert pads == [0, 0, 4]         # tail batch reports its pad
    it2 = ImageRecordIter(path_imgrec=rec, data_shape=(3, 64, 64),
                          batch_size=8, prefetch=False, round_batch=False)
    n2 = 0
    while True:
        try:
            it2.next()
        except StopIteration:
            break
        n2 += 8
    assert n2 == 16                  # tail discarded when round_batch=False


def test_record_iter_corrupt_image_raises(tmp_path):
    rec_path = os.path.join(str(tmp_path), "bad.rec")
    idx_path = os.path.join(str(tmp_path), "bad.idx")
    w = recordio.MXIndexedRecordIO(idx_path, rec_path, "w")
    w.write_idx(0, recordio.pack(recordio.IRHeader(0, 0.0, 0, 0),
                                 b"\xff\xd8not a real jpeg"))
    w.close()
    it = ImageRecordIter(path_imgrec=rec_path, data_shape=(3, 64, 64),
                         batch_size=1, prefetch=False)
    with pytest.raises(mx.base.MXNetError, match="corrupt"):
        it.next()


def test_pipeline_throughput_per_core(tmp_path):
    """The input pipeline must feed the chip: per-core decode+augment+batch
    throughput implies >= 2,400 img/s on the multi-core bench host (the
    compute side's measured rate, BENCH_r02). On a 1-core dev box the gate
    is the per-core floor; on >=4 cores the absolute gate applies."""
    n = 256
    rec, _ = _make_rec(tmp_path, n=n)
    it = ImageRecordIter(path_imgrec=rec, data_shape=(3, 224, 224),
                         batch_size=64, resize=256, rand_crop=True,
                         rand_mirror=True, mean_r=123.68, mean_g=116.28,
                         mean_b=103.53, prefetch=True)
    # core-scaling stage: read + fused native decode/augment to host numpy
    it.decode_batch_numpy(it.seq[:64], 0)  # warm (file cache, lib init)
    t0 = time.perf_counter()
    seen = 0
    for i in range(n // 64):
        d, _l = it.decode_batch_numpy(it.seq[i * 64:(i + 1) * 64], i)
        seen += d.shape[0]
    dt = time.perf_counter() - t0
    rate = seen / dt
    cores = os.cpu_count() or 1
    per_core = rate / min(cores, 16)
    print("decode+augment: %.0f img/s total, %.0f img/s/core (%d cores)"
          % (rate, per_core, cores))
    assert per_core >= 550, "per-core decode rate %.0f too slow" % per_core

    # full pipeline (prefetch + device transfer): absolute gate where the
    # cores exist to feed the chip
    if cores >= 4:
        it.reset()
        it.next()  # prime the prefetcher
        t0 = time.perf_counter()
        seen = 0
        for _ in range(n // 64 - 1):
            seen += it.next().data[0].shape[0]
        full_rate = seen / (time.perf_counter() - t0)
        print("full pipeline: %.0f img/s" % full_rate)
        assert full_rate >= 2400, \
            "pipeline %.0f img/s cannot feed the chip" % full_rate


def test_prefetch_overlaps_and_matches(tmp_path):
    rec, _ = _make_rec(tmp_path, n=32)
    a = ImageRecordIter(path_imgrec=rec, data_shape=(3, 128, 128),
                        batch_size=16, prefetch=False, seed=5)
    b = ImageRecordIter(path_imgrec=rec, data_shape=(3, 128, 128),
                        batch_size=16, prefetch=True, seed=5)
    for _ in range(2):
        np.testing.assert_array_equal(a.next().data[0].asnumpy(),
                                      b.next().data[0].asnumpy())


# -- detection ---------------------------------------------------------------

def _det_label(i):
    # [hdr_w, obj_w, id, x1, y1, x2, y2] one object per image
    return [2.0, 5.0, float(i % 3), 0.2, 0.3, 0.6, 0.8]


def test_det_iter_labels_and_shapes(tmp_path):
    rec, _ = _make_rec(tmp_path, n=8, label_fn=_det_label, name="det")
    it = ImageDetIter(batch_size=4, data_shape=(3, 128, 128),
                      path_imgrec=rec)
    batch = it.next()
    assert batch.data[0].shape == (4, 3, 128, 128)
    lab = batch.label[0].asnumpy()
    assert lab.shape == (4, it.max_objs, 5)
    np.testing.assert_allclose(lab[0, 0], [0.0, 0.2, 0.3, 0.6, 0.8],
                               atol=1e-6)


def test_det_flip_boxes():
    boxes = np.array([[1.0, 0.2, 0.3, 0.6, 0.8],
                      [-1.0, -1, -1, -1, -1]], np.float32)
    f = det_flip_boxes(boxes)
    np.testing.assert_allclose(f[0], [1.0, 0.4, 0.3, 0.8, 0.8], atol=1e-6)
    assert f[1, 0] == -1


def test_det_crop_boxes_keep_and_drop():
    boxes = np.array([[2.0, 0.1, 0.1, 0.4, 0.4],    # inside crop
                      [3.0, 0.8, 0.8, 0.95, 0.95]], np.float32)  # outside
    out = det_crop_boxes(boxes, 0.0, 0.0, 0.5, 0.5, min_overlap=0.5)
    np.testing.assert_allclose(out[0], [2.0, 0.2, 0.2, 0.8, 0.8], atol=1e-5)
    assert out[1, 0] == -1  # dropped


def test_det_iter_mirror_consistency(tmp_path):
    """Mirrored pixels and mirrored boxes stay in sync: paint a dark patch
    inside the box; after augmentation the (possibly flipped) box must still
    cover the dark region."""
    rng = np.random.RandomState(3)
    rec_path = os.path.join(str(tmp_path), "detm.rec")
    idx_path = os.path.join(str(tmp_path), "detm.idx")
    w = recordio.MXIndexedRecordIO(idx_path, rec_path, "w")
    for i in range(8):
        arr = np.full((200, 200, 3), 255, np.uint8)
        arr[60:160, 20:100] = 0  # dark object: x in [0.1,0.5], y in [0.3,0.8]
        b = io.BytesIO()
        Image.fromarray(arr).save(b, "JPEG", quality=95)
        header = recordio.IRHeader(
            0, [2.0, 5.0, 1.0, 0.1, 0.3, 0.5, 0.8], i, 0)
        w.write_idx(i, recordio.pack(header, b.getvalue()))
    w.close()
    it = ImageDetIter(batch_size=8, data_shape=(3, 100, 100),
                      path_imgrec=rec_path, rand_mirror=True, seed=11)
    batch = it.next()
    data = batch.data[0].asnumpy()
    lab = batch.label[0].asnumpy()
    flipped = 0
    for i in range(8):
        b = lab[i, 0]
        assert b[0] == 1.0
        x1, y1, x2, y2 = (b[1] * 100, b[2] * 100, b[3] * 100, b[4] * 100)
        inside = data[i, :, int(y1) + 5:int(y2) - 5,
                      int(x1) + 5:int(x2) - 5]
        outside = data[i, :, int(y1) + 5:int(y2) - 5, :]
        assert inside.mean() < 60, "box does not cover the dark object"
        if b[1] > 0.4:  # flipped: object now on the right
            flipped += 1
    assert 0 < flipped < 8  # rand_mirror actually flips some


def test_image_iter_superbatch_host_stacking(tmp_path):
    """ImageIter.next_host feeds SuperBatchIter host-side: stacking happens
    before any device transfer, and the superbatch matches per-batch next()."""
    from mxnet_tpu.image import ImageIter
    rec, jpegs = _make_rec(tmp_path, n=12, h=64, w=64)
    mk = lambda: ImageIter(batch_size=4, data_shape=(3, 64, 64),
                           path_imgrec=rec, shuffle=False)
    hb = mk().next_host()
    assert isinstance(hb.data[0], np.ndarray)  # host numpy, no device array

    sbs = list(mk().superbatch(2, prefetch=False))
    assert [sb.num_steps for sb in sbs] == [2, 1]
    assert sbs[0].data[0].shape == (2, 4, 3, 64, 64)
    ref = list(mk())
    np.testing.assert_array_equal(
        sbs[0].data[0].asnumpy(),
        np.stack([ref[0].data[0].asnumpy(), ref[1].data[0].asnumpy()]))
    np.testing.assert_array_equal(
        sbs[0].label[0].asnumpy(),
        np.stack([ref[0].label[0].asnumpy(), ref[1].label[0].asnumpy()]))
