"""Random + initializer tests (ref strategy: test_random.py, test_init.py)."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.initializer import (Uniform, Normal, Xavier, Orthogonal,
                                   Constant, Mixed, Load, InitDesc, One, Zero)


def test_seed_determinism():
    mx.random.seed(42)
    a = nd.uniform(shape=(5, 5)).asnumpy()
    mx.random.seed(42)
    b = nd.uniform(shape=(5, 5)).asnumpy()
    assert np.allclose(a, b)
    c = nd.uniform(shape=(5, 5)).asnumpy()
    assert not np.allclose(b, c)


def test_uniform_range():
    mx.random.seed(0)
    x = nd.uniform(low=-2, high=2, shape=(1000,)).asnumpy()
    assert x.min() >= -2 and x.max() <= 2
    assert abs(x.mean()) < 0.2


def test_normal_moments():
    mx.random.seed(0)
    x = nd.normal(loc=1.0, scale=2.0, shape=(5000,)).asnumpy()
    assert abs(x.mean() - 1.0) < 0.15
    assert abs(x.std() - 2.0) < 0.2


def test_initializer_dispatch():
    init = Xavier()
    w = nd.zeros((4, 8))
    init("fc1_weight", w)
    assert np.abs(w.asnumpy()).sum() > 0
    b = nd.ones((4,))
    init("fc1_bias", b)
    assert (b.asnumpy() == 0).all()
    g = nd.zeros((4,))
    init("bn_gamma", g)
    assert (g.asnumpy() == 1).all()
    mv = nd.ones((4,))
    init("bn_moving_mean", mv)
    assert (mv.asnumpy() == 0).all()


def test_uniform_scale():
    init = Uniform(0.5)
    w = nd.zeros((100, 10))
    init("w_weight", w)
    x = w.asnumpy()
    assert x.min() >= -0.5 and x.max() <= 0.5


def test_orthogonal():
    init = Orthogonal(scale=1.0)
    w = nd.zeros((8, 8))
    init("q_weight", w)
    q = w.asnumpy()
    assert np.allclose(q @ q.T, np.eye(8), atol=1e-4)


def test_constant_and_mixed():
    init = Mixed([".*bias", ".*"], [Constant(3), Uniform(0.1)])
    b = nd.zeros((4,))
    init("fc_bias", b)
    assert (b.asnumpy() == 3).all()
    w = nd.zeros((4, 4))
    init("fc_weight", w)
    assert np.abs(w.asnumpy()).max() <= 0.1


def test_load_initializer():
    src = {"fc_weight": nd.ones((2, 2))}
    init = Load(src, default_init=Zero())
    w = nd.zeros((2, 2))
    init("fc_weight", w)
    assert (w.asnumpy() == 1).all()
    other = nd.ones((3,))
    init("other_weight", other)
    assert (other.asnumpy() == 0).all()


def test_init_attr_override():
    from mxnet_tpu.initializer import Initializer
    desc = InitDesc("custom_weight", attrs={"__init__": One().dumps()})
    w = nd.zeros((3, 3))
    Uniform(0.1)(desc, w)  # __init__ attr overrides to One
    assert (w.asnumpy() == 1).all()


def test_sample_ops():
    mx.random.seed(7)
    g = mx.random.gamma(alpha=2.0, beta=1.0, shape=(2000,)).asnumpy()
    assert g.min() > 0 and abs(g.mean() - 2.0) < 0.3
    e = mx.random.exponential(lam=2.0, shape=(2000,)).asnumpy()
    assert abs(e.mean() - 0.5) < 0.1
    p = mx.random.poisson(lam=3.0, shape=(2000,)).asnumpy()
    assert abs(p.mean() - 3.0) < 0.3
