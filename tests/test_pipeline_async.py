"""Host off the critical path (ISSUE 4): pipelined K-step dispatch and
asynchronous checkpointing.

Pins the two contracts docs/perf.md "Host off the critical path" and
docs/robustness.md "Asynchronous checkpointing" state:

- bitwise parity: pipelined-vs-eager ``fit`` (params, optimizer state,
  metric folds, checkpoint files; guard on and off) and async-vs-sync
  checkpoint files byte-identical;
- guard semantics under lag: divergence still rolls back, a diverged
  state is never sealed, and the host step-clock mirror never drifts from
  the device counter;
- writer failure modes via the ``ckpt.async_write`` / ``ckpt.async_die``
  fault sites: back-pressure sheds-and-counts, a failed/dead writer loses
  only the in-flight save and restarts.

All tier-1, sleep-free (event-paced; the conftest wall-clock cap enforces
it).
"""
import glob
import json
import logging
import os
import threading

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import faults, sym
from mxnet_tpu.base import MXNetError
from mxnet_tpu.model import AsyncCheckpointWriter, CheckpointManager

pytestmark = pytest.mark.pipeline


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


def _mlp():
    data = sym.Variable("data")
    net = sym.FullyConnected(data=data, num_hidden=16, name="fc1")
    net = sym.Activation(data=net, act_type="relu", name="relu1")
    net = sym.FullyConnected(data=net, num_hidden=4, name="fc2")
    return sym.SoftmaxOutput(data=net, name="softmax")


def _toy_data(n=128, dim=10, classes=4, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, dim)).astype(np.float32)
    w = rng.normal(size=(dim, classes)).astype(np.float32)
    y = np.argmax(X @ w, axis=1).astype(np.float32)
    return X, y


def _opt_params():
    from mxnet_tpu import lr_scheduler
    return {"learning_rate": 0.1, "momentum": 0.9,
            "lr_scheduler": lr_scheduler.FactorScheduler(step=5,
                                                         factor=0.5)}


def _fit(X, y, depth, k=2, prefix=None, every=4, async_ckpt=False,
         guard=None, num_epoch=2, pace=False, callbacks=None, keep=10):
    """One deterministic fit; returns (module, manager, captured)."""
    mx.random.seed(3)
    it = mx.io.NDArrayIter(X, y, batch_size=16)
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    mgr = CheckpointManager(prefix, keep=keep) if prefix else None
    captured = []

    def cb(p):
        captured.append((p.epoch, p.nbatch,
                         tuple(v for _, v in
                               p.eval_metric.get_name_value())))
        if pace and mgr is not None:
            # parity runs: drain after every callback so back-pressure
            # (timing-dependent on a loaded host) never sheds a save
            mgr.drain()
        if callbacks:
            callbacks(p)

    mod.fit(it, num_epoch=num_epoch, steps_per_dispatch=k,
            optimizer_params=_opt_params(),
            eval_metric=mx.metric.create(["acc", "ce"]),
            dispatch_pipeline=depth,
            checkpoint_prefix=mgr,
            checkpoint_every_n_batches=every if mgr else None,
            checkpoint_async=async_ckpt, guard=guard,
            batch_end_callback=cb)
    return mod, mgr, captured


def _params_np(mod):
    arg, aux = mod.get_params()
    out = {n: v.asnumpy() for n, v in arg.items()}
    out.update({"aux:" + n: v.asnumpy() for n, v in aux.items()})
    return out


def _opt_states_np(mod):
    import pickle
    return pickle.loads(mod._updater.get_states())


def _files(prefix):
    d = os.path.dirname(prefix)
    return sorted(os.path.basename(p) for p in glob.glob(prefix + "*"))


# -- bitwise parity: pipelined vs eager -------------------------------------

@pytest.mark.parametrize("use_guard", [False, True])
def test_pipelined_vs_eager_fit_bitwise(tmp_path, use_guard, caplog):
    X, y = _toy_data()
    pe = str(tmp_path / "eager" / "ck")
    pp = str(tmp_path / "piped" / "ck")
    with caplog.at_level(logging.WARNING):
        a, _, cba = _fit(X, y, depth=0, prefix=pe, guard=use_guard or None)
        b, _, cbb = _fit(X, y, depth=2, prefix=pp, guard=use_guard or None)
    pa, pb = _params_np(a), _params_np(b)
    assert sorted(pa) == sorted(pb)
    for n in pa:
        np.testing.assert_array_equal(pa[n], pb[n], err_msg=n)
    sa, sb = _opt_states_np(a), _opt_states_np(b)
    assert sorted(sa) == sorted(sb)
    for i in sa:
        fa = sa[i][0] if isinstance(sa[i], tuple) else sa[i]
        fb = sb[i][0] if isinstance(sb[i], tuple) else sb[i]
        if fa is not None:
            np.testing.assert_array_equal(np.asarray(fa), np.asarray(fb))
    # the callback SEQUENCE (nbatch, metric folds) is identical — only the
    # wall-clock moment of each fire moved
    assert cba == cbb
    # checkpoint FILES byte-identical (cursor, rng, metric sums, params)
    fe, fp = _files(pe), _files(pp)
    assert fe == fp and len(fe) >= 8
    for name in fe:
        be = open(os.path.join(os.path.dirname(pe), name), "rb").read()
        bp = open(os.path.join(os.path.dirname(pp), name), "rb").read()
        assert be == bp, name


def test_pipelined_jit_cache_keys_unchanged():
    """Pipelining defers the readback; it must not touch what gets
    compiled — jit caches stay keyed (batch, k), guard-off caches stay
    guard-free, and the whole pipelined fit (multi-epoch, epoch tails
    included) never retraces a seen program (tracecheck cache-key differ
    names the drifting argument if it ever does)."""
    from mxnet_tpu.test_utils import assert_no_retrace
    X, y = _toy_data()
    a, _, _ = _fit(X, y, depth=0)
    with assert_no_retrace(msg="pipelined fit"):
        b, _, _ = _fit(X, y, depth=2)
    assert sorted(a._fused._jit_scan) == sorted(b._fused._jit_scan)
    assert not a._fused._jit_scan_g and not b._fused._jit_scan_g


def test_epoch_tail_drains_before_per_step(tmp_path):
    """96 samples / batch 16 with k=4: the 2-batch tail trains per-step —
    the pipeline must drain first so metric folds stay in dispatch order
    and every sample is covered."""
    X, y = _toy_data(n=96)
    mx.random.seed(3)
    it = mx.io.NDArrayIter(X, y, batch_size=16)
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    seen = []
    mod.fit(it, num_epoch=1, steps_per_dispatch=4, dispatch_pipeline=3,
            optimizer_params={"learning_rate": 0.1},
            batch_end_callback=lambda p: seen.append(
                (p.nbatch, p.eval_metric.num_inst)))
    assert mod._fused_step_count() == 6
    assert seen[-1] == (5, 96)
    # callbacks still arrive in nbatch order despite the lag
    assert [s[0] for s in seen] == sorted(s[0] for s in seen)


# -- host step-clock mirror (satellite) -------------------------------------

def test_fused_step_count_matches_device_without_sync():
    X, y = _toy_data()
    mod, _, _ = _fit(X, y, depth=2)
    assert mod._fused_step_count() == int(
        np.asarray(mod._fused_state["step"]))


def test_fused_step_count_tracks_guard_skips():
    """A guard-skipped step is a device no-op: the host mirror must trail
    num_update by exactly the skip count, matching the device counter."""
    X, y = _toy_data()
    faults.inject("guard.grad_nan", nth=3)
    mod, _, _ = _fit(X, y, depth=1, guard=True, num_epoch=1)
    dev = int(np.asarray(mod._fused_state["step"]))
    assert mod._fused_step_count() == dev
    assert dev == 8 - 1  # 8 steps dispatched, 1 skipped


# -- async vs sync checkpoint bytes -----------------------------------------

def test_async_checkpoint_files_byte_identical(tmp_path):
    X, y = _toy_data()
    ps = str(tmp_path / "sync" / "ck")
    pa = str(tmp_path / "async" / "ck")
    _fit(X, y, depth=1, prefix=ps, async_ckpt=False)
    _fit(X, y, depth=1, prefix=pa, async_ckpt=True, pace=True)
    fs, fa = _files(ps), _files(pa)
    assert fs == fa and len(fs) >= 8
    for name in fs:
        bs = open(os.path.join(os.path.dirname(ps), name), "rb").read()
        ba = open(os.path.join(os.path.dirname(pa), name), "rb").read()
        assert bs == ba, name
    # and the resulting run is resumable: latest validates, known-good
    st = CheckpointManager(pa).load_latest()
    assert st is not None and st.known_good is True


def test_async_save_decoupled_from_later_training(tmp_path):
    """The snapshot must capture save-time state even though training (and
    further saves) continue while the writer works: every manifest's
    num_update must be the cursor at ITS submit, strictly increasing."""
    X, y = _toy_data()
    prefix = str(tmp_path / "ck")
    _fit(X, y, depth=1, prefix=prefix, async_ckpt=True, pace=True, every=2)
    mgr = CheckpointManager(prefix)
    upds = []
    for tag in mgr.list_tags():
        man = json.load(open(mgr._file(tag, "manifest.json")))
        upds.append(man["num_update"])
        st = mgr.load(tag)  # validates checksums over the decoupled bytes
        assert st.known_good is True
    # monotone cursor (an epoch-end save legitimately repeats the last
    # cadence save's num_update with a different epoch cursor)
    assert upds == sorted(upds)


# -- writer mechanics: back-pressure, faults, death -------------------------

def test_writer_backpressure_sheds_and_counts():
    gate = threading.Event()
    done = []
    w = AsyncCheckpointWriter(logger=logging)
    try:
        assert w.submit(lambda: (gate.wait(30), done.append(1)))
        # second submit while the first blocks: shed, not queued
        assert not w.submit(lambda: done.append(2))
        w.note_skip("e0000-b00000008")
        assert w.skipped == 1
        gate.set()
        assert w.drain()
        assert done == [1]
        assert w.submitted == 1 and w.written == 1
    finally:
        gate.set()
        w.close()


def test_backpressure_skip_counts_into_training_health():
    from mxnet_tpu import guard as guard_mod
    h = guard_mod.TrainingHealth()
    gate = threading.Event()
    w = AsyncCheckpointWriter(logger=logging, health=h)
    try:
        assert w.submit(lambda: gate.wait(30))
        w.note_skip("tag")
        assert h.ckpt_skipped == 1
        assert h.report()["ckpt_skipped"] == 1
        gate.set()
    finally:
        gate.set()
        w.close()


def test_async_write_fault_drops_save_keeps_previous(tmp_path, caplog):
    """ckpt.async_write raise: the in-flight save is dropped and counted;
    latest keeps pointing at the previous valid generation."""
    X, y = _toy_data()
    prefix = str(tmp_path / "ck")
    mgr = CheckpointManager(prefix, keep=10)
    mod, _, _ = _fit(X, y, depth=0, num_epoch=1)
    assert mgr.save(mod, 1, 0) is not None
    before = mgr.load_latest()
    mgr.async_writer = AsyncCheckpointWriter(logger=logging)
    try:
        faults.inject("ckpt.async_write", nth=1, kind="raise")
        with caplog.at_level(logging.ERROR):
            mgr.save(mod, 1, 4)
            assert mgr.drain()
        assert mgr.async_writer.errors == 1
        assert any("async checkpoint save failed" in r.message
                   for r in caplog.records)
        st = mgr.load_latest()
        assert st is not None and st.tag == before.tag
    finally:
        mgr.async_writer.close()


def test_async_die_reaped_and_writer_restarts(tmp_path, caplog):
    """ckpt.async_die kills the writer thread mid-job: drain must not
    hang, the corpse is counted, and the next save works again."""
    X, y = _toy_data()
    prefix = str(tmp_path / "ck")
    mgr = CheckpointManager(prefix, keep=10)
    mod, _, _ = _fit(X, y, depth=0, num_epoch=1)
    mgr.async_writer = AsyncCheckpointWriter(logger=logging)
    try:
        faults.inject("ckpt.async_die", nth=1, kind="die")
        with caplog.at_level(logging.WARNING):
            assert mgr.save(mod, 1, 0) is not None
            assert mgr.drain() is False       # job lost, not hung
        assert mgr.async_writer.errors == 1
        assert mgr.load_latest() is None      # nothing was written
        # the writer restarts transparently on the next save
        assert mgr.save(mod, 1, 4) is not None
        assert mgr.drain() is True
        assert mgr.async_writer.restarts == 1
        st = mgr.load_latest()
        assert st is not None and st.batches_done == 4
    finally:
        mgr.async_writer.close()


def test_manager_reusable_after_async_fit(tmp_path):
    """fit detaches (not just closes) the writer it created: the same
    manager must drive a second async fit and a manual sync save without
    hitting the closed writer."""
    X, y = _toy_data()
    prefix = str(tmp_path / "ck")
    mgr = CheckpointManager(prefix, keep=10)
    mx.random.seed(3)
    it = mx.io.NDArrayIter(X, y, batch_size=16)
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    fit_kw = dict(num_epoch=1, steps_per_dispatch=2,
                  optimizer_params={"learning_rate": 0.1},
                  checkpoint_prefix=mgr, checkpoint_every_n_batches=4,
                  checkpoint_async=True)
    mod.fit(it, **fit_kw)
    assert mgr.async_writer is None            # detached at teardown
    assert mgr.last_async_writer.written >= 1  # counters survive
    it.reset()
    mod.fit(it, **fit_kw)                      # second async fit works
    assert mgr.save(mod, 9, 0) is not None     # manual save falls to sync
    assert mgr.load_latest() is not None


def test_sync_snapshot_skips_decoupled_state_copies(tmp_path):
    """A sync save writes inline before training resumes — it must not pay
    the device-side optimizer-state replica the async writer needs."""
    X, y = _toy_data()
    mod, _, _ = _fit(X, y, depth=0, num_epoch=1)
    calls = []
    orig = mod._snapshot_opt_states
    mod._snapshot_opt_states = lambda: calls.append(1) or orig()
    mgr = CheckpointManager(str(tmp_path / "ck"))
    mgr.save(mod, 1, 0)
    assert calls == []                         # sync: copy-free path
    mgr.async_writer = AsyncCheckpointWriter(logger=logging)
    try:
        mgr.save(mod, 1, 4)
        mgr.drain()
        assert calls == [1]                    # async: decoupled snapshot
    finally:
        mgr.async_writer.close()


def test_writer_drain_timeout_zero_polls():
    gate = threading.Event()
    w = AsyncCheckpointWriter(logger=logging)
    try:
        assert w.submit(lambda: gate.wait(30))
        assert w.drain(timeout=0) is False     # poll, never block
        gate.set()
        assert w.drain() is True
    finally:
        gate.set()
        w.close()


def test_closed_writer_rejects_submit():
    w = AsyncCheckpointWriter(logger=logging)
    w.close()
    with pytest.raises(MXNetError, match="closed"):
        w.submit(lambda: None)


# -- guard semantics under lag ----------------------------------------------

def test_pipelined_guard_divergence_still_rolls_back(tmp_path, caplog):
    """Divergence detection is allowed a bounded staleness of `depth`
    dispatches — but it must still fire, roll back to a pre-spike
    checkpoint, and never seal a diverged state."""
    from mxnet_tpu.guard import TrainingGuard
    X, y = _toy_data()
    prefix = str(tmp_path / "ck")
    g = TrainingGuard(window=50, spike_factor=4.0, patience=2,
                      max_rollbacks=5, logger=logging)
    faults.inject("guard.loss_spike", nth=5, times=2)
    with caplog.at_level(logging.WARNING):
        mod, mgr, _ = _fit(X, y, depth=2, prefix=prefix, every=2, guard=g)
    assert g.health.rollbacks == 1
    assert any("rolling back" in r.message for r in caplog.records)
    # every surviving checkpoint is known-good (diverged state never sealed)
    mgr2 = CheckpointManager(prefix, keep=10)
    for tag in mgr2.list_tags():
        man = json.load(open(mgr2._file(tag, "manifest.json")))
        assert man["known_good"] is True, tag
    # and training completed bitwise-reproducibly after the rollback
    assert all(np.isfinite(v).all() for v in _params_np(mod).values())


def test_guard_async_ckpt_and_pipeline_compose(tmp_path):
    """All three at once (guard + async ckpt + pipelined dispatch): a NaN
    step is skipped on device, counted, and the run's checkpoints stay
    resumable."""
    X, y = _toy_data()
    prefix = str(tmp_path / "ck")
    faults.inject("guard.grad_nan", nth=4)
    mod, mgr, _ = _fit(X, y, depth=2, prefix=prefix, every=4,
                       async_ckpt=True, pace=True, guard=True)
    st = CheckpointManager(prefix).load_latest()
    assert st is not None and st.known_good is True
    # manifest's fused_step trails num_update by the one skipped step
    assert st.fused_step == st.num_update - 1


# -- Speedometer suffix (satellite) -----------------------------------------

def test_speedometer_appends_pipeline_suffix(caplog):
    from collections import namedtuple
    from mxnet_tpu.callback import Speedometer
    BatchEndParam = namedtuple("BatchEndParams",
                               ["epoch", "nbatch", "eval_metric", "locals"])

    class _P(object):
        depth = 2
        host_stall = 0.0

    p = _P()
    sp = Speedometer(batch_size=16, frequent=4)
    with caplog.at_level(logging.INFO):
        for nbatch in (1, 3, 5, 7, 9):
            p.host_stall += 0.125
            sp(BatchEndParam(epoch=0, nbatch=nbatch, eval_metric=None,
                             locals={"pipeline": p}))
    lines = [r.getMessage() for r in caplog.records]
    piped = [ln for ln in lines if "Pipeline:" in ln]
    assert len(piped) >= 2, lines
    assert "depth=2" in piped[0]
    # per-window stall, not cumulative: the init call (nbatch 1) baselines
    # at 0.125, the first fire (nbatch 5) covers two 0.125 pushes, the
    # second fire (nbatch 9) two more
    assert "host_stall=0.250s" in piped[0]
    assert "host_stall=0.250s" in piped[1]


def test_speedometer_interleaved_stream_keeps_stall_baseline(caplog):
    """A param from another callback stream (no pipeline in locals — e.g.
    score()) must not reset the stall baseline: the next pipelined window
    reports only ITS stall, not the run's whole accumulated total."""
    from collections import namedtuple
    from mxnet_tpu.callback import Speedometer
    BatchEndParam = namedtuple("BatchEndParams",
                               ["epoch", "nbatch", "eval_metric", "locals"])

    class _P(object):
        depth = 2
        host_stall = 0.0

    p = _P()
    sp = Speedometer(batch_size=16, frequent=4)
    with caplog.at_level(logging.INFO):
        for nbatch in (1, 3):
            p.host_stall += 1.0
            sp(BatchEndParam(epoch=0, nbatch=nbatch, eval_metric=None,
                             locals={"pipeline": p}))
        # interleaved pipeline-less stream (fresh count restarts windows)
        sp(BatchEndParam(epoch=0, nbatch=0, eval_metric=None, locals={}))
        for nbatch in (1, 3, 5):
            p.host_stall += 0.125
            sp(BatchEndParam(epoch=0, nbatch=nbatch, eval_metric=None,
                             locals={"pipeline": p}))
    piped = [r.getMessage() for r in caplog.records
             if "Pipeline:" in r.getMessage()]
    assert piped, caplog.records
    # baseline was set at the first init (stall=1.0) and must survive the
    # interleaved call: the fire covers 2.375 - 1.0. A clobbered baseline
    # (the bug) would report the whole 2.375s run total
    assert "host_stall=1.375s" in piped[0], piped


def test_speedometer_no_pipeline_suffix_when_eager(caplog):
    from collections import namedtuple
    from mxnet_tpu.callback import Speedometer
    BatchEndParam = namedtuple("BatchEndParams",
                               ["epoch", "nbatch", "eval_metric", "locals"])
    sp = Speedometer(batch_size=16, frequent=2)
    with caplog.at_level(logging.INFO):
        for nbatch in (1, 3, 5):
            sp(BatchEndParam(epoch=0, nbatch=nbatch, eval_metric=None,
                             locals={}))
    assert not any("Pipeline:" in (r.getMessage())
                   for r in caplog.records)


# -- in-place imperative invoke (satellite, python side) --------------------

def test_imperative_invoke_in_place_updates_existing_handles():
    from mxnet_tpu import c_api
    code, h_in = c_api.MXNDArrayCreate([3], 1, 0)
    assert code == 0
    code, _ = c_api.MXNDArraySyncCopyFromCPU(
        h_in, np.array([1.0, 2.0, 3.0], np.float32))
    assert code == 0
    code, h_out = c_api.MXNDArrayCreate([3], 1, 0)
    assert code == 0
    target_before = c_api._get(h_out)
    code, n = c_api.MXImperativeInvokeInPlace("square", [h_in], {}, [h_out])
    assert code == 0 and n == 1
    # same NDArray object, new data — the handle identity is the contract
    assert c_api._get(h_out) is target_before
    np.testing.assert_array_equal(c_api._get(h_out).asnumpy(),
                                  [1.0, 4.0, 9.0])


def test_imperative_invoke_in_place_count_mismatch_fails():
    from mxnet_tpu import c_api
    code, h_in = c_api.MXNDArrayCreate([3], 1, 0)
    assert code == 0
    code, h1 = c_api.MXNDArrayCreate([3], 1, 0)
    assert code == 0
    code, h2 = c_api.MXNDArrayCreate([3], 1, 0)
    assert code == 0
    code, err = c_api.MXImperativeInvokeInPlace("square", [h_in], {},
                                                [h1, h2])
    assert code != 0
    msg = c_api.MXGetLastError()
    assert "output array" in msg


def test_imperative_invoke_in_place_shape_mismatch_fails():
    from mxnet_tpu import c_api
    code, h_in = c_api.MXNDArrayCreate([3], 1, 0)
    assert code == 0
    code, h_out = c_api.MXNDArrayCreate([2, 3], 2, 0)
    assert code == 0
    before = c_api._get(h_out).asnumpy().copy()
    code, err = c_api.MXImperativeInvokeInPlace("square", [h_in], {},
                                                [h_out])
    assert code != 0
    assert "shape mismatch" in c_api.MXGetLastError()
    # the caller's array must be untouched on a refused write
    np.testing.assert_array_equal(c_api._get(h_out).asnumpy(), before)


def test_imperative_invoke_in_place_records_autograd():
    # the in-place path must record the CALLER's out arrays on the tape
    # (invoke(out=...)), not hidden temporaries — backward through the out
    # handle has to reach the inputs
    from mxnet_tpu import c_api, nd, autograd as ag
    x = nd.array(np.array([1.0, 2.0, 3.0], np.float32))
    out = nd.zeros((3,))
    gx = nd.zeros((3,))
    ag.mark_variables([x], [gx])
    h_in = c_api._new_handle(x)
    h_out = c_api._new_handle(out)
    with ag.train_section():
        code, n = c_api.MXImperativeInvokeInPlace("square", [h_in], {},
                                                  [h_out])
        assert code == 0 and n == 1
    ag.compute_gradient([out])
    np.testing.assert_allclose(gx.asnumpy(), [2.0, 4.0, 6.0])


def test_imperative_invoke_in_place_dtype_mismatch_fails():
    from mxnet_tpu import c_api
    from mxnet_tpu.ndarray import NDArray
    import jax.numpy as jnp
    code, h_in = c_api.MXNDArrayCreate([3], 1, 0)
    assert code == 0
    h_out = c_api._new_handle(NDArray(jnp.zeros((3,), jnp.int32)))
    code, err = c_api.MXImperativeInvokeInPlace("square", [h_in], {},
                                                [h_out])
    assert code != 0
    assert "dtype mismatch" in c_api.MXGetLastError()
    assert c_api._get(h_out).dtype == np.int32
