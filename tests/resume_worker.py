"""Subprocess target for the kill-and-resume integration test.

Trains a small deterministic MLP with periodic checkpoints and
``resume='auto'``; prints ``BATCH <n>`` after every dispatch so the parent
test knows when to SIGKILL it mid-epoch, and writes the final params to an
npz when (if) it survives to the end. Re-running the same command line after
a kill must produce bitwise-identical final params to an uninterrupted run.

Usage: python resume_worker.py <ckpt_prefix> <out_npz> <steps_per_dispatch>
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import sym


def _mlp():
    data = sym.Variable("data")
    net = sym.FullyConnected(data=data, num_hidden=16, name="fc1")
    net = sym.Activation(data=net, act_type="relu")
    net = sym.FullyConnected(data=net, num_hidden=4, name="fc2")
    return sym.SoftmaxOutput(data=net, name="softmax")


def _convnet(num_classes=4):
    data = sym.Variable("data")
    net = sym.Convolution(data=data, num_filter=8, kernel=(3, 3),
                          stride=(2, 2), pad=(1, 1), name="c1")
    net = sym.BatchNorm(data=net, fix_gamma=False, name="bn1")
    net = sym.Activation(data=net, act_type="relu")
    net = sym.Pooling(data=net, global_pool=True, kernel=(1, 1),
                      pool_type="avg")
    net = sym.Flatten(data=net)
    net = sym.FullyConnected(data=net, num_hidden=num_classes, name="fc")
    return sym.SoftmaxOutput(data=net, name="softmax")


def _make_train_iter():
    """NDArrayIter by default; RESUME_WORKER_IMAGE_REC=<path.rec> switches
    to the device-fed input tier — ImageRecordIter through the decode
    worker pool (RESUME_WORKER_DATA_WORKERS, default 2) with deterministic
    shuffle — so the SIGKILL test covers resume fast-forward THROUGH the
    worker-parallel pipeline (docs/perf.md "Device-fed input pipeline")."""
    rec = os.environ.get("RESUME_WORKER_IMAGE_REC")
    if rec:
        nw = int(os.environ.get("RESUME_WORKER_DATA_WORKERS", "2") or 2)
        train = mx.image.ImageRecordIter(
            path_imgrec=rec, data_shape=(3, 24, 24), batch_size=16,
            shuffle=True, seed=5, rand_crop=True, rand_mirror=True,
            resize=28, num_workers=nw)
        return train, _convnet()
    rng = np.random.default_rng(3)
    X = rng.normal(size=(256, 10)).astype(np.float32)
    w = rng.normal(size=(10, 4)).astype(np.float32)
    y = np.argmax(X @ w, axis=1).astype(np.float32)
    return mx.io.NDArrayIter(X, y, batch_size=16), _mlp()  # 16 batches/epoch


def main(prefix, out_npz, k):
    # async-checkpoint kill test support: the parent arms a delay on the
    # writer thread (via env, so the SIGKILL lands mid-async-save while
    # the train loop races ahead); MXTPU_ASYNC_CKPT itself is read by fit
    delay = float(os.environ.get("RESUME_WORKER_ASYNC_DELAY", "0") or 0)
    nth = int(os.environ.get("RESUME_WORKER_ASYNC_DELAY_NTH", "0") or 0)
    if delay > 0 and nth > 0:
        from mxnet_tpu import faults
        faults.inject("ckpt.async_write", nth=nth, kind="delay",
                      delay=delay)
    # pace the first epoch-0 saves so the parent can rely on save #N-1
    # being durably on disk before the delayed save #N's job starts
    drain_until = int(os.environ.get("RESUME_WORKER_DRAIN_UNTIL", "0") or 0)
    ckpt_arg = prefix
    mgr = None
    if drain_until:
        from mxnet_tpu.model import CheckpointManager
        mgr = CheckpointManager(prefix, keep=3)
        ckpt_arg = mgr
    mx.random.seed(7)
    train, net = _make_train_iter()
    # RESUME_WORKER_CONTEXTS=N: train data-parallel over N devices (the
    # 8-device bitwise kill-and-resume test — docs/perf.md "Data-parallel
    # scaling"); the conftest-style XLA_FLAGS env is the parent's job
    nctx = int(os.environ.get("RESUME_WORKER_CONTEXTS", "1") or 1)
    ctx = [mx.cpu(i) for i in range(nctx)] if nctx > 1 else mx.cpu()
    mod = mx.mod.Module(net, context=ctx)

    def cb(param):
        print("BATCH %d.%d" % (param.epoch, param.nbatch), flush=True)
        if mgr is not None and param.epoch == 0 \
                and param.nbatch < drain_until:
            mgr.drain()

    from mxnet_tpu import lr_scheduler
    # RESUME_WORKER_CKPT_EVERY overrides the cadence (the SIGTERM test
    # sets it huge so only epoch-end saves exist — any mid-epoch tag then
    # proves the graceful-preemption emergency checkpoint ran);
    # RESUME_WORKER_TERM_OK=1 turns TrainingPreemptedError into a clean
    # "PREEMPTED" exit so the parent can tell graceful from crashed.
    every = int(os.environ.get("RESUME_WORKER_CKPT_EVERY", "4") or 4)
    try:
        mod.fit(train, num_epoch=2, steps_per_dispatch=k,
                optimizer_params={"learning_rate": 0.1, "momentum": 0.9,
                                  "lr_scheduler":
                                  lr_scheduler.FactorScheduler(
                                      step=10, factor=0.5)},
                batch_end_callback=cb,
                checkpoint_prefix=ckpt_arg, checkpoint_every_n_batches=every,
                resume="auto")
    except mx.TrainingPreemptedError as e:
        if os.environ.get("RESUME_WORKER_TERM_OK"):
            print("PREEMPTED %s" % e.tag, flush=True)
            return
        raise
    arg, aux = mod.get_params()
    np.savez(out_npz, **{n: v.asnumpy() for n, v in arg.items()})
    print("DONE", flush=True)


if __name__ == "__main__":
    main(sys.argv[1], sys.argv[2], int(sys.argv[3]))
