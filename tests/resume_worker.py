"""Subprocess target for the kill-and-resume integration test.

Trains a small deterministic MLP with periodic checkpoints and
``resume='auto'``; prints ``BATCH <n>`` after every dispatch so the parent
test knows when to SIGKILL it mid-epoch, and writes the final params to an
npz when (if) it survives to the end. Re-running the same command line after
a kill must produce bitwise-identical final params to an uninterrupted run.

Usage: python resume_worker.py <ckpt_prefix> <out_npz> <steps_per_dispatch>
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import sym


def _mlp():
    data = sym.Variable("data")
    net = sym.FullyConnected(data=data, num_hidden=16, name="fc1")
    net = sym.Activation(data=net, act_type="relu")
    net = sym.FullyConnected(data=net, num_hidden=4, name="fc2")
    return sym.SoftmaxOutput(data=net, name="softmax")


def main(prefix, out_npz, k):
    mx.random.seed(7)
    rng = np.random.default_rng(3)
    X = rng.normal(size=(256, 10)).astype(np.float32)
    w = rng.normal(size=(10, 4)).astype(np.float32)
    y = np.argmax(X @ w, axis=1).astype(np.float32)
    train = mx.io.NDArrayIter(X, y, batch_size=16)  # 16 batches/epoch
    mod = mx.mod.Module(_mlp(), context=mx.cpu())

    def cb(param):
        print("BATCH %d.%d" % (param.epoch, param.nbatch), flush=True)

    from mxnet_tpu import lr_scheduler
    mod.fit(train, num_epoch=2, steps_per_dispatch=k,
            optimizer_params={"learning_rate": 0.1, "momentum": 0.9,
                              "lr_scheduler": lr_scheduler.FactorScheduler(
                                  step=10, factor=0.5)},
            batch_end_callback=cb,
            checkpoint_prefix=prefix, checkpoint_every_n_batches=4,
            resume="auto")
    arg, aux = mod.get_params()
    np.savez(out_npz, **{n: v.asnumpy() for n, v in arg.items()})
    print("DONE", flush=True)


if __name__ == "__main__":
    main(sys.argv[1], sys.argv[2], int(sys.argv[3]))
