"""Worker program for the multi-process dist_sync tests.

Spawned by tests/test_dist_sync.py through tools/launch.py (the reference's
local tracker path, ref: tools/launch.py:46-78 + tests/nightly/
dist_sync_kvstore.py:30-45 + dist_lenet.py). Runs on the CPU backend with
one device per process; gradient aggregation crosses processes via Gloo.

Modes:
  kvstore — closed-form BSP push/pull assertions (every worker pushes a
            known value; the aggregate is exactly computable)
  lenet   — Module.fit with kvstore='dist_sync' on rank-partitioned
            synthetic data; asserts accuracy, the in-step-psum fused path,
            and cross-worker parameter consistency
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))
os.environ.pop("XLA_FLAGS", None)

import jax
jax.config.update("jax_platforms", "cpu")

import numpy as np


def main():
    mode = sys.argv[1]
    import mxnet_tpu as mx
    assert mx.tools_init_distributed(), "MXTPU_* env missing"
    rank = jax.process_index()
    nproc = jax.process_count()
    assert nproc >= 2, "dist test needs >= 2 processes"

    if mode == "kvstore":
        run_kvstore(mx, rank, nproc)
    elif mode == "lenet":
        run_lenet(mx, rank, nproc)
    elif mode == "deadworker":
        run_deadworker(mx, rank, nproc)
        # skip atexit/jax.distributed shutdown: the dead peer would make
        # the orderly shutdown barrier hang (ref: barrier_before_exit,
        # kvstore_dist.h:50-57)
        print("RANK-%d-PASS" % rank, flush=True)
        os._exit(0)
    elif mode == "resume":
        run_resume(mx, rank, nproc)
    else:
        raise SystemExit("unknown mode %r" % mode)
    print("RANK-%d-PASS" % rank, flush=True)


def run_kvstore(mx, rank, nproc):
    """Closed-form BSP semantics (ref: dist_sync_kvstore.py:30-45)."""
    from mxnet_tpu import nd
    kv = mx.kv.create("dist_sync")
    assert kv.rank == rank and kv.num_workers == nproc
    shape = (3, 4)

    # no-updater push: store <- sum over workers of (rank+1)
    kv.init(3, nd.ones(shape))
    kv.push(3, nd.ones(shape) * (rank + 1))
    out = nd.zeros(shape)
    kv.pull(3, out=out)
    expect = sum(r + 1 for r in range(nproc))
    np.testing.assert_allclose(out.asnumpy(), expect * np.ones(shape))

    # updater path: store += aggregated push, repeated (the reference's
    # accumulation check)
    kv2 = mx.kv.create("dist_sync")
    kv2._set_updater(lambda key, recv, stored: stored.__iadd__(recv))
    kv2.init("acc", nd.zeros(shape))
    nrepeat = 3
    for i in range(nrepeat):
        kv2.push("acc", nd.ones(shape) * (rank + 1))
    o = nd.zeros(shape)
    kv2.pull("acc", out=o)
    np.testing.assert_allclose(o.asnumpy(),
                               nrepeat * expect * np.ones(shape))

    # multi-device local list push combines with cross-worker reduce
    kv3 = mx.kv.create("dist_sync")
    kv3.init(9, nd.zeros(shape))
    kv3.push(9, [nd.ones(shape) * (rank + 1), nd.ones(shape) * (rank + 1)])
    o3 = nd.zeros(shape)
    kv3.pull(9, out=o3)
    np.testing.assert_allclose(o3.asnumpy(), 2 * expect * np.ones(shape))

    # workers whose host values diverged (per-rank seeding) must still
    # start from ONE authoritative copy: init broadcasts rank 0's value
    kv4 = mx.kv.create("dist_sync")
    kv4.init("b", nd.ones(shape) * (rank + 1) * 10)
    o4 = nd.zeros(shape)
    kv4.pull("b", out=o4)
    np.testing.assert_allclose(o4.asnumpy(), 10 * np.ones(shape))

    # liveness: every peer is beating over the coordination service, so
    # no node is dead (ref contract: kvstore_dist.h:159-168 GetDeadNodes)
    kv.barrier()                 # all ranks published their first beat
    assert kv.num_dead_node(0, timeout_sec=60) == 0, \
        "healthy cluster reported dead nodes"
    # a rank that never existed counts dead against a tight horizon
    hb = kv._heartbeat
    assert hb is not None and hb.dead_nodes(nproc + 1, timeout_sec=60) >= 1

    kv.barrier()


def run_deadworker(mx, rank, nproc):
    """Fault injection: the highest rank SIGKILLs itself; survivors must
    see num_dead_node > 0 within the heartbeat timeout (the scenario
    kvstore_dist.h:159-168's GetDeadNodes exists for). Rank 0 hosts the
    coordination service, so the victim is the LAST rank."""
    import signal
    import time

    kv = mx.kv.create("dist_sync")
    assert kv.num_workers == nproc
    kv.barrier()                     # every rank has published its beat
    assert kv.num_dead_node(0, timeout_sec=60) == 0, \
        "cluster reported dead nodes before the kill"

    victim = nproc - 1
    if rank == victim:
        os.kill(os.getpid(), signal.SIGKILL)     # no goodbye, no cleanup
        raise AssertionError("unreachable")

    # survivors: poll until the victim's heartbeat goes stale. Beat
    # interval is 2s; a 4s staleness horizon flags it on the first or
    # second missed beat. NO barriers from here on (the peer is gone).
    deadline = time.time() + 90
    dead = 0
    while time.time() < deadline:
        dead = kv.num_dead_node(0, timeout_sec=4)
        if dead >= 1:
            break
        time.sleep(1)
    assert dead >= 1, "rank %d never detected the killed worker" % rank


def run_resume(mx, rank, nproc):
    """Checkpoint mid-training, resume in a FRESH module, finish training
    (ref: Module.save_checkpoint/load + --load-epoch resume,
    example/image-classification/common/fit.py)."""
    from mxnet_tpu.io import NDArrayIter

    n_class, dim, n_per = 8, 32, 256
    rng = np.random.RandomState(7)
    templates = rng.randn(n_class, dim).astype(np.float32) * 3
    labels_all = np.arange(n_class * n_per) % n_class
    x_all = (templates[labels_all]
             + rng.randn(len(labels_all), dim).astype(np.float32) * 0.5)
    x, y = x_all[rank::nproc], labels_all[rank::nproc].astype(np.float32)

    def net():
        data = mx.sym.Variable("data")
        h = mx.sym.FullyConnected(data, name="fc1", num_hidden=64)
        h = mx.sym.Activation(h, name="relu1", act_type="relu")
        h = mx.sym.FullyConnected(h, name="fc2", num_hidden=n_class)
        return mx.sym.SoftmaxOutput(h, name="softmax")

    prefix = os.path.join(os.environ.get("MXTPU_TEST_TMPDIR", "/tmp"),
                          "dist_resume")
    mid_epoch = 3

    mod = mx.mod.Module(net())
    train = NDArrayIter(x, y, batch_size=64, shuffle=False)
    mod.fit(train, num_epoch=mid_epoch, kvstore="dist_sync",
            optimizer="sgd",
            optimizer_params={"learning_rate": 0.1, "momentum": 0.9},
            initializer=mx.initializer.Xavier())
    # replicas are consistent, so every rank saves an identical checkpoint;
    # rank 0's copy is authoritative (ref: per-rank prefixes, fit.py:25-44)
    if rank == 0:
        mod.save_checkpoint(prefix, mid_epoch, save_optimizer_states=True)
    kv0 = mx.kv.create("dist_sync")
    kv0.barrier()                   # checkpoint visible before anyone loads

    # resume in a FRESH module from the saved state (mid-training restart)
    mod2 = mx.mod.Module.load(prefix, mid_epoch,
                              load_optimizer_states=True)
    train.reset()
    mod2.fit(train, num_epoch=8, begin_epoch=mid_epoch,
             kvstore="dist_sync", optimizer="sgd",
             optimizer_params={"learning_rate": 0.1, "momentum": 0.9})
    score = mod2.score(NDArrayIter(x, y, batch_size=64), "acc")
    acc = dict(score)["accuracy"]
    assert acc >= 0.95, "rank %d resumed accuracy %.3f < 0.95" % (rank, acc)

    # resumed replicas must agree across workers
    arg_params, _ = mod2.get_params()
    blob = np.concatenate([arg_params[k].asnumpy().ravel()
                           for k in sorted(arg_params)])
    kv = mx.kv.create("dist_sync")
    tot = mx.nd.zeros(blob.shape)
    kv.init("resumecheck", tot)
    kv.push("resumecheck", mx.nd.array(blob))
    kv.pull("resumecheck", out=tot)
    np.testing.assert_allclose(tot.asnumpy(), nproc * blob, rtol=1e-6,
                               err_msg="resumed replicas diverged")


def run_lenet(mx, rank, nproc):
    """Distributed training to accuracy (ref: dist_lenet.py / test_mlp)."""
    from mxnet_tpu.io import NDArrayIter

    # rank-partitioned separable data: class templates + noise
    n_class, dim, n_per = 8, 32, 256
    rng = np.random.RandomState(7)  # same on all ranks
    templates = rng.randn(n_class, dim).astype(np.float32) * 3
    labels_all = np.arange(n_class * n_per) % n_class
    x_all = (templates[labels_all]
             + rng.randn(len(labels_all), dim).astype(np.float32) * 0.5)
    # each worker sees ONLY its shard (ref: part_index/num_parts)
    x, y = x_all[rank::nproc], labels_all[rank::nproc].astype(np.float32)

    data = mx.sym.Variable("data")
    h = mx.sym.FullyConnected(data, name="fc1", num_hidden=64)
    h = mx.sym.Activation(h, name="relu1", act_type="relu")
    # dropout exercises RNG threading through the multi-host fused step
    h = mx.sym.Dropout(h, name="drop1", p=0.2)
    h = mx.sym.FullyConnected(h, name="fc2", num_hidden=n_class)
    out = mx.sym.SoftmaxOutput(h, name="softmax")

    mod = mx.mod.Module(out)
    train = NDArrayIter(x, y, batch_size=64, shuffle=False)
    mod.fit(train, num_epoch=8, kvstore="dist_sync",
            optimizer="sgd",
            optimizer_params={"learning_rate": 0.1, "momentum": 0.9},
            initializer=mx.initializer.Xavier())

    # the dist bail-out is gone: fit must have used the fused in-step-psum
    # path over the global mesh
    assert mod._fused is not None, "dist fit fell back to the slow path"
    from mxnet_tpu.parallel.mesh import is_multiprocess
    assert is_multiprocess(mod._fused.mesh), "fused step not multi-host"

    score = mod.score(NDArrayIter(x, y, batch_size=64), "acc")
    acc = dict(score)["accuracy"]
    assert acc >= 0.95, "rank %d accuracy %.3f < 0.95" % (rank, acc)

    # replicas must not diverge: params bitwise identical across workers
    arg_params, _ = mod.get_params()
    blob = np.concatenate([arg_params[k].asnumpy().ravel()
                           for k in sorted(arg_params)])
    kv = mx.kv.create("dist_sync")  # fresh store: no updater installed
    mine = mx.nd.array(blob)
    tot = mx.nd.zeros(blob.shape)
    kv.init("paramcheck", tot)
    kv.push("paramcheck", mine)
    kv.pull("paramcheck", out=tot)
    np.testing.assert_allclose(tot.asnumpy(), nproc * blob, rtol=1e-6,
                               err_msg="worker replicas diverged")


if __name__ == "__main__":
    main()
