"""Worker program for the multi-process dist_sync tests.

Spawned by tests/test_dist_sync.py through tools/launch.py (the reference's
local tracker path, ref: tools/launch.py:46-78 + tests/nightly/
dist_sync_kvstore.py:30-45 + dist_lenet.py). Runs on the CPU backend with
one device per process; gradient aggregation crosses processes via Gloo.

Modes:
  kvstore — closed-form BSP push/pull assertions (every worker pushes a
            known value; the aggregate is exactly computable)
  lenet   — Module.fit with kvstore='dist_sync' on rank-partitioned
            synthetic data; asserts accuracy, the in-step-psum fused path,
            and cross-worker parameter consistency
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))
os.environ.pop("XLA_FLAGS", None)

import jax
jax.config.update("jax_platforms", "cpu")

import numpy as np


def main():
    mode = sys.argv[1]
    import mxnet_tpu as mx
    assert mx.tools_init_distributed(), "MXTPU_* env missing"
    rank = jax.process_index()
    nproc = jax.process_count()
    assert nproc >= 2, "dist test needs >= 2 processes"

    if mode == "kvstore":
        run_kvstore(mx, rank, nproc)
    elif mode == "lenet":
        run_lenet(mx, rank, nproc)
    elif mode == "deadworker":
        run_deadworker(mx, rank, nproc)
        # skip atexit/jax.distributed shutdown: the dead peer would make
        # the orderly shutdown barrier hang (ref: barrier_before_exit,
        # kvstore_dist.h:50-57)
        print("RANK-%d-PASS" % rank, flush=True)
        os._exit(0)
    elif mode == "resume":
        run_resume(mx, rank, nproc)
    elif mode == "elastic":
        run_elastic(mx, rank, nproc)
        # same as deadworker: one peer is gone, the orderly shutdown
        # barrier would hang
        print("RANK-%d-PASS" % rank, flush=True)
        os._exit(0)
    else:
        raise SystemExit("unknown mode %r" % mode)
    print("RANK-%d-PASS" % rank, flush=True)


def _survivor_sync(rank, nproc, victim, tag):
    """Completion sync over the raw coordination KV for tests that lose a
    worker: rank 0 hosts the coordination service, so it must exit LAST —
    otherwise a survivor still polling the plane aborts on
    connection-reset before its PASS line (jax's distributed client
    treats coordination-service loss as fatal). The ring barrier is no
    use here: it would wait on the dead victim."""
    import time

    from jax._src.distributed import global_state
    c = global_state.client
    try:
        # "ok", not "1": sub-2-byte values segfault jaxlib's dir-get
        c.key_value_set("%s_done/%d" % (tag, rank), "ok",
                        allow_overwrite=True)
    except Exception:
        return
    if rank != 0:
        return
    want = ["%s_done/%d" % (tag, r) for r in range(nproc) if r != victim]
    deadline = time.time() + 60
    while time.time() < deadline:
        try:
            got = c.key_value_dir_get("%s_done/" % tag)
        except Exception:
            return
        items = dict(got.items() if hasattr(got, "items") else got)
        if all(k in items for k in want):
            return
        time.sleep(0.2)


def run_kvstore(mx, rank, nproc):
    """Closed-form BSP semantics (ref: dist_sync_kvstore.py:30-45)."""
    from mxnet_tpu import nd
    kv = mx.kv.create("dist_sync")
    assert kv.rank == rank and kv.num_workers == nproc
    shape = (3, 4)

    # no-updater push: store <- sum over workers of (rank+1)
    kv.init(3, nd.ones(shape))
    kv.push(3, nd.ones(shape) * (rank + 1))
    out = nd.zeros(shape)
    kv.pull(3, out=out)
    expect = sum(r + 1 for r in range(nproc))
    np.testing.assert_allclose(out.asnumpy(), expect * np.ones(shape))

    # updater path: store += aggregated push, repeated (the reference's
    # accumulation check)
    kv2 = mx.kv.create("dist_sync")
    kv2._set_updater(lambda key, recv, stored: stored.__iadd__(recv))
    kv2.init("acc", nd.zeros(shape))
    nrepeat = 3
    for i in range(nrepeat):
        kv2.push("acc", nd.ones(shape) * (rank + 1))
    o = nd.zeros(shape)
    kv2.pull("acc", out=o)
    np.testing.assert_allclose(o.asnumpy(),
                               nrepeat * expect * np.ones(shape))

    # multi-device local list push combines with cross-worker reduce
    kv3 = mx.kv.create("dist_sync")
    kv3.init(9, nd.zeros(shape))
    kv3.push(9, [nd.ones(shape) * (rank + 1), nd.ones(shape) * (rank + 1)])
    o3 = nd.zeros(shape)
    kv3.pull(9, out=o3)
    np.testing.assert_allclose(o3.asnumpy(), 2 * expect * np.ones(shape))

    # workers whose host values diverged (per-rank seeding) must still
    # start from ONE authoritative copy: init broadcasts rank 0's value
    kv4 = mx.kv.create("dist_sync")
    kv4.init("b", nd.ones(shape) * (rank + 1) * 10)
    o4 = nd.zeros(shape)
    kv4.pull("b", out=o4)
    np.testing.assert_allclose(o4.asnumpy(), 10 * np.ones(shape))

    # liveness: every peer is beating over the coordination service, so
    # no node is dead (ref contract: kvstore_dist.h:159-168 GetDeadNodes)
    kv.barrier()                 # all ranks published their first beat
    assert kv.num_dead_node(0, timeout_sec=60) == 0, \
        "healthy cluster reported dead nodes"
    # a rank that never existed counts dead against a tight horizon —
    # with no startup grace: the phantom never published a beat, so the
    # grace window is the only thing that could excuse it
    hb = kv._heartbeat
    assert hb is not None
    grace = hb.startup_grace
    hb.startup_grace = 0.0
    try:
        assert hb.dead_nodes(nproc + 1, timeout_sec=60) >= 1
    finally:
        hb.startup_grace = grace

    kv.barrier()


def run_deadworker(mx, rank, nproc):
    """Fault injection: the highest rank SIGKILLs itself; survivors must
    see num_dead_node > 0 within the heartbeat timeout (the scenario
    kvstore_dist.h:159-168's GetDeadNodes exists for). Rank 0 hosts the
    coordination service, so the victim is the LAST rank."""
    import signal
    import time

    kv = mx.kv.create("dist_sync")
    assert kv.num_workers == nproc
    kv.barrier()                     # every rank has published its beat
    assert kv.num_dead_node(0, timeout_sec=60) == 0, \
        "cluster reported dead nodes before the kill"

    victim = nproc - 1
    if rank == victim:
        os.kill(os.getpid(), signal.SIGKILL)     # no goodbye, no cleanup
        raise AssertionError("unreachable")

    # survivors: poll until the victim's heartbeat goes stale. Beat
    # interval is 2s; a 4s staleness horizon flags it on the first or
    # second missed beat. NO barriers from here on (the peer is gone).
    deadline = time.time() + 90
    dead = 0
    while time.time() < deadline:
        dead = kv.num_dead_node(0, timeout_sec=4)
        if dead >= 1:
            break
        time.sleep(1)
    assert dead >= 1, "rank %d never detected the killed worker" % rank
    _survivor_sync(rank, nproc, victim, "deadworker")


def run_resume(mx, rank, nproc):
    """Checkpoint mid-training, resume in a FRESH module, finish training
    (ref: Module.save_checkpoint/load + --load-epoch resume,
    example/image-classification/common/fit.py)."""
    from mxnet_tpu.io import NDArrayIter

    n_class, dim, n_per = 8, 32, 256
    rng = np.random.RandomState(7)
    templates = rng.randn(n_class, dim).astype(np.float32) * 3
    labels_all = np.arange(n_class * n_per) % n_class
    x_all = (templates[labels_all]
             + rng.randn(len(labels_all), dim).astype(np.float32) * 0.5)
    x, y = x_all[rank::nproc], labels_all[rank::nproc].astype(np.float32)

    def net():
        data = mx.sym.Variable("data")
        h = mx.sym.FullyConnected(data, name="fc1", num_hidden=64)
        h = mx.sym.Activation(h, name="relu1", act_type="relu")
        h = mx.sym.FullyConnected(h, name="fc2", num_hidden=n_class)
        return mx.sym.SoftmaxOutput(h, name="softmax")

    prefix = os.path.join(os.environ.get("MXTPU_TEST_TMPDIR", "/tmp"),
                          "dist_resume")
    mid_epoch = 3

    mod = mx.mod.Module(net())
    train = NDArrayIter(x, y, batch_size=64, shuffle=False)
    mod.fit(train, num_epoch=mid_epoch, kvstore="dist_sync",
            optimizer="sgd",
            optimizer_params={"learning_rate": 0.1, "momentum": 0.9},
            initializer=mx.initializer.Xavier())
    # replicas are consistent, so every rank saves an identical checkpoint;
    # rank 0's copy is authoritative (ref: per-rank prefixes, fit.py:25-44)
    if rank == 0:
        mod.save_checkpoint(prefix, mid_epoch, save_optimizer_states=True)
    kv0 = mx.kv.create("dist_sync")
    kv0.barrier()                   # checkpoint visible before anyone loads

    # resume in a FRESH module from the saved state (mid-training restart)
    mod2 = mx.mod.Module.load(prefix, mid_epoch,
                              load_optimizer_states=True)
    train.reset()
    mod2.fit(train, num_epoch=8, begin_epoch=mid_epoch,
             kvstore="dist_sync", optimizer="sgd",
             optimizer_params={"learning_rate": 0.1, "momentum": 0.9})
    score = mod2.score(NDArrayIter(x, y, batch_size=64), "acc")
    acc = dict(score)["accuracy"]
    assert acc >= 0.95, "rank %d resumed accuracy %.3f < 0.95" % (rank, acc)

    # resumed replicas must agree across workers
    arg_params, _ = mod2.get_params()
    blob = np.concatenate([arg_params[k].asnumpy().ravel()
                           for k in sorted(arg_params)])
    kv = mx.kv.create("dist_sync")
    tot = mx.nd.zeros(blob.shape)
    kv.init("resumecheck", tot)
    kv.push("resumecheck", mx.nd.array(blob))
    kv.pull("resumecheck", out=tot)
    np.testing.assert_allclose(tot.asnumpy(), nproc * blob, rtol=1e-6,
                               err_msg="resumed replicas diverged")


def run_elastic(mx, rank, nproc):
    """Worker-loss survival end to end (docs/robustness.md "Elastic
    distributed training"): the highest rank SIGKILLs itself mid-epoch
    via the kv.worker_die fault site; survivors must take an emergency
    checkpoint, re-form the ring at N-1, re-shard the data, finish
    training to accuracy — and a fresh resume from the same prefix must
    be bitwise-identical to the live post-reform parameters."""
    import glob

    from mxnet_tpu import faults
    from mxnet_tpu.io import NDArrayIter

    n_class, dim, n_per = 8, 32, 192
    num_epoch, batch_size = 8, 64
    rng = np.random.RandomState(7)  # same on all ranks
    templates = rng.randn(n_class, dim).astype(np.float32) * 3
    labels_all = np.arange(n_class * n_per) % n_class
    x_all = (templates[labels_all]
             + rng.randn(len(labels_all), dim).astype(np.float32) * 0.5)

    class ElasticIter(NDArrayIter):
        """fit's re-shard hook: re-cut this worker's shard from the FULL
        dataset at the post-reform (index, size)."""

        def reshard_workers(self, part_index, num_parts):
            ElasticIter.__init__(
                self, x_all[part_index::num_parts],
                labels_all[part_index::num_parts].astype(np.float32),
                batch_size=batch_size, shuffle=False)

    def net():
        data = mx.sym.Variable("data")
        h = mx.sym.FullyConnected(data, name="fc1", num_hidden=64)
        h = mx.sym.Activation(h, name="relu1", act_type="relu")
        h = mx.sym.FullyConnected(h, name="fc2", num_hidden=n_class)
        return mx.sym.SoftmaxOutput(h, name="softmax")

    # per-rank prefix dirs: the leader's checkpoint blob is imported
    # under the LEADER's file names, which must not collide with this
    # rank's own pre-reform saves
    prefix = os.path.join(os.environ.get("MXTPU_TEST_TMPDIR", "/tmp"),
                          "r%d" % rank, "elastic")
    opt_params = {"learning_rate": 0.1, "momentum": 0.9}

    # rank 0 hosts the coordination service, so the victim is the LAST
    # rank. Ring op #30 = 4 init broadcasts + 26 train-step allreduces =
    # mid-epoch 3 (8 steps/epoch on a 512-sample shard) — the kill lands
    # between checkpointable batch boundaries
    victim = nproc - 1
    if rank == victim:
        faults.inject("kv.worker_die", nth=30, kind="die")

    import time

    mod = mx.mod.Module(net())
    train = ElasticIter(x_all[rank::nproc],
                        labels_all[rank::nproc].astype(np.float32),
                        batch_size=batch_size, shuffle=False)
    t0 = time.time()
    mod.fit(train, num_epoch=num_epoch, kvstore="dist_sync",
            optimizer="sgd", optimizer_params=opt_params,
            initializer=mx.initializer.Xavier(),
            checkpoint_prefix=prefix, checkpoint_keep=50)
    fit_s = time.time() - t0
    assert rank != victim, "victim outlived its SIGKILL"

    # survivors: exactly one re-form, membership shrank to N-1
    kv = mod._kvstore
    assert kv is not None and kv.reforms == 1, \
        "rank %d: expected 1 ring re-form, saw %r" % (rank, kv.reforms)
    assert kv.num_workers == nproc - 1, \
        "rank %d: ring did not shrink to %d" % (rank, nproc - 1)

    # the mid-kill emergency checkpoint is durably on disk (b > 0: only
    # the emergency path saves mid-epoch in this run)
    mids = [f for f in glob.glob(prefix + "-e*-b*.params")
            if not f.endswith("-b00000000.params")]
    assert mids, "rank %d: no mid-epoch emergency checkpoint" % rank

    # training finished to accuracy despite losing a worker mid-run
    score = mod.score(NDArrayIter(x_all, labels_all.astype(np.float32),
                                  batch_size=batch_size), "acc")
    acc = dict(score)["accuracy"]
    assert acc >= 0.90, "rank %d accuracy %.3f < 0.90" % (rank, acc)

    # survivors' replicas agree bitwise-identically: the fresh store sees
    # the RE-FORMED shared ring, so the sum spans nproc-1 members
    arg_live, _ = mod.get_params()
    blob = np.concatenate([arg_live[k].asnumpy().ravel()
                           for k in sorted(arg_live)])
    kvc = mx.kv.create("dist_sync")
    assert kvc.num_workers == nproc - 1
    tot = mx.nd.zeros(blob.shape)
    kvc.init("elasticcheck", tot)
    kvc.push("elasticcheck", mx.nd.array(blob))
    kvc.pull("elasticcheck", out=tot)
    np.testing.assert_allclose(tot.asnumpy(), (nproc - 1) * blob,
                               rtol=1e-6,
                               err_msg="survivor replicas diverged")

    # a FRESH module resuming from the prefix reproduces the live
    # post-reform state bitwise (resume='auto' lands on the final
    # epoch-end tag, so the epoch loop is already complete)
    mod2 = mx.mod.Module(net())
    train.reset()
    mod2.fit(train, num_epoch=num_epoch, kvstore="dist_sync",
             optimizer="sgd", optimizer_params=opt_params,
             initializer=mx.initializer.Xavier(),
             checkpoint_prefix=prefix, resume="auto")
    arg_res, _ = mod2.get_params()
    for name in sorted(arg_live):
        assert (arg_res[name].asnumpy().tobytes()
                == arg_live[name].asnumpy().tobytes()), \
            "rank %d: resumed %r differs from live state" % (rank, name)

    # machine-readable line for tools/dist_gate.py: collective wall time
    # + post-reform membership (the dataset is partitioned, so aggregate
    # throughput = num_epoch * full dataset / max survivor fit_s)
    print("RANK-%d-ELASTIC-STATS fit_s=%.3f epochs=%d samples=%d "
          "reforms=%d workers=%d"
          % (rank, fit_s, num_epoch, len(x_all), kv.reforms,
             kv.num_workers), flush=True)
    _survivor_sync(rank, nproc, victim, "elastic")


def run_lenet(mx, rank, nproc):
    """Distributed training to accuracy (ref: dist_lenet.py / test_mlp)."""
    from mxnet_tpu.io import NDArrayIter

    # rank-partitioned separable data: class templates + noise
    n_class, dim, n_per = 8, 32, 256
    rng = np.random.RandomState(7)  # same on all ranks
    templates = rng.randn(n_class, dim).astype(np.float32) * 3
    labels_all = np.arange(n_class * n_per) % n_class
    x_all = (templates[labels_all]
             + rng.randn(len(labels_all), dim).astype(np.float32) * 0.5)
    # each worker sees ONLY its shard (ref: part_index/num_parts)
    x, y = x_all[rank::nproc], labels_all[rank::nproc].astype(np.float32)

    data = mx.sym.Variable("data")
    h = mx.sym.FullyConnected(data, name="fc1", num_hidden=64)
    h = mx.sym.Activation(h, name="relu1", act_type="relu")
    # dropout exercises RNG threading through the multi-host fused step
    h = mx.sym.Dropout(h, name="drop1", p=0.2)
    h = mx.sym.FullyConnected(h, name="fc2", num_hidden=n_class)
    out = mx.sym.SoftmaxOutput(h, name="softmax")

    mod = mx.mod.Module(out)
    train = NDArrayIter(x, y, batch_size=64, shuffle=False)
    mod.fit(train, num_epoch=8, kvstore="dist_sync",
            optimizer="sgd",
            optimizer_params={"learning_rate": 0.1, "momentum": 0.9},
            initializer=mx.initializer.Xavier())

    # the dist bail-out is gone: fit must have used the fused path with
    # the cross-worker gradient reduction wired into every dispatch
    assert mod._fused is not None, "dist fit fell back to the slow path"
    assert mod._fused.dist_reduce is not None, \
        "fused step not wired to the cross-worker reduction"

    score = mod.score(NDArrayIter(x, y, batch_size=64), "acc")
    acc = dict(score)["accuracy"]
    assert acc >= 0.95, "rank %d accuracy %.3f < 0.95" % (rank, acc)

    # replicas must not diverge: params bitwise identical across workers
    arg_params, _ = mod.get_params()
    blob = np.concatenate([arg_params[k].asnumpy().ravel()
                           for k in sorted(arg_params)])
    kv = mx.kv.create("dist_sync")  # fresh store: no updater installed
    mine = mx.nd.array(blob)
    tot = mx.nd.zeros(blob.shape)
    kv.init("paramcheck", tot)
    kv.push("paramcheck", mine)
    kv.pull("paramcheck", out=tot)
    np.testing.assert_allclose(tot.asnumpy(), nproc * blob, rtol=1e-6,
                               err_msg="worker replicas diverged")


if __name__ == "__main__":
    main()
