"""Elastic control-plane ring, tier-1 (docs/robustness.md "Elastic
distributed training"). Threads stand in for worker processes over the
in-memory LocalClient plane; liveness is explicit (mark_dead), polling
interval is zero, and every fault fires at an exact call count — so no
test ever sleeps its way to a verdict and no failure mode can hang.
"""
import threading

import numpy as np
import pytest

from mxnet_tpu import faults
from mxnet_tpu.dist_ring import DIST_HEALTH, LocalClient, Ring
from mxnet_tpu.kvstore import KVStoreTimeoutError, WorkerLostError


@pytest.fixture(autouse=True)
def _clean():
    faults.clear()
    DIST_HEALTH.reset()
    yield
    faults.clear()
    DIST_HEALTH.reset()


def _rings(client, members, **kw):
    kw.setdefault("poll", 0.0)
    kw.setdefault("op_timeout", 30.0)
    return {r: Ring(client, r, members, **kw) for r in members}


def _run(fns):
    """Run one callable per worker on its own thread; re-raise the first
    failure (never swallow a worker's assertion)."""
    out, errs = {}, []

    def wrap(r, fn):
        try:
            out[r] = fn()
        except BaseException as e:  # noqa: BLE001 - reported to the test
            errs.append((r, e))

    ts = [threading.Thread(target=wrap, args=(r, fn), daemon=True)
          for r, fn in fns.items()]
    for t in ts:
        t.start()
    for t in ts:
        t.join(60)
        assert not t.is_alive(), "ring op hung (the one thing it must not)"
    if errs:
        raise errs[0][1]
    return out


# -- collectives -------------------------------------------------------------

def test_allreduce_sum_bitwise_identical():
    c = LocalClient()
    rings = _rings(c, [0, 1, 2])
    vals = {r: np.arange(6, dtype=np.float32).reshape(2, 3) * (r + 1)
            for r in rings}
    out = _run({r: (lambda rr=r: rings[rr].allreduce_sum(vals[rr]))
                for r in rings})
    want = vals[0] + vals[1] + vals[2]
    for r in rings:
        # bitwise: every member sums in member order, not arrival order
        assert out[r].tobytes() == want.tobytes()


def test_broadcast_and_barrier():
    c = LocalClient()
    rings = _rings(c, [0, 1])
    payload = np.array([3.5, -1.0])
    out = _run({0: lambda: rings[0].broadcast(payload),
                1: lambda: rings[1].broadcast(None)})
    np.testing.assert_array_equal(out[0], payload)
    np.testing.assert_array_equal(out[1], payload)
    out = _run({0: lambda: rings[0].broadcast_bytes(b"ckpt-blob"),
                1: lambda: rings[1].broadcast_bytes(b"")})
    assert out == {0: b"ckpt-blob", 1: b"ckpt-blob"}
    _run({r: rings[r].barrier for r in rings})  # completes, no error


def test_single_member_short_circuits():
    c = LocalClient()
    ring = Ring(c, 0, [0], poll=0.0)
    np.testing.assert_array_equal(ring.allreduce_sum(np.ones(3)), np.ones(3))
    assert ring.broadcast_bytes(b"x") == b"x"
    ring.barrier()
    assert c.dir("") == {}  # no control-plane traffic at size 1


# -- worker loss -------------------------------------------------------------

def test_dead_peer_raises_worker_lost_not_hang():
    c = LocalClient()
    rings = _rings(c, [0, 1, 2])
    c.mark_dead(2)  # rank 2 never shows up for the op

    def survivor(r):
        with pytest.raises(WorkerLostError) as ei:
            rings[r].allreduce_sum(np.ones(2))
        assert "2" in str(ei.value)
        return True

    out = _run({0: lambda: survivor(0), 1: lambda: survivor(1)})
    assert out == {0: True, 1: True}
    assert DIST_HEALTH.worker_lost >= 2
    assert rings[0].dead == (2,)
    assert rings[0].liveness_table()["2"] == "dead"


def test_reform_drops_dead_member_and_ring_works_again():
    c = LocalClient()
    rings = _rings(c, [0, 1, 2])
    c.mark_dead(2)
    _run({0: lambda: pytest.raises(WorkerLostError,
                                   rings[0].allreduce_sum, np.ones(1)),
          1: lambda: pytest.raises(WorkerLostError,
                                   rings[1].allreduce_sum, np.ones(1))})
    out = _run({0: rings[0].reform, 1: rings[1].reform})
    assert out[0] == out[1] == [0, 1]
    assert rings[0].gen == rings[1].gen == 1
    assert rings[1].index == 1  # logical placement re-derived
    assert DIST_HEALTH.reforms >= 1
    # the re-formed ring is fully functional
    out = _run({r: (lambda rr=r: rings[rr].allreduce_sum(
        np.full(2, float(rr + 1)))) for r in (0, 1)})
    np.testing.assert_array_equal(out[0], np.full(2, 3.0))
    np.testing.assert_array_equal(out[1], np.full(2, 3.0))


def test_pending_reform_aborts_waiters():
    """A survivor blocked in a fetch must abort to the re-form the moment
    any peer proposes one — not wait out its own op timeout."""
    c = LocalClient()
    rings = _rings(c, [0, 1], op_timeout=30.0)
    # rank 1 proposed generation 2's re-form (as if it already detected a
    # loss); rank 0 sits down to a normal op and must bail immediately
    c.set("mxring/reform/1/prop/0", '{"members": [0], "joiners": []}')
    with pytest.raises(WorkerLostError) as ei:
        rings[0].allreduce_sum(np.ones(1))
    assert "already proposed" in str(ei.value)
    assert rings[1] is not None  # rank 1 never even ran — no hang either


def test_evicted_rank_raises():
    c = LocalClient()
    ring = Ring(c, 1, [0, 1], poll=0.0, op_timeout=30.0)
    # the survivors' proposal for gen 1 excludes rank 1
    c.set("mxring/reform/1/prop/0", '{"members": [0], "joiners": []}')
    with pytest.raises(WorkerLostError) as ei:
        ring.reform()
    assert "evicted" in str(ei.value)


# -- join (late worker) ------------------------------------------------------

def test_join_at_reform_admits_new_member():
    c = LocalClient()
    rings = _rings(c, [0, 1])
    joiner = Ring(c, 2, [2], ns="mxring", poll=0.0, op_timeout=30.0)

    # the admission contract is epoch-boundary: incumbents re-form only
    # AFTER seeing the pending request (fit's _admit_dist_joiners), so
    # the request is on the plane before anyone proposes
    c.set("mxring/join/2", b"1")
    assert rings[0].poll_joiners() == [2]
    out = _run({0: rings[0].reform, 1: rings[1].reform,
                2: lambda: joiner.request_join(timeout=30.0)})
    assert out[0] == out[1] == out[2] == [0, 1, 2]
    assert joiner.gen == rings[0].gen == 1
    # all three exchange on the new generation
    res = _run({0: lambda: rings[0].allreduce_sum(np.ones(1)),
                1: lambda: rings[1].allreduce_sum(np.ones(1)),
                2: lambda: joiner.allreduce_sum(np.ones(1))})
    for r in res.values():
        np.testing.assert_array_equal(r, np.full(1, 3.0))
    assert rings[0].poll_joiners() == []  # request cleared at commit


# -- fault sites (docs/robustness.md "Fault injection") ----------------------

def test_kv_partition_drop_heals_and_counts():
    c = LocalClient()
    rings = _rings(c, [0, 1])
    before = DIST_HEALTH.requeued
    faults.inject("kv.partition", nth=1, kind="drop", times=3)
    out = _run({r: (lambda rr=r: rings[rr].allreduce_sum(
        np.full(1, float(rr)))) for r in rings})
    np.testing.assert_array_equal(out[0], np.full(1, 1.0))
    np.testing.assert_array_equal(out[1], np.full(1, 1.0))
    assert DIST_HEALTH.requeued == before + 3


def test_kv_partition_persistent_times_out_never_hangs():
    c = LocalClient()
    ring = Ring(c, 0, [0, 1], poll=0.0, op_timeout=0.05)
    faults.inject("kv.partition", kind="drop", times=10 ** 9)
    # rank 1 is alive and its key even lands — but this side's control
    # link drops every read: the op must end in a deadline error
    c.set("mxring/g0/red/0/1", b"\x01")
    with pytest.raises(KVStoreTimeoutError):
        ring.allreduce_sum(np.ones(1))


def test_kv_worker_die_raising_kind_propagates():
    c = LocalClient()
    ring = Ring(c, 0, [0, 1], poll=0.0)
    faults.inject("kv.worker_die", nth=1, kind="raise")
    with pytest.raises(faults.InjectedFault):
        ring.allreduce_sum(np.ones(1))
    # the op never published: a retry after the fault clears is clean
    assert not any("/red/" in k for k in c.dir(""))


def test_kv_push_delay_site_registered():
    from mxnet_tpu.kvstore import create
    faults.inject("kv.push_delay", nth=1, kind="delay", delay=0.0)
    kv = create("local")
    before = faults.count("kv.push_delay")
    # local stores never fire the dist push site; the site exists for the
    # dist stores and the rule must not leak into local training
    import mxnet_tpu.ndarray as nd
    kv.init(3, nd.ones((2,)))
    kv.push(3, nd.ones((2,)))
    assert faults.count("kv.push_delay") == before


# -- kv.reform_delay: a slow leader during ring re-form ----------------------

@pytest.mark.faults
def test_reform_delay_slow_leader_survivors_still_converge():
    """kv.reform_delay stalls the LEADER (min live rank) right before it
    publishes the membership proposal; the follower keeps polling and
    both survivors must still converge on the same re-formed ring."""
    import time
    c = LocalClient()
    rings = _rings(c, [0, 1, 2])
    c.mark_dead(2)
    faults.inject("kv.reform_delay", nth=1, kind="delay", delay=0.3)
    t0 = time.monotonic()
    out = _run({0: rings[0].reform, 1: rings[1].reform})
    elapsed = time.monotonic() - t0
    assert out[0] == out[1] == [0, 1]
    assert rings[0].gen == rings[1].gen == 1
    assert faults.fired("kv.reform_delay") == 1  # leader only, once
    assert elapsed >= 0.3                        # the stall was real
    # the re-formed ring still reduces correctly
    red = _run({r: (lambda rr=r: rings[rr].allreduce_sum(
        np.full(2, float(rr + 1)))) for r in (0, 1)})
    np.testing.assert_array_equal(red[0], np.full(2, 3.0))
    np.testing.assert_array_equal(red[1], np.full(2, 3.0))


@pytest.mark.faults
def test_reform_delay_beyond_deadline_raises_bounded():
    """A leader stalled PAST the re-form deadline must not hang anyone:
    every survivor raises KVStoreTimeoutError in bounded time (the
    docs/robustness.md 'converge or raise in bounded time' contract)."""
    import time
    c = LocalClient()
    rings = _rings(c, [0, 1, 2], op_timeout=0.5)
    c.mark_dead(2)
    faults.inject("kv.reform_delay", nth=1, kind="delay", delay=2.0)
    t0 = time.monotonic()
    out = _run({r: (lambda rr=r: pytest.raises(
        KVStoreTimeoutError, rings[rr].reform)) for r in (0, 1)})
    elapsed = time.monotonic() - t0
    assert set(out) == {0, 1}
    assert elapsed < 15.0, "re-form timeout was not bounded"
    assert faults.fired("kv.reform_delay") == 1
