"""NDArray tests (ref strategy: tests/python/unittest/test_ndarray.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd


def test_creation():
    a = nd.zeros((3, 4))
    assert a.shape == (3, 4)
    assert a.asnumpy().sum() == 0
    b = nd.ones((2, 2), dtype=np.float32)
    assert b.asnumpy().sum() == 4
    c = nd.full((2, 2), 7)
    assert (c.asnumpy() == 7).all()
    d = nd.array([[1, 2], [3, 4]])
    assert d.shape == (2, 2)
    e = nd.arange(0, 10, 2)
    assert (e.asnumpy() == np.arange(0, 10, 2)).all()


def test_arithmetic():
    a = nd.array(np.array([[1.0, 2.0], [3.0, 4.0]]))
    b = nd.array(np.array([[5.0, 6.0], [7.0, 8.0]]))
    assert ((a + b).asnumpy() == np.array([[6, 8], [10, 12]])).all()
    assert ((b - a).asnumpy() == 4).all()
    assert ((a * 2).asnumpy() == np.array([[2, 4], [6, 8]])).all()
    assert ((2 * a).asnumpy() == (a * 2).asnumpy()).all()
    assert np.allclose((1.0 / a).asnumpy(), 1.0 / a.asnumpy())
    assert np.allclose((a ** 2).asnumpy(), a.asnumpy() ** 2)
    assert ((-a).asnumpy() == -a.asnumpy()).all()


def test_inplace():
    a = nd.ones((2, 2))
    a += 1
    assert (a.asnumpy() == 2).all()
    a *= 3
    assert (a.asnumpy() == 6).all()
    a /= 2
    assert (a.asnumpy() == 3).all()


def test_slicing_and_writeback():
    a = nd.zeros((4, 3))
    a[1] = 1.0
    assert a.asnumpy()[1].sum() == 3
    a[2:4] = 2.0
    assert (a.asnumpy()[2:4] == 2).all()
    s = a[0:2]
    s[:] = 5.0
    assert (a.asnumpy()[0:2] == 5).all()  # view write-back


def test_setitem_array():
    a = nd.zeros((3, 2))
    a[1] = np.array([7.0, 8.0])
    assert (a.asnumpy()[1] == [7, 8]).all()


def test_copyto_and_context():
    a = nd.ones((2, 2))
    b = nd.zeros((2, 2))
    a.copyto(b)
    assert (b.asnumpy() == 1).all()
    c = a.copyto(mx.cpu())
    assert (c.asnumpy() == 1).all()
    assert a.context.device_type in ("cpu", "tpu")


def test_reshape_transpose():
    a = nd.arange(6).reshape((2, 3))
    assert a.shape == (2, 3)
    assert a.T.shape == (3, 2)
    assert (a.T.asnumpy() == a.asnumpy().T).all()


def test_reductions_and_ops():
    x = np.random.rand(3, 4).astype(np.float32)
    a = nd.array(x)
    assert np.allclose(nd.sum(a).asnumpy(), x.sum(), rtol=1e-5)
    assert np.allclose(nd.max(a, axis=1).asnumpy(), x.max(1), rtol=1e-5)
    assert np.allclose(nd.sqrt(a).asnumpy(), np.sqrt(x), rtol=1e-5)
    assert np.allclose(nd.dot(a, nd.array(x.T)).asnumpy(), x @ x.T, rtol=1e-4)
    assert np.allclose(nd.clip(a, a_min=0.2, a_max=0.8).asnumpy(),
                       np.clip(x, 0.2, 0.8))


def test_broadcast():
    a = nd.array(np.random.rand(3, 1).astype(np.float32))
    b = nd.array(np.random.rand(1, 4).astype(np.float32))
    c = nd.broadcast_add(a, b)
    assert c.shape == (3, 4)
    assert np.allclose(c.asnumpy(), a.asnumpy() + b.asnumpy())
    d = a.broadcast_to((3, 5))
    assert d.shape == (3, 5)


def test_comparison():
    a = nd.array(np.array([1.0, 2.0, 3.0]))
    b = nd.array(np.array([2.0, 2.0, 2.0]))
    assert ((a > b).asnumpy() == [0, 0, 1]).all()
    assert ((a == b).asnumpy() == [0, 1, 0]).all()


def test_save_load(tmp_path):
    fname = str(tmp_path / "nd.params")
    a = nd.ones((2, 3))
    b = nd.zeros((1, 4))
    nd.save(fname, [a, b])
    loaded = nd.load(fname)
    assert isinstance(loaded, list) and len(loaded) == 2
    assert (loaded[0].asnumpy() == 1).all()
    nd.save(fname, {"a": a, "b": b})
    loaded = nd.load(fname)
    assert isinstance(loaded, dict)
    assert (loaded["a"].asnumpy() == 1).all()


def test_onehot():
    idx = nd.array(np.array([0.0, 2.0]))
    out = nd.zeros((2, 3))
    nd.onehot_encode(idx, out)
    assert (out.asnumpy() == [[1, 0, 0], [0, 0, 1]]).all()


def test_add_n():
    arrs = [nd.ones((2, 2)) for _ in range(4)]
    s = nd.add_n(*arrs)
    assert (s.asnumpy() == 4).all()


def test_asscalar():
    a = nd.array(np.array([3.5]))
    assert a.asscalar() == pytest.approx(3.5)


def test_waitall():
    nd.waitall()


def test_imperative_batchnorm_with_aux():
    """Imperative aux-state op: trailing positionals are aux states."""
    x = nd.array(np.random.rand(8, 3).astype(np.float32) * 4)
    gamma, beta = nd.ones((3,)), nd.zeros((3,))
    mmean, mvar = nd.zeros((3,)), nd.ones((3,))
    out = mx.nd.BatchNorm(x, gamma, beta, mmean, mvar, fix_gamma=False,
                          momentum=0.5)
    # eval mode: normalized by moving stats (mean 0 var 1) => out == x
    assert np.allclose(out.asnumpy(), x.asnumpy(), atol=1e-2)
