"""Perl XS binding over the compiled ABI: the reference ships AI::MXNet
(perl-package/, 16.9k LoC over compiled glue); this proves the rebuilt ABI
is consumable from a non-C managed language the same way
(VERDICT r4 item 10)."""
import os
import shutil
import subprocess

import pytest

ROOT = os.path.join(os.path.dirname(__file__), "..")
PKG = os.path.join(ROOT, "perl-package")


@pytest.mark.skipif(shutil.which("perl") is None
                    or shutil.which("xsubpp") is None
                    or shutil.which("cc") is None,
                    reason="no perl/XS toolchain")
def test_perl_consumer_runs_inference():
    env = dict(os.environ)
    env["PYTHONPATH"] = ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    if not os.path.exists(os.path.join(ROOT, "lib", "libmxnet_tpu.so")):
        r = subprocess.run(["make", "-C",
                            os.path.join(ROOT, "src", "capi")],
                           capture_output=True, text=True, timeout=600,
                           env=env)
        assert r.returncode == 0, r.stdout + r.stderr
    r = subprocess.run(["make", "-C", PKG], capture_output=True, text=True,
                       timeout=300, env=env)
    assert r.returncode == 0, r.stdout + r.stderr
    r = subprocess.run(["perl", "predict.pl"], cwd=PKG,
                       capture_output=True, text=True, timeout=600, env=env)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "PERL PASS" in r.stdout
    import re
    m = re.search(r"ops visible through ABI: (\d+)", r.stdout)
    assert m and int(m.group(1)) > 200, r.stdout
