"""Module API tests (ref strategy: tests/python/unittest/test_module.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import sym, nd


def _mlp_sym(num_hidden=16, num_classes=4):
    data = sym.Variable("data")
    net = sym.FullyConnected(data=data, num_hidden=num_hidden, name="fc1")
    net = sym.Activation(data=net, act_type="relu")
    net = sym.FullyConnected(data=net, num_hidden=num_classes, name="fc2")
    return sym.SoftmaxOutput(data=net, name="softmax")


def _toy_data(n=256, dim=10, classes=4, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, dim)).astype(np.float32)
    w = rng.normal(size=(dim, classes)).astype(np.float32)
    y = np.argmax(X @ w, axis=1).astype(np.float32)
    return X, y


def test_module_fit_convergence():
    X, y = _toy_data()
    train = mx.io.NDArrayIter(X, y, batch_size=32, shuffle=True)
    mod = mx.mod.Module(_mlp_sym(), context=mx.cpu())
    mod.fit(train, num_epoch=15, optimizer_params={"learning_rate": 0.5})
    score = mod.score(mx.io.NDArrayIter(X, y, batch_size=32), "acc")
    assert score[0][1] > 0.9, score


def test_module_forward_outputs():
    X, y = _toy_data(64)
    mod = mx.mod.Module(_mlp_sym(), context=mx.cpu())
    mod.bind(data_shapes=[("data", (8, 10))],
             label_shapes=[("softmax_label", (8,))])
    mod.init_params()
    batch = mx.io.DataBatch(data=[nd.array(X[:8])], label=[nd.array(y[:8])])
    mod.forward(batch, is_train=False)
    outs = mod.get_outputs()
    assert len(outs) == 1 and outs[0].shape == (8, 4)
    assert np.allclose(outs[0].asnumpy().sum(1), 1.0, rtol=1e-4)


def test_module_predict_and_score():
    X, y = _toy_data(96)
    it = mx.io.NDArrayIter(X, y, batch_size=32)
    mod = mx.mod.Module(_mlp_sym(), context=mx.cpu())
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params()
    pred = mod.predict(it)
    assert pred.shape == (96, 4)


def test_module_checkpoint_roundtrip(tmp_path):
    X, y = _toy_data(64)
    train = mx.io.NDArrayIter(X, y, batch_size=32)
    mod = mx.mod.Module(_mlp_sym(), context=mx.cpu())
    mod.fit(train, num_epoch=2, optimizer_params={"learning_rate": 0.1})
    prefix = str(tmp_path / "mlp")
    mod.save_checkpoint(prefix, 2, save_optimizer_states=True)

    mod2 = mx.mod.Module.load(prefix, 2, context=mx.cpu())
    it = mx.io.NDArrayIter(X, y, batch_size=32)
    mod2.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    p1, _ = mod.get_params()
    p2, _ = mod2.get_params()
    for k in p1:
        assert np.allclose(p1[k].asnumpy(), p2[k].asnumpy()), k
    # predictions identical
    o1 = mod.predict(mx.io.NDArrayIter(X, y, batch_size=32)).asnumpy()
    o2 = mod2.predict(mx.io.NDArrayIter(X, y, batch_size=32)).asnumpy()
    assert np.allclose(o1, o2, rtol=1e-5)


def test_module_input_grads():
    mod = mx.mod.Module(_mlp_sym(), context=mx.cpu())
    mod.bind(data_shapes=[("data", (4, 10))],
             label_shapes=[("softmax_label", (4,))],
             inputs_need_grad=True)
    mod.init_params()
    batch = mx.io.DataBatch(data=[nd.ones((4, 10))],
                            label=[nd.zeros((4,))])
    mod.forward(batch, is_train=True)
    mod.backward()
    grads = mod.get_input_grads()
    assert grads[0].shape == (4, 10)
    assert np.abs(grads[0].asnumpy()).sum() > 0


def test_module_multi_device_data_parallel():
    """Multiple cpu contexts: SPMD data parallelism over a virtual mesh
    (ref strategy: test_module with cpu device lists)."""
    import jax
    n = min(4, len(jax.devices()))
    ctxs = [mx.cpu(i) for i in range(n)]
    X, y = _toy_data(256)
    train = mx.io.NDArrayIter(X, y, batch_size=32, shuffle=True)
    mod = mx.mod.Module(_mlp_sym(), context=ctxs)
    mod.fit(train, num_epoch=10, optimizer_params={"learning_rate": 0.5})
    score = mod.score(mx.io.NDArrayIter(X, y, batch_size=32), "acc")
    assert score[0][1] > 0.85, score


def test_set_get_params():
    mod = mx.mod.Module(_mlp_sym(), context=mx.cpu())
    mod.bind(data_shapes=[("data", (4, 10))],
             label_shapes=[("softmax_label", (4,))])
    mod.init_params()
    args, auxs = mod.get_params()
    w = np.random.rand(*args["fc1_weight"].shape).astype(np.float32)
    args["fc1_weight"] = nd.array(w)
    mod.set_params(args, auxs)
    args2, _ = mod.get_params()
    assert np.allclose(args2["fc1_weight"].asnumpy(), w)


def test_feedforward_api():
    X, y = _toy_data(128)
    model = mx.model.FeedForward(_mlp_sym(), ctx=mx.cpu(), num_epoch=25,
                                 numpy_batch_size=32, learning_rate=0.5)
    model.fit(X, y)
    pred = model.predict(X)
    acc = (pred.argmax(1) == y).mean()
    assert acc > 0.8
