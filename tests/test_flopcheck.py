"""flopcheck tests (docs/static_analysis.md "Roofline lints"): the
static per-kernel compute/memory roofline analyzer over compiled
programs.

The load-bearing assertions:

* the scheduled-HLO kernel parser builds the inventory right — dots by
  their contraction algebra, fusions by their callee sums, alias-aware
  bytes, in-loop multipliers from ``known_trip_count``, expansion-loop
  collapse (a scalar pool-backprop while becomes ONE merged kernel with
  one streaming pass of bytes, never per-iter bytes x trips), layout
  detection, and collectives/views/control-flow excluded;
* the roofline pricing holds: ``max(flops/peak, bytes/bw)`` per kernel,
  compute/memory bound vs the ridge, cost-analysis apportioning that
  normalizes on the once-each ``norm_flops`` basis;
* one SEEDED violation per roofline lint class — ``memory-bound-hot``,
  ``layout-copy``, ``tiny-dispatch``, ``predicted-mfu`` — is caught
  (with op path / source provenance where a real program seeds it);
* the baseline drift gate goes RED end-to-end on a seeded fusion
  regression (one clean dot shattered into two dozen mismatched dots)
  WITH the kernel breakdown and provenance (the ci/flopcheck.sh
  contract), and the absence-of-evidence discipline holds on both the
  write and compare paths;
* the CLI smoke (mlp, json mode) exits 0 with zero findings — the
  tier-1 mirror of the combined compile-once CI gate.
"""
import json
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from mxnet_tpu import flopcheck as fc  # noqa: E402
from mxnet_tpu import tracecheck as tc  # noqa: E402
from mxnet_tpu.base import MXNetError  # noqa: E402

SDS = jax.ShapeDtypeStruct

# a hand-picked spec for deterministic pricing: ridge = 100 FLOP/B
_PEAK, _BW = 1e12, 1e10


def _kernel(name, flops=0.0, nbytes=0, mult=1, opcode="fusion",
            layout=False, op_path=None, prov=None, norm_flops=None):
    return fc.KernelEntry(name, opcode, flops, nbytes, multiplier=mult,
                          is_layout=layout, op_path=op_path,
                          provenance=prov, norm_flops=norm_flops)


def _fake_roofline(name, kernels, hlo_unavailable=False, loop_trips=1,
                   flops=None):
    return fc.RooflineReport(
        name, jax.devices()[0].platform, kernels, loop_trips=loop_trips,
        flops=flops, peak_flops_per_s=_PEAK, hbm_bytes_per_s=_BW,
        peak_source="test-spec", hlo_unavailable=hlo_unavailable)


# ---------------------------------------------------------------------------
# the scheduled-HLO kernel parser
# ---------------------------------------------------------------------------

_FAKE_HLO = """HloModule t, is_scheduled=true, entry_computation_layout={(f32[8,32]{1,0})->f32[8,16]{1,0}}

%fused_add (p0: f32[128,64], p1: f32[128,64]) -> f32[128,64] {
  %p0 = f32[128,64]{1,0} parameter(0)
  %p1 = f32[128,64]{1,0} parameter(1)
  ROOT %add.2 = f32[128,64]{1,0} add(f32[128,64]{1,0} %p0, f32[128,64]{1,0} %p1)
}

%scan.body (wp: (s32[1], f32[64,64])) -> (s32[1], f32[64,64]) {
  %wp = (s32[1]{0}, f32[64,64]{1,0}) parameter(0)
  %mul.3 = f32[64,64]{1,0} multiply(f32[64,64]{1,0} %g.1, f32[64,64]{1,0} %g.1), metadata={op_name="jit(f)/jit(main)/while/body/mul" source_file="/tmp/t.py" source_line=9}
}

%exp.body (xp: (s32[1], f32[4096])) -> (s32[1], f32[4096]) {
  %xp = (s32[1]{0}, f32[4096]{0}) parameter(0)
  %add.7 = f32[1]{0} add(f32[1]{0} %e.1, f32[1]{0} %e.2)
}

ENTRY %main.1 (Arg_0.1: f32[8,32], Arg_1.2: f32[32,16]) -> f32[8,16] {
  %Arg_0.1 = f32[8,32]{1,0} parameter(0)
  %Arg_1.2 = f32[32,16]{1,0} parameter(1)
  %dot.4 = f32[8,16]{1,0} dot(f32[8,32]{1,0} %Arg_0.1, f32[32,16]{1,0} %Arg_1.2), lhs_contracting_dims={1}, rhs_contracting_dims={0}, metadata={op_name="jit(f)/jit(main)/dot" source_file="/tmp/t.py" source_line=4}
  %fusion.5 = f32[128,64]{1,0} fusion(f32[128,64]{1,0} %a.1, f32[128,64]{1,0} %a.2), kind=kLoop, calls=%fused_add, metadata={op_name="jit(f)/jit(main)/add" source_file="/tmp/t.py" source_line=5}
  %copy.6 = f32[512,512]{0,1} copy(f32[512,512]{1,0} %fusion.5), metadata={op_name="jit(f)/jit(main)/copy" source_file="/tmp/t.py" source_line=6}
  %dynamic-slice.12 = f32[1,16]{1,0} dynamic-slice(f32[8,16]{1,0} %dot.4, s32[1]{0} %i.1, s32[1]{0} %i.2), dynamic_slice_sizes={1,16}
  %while.8 = (s32[1]{0}, f32[64,64]{1,0}) while((s32[1]{0}, f32[64,64]{1,0}) %t.1), condition=%scan.cond, body=%scan.body, backend_config={"known_trip_count":{"n":"3"}}
  %while.9 = (s32[1]{0}, f32[4096]{0}) while((s32[1]{0}, f32[4096]{0}) %t.2), condition=%exp.cond, body=%exp.body, backend_config={"known_trip_count":{"n":"4096"}}
  %all-reduce.10 = f32[8,16]{1,0} all-reduce(f32[8,16]{1,0} %dot.4), channel_id=1, replica_groups={{0,1}}, to_apply=%sum.1
  %transpose.11 = f32[16,8]{1,0} transpose(f32[8,16]{1,0} %dot.4), dimensions={1,0}
}
"""


def test_parser_kernel_inventory():
    kernels = {k.instruction: k for k in fc.parse_kernels(_FAKE_HLO)}
    # parameters, the while/all-reduce instructions themselves: not kernels
    assert sorted(kernels) == ["copy.6", "dot.4", "dynamic-slice.12",
                               "fusion.5", "mul.3", "transpose.11",
                               "while.9"]
    dot = kernels["dot.4"]
    assert dot.flops == 2.0 * (8 * 16) * 32      # 2 x out x contracted
    assert dot.bytes == (8 * 32 + 32 * 16 + 8 * 16) * 4
    assert not dot.is_layout and not dot.in_loop and dot.multiplier == 1
    assert dot.op_path == "jit(f)/jit(main)/dot"
    assert dot.provenance == "/tmp/t.py:4"
    fus = kernels["fusion.5"]
    assert fus.flops == 128 * 64                  # the callee's add
    assert fus.bytes == 3 * 128 * 64 * 4          # 2 operands + result
    assert not fus.is_layout
    # pure data motion: a copy kernel, and a bare transpose
    assert kernels["copy.6"].is_layout
    assert kernels["copy.6"].bytes == 2 * 512 * 512 * 4
    assert kernels["transpose.11"].is_layout
    # alias-aware: a dynamic-slice reads only the slice it extracts
    assert kernels["dynamic-slice.12"].bytes == 2 * (1 * 16 * 4)
    # the K-trip scan body is inventoried in-loop with its multiplier
    mul = kernels["mul.3"]
    assert mul.in_loop and mul.multiplier == 3
    assert mul.op_path == "jit(f)/jit(main)/while/body/mul"
    assert mul.provenance == "/tmp/t.py:9"


def test_parser_expansion_loop_collapses_to_one_streaming_kernel():
    """A 4096-trip scalar while (the CPU pool-backprop lowering) must
    become ONE merged kernel: FLOPs = body x trips, but bytes = one
    read + one write of the loop-carried tuple state — NOT body-bytes x
    trips (each scalar iteration references the full arrays it slices
    from, so that would bill petabytes); and the normalization basis
    stays the one-trip body (the XLA cost model counts a body once)."""
    kernels = {k.instruction: k for k in fc.parse_kernels(_FAKE_HLO)}
    w = kernels["while.9"]
    assert w.opcode == "while" and w.multiplier == 1
    assert w.flops == 1.0 * 4096          # 1-elem add body x 4096 trips
    assert w.norm_flops == 1.0
    assert w.bytes == 2 * (4 + 4 * 4096)  # 2 x (s32[1] + f32[4096])
    # the scan-depth while (3 trips) did NOT collapse: its body kernels
    # carry the multiplier instead
    assert "while.8" not in kernels


_NOTRIP_HLO = """HloModule t, is_scheduled=true

ENTRY %main (p: f32[4]) -> f32[4] {
  %p = f32[4]{0} parameter(0)
  %while.1 = (s32[1]{0}, f32[4]{0}) while((s32[1]{0}, f32[4]{0}) %t.1), condition=%c.1, body=%b.1
}

%b.1 (bp: (s32[1], f32[4])) -> (s32[1], f32[4]) {
  %bp = (s32[1]{0}, f32[4]{0}) parameter(0)
  %exp.1 = f32[4]{0} exponential(f32[4]{0} %g.1)
}
"""


def test_parser_while_without_trip_count_uses_loop_trips():
    kernels = fc.parse_kernels(_NOTRIP_HLO, loop_trips=5)
    assert len(kernels) == 1
    assert kernels[0].instruction == "exp.1"
    assert kernels[0].in_loop and kernels[0].multiplier == 5


def test_parser_empty_text():
    assert fc.parse_kernels("") == []


# ---------------------------------------------------------------------------
# the report + roofline pricing
# ---------------------------------------------------------------------------

def test_report_pricing_and_roofline():
    # intensity 1000 FLOP/B >= ridge 100: compute bound, flops-limited
    k1 = _kernel("k.dot", flops=1e6, nbytes=1000, op_path="path/dot")
    # zero-FLOP copy, 2 executions: memory bound, 2 x 2000/bw
    k2 = _kernel("k.copy", nbytes=2000, mult=2, opcode="copy", layout=True)
    rep = _fake_roofline("p/step", [k1, k2])
    assert rep.ridge_intensity == 100.0
    assert k1.bound == "compute" and k1.seconds == 1e6 / _PEAK
    assert k2.bound == "memory" and k2.seconds == 2000 / _BW
    assert rep.kernel_count == 3                 # multiplier semantics
    assert rep.bytes_per_dispatch == 1000 + 2 * 2000
    t = 1e6 / _PEAK + 2 * (2000 / _BW)
    assert abs(rep.predicted_step_seconds - t) < 1e-12
    assert abs(rep.predicted_mfu - 1e6 / (t * _PEAK)) < 1e-9
    # kernels rank by held step time: the copy (4e-7s) over the dot (1e-6s)?
    # no — 1e-6 > 4e-7, the dot leads and its op_path is the pinned identity
    assert rep.top_hotspot == "path/dot"
    assert rep.hotspots(5, memory_only=True) == [k2]
    assert "p/step" in rep.format()


def test_report_cost_analysis_apportioning_respects_norm_basis():
    """Apportioning scales structural estimates so their sum matches the
    XLA cost model — on the ``norm_flops`` basis: a collapsed expansion
    loop weighs in at its ONE-trip body, so it cannot steal the whole
    program's FLOP budget."""
    merged = _kernel("w", flops=100.0 * 50, nbytes=8, norm_flops=100.0)
    plain = _kernel("k", flops=100.0, nbytes=8)
    rep = _fake_roofline("p/step", [merged, plain], flops=400.0)
    # basis = 100 + 100 = 200, scale = 2
    by_name = {k.instruction: k for k in rep.kernels}
    assert by_name["w"].flops == 10000.0
    assert by_name["k"].flops == 200.0


def test_report_blind_program_claims_nothing():
    rep = _fake_roofline("p/step", [], hlo_unavailable=True)
    assert rep.predicted_mfu is None
    assert rep.top_hotspot is None
    assert rep.as_dict()["hlo_unavailable"] is True


# ---------------------------------------------------------------------------
# seeded roofline lints
# ---------------------------------------------------------------------------

def _hot_program_size():
    return 4 << 20  # 4M f32 = 16 MiB: far above the 1 MiB test floor


def _seeded_hot_add(x):
    return x + 1.0


def test_lint_memory_bound_hot_seeded_real_program():
    """The flash-attention signature, seeded with the simplest possible
    HBM-bound program: one elementwise add over 16 MiB holds ~100% of
    the predicted step at intensity far below any ridge."""
    findings, report = fc.check_program(
        _seeded_hot_add, (SDS((_hot_program_size(),), np.float32),),
        name="seed/hot", hot_threshold=0.5, hot_floor=1 << 20,
        mfu_floor=0.0)
    hot = [f for f in findings if f.lint == "memory-bound-hot"]
    assert hot, "the seeded HBM-bound add must fire memory-bound-hot"
    f = hot[0]
    assert f.program == "seed/hot"
    assert f.op_path
    assert f.provenance and "test_flopcheck" in f.provenance
    assert "MXTPU_FLOPCHECK_HOT_FRAC" in f.message
    assert report.kernels[0].bound == "memory"


def test_lint_layout_copy_seeded_and_share_gated():
    big_copy = _kernel("relayout", nbytes=10 << 20, opcode="copy",
                       layout=True, op_path="jit(f)/transpose",
                       prov="m.py:7")
    small = _kernel("k", flops=100.0, nbytes=1 << 20)
    rep = _fake_roofline("seed/layout", [big_copy, small])
    findings = fc.lint_report(rep, mfu_floor=0.0)
    lay = [f for f in findings if f.lint == "layout-copy"]
    assert len(lay) == 1
    assert lay[0].op_path == "jit(f)/transpose"
    assert lay[0].provenance == "m.py:7"
    assert "MXTPU_FLOPCHECK_LAYOUT_FRAC" in lay[0].message
    # the share gate: the same copy next to 1 GiB of real traffic is a
    # rounding error (the vgg scan-stacking case) — silent
    huge = _kernel("conv", flops=1e12, nbytes=1 << 30)
    rep2 = _fake_roofline("seed/layout2", [big_copy, huge])
    assert not [f for f in fc.lint_report(rep2, mfu_floor=0.0)
                if f.lint == "layout-copy"]


def test_lint_tiny_dispatch_seeded():
    # 5000 sub-microsecond executions of one in-loop kernel
    shard = _kernel("tiny", flops=10.0, nbytes=40, mult=5000,
                    op_path="jit(f)/while/body/slice", prov="m.py:3")
    rep = _fake_roofline("seed/tiny", [shard])
    findings = fc.lint_report(rep, tiny_floor_us=1.0, tiny_threshold=4096,
                              mfu_floor=0.0)
    tiny = [f for f in findings if f.lint == "tiny-dispatch"]
    assert len(tiny) == 1
    assert "5000" in tiny[0].message
    assert "MXTPU_FLOPCHECK_TINY_COUNT" in tiny[0].message
    assert tiny[0].op_path == "jit(f)/while/body/slice"
    # below the threshold: silent
    shard2 = _kernel("tiny", flops=10.0, nbytes=40, mult=100)
    assert not fc.lint_report(_fake_roofline("q", [shard2]),
                              tiny_floor_us=1.0, tiny_threshold=4096,
                              mfu_floor=0.0)


def test_lint_predicted_mfu_seeded_and_disabled_by_default():
    # one memory-bound kernel: mfu = 1e4 / (1e-4 x 1e12) = 1e-4
    k = _kernel("hbm", flops=1e4, nbytes=int(1e6), op_path="jit(f)/add")
    rep = _fake_roofline("seed/mfu", [k])
    findings = fc.lint_report(rep, hot_threshold=2.0, mfu_floor=0.9)
    mfu = [f for f in findings if f.lint == "predicted-mfu"]
    assert len(mfu) == 1
    assert "MXTPU_FLOPCHECK_MIN_MFU" in mfu[0].message
    assert "Inventory:" in mfu[0].message
    # default floor is 0 = disarmed
    assert not [f for f in fc.lint_report(rep, hot_threshold=2.0)
                if f.lint == "predicted-mfu"]


def test_suppression_registry_shared_with_tracecheck():
    k = _kernel("hbm", flops=1e4, nbytes=int(1e6))
    rep = _fake_roofline("supp/step", [k])
    token = tc.add_suppression("predicted-mfu", program="supp/")
    try:
        findings = fc.lint_report(rep, hot_threshold=2.0, mfu_floor=0.9)
        assert findings and all(f.suppressed for f in findings)
        assert tc.unsuppressed(findings) == []
    finally:
        tc.remove_suppression(token)
    findings = fc.lint_report(rep, hot_threshold=2.0, mfu_floor=0.9)
    assert tc.unsuppressed(findings)


# ---------------------------------------------------------------------------
# knobs
# ---------------------------------------------------------------------------

def test_knob_defaults_and_env(monkeypatch):
    for var in ("HOT_FRAC", "HOT_BYTES", "LAYOUT_BYTES", "LAYOUT_FRAC",
                "TINY_US", "TINY_COUNT", "MIN_MFU", "TOL"):
        monkeypatch.delenv("MXTPU_FLOPCHECK_" + var, raising=False)
    assert fc.hot_frac() == 0.6
    assert fc.hot_bytes() == 4 << 20
    assert fc.layout_bytes() == 4 << 20
    assert fc.layout_frac() == 0.25
    assert fc.tiny_us() == 1.0
    assert fc.tiny_count() == 4096
    assert fc.min_mfu() == 0.0
    assert fc.tolerance() == 0.1
    monkeypatch.setenv("MXTPU_FLOPCHECK_HOT_FRAC", "0.8")
    monkeypatch.setenv("MXTPU_FLOPCHECK_HOT_BYTES", "8M")
    monkeypatch.setenv("MXTPU_FLOPCHECK_LAYOUT_FRAC", "0.5")
    monkeypatch.setenv("MXTPU_FLOPCHECK_TINY_COUNT", "128")
    monkeypatch.setenv("MXTPU_FLOPCHECK_MIN_MFU", "0.4")
    assert fc.hot_frac() == 0.8
    assert fc.hot_bytes() == 8 << 20
    assert fc.layout_frac() == 0.5
    assert fc.tiny_count() == 128
    assert fc.min_mfu() == 0.4
    monkeypatch.setenv("MXTPU_FLOPCHECK_HOT_BYTES", "banana")
    with pytest.raises(MXNetError, match="MXTPU_FLOPCHECK_HOT_BYTES"):
        fc.hot_bytes()
    monkeypatch.setenv("MXTPU_FLOPCHECK_HOT_FRAC", "banana")
    with pytest.raises(MXNetError, match="MXTPU_FLOPCHECK_HOT_FRAC"):
        fc.hot_frac()


def test_flopcheck_mode_knob(monkeypatch):
    from mxnet_tpu import engine
    engine.set_flopcheck(None)
    monkeypatch.delenv("MXTPU_FLOPCHECK", raising=False)
    assert engine.flopcheck_mode() == "off"
    monkeypatch.setenv("MXTPU_FLOPCHECK", "warn")
    assert engine.flopcheck_mode() == "warn"
    monkeypatch.setenv("MXTPU_FLOPCHECK", "error")
    assert engine.flopcheck_mode() == "error"
    monkeypatch.setenv("MXTPU_FLOPCHECK", "banana")
    with pytest.raises(MXNetError, match="MXTPU_FLOPCHECK"):
        engine.flopcheck_mode()
    monkeypatch.delenv("MXTPU_FLOPCHECK", raising=False)
    prev = engine.set_flopcheck("error")
    try:
        assert engine.flopcheck_mode() == "error"
    finally:
        engine.set_flopcheck(prev if prev != "off" else None)


# ---------------------------------------------------------------------------
# the dispatch hook (MXTPU_FLOPCHECK) — flopcheck audits EVERY program,
# single-device included: a fusion regression needs no mesh to hurt
# ---------------------------------------------------------------------------

def _train_step():
    from mxnet_tpu import models
    from mxnet_tpu.train_step import TrainStep
    ts = TrainStep(models.mlp(num_classes=4, hidden=(16,)),
                   optimizer="sgd", learning_rate=0.1)
    state = ts.init({"data": (8, 16)}, {"softmax_label": (8,)})
    rng = np.random.default_rng(0)
    sb = {"data": jnp.asarray(rng.normal(size=(2, 8, 16)), jnp.float32),
          "softmax_label": jnp.asarray(rng.integers(0, 4, (2, 8)),
                                       jnp.float32)}
    return ts, state, sb


def test_dispatch_hook_audits_single_device_program_once():
    from mxnet_tpu import engine
    prev = engine.set_flopcheck("warn")
    try:
        before = set(fc._AUDITED)
        ts, state, sb = _train_step()
        state, m = ts.run_steps(state, sb)
        new = set(fc._AUDITED) - before
        assert len(new) == 1 and "scan" in next(iter(new))
        # second dispatch: memoized, no re-audit
        state, m = ts.run_steps(state, sb)
        assert set(fc._AUDITED) - before == new
        assert m.num_samples > 0
    finally:
        engine.set_flopcheck(prev if prev != "off" else None)


def test_dispatch_hook_error_mode_raises_on_finding(monkeypatch):
    """MXTPU_FLOPCHECK=error + an impossible MFU floor: the first
    dispatch fails fast with the roofline findings instead of burning a
    profiling session."""
    from mxnet_tpu import engine
    monkeypatch.setenv("MXTPU_FLOPCHECK_MIN_MFU", "0.999")
    prev = engine.set_flopcheck("error")
    try:
        ts, state, sb = _train_step()
        with pytest.raises(MXNetError, match="predicted-mfu"):
            ts.run_steps(state, sb)
    finally:
        engine.set_flopcheck(prev if prev != "off" else None)


def test_dispatch_hook_off_by_default(monkeypatch):
    from mxnet_tpu import engine
    engine.set_flopcheck(None)
    monkeypatch.delenv("MXTPU_FLOPCHECK", raising=False)
    before = set(fc._AUDITED)
    ts, state, sb = _train_step()
    ts.run_steps(state, sb)
    assert set(fc._AUDITED) == before


def test_dispatch_hook_blind_compiled_does_not_pass_vacuously():
    from mxnet_tpu import engine

    class FakeCompiled:
        def as_text(self):
            raise RuntimeError("no HLO text on this backend")

        def cost_analysis(self):
            return None

    class FakeJit:
        def lower(self, *a, **k):
            return self

        def compile(self):
            return FakeCompiled()

    prev = engine.set_flopcheck("error")
    try:
        fc._AUDITED.discard("blind-prog")
        with pytest.raises(MXNetError, match="unavailable"):
            fc.maybe_audit_dispatch("blind-prog", FakeJit(), ())
    finally:
        engine.set_flopcheck(prev if prev != "off" else None)


# ---------------------------------------------------------------------------
# the baseline drift gate (ci/flopcheck.sh contract)
# ---------------------------------------------------------------------------

def _uniform_report(name, count=4, ms_total=1.0):
    per = int(ms_total * 1e-3 / count * _BW)  # bytes so each kernel
    kernels = [_kernel("k.%d" % i, nbytes=per, op_path="path/k.%d" % i)
               for i in range(count)]         # prices ms_total/count
    return _fake_roofline(name, kernels)


def test_baseline_roundtrip_passes(tmp_path):
    reports = {"a/step": _uniform_report("a/step", 4, 1.0),
               "b/scan[k=2]": _uniform_report("b/scan[k=2]", 7, 2.0)}
    path = str(tmp_path / "b.json")
    fc.write_baseline(reports, path)
    failures, notes = fc.compare_baseline(reports, path)
    assert failures == []
    assert notes == []


def _clean_gate(x):
    return x @ x


def _regressed_gate(x):
    # two dozen mismatched-shape dots: XLA cannot fuse or CSE them, the
    # one-kernel step shatters into a pile
    acc = jnp.zeros((), jnp.float32)
    for i in range(1, 25):
        acc = acc + jnp.sum(x[:i, :] @ x)
    return acc


def test_baseline_fails_seeded_fusion_regression_end_to_end(tmp_path):
    """The acceptance contract: a baseline pinned on the clean one-dot
    program goes RED when the same program name shatters into two dozen
    kernels — with the kernel breakdown and source provenance in the
    failure (before any profiler runs)."""
    arg = (SDS((32, 32), np.float32),)
    clean = fc.analyze(_clean_gate, arg, name="gate/step")
    path = str(tmp_path / "b.json")
    fc.write_baseline({"gate/step": clean}, path)
    regressed = fc.analyze(_regressed_gate, arg, name="gate/step")
    assert regressed.kernel_count > clean.kernel_count * 2
    failures, _ = fc.compare_baseline({"gate/step": regressed}, path)
    assert failures
    joined = "\n".join(failures)
    assert "kernel_count grew" in joined
    assert "MXTPU_FLOPCHECK_TOL" in joined
    assert "Inventory:" in joined            # the breakdown rides along
    assert "test_flopcheck" in joined        # ...with provenance


def test_baseline_mfu_drop_fails_rise_and_hotspot_move_note():
    rep = _fake_roofline(
        "a/step", [_kernel("hbm", flops=1e4, nbytes=int(1e6),
                           op_path="path/hbm")])
    mfu = rep.predicted_mfu  # 1e-4
    base = {"platform": jax.devices()[0].platform, "tolerance": 0.1,
            "programs": {"a/step": {
                "kernel_count": 1,
                "predicted_step_ms": rep.predicted_step_ms,
                "predicted_mfu": 0.9, "top_hotspot": "path/other"}}}
    failures, notes = fc.compare_baseline({"a/step": rep}, base)
    assert any("predicted_mfu dropped" in f for f in failures)
    assert any("top hotspot moved" in n for n in notes)
    base["programs"]["a/step"]["predicted_mfu"] = mfu / 2
    base["programs"]["a/step"]["top_hotspot"] = "path/hbm"
    failures, notes = fc.compare_baseline({"a/step": rep}, base)
    assert failures == []
    assert any("rose" in n for n in notes)


def test_baseline_missing_stale_platform_shrink_collapse(tmp_path):
    reports = {"a/step": _uniform_report("a/step", 8, 4.0)}
    path = str(tmp_path / "b.json")
    fc.write_baseline(reports, path)
    # missing program fails (deliberate-add contract), stale is a note
    failures, notes = fc.compare_baseline(
        {"a/step": reports["a/step"],
         "new/step": _uniform_report("new/step", 1, 0.1)}, path)
    assert len(failures) == 1 and "new/step" in failures[0]
    assert "--write-baseline" in failures[0]
    failures2, notes2 = fc.compare_baseline({}, path)
    assert failures2 == []
    assert any("stale" in n for n in notes2)
    # platform mismatch skips the gate with one note
    failures3, notes3 = fc.compare_baseline(reports, {
        "platform": "made-up-platform", "tolerance": 0.1,
        "programs": {"a/step": {"kernel_count": 1,
                                "predicted_step_ms": 1.0}}})
    assert failures3 == []
    assert len(notes3) == 1 and "platform" in notes3[0]
    # shrinks are notes, not failures
    failures4, notes4 = fc.compare_baseline(
        {"a/step": _uniform_report("a/step", 4, 1.0)}, path)
    assert failures4 == []
    assert any("kernel_count shrank" in n for n in notes4)
    assert any("predicted_step_ms shrank" in n for n in notes4)
    # ...but a TOTAL collapse to zero kernels on a nonzero-pinned
    # program fails: indistinguishable from a parser gone blind
    failures5, _ = fc.compare_baseline(
        {"a/step": _fake_roofline("a/step", [])}, path)
    assert any("collapsed" in f for f in failures5)


def test_baseline_tol_env_overrides_stored_band(tmp_path, monkeypatch):
    path = str(tmp_path / "b.json")
    fc.write_baseline({"a/step": _uniform_report("a/step", 10, 1.0)},
                      path, tol=0.1)
    grown = {"a/step": _uniform_report("a/step", 13, 1.0)}
    monkeypatch.delenv("MXTPU_FLOPCHECK_TOL", raising=False)
    failures, _ = fc.compare_baseline(grown, path)
    assert failures  # +30% kernels past the stored 10% band
    monkeypatch.setenv("MXTPU_FLOPCHECK_TOL", "0.5")
    failures, _ = fc.compare_baseline(grown, path)
    assert failures == []  # env-widened band wins


def test_baseline_refuses_absence_of_evidence(tmp_path):
    blind = _fake_roofline("blind/step", [], hlo_unavailable=True)
    with pytest.raises(MXNetError, match="fabricated"):
        fc.write_baseline({"blind/step": blind},
                          str(tmp_path / "b.json"))
    path = str(tmp_path / "b2.json")
    fc.write_baseline({"blind/step": _uniform_report("blind/step", 2, 1.0)},
                      path)
    failures, _ = fc.compare_baseline({"blind/step": blind}, path)
    assert len(failures) == 1
    assert "absence of evidence" in failures[0]


# ---------------------------------------------------------------------------
# hotspots: the Pallas shopping list
# ---------------------------------------------------------------------------

def test_autotune_hotspot_report_accessor():
    from mxnet_tpu import autotune

    def fn(x, b):
        return x @ x + b

    entries = autotune.hotspot_report(
        fn, (SDS((128, 128), np.float32), SDS((128,), np.float32)),
        name="tune/fn", top=5, memory_only=False)
    assert entries
    fracs = [e["step_time_frac"] for e in entries]
    assert all(0.0 <= f <= 1.0 for f in fracs)
    assert sum(fracs) <= 1.0 + 1e-6
    times = [e["predicted_us"] * e["multiplier"] for e in entries]
    assert times == sorted(times, reverse=True)  # ranked by held time
    assert all(e["bound"] in ("compute", "memory") for e in entries)


def test_transformer_attention_fusion_in_top3_memory_bound_hotspots():
    """The acceptance claim: on the transformer zoo model the attention
    fusion ranks in the top-3 memory-bound hotspots — the flash-attention
    candidate names itself."""
    from mxnet_tpu.tracecheck import train_step_programs, zoo_train_step
    ts, data_shapes, label_shapes = zoo_train_step("transformer")
    rep = None
    for pname, jitfn, pargs in train_step_programs(
            ts, data_shapes, label_shapes, k=2, guard=False,
            name="transformer"):
        if pname.endswith("/step"):
            rep = fc.analyze(jitfn, pargs, name=pname, mesh=ts.mesh)
            break
    assert rep is not None
    top3 = rep.hotspots(3, memory_only=True)
    assert top3
    paths = [(k.op_path or "") + " " + (k.provenance or "") for k in top3]
    assert any("attn" in p.lower() or "attention" in p.lower()
               for p in paths), paths


# ---------------------------------------------------------------------------
# CLI (tier-1 smoke of the ci/flopcheck.sh gate)
# ---------------------------------------------------------------------------

def test_cli_smoke_json_mlp(capsys):
    """The tier-1 mirror of the combined CI gate: mlp + lenet in json
    mode exit 0 with zero findings and a priced inventory for all 8
    programs."""
    rc = fc.main(["--models", "mlp,lenet", "--json"])
    data = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert data["findings"] == []
    assert data["suppressed"] == 0
    assert data["baseline_failures"] == []
    assert len(data["programs"]) == 8
    for rep in data["programs"].values():
        assert rep["kernel_count"] > 0
        assert rep["predicted_step_ms"] > 0
        assert rep["top_hotspot"]
        assert rep["hlo_unavailable"] is False
    assert data["platform"] == jax.devices()[0].platform
    assert data["analyzers_sharing_compile"] == 1


def test_cli_fails_on_hlo_unavailable_even_without_baseline(
        capsys, monkeypatch):
    """The absence-of-evidence contract holds in the no-baseline CLI
    modes too: a backend where as_text() fails must not print PASS over
    an audit that saw no HLO."""
    blind = _fake_roofline("mlp/step", [], hlo_unavailable=True)
    monkeypatch.setattr(fc, "compiled_zoo_programs",
                        lambda **kw: iter(()))
    monkeypatch.setattr(fc, "check_zoo",
                        lambda **kw: ([], {"mlp/step": blind}))
    rc = fc.main(["--models", "mlp", "--json"])
    data = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert any("absence of evidence" in f
               for f in data["baseline_failures"])
    assert data["programs"]["mlp/step"]["hlo_unavailable"] is True


def test_cli_list_and_bad_model(capsys):
    assert fc.main(["--list"]) == 0
    assert "mlp" in capsys.readouterr().out
    with pytest.raises(MXNetError, match="unknown zoo model"):
        fc.main(["--models", "nope"])


def test_cli_write_and_gate_baseline_with_hotspots(tmp_path, capsys):
    path = str(tmp_path / "b.json")
    rc = fc.main(["--models", "mlp", "--quiet", "--write-baseline", path])
    capsys.readouterr()
    assert rc == 0
    with open(path) as f:
        base = json.load(f)
    assert len(base["programs"]) == 4
    rc = fc.main(["--models", "mlp", "--quiet", "--baseline", path,
                  "--hotspots", "3"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "0 baseline regression(s)" in out
    assert "ridge" in out                    # the hotspot table printed
    # a stale baseline entry is a note, not a failure
    base["programs"]["ghost/step"] = {"kernel_count": 1,
                                      "predicted_step_ms": 1.0}
    with open(path, "w") as f:
        json.dump(base, f)
    rc = fc.main(["--models", "mlp", "--quiet", "--baseline", path])
    out = capsys.readouterr().out
    assert rc == 0
    assert "stale" in out
