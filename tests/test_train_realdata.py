"""End-to-end convergence on REAL images through the full data plane:
JPEG -> RecordIO -> native fused decode/augment (ImageRecordIter) ->
Module.fit conv net -> accuracy gate.

Ref strategy: tests/python/train/test_conv.py (MNIST conv to 0.93) and
tests/nightly/test_all.sh:44-67 (train jobs gated on validation accuracy).
"""
import io as _io
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import recordio

PIL = pytest.importorskip("PIL.Image")


def _make_color_rec(path, n=256, h=64, w=64, seed=0):
    """Color-separable 4-class dataset: class k has a dominant color patch
    whose position/size jitter, so rand_crop/mirror keep it learnable but
    non-trivial."""
    colors = np.array([[200, 40, 40], [40, 200, 40], [40, 40, 200],
                       [200, 200, 40]], np.float32)
    rng = np.random.default_rng(seed)
    idx = os.path.splitext(path)[0] + ".idx"
    rec = recordio.MXIndexedRecordIO(idx, path, "w")
    for i in range(n):
        k = i % 4
        img = rng.normal(110, 25, size=(h, w, 3))
        img = (img + 0.55 * (colors[k] - 110)).clip(0, 255)
        img = img.astype(np.uint8)
        buf = _io.BytesIO()
        PIL.fromarray(img).save(buf, format="JPEG", quality=92)
        rec.write_idx(i, recordio.pack(
            recordio.IRHeader(0, float(k), i, 0), buf.getvalue()))
    rec.close()
    return path


def _small_convnet(num_classes=4):
    data = mx.sym.Variable("data")
    net = mx.sym.Convolution(data=data, num_filter=16, kernel=(3, 3),
                             stride=(2, 2), pad=(1, 1), name="c1")
    net = mx.sym.BatchNorm(data=net, fix_gamma=False, name="bn1")
    net = mx.sym.Activation(data=net, act_type="relu")
    net = mx.sym.Convolution(data=net, num_filter=32, kernel=(3, 3),
                             stride=(2, 2), pad=(1, 1), name="c2")
    net = mx.sym.Activation(data=net, act_type="relu")
    net = mx.sym.Pooling(data=net, global_pool=True, kernel=(1, 1),
                         pool_type="avg")
    net = mx.sym.Flatten(data=net)
    net = mx.sym.FullyConnected(data=net, num_hidden=num_classes, name="fc")
    return mx.sym.SoftmaxOutput(data=net, name="softmax")


@pytest.mark.skipif(not os.path.exists("lib/libmxtpu_io.so")
                    and not os.path.exists("src/io/image_decode.cc"),
                    reason="native IO library unavailable")
def test_conv_convergence_on_real_images(tmp_path):
    rec = _make_color_rec(str(tmp_path / "color.rec"))
    train = mx.image.ImageRecordIter(
        path_imgrec=rec, data_shape=(3, 48, 48), batch_size=32,
        shuffle=True, rand_crop=True, rand_mirror=True, resize=56,
        mean_r=110.0, mean_g=110.0, mean_b=110.0,
        std_r=60.0, std_g=60.0, std_b=60.0, seed=1)
    val = mx.image.ImageRecordIter(
        path_imgrec=rec, data_shape=(3, 48, 48), batch_size=32,
        resize=56,
        mean_r=110.0, mean_g=110.0, mean_b=110.0,
        std_r=60.0, std_g=60.0, std_b=60.0)
    mod = mx.mod.Module(_small_convnet())
    mod.fit(train, num_epoch=4,
            initializer=mx.initializer.Xavier(),
            optimizer="sgd",
            optimizer_params={"learning_rate": 0.1, "momentum": 0.9})
    acc = mod.score(val, mx.metric.Accuracy())[0][1]
    assert acc >= 0.9, "real-image convergence gate: acc %.3f < 0.9" % acc


def test_record_iter_feeds_module_shapes(tmp_path):
    rec = _make_color_rec(str(tmp_path / "c2.rec"), n=64)
    it = mx.image.ImageRecordIter(path_imgrec=rec, data_shape=(3, 32, 32),
                                  batch_size=16, resize=40)
    b = it.next()
    assert b.data[0].shape == (16, 3, 32, 32)
    assert b.label[0].shape == (16,)
    assert it.provide_data[0].shape == (16, 3, 32, 32)
