"""RTC (Pallas user kernels), torch plugin, Predictor tests."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import sym, nd


def test_pallas_kernel_basic():
    from jax.experimental import pallas as pl

    def scale_kernel(x_ref, o_ref):
        o_ref[...] = x_ref[...] * 2.0

    k = mx.rtc.PallasKernel(scale_kernel, out_like=0)
    y = k(nd.ones((8, 128)))
    assert (y.asnumpy() == 2.0).all()


def test_pallas_kernel_two_inputs():
    def addmul_kernel(a_ref, b_ref, o_ref):
        o_ref[...] = a_ref[...] * b_ref[...] + a_ref[...]

    k = mx.rtc.PallasKernel(addmul_kernel, out_like=0)
    a = np.random.rand(8, 128).astype(np.float32)
    b = np.random.rand(8, 128).astype(np.float32)
    y = k(nd.array(a), nd.array(b))
    assert np.allclose(y.asnumpy(), a * b + a, rtol=1e-5)


def test_rtc_cuda_shim_errors():
    with pytest.raises(mx.MXNetError):
        mx.rtc.Rtc("k", [], [], "__global__ void k(){}")


def test_torch_module_forward_backward():
    torch = pytest.importorskip("torch")
    import torch.nn as tnn
    from mxnet_tpu.plugin.torch_module import TorchModule

    lin = tnn.Linear(4, 3)
    op = TorchModule(lin)
    x = np.random.rand(2, 4).astype(np.float32)
    y = op(nd.array(x))
    with torch.no_grad():
        expect = lin(torch.from_numpy(x)).numpy()
    assert np.allclose(y.asnumpy(), expect, rtol=1e-5)

    # symbolic with gradient through the torch module
    s = op.get_symbol(sym.Variable("data"))
    ag = nd.zeros((2, 4))
    ex = s.bind(mx.cpu(), {"data": nd.array(x)}, args_grad={"data": ag})
    ex.forward(is_train=True)
    ex.backward(out_grads=nd.ones((2, 3)))
    expect_grad = np.ones((2, 3), np.float32) @ lin.weight.detach().numpy()
    assert np.allclose(ag.asnumpy(), expect_grad, rtol=1e-4)


def test_torch_criterion():
    torch = pytest.importorskip("torch")
    import torch.nn as tnn
    from mxnet_tpu.plugin.torch_module import TorchCriterion

    crit = TorchCriterion(tnn.MSELoss())
    x = np.array([[1.0, 2.0]], np.float32)
    t = np.array([[0.0, 0.0]], np.float32)
    loss = crit(nd.array(x), nd.array(t))
    assert np.allclose(loss.asnumpy(), [(1 + 4) / 2], rtol=1e-5)


def test_predictor_roundtrip(tmp_path):
    # train a tiny model, checkpoint, predict via the standalone Predictor
    data = sym.Variable("data")
    net = sym.FullyConnected(data=data, num_hidden=4, name="fc")
    net = sym.SoftmaxOutput(data=net, name="softmax")
    X = np.random.rand(32, 6).astype(np.float32)
    y = (np.arange(32) % 4).astype(np.float32)
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.fit(mx.io.NDArrayIter(X, y, 8), num_epoch=1)
    prefix = str(tmp_path / "m")
    mod.save_checkpoint(prefix, 1)

    pred = mx.Predictor(prefix + "-symbol.json", prefix + "-0001.params",
                        {"data": (8, 6)})
    out = pred.forward(data=X[:8]).get_output(0)
    ref = mod.predict(mx.io.NDArrayIter(X[:8], None, 8)).asnumpy()
    assert np.allclose(out.asnumpy(), ref, rtol=1e-5)


def test_kvstore_server_role_collapse(monkeypatch):
    import mxnet_tpu.kvstore_server as ks
    monkeypatch.setenv("DMLC_ROLE", "server")
    with pytest.raises(RuntimeError):
        ks.init()
    monkeypatch.setenv("DMLC_ROLE", "worker")
    ks.init()  # no coordinator env: returns without error
