"""Round-4 bug-sweep regressions.

Pins the round-3 advisor/judge findings: optimizer/symbol picklability
(dist_sync set_optimizer pickles the Optimizer holding the Symbol),
checkpoint-reproducible fused-step RNG streams, and sharded-assignment
robustness.
"""
import pickle

import jax

import numpy as np
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import optimizer as opt
from mxnet_tpu.train_step import TrainStep


def _all_model_symbols():
    from mxnet_tpu import models
    return [models.get_symbol(name, num_classes=10)
            for name in ["mlp", "lenet", "alexnet", "vgg", "resnet",
                         "inception-bn"]]


def test_optimizer_with_every_model_symbol_pickles():
    """KVStore.set_optimizer pickles the Optimizer; the Optimizer holds the
    Symbol for lr_mult resolution (ref: python/mxnet/kvstore.py:226), so
    every model symbol must survive a pickle round-trip."""
    for sym in _all_model_symbols():
        o = opt.SGD(learning_rate=0.1, sym=sym)
        o2 = pickle.loads(pickle.dumps(o))
        assert o2.lr == o.lr
        # the restored symbol must still infer types (rules intact)
        s2 = o2.sym
        assert s2 is not None
        assert s2.list_arguments() == sym.list_arguments()


def test_loss_head_symbol_pickles_and_infers():
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=4)
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    net2 = pickle.loads(pickle.dumps(net))
    _, out_types, _ = net2.infer_type(data=np.float32)
    assert out_types[0] == np.dtype(np.float32)


def _tiny_dropout_net():
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=8, name="fc1")
    net = mx.sym.Dropout(net, p=0.5)
    net = mx.sym.FullyConnected(net, num_hidden=4, name="fc2")
    return mx.sym.LinearRegressionOutput(net, name="out")


def test_trainstep_rng_follows_state_step():
    """The dropout stream is a function of state["step"], so replaying from
    a restored state reproduces the same noise sequence (advisor r3 low:
    host-side counters diverge from checkpointed step counts)."""
    mx.random.seed(7)
    sym = _tiny_dropout_net()
    ts = TrainStep(sym, label_names=("out_label",),
                   optimizer=opt.SGD(learning_rate=0.05))
    state0 = ts.init({"data": (4, 6)}, {"out_label": (4, 4)})
    batch = {"data": np.ones((4, 6), np.float32),
             "out_label": np.zeros((4, 4), np.float32)}
    # advance two steps, remember outputs; checkpoint s1 to host first
    # (the fused step donates its input state buffers)
    s1, o1 = ts.step(state0, batch)
    ckpt = jax.tree_util.tree_map(np.asarray, s1)
    s2, o2 = ts.step(s1, batch)
    # replay step 2 from the restored checkpoint: same noise -> same out
    restored = jax.tree_util.tree_map(jnp.asarray, ckpt)
    s2b, o2b = ts.step(restored, batch)
    np.testing.assert_allclose(np.asarray(o2[0]), np.asarray(o2b[0]),
                               rtol=0, atol=0)
    # but step 1 vs step 2 differ (noise actually varies by step)
    assert not np.allclose(np.asarray(o1[0]), np.asarray(o2[0]))


def test_batchnorm_onepass_bf16_matches_numpy():
    """bf16 activations take the fused one-pass E[x^2]-E[x]^2 stats path;
    numerics must match a float64 reference within bf16 tolerance, including
    ill-conditioned data with |mean| >> std."""
    from mxnet_tpu.ops import registry as reg
    from mxnet_tpu.ops.registry import OpContext
    rng = np.random.default_rng(0)
    x64 = 100.0 + 0.5 * rng.normal(size=(8, 4, 5, 5))
    x = jnp.asarray(x64, jnp.bfloat16)
    gamma = jnp.ones((4,), jnp.bfloat16)
    beta = jnp.zeros((4,), jnp.bfloat16)
    mm = jnp.zeros((4,), jnp.float32)
    mv = jnp.ones((4,), jnp.float32)
    op = reg.get("BatchNorm")
    (y,), (nm, nv) = op.apply(OpContext(is_train=True), {"fix_gamma": "False"},
                              [x, gamma, beta], [mm, mv])
    xf = np.asarray(x, np.float64)  # reference stats over the bf16-rounded data
    m = xf.mean(axis=(0, 2, 3))
    v = xf.var(axis=(0, 2, 3))
    yref = (xf - m[None, :, None, None]) / np.sqrt(v[None, :, None, None] + 1e-3)
    # y is computed in bf16: (x - mean) at |x|~100 carries up to 0.25 abs
    # quantization (ulp 0.5), ~0.5 after scaling by 1/std=2. The loose bound
    # still catches the cancellation failure mode (var collapsing to ~0
    # inflates y by ~1/sqrt(eps) ~ 30x).
    np.testing.assert_allclose(np.asarray(y, np.float64), yref, atol=0.7)
    np.testing.assert_allclose(np.asarray(nm), 0.9 * 0 + 0.1 * m, rtol=0.02)
    np.testing.assert_allclose(np.asarray(nv), 0.9 * 1 + 0.1 * v, rtol=0.05)
