"""Parallelism tests: mesh helpers, blockwise/ring/Ulysses attention over the
virtual device mesh (the long-context story, SURVEY.md §5)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mxnet_tpu.parallel import make_mesh, data_parallel_mesh, grad_sync
from mxnet_tpu.parallel.ring import (blockwise_attention, ring_attention,
                                     ulysses_attention)


def _naive_attention(q, k, v, causal=False):
    d = q.shape[-1]
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / jnp.sqrt(d)
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        s = jnp.where(mask, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


def _qkv(b=2, h=2, s=32, d=8, seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(rng.normal(size=(b, h, s, d)).astype(np.float32))
    return mk(), mk(), mk()


def test_blockwise_attention_matches_naive():
    q, k, v = _qkv()
    out = blockwise_attention(q, k, v, block_size=8)
    ref = _naive_attention(q, k, v)
    assert np.allclose(out, ref, rtol=1e-4, atol=1e-5)


def test_blockwise_attention_causal():
    q, k, v = _qkv()
    out = blockwise_attention(q, k, v, block_size=8, causal=True)
    ref = _naive_attention(q, k, v, causal=True)
    assert np.allclose(out, ref, rtol=1e-4, atol=1e-5)


def test_blockwise_attention_ragged():
    q, k, v = _qkv(s=30)  # not a multiple of the block size
    out = blockwise_attention(q, k, v, block_size=8)
    ref = _naive_attention(q, k, v)
    assert np.allclose(out, ref, rtol=1e-4, atol=1e-5)


def _seq_mesh(n):
    devs = jax.devices()[:n]
    return jax.sharding.Mesh(np.array(devs), ("seq",))


def test_ring_attention_matches_full():
    """Ring attention over a 4-device 'seq' axis == full attention."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P
    n = 4
    mesh = _seq_mesh(n)
    q, k, v = _qkv(s=32)
    ref = _naive_attention(q, k, v)

    fn = shard_map(lambda q, k, v: ring_attention(q, k, v, axis_name="seq"),
                   mesh=mesh,
                   in_specs=(P(None, None, "seq", None),) * 3,
                   out_specs=P(None, None, "seq", None))
    out = fn(q, k, v)
    assert np.allclose(out, ref, rtol=1e-4, atol=1e-5)


def test_ring_attention_causal():
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P
    n = 4
    mesh = _seq_mesh(n)
    q, k, v = _qkv(s=32, seed=3)
    ref = _naive_attention(q, k, v, causal=True)
    fn = shard_map(lambda q, k, v: ring_attention(q, k, v, axis_name="seq",
                                                  causal=True),
                   mesh=mesh,
                   in_specs=(P(None, None, "seq", None),) * 3,
                   out_specs=P(None, None, "seq", None))
    out = fn(q, k, v)
    assert np.allclose(out, ref, rtol=1e-4, atol=1e-5)


def test_ulysses_attention_matches_full():
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P
    n = 2
    mesh = _seq_mesh(n)
    q, k, v = _qkv(b=1, h=4, s=16, seed=5)
    ref = _naive_attention(q, k, v)
    fn = shard_map(lambda q, k, v: ulysses_attention(q, k, v,
                                                     axis_name="seq"),
                   mesh=mesh,
                   in_specs=(P(None, None, "seq", None),) * 3,
                   out_specs=P(None, None, "seq", None))
    out = fn(q, k, v)
    assert np.allclose(out, ref, rtol=1e-4, atol=1e-5)


def test_make_mesh_and_grad_sync():
    mesh = make_mesh({"data": 4, "model": 2})
    assert mesh.shape == {"data": 4, "model": 2}
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P
    dp = data_parallel_mesh(4)

    def f(g):
        return grad_sync({"w": g}, "data")["w"]

    fn = shard_map(f, mesh=dp, in_specs=P("data"), out_specs=P("data"))
    g = jnp.arange(8.0)
    out = fn(g)
    # psum over 4 shards of 2: every element = sum of its shard-position peers
    expect = np.tile(np.array([0 + 2 + 4 + 6, 1 + 3 + 5 + 7]), 4)
    assert np.allclose(out, expect)


def test_mesh_size_mismatch_error():
    import mxnet_tpu as mx
    with pytest.raises(mx.MXNetError):
        make_mesh({"data": 16})  # more than available devices
