"""Unified observability tests (docs/observability.md): the host-span
tracer (Chrome trace-event JSON, correlation IDs, near-zero-cost off
mode), the one metrics registry (typed instruments + views over the five
legacy health/stats objects, Prometheus export, windowed deltas), the
crash flight recorder (ring bounds, atomic never-raising dumps, the
guard-divergence and fleet-replica-death triggers via faults.py), the
deferred profiler autostart, and the Speedometer suffix consolidation
onto ``obs.registry.Window``.
"""
import json
import logging
import os
import sys
import threading
import time

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import faults, guard as guard_mod, obs, serving, sym  # noqa: E402
from mxnet_tpu.base import MXNetError  # noqa: E402
from mxnet_tpu.guard import TrainingGuard, TrainingDivergedError  # noqa: E402
from mxnet_tpu.obs import flight as obs_flight  # noqa: E402
from mxnet_tpu.obs import registry as obs_registry  # noqa: E402
from mxnet_tpu.obs import trace as obs_trace  # noqa: E402


@pytest.fixture(autouse=True)
def _clean_slate(tmp_path, monkeypatch):
    """Each test starts with the tracer off+empty, the flight recorder
    empty and dumping into a throwaway path, and no armed faults."""
    faults.clear()
    obs_trace.stop()
    obs_trace.clear()
    obs_flight.FLIGHT.clear()
    guard_mod.TRAINING_HEALTH.reset()
    monkeypatch.setenv("MXTPU_FLIGHT_RECORDER_PATH",
                       str(tmp_path / "flight.json"))
    yield
    faults.clear()
    obs_trace.stop()
    obs_trace.clear()
    obs_flight.FLIGHT.clear()
    guard_mod.TRAINING_HEALTH.reset()


# ---------------------------------------------------------------------------
# tracer
# ---------------------------------------------------------------------------

def test_span_off_is_shared_noop():
    """Tracing+recording off: span() returns ONE shared no-op instance —
    no allocation on the hot path — and records nothing."""
    was = obs_flight.enabled()
    obs_flight.set_enabled(False)
    try:
        a = obs_trace.span("x", dispatch=1)
        b = obs_trace.span("y")
        assert a is b is obs_trace._NOOP
        with a:
            pass
        obs_trace.complete("z", 0.1)
        obs_trace.instant("w")
        obs_trace.async_complete("v", 0.1, id=1)
        assert obs_trace.events() == []
    finally:
        obs_flight.set_enabled(was)


def test_span_records_args_nesting_and_thread_metadata():
    obs_trace.start()
    with obs_trace.span("outer", dispatch=3):
        with obs_trace.span("inner", dispatch=3, k=4):
            time.sleep(0.001)
    evs = obs_trace.events()
    meta = [e for e in evs if e["ph"] == "M"]
    assert meta and meta[0]["name"] == "thread_name"
    spans = {e["name"]: e for e in evs if e["ph"] == "X"}
    assert spans["inner"]["args"] == {"dispatch": 3, "k": 4}
    assert spans["outer"]["args"] == {"dispatch": 3}
    # inner nests inside outer on the same track
    o, i = spans["outer"], spans["inner"]
    assert o["ts"] <= i["ts"]
    assert i["ts"] + i["dur"] <= o["ts"] + o["dur"]
    assert obs_trace.nest_check(evs) == []


def test_span_exception_annotates_and_propagates():
    obs_trace.start()
    with pytest.raises(ValueError):
        with obs_trace.span("bad"):
            raise ValueError("boom")
    ev = [e for e in obs_trace.events() if e["ph"] == "X"][0]
    assert ev["args"]["error"] == "ValueError"


def test_complete_backdates_and_instant_marks():
    obs_trace.start()
    obs_trace.complete("measured", 0.05, dispatch=7)
    obs_trace.instant("mark", req=9)
    evs = obs_trace.events()
    comp = [e for e in evs if e["name"] == "measured"][0]
    inst = [e for e in evs if e["name"] == "mark"][0]
    assert comp["ph"] == "X" and comp["dur"] >= 49000  # ~50ms in us
    assert comp["args"]["dispatch"] == 7
    assert inst["ph"] == "i" and inst["args"]["req"] == 9


def test_async_complete_emits_begin_end_pair():
    obs_trace.start()
    obs_trace.async_complete("serve_queue", 0.02, id=42, req=42)
    b, e = [ev for ev in obs_trace.events() if ev["ph"] in ("b", "e")]
    assert b["ph"] == "b" and e["ph"] == "e"
    assert b["id"] == e["id"] == 42
    assert e["ts"] - b["ts"] >= 19000


def test_save_writes_perfetto_loadable_chrome_json(tmp_path):
    obs_trace.start()
    with obs_trace.span("s", dispatch=0):
        pass
    p = obs_trace.save(str(tmp_path / "t.json"))
    doc = json.load(open(p))
    assert isinstance(doc["traceEvents"], list) and doc["traceEvents"]
    for ev in doc["traceEvents"]:
        assert "ph" in ev and "pid" in ev and "tid" in ev
        if ev["ph"] == "X":
            assert "ts" in ev and "dur" in ev
    assert doc["otherData"]["dropped_events"] == 0


def test_trace_buffer_is_bounded(monkeypatch):
    monkeypatch.setattr(obs_trace, "_MAX_EVENTS", 10)
    obs_trace.start()
    for i in range(50):
        obs_trace.instant("e%d" % i)
    assert len(obs_trace.events()) <= 10
    p = obs_trace.save()
    try:
        assert json.load(open(p))["otherData"]["dropped_events"] > 0
    finally:
        os.unlink(p)


def test_spans_from_many_threads_all_land():
    obs_trace.start()

    def work(n):
        for i in range(20):
            with obs_trace.span("t%d" % n, i=i):
                pass

    ts = [threading.Thread(target=work, args=(n,)) for n in range(4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    spans = [e for e in obs_trace.events() if e["ph"] == "X"]
    assert len(spans) == 80
    assert obs_trace.nest_check(obs_trace.events()) == []


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------

def test_registry_typed_instruments():
    reg = obs_registry.Registry()
    c = reg.counter("req_total", "requests")
    g = reg.gauge("queue_depth")
    h = reg.histogram("latency_s")
    c.inc()
    c.inc(4)
    g.set(7)
    h.observe(0.5)
    h.observe(1.5)
    snap = reg.snapshot()
    assert snap["req_total"] == 5
    assert snap["queue_depth"] == 7.0
    assert snap["latency_s_count"] == 2
    assert snap["latency_s_sum"] == 2.0
    assert snap["latency_s_min"] == 0.5 and snap["latency_s_max"] == 1.5
    with pytest.raises(MXNetError, match="must be >= 0"):
        c.inc(-1)
    # idempotent re-get; kind conflict raises
    assert reg.counter("req_total") is c
    with pytest.raises(MXNetError, match="already registered"):
        reg.gauge("req_total")


def test_registry_snapshot_carries_every_legacy_health_key():
    """The five legacy process-global objects are registry views: every
    key of every report() appears in ONE flat snapshot — the back-compat
    mirrors stay untouched."""
    from mxnet_tpu import io as mxio, tracecheck
    from mxnet_tpu.data import stats as dstats
    from mxnet_tpu.serving import health as shealth
    snap = obs.REGISTRY.snapshot()
    expect = {
        "data_health": mxio.DATA_HEALTH.report(),
        "training_health": guard_mod.TRAINING_HEALTH.report(),
        "serving_health": shealth.SERVING_HEALTH.report(),
        "pipeline_stats": dstats.PIPELINE_STATS.report(),
        "retrace_events": {"count": tracecheck.retrace_count()},
    }
    for view, rep in expect.items():
        for key in rep:
            assert "%s.%s" % (view, key) in snap, (view, key)


def test_registry_view_error_does_not_break_snapshot():
    reg = obs_registry.Registry()
    reg.counter("ok").inc()
    reg.register_view("bad", lambda: 1 / 0)
    snap = reg.snapshot()
    assert snap["ok"] == 1
    assert "ZeroDivisionError" in snap["bad.error"]


def test_prometheus_export_numeric_and_mangled():
    reg = obs_registry.Registry()
    reg.counter("serve.requests").inc(3)
    reg.register_view("v", lambda: {"x": 1.5, "last_error": "nope"})
    text = reg.to_prometheus()
    assert "serve_requests 3" in text
    assert "v_x 1.5" in text
    assert "nope" not in text           # strings never exported
    assert "# TYPE serve_requests counter" in text


def test_window_delta_peek_rebase_and_keying():
    vals = {"a": 0, "s": "str"}
    w = obs_registry.Window(lambda: dict(vals))
    vals["a"] = 5
    assert w.delta() == {"a": 5, "s": "str"}
    vals["a"] = 7
    assert w.peek() == {"a": 2, "s": "str"}   # peek does NOT advance
    assert w.delta() == {"a": 2, "s": "str"}
    w.rebase()
    assert w.delta() == {"a": 0, "s": "str"}
    # keyed window refuses a foreign source without touching the baseline
    key = object()
    wk = obs_registry.Window(lambda: dict(vals), key=key)
    vals["a"] = 17
    assert wk.delta(object()) is None
    assert wk.delta(key) == {"a": 10, "s": "str"}
    with pytest.raises(MXNetError, match="callable"):
        obs_registry.Window(42)


def test_registry_window_over_global_views():
    w = obs.REGISTRY.window()
    guard_mod.TRAINING_HEALTH.record_steps(4, 1)
    d = w.delta()
    assert d["training_health.steps"] == 4
    assert d["training_health.skipped"] == 1


# ---------------------------------------------------------------------------
# Speedometer consolidation (one Window mechanism behind every suffix)
# ---------------------------------------------------------------------------

def _bep(nbatch, locals_):
    from mxnet_tpu.module.base_module import BatchEndParam
    return BatchEndParam(epoch=0, nbatch=nbatch, eval_metric=None,
                         locals=locals_)


def test_speedometer_interleaved_pipelines_keep_separate_baselines(caplog):
    """Two pipelined runs alternating on ONE Speedometer each report only
    their own window — the per-source Window keying makes cross-charging
    impossible (the stronger form of the PR 4 interleave fix)."""
    from mxnet_tpu.callback import Speedometer

    class _P(object):
        def __init__(self):
            self.depth = 2
            self.host_stall = 0.0

    p1, p2 = _P(), _P()
    sp = Speedometer(batch_size=16, frequent=4)
    with caplog.at_level(logging.INFO):
        sp(_bep(1, {"pipeline": p1}))       # init: baselines p1 at 0
        p1.host_stall += 1.0
        p2.host_stall += 9.0                # p2 accumulates elsewhere
        sp(_bep(5, {"pipeline": p1}))       # fire: p1 window = 1.0
        sp(_bep(0, {"pipeline": p2}))       # re-init on p2's stream
        p2.host_stall += 0.5
        sp(_bep(5, {"pipeline": p2}))       # fire: p2 window = 0.5, NOT 9.5
    piped = [r.getMessage() for r in caplog.records
             if "Pipeline:" in r.getMessage()]
    assert "host_stall=1.000s" in piped[0], piped
    assert "host_stall=0.500s" in piped[1], piped


def test_speedometer_data_suffix_windows_per_source(caplog):
    from mxnet_tpu.callback import Speedometer
    from mxnet_tpu.data.stats import PipelineStats

    st = PipelineStats()
    sp = Speedometer(batch_size=16, frequent=4)
    with caplog.at_level(logging.INFO):
        st.add("stall", 2.0)
        sp(_bep(1, {"data_stats": st}))     # init: baseline at 2.0
        st.add("stall", 0.25)
        sp(_bep(5, {"data_stats": st}))     # fire: window = 0.25
    lines = [r.getMessage() for r in caplog.records
             if "Data:" in r.getMessage()]
    assert lines and "stall=0.250s" in lines[0], lines


def test_speedometer_windows_share_one_mechanism():
    """The consolidation claim itself: every windowed suffix's state is an
    obs.registry.Window in ONE store — no per-suffix baseline attributes
    left to drift."""
    from mxnet_tpu.callback import Speedometer

    class _P(object):
        depth = 1
        host_stall = 0.0

    sp = Speedometer(batch_size=1, frequent=10)
    sp(_bep(1, {"pipeline": _P()}))
    assert sp._windows, "suffixes must register Windows"
    for _wr, w in sp._windows.values():
        assert isinstance(w, obs_registry.Window)
    for legacy in ("_stall_seen", "_data_stall_seen", "_retrace_base"):
        assert not hasattr(sp, legacy), legacy


def test_speedometer_window_store_does_not_retain_dead_runs():
    """A long-lived Speedometer reused across many runs must not pin each
    dead run's pipeline/stats objects: sources are held weakly and dead
    entries are pruned."""
    import gc
    import weakref
    from mxnet_tpu.callback import Speedometer
    from mxnet_tpu.module.base_module import _DispatchPipeline

    sp = Speedometer(batch_size=1, frequent=10)
    p = _DispatchPipeline(2)    # the REAL (slots) pipeline class
    sp(_bep(1, {"pipeline": p}))
    ref = weakref.ref(p)
    del p
    gc.collect()
    assert ref() is None, "Speedometer must not keep the pipeline alive"
    # the next interaction prunes the dead entry
    sp(_bep(2, {"pipeline": _DispatchPipeline(1)}))
    assert len([k for k in sp._windows if k[0] == "pipeline"]) == 1


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------

def test_flight_ring_is_bounded_and_fed_by_spans():
    rec = obs_flight.FlightRecorder(ring=16)
    for i in range(100):
        rec.on_event({"ph": "X", "name": "s%d" % i})
    assert len(rec._spans) == 16
    assert rec._spans[-1]["name"] == "s99"


def test_flight_note_captures_registry_deltas():
    reg = obs_registry.Registry()
    c = reg.counter("x")
    rec = obs_flight.FlightRecorder(ring=16, registry=reg)
    rec.note("dispatch_retired", dispatch=0)   # first note: baseline
    c.inc(3)
    rec.note("dispatch_retired", dispatch=1)
    marks = list(rec._marks)
    assert marks[0]["dispatch"] == 0 and marks[0]["delta"] == {}
    assert marks[1]["dispatch"] == 1 and marks[1]["delta"] == {"x": 3}


def test_flight_dump_atomic_and_contains_spans_counters(tmp_path):
    obs_trace.start()
    with obs_trace.span("dispatch", dispatch=5):
        pass
    obs_flight.note("dispatch_retired", dispatch=5)
    p = obs_flight.dump("unit test", path=str(tmp_path / "d.json"))
    doc = json.load(open(p))
    assert doc["reason"] == "unit test"
    assert any(ev.get("name") == "dispatch" for ev in doc["spans"])
    assert any(m.get("dispatch") == 5 for m in doc["counter_deltas"])
    assert "training_health.skipped" in doc["counters"]
    assert obs_flight.FLIGHT.last_dump_path == p


def test_flight_dump_never_raises(monkeypatch, tmp_path):
    """The dump runs INSIDE failure paths: a broken write (or an
    unserializable extra) must degrade to a logged warning, never a
    second exception."""
    import mxnet_tpu.model as model
    monkeypatch.setattr(model, "atomic_write_bytes",
                        lambda *a, **k: 1 / 0)
    assert obs_flight.dump("broken") is None
    monkeypatch.undo()
    p = obs_flight.dump("odd extra", path=str(tmp_path / "e.json"),
                        extra={"bad": object()})
    assert "object" in json.load(open(p))["extra"]["unserializable"]


def test_flight_disabled_skips_dump(monkeypatch):
    was = obs_flight.enabled()
    obs_flight.set_enabled(False)
    try:
        assert obs_flight.dump("nope") is None
    finally:
        obs_flight.set_enabled(was)


# -- fault-injected triggers (the ISSUE's acceptance paths) -----------------

def _guard_mlp():
    data = sym.Variable("data")
    net = sym.FullyConnected(data=data, num_hidden=16, name="fc1")
    net = sym.FullyConnected(data=net, num_hidden=4, name="fc2")
    return sym.SoftmaxOutput(data=net, name="softmax")


def _toy_data(n=128, dim=10, classes=4, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, dim)).astype(np.float32)
    w = rng.normal(size=(dim, classes)).astype(np.float32)
    y = np.argmax(X @ w, axis=1).astype(np.float32)
    return X, y


@pytest.mark.faults
def test_injected_divergence_produces_flight_dump(tmp_path):
    """ACCEPTANCE: an injected ``guard.grad_nan`` skip storm diverges the
    run; the TrainingDivergedError path dumps a post-mortem containing
    the correlated dispatch spans and the per-dispatch counter deltas —
    and the dump lands even though fit() raises."""
    obs_trace.start()
    dump_path = str(tmp_path / "flight.json")
    os.environ["MXTPU_FLIGHT_RECORDER_PATH"] = dump_path
    X, y = _toy_data()
    mx.random.seed(3)
    train = mx.io.NDArrayIter(X, y, batch_size=16)
    mod = mx.mod.Module(_guard_mlp(), context=mx.cpu())
    g = TrainingGuard(max_skips_per_window=2, window=50)
    faults.inject("guard.grad_nan", nth=2, times=2)
    with pytest.raises(TrainingDivergedError):
        mod.fit(train, num_epoch=1, steps_per_dispatch=4, guard=g,
                optimizer_params={"learning_rate": 0.1})
    assert os.path.exists(dump_path)
    doc = json.load(open(dump_path))
    assert "TrainingDivergedError" in doc["reason"]
    disp_spans = [ev for ev in doc["spans"]
                  if ev.get("name") == "dispatch"]
    assert disp_spans, "dump must carry the recent dispatch spans"
    assert all("dispatch" in ev["args"] for ev in disp_spans)
    retired = [m for m in doc["counter_deltas"]
               if m.get("marker") == "dispatch_retired"]
    assert retired, "dump must carry per-dispatch counter deltas"
    # the skip storm is visible in the captured deltas
    assert any(m["delta"].get("training_health.skipped")
               for m in retired)
    assert doc["extra"]["health"]["divergences"] == 1


def _serve_engine():
    net = mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=4,
                                name="fc1")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    rs = np.random.RandomState(0)
    params = {"arg:fc1_weight": rs.randn(4, 6).astype(np.float32),
              "arg:fc1_bias": rs.randn(4).astype(np.float32)}
    return serving.ServingEngine(net, params, {"data": (1, 6)},
                                 buckets=(4, 8))


@pytest.mark.faults
def test_replica_death_produces_flight_dump(tmp_path):
    """ACCEPTANCE: an injected ``fleet.replica_die`` produces a replica-
    death post-mortem with the dead replica's report and recent serving
    spans, while the fleet still recovers every request."""
    obs_trace.start()
    dump_path = str(tmp_path / "flight.json")
    os.environ["MXTPU_FLIGHT_RECORDER_PATH"] = dump_path
    router = serving.FleetRouter(
        [serving.Batcher(_serve_engine(), max_latency_ms=2.0),
         serving.Batcher(_serve_engine(), max_latency_ms=2.0)],
        tick_ms=5.0)
    try:
        faults.inject("fleet.replica_die", nth=1, kind="die")
        x = np.random.RandomState(1).randn(1, 1, 6).astype(np.float32)
        reqs = [router.submit({"data": x}, deadline_ms=15000)
                for _ in range(8)]
        for r in reqs:
            assert r.result(timeout=20.0)
    finally:
        router.close()
    assert os.path.exists(dump_path)
    doc = json.load(open(dump_path))
    assert "died" in doc["reason"]
    assert doc["extra"]["report"]["state"] == "dead"
    names = {ev.get("name") for ev in doc["spans"]}
    assert "fleet_submit" in names or "serve_dispatch" in names, names
    assert "serving_health.requests" in doc["counters"]


@pytest.mark.faults
def test_batcher_death_dump_and_decode_death_dump(tmp_path):
    obs_trace.start()
    # batcher thread death
    b = serving.Batcher(_serve_engine(), max_latency_ms=1.0,
                        fault_site="fleet.replica_die")
    faults.inject("fleet.replica_die", nth=1, kind="die")
    req = b.submit({"data": np.zeros((1, 1, 6), np.float32)},
                   deadline_ms=4000)
    with pytest.raises(serving.ServingClosedError):
        b.wait(req)
    t0 = time.monotonic()
    while obs_flight.FLIGHT.dumps < 1 and time.monotonic() - t0 < 5.0:
        time.sleep(0.01)
    assert obs_flight.FLIGHT.dumps >= 1
    assert "batcher thread died" in obs_flight.FLIGHT.last_dump["reason"]


# ---------------------------------------------------------------------------
# profiler satellites
# ---------------------------------------------------------------------------

def test_profiler_autostart_deferred_to_first_dispatch(monkeypatch):
    """MXNET_PROFILER_AUTOSTART no longer fires at import (where it would
    race profiler_set_config): the pending flag resolves at the first
    dispatch via maybe_autostart, AFTER set_config has pointed the trace
    somewhere."""
    from mxnet_tpu import profiler
    calls = []
    monkeypatch.setattr(profiler.jax.profiler, "start_trace",
                        lambda d: calls.append(d))
    monkeypatch.setattr(profiler.jax.profiler, "stop_trace", lambda: None)
    monkeypatch.setattr(profiler, "_autostart_pending", True)
    # config BEFORE the first dispatch: honored, because nothing started
    profiler.profiler_set_config(filename="/tmp/late_config.json")
    assert calls == []
    profiler.maybe_autostart()
    assert calls == ["/tmp/late_config_trace"]
    profiler._state["running"] = False
    # resolved: later dispatches are a no-op boolean check
    profiler.maybe_autostart()
    assert len(calls) == 1


def test_profiler_scope_emits_host_span(monkeypatch):
    from mxnet_tpu import profiler
    obs_trace.start()

    class _FakeAnnotation(object):
        def __init__(self, name):
            pass

        def __enter__(self):
            return self

        def __exit__(self, *a):
            return False

    monkeypatch.setattr(profiler.jax.profiler, "TraceAnnotation",
                        _FakeAnnotation)
    with profiler.Scope("user_region", epoch=3):
        pass
    spans = [e for e in obs_trace.events() if e["ph"] == "X"]
    assert spans and spans[0]["name"] == "user_region"
    assert spans[0]["args"] == {"epoch": 3}


# ---------------------------------------------------------------------------
# end-to-end: fused fit + batcher serve under MXTPU_TRACE (the CI gate's
# in-process twin)
# ---------------------------------------------------------------------------

def test_fused_fit_trace_correlates_stages_per_dispatch(tmp_path):
    obs_trace.start()
    X, y = _toy_data(64)
    mx.random.seed(0)
    it = mx.io.NDArrayIter(X, y, batch_size=8)
    mod = mx.mod.Module(_guard_mlp(), context=mx.cpu())
    mod.fit(it, num_epoch=2, steps_per_dispatch=2,
            optimizer_params={"learning_rate": 0.1},
            checkpoint_prefix=str(tmp_path / "ck"),
            checkpoint_every_n_batches=4)
    evs = obs_trace.events()
    assert obs_trace.nest_check(evs) == []
    by = {}
    for e in evs:
        if e["ph"] == "X":
            by.setdefault(e["name"], []).append(e)
    for stage in ("data_wait", "h2d", "superbatch_assemble", "dispatch",
                  "readback_stall", "checkpoint"):
        assert stage in by, (stage, sorted(by))
    # correlation: every dispatch index that was dispatched also has an
    # h2d and a readback with the SAME index
    disp = {e["args"]["dispatch"] for e in by["dispatch"]}
    h2d = {e["args"]["dispatch"] for e in by["h2d"]}
    rb = {e["args"]["dispatch"] for e in by["readback_stall"]}
    assert disp and disp <= h2d, (disp, h2d)
    assert disp == rb
    # 2 epochs x 64/(8*2) dispatches, monotonic ids
    assert sorted(disp) == list(range(len(disp)))
