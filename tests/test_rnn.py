"""RNN toolkit tests (ref strategy: tests/python/unittest/test_rnn.py —
cell unroll vs fused consistency)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import sym, nd
from mxnet_tpu.rnn import (RNNCell, LSTMCell, GRUCell, FusedRNNCell,
                           SequentialRNNCell, BidirectionalCell, DropoutCell,
                           BucketSentenceIter, encode_sentences)


def _bind_unrolled(outputs, states, batch, seq, dim, hidden, extra=None):
    net = sym.Group(outputs if isinstance(outputs, list) else [outputs])
    shapes = {"t%d_data" % i: (batch, dim) for i in range(seq)}
    if extra:
        shapes.update(extra)
    arg_shapes, out_shapes, _ = net.infer_shape_partial(**shapes)
    return net, arg_shapes, out_shapes


def test_rnn_cell_unroll_shapes():
    cell = RNNCell(num_hidden=16, prefix="rnn_")
    outputs, states = cell.unroll(3, input_prefix="rnn_")
    assert sorted(cell.params._params.keys()) == [
        "rnn_h2h_bias", "rnn_h2h_weight", "rnn_i2h_bias", "rnn_i2h_weight"]
    net = sym.Group(outputs)
    assert net.list_outputs() == ["rnn_t0_out_output", "rnn_t1_out_output",
                                  "rnn_t2_out_output"]
    shapes = {"rnn_t%d_data" % i: (10, 50) for i in range(3)}
    shapes["rnn_begin_state_0"] = (10, 16)
    _, outs, _ = net.infer_shape(**shapes)
    assert outs == [(10, 16)] * 3


def test_lstm_cell_unroll_executes():
    cell = LSTMCell(num_hidden=8, prefix="lstm_")
    outputs, states = cell.unroll(4, input_prefix="lstm_")
    net = sym.Group(outputs)
    shapes = {"lstm_t%d_data" % i: (2, 5) for i in range(4)}
    shapes.update({"lstm_begin_state_0": (2, 8),
                   "lstm_begin_state_1": (2, 8)})
    ex = net.simple_bind(mx.cpu(), **shapes)
    for k, v in ex.arg_dict.items():
        v[:] = np.random.uniform(-0.1, 0.1, v.shape)
    ex.forward()
    assert ex.outputs[0].shape == (2, 8)
    assert len(ex.outputs) == 4


def test_gru_cell():
    cell = GRUCell(num_hidden=8, prefix="gru_")
    outputs, states = cell.unroll(2, input_prefix="gru_")
    net = sym.Group(outputs)
    shapes = {"gru_t%d_data" % i: (2, 4) for i in range(2)}
    shapes["gru_begin_state_0"] = (2, 8)
    ex = net.simple_bind(mx.cpu(), **shapes)
    ex.forward()
    assert ex.outputs[0].shape == (2, 8)


def test_fused_rnn_op_shapes():
    from mxnet_tpu.ops.rnn_op import rnn_param_size
    T, N, C, H, L = 5, 3, 4, 8, 2
    psize = rnn_param_size("lstm", C, H, L, False)
    data = nd.array(np.random.uniform(-1, 1, (T, N, C)).astype(np.float32))
    params = nd.array(np.random.uniform(-0.1, 0.1, (psize,)).astype(np.float32))
    state = nd.zeros((L, N, H))
    cell_state = nd.zeros((L, N, H))
    out = mx.nd.RNN(data, params, state, cell_state, state_size=H,
                    num_layers=L, mode="lstm", state_outputs=True)
    assert out[0].shape == (T, N, H)
    assert out[1].shape == (L, N, H)
    assert out[2].shape == (L, N, H)


def test_fused_vs_unrolled_lstm_consistency():
    """The reference's central RNN test: FusedRNNCell == its unfuse()
    (ref: test_rnn.py fused vs cell consistency)."""
    T, N, C, H = 4, 2, 3, 5
    fused = FusedRNNCell(H, num_layers=1, mode="lstm", prefix="lstm_",
                         get_next_state=True)
    data = sym.Variable("data")  # (N, T, C) NTC
    f_out, f_states = fused.unroll(T, inputs=data, layout="NTC",
                                   merge_outputs=True)
    f_net = f_out

    unfused = fused.unfuse()
    u_out, u_states = unfused.unroll(
        T, inputs=sym.Variable("data"), layout="NTC", merge_outputs=True)

    x = np.random.uniform(-1, 1, (N, T, C)).astype(np.float32)
    from mxnet_tpu.ops.rnn_op import rnn_param_size
    psize = rnn_param_size("lstm", C, H, 1, False)
    flat = np.random.uniform(-0.2, 0.2, psize).astype(np.float32)

    # fused executor
    f_args = {"data": nd.array(x), "lstm_parameters": nd.array(flat),
              "lstm_begin_state_0": nd.zeros((1, N, H)),
              "lstm_begin_state_1": nd.zeros((1, N, H))}
    f_ex = f_net.bind(mx.cpu(), f_args)
    f_ex.forward()
    fused_out = f_ex.outputs[0].asnumpy()

    # unfused executor with unpacked weights
    unpacked = fused.unpack_weights({"lstm_parameters": nd.array(flat)})
    u_args = {"data": nd.array(x)}
    u_args.update(unpacked)
    u_arg_names = sym.Group(u_out if isinstance(u_out, list) else [u_out]
                            ).list_arguments()
    for name in u_arg_names:
        if "begin_state" in name:
            u_args[name] = nd.zeros((N, H))
    u_args = {k: v for k, v in u_args.items() if k in u_arg_names}
    u_ex = u_out.bind(mx.cpu(), u_args)
    u_ex.forward()
    unfused_out = u_ex.outputs[0].asnumpy()
    assert fused_out.shape == unfused_out.shape
    assert np.allclose(fused_out, unfused_out, rtol=1e-3, atol=1e-5), \
        np.abs(fused_out - unfused_out).max()


def test_bidirectional_cell():
    cell = BidirectionalCell(LSTMCell(4, prefix="l_"),
                             LSTMCell(4, prefix="r_"))
    outputs, states = cell.unroll(3, inputs=[sym.Variable("t%d" % i)
                                             for i in range(3)])
    net = sym.Group(outputs)
    shapes = {"t%d" % i: (2, 5) for i in range(3)}
    shapes.update({"l_begin_state_0": (2, 4), "l_begin_state_1": (2, 4),
                   "r_begin_state_0": (2, 4), "r_begin_state_1": (2, 4)})
    _, outs, _ = net.infer_shape(**shapes)
    assert outs == [(2, 8)] * 3  # concat of both directions


def test_sequential_stack():
    stack = SequentialRNNCell()
    stack.add(LSTMCell(8, prefix="l0_"))
    stack.add(DropoutCell(0.5, prefix="d0_"))
    stack.add(LSTMCell(8, prefix="l1_"))
    outputs, states = stack.unroll(2, inputs=[sym.Variable("t0"),
                                              sym.Variable("t1")])
    assert len(states) == 4  # two LSTM cells x (h, c)


def test_bucket_sentence_iter():
    sentences = [[1, 2, 3], [4, 5], [1, 2, 3, 4, 5, 6], [7, 8], [1, 2],
                 [3, 4], [5, 6], [7, 8, 9]]
    it = BucketSentenceIter(sentences, batch_size=2, buckets=[3, 7],
                            invalid_label=0, layout="NT")
    assert it.default_bucket_key == 7
    batches = list(it)
    assert len(batches) >= 2
    for b in batches:
        assert b.bucket_key in (3, 7)
        assert b.data[0].shape == (2, b.bucket_key)


def test_encode_sentences():
    sents = [["a", "b"], ["b", "c"]]
    coded, vocab = encode_sentences(sents, invalid_label=0, start_label=1)
    assert len(vocab) >= 3
    assert coded[0][1] == coded[1][0]  # same token -> same id
