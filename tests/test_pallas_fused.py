"""Pallas conv+BN-stats fusion and NHWC layout support.

Kernel numerics run in pallas interpret mode (CPU); the executor fusion
pass is exercised end-to-end with MXTPU_FUSE_CONV_BN=interpret.
Ref role: cuDNN fused conv+BN epilogues (src/operator/cudnn_batch_norm-inl.h).
"""
import os

import numpy as np
import pytest
import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu.ops import pallas_fused as pf


def test_matmul_stats_forward():
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(64, 32), jnp.float32)
    w = jnp.asarray(rng.randn(32, 128), jnp.float32)
    y, s1, s2 = pf.matmul_stats(x, w, True)
    yr = np.asarray(x) @ np.asarray(w)
    np.testing.assert_allclose(np.asarray(y), yr, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(s1), yr.sum(0), rtol=1e-4)
    np.testing.assert_allclose(np.asarray(s2), (yr * yr).sum(0), rtol=1e-4)


def test_matmul_stats_grad_vs_reference():
    rng = np.random.RandomState(1)
    M, K, N = 32, 16, 128
    x = jnp.asarray(rng.randn(M, K), jnp.float32)
    w = jnp.asarray(rng.randn(K, N), jnp.float32)
    t = jnp.asarray(rng.randn(M, N), jnp.float32)

    def loss(fused):
        def f(x, w):
            if fused:
                y, s1, s2 = pf.matmul_stats(x, w, True)
            else:
                y = x @ w
                s1, s2 = jnp.sum(y, 0), jnp.sum(y * y, 0)
            mean = s1 / M
            var = s2 / M - mean ** 2
            z = (y - mean[None]) * jax.lax.rsqrt(var[None] + 1e-5)
            return jnp.sum(z * t)
        return jax.grad(f, argnums=(0, 1))(x, w)

    for a, b in zip(loss(True), loss(False)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


def test_matmul_stats_fallback_shapes():
    # N not 128-aligned and M with no 16-divisor: XLA fallback, same results
    rng = np.random.RandomState(2)
    x = jnp.asarray(rng.randn(30, 8), jnp.float32)
    w = jnp.asarray(rng.randn(8, 24), jnp.float32)
    y, s1, s2 = pf.matmul_stats(x, w, True)
    yr = np.asarray(x) @ np.asarray(w)
    np.testing.assert_allclose(np.asarray(y), yr, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(s1), yr.sum(0), rtol=1e-4)


def test_tile_m():
    assert pf._tile_m(401408) == 1024
    assert pf._tile_m(25088) == 896
    assert pf._tile_m(6272) == 896
    assert pf._tile_m(1568) == 784
    assert pf._tile_m(7) is None


def test_conv1x1_fusable_predicate():
    ok = {"kernel": "(1, 1)", "no_bias": "True", "layout": "NHWC"}
    assert pf.conv1x1_fusable(ok)
    assert not pf.conv1x1_fusable({**ok, "layout": "NCHW"})
    assert not pf.conv1x1_fusable({**ok, "kernel": "(3, 3)"})
    assert not pf.conv1x1_fusable({**ok, "stride": "(2, 2)"})
    assert not pf.conv1x1_fusable({**ok, "no_bias": "False"})
    assert not pf.conv1x1_fusable({**ok, "num_group": "2"})


@pytest.mark.parametrize("op", ["conv", "pool_max", "pool_avg", "global"])
def test_nhwc_matches_nchw(op):
    rng = np.random.RandomState(3)
    x = rng.rand(2, 8, 10, 10).astype(np.float32)   # NCHW
    xh = np.transpose(x, (0, 2, 3, 1)).copy()
    if op == "conv":
        w = rng.randn(16, 8, 3, 3).astype(np.float32)
        a = mx.nd.Convolution(mx.nd.array(x), mx.nd.array(w),
                              kernel=(3, 3), pad=(1, 1), stride=(2, 2),
                              num_filter=16, no_bias=True).asnumpy()
        b = mx.nd.Convolution(mx.nd.array(xh), mx.nd.array(w),
                              kernel=(3, 3), pad=(1, 1), stride=(2, 2),
                              num_filter=16, no_bias=True,
                              layout="NHWC").asnumpy()
        b = np.transpose(b, (0, 3, 1, 2))
    elif op.startswith("pool"):
        pt = op.split("_")[1]
        a = mx.nd.Pooling(mx.nd.array(x), kernel=(3, 3), stride=(2, 2),
                          pad=(1, 1), pool_type=pt).asnumpy()
        b = mx.nd.Pooling(mx.nd.array(xh), kernel=(3, 3), stride=(2, 2),
                          pad=(1, 1), pool_type=pt, layout="NHWC").asnumpy()
        b = np.transpose(b, (0, 3, 1, 2))
    else:
        a = mx.nd.Pooling(mx.nd.array(x), global_pool=True, kernel=(1, 1),
                          pool_type="avg").asnumpy()
        b = mx.nd.Pooling(mx.nd.array(xh), global_pool=True, kernel=(1, 1),
                          pool_type="avg", layout="NHWC").asnumpy()
        b = np.transpose(b, (0, 3, 1, 2))
    np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-5)


def test_batchnorm_axis_last():
    rng = np.random.RandomState(4)
    x = rng.rand(2, 6, 5, 5).astype(np.float32)
    xh = np.transpose(x, (0, 2, 3, 1)).copy()
    g = rng.rand(6).astype(np.float32) + 0.5
    bt = rng.rand(6).astype(np.float32)
    kw = dict(fix_gamma=False, eps=2e-5)
    a = mx.nd.BatchNorm(mx.nd.array(x), mx.nd.array(g), mx.nd.array(bt),
                        mx.nd.zeros((6,)), mx.nd.ones((6,)), **kw).asnumpy()
    b = mx.nd.BatchNorm(mx.nd.array(xh), mx.nd.array(g), mx.nd.array(bt),
                        mx.nd.zeros((6,)), mx.nd.ones((6,)), axis=3,
                        **kw).asnumpy()
    np.testing.assert_allclose(a, np.transpose(b, (0, 3, 1, 2)),
                               rtol=1e-4, atol=1e-4)


def _tiny_grads(fuse, monkeypatch):
    monkeypatch.setenv("MXTPU_FUSE_CONV_BN", fuse)
    np.random.seed(5)
    data = mx.sym.Variable("data")
    c = mx.sym.Convolution(data=data, num_filter=128, kernel=(1, 1),
                           no_bias=True, layout="NHWC", name="c")
    bn = mx.sym.BatchNorm(data=c, axis=3, fix_gamma=False, eps=2e-5,
                          name="bn")
    r = mx.sym.Activation(data=bn, act_type="relu")
    out = mx.sym.MakeLoss(mx.sym.sum(r))
    x = np.random.rand(2, 8, 8, 64).astype(np.float32)
    wv = (np.random.randn(128, 64, 1, 1) * 0.1).astype(np.float32)
    args = {"data": mx.nd.array(x), "c_weight": mx.nd.array(wv),
            "bn_gamma": mx.nd.ones((128,)), "bn_beta": mx.nd.zeros((128,))}
    grads = {k: mx.nd.zeros(v.shape) for k, v in args.items()}
    exe = out.bind(mx.cpu(), args, args_grad=grads,
                   aux_states={"bn_moving_mean": mx.nd.zeros((128,)),
                               "bn_moving_var": mx.nd.ones((128,))})
    exe.forward(is_train=True)
    exe.backward()
    return ({k: v.asnumpy() for k, v in grads.items()},
            exe.outputs[0].asnumpy())


def test_executor_fusion_end_to_end(monkeypatch):
    g0, o0 = _tiny_grads("0", monkeypatch)
    g1, o1 = _tiny_grads("interpret", monkeypatch)
    np.testing.assert_allclose(o0, o1, rtol=1e-4, atol=1e-4)
    for k in g0:
        np.testing.assert_allclose(g0[k], g1[k], rtol=1e-3, atol=1e-3)


def test_fusion_skips_eval_mode(monkeypatch):
    """In eval, BN uses moving stats; the fused path must not activate."""
    monkeypatch.setenv("MXTPU_FUSE_CONV_BN", "interpret")
    np.random.seed(6)
    data = mx.sym.Variable("data")
    c = mx.sym.Convolution(data=data, num_filter=128, kernel=(1, 1),
                           no_bias=True, layout="NHWC", name="c")
    bn = mx.sym.BatchNorm(data=c, axis=3, name="bn")
    x = np.random.rand(2, 4, 4, 64).astype(np.float32)
    wv = (np.random.randn(128, 64, 1, 1) * 0.1).astype(np.float32)
    args = {"data": mx.nd.array(x), "c_weight": mx.nd.array(wv),
            "bn_gamma": mx.nd.ones((128,)), "bn_beta": mx.nd.zeros((128,))}
    mean = np.random.rand(128).astype(np.float32)
    var = np.random.rand(128).astype(np.float32) + 0.5
    exe = bn.bind(mx.cpu(), args,
                  aux_states={"bn_moving_mean": mx.nd.array(mean),
                              "bn_moving_var": mx.nd.array(var)})
    exe.forward(is_train=False)
    got = exe.outputs[0].asnumpy()
    y = (np.transpose(x, (0, 3, 1, 2)).reshape(2, 64, -1).transpose(1, 0, 2)
         .reshape(64, -1).T @ wv.reshape(128, 64).T)
    y = y.reshape(2, 4, 4, 128)
    ref = (y - mean) / np.sqrt(var + 1e-3)
    np.testing.assert_allclose(got, ref, rtol=1e-3, atol=1e-3)


def test_resnet_nhwc_one_step_close_to_nchw():
    """Full resnet-18 train step NHWC(+fusion interpret) vs NCHW: aux stats
    must match tightly; params to loose tolerance (roundoff chaos through
    depth is expected — the f64 check in docs/perf.md shows 1e-13 algebraic
    agreement)."""
    from mxnet_tpu import models
    from mxnet_tpu.train_step import TrainStep
    os.environ["MXTPU_FUSE_CONV_BN"] = "interpret"
    try:
        np.random.seed(0)
        B, H = 2, 16
        res = {}
        for layout in ("NCHW", "NHWC"):
            sym = models.resnet(num_classes=4, num_layers=18,
                                image_shape="3,%d,%d" % (H, H),
                                layout=layout)
            shp = (B, 3, H, H) if layout == "NCHW" else (B, H, H, 3)
            step = TrainStep(sym, optimizer="sgd", learning_rate=0.01)
            st = step.init({"data": shp}, {"softmax_label": (B,)}, seed=3)
            x = np.random.RandomState(1).rand(B, 3, H, H).astype(np.float32)
            if layout == "NHWC":
                xin = np.transpose(x, (0, 2, 3, 1)).copy()
            else:
                xin = x
            yv = np.array([0, 1], np.float32)
            st2, _ = step.step(st, {"data": xin, "softmax_label": yv})
            res[layout] = st2
        a, b = res["NCHW"], res["NHWC"]
        for k in a["aux"]:
            np.testing.assert_allclose(np.asarray(a["aux"][k]),
                                       np.asarray(b["aux"][k]),
                                       rtol=1e-3, atol=1e-3)
    finally:
        os.environ.pop("MXTPU_FUSE_CONV_BN", None)
