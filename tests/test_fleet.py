"""Fleet-tier tests (docs/serving.md "Fleet tier"): model-axis-sharded
engines, the priority-aware FleetRouter, elastic drain/join, replica-death
re-queue, and the batcher race/deadline fixes that ride this PR.

The load-bearing assertions:

* a model-axis-sharded ``ServingEngine.infer`` is BITWISE identical to the
  single-chip engine on the same checkpoint, and its per-bucket programs
  pass memcheck + commscheck with zero findings;
* a dead replica's queued-but-undispatched requests are RE-QUEUED onto
  surviving replicas — no hang, no silent shed;
* priority classes keep their own deadlines under mixed load: an expired
  batch request never poisons an interactive co-rider, and the per-class
  ``ServingHealth`` counters attribute to the right class;
* ``Batcher.submit``/``close`` can no longer race a request into a
  just-shed queue, and ``wait()`` tracks the request's actual deadline
  instead of a 50 ms poll quantum.
"""
import os
import sys
import threading
import time

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import faults, serving  # noqa: E402
from mxnet_tpu.base import MXNetError, env_int  # noqa: E402


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


# ---------------------------------------------------------------------------
# fixtures
# ---------------------------------------------------------------------------

def _mlp_sym():
    net = mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=8,
                                name="fc1")
    net = mx.sym.Activation(net, act_type="relu", name="relu1")
    net = mx.sym.FullyConnected(net, num_hidden=4, name="fc2")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def _mlp_params(seed=0):
    rs = np.random.RandomState(seed)
    return {
        "arg:fc1_weight": rs.randn(8, 6).astype(np.float32) * 0.5,
        "arg:fc1_bias": rs.randn(8).astype(np.float32) * 0.1,
        "arg:fc2_weight": rs.randn(4, 8).astype(np.float32) * 0.5,
        "arg:fc2_bias": rs.randn(4).astype(np.float32) * 0.1,
    }


def _engine(buckets=(4, 8), **kw):
    return serving.ServingEngine(_mlp_sym(), _mlp_params(), {"data": (6,)},
                                 buckets=buckets, **kw)


def _batcher(**kw):
    kw.setdefault("max_latency_ms", 1.0)
    return serving.Batcher(_engine(), **kw)


def _x(n, seed=1):
    return np.random.RandomState(seed).rand(n, 6).astype(np.float32)


class _GatedEngine(object):
    """Engine proxy whose dispatches block until ``gate`` is set — lets a
    test hold a replica busy without sleeps."""

    def __init__(self, engine):
        self._engine = engine
        self.gate = threading.Event()

    def infer(self, inputs):
        self.gate.wait(10.0)
        return self._engine.infer(inputs)

    def __getattr__(self, name):
        return getattr(self._engine, name)


# ---------------------------------------------------------------------------
# satellites: env_int, close/submit race, wait() deadline fidelity
# ---------------------------------------------------------------------------

def test_env_int_rejects_non_integer_spellings(monkeypatch):
    monkeypatch.setenv("MXTPU_SERVE_QUEUE", "64")
    assert env_int("MXTPU_SERVE_QUEUE", 256) == 64
    for bad in ("256.5", "junk", "1e3"):
        monkeypatch.setenv("MXTPU_SERVE_QUEUE", bad)
        with pytest.raises(MXNetError, match="MXTPU_SERVE_QUEUE"):
            env_int("MXTPU_SERVE_QUEUE", 256)
    monkeypatch.setenv("MXTPU_SERVE_QUEUE", "")
    assert env_int("MXTPU_SERVE_QUEUE", 256) == 256


def test_batcher_rejects_non_integer_queue_env(monkeypatch):
    monkeypatch.setenv("MXTPU_SERVE_QUEUE", "12.7")
    with pytest.raises(MXNetError, match="MXTPU_SERVE_QUEUE"):
        _batcher(start=False)


def test_fleet_rejects_non_integer_queue_env(monkeypatch):
    monkeypatch.setenv("MXTPU_FLEET_QUEUE", "big")
    with pytest.raises(MXNetError, match="MXTPU_FLEET_QUEUE"):
        serving.FleetRouter([_batcher(start=False)])


def test_batcher_close_submit_race_never_orphans_a_request():
    """Regression for the close()/submit() race: a submit that passed the
    _closed check can no longer enqueue AFTER close()'s final shed — every
    accepted request must settle (shed or served), and post-close submits
    fail fast. Hammered across interleavings; with the old unlocked
    enqueue an orphaned request's event stays unset forever."""
    for _ in range(30):
        b = _batcher(start=False)
        accepted = []
        errors = []

        def submitter():
            for _ in range(4):
                try:
                    accepted.append(b.submit({"data": _x(1)}))
                except serving.ServingClosedError:
                    errors.append("closed")

        t1 = threading.Thread(target=submitter)
        t2 = threading.Thread(target=b.close)
        t1.start(); t2.start()
        t1.join(5.0); t2.join(5.0)
        deadline = time.monotonic() + 2.0
        for req in accepted:
            assert req.event.wait(max(0.0, deadline - time.monotonic())), \
                "request accepted by submit() was never settled"
        with pytest.raises(serving.ServingClosedError):
            b.submit({"data": _x(1)})


def test_batcher_wait_tracks_actual_deadline_not_poll_quantum():
    """wait() sleeps toward the request's real remaining deadline: a
    120 ms deadline resolves at ~120 ms, not rounded up to a 50 ms poll
    grid (the old loop woke 20x/s and quantized every deadline)."""
    b = _batcher(start=False)     # parked: nothing will serve it
    req = b.submit({"data": _x(1)}, deadline_ms=120.0)
    t0 = time.monotonic()
    with pytest.raises(serving.ServingDeadlineError):
        b.wait(req)
    elapsed = time.monotonic() - t0
    assert 0.10 <= elapsed < 0.17, elapsed
    b.close()


def test_batcher_on_done_fires_exactly_once():
    calls = []
    b = _batcher(start=False)
    req = b.submit({"data": _x(1)}, on_done=calls.append)
    b.close()                     # settles it (shed)
    assert calls == [req]
    assert req.error is not None
    # double-settle attempts are no-ops
    assert not req.fail(RuntimeError("late"))
    assert calls == [req]

    done = []
    b2 = _batcher()
    r2 = b2.submit({"data": _x(2)}, on_done=done.append)
    out = b2.wait(r2)
    assert out[0].shape == (2, 4)
    assert done == [r2]
    b2.close()


def test_batcher_take_queued_returns_without_failing():
    b = _batcher(start=False)
    r1 = b.submit({"data": _x(1)})
    r2 = b.submit({"data": _x(1)})
    taken = b.take_queued()
    assert taken == [r1, r2]
    assert not r1.event.is_set() and not r2.event.is_set()
    assert b.backlog() == 0
    b.close()


# ---------------------------------------------------------------------------
# model-axis-sharded engine (acceptance: bitwise + analyzer-clean)
# ---------------------------------------------------------------------------

def test_sharded_engine_bitwise_and_analyzer_clean():
    """ACCEPTANCE: a model-axis-sharded ServingEngine.infer is BITWISE
    identical to the single-chip engine on the same checkpoint, and every
    bucket program passes memcheck + commscheck with zero findings."""
    x = _x(3)
    ref = _engine().infer({"data": x})
    for nctx in (2, 4):
        eng = _engine(contexts=[mx.cpu(i) for i in range(nctx)])
        assert eng.model_devices == nctx
        out = eng.infer({"data": x})
        for o, r in zip(out, ref):
            assert np.array_equal(o, r)
        findings = [f for f in eng.check(memory=True, comms=True)
                    if not f.suppressed]
        assert findings == [], [f.format() for f in findings]


def test_sharded_engine_params_actually_sharded():
    """The capacity win is real: a sharded engine's weights live split
    over the model mesh (each device holds 1/N of the rows), and its
    compiled programs really contain collectives."""
    eng = _engine(contexts=2)
    w = eng._params["fc1_weight"]           # (8, 6), first-dim rule
    shard_shapes = {tuple(s.data.shape) for s in w.addressable_shards}
    assert shard_shapes == {(4, 6)}
    reports = eng.comms_report()
    assert reports and all(r.collective_count > 0
                           for r in reports.values())


def test_sharded_engine_int_contexts_and_batcher_compose():
    eng = _engine(contexts=2)
    b = serving.Batcher(eng, max_latency_ms=1.0)
    out = b.infer({"data": _x(2)})
    assert np.array_equal(out[0], _engine().infer({"data": _x(2)})[0])
    b.close()


def test_single_chip_engine_reports_no_collectives():
    eng = _engine()
    assert eng.model_devices == 1
    reports = eng.comms_report()
    assert reports and all(r.collective_count == 0
                           for r in reports.values())


# ---------------------------------------------------------------------------
# FleetRouter: routing, priority, drain/join, death
# ---------------------------------------------------------------------------

def test_fleet_routes_and_matches_engine_output():
    router = serving.FleetRouter([_batcher(), _batcher()])
    try:
        x = _x(2)
        out = router.infer({"data": x})
        assert np.array_equal(out[0], _engine().infer({"data": x})[0])
        rep = router.report()
        assert rep["fleet"]["requests"] == 1
        assert rep["classes"]["interactive"]["requests"] == 1
        assert rep["classes"]["batch"]["requests"] == 0
    finally:
        router.close()


def test_fleet_validates_at_submit():
    router = serving.FleetRouter([_batcher()])
    try:
        with pytest.raises(MXNetError, match="per-example shape"):
            router.submit({"data": np.zeros((1, 7), np.float32)})
        with pytest.raises(MXNetError, match="priority"):
            router.submit({"data": _x(1)}, priority="bulk")
        with pytest.raises(MXNetError, match="empty"):
            router.submit({"data": _x(0)})
    finally:
        router.close()


def test_fleet_least_loaded_dispatch_balances():
    """With both replicas parked, assignments alternate by in-flight
    depth — queue-depth-aware dispatch, not round-robin by accident."""
    b1, b2 = _batcher(start=False), _batcher(start=False)
    router = serving.FleetRouter({"a": b1, "b": b2})
    try:
        reqs = [router.submit({"data": _x(1)}, deadline_ms=5000)
                for _ in range(6)]
        t0 = time.monotonic()
        while time.monotonic() - t0 < 2.0:
            rep = router.replica_report()
            if (rep["a"]["assigned"] + rep["b"]["assigned"]) == 6:
                break
            time.sleep(0.01)
        rep = router.replica_report()
        assert rep["a"]["assigned"] == 3
        assert rep["b"]["assigned"] == 3
        b1.start(); b2.start()
        for r in reqs:
            assert len(r.result(timeout=10.0)) > 0
    finally:
        router.close()


def test_fleet_strict_priority_and_expired_batch_never_poisons():
    """Mixed-load per-class semantics (the satellite): with the single
    replica saturated, a later interactive request dispatches BEFORE an
    earlier batch request (strict priority), an expired batch request is
    failed at pop without occupying a dispatch, its expiry is attributed
    to the batch class, and the interactive co-riders all complete."""
    gated = _GatedEngine(_engine())
    b = serving.Batcher(gated, max_latency_ms=1.0, queue_size=1,
                        max_batch=4)
    router = serving.FleetRouter([b], tick_ms=5.0)
    order = []
    try:
        # A occupies the replica queue (gate closed, queue_size=1)
        ra = router.submit({"data": _x(1)}, deadline_ms=8000,
                           on_done=lambda r: order.append("A"))
        t0 = time.monotonic()
        while b.backlog() == 0 and time.monotonic() - t0 < 2.0:
            time.sleep(0.005)
        # B (batch, will expire) and C (batch) queue at the ROUTER;
        # D (interactive) arrives LAST but must dispatch before C
        rb = router.submit({"data": _x(1)}, priority="batch",
                           deadline_ms=30.0,
                           on_done=lambda r: order.append("B"))
        rc = router.submit({"data": _x(1)}, priority="batch",
                           deadline_ms=8000,
                           on_done=lambda r: order.append("C"))
        rd = router.submit({"data": _x(1)}, deadline_ms=8000,
                           on_done=lambda r: order.append("D"))
        time.sleep(0.06)          # let B's deadline lapse in the queue
        gated.gate.set()
        assert len(ra.result(timeout=10.0)) > 0
        assert len(rc.result(timeout=10.0)) > 0
        assert len(rd.result(timeout=10.0)) > 0
        with pytest.raises(serving.ServingDeadlineError):
            rb.result(timeout=10.0)
        assert order.index("D") < order.index("C")
        ch = router.class_health
        assert ch["batch"].expired == 1
        assert ch["interactive"].expired == 0
        assert ch["interactive"].errors == 0
    finally:
        gated.gate.set()
        router.close()


def test_fleet_class_default_deadlines(monkeypatch):
    monkeypatch.setenv("MXTPU_FLEET_INTERACTIVE_DEADLINE_MS", "750")
    monkeypatch.setenv("MXTPU_FLEET_BATCH_DEADLINE_MS", "9000")
    router = serving.FleetRouter([_batcher()])
    try:
        now = time.monotonic()
        ri = router.submit({"data": _x(1)})
        rb = router.submit({"data": _x(1)}, priority="batch")
        assert 0.4 < ri.deadline - now < 0.80
        assert 8.0 < rb.deadline - now < 9.05
        ri.result(timeout=10.0)
        rb.result(timeout=10.0)
    finally:
        router.close()


def test_fleet_backpressure_bounded_per_class(monkeypatch):
    gated = _GatedEngine(_engine())
    b = serving.Batcher(gated, queue_size=1, max_latency_ms=1.0)
    router = serving.FleetRouter([b], queue_size=2)
    try:
        for _ in range(4):   # 1 in replica queue + 2 router + in-flight
            try:
                router.submit({"data": _x(1)}, priority="batch",
                              deadline_ms=5000)
            except serving.ServingOverloadedError:
                break
        with pytest.raises(serving.ServingOverloadedError):
            for _ in range(4):
                router.submit({"data": _x(1)}, priority="batch",
                              deadline_ms=5000)
        assert router.class_health["batch"].dropped >= 1
        assert router.class_health["interactive"].dropped == 0
    finally:
        gated.gate.set()
        router.close()


def test_fleet_drain_flushes_then_retires():
    """Drain under load: stop assigning, flush what the replica owns,
    retire — zero requests shed."""
    gated = _GatedEngine(_engine())
    b0 = serving.Batcher(gated, max_latency_ms=1.0)
    router = serving.FleetRouter({"r0": b0, "r1": _batcher()})
    try:
        reqs = [router.submit({"data": _x(1)}, deadline_ms=10000)
                for _ in range(8)]
        res = {}

        def do_drain():
            res["report"] = router.drain("r0", timeout=15.0)

        t = threading.Thread(target=do_drain)
        t.start()
        time.sleep(0.03)
        gated.gate.set()
        t.join(20.0)
        assert res["report"]["state"] == serving.fleet.RETIRED
        for r in reqs:
            assert len(r.result(timeout=10.0)) > 0
        assert router.health.shed == 0
        assert "r0" not in router.replica_names()
        # a retired replica takes no further work but the fleet serves on
        out = router.infer({"data": _x(1)}, deadline_ms=5000)
        assert out[0].shape == (1, 4)
    finally:
        gated.gate.set()
        router.close()


def test_fleet_join_warms_and_enters_rotation():
    router = serving.FleetRouter([_batcher()])
    try:
        router.join("fresh", _batcher)
        assert "fresh" in router.replica_names()
        # warm-up ran one request per bucket through the new engine
        rep = router.replica_report()["fresh"]
        assert rep["engine_health"]["batches"] >= 2
        out = router.infer({"data": _x(2)})
        assert out[0].shape == (2, 4)
    finally:
        router.close()


def test_fleet_join_rejects_mismatched_signature():
    router = serving.FleetRouter([_batcher()])
    try:
        def bad():
            rs = np.random.RandomState(0)
            params = {
                "arg:fc1_weight": rs.randn(8, 7).astype(np.float32),
                "arg:fc1_bias": rs.randn(8).astype(np.float32),
                "arg:fc2_weight": rs.randn(4, 8).astype(np.float32),
                "arg:fc2_bias": rs.randn(4).astype(np.float32),
            }
            return serving.ServingEngine(_mlp_sym(), params,
                                         {"data": (7,)}, buckets=(4,))
        with pytest.raises(MXNetError, match="signature"):
            router.join("bad", bad)
        assert "bad" not in router.replica_names()
    finally:
        router.close()


@pytest.mark.faults
def test_fleet_replica_die_requeues_undispatched_onto_survivors():
    """ACCEPTANCE: a dead replica's queued-but-undispatched requests are
    re-queued onto survivors — every request completes, nothing hangs,
    nothing is silently shed."""
    router = serving.FleetRouter([_batcher(), _batcher()], tick_ms=5.0)
    try:
        faults.inject("fleet.replica_die", nth=1, kind="die")
        x = _x(1)
        ref = _engine().infer({"data": x})[0]
        reqs = [router.submit({"data": x}, deadline_ms=15000)
                for _ in range(16)]
        for r in reqs:
            out = r.result(timeout=20.0)
            assert np.array_equal(out[0], ref)
        rep = router.report()
        assert rep["fleet"]["requeued"] >= 1
        assert rep["fleet"]["shed"] == 0
        states = sorted(r["state"] for r in rep["replicas"].values())
        assert states == [serving.fleet.ACTIVE, serving.fleet.DEAD]
        dead = [r for r in rep["replicas"].values()
                if r["state"] == serving.fleet.DEAD][0]
        assert "replica death" in dead["died"]
    finally:
        router.close()


@pytest.mark.faults
def test_fleet_single_replica_death_requeues_then_join_recovers():
    """With NO survivor, re-queued requests wait in the router (deadline-
    aware, not shed); a joining replica then serves them."""
    router = serving.FleetRouter([_batcher()], tick_ms=5.0)
    try:
        faults.inject("fleet.replica_die", nth=1, kind="die")
        reqs = [router.submit({"data": _x(1)}, deadline_ms=15000)
                for _ in range(6)]
        t0 = time.monotonic()
        while not router.replica_names(states=(serving.fleet.DEAD,)) \
                and time.monotonic() - t0 < 5.0:
            time.sleep(0.01)
        router.join("rescue", _batcher)
        for r in reqs:
            assert len(r.result(timeout=20.0)) > 0
        assert router.health.shed == 0
    finally:
        router.close()


def test_fleet_close_sheds_queued_with_clear_error():
    gated = _GatedEngine(_engine())
    b = serving.Batcher(gated, queue_size=1, max_latency_ms=1.0)
    router = serving.FleetRouter([b], queue_size=8)
    reqs = [router.submit({"data": _x(1)}, priority="batch",
                          deadline_ms=30000) for _ in range(5)]
    router.close()
    gated.gate.set()
    failed = 0
    for r in reqs:
        try:
            r.result(timeout=10.0)
        except serving.ServingClosedError:
            failed += 1
        except serving.ServingDeadlineError:
            pytest.fail("close must shed promptly, not leak to deadline")
    assert failed >= 1            # everything unserved failed with Closed
    with pytest.raises(serving.ServingClosedError):
        router.submit({"data": _x(1)})


def test_fleet_health_rollup_mirrors_to_process_global():
    base = serving.SERVING_HEALTH.report()["requests"]
    router = serving.FleetRouter([_batcher()])
    try:
        router.infer({"data": _x(1)})
        router.infer({"data": _x(1)}, priority="batch")
        assert serving.SERVING_HEALTH.report()["requests"] >= base + 2
        assert router.health.requests == 2
        assert router.class_health["interactive"].requests == 1
        assert router.class_health["batch"].requests == 1
    finally:
        router.close()


# ---------------------------------------------------------------------------
# model-axis-sharded decode loop
# ---------------------------------------------------------------------------

def _lm_params(num_layers=2, num_heads=4, embed=16, vocab=32, max_len=24,
               seed=3):
    rs = np.random.RandomState(seed)
    p = {"tok_embed_weight": rs.randn(vocab, embed) * 0.3,
         "pos_embed_weight": rs.randn(max_len, embed) * 0.1,
         "final_ln_gamma": np.ones(embed), "final_ln_beta": np.zeros(embed),
         "lm_head_weight": rs.randn(vocab, embed) * 0.3,
         "lm_head_bias": np.zeros(vocab)}
    for i in range(num_layers):
        pre = "layer%d" % i
        p[pre + "_ln1_gamma"] = np.ones(embed)
        p[pre + "_ln1_beta"] = np.zeros(embed)
        p[pre + "_ln2_gamma"] = np.ones(embed)
        p[pre + "_ln2_beta"] = np.zeros(embed)
        p[pre + "_attn_qkv_weight"] = rs.randn(3 * embed, embed) * 0.2
        p[pre + "_attn_qkv_bias"] = np.zeros(3 * embed)
        p[pre + "_attn_out_weight"] = rs.randn(embed, embed) * 0.2
        p[pre + "_attn_out_bias"] = np.zeros(embed)
        p[pre + "_ffn_fc1_weight"] = rs.randn(4 * embed, embed) * 0.2
        p[pre + "_ffn_fc1_bias"] = np.zeros(4 * embed)
        p[pre + "_ffn_fc2_weight"] = rs.randn(embed, 4 * embed) * 0.2
        p[pre + "_ffn_fc2_bias"] = np.zeros(embed)
    return {k: np.asarray(v, np.float32) for k, v in p.items()}


def test_sharded_decode_greedy_token_parity():
    """Sharded decode (KV cache over heads) emits the same greedy tokens
    as the single-chip loop, with the cache genuinely distributed and the
    program set analyzer-clean (donation of the sharded cache included)."""
    params = _lm_params()
    l1 = serving.DecodeLoop(params, 2, 4, 24, slots=2)
    t1 = l1.generate([3, 5, 7], 8).result(timeout=30.0)
    l1.close()
    l2 = serving.DecodeLoop(params, 2, 4, 24, slots=2, contexts=2)
    try:
        t2 = l2.generate([3, 5, 7], 8).result(timeout=30.0)
        assert t1 == t2
        shard_shapes = {tuple(s.data.shape)
                        for s in l2._state["k"].addressable_shards}
        assert shard_shapes == {(2, 2, 2, 24, 4)}   # heads 4 -> 2 per dev
        bad = [f for f in l2.check(memory=True, comms=True)
               if not f.suppressed]
        assert bad == [], [f.format() for f in bad]
    finally:
        l2.close()


def test_sharded_decode_rejects_indivisible_heads():
    with pytest.raises(MXNetError, match="heads"):
        serving.DecodeLoop(_lm_params(num_heads=4), 2, 3, 24, contexts=2)
