"""memcheck tests (docs/static_analysis.md "Memory lints"): the static
HBM analyzer over compiled step programs.

The load-bearing assertions:

* a TrainStep's full program set reports peak/argument/temp/alias bytes
  with the donated state's alias savings realized (alias > 0, no waste);
* one SEEDED violation per memory lint class — ``hbm-budget``,
  ``donation-waste``, ``temp-blowup``, ``resident-set`` — is caught with
  the op path (and source provenance where the HLO carries it) asserted;
* the baseline regression gate fails on an injected temp-bytes
  regression and passes on the honest baseline (the ci/memcheck.sh
  contract);
* the CLI smoke (mlp + lenet, json mode) exits 0 with zero findings —
  the tier-1 mirror of the full-zoo CI gate.
"""
import json
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from mxnet_tpu import memcheck as mc  # noqa: E402
from mxnet_tpu import tracecheck as tc  # noqa: E402
from mxnet_tpu.base import MXNetError  # noqa: E402


def _sds(shape, dtype=np.float32):
    return jax.ShapeDtypeStruct(tuple(shape), dtype)


@pytest.fixture(scope="module")
def mlp_audit():
    """One compile of the mlp program set shared by the report/baseline
    tests (4 programs — the expensive part of this suite)."""
    from mxnet_tpu import models
    from mxnet_tpu.train_step import TrainStep
    cfg = tc.ZOO["mlp"]
    sym = models.get_symbol("mlp", **cfg["kwargs"])
    ts = TrainStep(sym, optimizer="sgd", learning_rate=0.1)
    return mc.check_train_step(ts, {"data": cfg["data"]},
                               {"softmax_label": cfg["label"]}, k=2,
                               name="mlp")


# ---------------------------------------------------------------------------
# reports
# ---------------------------------------------------------------------------

def test_train_step_reports_all_programs(mlp_audit):
    findings, reports = mlp_audit
    assert sorted(reports) == ["mlp/guarded-scan[k=2]", "mlp/guarded-step",
                               "mlp/scan[k=2]", "mlp/step"]
    for rep in reports.values():
        assert rep.peak_bytes > 0
        assert rep.argument_bytes > 0
        assert rep.output_bytes > 0
        assert rep.temp_bytes > 0
        # the donated state aliased: donation is realized as savings
        assert rep.alias_bytes > 0
        assert rep.donated_bytes >= rep.alias_bytes // 2
        assert rep.top_buffers and rep.top_buffers[0]["bytes"] > 0
    # the default budget audits the zoo clean (the acceptance bar)
    assert [f.format() for f in findings] == []


def test_report_peak_formula_and_dict(mlp_audit):
    _, reports = mlp_audit
    rep = reports["mlp/step"]
    assert rep.peak_bytes == (rep.argument_bytes + rep.output_bytes
                              + rep.temp_bytes - rep.alias_bytes)
    d = rep.as_dict()
    assert d["peak_bytes"] == rep.peak_bytes
    assert d["program"] == "mlp/step"
    assert isinstance(d["top_buffers"], list)
    assert "MemoryReport" in repr(rep)


def test_hlo_buffer_parse_shapes():
    """The HLO shape parser handles every dtype width the step programs
    use (and sub-byte types), and skips view ops."""
    txt = """HloModule t, is_scheduled=true, input_output_alias={ {0}: (1, {}, may-alias) }, entry_computation_layout={(f32[4]{0})->f32[4]{0}}

%fused_computation (p: f32[8,8]) -> f32[8,8] {
  %inner.1 = f32[8,8]{1,0} multiply(f32[8,8]{1,0} %p, f32[8,8]{1,0} %p)
}

ENTRY %main.1 (Arg_0.1: f32[4]) -> f32[4] {
  %Arg_0.1 = f32[4]{0} parameter(0), metadata={op_name="state[\\'p\\']"}
  %Arg_1.2 = bf16[2,3]{1,0} parameter(1), metadata={op_name="batch"}
  %big.1 = f32[128,2]{1,0} broadcast(f32[4]{0} %Arg_0.1), metadata={op_name="jit(f)/bcast" source_file="x.py" source_line=7}
  %gte.1 = f32[4]{0} get-tuple-element(%big.1), index=0
  %pred.1 = pred[16]{0} compare(f32[4]{0} %Arg_0.1, f32[4]{0} %Arg_0.1)
}
"""
    buffers, params, aliased = mc.parse_hlo_buffers(txt)
    assert aliased == {1}
    assert params[0] == ("state['p']", 16)
    assert params[1] == ("batch", 12)  # bf16 2x3 = 12 bytes
    by_instr = {b["instruction"]: b for b in buffers}
    assert "inner.1" not in by_instr        # fusion internals skipped
    assert "gte.1" not in by_instr          # views skipped
    assert by_instr["big.1"]["bytes"] == 128 * 2 * 4
    assert by_instr["big.1"]["op_path"] == "jit(f)/bcast"
    assert by_instr["big.1"]["provenance"] == "x.py:7"
    assert by_instr["pred.1"]["bytes"] == 16
    assert buffers[0]["instruction"] == "big.1"  # sorted largest first


# ---------------------------------------------------------------------------
# seeded violations — one per lint class, op path + provenance asserted
# ---------------------------------------------------------------------------

def _hog(x):
    # dot operands must materialize: outer(x, x) lands a 4 MiB temp (and
    # the dot result another) against 4 KiB of arguments — the blowup
    # shape of a rematerialization/fusion regression
    big = jnp.outer(x, x)
    return jnp.sum(big @ big)


def test_hbm_budget_finding_seeded():
    findings, rep = mc.check_program(_hog, (_sds((1024,)),), name="seeded-hog",
                                     budget=64 << 10)
    hits = [f for f in findings if f.lint == "hbm-budget"]
    assert len(hits) == 1
    assert "peak HBM" in hits[0].message
    assert "Largest buffers" in hits[0].message
    # attributed to the blowup op with source provenance
    assert hits[0].op_path and "jit(_hog)" in hits[0].op_path
    assert hits[0].provenance and "test_memcheck" in hits[0].provenance


def test_temp_blowup_finding_seeded():
    findings, rep = mc.check_program(_hog, (_sds((1024,)),), name="seeded-hog",
                                     temp_mult=2.0)
    hits = [f for f in findings if f.lint == "temp-blowup"]
    assert len(hits) == 1
    assert "MXTPU_MEMCHECK_TEMP_MULT" in hits[0].message
    assert hits[0].op_path and "jit(_hog)" in hits[0].op_path
    assert hits[0].provenance and "test_memcheck" in hits[0].provenance
    assert rep.temp_bytes > 2 * (rep.argument_bytes + rep.output_bytes)


def test_donation_waste_finding_seeded():
    """A donated buffer whose bytes cannot alias any output (shape
    changes) is pure waste: the finding names the argument by path and
    accounts the unrealized bytes."""
    def f(x):
        return x[::2] * jnp.float32(2.0)

    findings, rep = mc.check_program(f, (_sds((1024,)),),
                                     donate_argnums=(0,),
                                     name="seeded-waste")
    hits = [f_ for f_ in findings if f_.lint == "donation-waste"]
    assert len(hits) == 1
    assert hits[0].op_path == "x"       # HLO labels the entry param
    assert "4.00 KiB" in hits[0].message
    assert rep.wasted_donation_bytes == 4096
    assert rep.unaliased_donated == [("x", 4096)]


def test_donation_waste_quiet_when_alias_realized():
    def f(x):
        return x * jnp.float32(2.0)

    findings, rep = mc.check_program(f, (_sds((1024,)),),
                                     donate_argnums=(0,),
                                     name="clean-donation")
    assert [f_ for f_ in findings if f_.lint == "donation-waste"] == []
    assert rep.alias_bytes == 4096
    assert rep.unaliased_donated == []


def test_resident_set_finding_seeded(mlp_audit):
    _, reports = mlp_audit
    findings = mc.lint_resident_set(reports.values(), "mlp/resident-set",
                                    budget=1024)
    assert len(findings) == 1
    f = findings[0]
    assert f.lint == "resident-set"
    assert f.program == "mlp/resident-set"
    # every co-resident member is accounted in the message, and the op
    # path points at the largest temp holder
    for name in reports:
        assert name in f.message
    assert f.op_path in reports
    assert "jit caches keep every executable" in f.message
    # the footprint model: shared args/out once, every temp retained
    total = mc.resident_bytes(reports.values())
    assert total > max(r.peak_bytes for r in reports.values())
    assert total < sum(r.peak_bytes for r in reports.values()) + 1


def test_memory_lints_suppressible():
    tok = tc.add_suppression("temp-blowup", program="seeded-hog")
    try:
        findings, _ = mc.check_program(_hog, (_sds((1024,)),),
                                       name="seeded-hog", temp_mult=2.0)
        hits = [f for f in findings if f.lint == "temp-blowup"]
        assert hits and all(f.suppressed for f in hits)
        assert mc.unsuppressed(hits) == []
    finally:
        tc.remove_suppression(tok)


def test_unknown_mem_lint_rejected():
    with pytest.raises(MXNetError, match="unknown lint"):
        tc.add_suppression("hbm-banana")


# ---------------------------------------------------------------------------
# knobs
# ---------------------------------------------------------------------------

def test_donation_waste_needs_aliasing_evidence():
    """If the executable's HLO text is unavailable (or a future XLA's text
    no longer matches the parser) while the compiler DOES report alias
    savings, analyze_compiled must claim nothing about donation waste — a
    false claim would fail healthy deploys under MXTPU_MEMCHECK=error."""
    class FakeStats:
        argument_size_in_bytes = 4096
        output_size_in_bytes = 4096
        temp_size_in_bytes = 128
        alias_size_in_bytes = 4096     # the donation DID succeed
        generated_code_size_in_bytes = 0

    class FakeCompiled:
        def memory_analysis(self):
            return FakeStats()

        def as_text(self):
            raise RuntimeError("text unavailable on this backend")

    rep = mc.analyze_compiled(FakeCompiled(), "fake",
                              args=(_sds((1024,)),), donate_argnums=(0,))
    assert rep.alias_bytes == 4096
    assert rep.unaliased_donated == []       # no evidence -> no claim
    assert [f for f in mc.lint_report(rep, budget=1 << 30)
            if f.lint == "donation-waste"] == []


def test_baseline_tol_env_overrides_stored_band(mlp_audit, tmp_path,
                                                monkeypatch):
    """MXTPU_MEMCHECK_TOL (the operator loosening a gate run) must beat
    the tolerance stored inside the baseline file."""
    _, reports = mlp_audit
    path = str(tmp_path / "baseline.json")
    mc.write_baseline(reports, path, tol=0.1)
    name = "mlp/scan[k=2]"
    bad = dict(reports)
    bad[name] = _clone_with(bad[name],
                            temp_bytes=bad[name].temp_bytes + (1 << 20))
    monkeypatch.delenv("MXTPU_MEMCHECK_TOL", raising=False)
    failures, _ = mc.compare_baseline(bad, path)
    assert failures  # the stored 10% band catches the +1 MiB growth
    monkeypatch.setenv("MXTPU_MEMCHECK_TOL", "100.0")
    failures, _ = mc.compare_baseline(bad, path)
    assert failures == []  # env-widened band wins over the stored one


def test_budget_env_parsing(monkeypatch):
    monkeypatch.setenv("MXTPU_MEMCHECK_BUDGET", "12G")
    assert mc.budget_bytes() == 12 << 30
    monkeypatch.setenv("MXTPU_MEMCHECK_BUDGET", "1.5M")
    assert mc.budget_bytes() == int(1.5 * (1 << 20))
    monkeypatch.setenv("MXTPU_MEMCHECK_BUDGET", "2048")
    assert mc.budget_bytes() == 2048
    for bad in ("lots", "e", ".", "+", "E3", "-1G"):
        monkeypatch.setenv("MXTPU_MEMCHECK_BUDGET", bad)
        with pytest.raises(MXNetError, match="MXTPU_MEMCHECK_BUDGET"):
            mc.budget_bytes()


def test_budget_default_derives_from_device(monkeypatch):
    monkeypatch.delenv("MXTPU_MEMCHECK_BUDGET", raising=False)
    # CPU reports no bytes_limit -> the documented 16 GiB fallback
    assert mc.budget_bytes() == mc.device_budget()
    class FakeDev:
        def memory_stats(self):
            return {"bytes_limit": 123456789}
    assert mc.device_budget(FakeDev()) == 123456789


def test_memcheck_mode_knob(monkeypatch):
    from mxnet_tpu import engine
    # clear any override a prior test restored by effective value
    # (set_memcheck(prev) pins prev as an override, like set_tracecheck)
    engine.set_memcheck(None)
    monkeypatch.delenv("MXTPU_MEMCHECK", raising=False)
    assert engine.memcheck_mode() == "off"
    monkeypatch.setenv("MXTPU_MEMCHECK", "warn")
    assert engine.memcheck_mode() == "warn"
    monkeypatch.setenv("MXTPU_MEMCHECK", "error")
    assert engine.memcheck_mode() == "error"
    monkeypatch.setenv("MXTPU_MEMCHECK", "banana")
    with pytest.raises(MXNetError, match="MXTPU_MEMCHECK"):
        engine.memcheck_mode()
    monkeypatch.delenv("MXTPU_MEMCHECK", raising=False)
    prev = engine.set_memcheck("error")
    try:
        assert engine.memcheck_mode() == "error"
    finally:
        engine.set_memcheck(prev if prev != "off" else None)


# ---------------------------------------------------------------------------
# the baseline regression gate (ci/memcheck.sh contract)
# ---------------------------------------------------------------------------

def _clone_with(rep, **over):
    kw = dict(program=rep.program, platform=rep.platform,
              argument_bytes=rep.argument_bytes,
              output_bytes=rep.output_bytes, temp_bytes=rep.temp_bytes,
              alias_bytes=rep.alias_bytes,
              generated_code_bytes=rep.generated_code_bytes,
              top_buffers=rep.top_buffers, donated=rep.donated,
              unaliased_donated=rep.unaliased_donated)
    kw.update(over)
    return mc.MemoryReport(**kw)


def test_baseline_roundtrip_passes(mlp_audit, tmp_path):
    _, reports = mlp_audit
    path = str(tmp_path / "baseline.json")
    mc.write_baseline(reports, path)
    failures, notes = mc.compare_baseline(reports, path)
    assert failures == []
    assert notes == []


def test_baseline_catches_injected_temp_regression(mlp_audit, tmp_path):
    """The CI contract: a program whose temp bytes grew past the
    tolerance band fails the gate WITH the buffer breakdown in the
    message."""
    _, reports = mlp_audit
    path = str(tmp_path / "baseline.json")
    mc.write_baseline(reports, path)
    bad = dict(reports)
    name = "mlp/scan[k=2]"
    grown = bad[name].temp_bytes + (1 << 20)  # +1 MiB: over 10% + slack
    bad[name] = _clone_with(bad[name], temp_bytes=grown)
    failures, _notes = mc.compare_baseline(bad, path)
    assert len(failures) == 2  # temp grew, and peak (derived) grew with it
    joined = "\n".join(failures)
    assert name in joined
    assert "temp_bytes grew" in joined
    assert "Largest buffers" in joined
    assert "MXTPU_MEMCHECK_TOL" in joined


def test_baseline_missing_program_fails(mlp_audit, tmp_path):
    _, reports = mlp_audit
    path = str(tmp_path / "baseline.json")
    partial = {n: r for n, r in reports.items() if n != "mlp/step"}
    mc.write_baseline(partial, path)
    failures, notes = mc.compare_baseline(reports, path)
    assert len(failures) == 1
    assert "mlp/step" in failures[0]
    assert "--write-baseline" in failures[0]
    # and the reverse direction is a NOTE (stale entry), not a failure
    failures2, notes2 = mc.compare_baseline(partial, {
        "platform": jax.devices()[0].platform, "tolerance": 0.1,
        "programs": {n: {"peak_bytes": r.peak_bytes,
                         "temp_bytes": r.temp_bytes}
                     for n, r in reports.items()}})
    assert failures2 == []
    assert any("stale" in n for n in notes2)


def test_baseline_platform_mismatch_skips_gate(mlp_audit):
    _, reports = mlp_audit
    failures, notes = mc.compare_baseline(reports, {
        "platform": "tpu", "tolerance": 0.1,
        "programs": {"mlp/step": {"peak_bytes": 1, "temp_bytes": 1}}})
    assert failures == []
    assert len(notes) == 1 and "platform" in notes[0]


def test_baseline_shrink_is_a_note_not_a_failure(mlp_audit, tmp_path):
    _, reports = mlp_audit
    path = str(tmp_path / "baseline.json")
    # baseline claims the program used to be much bigger
    inflated = {n: _clone_with(r, temp_bytes=r.temp_bytes + (4 << 20),
                               argument_bytes=r.argument_bytes + (4 << 20))
                for n, r in reports.items()}
    mc.write_baseline(inflated, path)
    failures, notes = mc.compare_baseline(reports, path)
    assert failures == []
    assert any("shrank" in n for n in notes)


# ---------------------------------------------------------------------------
# CLI (tier-1 smoke of the ci/memcheck.sh gate)
# ---------------------------------------------------------------------------

def test_cli_smoke_json_mlp_lenet(capsys):
    """The tier-1 mirror of the full-zoo CI gate: mlp + lenet in json
    mode exit 0 with zero findings and a full per-program report."""
    rc = mc.main(["--models", "mlp,lenet", "--json"])
    data = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert data["findings"] == []
    assert data["suppressed"] == 0
    assert len(data["programs"]) == 8
    for rep in data["programs"].values():
        assert rep["peak_bytes"] > 0
        assert rep["temp_bytes"] > 0
    assert data["budget_bytes"] > 0
    assert data["platform"] == jax.devices()[0].platform


def test_cli_list_and_bad_model(capsys):
    assert mc.main(["--list"]) == 0
    assert "mlp" in capsys.readouterr().out
    with pytest.raises(MXNetError, match="unknown zoo model"):
        mc.main(["--models", "nope"])


def test_cli_write_and_gate_baseline(tmp_path, capsys):
    """CLI end-to-end: --write-baseline then --baseline passes; a doctored
    baseline (simulating a regression against it) fails with the
    breakdown on stdout."""
    path = str(tmp_path / "b.json")
    rc = mc.main(["--models", "mlp", "--quiet", "--write-baseline", path])
    capsys.readouterr()
    assert rc == 0
    rc = mc.main(["--models", "mlp", "--quiet", "--baseline", path])
    out = capsys.readouterr().out
    assert rc == 0
    assert "0 baseline regression(s)" in out
    # doctor the baseline: pretend the committed numbers were tiny
    with open(path) as f:
        base = json.load(f)
    for entry in base["programs"].values():
        entry["temp_bytes"] = 1
        entry["peak_bytes"] = 1
    # shrink the slack-dominated band by dropping the program size gap:
    # mlp programs are tiny, so gate a synthetic compare directly too
    with open(path, "w") as f:
        json.dump(base, f)
    rc = mc.main(["--models", "mlp", "--quiet", "--baseline", path])
    out = capsys.readouterr().out
    # mlp programs are under the 64 KiB absolute slack — the CLI must
    # still PASS (tiny programs can't regress meaningfully)...
    assert rc == 0
    # ...while a lenet-sized program (MiB temps) trips the gate
    rc = mc.main(["--models", "lenet", "--quiet", "--baseline", path])
    out = capsys.readouterr().out
    assert rc == 1
    assert "BASELINE REGRESSION" in out
    assert "not in the baseline" in out
