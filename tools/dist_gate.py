#!/usr/bin/env python
"""CI gate: elastic multi-process distributed training
(docs/robustness.md "Elastic distributed training").

What it proves, end to end, on REAL worker processes:

1. a 3-worker ``dist_sync`` run loses its highest rank to SIGKILL
   mid-epoch (the ``kv.worker_die`` fault site) and the survivors take
   an emergency checkpoint, re-form the control-plane ring at N-1,
   re-shard the data, and finish training to the accuracy floor — the
   per-rank asserts live in tests/dist_worker.py's ``elastic`` mode and
   a rank only prints its PASS line after every one of them held;
2. a FRESH module resuming from the surviving checkpoint prefix is
   bitwise-identical to the live post-reform parameters (same worker
   asserts);
3. the collective throughput of the run that lost a worker holds a
   scaling floor against a single-worker run of the same model and
   data: ``dist_sps / single_sps >= MXTPU_DIST_MIN_SCALE`` (default
   0.10 — deliberately conservative: CI hosts timeshare every worker
   process on the same small core budget, so the dist run pays 3x
   oversubscription, the ring's control-plane traffic, and a second
   fused-step compile after the re-form reshards the data; the floor
   catches collapse, not ideal-scaling misses). The dead-worker
   DETECTION stall is excluded first: it is a configured latency
   (``MXTPU_DIST_DEAD_FOR``, spent waiting for the victim's heartbeat
   to age out), not throughput, so it is subtracted from the dist
   wall clock before the ratio.

Emits DIST_r17.json (committed, like the MULTICHIP_r*.json series).

Run via ci/dist.sh. Self-contained: the single-worker baseline is this
same file re-invoked with --baseline in a clean subprocess (no forced
multi-device XLA_FLAGS), so both sides measure the same fit loop.
"""
import json
import os
import re
import socket
import subprocess
import sys
import tempfile
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
NPROC = 3
EPOCHS = 8          # must match tests/dist_worker.py run_elastic
FLOOR_ENV = "MXTPU_DIST_MIN_SCALE"


def _free_port():
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _baseline():
    """Single-worker fit of the same model/data as run_elastic; prints a
    machine-readable throughput line."""
    import numpy as np

    sys.path.insert(0, ROOT)
    import mxnet_tpu as mx
    from mxnet_tpu.io import NDArrayIter

    n_class, dim, n_per, batch_size = 8, 32, 192, 64
    rng = np.random.RandomState(7)
    templates = rng.randn(n_class, dim).astype(np.float32) * 3
    labels = np.arange(n_class * n_per) % n_class
    x = (templates[labels]
         + rng.randn(len(labels), dim).astype(np.float32) * 0.5)

    data = mx.sym.Variable("data")
    h = mx.sym.FullyConnected(data, name="fc1", num_hidden=64)
    h = mx.sym.Activation(h, name="relu1", act_type="relu")
    h = mx.sym.FullyConnected(h, name="fc2", num_hidden=n_class)
    net = mx.sym.SoftmaxOutput(h, name="softmax")

    mod = mx.mod.Module(net)
    train = NDArrayIter(x, labels.astype(np.float32),
                        batch_size=batch_size, shuffle=False)
    t0 = time.time()
    mod.fit(train, num_epoch=EPOCHS, optimizer="sgd",
            optimizer_params={"learning_rate": 0.1, "momentum": 0.9},
            initializer=mx.initializer.Xavier())
    fit_s = time.time() - t0
    print("BASELINE-STATS fit_s=%.3f epochs=%d samples=%d"
          % (fit_s, EPOCHS, len(x)), flush=True)


def main():
    if "--baseline" in sys.argv[1:]:
        _baseline()
        return

    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)   # workers are single-device processes
    env["JAX_PLATFORMS"] = "cpu"
    tmpdir = tempfile.mkdtemp(prefix="mxtpu_dist_gate_")
    env["MXTPU_TEST_TMPDIR"] = tmpdir

    # 1. single-worker baseline (clean subprocess: same env rules)
    r = subprocess.run([sys.executable, os.path.abspath(__file__),
                        "--baseline"],
                       env=env, capture_output=True, text=True,
                       timeout=600)
    base = re.search(r"BASELINE-STATS fit_s=([\d.]+) epochs=(\d+) "
                     r"samples=(\d+)", r.stdout + r.stderr)
    if r.returncode != 0 or not base:
        sys.exit("dist_gate FAIL: baseline fit died:\n%s"
                 % (r.stdout + r.stderr))
    base_s = float(base.group(1))
    n_samples = int(base.group(3))
    single_sps = EPOCHS * n_samples / base_s

    # 2. the elastic 3-worker run (mid-epoch SIGKILL baked into the
    # worker's elastic mode); nonzero launcher rc is by design — the
    # victim dies — so the verdict is the survivors' PASS lines
    worker = os.path.join(ROOT, "tests", "dist_worker.py")
    cmd = [sys.executable, os.path.join(ROOT, "tools", "launch.py"),
           "-n", str(NPROC), "--coord-port", str(_free_port()),
           "%s %s elastic" % (sys.executable, worker)]
    r = subprocess.run(cmd, env=env, capture_output=True, text=True,
                       timeout=900)
    out = r.stdout + r.stderr
    for rank in range(NPROC - 1):
        if "RANK-%d-PASS" % rank not in out:
            sys.exit("dist_gate FAIL: survivor rank %d never passed "
                     "(re-form / bitwise-resume asserts live in the "
                     "worker):\n%s" % (rank, out))
    if "RANK-%d-PASS" % (NPROC - 1) in out:
        sys.exit("dist_gate FAIL: the victim rank survived its SIGKILL")

    stats = {int(m.group(1)): m for m in re.finditer(
        r"RANK-(\d+)-ELASTIC-STATS fit_s=([\d.]+) epochs=(\d+) "
        r"samples=(\d+) reforms=(\d+) workers=(\d+)", out)}
    if not stats:
        sys.exit("dist_gate FAIL: no survivor stats line:\n%s" % out)
    reforms = {int(m.group(5)) for m in stats.values()}
    workers = {int(m.group(6)) for m in stats.values()}
    if reforms != {1} or workers != {NPROC - 1}:
        sys.exit("dist_gate FAIL: expected exactly 1 re-form to %d "
                 "workers on every survivor, saw reforms=%s workers=%s"
                 % (NPROC - 1, sorted(reforms), sorted(workers)))

    # the shards partition the dataset: collective rate = full passes
    # over the whole dataset / the slowest survivor's wall clock, minus
    # the configured dead-worker detection stall (a latency knob, not
    # throughput — the survivors sit out MXTPU_DIST_DEAD_FOR waiting
    # for the victim's heartbeat to age out before re-forming)
    dead_for = float(os.environ.get("MXTPU_DIST_DEAD_FOR", "") or 6.0)
    dist_wall = max(float(m.group(2)) for m in stats.values())
    dist_s = max(dist_wall - dead_for, 1e-3)
    dist_sps = EPOCHS * n_samples / dist_s
    scale = dist_sps / single_sps
    floor = float(os.environ.get(FLOOR_ENV, "") or 0.10)
    if scale < floor:
        sys.exit("dist_gate FAIL: dist throughput %.1f samples/s is "
                 "%.2fx the single-worker %.1f — under the %s=%.2f "
                 "floor" % (dist_sps, scale, single_sps, FLOOR_ENV,
                            floor))

    report = {
        "gate": "dist",
        "workers_start": NPROC,
        "workers_end": NPROC - 1,
        "reforms": 1,
        "epochs": EPOCHS,
        "samples": n_samples,
        "single_fit_s": round(base_s, 3),
        "dist_fit_wall_s": round(dist_wall, 3),
        "detect_stall_s": dead_for,
        "dist_fit_s": round(dist_s, 3),
        "single_sps": round(single_sps, 1),
        "dist_sps": round(dist_sps, 1),
        "scale": round(scale, 3),
        "scale_floor": floor,
        "survivor_asserts": [
            "emergency checkpoint durable before re-form",
            "ring re-formed at N-1, data re-sharded",
            "accuracy floor after worker loss",
            "survivor replicas bitwise consistent",
            "fresh resume bitwise-identical to live state",
        ],
    }
    out_path = os.path.join(ROOT, "DIST_r17.json")
    with open(out_path, "w") as f:
        json.dump(report, f, indent=2, sort_keys=True)
        f.write("\n")
    print("dist_gate: 3->2 worker elastic run ok (1 re-form, bitwise "
          "resume); %.1f samples/s vs single %.1f (%.2fx >= %.2f "
          "floor) -> %s"
          % (dist_sps, single_sps, scale, floor, out_path))


if __name__ == "__main__":
    main()
