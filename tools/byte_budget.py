#!/usr/bin/env python
"""Itemized HBM byte budget of the compiled ResNet train step.

VERDICT r4 asked for the roofline *argument* to become an *artifact*: a
per-buffer table showing which tensors account for the step's HBM traffic
(the reference's analog is the memory section of docs/how_to/perf.md plus
the memonger study; here the source of truth is XLA itself).

Method: lower+compile the exact train step bench.py times, then walk the
optimized HLO ENTRY computation. Every top-level instruction materializes
its output in HBM and reads its operands from HBM (internals of a fusion
are VMEM/register-resident and never touch HBM), so

    traffic(instr) = bytes(output) + sum(bytes(operands))

with bytes() honoring the TPU tiling annotation (e.g. ``{3,2,1,0:T(8,128)}``
pads the two minor dims). Attribution comes from the ``op_name`` metadata
that the op library threads through ``jax.named_scope`` — the same plumbing
the profiler uses — so each HLO fusion maps back to a framework op.

Outputs a markdown table (top-N instructions by traffic), per-framework-op
rollup, totals, and XLA's own aggregate memory/cost analysis for
cross-checking. Copy the tables into docs/perf.md.

Usage: python tools/byte_budget.py [--batch 128] [--top 15] [--dtype bfloat16]
"""
import argparse
import collections
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "s32": 4, "u32": 4,
    "s64": 8, "u64": 8, "f16": 2, "bf16": 2, "f32": 4, "f64": 8,
    "c64": 8, "c128": 16,
}

# one HLO shape like  bf16[128,256,56,56]{3,2,1,0:T(8,128)(2,1)}
_SHAPE_RE = re.compile(
    r"(?P<dt>%s)\[(?P<dims>[\d,]*)\]"
    r"(?:\{(?P<layout>[\d,]*)(?::(?P<tiles>[^}]*))?\})?"
    % "|".join(_DTYPE_BYTES))
_TILE_RE = re.compile(r"T\((\d+),(\d+)\)")


def shape_bytes(m):
    """Physical bytes of one parsed shape, honoring minor-dim tiling pads.

    Shapes annotated with a memory space ``S(n)`` live outside default HBM
    (S(1) = VMEM/scoped prefetch destinations, S(2) = sync flags) — they
    count zero here; their HBM side is charged at the copy/slice-start that
    filled them."""
    dt = m.group("dt")
    dims_s = m.group("dims")
    dims = [int(d) for d in dims_s.split(",") if d] if dims_s else []
    tiles_all = m.group("tiles") or ""
    if "S(" in tiles_all:
        return 0
    if not dims:
        return _DTYPE_BYTES[dt]
    layout = m.group("layout")
    tiles = tiles_all
    tm = _TILE_RE.search(tiles)
    phys = list(dims)
    if tm and layout:
        # layout lists minor-to-major dim ids; tile pads the two minor dims
        order = [int(x) for x in layout.split(",") if x]
        t_sub, t_lane = int(tm.group(1)), int(tm.group(2))
        if len(order) >= 1:
            lane = order[0]
            phys[lane] = -(-phys[lane] // t_lane) * t_lane
        if len(order) >= 2:
            sub = order[1]
            phys[sub] = -(-phys[sub] // t_sub) * t_sub
    n = 1
    for d in phys:
        n *= d
    return n * _DTYPE_BYTES[dt]


def all_shapes_bytes(text):
    """Sum bytes over every shape in a type string (handles tuples)."""
    return sum(shape_bytes(m) for m in _SHAPE_RE.finditer(text))


_INSTR_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?(?P<name>[\w.\-]+)\s*=\s*(?P<type>\(?.*?\)?)\s+"
    r"(?P<op>[\w\-]+)\((?P<rest>.*)$")
_META_RE = re.compile(r'op_name="([^"]*)"')
_OPERAND_RE = re.compile(r"%?([\w.\-]+)")


def parse_entry(hlo_text):
    """Yield (name, opkind, out_bytes, operand_names, op_name_meta) for each
    instruction in the ENTRY computation."""
    lines = hlo_text.splitlines()
    in_entry = False
    depth = 0
    shapes = {}  # instr name -> output bytes (from its definition line)
    instrs = []
    for ln in lines:
        if ln.startswith("ENTRY "):
            in_entry = True
            depth = ln.count("{") - ln.count("}")
            continue
        if not in_entry:
            continue
        depth += ln.count("{") - ln.count("}")
        if depth < 0:
            break
        m = _INSTR_RE.match(ln)
        if not m:
            continue
        name, opkind = m.group("name"), m.group("op")
        out_b = all_shapes_bytes(m.group("type"))
        shapes[name] = out_b
        # operands: %-prefixed refs in the call args before any attribute
        rest = m.group("rest")
        args = rest.split("),", 1)[0]
        opnames = [x for x in _OPERAND_RE.findall(args) if x in shapes]
        meta = _META_RE.search(ln)
        instrs.append((name, opkind, out_b, opnames,
                       meta.group(1) if meta else ""))
    return instrs, shapes


# HLO ops that never move HBM bytes themselves. ``*-done`` halves of async
# pairs are also free (traffic charged at the ``*-start``).
_FREE_OPS = {"parameter", "constant", "tuple", "get-tuple-element",
             "bitcast", "bitcast-convert", "after-all", "partition-id",
             "replica-id", "iota"}


def scope_of(op_name_meta):
    """Collapse a jax op_name path to the framework-level scope."""
    if not op_name_meta:
        return "(unattributed)"
    parts = [p for p in op_name_meta.split("/") if p and p != "jit(step_fn)"]
    # keep transpose marker + first named scope under it
    keep = []
    for p in parts:
        if p.startswith("jit("):
            continue
        keep.append(p)
        if len(keep) >= 2:
            break
    return "/".join(keep) if keep else "(unattributed)"


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=128)
    ap.add_argument("--depth", type=int, default=50)
    ap.add_argument("--image", type=int, default=224)
    ap.add_argument("--dtype", default="bfloat16")
    ap.add_argument("--storage-dtype", default="float32")
    ap.add_argument("--top", type=int, default=15)
    ap.add_argument("--layout", default="NCHW")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    from mxnet_tpu import models
    from mxnet_tpu.train_step import TrainStep

    batch, image = args.batch, args.image
    dshape = ((batch, image, image, 3) if args.layout == "NHWC"
              else (batch, 3, image, image))
    sym = models.resnet(num_classes=1000, num_layers=args.depth,
                        image_shape="3,%d,%d" % (image, image),
                        layout=args.layout)
    step = TrainStep(sym, optimizer="sgd", learning_rate=0.1, momentum=0.9,
                     wd=1e-4, dtype=args.storage_dtype,
                     compute_dtype=None if args.dtype == "float32"
                     else args.dtype)
    state = step.init({"data": dshape}, {"softmax_label": (batch,)})
    rng = np.random.default_rng(0)
    data = {"data": jnp.asarray(rng.normal(size=dshape), np.float32),
            "softmax_label": jnp.asarray(rng.integers(0, 1000, batch),
                                         np.float32)}
    jitted = step._build(batch)
    lowered = jitted.lower(state, data, jax.random.key(0),
                           jnp.asarray(0.1, jnp.float32))
    compiled = lowered.compile()
    hlo = compiled.as_text()
    instrs, _shapes = parse_entry(hlo)

    rows = []
    by_scope = collections.Counter()
    shapes = {}
    for name, opkind, out_b, opnames, meta in instrs:
        if opkind.endswith("-done"):
            # async pair: HBM read was charged at the -start; the S(1)
            # destination is not HBM. Result consumed from VMEM is free.
            shapes[name] = 0
            continue
        shapes[name] = out_b
        if opkind in _FREE_OPS:
            continue
        in_b = sum(shapes.get(o, 0) for o in opnames)
        if opkind.endswith("-start"):
            total = in_b  # HBM read side of the async copy/slice
            out_b = 0
        else:
            total = out_b + in_b
        rows.append((total, out_b, in_b, opkind, meta, name))
        scope = scope_of(meta)
        if not meta and ("copy" in opkind or opkind.endswith("-start")):
            scope = "(layout/prefetch copies)"
        by_scope[scope] += total
    rows.sort(reverse=True)
    grand = sum(r[0] for r in rows)

    print("## Per-instruction HBM traffic (top %d), b%d %s %s"
          % (args.top, batch, args.dtype, args.layout))
    print()
    print("| MB moved | out MB | in MB | HLO op | framework op |")
    print("|---:|---:|---:|---|---|")
    for total, out_b, in_b, opkind, meta, name in rows[:args.top]:
        print("| %.1f | %.1f | %.1f | %s | %s |"
              % (total / 1e6, out_b / 1e6, in_b / 1e6, opkind,
                 scope_of(meta) or name))
    print()
    print("## Rollup by framework op (top %d)" % args.top)
    print()
    print("| MB moved | MB/image | share | scope |")
    print("|---:|---:|---:|---|")
    for scope, b in by_scope.most_common(args.top):
        print("| %.1f | %.2f | %.1f%% | %s |"
              % (b / 1e6, b / 1e6 / batch, 100.0 * b / grand, scope))
    print()
    total_mb = grand / 1e6
    print("entry-instruction traffic (upper bound: assumes zero inter-op "
          "HBM reuse): %.1f MB/step = %.1f MB/image" % (total_mb,
                                                        total_mb / batch))
    try:
        ma = compiled.memory_analysis()
        print("XLA memory_analysis: args=%.1f MB out=%.1f MB temp=%.1f MB "
              "alias=%.1f MB peak(temp+args)=%.1f MB"
              % (ma.argument_size_in_bytes / 1e6,
                 ma.output_size_in_bytes / 1e6,
                 ma.temp_size_in_bytes / 1e6,
                 ma.alias_size_in_bytes / 1e6,
                 (ma.temp_size_in_bytes + ma.argument_size_in_bytes) / 1e6))
    except Exception as exc:
        print("memory_analysis unavailable: %r" % exc)
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, list):
            ca = ca[0]
        print("XLA cost_analysis: %.1f GFLOP/step, bytes accessed %.1f MB "
              "(%.1f MB/image), intensity %.1f FLOP/byte"
              % (ca["flops"] / 1e9, ca.get("bytes accessed", 0) / 1e6,
                 ca.get("bytes accessed", 0) / 1e6 / batch,
                 ca["flops"] / max(ca.get("bytes accessed", 1), 1)))
    except Exception as exc:
        print("cost_analysis unavailable: %r" % exc)


if __name__ == "__main__":
    main()
