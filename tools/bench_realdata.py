#!/usr/bin/env python
"""End-to-end REAL-DATA training throughput: RecordIO shards -> native
fused JPEG decode/augment (src/io/image_decode.cc) -> prefetch/double
buffer -> fused ResNet train step on the chip.

The proof VERDICT r3 asked for: the synthetic bench (bench.py) measures
compute only; this measures the full input-bound path and reports both,
plus the ratio (target: real >= 90% of synthetic).

Builds a reusable synthetic ImageNet-like .rec (random JPEGs, real libjpeg
decode cost) under --workdir on first run. Ref: the reference benchmarks
train_imagenet.py with ImageRecordIter the same way
(example/image-classification/README.md; src/io/iter_image_recordio_2.cc).

Prints ONE JSON line like bench.py.
"""
import argparse
import io as _io
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

import numpy as np


def build_rec(path, n=2048, h=256, w=256, num_classes=1000, quality=90):
    from PIL import Image
    from mxnet_tpu import recordio
    rng = np.random.default_rng(0)
    idx = os.path.splitext(path)[0] + ".idx"
    rec = recordio.MXIndexedRecordIO(idx, path, "w")
    for i in range(n):
        # random-ish natural image: low-frequency noise so JPEG size/decode
        # cost is realistic (~20-40 KB at q90), not pathological white noise
        base = rng.normal(128, 48, size=(h // 8, w // 8, 3))
        img = np.clip(np.kron(base, np.ones((8, 8, 1))) +
                      rng.normal(0, 12, size=(h, w, 3)), 0, 255).astype(
                          np.uint8)
        buf = _io.BytesIO()
        Image.fromarray(img).save(buf, format="JPEG", quality=quality)
        header = recordio.IRHeader(0, float(i % num_classes), i, 0)
        rec.write_idx(i, recordio.pack(header, buf.getvalue()))
    rec.close()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--workdir", default="/tmp/mxtpu_realdata")
    ap.add_argument("--batch", type=int, default=128)
    ap.add_argument("--images", type=int, default=2048)
    ap.add_argument("--depth", type=int, default=50)
    ap.add_argument("--steps", type=int, default=60)
    ap.add_argument("--dtype", default="bfloat16")
    args = ap.parse_args()

    os.makedirs(args.workdir, exist_ok=True)
    rec_path = os.path.join(args.workdir, "train_%d.rec" % args.images)
    if not os.path.exists(rec_path):
        print("building %s ..." % rec_path, file=sys.stderr)
        build_rec(rec_path, n=args.images)

    import jax
    import jax.numpy as jnp
    import mxnet_tpu as mx
    from mxnet_tpu import models
    from mxnet_tpu.train_step import TrainStep

    sym = models.resnet(num_classes=1000, num_layers=args.depth,
                        image_shape="3,224,224")
    step = TrainStep(sym, optimizer="sgd", learning_rate=0.1, momentum=0.9,
                     wd=1e-4,
                     compute_dtype=None if args.dtype == "float32"
                     else args.dtype)
    state = step.init({"data": (args.batch, 3, 224, 224)},
                      {"softmax_label": (args.batch,)})

    it = mx.image.ImageRecordIter(
        path_imgrec=rec_path, data_shape=(3, 224, 224),
        batch_size=args.batch, shuffle=True, rand_crop=True,
        rand_mirror=True, resize=256,
        mean_r=123.68, mean_g=116.78, mean_b=103.94,
        std_r=58.4, std_g=57.12, std_b=57.38)

    def batches():
        while True:
            try:
                b = it.next()
            except StopIteration:
                it.reset()
                b = it.next()
            yield b

    gen = batches()

    def run(n):
        t0 = time.perf_counter()
        nonlocal state
        for _ in range(n):
            b = next(gen)
            state, _ = step.step(state, {"data": b.data[0].data,
                                         "softmax_label": b.label[0].data})
        np.asarray(state["step"])     # tunnel-honored sync
        return time.perf_counter() - t0

    run(3)                            # compile + warm pipeline
    short = max(args.steps // 6, 5)
    t_s = run(short)
    t_l = run(args.steps)
    ips = args.batch * (args.steps - short) / (t_l - t_s) \
        if t_l > t_s else args.batch * args.steps / t_l

    # synthetic ceiling on the same process/chip for the ratio
    data_syn = {"data": jnp.asarray(np.random.rand(
        args.batch, 3, 224, 224), np.float32),
        "softmax_label": jnp.asarray(
            np.random.randint(0, 1000, args.batch), np.float32)}

    def run_syn(n):
        t0 = time.perf_counter()
        nonlocal state
        for _ in range(n):
            state, _ = step.step(state, data_syn)
        np.asarray(state["step"])
        return time.perf_counter() - t0

    run_syn(3)
    t_s2 = run_syn(short)
    t_l2 = run_syn(args.steps)
    ips_syn = args.batch * (args.steps - short) / (t_l2 - t_s2) \
        if t_l2 > t_s2 else args.batch * args.steps / t_l2

    # stage decomposition so the headline is interpretable: on a tunneled
    # single-chip dev host the host->device link (~tens of MB/s) is the
    # binding constraint, not the decode pipeline or the chip
    keys = it.seq[:args.batch]
    t0 = time.perf_counter()
    for i in range(3):
        it.decode_batch_numpy(keys, i)
    decode_ips = 3 * args.batch / (time.perf_counter() - t0)
    xh = np.random.rand(args.batch, 3, 224, 224).astype(np.float32)
    jnp.asarray(xh).block_until_ready()
    t0 = time.perf_counter()
    a = jnp.asarray(xh)
    np.asarray(a[0, 0, 0, 0])
    h2d_mbps = xh.nbytes / 1e6 / (time.perf_counter() - t0)
    h2d_ips = h2d_mbps * 1e6 / xh.nbytes * args.batch

    bound = min(decode_ips, h2d_ips, ips_syn)
    print(json.dumps({
        "metric": "resnet%d_e2e_realdata_images_per_sec_b%d_%s"
                  % (args.depth, args.batch, args.dtype),
        "value": round(ips, 2), "unit": "images/sec",
        "synthetic_same_process": round(ips_syn, 2),
        "ratio_vs_synthetic": round(ips / ips_syn, 3) if ips_syn else None,
        "stage_decode_only": round(decode_ips, 1),
        "stage_h2d_mbps": round(h2d_mbps, 1),
        "stage_h2d_images_per_sec": round(h2d_ips, 1),
        "host_cores": os.cpu_count(),
        "binding_stage": ("h2d_link" if bound == h2d_ips else
                          "decode" if bound == decode_ips else "compute"),
        "pipeline_efficiency_vs_binding_stage": round(ips / bound, 3),
    }))


if __name__ == "__main__":
    main()
