#!/usr/bin/env python
"""CI gate: every zoo model reports a NON-FALLBACK K-step dispatch path
(docs/perf.md "Packed accumulators").

Two layers:

1. **Precheck sweep (every zoo model, nothing executes).** Bind a Module
   at the zoo audit shapes with the model's natural metric and ask
   ``_can_bulk_dispatch(metric)`` — the exact predicate ``fit`` consults
   before engaging ``steps_per_dispatch>1``. A model may only answer
   "fallback" when ``DOCUMENTED_FALLBACKS`` names why; an undocumented
   fallback fails the gate, so a metric/shape regression that would
   silently re-introduce the k=1 class is caught here, not in a
   production run's logs.

2. **Engagement proof (the cheap models, real fits).** mlp, lenet, ssd
   and the transformer actually train one epoch at steps_per_dispatch=2
   and must land a compiled scan in the jit cache; afterwards the
   registered program set must be tracecheck-clean.

The heavy 224px classifiers are covered by layer 1 only — executing VGG
steps on a 1-core CI host costs minutes and proves nothing layer 1
doesn't (fit takes the same precheck).
"""
import logging
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import metric as M
from mxnet_tpu import models, tracecheck

#: zoo models allowed to fall back, each with the documented reason the
#: fit warning must name. EMPTY since the packed-accumulator protocol:
#: every shipped model declares a device-sum layout.
DOCUMENTED_FALLBACKS = {}

#: models cheap enough to fit end-to-end on a 1-core CI host
FIT_MODELS = ("mlp", "lenet", "ssd", "transformer")


def natural_metric(mname):
    if mname == "transformer":
        return M.Perplexity(ignore_label=None)
    if mname == "ssd":
        return M.MultiBoxMetric()
    return M.create(["acc", "ce"])


def synth_iter(cfg, lname, batches=4, k=2):
    rng = np.random.default_rng(0)
    n = cfg["data"][0] * batches * k
    dshape = (n,) + tuple(cfg["data"][1:])
    lshape = (n,) + tuple(cfg["label"][1:])
    if lname == "label":          # ssd: [cls, x1, y1, x2, y2] rows
        lab = rng.random(lshape).astype(np.float32)
        lab[..., 0] = rng.integers(0, 3, lshape[:-1])
        x1 = np.minimum(lab[..., 1], lab[..., 3])
        y1 = np.minimum(lab[..., 2], lab[..., 4])
        lab[..., 3] = np.maximum(lab[..., 1], lab[..., 3]) + 0.05
        lab[..., 4] = np.maximum(lab[..., 2], lab[..., 4]) + 0.05
        lab[..., 1], lab[..., 2] = x1, y1
    else:
        # class ids 0/1 are valid for every zoo head (smallest is 3-way)
        lab = rng.integers(0, 2, lshape).astype(np.float32)
    X = rng.normal(size=dshape).astype(np.float32)
    return mx.io.NDArrayIter({"data": X}, {lname: lab},
                             batch_size=cfg["data"][0])


def main():
    logging.basicConfig(level=logging.INFO)
    failures = []
    for mname in sorted(tracecheck.ZOO):
        cfg = tracecheck.ZOO[mname]
        lname = cfg.get("label_name", "softmax_label")
        sym = models.get_symbol(mname, **cfg["kwargs"])
        metric = natural_metric(mname)
        mod = mx.mod.Module(sym, data_names=("data",),
                            label_names=(lname,), context=mx.cpu())
        mod.bind(data_shapes=[("data", cfg["data"])],
                 label_shapes=[(lname, cfg["label"])])
        mod.init_params(initializer=mx.initializer.Xavier())
        mod.init_optimizer(optimizer="sgd",
                           optimizer_params={"learning_rate": 0.01})
        ok, why = mod._can_bulk_dispatch(metric)
        if ok:
            print("zoo-dispatch: %-13s OK (metric %s, packed slots %s)"
                  % (mname, type(metric).__name__,
                     mod._fused_metric_spec.slots))
        elif mname in DOCUMENTED_FALLBACKS:
            print("zoo-dispatch: %-13s documented fallback: %s"
                  % (mname, why))
            if DOCUMENTED_FALLBACKS[mname] not in (why or ""):
                failures.append(
                    "%s: fallback reason drifted from the documented one "
                    "(%r vs documented %r)"
                    % (mname, why, DOCUMENTED_FALLBACKS[mname]))
        else:
            failures.append("%s: UNDOCUMENTED k=1 fallback: %s"
                            % (mname, why))

    # engagement proof: real fits on the cheap models
    for mname in FIT_MODELS:
        cfg = tracecheck.ZOO[mname]
        lname = cfg.get("label_name", "softmax_label")
        sym = models.get_symbol(mname, **cfg["kwargs"])
        metric = natural_metric(mname)
        it = synth_iter(cfg, lname)
        mod = mx.mod.Module(sym, data_names=("data",),
                            label_names=(lname,), context=mx.cpu())
        mx.random.seed(0)
        mod.fit(it, num_epoch=1, steps_per_dispatch=2,
                initializer=mx.initializer.Xavier(), eval_metric=metric,
                optimizer_params={"learning_rate": 0.01})
        engaged = (mod._fused is not None
                   and any(key[1] == 2 for key in mod._fused._jit_scan))
        if not engaged:
            failures.append("%s: fit(steps_per_dispatch=2) did not land "
                            "a compiled scan" % mname)
        else:
            vals = metric.get_name_value()
            print("zoo-dispatch: %-13s fit engaged scan; train %s"
                  % (mname, vals))

    findings = tracecheck.unsuppressed(tracecheck.check_registered())
    if findings:
        for f in findings:
            print(f.format(), file=sys.stderr)
        failures.append("%d tracecheck finding(s) over the dispatched "
                        "program set" % len(findings))

    if failures:
        for f in failures:
            print("zoo-dispatch FAIL: %s" % f, file=sys.stderr)
        return 1
    print("zoo-dispatch gate PASS (%d models prechecked, %d fit)"
          % (len(tracecheck.ZOO), len(FIT_MODELS)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
