#!/usr/bin/env python
"""On-chip ResNet convergence gate (ref: tests/nightly/test_all.sh:44-67
check_val — train jobs gated on validation accuracy; this is the
ResNet-scale step beyond the MNIST/LeNet unit gates).

Trains ResNet on a synthetic 10-class dataset that lives ON DEVICE (a
fixed pool of structured color/texture images), so the tunnel-limited
host->device link (docs/perf.md) is out of the loop and the gate measures
the training machinery itself: fused step, BN statistics, optimizer, lr
schedule. Asserts held-out accuracy.

  python tools/convergence_gate.py            # resnet-18 @64px, ~3 min
  python tools/convergence_gate.py --depth 50 --steps 400
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

import numpy as np


def make_pool(rng, n, size, classes):
    """Structured, augment-robust class templates: per-class base color +
    per-class stripe frequency, plus instance noise."""
    ang = rng.uniform(0, np.pi, classes)
    freq = rng.uniform(2, 8, classes)
    base = rng.uniform(0.2, 0.8, (classes, 3))
    xs = np.linspace(0, 1, size)
    gx, gy = np.meshgrid(xs, xs, indexing="ij")
    imgs = np.empty((n, 3, size, size), np.float32)
    labels = np.empty((n,), np.float32)
    for i in range(n):
        k = i % classes
        wave = np.sin(2 * np.pi * freq[k]
                      * (gx * np.cos(ang[k]) + gy * np.sin(ang[k])))
        img = base[k][:, None, None] + 0.25 * wave[None]
        img = img + rng.normal(0, 0.15, img.shape)
        imgs[i] = img.astype(np.float32)
        labels[i] = k
    return imgs, labels


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--depth", type=int, default=18)
    ap.add_argument("--size", type=int, default=64)
    ap.add_argument("--classes", type=int, default=10)
    ap.add_argument("--pool", type=int, default=2048)
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--steps", type=int, default=200)
    ap.add_argument("--lr", type=float, default=1e-3)
    ap.add_argument("--optimizer", default="adam",
                    help="adam converges in <50 steps; sgd works with a "
                         "tuned lr schedule")
    ap.add_argument("--dtype", default="bfloat16")
    ap.add_argument("--min-acc", type=float, default=0.9)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    from mxnet_tpu import models
    from mxnet_tpu.train_step import TrainStep

    rng = np.random.default_rng(0)
    imgs, labels = make_pool(rng, args.pool, args.size, args.classes)
    n_train = args.pool * 3 // 4
    # device-resident data pool: one upload, minibatches sliced on device
    d_imgs = jnp.asarray(imgs[:n_train])
    d_labels = jnp.asarray(labels[:n_train])
    v_imgs = jnp.asarray(imgs[n_train:])
    v_labels = labels[n_train:]

    from mxnet_tpu import optimizer as opt_mod, lr_scheduler
    sym = models.resnet(num_classes=args.classes, num_layers=args.depth,
                        image_shape="3,%d,%d" % (args.size, args.size))
    sched = lr_scheduler.MultiFactorScheduler(
        step=[args.steps * 2 // 3], factor=0.1)
    # rescale_grad must be set explicitly on instance optimizers:
    # TrainStep only defaults to 1/batch for string-named ones
    if args.optimizer == "adam":
        opt = opt_mod.create("adam", learning_rate=args.lr,
                             rescale_grad=1.0 / args.batch,
                             lr_scheduler=sched)
    else:
        opt = opt_mod.create("sgd", learning_rate=args.lr, momentum=0.9,
                             wd=1e-4, rescale_grad=1.0 / args.batch,
                             lr_scheduler=sched)
    step = TrainStep(sym, optimizer=opt,
                     compute_dtype=None if args.dtype == "float32"
                     else args.dtype)
    state = step.init({"data": (args.batch, 3, args.size, args.size)},
                      {"softmax_label": (args.batch,)})

    t0 = time.perf_counter()
    order = rng.permutation(n_train)
    for s in range(args.steps):
        idx = jnp.asarray(order[(np.arange(args.batch)
                                 + s * args.batch) % n_train])
        batch = {"data": d_imgs[idx], "softmax_label": d_labels[idx]}
        state, _ = step.step(state, batch)
    np.asarray(state["step"])
    train_s = time.perf_counter() - t0

    # held-out accuracy via an eval-mode forward (moving BN stats)
    from mxnet_tpu.executor import _build_graph_runner
    run, _nodes = _build_graph_runner(sym)

    @jax.jit
    def fwd(params, aux, data):
        vals = dict(params)
        vals["data"] = data
        vals["softmax_label"] = jnp.zeros((data.shape[0],), jnp.float32)
        outs, _ = run(vals, aux, None, False)
        return outs[0]

    correct = 0
    for i in range(0, len(v_labels) - args.batch + 1, args.batch):
        out = fwd(state["params"], state["aux"], v_imgs[i:i + args.batch])
        pred = np.asarray(out).argmax(axis=1)
        correct += int((pred == v_labels[i:i + args.batch]).sum())
    n_eval = (len(v_labels) // args.batch) * args.batch
    if n_eval == 0:
        raise SystemExit("holdout split (%d) smaller than --batch (%d); "
                         "raise --pool or lower --batch"
                         % (len(v_labels), args.batch))
    acc = correct / n_eval
    print(json.dumps({
        "metric": "resnet%d_synthetic10_holdout_acc" % args.depth,
        "value": round(acc, 4),
        "steps": args.steps,
        "train_seconds": round(train_s, 1),
        "images_per_sec": round(args.steps * args.batch / train_s, 1),
    }))
    assert acc >= args.min_acc, "convergence gate: %.3f < %.3f" % (
        acc, args.min_acc)
    print("CONVERGENCE PASS")


if __name__ == "__main__":
    main()
