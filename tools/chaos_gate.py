#!/usr/bin/env python
"""CI gate: the seeded deterministic chaos harness
(docs/robustness.md "Chaos harness").

Four legs, in order:

1. **RED self-test** — before trusting a single green verdict, prove the
   plumbing can fail: one cheap scenario runs with
   ``MXTPU_CHAOS_BREAK_INVARIANT=typed_outcome`` (the invariant checker
   deliberately inverts that verdict) and the gate DEMANDS a violation.
   A harness that cannot turn red gates nothing.
2. **Seeded rounds** — ``MXTPU_CHAOS_ROUNDS`` (default 3) plans per
   scenario, seeds ``MXTPU_CHAOS_SEED + round``. Every round must come
   back with zero violations and zero watchdog fires: each plan's
   composed faults either recover (bitwise-resume / exactly-once
   settlement / health-counter consistency hold) or fail typed.
3. **Regression replays** — every committed plan under
   ``tests/chaos_plans/`` is replayed; these are schedules worth pinning
   forever (a worker-die + slow-reform-leader compose, a torn-write +
   mid-run-raise compose, ...), and the plan JSON's byte-for-byte
   determinism is what makes the replay exact.
4. **Shrinker exercise** — the first seeded plan is shrunk under the
   inverted-invariant judge (every run "fails", so the shrinker must
   reduce to a single rule in a bounded number of re-runs) — the
   reduction loop stays covered without needing a real standing bug.

Emits CHAOS_r18.json (committed, like the DIST_r*.json series).
Knobs: MXTPU_CHAOS_SEED (default 0), MXTPU_CHAOS_ROUNDS (default 3),
MXTPU_CHAOS_DEADLINE (per-scenario watchdog override).
"""
import json
import os
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

from mxnet_tpu.base import env_int  # noqa: E402
from mxnet_tpu.chaos import (ChaosPlan, sample_plan, check_scenario,
                             shrink_plan, SCENARIOS)  # noqa: E402
from mxnet_tpu.chaos.runner import run_plan  # noqa: E402

OUT = os.path.join(ROOT, "CHAOS_r18.json")
PLANS_DIR = os.path.join(ROOT, "tests", "chaos_plans")


def _fail(msg):
    print("chaos gate FAIL: %s" % msg)
    sys.exit(1)


def _judge(plan, workdir):
    outcome = run_plan(plan, workdir)
    violations = check_scenario(plan, outcome)
    return outcome, violations


def _round_record(plan, outcome, violations):
    return {"scenario": plan.scenario, "seed": plan.seed,
            "plan": plan.describe(), "n_faults": len(plan),
            "wall_s": round(outcome["wall_s"], 2),
            "watchdog_fired": outcome["watchdog_fired"],
            "violations": [v.to_dict() for v in violations]}


def main():
    import tempfile
    base = tempfile.mkdtemp(prefix="mxtpu-chaos-gate-")
    seed0 = env_int("MXTPU_CHAOS_SEED", 0)
    rounds = env_int("MXTPU_CHAOS_ROUNDS", 3)
    report = {"schema": "mxtpu-chaos-gate-v1", "seed": seed0,
              "rounds": rounds, "red_self_test": None,
              "scenarios": {}, "regressions": [], "shrink": None}

    # -- leg 1: the gate must be able to turn RED ----------------------
    os.environ["MXTPU_CHAOS_BREAK_INVARIANT"] = "typed_outcome"
    try:
        plan = sample_plan(seed0, "serve")
        _outcome, viols = _judge(plan, os.path.join(base, "red"))
    finally:
        del os.environ["MXTPU_CHAOS_BREAK_INVARIANT"]
    if not viols:
        _fail("RED self-test: the deliberately broken invariant "
              "produced a GREEN run — the gate's plumbing proves "
              "nothing. Check MXTPU_CHAOS_BREAK_INVARIANT handling in "
              "chaos/invariants.py.")
    report["red_self_test"] = {"violations": [v.to_dict() for v in viols],
                               "ok": True}
    print("[red self-test] broken invariant turned the run red: OK")

    # -- leg 2: seeded rounds per scenario -----------------------------
    t0 = time.time()
    for scenario in SCENARIOS:
        recs = []
        for rnd in range(rounds):
            seed = seed0 + rnd
            plan = sample_plan(seed, scenario)
            wd = os.path.join(base, "%s-s%d" % (scenario, seed))
            outcome, viols = _judge(plan, wd)
            rec = _round_record(plan, outcome, viols)
            recs.append(rec)
            status = "GREEN" if not viols else "RED"
            print("[%s seed=%d] %s %.1fs  %s"
                  % (scenario, seed, status, outcome["wall_s"],
                     plan.describe()))
            if viols:
                for v in viols:
                    print("  VIOLATION [%s] %s" % (v.invariant, v.detail))
                print("  worker log: %s" % outcome["log"])
                _fail("%s seed=%d: %d violation(s)"
                      % (scenario, seed, len(viols)))
        report["scenarios"][scenario] = recs

    # -- leg 3: committed regression replays ---------------------------
    for name in sorted(os.listdir(PLANS_DIR)):
        plan = ChaosPlan.load(os.path.join(PLANS_DIR, name))
        wd = os.path.join(base, "regress-%s" % name.replace(".json", ""))
        outcome, viols = _judge(plan, wd)
        rec = _round_record(plan, outcome, viols)
        rec["file"] = name
        report["regressions"].append(rec)
        print("[regression %s] %s %.1fs"
              % (name, "GREEN" if not viols else "RED",
                 outcome["wall_s"]))
        if viols:
            for v in viols:
                print("  VIOLATION [%s] %s" % (v.invariant, v.detail))
            _fail("regression replay %s: %d violation(s)"
                  % (name, len(viols)))

    # -- leg 4: shrink loop under the inverted judge -------------------
    plan = sample_plan(seed0, "serve")
    os.environ["MXTPU_CHAOS_BREAK_INVARIANT"] = "typed_outcome"
    try:
        counter = {"n": 0}

        def violates(candidate):
            counter["n"] += 1
            wd = os.path.join(base, "shrink-%d" % counter["n"])
            _o, v = _judge(candidate, wd)
            return bool(v)

        shrunk, runs = shrink_plan(plan, violates, log=print)
    finally:
        del os.environ["MXTPU_CHAOS_BREAK_INVARIANT"]
    if len(shrunk) != 1:
        _fail("shrinker: an always-failing judge must reduce to ONE "
              "rule, got %d" % len(shrunk))
    report["shrink"] = {"from": len(plan), "to": len(shrunk),
                        "runs": runs, "minimal": shrunk.describe()}
    print("[shrink] %d -> %d rule(s) in %d re-run(s)"
          % (len(plan), len(shrunk), runs))

    report["wall_s"] = round(time.time() - t0, 1)
    with open(OUT, "w") as f:
        json.dump(report, f, indent=1, sort_keys=True)
        f.write("\n")
    print("chaos gate PASS (%d scenarios x %d rounds + %d regressions, "
          "%.0fs) -> %s"
          % (len(SCENARIOS), rounds, len(report["regressions"]),
             report["wall_s"], OUT))


if __name__ == "__main__":
    main()
