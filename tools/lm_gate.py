#!/usr/bin/env python
"""CI gate for the flagship LM (docs/perf.md "Flagship LM").

A small transformer LM trained through ``Module.fit``'s fused K-step
scan on the FORCED-HOST dp x sp mesh, asserting the whole
train-to-serve story closes on 4 virtual CPU devices:

1. dp2 x sp2 multi-axis fit matches the single-device fit's final
   parameters (rtol 2e-3) — the composed mesh changes the schedule,
   never the math;
2. MID-FIT hot reload: an epoch-end callback swaps the live epoch-2
   parameters into a :class:`DecodeLoop` that is already serving —
   ZERO recompiles (``assert_no_retrace``) and the greedy decode is
   BITWISE identical to a fresh engine built from the same snapshot;
3. zero unexpected retraces across both fits (the multi-axis scan
   carry is pinned by the jit-root ``out_shardings`` — a miss here is
   a recompile storm in production);
4. zero analyzer findings: the comms lints over the dp x sp scan
   program, and ``memcheck.lint_resident_set`` over the CO-RESIDENT
   train + serve program set (the fused scan plus every compiled
   serving bucket — exactly what a train-then-serve host keeps live).

Run via ci/lm.sh (sets the forced-host device count).
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

V, E, H, L, S, B, K = 32, 32, 4, 2, 16, 8, 2
EPOCHS = 3
MESH = "data=2,seq=2"


def main():
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import numpy as np
    import jax
    import jax.numpy as jnp
    import mxnet_tpu as mx
    from mxnet_tpu import commscheck, memcheck, models, tracecheck
    from mxnet_tpu.serving import DecodeLoop, ServingEngine
    from mxnet_tpu.test_utils import assert_no_retrace

    if len(jax.devices()) < 4:
        sys.exit("lm_gate: needs 4 devices for the %s mesh — run via "
                 "ci/lm.sh (XLA_FLAGS=--xla_force_host_platform_"
                 "device_count=8)" % MESH)

    sym = models.transformer(vocab_size=V, embed=E, num_heads=H,
                             num_layers=L, seq_len=S)
    # the dp x sp fit runs the RING schedule (ppermute over 'seq') with
    # the rank-3 preserve_shape head — the default symbol would leave
    # the seq-sharded attention to GSPMD's generic resharding and merge
    # sharded batch x seq dims at the head, whose in-loop all-gathers
    # the comms lint rightly flags; parity of ring-vs-plain IS the
    # tentpole's claim
    sym_ring = models.transformer(vocab_size=V, embed=E, num_heads=H,
                                  num_layers=L, seq_len=S,
                                  seq_parallel="ring",
                                  preserve_shape=True)
    rng = np.random.RandomState(0)
    data = rng.randint(0, V, (4 * B, S)).astype(np.float32)
    label = rng.randint(0, V, (4 * B, S)).astype(np.float32)

    def make_iter():
        return mx.io.NDArrayIter(data={"data": data},
                                 label={"softmax_label": label},
                                 batch_size=B)

    def run_fit(s=sym, mesh_axes=None, epoch_end=None, shardings=None,
                **kw):
        mod = mx.mod.Module(s, context=mx.cpu(), mesh_axes=mesh_axes,
                            param_shardings=shardings)
        mx.random.seed(7)
        mod.fit(make_iter(), num_epoch=EPOCHS, optimizer="sgd",
                optimizer_params={"learning_rate": 0.05},
                initializer=mx.initializer.Xavier(),
                eval_metric=mx.metric.Perplexity(ignore_label=None),
                epoch_end_callback=epoch_end, **kw)
        return mod

    # -- 1) single-device reference
    ref = run_fit()
    a_ref, _ = ref.get_params()
    a_ref = {k: v.asnumpy().copy() for k, v in a_ref.items()}

    # -- 2) dp x sp fit with the mid-fit decode hot reload riding along
    prompt = [1, 2, 3]
    mid = {}

    def epoch_end(epoch, _sym, arg, _aux):
        snap = {k: v.asnumpy().copy() for k, v in arg.items()}
        if epoch == 0:
            # the serving loop goes live off the first epoch's params
            mid["loop"] = DecodeLoop(snap, num_layers=L, num_heads=H,
                                     max_len=S, slots=2)
            mid["loop"].generate(prompt, 4).result(timeout=120)
        elif epoch == 1:
            # MID-FIT: swap epoch-2 params into the live loop — zero
            # recompiles, decode must match a fresh engine bitwise
            mid["params"] = snap
            with assert_no_retrace(msg="mid-fit decode hot reload"):
                mid["loop"].update_params(snap)
                mid["tokens"] = mid["loop"].generate(
                    prompt, 4).result(timeout=120)

    # pos_embed rows belong to their 'seq' shard: the grad is naturally
    # seq-sharded, so a replicated table would all-gather it every trip
    # inside the optimizer (the comms lint catches exactly that)
    mod = run_fit(s=sym_ring, mesh_axes=MESH, epoch_end=epoch_end,
                  shardings={"pos_embed_weight": jax.sharding.PartitionSpec(
                      "seq", None)},
                  steps_per_dispatch=K)
    a, _ = mod.get_params()
    a = {k: v.asnumpy().copy() for k, v in a.items()}

    # -- parity
    if set(a) != set(a_ref):
        sys.exit("lm_gate FAIL: param set drifted under %s: %r vs %r"
                 % (MESH, sorted(a), sorted(a_ref)))
    for k in sorted(a_ref):
        if not np.allclose(a[k], a_ref[k], rtol=2e-3, atol=2e-5):
            err = float(np.max(np.abs(a[k] - a_ref[k])))
            sys.exit("lm_gate FAIL: %s mismatch vs single device on %s "
                     "(max abs err %.3g) — the mesh changed the math"
                     % (MESH, k, err))

    # -- mid-fit hot reload: bitwise vs a fresh engine
    if "tokens" not in mid:
        sys.exit("lm_gate FAIL: the epoch-1 hot-reload callback never "
                 "fired (epochs run: %d)" % EPOCHS)
    fresh = DecodeLoop(mid["params"], num_layers=L, num_heads=H,
                       max_len=S, slots=2)
    want = fresh.generate(prompt, 4).result(timeout=120)
    mid["loop"].close()
    fresh.close()
    if mid["tokens"] != want:
        sys.exit("lm_gate FAIL: mid-fit hot-reloaded decode %r != fresh "
                 "engine %r (must be bitwise)" % (mid["tokens"], want))

    # -- zero unexpected retraces across both fits + the reload
    if tracecheck.retrace_count():
        sys.exit("lm_gate FAIL: %d unexpected retraces:\n%s"
                 % (tracecheck.retrace_count(),
                    "\n".join(map(str, tracecheck.RETRACE_EVENTS))))

    # -- analyzers over the CO-RESIDENT train + serve program set
    fused, state = mod._fused, mod._fused_state
    sb = fused.shard_superbatch(
        {"data": np.stack([data[:B]] * K),
         "softmax_label": np.stack([label[:B]] * K)})
    args = commscheck.struct_args(
        (state, sb, fused._dispatch_key(), jnp.zeros((K,), jnp.float32)))
    from mxnet_tpu.parallel.mesh import MeshScope
    with MeshScope(fused.mesh):  # the ring op resolves 'seq' from it
        compiled = fused._build_scan(B, K, state=state) \
            .lower(*args).compile()
    crep = commscheck.analyze_compiled(
        compiled, "lm-gate/dp2xsp2/scan[k=%d]" % K, mesh=fused.mesh,
        loop_trips=K)
    findings = list(commscheck.lint_report(crep))
    scan_mem = memcheck.analyze_compiled(
        compiled, "lm-gate/dp2xsp2/scan[k=%d]" % K, args=args,
        donate_argnums=(0,))
    eng = ServingEngine(sym.tojson(),
                        {"arg:" + k: v for k, v in a.items()},
                        {"data": (S,)}, buckets=(4,))
    eng.infer({"data": data[:4]})
    resident = [scan_mem] + list(eng.memory_report().values())
    findings += list(memcheck.lint_resident_set(
        resident, "lm-gate train+serve"))
    if findings:
        sys.exit("lm_gate FAIL: %d analyzer findings over the train+serve "
                 "set:\n%s" % (len(findings),
                               "\n".join("  %s" % (f,) for f in findings)))

    print("lm_gate: %s fit parity ok (%d params), mid-fit hot reload "
          "bitwise ok (tokens %r), 0 retraces, 0 findings over %d "
          "co-resident programs (scan + %d serving buckets)"
          % (MESH, len(a), mid["tokens"], len(resident),
             len(resident) - 1))


if __name__ == "__main__":
    main()
