#!/usr/bin/env python
"""Pack an image dataset into RecordIO (ref: tools/im2rec.py + the C++
tools/im2rec.cc binary).

Usage:
  python tools/im2rec.py prefix image_root --list      # generate .lst
  python tools/im2rec.py prefix image_root             # pack prefix.lst
Produces prefix.rec + prefix.idx readable by mxnet_tpu.image.ImageIter.
"""
import argparse
import os
import random
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

from mxnet_tpu import recordio


def list_images(root, recursive=True, exts=(".jpg", ".jpeg", ".png")):
    cat = {}
    out = []
    i = 0
    for path, dirs, files in sorted(os.walk(root)):
        dirs.sort()
        for fname in sorted(files):
            if os.path.splitext(fname)[1].lower() not in exts:
                continue
            label_name = os.path.relpath(path, root)
            if label_name not in cat:
                cat[label_name] = len(cat)
            rel = os.path.relpath(os.path.join(path, fname), root)
            out.append((i, cat[label_name], rel))
            i += 1
        if not recursive:
            break
    return out


def write_list(prefix, image_list, shuffle=True):
    if shuffle:
        random.shuffle(image_list)
    with open(prefix + ".lst", "w") as f:
        for idx, label, rel in image_list:
            f.write("%d\t%f\t%s\n" % (idx, float(label), rel))


def read_list(path):
    with open(path) as f:
        for line in f:
            parts = line.strip().split("\t")
            yield int(parts[0]), float(parts[1]), parts[-1]


def pack(prefix, root, quality=95, resize=0):
    from PIL import Image
    import io as _io
    rec = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "w")
    n = 0
    for idx, label, rel in read_list(prefix + ".lst"):
        path = os.path.join(root, rel)
        img = Image.open(path).convert("RGB")
        if resize:
            w, h = img.size
            if w < h:
                img = img.resize((resize, h * resize // w), Image.BILINEAR)
            else:
                img = img.resize((w * resize // h, resize), Image.BILINEAR)
        buf = _io.BytesIO()
        img.save(buf, format="JPEG", quality=quality)
        header = recordio.IRHeader(0, label, idx, 0)
        rec.write_idx(idx, recordio.pack(header, buf.getvalue()))
        n += 1
    rec.close()
    print("packed %d images into %s.rec" % (n, prefix))


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("prefix")
    parser.add_argument("root")
    parser.add_argument("--list", action="store_true",
                        help="generate the .lst file instead of packing")
    parser.add_argument("--no-shuffle", action="store_true")
    parser.add_argument("--quality", type=int, default=95)
    parser.add_argument("--resize", type=int, default=0)
    args = parser.parse_args()
    if args.list:
        write_list(args.prefix, list_images(args.root),
                   shuffle=not args.no_shuffle)
    else:
        pack(args.prefix, args.root, args.quality, args.resize)


if __name__ == "__main__":
    main()
