#!/usr/bin/env python
"""Hard convergence gate: 12-class real-JPEG dataset through the FULL
native data plane (ref: tests/nightly/test_all.sh:44-67 check_val — the
reference gates multi-epoch conv-net training on real decoded images).

Generates a few thousand JPEG images (12 texture/color classes whose
signal survives random crops and mirrors — augmentation pressure is
real), packs them into RecordIO with the IRHeader format, trains ResNet-18
THROUGH ImageRecordIter (native fused JPEG decode + crop/mirror
augmenters, src/io/image_decode.cc) for multiple epochs under a
MultiFactor LR schedule, and gates held-out accuracy from a separate
val .rec. Unlike the synthetic on-device gate (convergence_gate.py),
every byte crosses the real pipeline: JPEG -> decode -> augment ->
normalize -> batch -> device.

  python tools/convergence_gate_realdata.py               # ~5 min cpu
  python tools/convergence_gate_realdata.py --epochs 8 --min-acc 0.9
"""
import argparse
import io as _io
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

import numpy as np


def make_jpeg_dataset(root, n_per_class, classes, size, rng, quality=90):
    """Class = base color + stripe orientation/frequency; instances vary in
    phase, brightness and noise, so crops/mirrors preserve the label but
    memorizing pixels does not work."""
    from PIL import Image
    from mxnet_tpu import recordio

    ang = rng.uniform(0, np.pi, classes)
    freq = rng.uniform(3, 9, classes)
    base = rng.uniform(0.25, 0.75, (classes, 3))
    xs = np.linspace(0, 1, size)
    gx, gy = np.meshgrid(xs, xs, indexing="ij")

    def render(k):
        phase = rng.uniform(0, 2 * np.pi)
        bright = rng.uniform(0.85, 1.15)
        wave = np.sin(2 * np.pi * freq[k]
                      * (gx * np.cos(ang[k]) + gy * np.sin(ang[k])) + phase)
        img = (base[k][:, None, None] + 0.22 * wave[None]) * bright
        img = img + rng.normal(0, 0.06, img.shape)
        arr = (np.clip(img, 0, 1) * 255).astype(np.uint8)
        return np.transpose(arr, (1, 2, 0))  # HWC for PIL

    def pack_split(fname, n_each):
        path = os.path.join(root, fname)
        idx_path = os.path.splitext(path)[0] + ".idx"
        rec = recordio.MXIndexedRecordIO(idx_path, path, "w")
        order = rng.permutation(classes * n_each)
        entries = [(i % classes) for i in range(classes * n_each)]
        for i, idx in enumerate(order):
            k = entries[idx]
            buf = _io.BytesIO()
            Image.fromarray(render(k)).save(buf, format="JPEG",
                                            quality=quality)
            header = recordio.IRHeader(flag=0, label=float(k), id=int(idx),
                                       id2=0)
            rec.write_idx(i, recordio.pack(header, buf.getvalue()))
        rec.close()
        return path

    train = pack_split("train.rec", n_per_class)
    val = pack_split("val.rec", max(n_per_class // 4, 8))
    return train, val


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--classes", type=int, default=12)
    ap.add_argument("--n-per-class", type=int, default=200)
    ap.add_argument("--size", type=int, default=48)
    ap.add_argument("--crop", type=int, default=40)
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--epochs", type=int, default=6)
    ap.add_argument("--lr", type=float, default=0.002)
    ap.add_argument("--min-acc", type=float, default=0.9)
    ap.add_argument("--workdir", default=None)
    args = ap.parse_args()

    import jax
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    import mxnet_tpu as mx
    from mxnet_tpu import models

    rng = np.random.default_rng(7)
    workdir = args.workdir or tempfile.mkdtemp(prefix="convgate_")
    t0 = time.perf_counter()
    train_rec, val_rec = make_jpeg_dataset(
        workdir, args.n_per_class, args.classes, args.size, rng)
    gen_s = time.perf_counter() - t0

    data_shape = (3, args.crop, args.crop)
    norm = dict(mean_r=128, mean_g=128, mean_b=128,
                std_r=64, std_g=64, std_b=64)
    train = mx.image.ImageRecordIter(
        path_imgrec=train_rec, data_shape=data_shape,
        batch_size=args.batch, shuffle=True, rand_crop=True,
        rand_mirror=True, **norm)
    val = mx.image.ImageRecordIter(
        path_imgrec=val_rec, data_shape=data_shape,
        batch_size=args.batch, **norm)

    sym = models.resnet(num_classes=args.classes, num_layers=18,
                        image_shape="3,%d,%d" % (args.crop, args.crop))
    # multi-epoch LR schedule: drop at 2/3 of training (ref:
    # train_imagenet's --lr-step-epochs over MultiFactorScheduler)
    steps_per_epoch = args.classes * args.n_per_class // args.batch
    sched = mx.lr_scheduler.MultiFactorScheduler(
        step=[steps_per_epoch * args.epochs * 2 // 3], factor=0.1)
    mod = mx.mod.Module(sym)
    t1 = time.perf_counter()
    mod.fit(train, num_epoch=args.epochs,
            optimizer="adam",
            optimizer_params={"learning_rate": args.lr,
                              "lr_scheduler": sched},
            initializer=mx.initializer.Xavier(rnd_type="gaussian",
                                              factor_type="in",
                                              magnitude=2),
            batch_end_callback=mx.callback.Speedometer(args.batch, 20))
    train_s = time.perf_counter() - t1
    acc = mod.score(val, mx.metric.Accuracy())[0][1]
    print(json.dumps({
        "metric": "resnet18_realjpeg%d_holdout_acc" % args.classes,
        "value": round(float(acc), 4),
        "epochs": args.epochs,
        "images": args.classes * args.n_per_class,
        "gen_seconds": round(gen_s, 1),
        "train_seconds": round(train_s, 1),
    }))
    assert acc >= args.min_acc, \
        "real-data convergence gate: %.3f < %.3f" % (acc, args.min_acc)
    print("REALDATA CONVERGENCE PASS")


if __name__ == "__main__":
    main()
