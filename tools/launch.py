#!/usr/bin/env python
"""Multi-host launcher (ref: tools/launch.py over dmlc-core trackers —
local/ssh/mpi/sge/yarn, setting DMLC_ROLE/DMLC_PS_ROOT_* per process).

TPU-native: there are no parameter-server roles — every process is a worker
in one SPMD program; ``jax.distributed.initialize`` replaces the tracker
rendezvous (coordinator address + process_id + num_processes), and gradient
sync rides psum over ICI/DCN instead of ps-lite push/pull.

Launchers:
  local — spawn N worker processes on this host (the reference's local
          tracker; useful with a CPU mesh for testing dist_sync semantics)
  ssh   — spawn one worker per host in --host-file via ssh

Each worker gets MXTPU_COORD / MXTPU_RANK / MXTPU_NPROC env vars; call
``mxnet_tpu.tools_init_distributed()`` (or jax.distributed.initialize
directly) at program start.
"""
import argparse
import os
import subprocess
import sys


def launch_local(n, command, coord_port=12421):
    procs = []
    for rank in range(n):
        env = dict(os.environ)
        env.update(MXTPU_COORD="localhost:%d" % coord_port,
                   MXTPU_RANK=str(rank), MXTPU_NPROC=str(n),
                   # workers on one host must split visible devices or run cpu
                   JAX_PLATFORMS=env.get("JAX_PLATFORMS", ""))
        procs.append(subprocess.Popen(command, shell=True, env=env))
    code = 0
    for p in procs:
        code = p.wait() or code
    return code


def launch_ssh(host_file, command, coord_port=12421):
    with open(host_file) as f:
        hosts = [h.strip() for h in f if h.strip()]
    coord = "%s:%d" % (hosts[0], coord_port)
    procs = []
    for rank, host in enumerate(hosts):
        env_prefix = ("MXTPU_COORD=%s MXTPU_RANK=%d MXTPU_NPROC=%d"
                      % (coord, rank, len(hosts)))
        procs.append(subprocess.Popen(
            ["ssh", "-o", "StrictHostKeyChecking=no", host,
             "cd %s && %s %s" % (os.getcwd(), env_prefix, command)]))
    code = 0
    for p in procs:
        code = p.wait() or code
    return code


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("-n", "--num-workers", type=int, required=True)
    parser.add_argument("--launcher", choices=["local", "ssh"],
                        default="local")
    parser.add_argument("--host-file", default=None)
    parser.add_argument("--coord-port", type=int, default=12421,
                        help="jax.distributed coordinator port")
    parser.add_argument("command", nargs=argparse.REMAINDER)
    args = parser.parse_args()
    command = " ".join(args.command)
    if args.launcher == "local":
        sys.exit(launch_local(args.num_workers, command, args.coord_port))
    else:
        assert args.host_file, "ssh launcher needs --host-file"
        sys.exit(launch_ssh(args.host_file, command, args.coord_port))


if __name__ == "__main__":
    main()
