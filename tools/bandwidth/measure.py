#!/usr/bin/env python
"""Measure device-collective bandwidth (ref: tools/bandwidth/measure.py,
which timed kvstore push/pull over PCIe/network).

TPU-native: times the psum allreduce over the device mesh (ICI) — the
operation that replaced kvstore gradient sync — plus host<->device transfer.
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--size-mb", type=float, default=64,
                        help="payload per device, MB")
    parser.add_argument("--iters", type=int, default=10)
    args = parser.parse_args()

    import jax
    import jax.numpy as jnp
    from jax.experimental.shard_map import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    devs = jax.devices()
    n = len(devs)
    elems = int(args.size_mb * 1e6 / 4)
    x = jnp.ones((n, elems), jnp.float32)

    # host -> device
    xh = np.ones((elems,), np.float32)
    t0 = time.perf_counter()
    for _ in range(args.iters):
        jax.block_until_ready(jax.device_put(xh, devs[0]))
    h2d = args.size_mb * args.iters / (time.perf_counter() - t0)
    print("host->device: %.2f MB/s" % h2d)

    if n > 1:
        mesh = Mesh(np.array(devs), ("data",))
        f = shard_map(lambda v: jax.lax.psum(v, "data"), mesh=mesh,
                      in_specs=P("data"), out_specs=P())

        xs = jax.device_put(x, jax.sharding.NamedSharding(mesh, P("data")))
        jax.block_until_ready(f(xs))  # compile
        t0 = time.perf_counter()
        for _ in range(args.iters):
            jax.block_until_ready(f(xs))
        dt = time.perf_counter() - t0
        # ring allreduce moves 2*(n-1)/n of the payload per device
        algbw = args.size_mb * args.iters / dt
        busbw = algbw * 2 * (n - 1) / n
        print("allreduce (psum) over %d devices: algbw %.2f MB/s, "
              "busbw %.2f MB/s" % (n, algbw, busbw))
    else:
        print("single device: no collective to measure")


if __name__ == "__main__":
    main()
