#!/usr/bin/env python
"""CI smoke gate for mxnet_tpu.autotune (docs/perf.md "Autotuning").

Runs a tiny exhaustive grid over the zoo mlp on CPU and asserts the whole
loop closes:

1. the static pruner rejects at least one over-budget candidate
   (``MXTPU_AUTOTUNE_BUDGET=128K`` makes the K=16 superbatch scan exceed
   the budget) WITHOUT executing it;
2. a winner is found whose measured score >= the built-in default's
   (the default config is always trial #0) and is persisted to the
   tuning DB;
3. a FRESH ``Module.fit`` with no knob arguments resolves the winner's
   knobs from the DB (obs-registry counter + compiled-scan cache key)
   with ZERO extra retraces (``test_utils.assert_no_retrace`` over the
   whole fit).

Run via ci/autotune.sh (sets the temp DB path + budget).
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

BATCH = 48


def main():
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.setdefault("MXTPU_AUTOTUNE_BUDGET", "128K")
    os.environ.setdefault("MXTPU_AUTOTUNE_MEASURE", "6,18")
    if not os.environ.get("MXTPU_AUTOTUNE_DB"):
        sys.exit("autotune_gate: set MXTPU_AUTOTUNE_DB to a scratch path "
                 "(the gate must not write the committed DB)")

    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import autotune, models
    from mxnet_tpu.autotune.db import TuningDB
    from mxnet_tpu.obs import REGISTRY
    from mxnet_tpu.test_utils import assert_no_retrace
    from mxnet_tpu.tracecheck import ZOO

    # -- 1+2: the sweep — grid over {K, pipeline depth}, K=16 over-budget
    res = autotune.tune(
        model="mlp", objective="img_per_sec", budget=8, batch=BATCH,
        write_db=True, rounds=2,
        space=[autotune.Knob("steps_per_dispatch", (1, 2, 16)),
               autotune.Knob("dispatch_pipeline", (1, 0))],
        log=lambda m: print("autotune: %s" % m, file=sys.stderr))
    counts = res["counts"]
    if counts.get("pruned", 0) < 1:
        sys.exit("autotune_gate FAIL: no candidate was statically pruned "
                 "(expected K=16 over the 128K budget); counts %r"
                 % counts)
    for t in res["trials"]:
        if t["knobs"]["steps_per_dispatch"] == 16 \
                and t["status"] != "pruned":
            sys.exit("autotune_gate FAIL: the over-budget K=16 candidate "
                     "was %s, not pruned — it must never execute"
                     % t["status"])
    best, default = res["best"], res["default"]
    if best is None:
        sys.exit("autotune_gate FAIL: no successful trial (counts %r)"
                 % counts)
    if not (default and default["status"] == "ok"
            and best["score"] >= default["score"]):
        sys.exit("autotune_gate FAIL: winner %r does not reach the "
                 "default config's score (%r)" % (best, default))
    db = TuningDB.load(os.environ["MXTPU_AUTOTUNE_DB"])
    key, entry, _ = db.lookup("train", symbol_sig=res["symbol_sig"],
                              global_batch=BATCH)
    if entry is None or entry["knobs"] != best["knobs"]:
        sys.exit("autotune_gate FAIL: winner not persisted to the tuning "
                 "DB (entry %r)" % (entry,))
    print("autotune_gate: winner %r at %.1f %s (default %.1f), "
          "%d pruned, persisted as %s"
          % (best["knobs"], best["score"], res["unit"],
             default["score"], counts["pruned"], key))

    # -- 3: a fresh Module.fit resolves the winner from the DB with zero
    # extra retraces (compiles are first-traces; any RETRACE EVENT or
    # watched-cache growth inside the block fails)
    sym = models.get_symbol("mlp", **ZOO["mlp"]["kwargs"])
    rng = np.random.default_rng(0)
    X = rng.normal(size=(BATCH * 4, 64)).astype(np.float32)
    y = rng.integers(0, 4, BATCH * 4).astype(np.float32)
    it = mx.io.NDArrayIter(X, y, batch_size=BATCH)
    mod = mx.mod.Module(sym, context=mx.cpu())
    before = REGISTRY.snapshot().get("autotune.db_resolutions", 0)
    with assert_no_retrace(msg="DB-resolved fit"):
        mod.fit(it, num_epoch=2,
                optimizer_params={"learning_rate": 0.1, "momentum": 0.9})
    hits = REGISTRY.snapshot().get("autotune.db_resolutions", 0) - before
    if hits != 1:
        sys.exit("autotune_gate FAIL: expected exactly one obs-logged DB "
                 "resolution in the fresh fit, saw %d" % hits)
    k_best = best["knobs"]["steps_per_dispatch"]
    if k_best > 1:
        scans = list(mod._fused._jit_scan) if mod._fused else []
        if not any(ck[1] == k_best for ck in scans):
            sys.exit("autotune_gate FAIL: fresh fit did not train at the "
                     "DB's K=%d (compiled scans: %r)" % (k_best, scans))
    else:
        # winner K=1 on this host: the fused per-step path carries it
        if mod._fused is None:
            sys.exit("autotune_gate FAIL: fresh fit never engaged the "
                     "fused path")
    print("autotune_gate: fresh Module.fit resolved %r from the DB with "
          "zero extra retraces" % (best["knobs"],))
    print("autotune gate PASS")


if __name__ == "__main__":
    main()
