#!/usr/bin/env python
"""CI gate: the unified observability layer (docs/observability.md).

Four checks, one process:

1. **Trace schema over a fused fit.** A 2-epoch ``Module.fit
   (steps_per_dispatch=2)`` under ``MXTPU_TRACE=1`` must emit a Chrome
   trace-event JSON whose complete events nest properly per thread, that
   carries every expected training stage (data_wait, h2d,
   superbatch_assemble, dispatch, readback_stall, checkpoint), and whose
   dispatch correlation IDs agree end to end: every dispatched index has
   an h2d span and a readback_stall span with the SAME index.
2. **Trace schema over a batcher serve run.** The request lifecycle
   (serve_submit -> serve_queue -> serve_coalesce -> serve_dispatch ->
   serve_split) must be present and id-consistent: every request id that
   reached a dispatch was submitted.
3. **Registry snapshot completeness.** ``obs.REGISTRY.snapshot()`` must
   carry EVERY key of every legacy health/stats object's report() — the
   five process-global counters are views, and a view falling off the
   registry would silently blind the bench/flight-recorder exports.
4. **Tracing-off cost A/B.** With tracing and the flight recorder off,
   ``obs.span`` must be a shared-noop flag check: the gate measures the
   per-call cost of the off path (bounded in microseconds) AND runs the
   same small fit traced vs untraced, asserting the untraced run pays no
   measurable per-dispatch cost (band ``MXTPU_OBS_AB_TOL``, default
   1.5x — generous because a 1-core CI host is noisy; the real contract
   is the microbenchmark).

Exit nonzero on any violation, with the offending spans/keys named.
"""
import json
import logging
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

import numpy as np  # noqa: E402


def _mlp():
    from mxnet_tpu import sym
    net = sym.FullyConnected(sym.Variable("data"), num_hidden=16,
                             name="fc1")
    net = sym.Activation(net, act_type="relu")
    net = sym.FullyConnected(net, num_hidden=4, name="fc2")
    return sym.SoftmaxOutput(net, name="softmax")


def _toy(n=96, dim=10, classes=4, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, dim)).astype(np.float32)
    w = rng.normal(size=(dim, classes)).astype(np.float32)
    y = np.argmax(X @ w, axis=1).astype(np.float32)
    return X, y


def _fit_once(tmpdir, tag, k=2, epochs=2):
    import mxnet_tpu as mx
    X, y = _toy()
    mx.random.seed(0)
    it = mx.io.NDArrayIter(X, y, batch_size=8)
    mod = mx.mod.Module(_mlp(), context=mx.cpu(),
                        logger=logging.getLogger("obs_gate"))
    t0 = time.perf_counter()
    mod.fit(it, num_epoch=epochs, steps_per_dispatch=k,
            optimizer_params={"learning_rate": 0.1},
            checkpoint_prefix=os.path.join(tmpdir, tag, "ck"),
            checkpoint_every_n_batches=4)
    return time.perf_counter() - t0


def _fail(msg):
    print("obs gate FAIL: %s" % msg)
    sys.exit(1)


def check_train_trace(tmpdir):
    from mxnet_tpu import obs
    obs.trace.clear()
    obs.start()
    _fit_once(tmpdir, "traced")
    evs = obs.events()
    obs.stop()
    path = os.path.join(tmpdir, "train_trace.json")
    obs.save(path)
    doc = json.load(open(path))
    if not doc.get("traceEvents"):
        _fail("train trace has no events")
    bad = obs.trace.nest_check(doc["traceEvents"])
    if bad:
        _fail("train trace nesting violations:\n  " + "\n  ".join(bad))
    by = {}
    for ev in evs:
        if ev["ph"] == "X":
            by.setdefault(ev["name"], []).append(ev)
    for stage in ("data_wait", "h2d", "superbatch_assemble", "dispatch",
                  "readback_stall", "checkpoint"):
        if stage not in by:
            _fail("train trace missing stage %r (have %s)"
                  % (stage, sorted(by)))
    disp = {e["args"]["dispatch"] for e in by["dispatch"]}
    h2d = {e["args"]["dispatch"] for e in by["h2d"]}
    rb = {e["args"]["dispatch"] for e in by["readback_stall"]}
    if not disp:
        _fail("no dispatch spans recorded")
    if not disp <= h2d:
        _fail("dispatch ids %s lack matching h2d spans %s"
              % (sorted(disp - h2d), sorted(h2d)))
    if disp != rb:
        _fail("dispatch ids %s != readback ids %s"
              % (sorted(disp), sorted(rb)))
    print("obs gate: train trace ok — %d events, %d dispatches, "
          "stages %s" % (len(doc["traceEvents"]), len(disp),
                         ",".join(sorted(by))))


def check_serve_trace(tmpdir):
    import mxnet_tpu as mx
    from mxnet_tpu import obs, serving
    obs.trace.clear()
    obs.start()
    net = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=4,
                              name="fc1"), name="softmax")
    rs = np.random.RandomState(0)
    params = {"arg:fc1_weight": rs.randn(4, 6).astype(np.float32),
              "arg:fc1_bias": rs.randn(4).astype(np.float32)}
    eng = serving.ServingEngine(net, params, {"data": (1, 6)},
                                buckets=(4, 8))
    b = serving.Batcher(eng, max_latency_ms=2.0)
    reqs = [b.submit({"data": rs.randn(1, 1, 6).astype(np.float32)},
                     deadline_ms=10000) for _ in range(12)]
    for r in reqs:
        b.wait(r)
    b.close()
    evs = obs.events()
    obs.stop()
    names = {e["name"] for e in evs}
    for stage in ("serve_submit", "serve_queue", "serve_coalesce",
                  "serve_dispatch", "serve_split"):
        if stage not in names:
            _fail("serve trace missing stage %r (have %s)"
                  % (stage, sorted(names)))
    submitted = {e["args"]["req"] for e in evs
                 if e["name"] == "serve_submit"}
    dispatched = set()
    for e in evs:
        if e["name"] == "serve_dispatch" and e["ph"] == "X":
            dispatched.update(e["args"]["reqs"])
    if not dispatched <= submitted:
        _fail("dispatched request ids %s never submitted"
              % sorted(dispatched - submitted))
    if len(submitted) != 12:
        _fail("expected 12 submitted request ids, saw %d"
              % len(submitted))
    print("obs gate: serve trace ok — %d requests submitted, %d reached "
          "a dispatch" % (len(submitted), len(dispatched)))


def check_registry():
    from mxnet_tpu import guard, io as mxio, obs, tracecheck
    from mxnet_tpu.data import stats as dstats
    from mxnet_tpu.serving import health as shealth
    snap = obs.REGISTRY.snapshot()
    legacy = {
        "data_health": mxio.DATA_HEALTH.report(),
        "training_health": guard.TRAINING_HEALTH.report(),
        "serving_health": shealth.SERVING_HEALTH.report(),
        "pipeline_stats": dstats.PIPELINE_STATS.report(),
        "retrace_events": {"count": tracecheck.retrace_count()},
    }
    missing = ["%s.%s" % (v, k) for v, rep in legacy.items()
               for k in rep if "%s.%s" % (v, k) not in snap]
    if missing:
        _fail("registry snapshot missing legacy keys: %s" % missing)
    # the Prometheus export must render without blowing up and carry a
    # representative numeric sample
    text = obs.REGISTRY.to_prometheus()
    if "training_health_steps" not in text:
        _fail("prometheus export lacks training_health_steps")
    print("obs gate: registry snapshot carries all %d legacy keys"
          % sum(len(r) for r in legacy.values()))


def check_off_cost(tmpdir):
    from mxnet_tpu import obs
    from mxnet_tpu.obs import flight
    # microbenchmark: the off path is one flag check + shared noop
    obs.stop()
    was = flight.enabled()
    flight.set_enabled(False)
    try:
        n = 200000
        t0 = time.perf_counter()
        for _ in range(n):
            with obs.span("hot", dispatch=0):
                pass
        per_call = (time.perf_counter() - t0) / n
    finally:
        flight.set_enabled(was)
    cap = float(os.environ.get("MXTPU_OBS_OFF_NS_CAP", "5000"))
    if per_call * 1e9 > cap:
        _fail("tracing-off span() costs %.0f ns/call (cap %.0f) — the "
              "off path must stay a flag check" % (per_call * 1e9, cap))
    # fit A/B: untraced must not be slower than traced beyond noise —
    # tracing must actually be ON for the t_on side, or the band
    # compares noise against noise and a costly off-path slips through
    obs.trace.clear()
    obs.start()
    t_on = min(_fit_once(tmpdir, "ab_on_%d" % i) for i in range(2))
    obs.stop()
    t_off = min(_fit_once(tmpdir, "ab_off_%d" % i) for i in range(2))
    tol = float(os.environ.get("MXTPU_OBS_AB_TOL", "1.5"))
    if t_off > t_on * tol:
        _fail("tracing-off fit %.3fs vs traced %.3fs exceeds %gx band"
              % (t_off, t_on, tol))
    print("obs gate: off-cost ok — span() %.0f ns/call off; fit off "
          "%.3fs vs traced %.3fs" % (per_call * 1e9, t_off, t_on))


def main():
    logging.basicConfig(level=logging.WARNING)
    os.environ.setdefault("MXTPU_TRACE", "0")
    with tempfile.TemporaryDirectory() as tmpdir:
        os.environ["MXTPU_FLIGHT_RECORDER_PATH"] = os.path.join(
            tmpdir, "flight.json")
        from mxnet_tpu import obs  # noqa: F401  (import before arming)
        check_train_trace(tmpdir)
        check_serve_trace(tmpdir)
        check_registry()
        check_off_cost(tmpdir)
    print("obs gate PASS")


if __name__ == "__main__":
    main()
